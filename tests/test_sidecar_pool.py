"""Sidecar worker pool + end-to-end integrity tier (ISSUE 5).

Covers the crash-tolerance contract from both ends:

- POOL: failover on worker death (in-process fake workers for the fast
  tier; real kill -9 / chaos ``crash`` storms in the slow tier),
  respawn + SET_ARENA re-hydration, pool-scoped breaker accounting
  (one dead worker among living peers never trips it), per-worker
  STATS aggregation.
- INTEGRITY: CRC trailers on wire frames both directions (verified,
  negotiated per frame, legacy interop preserved), CRC-framed disk
  spills (a corrupted-on-disk spill raises retryable DataCorruption
  and re-materializes via the retry machinery, never wrong rows),
  shuffle exchange payload checksums, and the ``corrupt`` fault kind
  the CRC layer must catch.

The in-process worker trick: ``sidecar._handle_conn`` is a plain
function over a socket, so the fast tier serves REAL protocol traffic
from accept-loop threads in this process — full framing, arenas over
SCM_RIGHTS, STATS — without paying a jax child boot per test. Real
subprocess workers run in the slow tier (ci/premerge.sh crash-storm
tier runs them env-armed).
"""

import json
import os
import signal
import socket
import struct
import tempfile
import threading
import time

import numpy as np
import pytest

import spark_rapids_jni_tpu  # noqa: F401
import jax.numpy as jnp

from spark_rapids_jni_tpu import memgov, sidecar, sidecar_pool
from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.columnar import dtype as dt
from spark_rapids_jni_tpu.utils import faultinj, integrity, metrics, retry
from spark_rapids_jni_tpu.utils.errors import DataCorruption, RetryableError


def _counter(name):
    return metrics.registry().value(name)


def _scrub_worker_namespace():
    """The in-process worker trick below runs ``_handle_conn`` in THIS
    process, so its always-on request COUNTERS share the registry with
    the ``sidecar.worker.*`` GAUGES other suite files fold remote
    snapshots into — a type clash the two-process deployment can never
    hit. Scrub the namespace both ways (before: earlier folds must not
    break the in-proc worker; after: the in-proc counters must not
    break a later fold under randomized test ordering)."""
    reg = metrics.registry()
    with reg._lock:
        for name in list(reg._metrics):
            if name.startswith("sidecar.worker."):
                del reg._metrics[name]


@pytest.fixture(autouse=True)
def _clean_state():
    faultinj.disable()
    retry.disable()
    retry.reset_stats()
    _scrub_worker_namespace()
    yield
    faultinj.disable()
    retry.disable()
    retry.reset_stats()
    _scrub_worker_namespace()


# ---------------------------------------------------------------------------
# in-process worker: the real protocol loop without a subprocess
# ---------------------------------------------------------------------------


class _InProcWorker:
    """Duck-types the Popen surface SidecarPool supervises, but serves
    ``sidecar._handle_conn`` from threads in THIS process. ``kill()``
    models kill -9: the listener and every live connection drop
    mid-frame, exactly what a client of a SIGKILLed worker observes."""

    def __init__(self):
        self.sock_path = tempfile.mktemp(prefix="srjt-inproc-") + ".sock"
        self.pid = os.getpid()
        self.returncode = None
        self._conns = []
        self._srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._srv.bind(self.sock_path)
        self._srv.listen(8)
        self._t = threading.Thread(target=self._accept_loop, daemon=True)
        self._t.start()

    def _accept_loop(self):
        while True:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return  # killed
            self._conns.append(conn)

            def _serve(c=conn):
                try:
                    sidecar._handle_conn(c, "cpu", lambda: None)
                except OSError:
                    pass  # kill() closed the socket under the handler

            threading.Thread(target=_serve, daemon=True).start()

    # Popen surface the pool touches
    def poll(self):
        return self.returncode

    def wait(self, timeout=None):
        return self.returncode if self.returncode is not None else 0

    def terminate(self):
        self.kill()

    def kill(self):
        if self.returncode is None:
            self.returncode = -signal.SIGKILL
        try:
            self._srv.close()
        except OSError:
            pass
        for c in self._conns:
            try:
                c.close()
            except OSError:
                pass
        try:
            os.unlink(self.sock_path)
        except OSError:
            pass


def _inproc_spawn(startup_timeout_s=None, env=None):
    w = _InProcWorker()
    return w, w.sock_path


@pytest.fixture
def inproc_pool():
    pool = sidecar_pool.SidecarPool(
        size=2, deadline_s=20, heartbeat_s=1e9, spawn_fn=_inproc_spawn
    )
    yield pool
    pool.shutdown()


def _groupby_payload(n=600, k=16, seed=3):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, k, n).astype(np.int64)
    vals = rng.standard_normal(n).astype(np.float32)
    return struct.pack("<IQ", k, n) + keys.tobytes() + vals.tobytes()


# ---------------------------------------------------------------------------
# integrity helper unit tests
# ---------------------------------------------------------------------------


class TestIntegrityHelpers:
    def test_checksum_roundtrip_and_mismatch(self):
        data = os.urandom(4096)
        c = integrity.checksum(data)
        integrity.verify(data, c, "unit")  # no raise
        before = _counter("sidecar.integrity.crc_mismatch")
        with pytest.raises(DataCorruption, match="CRC mismatch"):
            integrity.verify(data[:-1] + b"\x00", c, "unit")
        assert _counter("sidecar.integrity.crc_mismatch") == before + 1
        assert _counter("sidecar.integrity.crc_mismatch.unit") >= 1

    def test_disabled_gate_skips_verification(self):
        with integrity.disabled():
            integrity.verify(b"anything", 0xDEAD, "unit")  # silently passes

    def test_corruption_is_retryable(self):
        assert issubclass(DataCorruption, RetryableError)

    def test_pack_unpack(self):
        assert integrity.unpack_crc(integrity.pack_crc(0xDEADBEEF)) == 0xDEADBEEF


# ---------------------------------------------------------------------------
# wire-frame CRC protocol (in-process worker, real SupervisedClient)
# ---------------------------------------------------------------------------


class TestFrameIntegrity:
    def test_crc_framed_request_roundtrip(self):
        w = _InProcWorker()
        try:
            client = sidecar.SupervisedClient(w.sock_path, deadline_s=20, heartbeat_s=1e9)
            with client:
                payload = _groupby_payload()
                before = _counter("sidecar.integrity.frames_checked")
                resp = client.request(sidecar.OP_GROUPBY_SUM_F32, payload)
                assert resp == sidecar._dispatch(
                    sidecar.OP_GROUPBY_SUM_F32, payload, "cpu"
                )
                # both directions verified: worker checked the request,
                # client checked the response
                assert _counter("sidecar.integrity.frames_checked") >= before + 2
        finally:
            w.kill()

    def test_corrupted_request_rejected_by_worker(self):
        """A frame whose trailer doesn't match its payload must answer
        status 1 with the DataCorruption taxonomy prefix — and the
        worker must keep serving."""
        w = _InProcWorker()
        try:
            conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            conn.connect(w.sock_path)
            payload = _groupby_payload()
            bad_crc = integrity.pack_crc(integrity.checksum(payload) ^ 0xFFFF)
            conn.sendall(
                struct.pack(
                    "<IQ", sidecar.OP_GROUPBY_SUM_F32 | sidecar.CRC_FLAG, len(payload)
                )
                + bad_crc
                + payload
            )
            status, rlen = struct.unpack("<IQ", sidecar._recv_exact(conn, 12))
            assert status & sidecar.CRC_FLAG  # the error reply is framed too
            sidecar._recv_exact(conn, 4)  # its trailer
            body = sidecar._recv_exact(conn, rlen)
            assert (status & ~sidecar._FLAG_MASK) == sidecar.STATUS_ERROR
            assert body.startswith(b"DataCorruption:")
            # worker survived: a clean PING round-trips on the same conn
            conn.sendall(struct.pack("<IQ", sidecar.OP_PING, 0))
            status, rlen = struct.unpack("<IQ", sidecar._recv_exact(conn, 12))
            assert status == sidecar.STATUS_OK
            assert sidecar._recv_exact(conn, rlen) == b"cpu"
            conn.close()
        finally:
            w.kill()

    def test_corrupt_fault_caught_by_client_crc(self):
        """The `corrupt` chaos kind flips response bytes after the
        worker checksums: the client's CRC check must convert it into
        DataCorruption — and with the retry orchestrator armed the op
        heals once the fault budget is spent."""
        w = _InProcWorker()
        try:
            client = sidecar.SupervisedClient(w.sock_path, deadline_s=20, heartbeat_s=1e9)
            payload = _groupby_payload()
            want = sidecar._dispatch(sidecar.OP_GROUPBY_SUM_F32, payload, "cpu")
            faultinj.configure(
                {"seed": 11, "faults": {"sidecar.worker.GROUPBY_SUM_F32": {
                    "type": "corrupt", "percent": 100, "interceptionCount": 1}}}
            )
            before = _counter("sidecar.integrity.crc_mismatch")
            with client:
                with pytest.raises(DataCorruption):
                    client.request(sidecar.OP_GROUPBY_SUM_F32, payload)
                assert _counter("sidecar.integrity.crc_mismatch") == before + 1
                # budget spent: the re-fetch returns pristine bytes
                assert client.request(sidecar.OP_GROUPBY_SUM_F32, payload) == want
        finally:
            w.kill()

    def test_corrupt_fault_with_retry_orchestrator_heals(self):
        w = _InProcWorker()
        try:
            client = sidecar.SupervisedClient(w.sock_path, deadline_s=20, heartbeat_s=1e9)
            payload = _groupby_payload()
            want = sidecar._dispatch(sidecar.OP_GROUPBY_SUM_F32, payload, "cpu")
            faultinj.configure(
                {"seed": 11, "faults": {"sidecar.worker.GROUPBY_SUM_F32": {
                    "type": "corrupt", "percent": 100, "interceptionCount": 2}}}
            )
            with client, metrics.enabled(), retry.enabled(
                max_attempts=5, base_delay_ms=1
            ):
                assert client.call(sidecar.OP_GROUPBY_SUM_F32, payload) == want
            assert retry.stats()["retries"] >= 1
            # per-class accounting: corruption retries are visible as
            # their own class (gated counter, hence metrics armed above)
            assert _counter("retry.retries.DataCorruption") >= 1
        finally:
            w.kill()

    def test_integrity_off_is_legacy_framing(self):
        """SRJT_INTEGRITY_CHECKS=0 posture: no CRC flag on the wire,
        no verification — and an injected corruption therefore flows
        through silently (the counterfactual that justifies the
        layer's existence)."""
        w = _InProcWorker()
        try:
            client = sidecar.SupervisedClient(w.sock_path, deadline_s=20, heartbeat_s=1e9)
            payload = _groupby_payload()
            want = sidecar._dispatch(sidecar.OP_GROUPBY_SUM_F32, payload, "cpu")
            with client, integrity.disabled():
                assert client.request(sidecar.OP_GROUPBY_SUM_F32, payload) == want
                faultinj.configure(
                    {"seed": 1, "faults": {"sidecar.worker.GROUPBY_SUM_F32": {
                        "type": "corrupt", "percent": 100, "interceptionCount": 1}}}
                )
                got = client.request(sidecar.OP_GROUPBY_SUM_F32, payload)
                assert got != want  # corruption passed: wrong bytes, no error
        finally:
            w.kill()


# ---------------------------------------------------------------------------
# spill-file CRC (the at-rest half of the integrity layer)
# ---------------------------------------------------------------------------


class TestSpillIntegrity:
    def test_disk_spill_roundtrip_bit_exact(self, tmp_path):
        from spark_rapids_jni_tpu.memgov.catalog import BufferCatalog

        cat = BufferCatalog(spill_dir=str(tmp_path))
        src = np.arange(1000, dtype=np.float64).view(np.uint64)
        h = cat.register("rt", jnp.asarray(src))
        h.spill(to_disk=True)
        assert h.tier == memgov.TIER_DISK
        got = np.asarray(h.get())
        assert got.tobytes() == src.tobytes()
        cat.close()

    def test_corrupted_spill_raises_data_corruption(self, tmp_path):
        from spark_rapids_jni_tpu.memgov.catalog import BufferCatalog

        cat = BufferCatalog(spill_dir=str(tmp_path))
        h = cat.register("bad", jnp.arange(500, dtype=jnp.int64))
        h.spill(to_disk=True)
        path = h._disk_path
        raw = bytearray(open(path, "rb").read())
        raw[len(raw) // 2] ^= 0xFF  # one flipped bit in the payload
        open(path, "wb").write(bytes(raw))
        before = _counter("sidecar.integrity.crc_mismatch")
        with pytest.raises(DataCorruption):
            h.get()
        assert _counter("sidecar.integrity.crc_mismatch") == before + 1
        # the bad copy is retired: the entry is gone, not resident-corrupt
        assert cat.unregister("bad") is False
        cat.close()

    def test_corrupted_spill_rematerializes_via_split_retry(self, tmp_path):
        """The acceptance path: an op whose cached input rotted on disk
        re-computes through the retry/split machinery and lands
        bit-identical — corruption costs a retry, never correctness."""
        from spark_rapids_jni_tpu.memgov.catalog import BufferCatalog

        cat = BufferCatalog(spill_dir=str(tmp_path))
        src = np.arange(256, dtype=np.int64)
        h = cat.register("cache", jnp.asarray(src))
        h.spill(to_disk=True)
        raw = bytearray(open(h._disk_path, "rb").read())
        raw[-3] ^= 0x55
        open(h._disk_path, "wb").write(bytes(raw))

        fetches = {"cached": 0, "recomputed": 0}

        def fetch(batch):
            try:
                out = h.get()  # first attempt: DataCorruption (counted)
                fetches["cached"] += 1
                return out
            except ValueError:
                # entry retired by the corruption: re-materialize from
                # source — what a real op does when its cache is gone
                fetches["recomputed"] += 1
                return jnp.asarray(np.asarray(batch))

        with retry.enabled(max_attempts=4, base_delay_ms=1):
            out = retry.retry_with_split(
                fetch, src, split=lambda b: (b[: len(b) // 2], b[len(b) // 2 :]),
                combine=lambda parts: np.concatenate(parts), op_name="spill_refetch",
            )
        assert np.asarray(out).tobytes() == src.tobytes()
        assert fetches == {"cached": 0, "recomputed": 1}
        assert retry.stats()["retries"] >= 1
        cat.close()

    def test_spill_crc_cost_is_spill_path_only(self, tmp_path):
        """Host-tier spills (the common demotion) never touch the CRC
        machinery — only the disk tier frames."""
        from spark_rapids_jni_tpu.memgov.catalog import BufferCatalog

        cat = BufferCatalog(spill_dir=str(tmp_path))
        before = _counter("sidecar.integrity.spills_checked")
        h = cat.register("host_only", jnp.arange(64, dtype=jnp.int32))
        h.spill(to_disk=False)
        assert np.array_equal(np.asarray(h.get()), np.arange(64))
        assert _counter("sidecar.integrity.spills_checked") == before
        cat.close()


# ---------------------------------------------------------------------------
# shuffle exchange payload checksum
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def mesh8():
    import jax

    from spark_rapids_jni_tpu.parallel import mesh as mesh_mod

    assert len(jax.devices()) == 8, "conftest must force the 8-device CPU mesh"
    return mesh_mod.make_mesh({"data": 8})


class TestExchangeIntegrity:
    def _arrays(self):
        rng = np.random.default_rng(5)
        n = 8 * 32
        vals = jnp.asarray(rng.integers(-1000, 1000, n).astype(np.int64))
        dest = jnp.asarray((rng.integers(0, 8, n)).astype(np.int32))
        return [vals], dest

    def test_clean_exchange_passes_checksum(self, mesh8):
        from spark_rapids_jni_tpu.parallel import shuffle

        arrays, dest = self._arrays()
        before = _counter("sidecar.integrity.exchanges_checked")
        received, mask, overflow = shuffle.all_to_all_exchange(
            arrays, dest, mesh8, capacity=None
        )
        assert not bool(np.asarray(overflow).any())
        assert _counter("sidecar.integrity.exchanges_checked") == before + 1

    def test_tampered_exchange_raises_data_corruption(self, mesh8, monkeypatch):
        from spark_rapids_jni_tpu.parallel import shuffle

        real = shuffle._exchange_once

        def tampered(arrays, dest, mesh, axis, capacity, n_parts):
            received, mask, overflow = real(arrays, dest, mesh, axis, capacity, n_parts)
            flipped = [r.at[0].set(r[0] + 1) for r in received]  # one lane off
            return flipped, mask, overflow

        monkeypatch.setattr(shuffle, "_exchange_once", tampered)
        arrays, dest = self._arrays()
        before = _counter("sidecar.integrity.crc_mismatch")
        with pytest.raises(DataCorruption, match="shuffle.exchange"):
            shuffle.all_to_all_exchange(arrays, dest, mesh8, capacity=None)
        assert _counter("sidecar.integrity.crc_mismatch") == before + 1

    def test_integrity_off_skips_exchange_checksum(self, mesh8):
        from spark_rapids_jni_tpu.parallel import shuffle

        arrays, dest = self._arrays()
        before = _counter("sidecar.integrity.exchanges_checked")
        with integrity.disabled():
            shuffle.all_to_all_exchange(arrays, dest, mesh8, capacity=None)
        assert _counter("sidecar.integrity.exchanges_checked") == before


# ---------------------------------------------------------------------------
# faultinj: the new kinds' config surface + scheduling
# ---------------------------------------------------------------------------


class TestFaultKinds:
    def test_crash_and_corrupt_parse(self):
        faultinj.configure(
            {"faults": {
                "a": {"type": "crash", "percent": 50, "after": 2},
                "b": {"type": "corrupt", "percent": 100, "ramp": 3},
            }}
        )
        assert faultinj.is_enabled()

    def test_unknown_kind_still_rejected(self):
        with pytest.raises(ValueError, match="unknown fault type"):
            faultinj.configure({"faults": {"x": {"type": "meltdown"}}})

    def test_corrupt_budget_and_after_scheduling(self):
        faultinj.configure(
            {"seed": 9, "faults": {"x": {"type": "corrupt", "percent": 100,
                                          "after": 2, "interceptionCount": 1}}}
        )
        data = bytes(64)
        assert faultinj.maybe_corrupt("x", data) == data  # after: held
        assert faultinj.maybe_corrupt("x", data) == data  # after: held
        assert faultinj.maybe_corrupt("x", data) != data  # armed, budget 1
        assert faultinj.maybe_corrupt("x", data) == data  # budget spent

    def test_corrupt_rule_inert_under_maybe_inject(self):
        faultinj.configure(
            {"faults": {"x": {"type": "corrupt", "percent": 100,
                               "interceptionCount": 1}}}
        )
        faultinj.maybe_inject("x")  # must not raise, burn budget, or kill
        data = bytes(16)
        assert faultinj.maybe_corrupt("x", data) != data  # budget intact

    def test_inject_rule_inert_under_maybe_corrupt(self):
        faultinj.configure(
            {"faults": {"x": {"type": "retryable", "percent": 100,
                               "interceptionCount": 1}}}
        )
        data = bytes(16)
        assert faultinj.maybe_corrupt("x", data) == data  # wrong family
        with pytest.raises(RetryableError):
            faultinj.maybe_inject("x")  # budget intact for its own family


# ---------------------------------------------------------------------------
# SET_ARENA re-registration: gauges stay flat across re-uploads
# ---------------------------------------------------------------------------


def _send_set_arena(conn, size):
    import array

    fd = os.memfd_create("rereg-arena")
    os.ftruncate(fd, size)
    hdr = struct.pack("<IQ", sidecar.OP_SET_ARENA, 8) + struct.pack("<Q", size)
    conn.sendmsg(
        [hdr],
        [(socket.SOL_SOCKET, socket.SCM_RIGHTS, array.array("i", [fd]).tobytes())],
    )
    os.close(fd)
    status, rlen = struct.unpack("<IQ", sidecar._recv_exact(conn, 12))
    if rlen:
        sidecar._recv_exact(conn, rlen)
    assert (status & ~sidecar._FLAG_MASK) == sidecar.STATUS_OK


def test_set_arena_reregistration_keeps_gauges_flat():
    """ISSUE 5 satellite: a second SET_ARENA on the same connection
    REPLACES the catalog entry (unregister-then-register) — the
    memgov.arena* gauges must track exactly one arena at the latest
    size, never accumulate."""
    w = _InProcWorker()
    try:
        base = memgov.catalog().snapshot()
        conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        conn.connect(w.sock_path)
        _send_set_arena(conn, 1 << 16)
        snap1 = memgov.catalog().snapshot()
        assert snap1["arenas"] == base["arenas"] + 1
        assert snap1["arena_bytes"] == base["arena_bytes"] + (1 << 16)
        for size in (1 << 18, 1 << 16, 1 << 20):
            _send_set_arena(conn, size)
            snap = memgov.catalog().snapshot()
            assert snap["arenas"] == base["arenas"] + 1, "arena entry leaked"
            assert snap["arena_bytes"] == base["arena_bytes"] + size
        conn.close()
        time.sleep(0.2)  # the conn handler's finally unregisters
        snap_end = memgov.catalog().snapshot()
        assert snap_end["arenas"] == base["arenas"]
        assert snap_end["arena_bytes"] == base["arena_bytes"]
    finally:
        w.kill()


# ---------------------------------------------------------------------------
# pool: routing, failover, respawn, re-hydration (in-process tier)
# ---------------------------------------------------------------------------


class TestPoolFailover:
    def test_round_robin_routing(self, inproc_pool):
        payload = _groupby_payload()
        want = sidecar._dispatch(sidecar.OP_GROUPBY_SUM_F32, payload, "cpu")
        with retry.enabled(max_attempts=4, base_delay_ms=1):
            for _ in range(4):
                assert inproc_pool.call(sidecar.OP_GROUPBY_SUM_F32, payload) == want
        # both workers served traffic
        stats = inproc_pool.worker_stats(fold=False)
        assert set(stats) == {"w0", "w1"}

    def test_kill_one_worker_exactly_one_failover_zero_breaker_trips(
        self, inproc_pool
    ):
        payload = _groupby_payload()
        want = sidecar._dispatch(sidecar.OP_GROUPBY_SUM_F32, payload, "cpu")
        failovers0 = _counter("sidecar.pool.failovers")
        opened0 = _counter("sidecar.breaker.opened_total")
        fallbacks0 = _counter("sidecar.pool.host_fallbacks")
        # kill the worker the router will pick NEXT: the very next call
        # must fail over mid-flight
        victim = inproc_pool._workers[inproc_pool._rr % inproc_pool.size]
        victim.proc.kill()
        with retry.enabled(max_attempts=6, base_delay_ms=1):
            for _ in range(4):
                assert inproc_pool.call(sidecar.OP_GROUPBY_SUM_F32, payload) == want
        assert _counter("sidecar.pool.failovers") == failovers0 + 1
        assert _counter("sidecar.breaker.opened_total") == opened0
        assert _counter("sidecar.pool.host_fallbacks") == fallbacks0
        assert inproc_pool.wait_healthy(20), "respawn did not complete"

    def test_whole_pool_dark_degrades_to_host_and_counts_breaker(self):
        pool = sidecar_pool.SidecarPool(
            size=2, deadline_s=5, heartbeat_s=1e9, spawn_fn=_inproc_spawn
        )
        try:
            payload = _groupby_payload()
            want = sidecar._dispatch(sidecar.OP_GROUPBY_SUM_F32, payload, "cpu")
            fallbacks0 = _counter("sidecar.pool.host_fallbacks")
            # stop the respawner from resurrecting anyone, then kill all
            pool._respawn_max = 0
            for w in pool._workers:
                w.proc.kill()
            with retry.enabled(max_attempts=3, base_delay_ms=1):
                got = pool.call(sidecar.OP_GROUPBY_SUM_F32, payload)
            assert got == want  # results keep flowing: host engine floor
            assert _counter("sidecar.pool.host_fallbacks") == fallbacks0 + 1
        finally:
            pool.shutdown()
            # scrub breaker state for later tests
            sidecar.breaker().reset()

    def test_arena_rehydration_on_respawn(self, inproc_pool):
        payload = _groupby_payload()
        want = sidecar._dispatch(sidecar.OP_GROUPBY_SUM_F32, payload, "cpu")
        inproc_pool.set_arena(1 << 20)
        rehydr0 = _counter("sidecar.pool.rehydrations")
        with retry.enabled(max_attempts=6, base_delay_ms=1):
            assert inproc_pool.call_arena(
                sidecar.OP_GROUPBY_SUM_F32, payload
            ) == want
            victim = inproc_pool._workers[inproc_pool._rr % inproc_pool.size]
            victim.proc.kill()
            # the region is scratch (the response replaces the request
            # payload): the POOL's per-call snapshot replays the request
            # bytes under a fresh generation across failover attempts
            assert inproc_pool.call_arena(
                sidecar.OP_GROUPBY_SUM_F32, payload
            ) == want
        assert inproc_pool.wait_healthy(20)
        assert _counter("sidecar.pool.rehydrations") == rehydr0 + 1
        # the respawned worker serves region traffic (slab re-uploaded)
        with retry.enabled(max_attempts=6, base_delay_ms=1):
            for _ in range(2):
                assert inproc_pool.call_arena(
                    sidecar.OP_GROUPBY_SUM_F32, payload
                ) == want

    def test_stream_ops_work_after_slab_arena(self, inproc_pool):
        """Slab-mode connections never answer STREAM ops through the
        arena (that opportunism is what serialized the whole pool):
        stream requests keep streaming after the slab exists, and the
        responses arrive promptly on the socket."""
        payload = _groupby_payload()
        want = sidecar._dispatch(sidecar.OP_GROUPBY_SUM_F32, payload, "cpu")
        inproc_pool.ensure_slab()
        t0 = time.monotonic()
        with retry.enabled(max_attempts=4, base_delay_ms=1):
            for _ in range(3):
                assert inproc_pool.call(sidecar.OP_GROUPBY_SUM_F32, payload) == want
        assert time.monotonic() - t0 < 5, "stream op stalled after slab upload"

    def test_arena_survives_client_reconnect(self, inproc_pool):
        """Worker-side arena state is per-connection: a client redial
        (timeout, desync close) silently drops it, so the pool must
        replay SET_ARENA on the fresh connection — a region op after a
        reconnect stays on the device path, never a host fallback."""
        payload = _groupby_payload()
        want = sidecar._dispatch(sidecar.OP_GROUPBY_SUM_F32, payload, "cpu")
        inproc_pool.ensure_slab()
        rehydr0 = _counter("sidecar.pool.rehydrations")
        fallbacks0 = _counter("sidecar.pool.host_fallbacks")
        with retry.enabled(max_attempts=4, base_delay_ms=1):
            assert inproc_pool.call_arena(
                sidecar.OP_GROUPBY_SUM_F32, payload
            ) == want
            # force redials on every slot WITHOUT killing any worker
            for w in inproc_pool._workers:
                w.client.close()
            assert inproc_pool.call_arena(
                sidecar.OP_GROUPBY_SUM_F32, payload
            ) == want
        assert _counter("sidecar.pool.rehydrations") == rehydr0 + 1
        assert _counter("sidecar.pool.host_fallbacks") == fallbacks0
        assert inproc_pool.live_count() == 2  # nobody was declared dead

    def test_oversized_region_write_is_retryable_with_needed_size(
        self, inproc_pool
    ):
        """ISSUE 6 satellite: a request larger than its leased region
        raises RetryableError carrying the needed size (and the
        RESOURCE_EXHAUSTED marker retry-with-split keys on) — never a
        silent truncated write."""
        region = inproc_pool.lease(64)
        try:
            with pytest.raises(RetryableError, match="RESOURCE_EXHAUSTED") as ei:
                region.write(b"x" * (region.capacity + 1))
            assert str(region.capacity + 1) in str(ei.value)  # needed size
            assert retry.is_resource_exhausted(ei.value)  # split engages
        finally:
            region.release()

    def test_legacy_arena_len_overflow_is_retryable(self):
        """The SupervisedClient legacy single-buffer path enforces the
        same contract: arena_len beyond the mapped arena raises
        retryably with the needed size instead of ValueError."""
        import mmap as mmap_mod

        client = sidecar.SupervisedClient("/nonexistent.sock", deadline_s=1)
        client.arena_mm = mmap_mod.mmap(-1, 4096)
        try:
            with pytest.raises(RetryableError, match="RESOURCE_EXHAUSTED"):
                client._raw_request(sidecar.OP_PING, b"", arena_len=8192)
        finally:
            client.arena_mm.close()
            client.arena_mm = None

    def test_shutdown_joins_inflight_respawn_and_reaps(self):
        """shutdown() during an in-flight respawn must JOIN the
        respawner so the worker it was mid-spawning is reaped, not
        orphaned — a daemon thread killed at interpreter exit inside
        spawn_fn leaks a live child that outlives the pool (observed as
        stray sidecar processes holding the parent's stdio pipes)."""
        entered = threading.Event()
        release = threading.Event()
        spawned = []

        def spawn_fn(startup_timeout_s=None, env=None):
            if len(spawned) >= 2:  # a RESPAWN, not an initial spawn
                entered.set()
                release.wait(20)
            w = _InProcWorker()
            spawned.append(w)
            return w, w.sock_path

        pool = sidecar_pool.SidecarPool(
            size=2, deadline_s=5, heartbeat_s=1e9, spawn_fn=spawn_fn
        )
        try:
            victim = pool._workers[0]
            victim.proc.kill()
            pool._on_worker_failure(victim, RetryableError("Socket closed"))
            t = victim.respawn_thread
            assert t is not None
            # shutdown must catch the respawner INSIDE spawn_fn — the
            # leak window this test exists for
            assert entered.wait(10), "respawner never reached spawn_fn"
            # unblock the spawner just after shutdown starts waiting
            threading.Timer(0.2, release.set).start()
            pool.shutdown()
            assert not t.is_alive(), "shutdown returned with respawner live"
            assert len(spawned) == 3
            assert spawned[-1].returncode is not None, (
                "respawned-during-shutdown worker was leaked, not reaped"
            )
        finally:
            release.set()
            pool.shutdown()

    def test_pool_size_env_default(self, monkeypatch):
        monkeypatch.delenv("SRJT_SIDECAR_POOL_SIZE", raising=False)
        pool = sidecar_pool.SidecarPool(spawn_fn=_inproc_spawn)
        try:
            assert pool.size == 1  # today's behavior
        finally:
            pool.shutdown()
        monkeypatch.setenv("SRJT_SIDECAR_POOL_SIZE", "3")
        pool = sidecar_pool.SidecarPool(spawn_fn=_inproc_spawn)
        try:
            assert pool.size == 3
        finally:
            pool.shutdown()


# ---------------------------------------------------------------------------
# STATS aggregation across the pool
# ---------------------------------------------------------------------------


class TestPoolStats:
    def test_worker_stats_keyed_per_worker_and_folded(self, inproc_pool):
        payload = _groupby_payload()
        with retry.enabled(max_attempts=4, base_delay_ms=1):
            for _ in range(2):
                inproc_pool.call(sidecar.OP_GROUPBY_SUM_F32, payload)
        stats = inproc_pool.worker_stats(fold=True)
        assert set(stats) == {"w0", "w1"}
        for s in stats.values():
            assert s["backend"] == "cpu"
            assert "snapshot" in s
        snap = metrics.snapshot()["gauges"]
        # clean per-worker keying: the base sidecar.worker. namespace is
        # stripped before the w<id> prefix — never a stuttered
        # sidecar.worker.w0.sidecar.worker.requests.PING
        assert "sidecar.worker.w0.requests.GROUPBY_SUM_F32" in snap
        assert "sidecar.worker.w1.requests.GROUPBY_SUM_F32" in snap
        assert not any("sidecar.worker.w0.sidecar.worker." in k for k in snap)

    def test_runtime_device_stats_merges_pool_workers(self):
        from spark_rapids_jni_tpu import runtime

        pool = sidecar_pool.connect_pool(
            size=2, deadline_s=20, heartbeat_s=1e9, spawn_fn=_inproc_spawn
        )
        try:
            stats = runtime.device_stats(fold=True)
            assert stats is not None
            assert set(stats["pool_workers"]) == {"w0", "w1"}
        finally:
            sidecar_pool.shutdown_pool()
        assert sidecar_pool.current_pool() is None

    def test_stats_report_has_pool_and_integrity_sections(self, inproc_pool):
        from spark_rapids_jni_tpu import runtime

        rep = runtime.stats_report()
        assert "integrity" in rep and "crc_mismatch" in rep["integrity"]
        assert "pool" in rep  # None without a GLOBAL pool: key present
        srep = metrics.stage_report("x")
        assert "failovers" in srep["pool"]
        assert "crc_mismatch" in srep["integrity"]
        assert json.dumps(rep["integrity"])  # JSON-clean

    def test_pool_snapshot_shape(self, inproc_pool):
        snap = inproc_pool.snapshot()
        assert snap["size"] == 2 and snap["live"] == 2
        assert set(snap["workers"]) == {"w0", "w1"}
        assert json.dumps(snap)  # JSON-clean


# ---------------------------------------------------------------------------
# real subprocess workers: kill -9 + chaos storm (slow tier; premerge
# runs these env-armed in the crash-storm tier)
# ---------------------------------------------------------------------------


def _roundtrip_table_through_pool(pool, table):
    """Ship ``table`` through the pool's device row-conversion pair
    (CONVERT_TO_ROWS -> CONVERT_FROM_ROWS) and rebuild it — the
    mid-query device traffic the failover must carry."""
    payload = sidecar._write_table(table)
    resp = pool.call(sidecar.OP_CONVERT_TO_ROWS, payload)
    (nbatches,) = struct.unpack_from("<I", resp, 0)
    assert nbatches == 1
    pos = 4
    (nrows,) = struct.unpack_from("<Q", resp, pos)
    pos += 8
    offs = resp[pos : pos + 4 * (nrows + 1)]
    pos += 4 * (nrows + 1)
    (blen,) = struct.unpack_from("<Q", resp, pos)
    pos += 8
    blob = resp[pos : pos + blen]
    dtypes = list(table.dtypes())
    req = (
        struct.pack("<I", len(dtypes))
        + np.asarray([int(d.id) for d in dtypes], np.int32).tobytes()
        + np.asarray([getattr(d, "scale", 0) or 0 for d in dtypes], np.int32).tobytes()
        + struct.pack("<Q", nrows)
        + offs
        + struct.pack("<Q", blen)
        + blob
    )
    out = pool.call(sidecar.OP_CONVERT_FROM_ROWS, req)
    rebuilt = sidecar._read_table(out)
    return Table(rebuilt.columns, list(table.names))


class TestRealWorkerPool:
    def test_q1_bit_identical_through_kill9_failover(self):
        """The acceptance scenario: TPC-H q1's device traffic rides a
        pool of 2 REAL workers; one is kill -9'd mid-query. The query
        result must be bit-identical to the host oracle, with exactly
        one failover and zero breaker trips."""
        from spark_rapids_jni_tpu.models.tpch import gen_lineitem, q1

        lineitem = gen_lineitem(300, seed=7)
        oracle = q1(lineitem)
        want = [np.asarray(c.data).tobytes() for c in oracle.columns]

        failovers0 = _counter("sidecar.pool.failovers")
        opened0 = _counter("sidecar.breaker.opened_total")
        pool = sidecar_pool.SidecarPool(
            size=2, deadline_s=60, heartbeat_s=1e9, startup_timeout_s=180
        )
        try:
            with retry.enabled(max_attempts=6, base_delay_ms=1):
                # warm pass, no faults: the device path round-trips
                warm = _roundtrip_table_through_pool(pool, lineitem)
                # kill the worker the router picks next, MID-QUERY
                victim = pool._workers[pool._rr % pool.size]
                os.kill(victim.proc.pid, signal.SIGKILL)
                cold = _roundtrip_table_through_pool(pool, lineitem)
            for t in (warm, cold):
                got = [np.asarray(c.data).tobytes() for c in q1(t).columns]
                assert got == want, "q1 diverged from the host oracle"
            assert _counter("sidecar.pool.failovers") == failovers0 + 1
            assert _counter("sidecar.breaker.opened_total") == opened0
            assert pool.wait_healthy(180), "kill -9 victim was not respawned"
        finally:
            pool.shutdown()

    def test_crash_and_corrupt_storm_survives(self):
        """ci/chaos_crash.json armed inside REAL workers: `crash` SIGKILLs
        a worker mid-op, `corrupt` flips response bytes under the CRC.
        Every op must land exact (failover / re-fetch / host floor), with
        the storm visibly caught in the metrics. ONE source of truth: the
        workers load the same profile ci/premerge.sh documents, so the
        committed file and the gate cannot drift (the test_chaos pattern)."""
        cfg = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "ci", "chaos_crash.json",
        )
        deaths0 = _counter("sidecar.pool.worker_deaths")
        mismatch0 = _counter("sidecar.integrity.crc_mismatch")
        pool = sidecar_pool.SidecarPool(
            size=2, deadline_s=60, heartbeat_s=1e9, startup_timeout_s=180,
            env={"SRJT_FAULTINJ_CONFIG": cfg},
        )
        try:
            payload = _groupby_payload()
            want_g = sidecar._dispatch(sidecar.OP_GROUPBY_SUM_F32, payload, "cpu")
            tbl = Table(
                [Column(dt.INT32, data=jnp.arange(128, dtype=jnp.int32))], ["a"]
            )
            tp = sidecar._write_table(tbl)
            want_c = sidecar._dispatch(sidecar.OP_CONVERT_TO_ROWS, tp, "cpu")
            with retry.enabled(max_attempts=8, base_delay_ms=1):
                for _ in range(4):
                    assert pool.call(sidecar.OP_CONVERT_TO_ROWS, tp) == want_c
                for _ in range(3):
                    assert pool.call(sidecar.OP_GROUPBY_SUM_F32, payload) == want_g
            # the storm actually fired AND was contained
            assert _counter("sidecar.pool.worker_deaths") > deaths0
            assert _counter("sidecar.integrity.crc_mismatch") > mismatch0
        finally:
            pool.shutdown()
            sidecar.breaker().reset()

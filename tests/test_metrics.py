"""Runtime metrics subsystem (ISSUE 2): registry semantics (threaded
increments, log2 histogram bucketing, disabled-mode no-ops), the
structured JSON-lines event log, the cross-layer stats_report, the
sidecar STATS protocol verb, and the chaos-integration exactness
contract — retry/split counters must match the faults injected by
utils/faultinj.py BIT-EXACTLY (deterministic budgets, percent=100)."""

import json
import threading

import numpy as np
import pytest

import spark_rapids_jni_tpu  # noqa: F401
import jax.numpy as jnp

from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.columnar import dtype as dt
from spark_rapids_jni_tpu.utils import faultinj, memory, metrics, retry
from spark_rapids_jni_tpu.utils.dispatch import op_boundary
from spark_rapids_jni_tpu.utils.errors import FatalDeviceError, RetryableError


@pytest.fixture(autouse=True)
def _clean_state():
    """Metrics may arrive armed from the environment (the premerge
    observability tier runs this file with SRJT_METRICS_ENABLED=1);
    every test pins its own arming and leaves a zeroed registry."""
    prev = metrics.is_enabled()
    faultinj.disable()
    retry.disable()
    retry.reset_stats()
    metrics.reset()
    yield
    faultinj.disable()
    retry.disable()
    retry.reset_stats()
    metrics.reset()
    (metrics.enable if prev else metrics.disable)()


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------


def test_counter_threaded_increments_are_exact():
    c = metrics.registry().counter("t.threads")
    n_threads, per = 8, 5000

    def work():
        for _ in range(per):
            c.inc()

    ts = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert c.value == n_threads * per


def test_histogram_log2_buckets():
    h = metrics.registry().histogram("t.hist")
    # bucket k spans [2^(k-1), 2^k); bucket 0 holds < 1
    for v in (0, 0.5, 1, 1.9, 2, 3, 4, 7, 8, 1000):
        h.record(v)
    snap = h._snapshot()
    assert snap["count"] == 10
    assert snap["min"] == 0 and snap["max"] == 1000
    assert snap["buckets"] == {
        "0": 2,      # 0, 0.5
        "1": 2,      # 1, 1.9
        "2": 2,      # 2, 3
        "4": 2,      # 4, 7
        "8": 1,      # 8
        "512": 1,    # 1000 in [512, 1024)
    }


def test_registry_type_collision_is_loud():
    metrics.registry().counter("t.kind")
    with pytest.raises(TypeError, match="already registered"):
        metrics.registry().gauge("t.kind")


def test_gauge_set_and_snapshot_shape():
    metrics.registry().gauge("t.g").set(41)
    metrics.registry().counter("t.c").inc(3)
    snap = metrics.snapshot()
    assert snap["gauges"]["t.g"] == 41
    assert snap["counters"]["t.c"] == 3
    assert set(snap) == {"counters", "gauges", "histograms"}
    json.dumps(snap)  # must be JSON-serializable as-is


def test_reset_zeroes_but_keeps_names():
    metrics.registry().counter("t.r").inc(5)
    metrics.registry().histogram("t.rh").record(9)
    metrics.reset()
    assert metrics.registry().counter("t.r").value == 0
    assert metrics.registry().histogram("t.rh").count == 0
    assert "t.r" in metrics.registry().names()


# ---------------------------------------------------------------------------
# disabled-mode overhead guard
# ---------------------------------------------------------------------------


def test_disabled_mode_is_noop():
    """The overhead-guard contract (premerge asserts this test): with
    metrics disarmed, the gated accessors hand out no-op stubs, the op
    boundary records nothing and reads no clock-derived state, and the
    event log stays untouched — an instrumented hot path costs one
    boolean read."""

    @op_boundary("metrics_guard_op")
    def op():
        return 11

    with metrics.disabled():
        c = metrics.counter("guard.c")
        c.inc(100)
        metrics.histogram("guard.h").record(5)
        metrics.gauge("guard.g").set(5)
        with metrics.timer("guard.t"):
            pass
        metrics.event("guard.event", x=1)
        assert op() == 11
    names = metrics.registry().names()
    assert not any(n.startswith("guard.") for n in names)
    assert not any(n.startswith("op.metrics_guard_op") for n in names)
    # the stub is shared and inert
    assert c.value == 0


def test_enabled_op_boundary_records_calls_and_wall_time():
    @op_boundary("metrics_timed_op")
    def op():
        return 5

    with metrics.enabled():
        for _ in range(3):
            assert op() == 5
        snap = metrics.snapshot()
    assert snap["counters"]["op.metrics_timed_op.calls"] == 3
    h = snap["histograms"]["op.metrics_timed_op.wall_us"]
    assert h["count"] == 3 and h["sum"] >= 0


# ---------------------------------------------------------------------------
# structured event log
# ---------------------------------------------------------------------------


def test_event_log_json_lines(tmp_path):
    path = str(tmp_path / "events.jsonl")
    with metrics.enabled(log_path=path):
        metrics.event("unit.test", op="x", n=3)
        metrics.event("unit.test2", nested={"a": 1})
    metrics.close_log()
    lines = [json.loads(s) for s in open(path).read().splitlines()]
    assert [r["event"] for r in lines] == ["unit.test", "unit.test2"]
    assert lines[0]["op"] == "x" and lines[0]["n"] == 3
    assert lines[1]["nested"] == {"a": 1}
    assert all("ts" in r for r in lines)


def test_event_log_disabled_without_path(tmp_path):
    with metrics.enabled():  # armed, but no path configured
        prev = metrics.log_path()
        metrics.set_log_path(None)
        try:
            metrics.event("nowhere")
        finally:
            metrics.set_log_path(prev)
    # nothing to assert beyond "did not raise"; the payoff is above


# ---------------------------------------------------------------------------
# chaos integration: counters match injected faults EXACTLY
# ---------------------------------------------------------------------------


def test_retry_counters_match_injected_fault_budget():
    """percent=100 + interceptionCount=N makes the injector fire on
    exactly the first N dispatches of the op; with the orchestrator
    armed the metrics must read exactly N retries of the injected
    class, N+1 attempts, one op call — bit-exact, not >=."""

    @op_boundary("metrics_chaos_op")
    def op():
        return 42

    faultinj.configure(
        {"seed": 7, "faults": {"metrics_chaos_op": {
            "type": "retryable", "percent": 100, "interceptionCount": 4}}}
    )
    with metrics.enabled(), retry.enabled(max_attempts=10, base_delay_ms=0):
        assert op() == 42
    snap = metrics.snapshot()["counters"]
    assert snap["retry.retries"] == 4
    assert snap["retry.retries.RetryableError"] == 4
    assert snap["retry.attempts"] == 5  # 4 failures + the success
    assert snap["op.metrics_chaos_op.calls"] == 1
    assert snap.get("retry.fatal", 0) == 0
    assert snap.get("retry.exhausted", 0) == 0
    # the always-on retry stats agree with the registry mirror
    s = retry.stats()
    assert s["retries"] == 4 and s["attempts"] == 5


def test_fatal_fault_counts_once_and_never_retries():
    @op_boundary("metrics_fatal_op")
    def op():
        return 1

    faultinj.configure(
        {"faults": {"metrics_fatal_op": {
            "type": "fatal", "percent": 100, "interceptionCount": 1}}}
    )
    with metrics.enabled(), retry.enabled(max_attempts=5, base_delay_ms=0):
        with pytest.raises(FatalDeviceError):
            op()
    snap = metrics.snapshot()["counters"]
    assert snap["retry.fatal"] == 1
    assert snap.get("retry.retries", 0) == 0  # fatal NEVER retries


def test_split_counters_match_split_tree():
    """Deterministic split tree: an 8-row batch failing RESOURCE_
    EXHAUSTED above 2 rows splits 8 -> 4+4 -> (2,2)+(2,2): exactly 3
    split events, 4 leaf successes."""
    calls = []

    def fn(b):
        calls.append(len(b))
        if len(b) > 2:
            raise RetryableError("RESOURCE_EXHAUSTED: batch too big")
        return sum(b)

    with metrics.enabled():
        out = retry.retry_with_split(
            fn, list(range(8)),
            split=lambda b: (b[: len(b) // 2], b[len(b) // 2:]),
            combine=lambda ps: sum(ps),
            policy=retry.RetryPolicy(max_attempts=1, split_depth=4),
        )
    assert out == sum(range(8))
    snap = metrics.snapshot()["counters"]
    assert snap["retry.splits"] == 3
    assert snap["retry.splits.RetryableError"] == 3
    assert retry.stats()["splits"] == 3


def test_chaos_event_log_records_each_injected_fault(tmp_path):
    path = str(tmp_path / "chaos.jsonl")

    @op_boundary("metrics_logged_op")
    def op():
        return 9

    faultinj.configure(
        {"faults": {"metrics_logged_op": {
            "type": "retryable", "percent": 100, "interceptionCount": 2}}}
    )
    with metrics.enabled(log_path=path), retry.enabled(
        max_attempts=5, base_delay_ms=0
    ):
        assert op() == 9
    metrics.close_log()
    events = [json.loads(s) for s in open(path).read().splitlines()]
    backoffs = [e for e in events if e["event"] == "retry.backoff"]
    assert len(backoffs) == 2  # one line per injected fault
    assert all(e["op"] == "metrics_logged_op" for e in backoffs)


# ---------------------------------------------------------------------------
# memory split counter migration (satellite 1)
# ---------------------------------------------------------------------------


def test_split_retry_count_is_registry_alias():
    before = memory.split_retry_count()
    assert before == metrics.registry().counter("memory.split_retries").value
    memory._note_split()
    assert memory.split_retry_count() == before + 1
    assert metrics.registry().counter("memory.split_retries").value == before + 1


def test_split_counter_counts_with_metrics_disabled():
    # the migration must not regress the always-on contract: splits
    # count whether or not the hot-path tier is armed
    with metrics.disabled():
        before = memory.split_retry_count()
        memory._note_split()
        assert memory.split_retry_count() == before + 1


# ---------------------------------------------------------------------------
# shuffle instrumentation (distributed tier)
# ---------------------------------------------------------------------------


def test_shuffle_exchange_records_bytes_and_escalations():
    import jax

    from spark_rapids_jni_tpu.parallel import mesh as mesh_mod, shuffle

    assert len(jax.devices()) == 8, "conftest must force the 8-device CPU mesh"
    mesh = mesh_mod.make_mesh({"data": 8})
    rng = np.random.default_rng(5)
    n = 8 * 64
    # heavy skew: everything lands on a few shards, forcing the
    # capacity=4 start to escalate geometrically
    keys = rng.integers(0, 3, n).astype(np.int64)
    t = Table(
        [Column(dt.INT64, data=jnp.asarray(keys)),
         Column(dt.INT64, data=jnp.asarray(rng.integers(0, 100, n)))],
        ["k", "v"],
    )
    part, _ = shuffle.hash_partition(t, 8, ["k"])
    t_s = mesh_mod.shard_table_rows(part, mesh)
    with metrics.enabled():
        pairs, mask, overflow = shuffle.exchange_by_key(
            t_s, ["k"], mesh, capacity=4, on_overflow="retry"
        )
        snap = metrics.snapshot()
    assert not bool(np.asarray(overflow).any())
    c = snap["counters"]
    assert c["shuffle.exchanges"] == 1
    assert c["shuffle.bytes_exchanged"] >= 2 * n * 8  # two i64 columns
    assert c["shuffle.capacity_retries"] >= 1
    assert snap["histograms"]["shuffle.exchange_us"]["count"] == 1
    # the orchestrator's own stats saw the same escalations
    assert retry.stats()["capacity_retries"] == c["shuffle.capacity_retries"]


# ---------------------------------------------------------------------------
# stats_report: the end-to-end snapshot
# ---------------------------------------------------------------------------


def test_stats_report_sections_and_pretty_render():
    from spark_rapids_jni_tpu import runtime

    @op_boundary("metrics_report_op")
    def op():
        return 1

    with metrics.enabled():
        op()
        rep = runtime.stats_report()
        assert set(rep) >= {"metrics", "retry", "memory", "native_sidecar"}
        assert rep["metrics"]["counters"]["op.metrics_report_op.calls"] == 1
        assert rep["memory"]["split_retries"] == memory.split_retry_count()
        json.dumps(rep)  # the snapshot artifact is JSON-clean
        text = runtime.stats_report(pretty=True)
    assert isinstance(text, str)
    assert "op.metrics_report_op.calls" in text


def test_bench_stage_report_shape():
    with metrics.enabled():
        with metrics.timer("bench.stage_x"):
            pass
        rep = metrics.stage_report("stage_x")
    assert rep["stage"] == "stage_x"
    assert "bench.stage_x" in rep["ops"]
    assert set(rep["shuffle"]) == {"exchanges", "bytes_exchanged",
                                   "capacity_retries"}
    assert "retries" in rep["retry"]
    assert "split_retries" in rep["memory"]


# ---------------------------------------------------------------------------
# sidecar STATS protocol verb (worker side, pure Python — no native lib)
# ---------------------------------------------------------------------------


def test_sidecar_stats_verb_and_fold(tmp_path):
    from spark_rapids_jni_tpu import sidecar

    proc, sock = sidecar.spawn_worker(startup_timeout_s=120)
    try:
        with metrics.enabled():
            client = sidecar.SupervisedClient(sock, deadline_s=60,
                                              heartbeat_s=1e9)
            with client:
                assert client.ping() == "cpu"
                stats = client.worker_stats()
                counters = stats["snapshot"]["counters"]
                # 2 PINGs: spawn_worker's startup handshake + the
                # explicit heartbeat above (ISSUE 3 spawn hardening)
                assert counters["sidecar.worker.requests.PING"] == 2
                assert counters["sidecar.worker.requests.STATS"] == 1
                # folded into THIS process's registry as gauges
                snap = metrics.snapshot()
                assert snap["gauges"]["sidecar.worker.requests.PING"] == 2
                # client-side supervision counters recorded too
                assert snap["counters"]["sidecar.heartbeats"] == 1
                # the stats poll must NOT count itself into the
                # data-path counters it reports (native-client parity)
                assert snap["counters"].get("sidecar.requests", 0) == 0
                # a real data op DOES count
                tbl = Table(
                    [Column(dt.INT32, data=jnp.arange(8, dtype=jnp.int32))],
                    ["a"],
                )
                client.request(sidecar.OP_CONVERT_TO_ROWS,
                               sidecar._write_table(tbl))
                snap = metrics.snapshot()
                assert snap["counters"]["sidecar.requests"] == 1
                assert snap["histograms"]["sidecar.request_us"]["count"] == 1
    finally:
        if proc.poll() is None:
            proc.terminate()
        proc.wait(timeout=30)


def test_sidecar_degrade_records_fallback_metrics(tmp_path):
    """A worker-side fatal fault degrades to the host engine and the
    registry shows exactly one fallback event."""
    from spark_rapids_jni_tpu import sidecar

    cfg = tmp_path / "faults.json"
    cfg.write_text(
        '{"faults": {"convert_to_rows": {"type": "fatal", "percent": 100}}}'
    )
    proc, sock = sidecar.spawn_worker(
        startup_timeout_s=120, env={"SRJT_FAULTINJ_CONFIG": str(cfg)}
    )
    try:
        with metrics.enabled():
            client = sidecar.SupervisedClient(sock, deadline_s=60,
                                              heartbeat_s=1e9)
            with client:
                tbl = Table(
                    [Column(dt.INT32, data=jnp.arange(16, dtype=jnp.int32))],
                    ["a"],
                )
                payload = sidecar._write_table(tbl)
                with retry.enabled(max_attempts=3, base_delay_ms=1):
                    resp = client.call(sidecar.OP_CONVERT_TO_ROWS, payload)
                assert resp == sidecar._dispatch(
                    sidecar.OP_CONVERT_TO_ROWS, payload, "cpu"
                )
            snap = metrics.snapshot()["counters"]
        assert snap["sidecar.host_fallbacks"] == 1
        assert client.host_fallbacks == 1  # instance attr stays in step
    finally:
        if proc.poll() is None:
            proc.terminate()
        proc.wait(timeout=30)

"""srjt-cbo (ISSUE 19): property tests for the statistics subsystem —
HLL distinct counts within 2x of truth on uniform/skewed/null-heavy/
empty columns, equi-depth histogram selectivity bounds, the exact
``unique`` witness (never True under sampling or nulls — the build-side
rules bet correctness on it), and generation-stamp cache hygiene
(a declared mutation is never served a stale sketch)."""

import numpy as np
import pytest

import jax.numpy as jnp
import spark_rapids_jni_tpu  # noqa: F401
from spark_rapids_jni_tpu import plan as P
from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.columnar import dtype as dt
from spark_rapids_jni_tpu.plan import stats as S


def icol(a, d=dt.INT32):
    return Column(d, data=jnp.asarray(np.asarray(a, np.dtype(d.np_dtype))))


def fcol(a):
    return Column(dt.FLOAT64,
                  data=jnp.asarray(np.asarray(a, np.float64).view(np.uint64)))


def vcol(a, valid):
    return Column(dt.INT32,
                  data=jnp.asarray(np.asarray(a, np.int32)),
                  validity=jnp.asarray(np.asarray(valid, bool)))


@pytest.fixture(autouse=True)
def _fresh_stats():
    S.reset()
    yield
    S.reset()


# ---------------------------------------------------------------------------
# HLL distinct counts: within 2x of truth across value shapes
# ---------------------------------------------------------------------------


class TestHll:
    def test_uniform_within_2x(self, rng):
        vals = rng.integers(0, 5000, 20000)
        truth = len(set(vals.tolist()))
        sk = S.sketch_column(icol(vals))
        assert truth / 2 <= sk.ndv <= truth * 2
        assert sk.rows == 20000 and sk.nulls == 0
        assert sk.min_val == float(vals.min())
        assert sk.max_val == float(vals.max())

    def test_skewed_within_2x(self, rng):
        # zipf head-heavy: a few values carry most of the mass, long
        # sparse tail — the regime plain sampling misestimates worst
        vals = np.minimum(rng.zipf(1.3, 20000), np.int64(1) << 40)
        truth = len(set(vals.tolist()))
        sk = S.sketch_column(icol(vals, dt.INT64))
        assert truth / 2 <= sk.ndv <= truth * 2

    def test_null_heavy_within_2x(self, rng):
        n = 8000
        vals = rng.integers(0, 300, n)
        valid = rng.random(n) < 0.1  # ~90% null
        truth = len(set(vals[valid].tolist()))
        sk = S.sketch_column(vcol(vals, valid))
        assert truth / 2 <= sk.ndv <= truth * 2
        assert sk.null_fraction == pytest.approx(1.0 - valid.mean(), abs=0.01)
        assert not sk.unique

    def test_float_lanes_decoded_before_sketching(self, rng):
        # FLOAT64 columns store uint64 bit-lanes; min/max/ndv must come
        # from the decoded logical domain, not the raw lane integers
        vals = rng.uniform(-10, 10, 5000).round(4)
        truth = len(set(vals.tolist()))
        sk = S.sketch_column(fcol(vals))
        assert sk.min_val == float(vals.min())
        assert sk.max_val == float(vals.max())
        assert truth / 2 <= sk.ndv <= truth * 2

    def test_empty_column(self):
        sk = S.sketch_column(icol([]))
        assert sk.rows == 0 and sk.ndv == 0.0
        assert sk.min_val is None and sk.max_val is None and sk.edges == ()
        assert sk.sel_cmp("lt", 5.0) == 0.0
        assert sk.sel_eq(1.0) == 0.0

    def test_all_null_column(self):
        n = 64
        sk = S.sketch_column(vcol(np.zeros(n), np.zeros(n, bool)))
        assert sk.nulls == n and sk.null_fraction == 1.0
        assert sk.ndv == 0.0 and sk.min_val is None
        assert not sk.unique


# ---------------------------------------------------------------------------
# equi-depth histogram selectivity
# ---------------------------------------------------------------------------


class TestHistogram:
    def test_sel_cmp_tracks_truth_within_a_bin(self, rng):
        n = 10000
        vals = rng.integers(0, 1000, n)
        sk = S.sketch_column(icol(vals), bins=16)
        for cut in (100.0, 250.0, 500.0, 900.0):
            truth = float((vals < cut).mean())
            est = sk.sel_cmp("lt", cut)
            assert 0.0 <= est <= 1.0
            # partial bins count in full: within one bin of the truth
            assert abs(est - truth) <= 1.0 / 16 + 0.02
        # complements partition the non-null mass
        assert sk.sel_cmp("lt", 500.0) + sk.sel_cmp("ge", 500.0) == \
            pytest.approx(1.0)

    def test_out_of_range_cuts_clamp(self, rng):
        vals = rng.integers(100, 200, 2000)
        sk = S.sketch_column(icol(vals))
        assert sk.sel_cmp("lt", 50.0) == 0.0
        assert sk.sel_cmp("gt", 500.0) == 0.0
        assert sk.sel_cmp("le", 500.0) == pytest.approx(1.0)
        assert sk.sel_eq(999.0) == 0.0

    def test_sel_eq_scales_with_ndv(self, rng):
        vals = rng.integers(0, 100, 5000)
        sk = S.sketch_column(icol(vals))
        # ~uniform over 100 distinct values: eq keeps ~1% (HLL slack)
        assert 0.004 <= sk.sel_eq(50.0) <= 0.03

    def test_predicate_selectivity_bounds(self, rng):
        n = 6000
        sketches = {
            "a": S.sketch_column(icol(rng.integers(0, 1000, n))),
            "b": S.sketch_column(icol(rng.integers(0, 10, n))),
        }
        resolve = sketches.get
        a_half = P.pcol("a") < P.plit(np.int32(500))
        b_half = P.pcol("b") >= P.plit(np.int32(5))
        half = S.selectivity(a_half, resolve)
        assert 0.35 <= half <= 0.65
        conj = S.selectivity(a_half & b_half, resolve)
        disj = S.selectivity(a_half | b_half, resolve)
        assert 0.0 <= conj <= half <= disj <= 1.0
        # unsketched column: the default, still a valid fraction
        unknown = S.selectivity(P.pcol("zzz") < P.plit(np.int32(3)), resolve)
        assert 0.0 < unknown < 1.0


# ---------------------------------------------------------------------------
# the exact `unique` witness
# ---------------------------------------------------------------------------


class TestUniqueWitness:
    def test_permutation_is_witnessed_unique(self, rng):
        assert S.sketch_column(icol(rng.permutation(1000))).unique

    def test_single_duplicate_defeats_witness(self, rng):
        v = rng.permutation(1000)
        v[500] = v[3]
        assert not S.sketch_column(icol(v)).unique

    def test_sampling_never_claims_unique(self, rng):
        # a head sample cannot PROVE global uniqueness, and the dense
        # build-side map rejects duplicate keys at runtime — so the
        # witness must drop to False the moment the scan is capped
        v = rng.permutation(4096)
        sk = S.sketch_column(icol(v), max_rows=1024)
        assert not sk.unique
        assert sk.rows == 4096
        # the sampled ndv is still scaled back to full-table ballpark
        assert 4096 / 2 <= sk.ndv <= 4096 * 2

    def test_nulls_defeat_witness(self):
        n = 100
        valid = np.ones(n, bool)
        valid[7] = False
        assert not S.sketch_column(vcol(np.arange(n), valid)).unique


# ---------------------------------------------------------------------------
# generation-stamp cache: never serve a stale sketch
# ---------------------------------------------------------------------------


class TestStampCache:
    def test_cache_hit_then_invalidate(self):
        t = Table([icol(np.arange(100))], ["k"])
        s1 = S.table_stats(t)
        assert S.table_stats(t) is s1  # generation-stamp hit
        S.invalidate_table(t)
        assert S.table_stats(t) is not s1

    def test_invalidate_never_serves_stale(self):
        t = Table([icol(np.arange(100))], ["k"])
        assert S.table_stats(t).sketch("k").max_val == 99.0
        t.columns[0] = icol(2 * np.arange(100))
        S.invalidate_table(t)
        assert S.table_stats(t).sketch("k").max_val == 198.0

    def test_distinct_tables_never_share(self):
        a = Table([icol(np.arange(10))], ["k"])
        b = Table([icol(np.arange(10))], ["k"])  # equal data, new identity
        assert S.table_stats(a) is not S.table_stats(b)

    def test_reset_clears(self):
        t = Table([icol(np.arange(10))], ["k"])
        s1 = S.table_stats(t)
        S.reset()
        assert S.table_stats(t) is not s1

    def test_memory_bytes_bounded(self, rng):
        t = Table(
            [icol(rng.integers(0, 1000, 50000)),
             fcol(rng.uniform(0, 1, 50000)),
             icol(rng.integers(0, 5, 50000), dt.INT64)],
            ["a", "b", "c"],
        )
        ts = S.table_stats(t)
        # sketches are O(bins), independent of the 50k-row table
        assert 0 < ts.memory_bytes < 16 * 1024


# ---------------------------------------------------------------------------
# knob surface
# ---------------------------------------------------------------------------


class TestKnobs:
    def test_stats_disabled_no_estimator(self, monkeypatch):
        monkeypatch.setenv("SRJT_STATS_ENABLED", "0")
        t = Table([icol(np.arange(10))], ["k"])
        assert S.make_estimator({"t": t}) is None

    def test_histogram_bins_knob(self, monkeypatch):
        monkeypatch.setenv("SRJT_STATS_HISTOGRAM_BINS", "4")
        t = Table([icol(np.arange(1000))], ["k"])
        assert len(S.table_stats(t).sketch("k").edges) == 5

    def test_max_rows_knob_forces_sampling(self, monkeypatch, rng):
        monkeypatch.setenv("SRJT_STATS_MAX_ROWS", "256")
        t = Table([icol(rng.permutation(2048))], ["k"])
        assert not S.table_stats(t).sketch("k").unique

"""Query-operator tier tests: gather/filter/sort/hash/groupby/join/expr.

pandas is the oracle for the relational semantics (it shares SQL's
null-handling for the cases under test).
"""

import numpy as np
import pandas as pd
import pytest

import spark_rapids_jni_tpu  # noqa: F401
import jax.numpy as jnp
from spark_rapids_jni_tpu.columnar import dtype as dt
from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.ops import copying, hashing, sort
from spark_rapids_jni_tpu.ops.aggregate import groupby_aggregate
from spark_rapids_jni_tpu.ops.expressions import col, lit
from spark_rapids_jni_tpu.ops.join import (
    full_join,
    inner_join,
    left_anti_join,
    left_join,
    left_semi_join,
)


def make_table(**cols):
    names, columns = [], []
    for name, (vals, d) in cols.items():
        names.append(name)
        columns.append(Column.from_pylist(vals, d))
    return Table(columns, names)


# ---------------------------------------------------------------------------
# copying
# ---------------------------------------------------------------------------


def test_gather_fixed_and_string():
    t = make_table(
        a=([10, 20, 30, 40], dt.INT32),
        s=(["aa", "b", None, "dddd"], dt.STRING),
    )
    g = copying.gather(t, jnp.asarray([3, 0, 0, 2], jnp.int32))
    assert g.column("a").to_pylist() == [40, 10, 10, 30]
    assert g.column("s").to_pylist() == ["dddd", "aa", "aa", None]


def test_gather_bounds_nullify():
    t = make_table(a=([1, 2], dt.INT32))
    g = copying.gather(t, jnp.asarray([0, 5, -1], jnp.int32), check_bounds=True)
    assert g.column("a").to_pylist() == [1, None, None]


def test_apply_boolean_mask():
    t = make_table(a=([1, 2, 3, 4, 5], dt.INT32), s=(["a", "b", "c", "d", "e"], dt.STRING))
    m = Column.from_pylist([True, False, None, True, False], dt.BOOL8)
    f = copying.apply_boolean_mask(t, m)
    assert f.column("a").to_pylist() == [1, 4]
    assert f.column("s").to_pylist() == ["a", "d"]


def test_concatenate():
    t1 = make_table(a=([1, 2], dt.INT32), s=(["x", None], dt.STRING))
    t2 = make_table(a=([3], dt.INT32), s=(["yz"], dt.STRING))
    c = copying.concatenate([t1, t2])
    assert c.column("a").to_pylist() == [1, 2, 3]
    assert c.column("s").to_pylist() == ["x", None, "yz"]


# ---------------------------------------------------------------------------
# sort
# ---------------------------------------------------------------------------


def test_sort_multi_key_with_nulls(rng):
    a = [3, 1, None, 2, 1, None, 3]
    b = [1.5, -2.0, 0.0, None, 7.25, 1.0, -1.5]
    t = make_table(a=(a, dt.INT32), b=(b, dt.FLOAT64))
    order = np.asarray(sort.sorted_order(t))
    df = pd.DataFrame({"a": a, "b": b})
    expected = df.sort_values(["a", "b"], na_position="first", kind="stable").index.tolist()
    # nulls_first=True for both; pandas puts NaN per-key: emulate by ranking
    key_a = [(-1 if v is None else v) for v in a]
    key_b = [(-np.inf if v is None else v) for v in b]
    expected = sorted(range(len(a)), key=lambda i: (key_a[i], key_b[i]))
    assert order.tolist() == expected


def test_sort_descending():
    t = make_table(a=([5, 1, 9, 3], dt.INT64))
    order = np.asarray(sort.sorted_order(t, ascending=[False]))
    assert order.tolist() == [2, 0, 3, 1]


def test_sort_float64_exact_order():
    vals = [1e300, -1e300, 1.0 + 2**-50, 1.0, -0.0, 0.0, 5e-324]
    t = make_table(a=(vals, dt.FLOAT64))
    order = np.asarray(sort.sorted_order(t))
    got = [vals[i] for i in order]
    assert got == sorted(vals)


def test_sort_strings():
    s = ["pear", "apple", None, "banana", "app"]
    t = make_table(s=(s, dt.STRING))
    order = np.asarray(sort.sorted_order(t))
    got = [s[i] for i in order]
    assert got == [None, "app", "apple", "banana", "pear"]


# ---------------------------------------------------------------------------
# hashing
# ---------------------------------------------------------------------------


def test_murmur3_deterministic_and_spread():
    t = make_table(a=(list(range(1000)), dt.INT32))
    h1 = np.asarray(hashing.murmur3_table(t))
    h2 = np.asarray(hashing.murmur3_table(t))
    assert (h1 == h2).all()
    assert len(np.unique(h1)) > 990  # good dispersion


def _mm3_oracle(v, seed=42):
    """Murmur3_x86_32 hashInt, pure python (Spark Murmur3Hash semantics)."""
    M = 0xFFFFFFFF

    def rotl(x, r):
        return ((x << r) | (x >> (32 - r))) & M

    k = (v & M) * 0xCC9E2D51 & M
    k = rotl(k, 15) * 0x1B873593 & M
    h = (rotl(seed ^ k, 13) * 5 + 0xE6546B64) & M
    h ^= 4
    h ^= h >> 16
    h = h * 0x85EBCA6B & M
    h ^= h >> 13
    h = h * 0xC2B2AE35 & M
    return h ^ (h >> 16)


def test_murmur3_int_oracle_values():
    vals = [0, 1, -1, 42, 2**31 - 1]
    t = make_table(a=(vals, dt.INT32))
    h = np.asarray(hashing.murmur3_table(t))
    assert h.tolist() == [_mm3_oracle(v) for v in vals]


def test_hash_partition_map_balanced():
    t = make_table(a=(list(range(10000)), dt.INT64))
    p = np.asarray(hashing.hash_partition_map(t, 8))
    counts = np.bincount(p, minlength=8)
    assert (p >= 0).all() and (p < 8).all()
    assert counts.min() > 1000  # roughly balanced


# ---------------------------------------------------------------------------
# groupby
# ---------------------------------------------------------------------------


def test_groupby_sum_count_minmax(rng):
    keys = [int(k) for k in rng.integers(0, 7, 200)]
    vals = [float(v) for v in rng.normal(size=200)]
    some_null = [v if i % 13 else None for i, v in enumerate(vals)]
    t_keys = make_table(k=(keys, dt.INT32))
    t_vals = make_table(v=(some_null, dt.FLOAT64))
    out = groupby_aggregate(t_keys, t_vals, [("v", "sum"), ("v", "count"), ("v", "min"), ("v", "max")])

    df = pd.DataFrame({"k": keys, "v": some_null})
    exp = df.groupby("k")["v"].agg(["sum", "count", "min", "max"]).reset_index()
    assert out.column("k").to_pylist() == exp["k"].tolist()
    np.testing.assert_allclose(out.column("v_sum").to_pylist(), exp["sum"], rtol=1e-6)
    assert out.column("v_count").to_pylist() == exp["count"].tolist()
    np.testing.assert_allclose(out.column("v_min").to_pylist(), exp["min"], rtol=0)
    np.testing.assert_allclose(out.column("v_max").to_pylist(), exp["max"], rtol=0)


def test_groupby_int_minmax(rng):
    # signed-int min/max goes through the total-order-key round trip
    keys = [int(k) for k in rng.integers(0, 5, 100)]
    vals = [int(v) for v in rng.integers(-1000, 1000, 100)]
    t_keys = make_table(k=(keys, dt.INT32))
    t_vals = make_table(v=(vals, dt.INT32))
    out = groupby_aggregate(t_keys, t_vals, [("v", "min"), ("v", "max")])
    df = pd.DataFrame({"k": keys, "v": vals})
    exp = df.groupby("k")["v"].agg(["min", "max"]).reset_index()
    assert out.column("v_min").to_pylist() == exp["min"].tolist()
    assert out.column("v_max").to_pylist() == exp["max"].tolist()


def test_groupby_int64_sum_exact():
    t_keys = make_table(k=(["a", "b", "a", "b", "a"], dt.STRING))
    t_vals = make_table(v=([2**40, 1, 2**40, 2, 5], dt.INT64))
    out = groupby_aggregate(t_keys, t_vals, [("v", "sum")])
    assert out.column("k").to_pylist() == ["a", "b"]
    assert out.column("v_sum").to_pylist() == [2**41 + 5, 3]


def test_groupby_null_keys_group_together():
    t_keys = make_table(k=([1, None, 1, None], dt.INT32))
    t_vals = make_table(v=([1, 2, 3, 4], dt.INT64))
    out = groupby_aggregate(t_keys, t_vals, [("v", "sum")])
    assert out.column("k").to_pylist() == [None, 1]
    assert out.column("v_sum").to_pylist() == [6, 4]


def test_groupby_count_all_vs_count():
    t_keys = make_table(k=([1, 1, 2], dt.INT32))
    t_vals = make_table(v=([None, 5, None], dt.INT64))
    out = groupby_aggregate(t_keys, t_vals, [("v", "count"), ("v", "count_all")])
    assert out.column("v_count").to_pylist() == [1, 0]
    assert out.column("v_count_all").to_pylist() == [2, 1]


# ---------------------------------------------------------------------------
# join
# ---------------------------------------------------------------------------


def test_inner_join_duplicates():
    left = make_table(k=([1, 2, 2, 3], dt.INT32), lv=([10, 20, 21, 30], dt.INT64))
    right = make_table(k=([2, 2, 4, 1], dt.INT32), rv=([200, 201, 400, 100], dt.INT64))
    out = inner_join(left, right, ["k"])
    df = pd.merge(
        pd.DataFrame({"k": [1, 2, 2, 3], "lv": [10, 20, 21, 30]}),
        pd.DataFrame({"k": [2, 2, 4, 1], "rv": [200, 201, 400, 100]}),
        on="k",
    )
    got = sorted(zip(out.column("k").to_pylist(), out.column("lv").to_pylist(),
                     out.column("rv").to_pylist()))
    exp = sorted(zip(df["k"], df["lv"], df["rv"]))
    assert got == exp


def test_left_join_unmatched_null():
    left = make_table(k=([1, 5], dt.INT32), lv=([10, 50], dt.INT64))
    right = make_table(k=([1], dt.INT32), rv=([100], dt.INT64))
    out = left_join(left, right, ["k"])
    rows = sorted(zip(out.column("k").to_pylist(), out.column("lv").to_pylist(),
                      out.column("rv").to_pylist()))
    assert rows == [(1, 10, 100), (5, 50, None)]


def test_join_null_keys_never_match():
    left = make_table(k=([None, 1], dt.INT32), lv=([1, 2], dt.INT64))
    right = make_table(k=([None, 1], dt.INT32), rv=([3, 4], dt.INT64))
    out = inner_join(left, right, ["k"])
    assert out.num_rows == 1
    assert out.column("k").to_pylist() == [1]


def test_full_join_matches_pandas():
    lk, lv = [1, 2, 2, 3, None], [10, 20, 21, 30, 40]
    rk, rv = [2, 2, 4, 1, None], [200, 201, 400, 100, 500]
    left = make_table(k=(lk, dt.INT32), lv=(lv, dt.INT64))
    right = make_table(k=(rk, dt.INT32), rv=(rv, dt.INT64))
    out = full_join(left, right, ["k"])
    # SQL full-outer semantics: null keys NEVER match (pandas outer
    # merge matches NA==NA, so the null-key rows are oracled by hand)
    df = pd.merge(
        pd.DataFrame({"k": [k for k in lk if k is not None],
                      "lv": [v for k, v in zip(lk, lv) if k is not None]}),
        pd.DataFrame({"k": [k for k in rk if k is not None],
                      "rv": [v for k, v in zip(rk, rv) if k is not None]}),
        on="k",
        how="outer",
    )
    exp_rows = [
        (None if pd.isna(r.k) else int(r.k),
         None if pd.isna(r.lv) else int(r.lv),
         None if pd.isna(r.rv) else int(r.rv))
        for r in df.itertuples()
    ]
    exp_rows += [(None, 40, None), (None, None, 500)]  # unmatched null keys
    key = lambda t: tuple((x is None, x or 0) for x in t)
    got = sorted(
        zip(out.column("k").to_pylist(), out.column("lv").to_pylist(), out.column("rv").to_pylist()),
        key=key,
    )
    assert got == sorted(exp_rows, key=key)


def test_semi_anti_join():
    left = make_table(k=([1, 2, 2, 3, None], dt.INT32), lv=([10, 20, 21, 30, 40], dt.INT64))
    right = make_table(k=([2, 2, 5, None], dt.INT32), rv=([1, 2, 3, 4], dt.INT64))
    semi = left_semi_join(left, right, ["k"])
    # each matching left row appears ONCE despite duplicate right matches;
    # null left keys never match
    assert sorted(semi.column("lv").to_pylist()) == [20, 21]
    anti = left_anti_join(left, right, ["k"])
    # null left key has no match -> kept (NOT EXISTS semantics)
    assert sorted(anti.column("lv").to_pylist()) == [10, 30, 40]


def test_full_join_string_keys_matches_pandas():
    # VERDICT r3 missing #4: cudf's full join has no key-type
    # restriction — STRING keys coalesce through the padded merge
    lk = ["apple", "banana", "banana", "cherry", None]
    lv = [10, 20, 21, 30, 40]
    rk = ["banana", "date", "apple", None]
    rv = [200, 400, 100, 500]
    left = make_table(k=(lk, dt.STRING), lv=(lv, dt.INT64))
    right = make_table(k=(rk, dt.STRING), rv=(rv, dt.INT64))
    out = full_join(left, right, ["k"])
    df = pd.merge(
        pd.DataFrame({"k": [k for k in lk if k is not None],
                      "lv": [v for k, v in zip(lk, lv) if k is not None]}),
        pd.DataFrame({"k": [k for k in rk if k is not None],
                      "rv": [v for k, v in zip(rk, rv) if k is not None]}),
        on="k", how="outer",
    )
    exp_rows = [
        (None if pd.isna(r.k) else r.k,
         None if pd.isna(r.lv) else int(r.lv),
         None if pd.isna(r.rv) else int(r.rv))
        for r in df.itertuples()
    ]
    exp_rows += [(None, 40, None), (None, None, 500)]  # unmatched null keys
    key = lambda t: tuple((x is None, x or 0 if not isinstance(x, str) else x) for x in t)
    got = sorted(
        zip(out.column("k").to_pylist(), out.column("lv").to_pylist(), out.column("rv").to_pylist()),
        key=key,
    )
    assert got == sorted(exp_rows, key=key)


def test_full_join_string_keys_empty_and_long():
    left = make_table(k=([], dt.STRING), lv=([], dt.INT64))
    right = make_table(k=(["only-right-row-with-a-long-key"], dt.STRING), rv=([70], dt.INT64))
    out = full_join(left, right, ["k"])
    assert out.column("k").to_pylist() == ["only-right-row-with-a-long-key"]
    assert out.column("lv").to_pylist() == [None]
    assert out.column("rv").to_pylist() == [70]


def test_full_join_empty_sides():
    left = make_table(k=([], dt.INT32), lv=([], dt.INT64))
    right = make_table(k=([7], dt.INT32), rv=([70], dt.INT64))
    out = full_join(left, right, ["k"])
    assert out.column("k").to_pylist() == [7]
    assert out.column("lv").to_pylist() == [None]
    assert out.column("rv").to_pylist() == [70]
    out2 = full_join(right, left, ["k"])
    assert out2.column("k").to_pylist() == [7]
    assert out2.column("rv").to_pylist() == [70]


def test_join_string_keys():
    left = make_table(k=(["apple", "pear", "fig"], dt.STRING), lv=([1, 2, 3], dt.INT64))
    right = make_table(k=(["fig", "apple"], dt.STRING), rv=([30, 10], dt.INT64))
    out = inner_join(left, right, ["k"])
    rows = sorted(zip(out.column("k").to_pylist(), out.column("rv").to_pylist()))
    assert rows == [("apple", 10), ("fig", 30)]


# ---------------------------------------------------------------------------
# expressions
# ---------------------------------------------------------------------------


def test_expression_arithmetic_and_compare():
    t = make_table(q=([1, 6, 3, None], dt.INT64), p=([2.0, 0.5, 1.0, 4.0], dt.FLOAT64))
    revenue = (col("q").cast(dt.FLOAT64) * col("p")).evaluate(t)
    assert revenue.to_pylist()[:3] == [2.0, 3.0, 3.0]
    assert revenue.to_pylist()[3] is None

    pred = ((col("q") > lit(2)) & col("q").is_not_null()).evaluate(t)
    assert pred.to_pylist() == [False, True, True, False]


def test_expression_three_valued_logic():
    t = make_table(a=([True, False, None], dt.BOOL8))
    # null AND false == false; null OR true == true
    f = (col("a") & lit(False)).evaluate(t)
    assert f.to_pylist() == [False, False, False]
    tr = (col("a") | lit(True)).evaluate(t)
    assert tr.to_pylist() == [True, True, True]
    n = (col("a") & lit(True)).evaluate(t)
    assert n.to_pylist() == [True, False, None]


def test_expression_case_when():
    from spark_rapids_jni_tpu.ops.expressions import when

    t = make_table(
        a=([1, 5, None, 7], dt.INT64),
        x=([10.0, 20.0, 30.0, None], dt.FLOAT64),
        y=([-1.0, -2.0, -3.0, -4.0], dt.FLOAT64),
    )
    # NULL condition selects the ELSE branch (SQL CASE semantics)
    r = when(col("a") > lit(2), col("x"), col("y")).evaluate(t)
    assert r.to_pylist() == [-1.0, 20.0, -3.0, None]
    # literal branches + nesting (multi-arm CASE)
    r2 = when(col("a") > lit(6), lit(100), when(col("a") > lit(2), lit(50), lit(0))).evaluate(t)
    assert r2.to_pylist() == [0, 50, 0, 100]
    # the pivot idiom: SUM(CASE WHEN p THEN v ELSE 0 END)
    piv = when(col("a") == lit(5), col("x"), lit(0.0)).evaluate(t)
    assert piv.to_pylist() == [0.0, 20.0, 0.0, 0.0]


def test_expression_divide_by_zero_null():
    t = make_table(a=([4, 9], dt.INT64), b=([2, 0], dt.INT64))
    r = (col("a") / col("b")).evaluate(t)
    vals = r.to_pylist()
    assert vals[0] == 2.0
    assert vals[1] is None


def test_groupby_sum_bounded_matches_general(rng):
    from spark_rapids_jni_tpu.ops.aggregate import groupby_sum_bounded

    keys = rng.integers(0, 50, 500).astype(np.int64)
    vals = rng.integers(-100, 100, 500).astype(np.int64)
    sums, counts = groupby_sum_bounded(jnp.asarray(keys), jnp.asarray(vals), 50)
    df = pd.DataFrame({"k": keys, "v": vals}).groupby("k")["v"].agg(["sum", "count"])
    for k in range(50):
        want_sum = int(df["sum"].get(k, 0))
        want_cnt = int(df["count"].get(k, 0))
        assert int(np.asarray(sums)[k]) == want_sum
        assert int(np.asarray(counts)[k]) == want_cnt


def test_groupby_sum_bounded_out_of_domain_dropped():
    from spark_rapids_jni_tpu.ops.aggregate import groupby_sum_bounded

    keys = jnp.asarray([0, 1, 99, -5], jnp.int64)
    vals = jnp.asarray([10, 20, 30, 40], jnp.int64)
    sums, counts = groupby_sum_bounded(keys, vals, 2)
    assert np.asarray(sums).tolist() == [10, 20]
    assert np.asarray(counts).tolist() == [1, 1]


def test_groupby_nunique(rng):
    keys = [int(k) for k in rng.integers(0, 6, 300)]
    vals = [int(v) for v in rng.integers(0, 10, 300)]
    with_nulls = [v if i % 7 else None for i, v in enumerate(vals)]
    t_keys = make_table(k=(keys, dt.INT32))
    t_vals = make_table(v=(with_nulls, dt.INT64))
    out = groupby_aggregate(t_keys, t_vals, [("v", "nunique"), ("v", "count")])
    df = pd.DataFrame({"k": keys, "v": with_nulls})
    exp = df.groupby("k")["v"].agg(["nunique", "count"]).reset_index()
    assert out.column("k").to_pylist() == exp["k"].tolist()
    assert out.column("v_nunique").to_pylist() == exp["nunique"].tolist()
    assert out.column("v_count").to_pylist() == exp["count"].tolist()


def test_groupby_nunique_strings():
    t_keys = make_table(k=([1, 1, 1, 2, 2], dt.INT32))
    t_vals = make_table(s=(["a", "b", "a", "c", "c"], dt.STRING))
    out = groupby_aggregate(t_keys, t_vals, [("s", "nunique")])
    assert out.column("s_nunique").to_pylist() == [2, 1]


def test_groupby_var_std_matches_pandas(rng):
    keys = [int(k) for k in rng.integers(0, 6, 400)]
    vals = rng.standard_normal(400) * 50 + 10
    with_nulls = [float(v) if i % 9 else None for i, v in enumerate(vals)]
    t_keys = make_table(k=(keys, dt.INT32))
    t_vals = make_table(v=(with_nulls, dt.FLOAT64))
    out = groupby_aggregate(t_keys, t_vals, [("v", "var"), ("v", "std")])
    df = pd.DataFrame({"k": keys, "v": with_nulls})
    exp = df.groupby("k")["v"].agg(["var", "std"]).reset_index()
    got_var = out.column("v_var").to_pylist()
    got_std = out.column("v_std").to_pylist()
    np.testing.assert_allclose(got_var, exp["var"].values, rtol=1e-9)
    np.testing.assert_allclose(got_std, exp["std"].values, rtol=1e-9)

    # integer inputs promote to DOUBLE, Spark var_samp semantics
    t_ints = make_table(v=([int(v) for v in rng.integers(-100, 100, 400)], dt.INT64))
    out2 = groupby_aggregate(t_keys, t_ints, [("v", "var")])
    exp2 = pd.DataFrame({"k": keys, "v": np.asarray(t_ints.column("v").data)}).groupby("k")["v"].var()
    np.testing.assert_allclose(out2.column("v_var").to_pylist(), exp2.values, rtol=1e-9)

    # fewer than two valid rows -> NULL
    t_k1 = make_table(k=([1, 1, 2], dt.INT32))
    t_v1 = make_table(v=([5.0, None, 7.0], dt.FLOAT64))
    out3 = groupby_aggregate(t_k1, t_v1, [("v", "std")])
    assert out3.column("v_std").to_pylist() == [None, None]


def test_groupby_var_pop_stddev_pop_matches_pandas(rng):
    # population variants (Spark var_pop/stddev_pop; VERDICT item 6
    # first slice): same stable M2 as var/std, divisor n, NULL only
    # when a group has NO valid rows (one valid row -> 0.0)
    keys = [int(k) for k in rng.integers(0, 6, 400)]
    vals = rng.standard_normal(400) * 50 + 10
    with_nulls = [float(v) if i % 9 else None for i, v in enumerate(vals)]
    t_keys = make_table(k=(keys, dt.INT32))
    t_vals = make_table(v=(with_nulls, dt.FLOAT64))
    out = groupby_aggregate(t_keys, t_vals, [("v", "var_pop"), ("v", "stddev_pop")])
    df = pd.DataFrame({"k": keys, "v": with_nulls})
    exp_var = df.groupby("k")["v"].agg(lambda s: s.var(ddof=0)).reset_index()
    exp_std = df.groupby("k")["v"].agg(lambda s: s.std(ddof=0)).reset_index()
    np.testing.assert_allclose(
        out.column("v_var_pop").to_pylist(), exp_var["v"].values, rtol=1e-9
    )
    np.testing.assert_allclose(
        out.column("v_stddev_pop").to_pylist(), exp_std["v"].values, rtol=1e-9
    )

    # integer inputs promote to DOUBLE, like var/std
    t_ints = make_table(v=([int(v) for v in rng.integers(-100, 100, 400)], dt.INT64))
    out2 = groupby_aggregate(t_keys, t_ints, [("v", "var_pop")])
    exp2 = pd.DataFrame(
        {"k": keys, "v": np.asarray(t_ints.column("v").data)}
    ).groupby("k")["v"].var(ddof=0)
    np.testing.assert_allclose(out2.column("v_var_pop").to_pylist(), exp2.values, rtol=1e-9)

    # ONE valid row -> 0.0 (var_samp would be NULL); zero valid -> NULL
    t_k1 = make_table(k=([1, 1, 2], dt.INT32))
    t_v1 = make_table(v=([5.0, None, None], dt.FLOAT64))
    out3 = groupby_aggregate(t_k1, t_v1, [("v", "var_pop"), ("v", "stddev_pop")])
    assert out3.column("v_var_pop").to_pylist() == [0.0, None]
    assert out3.column("v_stddev_pop").to_pylist() == [0.0, None]

    # same numeric-type gate as var/std (ADVICE r5 low #5)
    t_bool = make_table(v=([True, False, True], dt.BOOL8))
    with pytest.raises(ValueError, match="numeric"):
        groupby_aggregate(t_k1, t_bool, [("v", "var_pop")])


def test_groupby_var_large_mean_stable(rng):
    # the raw-moment formulation (sumsq - sum^2/n) returns pure noise
    # here; the two-pass deviations form must hold full precision
    keys = [int(k) for k in rng.integers(0, 3, 300)]
    vals = (rng.standard_normal(300) + 1e9).tolist()
    t_keys = make_table(k=(keys, dt.INT32))
    t_vals = make_table(v=(vals, dt.FLOAT64))
    out = groupby_aggregate(t_keys, t_vals, [("v", "std")])
    exp = pd.DataFrame({"k": keys, "v": vals}).groupby("k")["v"].std()
    # cross-implementation mean rounding differs at ~2e-8 here; the
    # property under test is STABILITY (raw moments would be ~100% off)
    np.testing.assert_allclose(out.column("v_std").to_pylist(), exp.values, rtol=1e-6)


def test_groupby_var_std_dd_branch(rng, monkeypatch):
    # force the f64-less (dd) formulation on the CPU tier so the TPU
    # branch of _var_std_column is exercised hermetically
    from spark_rapids_jni_tpu.ops import aggregate as agg_mod
    from spark_rapids_jni_tpu.ops import bitutils

    monkeypatch.setattr(bitutils, "backend_has_f64", lambda: False)
    keys = [int(k) for k in rng.integers(0, 5, 300)]
    vals = (rng.standard_normal(300) * 30 + 10).tolist()
    with_nulls = [v if i % 11 else None for i, v in enumerate(vals)]
    t_keys = make_table(k=(keys, dt.INT32))
    t_vals = make_table(v=(with_nulls, dt.FLOAT64))
    out = groupby_aggregate(t_keys, t_vals, [("v", "var"), ("v", "std")])
    df = pd.DataFrame({"k": keys, "v": with_nulls})
    exp = df.groupby("k")["v"].agg(["var", "std"]).reset_index()
    np.testing.assert_allclose(out.column("v_var").to_pylist(), exp["var"].values, rtol=1e-9)
    np.testing.assert_allclose(out.column("v_std").to_pylist(), exp["std"].values, rtol=1e-9)
    # integer source through dd promotion
    t_ints = make_table(v=([int(v) for v in rng.integers(-500, 500, 300)], dt.INT64))
    out2 = groupby_aggregate(t_keys, t_ints, [("v", "std")])
    exp2 = pd.DataFrame({"k": keys, "v": np.asarray(t_ints.column("v").data)}).groupby("k")["v"].std()
    np.testing.assert_allclose(out2.column("v_std").to_pylist(), exp2.values, rtol=1e-9)

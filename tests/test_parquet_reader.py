"""Parquet data decode tests: pyarrow-written files as the oracle."""

import io

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import spark_rapids_jni_tpu  # noqa: F401
from spark_rapids_jni_tpu.io.parquet_reader import read_table


def write(table, **kw):
    buf = io.BytesIO()
    pq.write_table(table, buf, **kw)
    return buf.getvalue()


def check_roundtrip(pa_table, **kw):
    data = write(pa_table, **kw)
    got = read_table(data)
    for name in pa_table.column_names:
        expected = pa_table.column(name).to_pylist()
        actual = got.column(name).to_pylist()
        if pa.types.is_floating(pa_table.schema.field(name).type):
            for e, a in zip(expected, actual):
                assert (e is None) == (a is None)
                if e is not None:
                    assert abs(e - a) < 1e-6 or e == a
        else:
            assert actual == expected, f"column {name}"


BASIC = pa.table({
    "i32": pa.array([1, -2, 3, None, 5], pa.int32()),
    "i64": pa.array([2**40, None, -7, 0, 9], pa.int64()),
    "f32": pa.array([1.5, 2.5, None, -0.25, 0.0], pa.float32()),
    "f64": pa.array([1e300, None, -2.25, 0.5, 3.125], pa.float64()),
    "s": pa.array(["hello", "", None, "spark", "tpu"], pa.string()),
    "b": pa.array([True, False, None, True, False], pa.bool_()),
})


@pytest.mark.parametrize("codec", ["NONE", "snappy", "zstd", "gzip"])
def test_roundtrip_codecs(codec):
    check_roundtrip(BASIC, compression=codec)


def test_roundtrip_plain_encoding():
    check_roundtrip(BASIC, use_dictionary=False, compression="NONE")


def test_roundtrip_dictionary_encoding():
    check_roundtrip(BASIC, use_dictionary=True)


def test_roundtrip_v2_pages():
    check_roundtrip(BASIC, data_page_version="2.0")
    check_roundtrip(BASIC, data_page_version="2.0", use_dictionary=False)


def test_multiple_row_groups(rng):
    t = pa.table({
        "x": pa.array([int(v) for v in rng.integers(0, 1000, 5000)], pa.int64()),
        "y": pa.array([f"k{int(v) % 50}" for v in rng.integers(0, 1000, 5000)]),
    })
    data = write(t, row_group_size=750)
    got = read_table(data)
    assert got.column("x").to_pylist() == t.column("x").to_pylist()
    assert got.column("y").to_pylist() == t.column("y").to_pylist()


def test_column_selection():
    got = read_table(write(BASIC), columns=["s", "i32"])
    assert got.names == ["i32", "s"]
    assert got.column("i32").to_pylist() == BASIC.column("i32").to_pylist()


def test_all_nulls_column():
    t = pa.table({"n": pa.array([None, None, None], pa.int32())})
    got = read_table(write(t))
    assert got.column("n").to_pylist() == [None, None, None]


def test_empty_table():
    t = pa.table({"a": pa.array([], pa.int32())})
    got = read_table(write(t))
    assert got.num_rows == 0


# ---------------------------------------------------------------------------
# nested schemas (lists / structs / maps) vs the pyarrow oracle
# ---------------------------------------------------------------------------


def test_list_of_int():
    t = pa.table({
        "l": pa.array([[1, 2, 3], [], None, [4], [None, 5]], pa.list_(pa.int64())),
    })
    check_roundtrip(t)
    check_roundtrip(t, use_dictionary=False)
    check_roundtrip(t, data_page_version="2.0")


def test_list_of_strings():
    t = pa.table({
        "l": pa.array([["a", "bb"], None, [], ["", None, "ccc"]], pa.list_(pa.string())),
    })
    check_roundtrip(t)


def test_struct_flat():
    t = pa.table({
        "s": pa.array(
            [{"a": 1, "b": "x"}, None, {"a": None, "b": "z"}, {"a": 4, "b": None}],
            pa.struct([("a", pa.int32()), ("b", pa.string())]),
        ),
    })
    check_roundtrip(t)


def test_struct_of_list():
    t = pa.table({
        "s": pa.array(
            [{"v": [1, 2]}, {"v": None}, None, {"v": []}, {"v": [None, 3]}],
            pa.struct([("v", pa.list_(pa.int64()))]),
        ),
    })
    check_roundtrip(t)


def test_list_of_struct():
    t = pa.table({
        "l": pa.array(
            [[{"a": 1}, {"a": None}], [], None, [{"a": 7}]],
            pa.list_(pa.struct([("a", pa.int64())])),
        ),
    })
    check_roundtrip(t)


def test_list_of_list():
    t = pa.table({
        "ll": pa.array(
            [[[1], [2, 3]], [], None, [None, [4, None]], [[]]],
            pa.list_(pa.list_(pa.int32())),
        ),
    })
    check_roundtrip(t)


def test_map_column():
    t = pa.table({
        "m": pa.array(
            [[("k1", 1), ("k2", 2)], [], None, [("k3", None)]],
            pa.map_(pa.string(), pa.int64()),
        ),
    })
    got = read_table(write(t))
    # maps land as LIST<STRUCT<key, value>> (the cudf representation)
    want = [
        None if row is None else [{"key": k, "value": v} for k, v in row]
        for row in t.column("m").to_pylist()
    ]
    assert got.column("m").to_pylist() == want


def test_deep_nesting_row_groups(rng):
    rows = []
    for i in range(700):
        r = int(rng.integers(0, 6))
        if r == 0:
            rows.append(None)
        else:
            rows.append(
                [
                    {
                        "tags": None if rng.integers(0, 5) == 0 else [
                            f"t{int(x)}" for x in rng.integers(0, 9, int(rng.integers(0, 3)))
                        ],
                        "n": None if rng.integers(0, 5) == 0 else int(rng.integers(0, 100)),
                    }
                    for _ in range(int(rng.integers(0, 3)))
                ]
            )
    typ = pa.list_(pa.struct([("tags", pa.list_(pa.string())), ("n", pa.int64())]))
    t = pa.table({"events": pa.array(rows, typ), "id": pa.array(range(700), pa.int64())})
    data = write(t, row_group_size=128)
    got = read_table(data)
    assert got.column("events").to_pylist() == t.column("events").to_pylist()
    assert got.column("id").to_pylist() == t.column("id").to_pylist()


def test_nested_next_to_flat_selection():
    t = pa.table({
        "flat": pa.array([1, 2, 3], pa.int32()),
        "l": pa.array([[1], [], [2, 3]], pa.list_(pa.int32())),
    })
    got = read_table(write(t), columns=["l"])
    assert got.names == ["l"]
    assert got.column("l").to_pylist() == t.column("l").to_pylist()


def test_lz4_raw_codec():
    check_roundtrip(BASIC, compression="lz4")  # pyarrow writes LZ4_RAW


def test_lz4_hadoop_framing():
    """Legacy codec 5 pages use Hadoop block framing: repeated
    [u32 BE usize][u32 BE csize][raw LZ4 block] (advisor round-2 low
    finding: these were fed whole to the LZ4 *frame* decoder)."""
    import struct

    import pyarrow as pa_mod

    from spark_rapids_jni_tpu.io.parquet_reader import _lz4_hadoop

    plain = b"spark-rapids-jni-tpu hadoop lz4 framing " * 40
    half = len(plain) // 2
    blocks = []
    for part in (plain[:half], plain[half:]):
        comp = pa_mod.Codec("lz4_raw").compress(part).to_pybytes()
        blocks.append(struct.pack(">II", len(part), len(comp)) + comp)
    framed = b"".join(blocks)
    assert _lz4_hadoop(framed, len(plain)) == plain
    # LZ4-frame payloads (non-Hadoop writers) must be rejected -> None
    frame = pa_mod.Codec("lz4").compress(plain).to_pybytes()
    assert _lz4_hadoop(frame, len(plain)) is None


def test_zstd_decodes_through_native_tier(monkeypatch):
    """The zstd path must run on the native codec (nvcomp analog), not
    the pyarrow fallback."""
    from spark_rapids_jni_tpu import runtime

    if not runtime.native_available():
        pytest.skip("native runtime not built")
    import pyarrow as pa_mod

    def _boom(*a, **k):
        raise AssertionError("pyarrow codec used for zstd")

    monkeypatch.setattr(pa_mod, "Codec", _boom)
    check_roundtrip(BASIC, compression="zstd")

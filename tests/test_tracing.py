"""srjt-trace: distributed per-query tracing + flight recorder (ISSUE 12).

Covers the tentpole end to end: gated no-op stubs, span nesting and
context propagation (incl. the contextvars hand-off into threads), the
cross-process wire protocol (sidecar TRACE flag bit, exchange traced
GET verb), the flight recorder's slow/shed/failed capture, the
tracemerge join + orphan gate + Chrome export, and the per-layer
instrumentation (op boundary, retry attempts/splits, memgov admission
and spill, serve scheduler, pool routing/hedging).

The slow acceptance (``TestRealPoolCrossProcess``) runs a traced query
through a REAL pool of 2 with one hedged request and one kill -9
failover, then merges the per-process span logs and asserts the tree:
hedge legs are siblings with the winner marked exactly once, the
failover retry is a child of the original op span, and a worker span
from another pid resolves to its client-side parent — zero orphans.
ci/premerge.sh runs this file env-armed in the dedicated trace tier and
gates the archived artifacts.
"""

import json
import os
import signal
import socket
import struct
import tempfile
import threading
import time

import numpy as np
import pytest

from spark_rapids_jni_tpu import memgov, runtime, serve, sidecar, sidecar_pool
from spark_rapids_jni_tpu.analysis import tracemerge
from spark_rapids_jni_tpu.utils import (
    dispatch,
    faultinj,
    knobs,
    metrics,
    retry,
    trace_sink,
    tracing,
)
from spark_rapids_jni_tpu.utils.errors import Overloaded, RetryableError


def _scrub_worker_namespace():
    """In-proc workers count registry-direct sidecar.worker.* COUNTERS
    in this process, which clash with the GAUGES other suites fold
    remote snapshots into (the test_sidecar_pool discipline)."""
    reg = metrics.registry()
    with reg._lock:
        for name in list(reg._metrics):
            if name.startswith("sidecar.worker."):
                del reg._metrics[name]


@pytest.fixture(autouse=True)
def _clean_state(tmp_path):
    """Every test gets a fresh recorder and its own span log under
    tmp_path; the env-configured base (the CI trace tier's artifacts
    path) is restored afterwards so the real-pool acceptance — which
    deliberately uses the env path — still archives its spans."""
    prev_base = trace_sink.log_path()
    prev_enabled = tracing.is_enabled()
    # the premerge trace tier arms SRJT_TRACE_ENABLED=1 process-wide;
    # tests own the gate explicitly (tracing.enabled() scopes), so the
    # default inside this suite is OFF either way
    tracing.set_enabled(False)
    trace_sink.reset_for_tests()
    trace_sink.set_log_path(str(tmp_path / "spans.jsonl"))
    faultinj.disable()
    retry.disable()
    retry.reset_stats()
    _scrub_worker_namespace()
    yield
    faultinj.disable()
    retry.disable()
    retry.reset_stats()
    trace_sink.reset_for_tests()
    trace_sink.set_log_path(prev_base)
    tracing.set_enabled(prev_enabled)
    _scrub_worker_namespace()


def _log_spans():
    path = trace_sink.resolved_log_path()
    if path is None or not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return [r for r in out if r.get("kind") == "span"]


def _wait_for_span(name, timeout_s=5.0):
    end = time.monotonic() + timeout_s
    while time.monotonic() < end:
        hits = [s for s in _log_spans() if s["name"] == name]
        if hits:
            return hits
        time.sleep(0.02)
    raise AssertionError(f"span {name!r} never reached the log")


# ---------------------------------------------------------------------------
# gate + stubs
# ---------------------------------------------------------------------------


class TestGateAndStubs:
    def test_disabled_is_all_noops(self):
        assert not tracing.is_enabled()
        assert tracing.start_trace("q") is None
        assert tracing.wire_context() is None
        with tracing.span("x", a=1) as sp:
            sp.annotate(b=2)  # null span: a pass
        tracing.closed_span("y", 0.1)
        tracing.annotate(c=3)
        assert tracing.current_context() is None
        assert _log_spans() == []
        assert trace_sink.recorder().last(5) == []

    def test_set_enabled_roundtrip(self):
        tracing.set_enabled(True)
        try:
            assert tracing.is_enabled()
        finally:
            tracing.set_enabled(False)
        assert not tracing.is_enabled()

    def test_span_outside_any_context_is_noop_even_armed(self):
        with tracing.enabled():
            with tracing.span("stray") as sp:
                assert sp is tracing._NULL_SPAN
        assert _log_spans() == []

    def test_sampler_zero_disables_roots(self, monkeypatch):
        monkeypatch.setenv("SRJT_TRACE_SAMPLE", "0")
        with tracing.enabled():
            qt = tracing.start_trace("q")
            # an UNSAMPLED trace is a real (silent) context, not None:
            # inner layers must see "a decision was made" (see below)
            assert qt is not None and not qt.ctx.sampled
            with qt.activate():
                with tracing.span("inner") as sp:
                    assert sp is tracing._NULL_SPAN
                assert tracing.wire_context() is None
            qt.finish("ok")
        assert metrics.registry().value("trace.unsampled") >= 1
        assert trace_sink.recorder().last(5) == []
        assert _log_spans() == []

    def test_unsampled_query_suppresses_op_auto_roots(self, monkeypatch):
        """The sampler's decision covers the WHOLE query: an unsampled
        serve submission must not let every inner op boundary re-roll
        and mint one-op fragment traces."""
        monkeypatch.setenv("SRJT_TRACE_SAMPLE", "0")

        @dispatch.op_boundary("frag_op")
        def frag_op():
            return 1

        with tracing.enabled():
            qt = tracing.start_trace("serve.query")
            with qt.activate():
                for _ in range(5):
                    assert frag_op() == 1
            qt.finish("ok")
        assert trace_sink.recorder().last(10) == []
        assert _log_spans() == []


class TestProfileTo:
    def test_disabled_never_touches_the_profiler(self, monkeypatch):
        import jax

        def boom(*a, **k):
            raise AssertionError("profiler touched while disabled")

        monkeypatch.setattr(jax.profiler, "start_trace", boom)
        monkeypatch.setattr(jax.profiler, "stop_trace", boom)
        with tracing.profile_to("/nonexistent"):
            pass

    def test_start_failure_tears_down_and_propagates(self, monkeypatch):
        import jax

        stopped = []

        def bad_start(*a, **k):
            raise RuntimeError("partial setup")

        monkeypatch.setattr(jax.profiler, "start_trace", bad_start)
        monkeypatch.setattr(
            jax.profiler, "stop_trace", lambda: stopped.append(1)
        )
        with tracing.enabled():
            with pytest.raises(RuntimeError, match="partial setup"):
                with tracing.profile_to("/tmp/x"):
                    raise AssertionError("body must not run")
        assert stopped == [1]  # the half-armed session was torn down

    def test_body_failure_still_stops(self, monkeypatch):
        import jax

        calls = []
        monkeypatch.setattr(
            jax.profiler, "start_trace", lambda *a, **k: calls.append("start")
        )
        monkeypatch.setattr(
            jax.profiler, "stop_trace", lambda: calls.append("stop")
        )
        with tracing.enabled():
            with pytest.raises(ValueError):
                with tracing.profile_to("/tmp/x"):
                    raise ValueError("body")
        assert calls == ["start", "stop"]


# ---------------------------------------------------------------------------
# spans, context, wire codec
# ---------------------------------------------------------------------------


class TestSpans:
    def test_nesting_and_parentage(self):
        with tracing.enabled():
            qt = tracing.start_trace("q", tenant="t")
            with qt.activate():
                with tracing.span("outer") as o:
                    with tracing.span("inner") as i:
                        assert i.parent_id == o.span_id
                        assert i.depth == o.depth + 1
            qt.finish("ok")
        rec = trace_sink.recorder().worst()
        by_name = {s["name"]: s for s in rec["spans"]}
        assert by_name["outer"]["parent"] == by_name["q"]["span"]
        assert by_name["inner"]["parent"] == by_name["outer"]["span"]
        assert by_name["q"]["parent"] is None

    def test_error_status_and_annotations(self):
        with tracing.enabled():
            qt = tracing.start_trace("q")
            with qt.activate():
                with pytest.raises(ValueError):
                    with tracing.span("bad", k=1) as sp:
                        sp.annotate(extra=2)
                        raise ValueError("x")
            qt.finish("failed")
        rec = trace_sink.recorder().worst()
        bad = next(s for s in rec["spans"] if s["name"] == "bad")
        assert bad["status"] == "error"
        assert bad["annotations"] == {"k": 1, "extra": 2, "error": "ValueError"}
        assert rec["status"] == "failed" and rec.get("flushed")

    def test_span_cap_counts_overflow_but_log_is_uncapped(self, monkeypatch):
        monkeypatch.setenv("SRJT_TRACE_MAX_SPANS", "16")
        with tracing.enabled():
            qt = tracing.start_trace("q")
            with qt.activate():
                for i in range(20):
                    with tracing.span(f"s{i}"):
                        pass
            qt.finish("ok")
        rec = trace_sink.recorder().worst()
        assert rec["dropped_spans"] == 20 - 16 + 1  # +1: the root itself
        assert len(_log_spans()) == 21  # every span + root reached the log

    def test_context_rides_copy_context_into_threads(self):
        import contextvars

        seen = {}

        def child():
            with tracing.span("threaded") as sp:
                seen["parent"] = sp.parent_id

        with tracing.enabled():
            qt = tracing.start_trace("q")
            with qt.activate():
                with tracing.span("launcher") as lsp:
                    ctx = contextvars.copy_context()
                    t = threading.Thread(target=ctx.run, args=(child,))
                    t.start()
                    t.join()
                    assert seen["parent"] == lsp.span_id
            qt.finish("ok")

    def test_wire_codec_roundtrip(self):
        assert tracing.TRACE_CTX_LEN == 17
        with tracing.enabled():
            qt = tracing.start_trace("q")
            with qt.activate():
                blob = tracing.wire_context()
                assert blob is not None and len(blob) == 17
                tid, parent, sampled = tracing.decode_wire_context(blob)
                assert tid == qt.ctx.trace_id
                assert parent == qt.root.span_id
                assert sampled
            qt.finish("ok")

    def test_remote_scope_parents_to_wire_span(self):
        with tracing.enabled():
            qt = tracing.start_trace("q")
            with qt.activate():
                blob = tracing.wire_context()
            tid, parent, sampled = tracing.decode_wire_context(blob)
            with tracing.remote_scope(tid, parent, sampled):
                with tracing.span("remote") as sp:
                    assert sp.parent_id == parent
                    assert sp.ctx.trace_id == tid
                    assert sp.ctx.remote
            qt.finish("ok")

    def test_per_process_log_file_carries_pid(self):
        path = trace_sink.resolved_log_path()
        assert f".{os.getpid()}." in os.path.basename(path)


# ---------------------------------------------------------------------------
# flight recorder + explain
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def _mk(self, status="ok", dur=0.01, name="q"):
        return {"kind": "trace", "trace": "00", "name": name,
                "status": status, "duration_s": dur, "spans": [],
                "dropped_spans": 0, "metrics_delta": {}}

    def test_ring_is_bounded(self):
        r = trace_sink.FlightRecorder(capacity=3)
        for i in range(10):
            r.record(self._mk(name=f"q{i}"))
        assert [x["name"] for x in r.last(10)] == ["q7", "q8", "q9"]
        assert r.snapshot()["recorded"] == 10

    def test_non_ok_always_flushes_ok_does_not(self):
        r = trace_sink.FlightRecorder(capacity=8)
        r.record(self._mk("ok"))
        r.record(self._mk("shed"))
        r.record(self._mk("failed"))
        flags = [x.get("flushed", False) for x in r.last(3)]
        assert flags == [False, True, True]

    def test_slow_query_flushes(self, monkeypatch):
        monkeypatch.setenv("SRJT_SLOW_QUERY_SEC", "0.5")
        r = trace_sink.FlightRecorder(capacity=8)
        r.record(self._mk("ok", dur=0.1))
        r.record(self._mk("ok", dur=0.9))
        flags = [x.get("flushed", False) for x in r.last(2)]
        assert flags == [False, True]

    def test_worst_prefers_failures_then_duration(self):
        r = trace_sink.FlightRecorder(capacity=8)
        r.record(self._mk("ok", dur=9.0, name="slow_ok"))
        r.record(self._mk("failed", dur=0.1, name="fast_fail"))
        assert r.worst()["name"] == "fast_fail"

    def test_explain_last_renders_tree(self):
        with tracing.enabled():
            qt = tracing.start_trace("q", tenant="acme")
            with qt.activate():
                with tracing.span("stage_a"):
                    with tracing.span("stage_b"):
                        pass
            qt.finish("ok")
        text = runtime.explain_last()
        assert "stage_a" in text and "stage_b" in text
        assert "tenant=acme" in text
        # indentation proves nesting: b deeper than a
        la = next(l for l in text.splitlines() if "stage_a" in l)
        lb = next(l for l in text.splitlines() if "stage_b" in l)
        assert len(lb) - len(lb.lstrip()) > len(la) - len(la.lstrip())

    def test_explain_last_none_when_untraced(self):
        assert runtime.explain_last() is None

    def test_stats_report_carries_trace_section(self):
        rep = runtime.stats_report()
        assert "trace" in rep
        assert "spans" in rep["trace"] and "recorder" in rep["trace"]

    def test_stage_report_carries_trace_section(self):
        rep = metrics.stage_report("t")
        assert set(rep["trace"]) == {"spans", "traces", "flushed"}

    def test_stage_summary_shape(self):
        with tracing.enabled():
            qt = tracing.start_trace("q")
            with qt.activate():
                with tracing.span("a"):
                    pass
            qt.finish("ok")
        s = trace_sink.stage_summary()
        assert s["spans"] >= 2 and s["traces"] >= 1
        assert s["max_depth"] >= 1
        assert s["p99_span_us"] is not None


# ---------------------------------------------------------------------------
# tracemerge
# ---------------------------------------------------------------------------


def _span(trace, span, parent, name, ts=1.0, pid=1, **ann):
    rec = {"kind": "span", "trace": trace, "span": span, "parent": parent,
           "name": name, "ts": ts, "dur_us": 100.0, "pid": pid, "tid": 1,
           "status": "ok"}
    if ann:
        rec["annotations"] = ann
    return rec


class TestTracemerge:
    def _write(self, path, recs):
        with open(path, "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")

    def test_merge_joins_files_by_trace_id(self, tmp_path):
        a = str(tmp_path / "client.1.jsonl")
        b = str(tmp_path / "worker.2.jsonl")
        self._write(a, [
            _span("t1", "r", None, "root", ts=1.0),
            _span("t1", "c", "r", "request", ts=1.1),
        ])
        self._write(b, [_span("t1", "w", "c", "worker_op", ts=1.2, pid=2)])
        merged = tracemerge.merge(tracemerge.load_spans([a, b]))
        assert merged["orphans"] == 0
        t = merged["traces"]["t1"]
        assert [s["name"] for s in t["spans"]] == ["root", "request",
                                                   "worker_op"]
        assert t["pids"] == [1, 2]
        assert t["roots"] == ["r"]

    def test_orphans_detected_and_gated(self, tmp_path):
        p = str(tmp_path / "x.jsonl")
        self._write(p, [
            _span("t1", "r", None, "root"),
            _span("t1", "o", "missing", "stray"),
        ])
        merged = tracemerge.merge(tracemerge.load_spans([p]))
        assert merged["orphans"] == 1
        assert merged["traces"]["t1"]["orphans"] == ["o"]
        out = str(tmp_path / "m.json")
        rc = tracemerge.main([p, "--format", "json", "--out", out,
                              "--gate-orphans"])
        assert rc == 1
        rc = tracemerge.main([p, "--format", "json", "--out", out])
        assert rc == 0

    def test_chrome_export_is_perfetto_shaped(self, tmp_path):
        p = str(tmp_path / "x.jsonl")
        self._write(p, [_span("t1", "r", None, "root", wid=3)])
        out = str(tmp_path / "chrome.json")
        assert tracemerge.main([p, "--format", "chrome", "--out", out]) == 0
        doc = json.load(open(out))
        ev = doc["traceEvents"][0]
        assert ev["ph"] == "X" and ev["name"] == "root"
        assert ev["args"]["trace"] == "t1" and ev["args"]["wid"] == 3
        assert ev["dur"] == 100.0

    def test_torn_lines_and_duplicates_are_tolerated(self, tmp_path):
        p = str(tmp_path / "x.jsonl")
        with open(p, "w") as f:
            f.write(json.dumps(_span("t1", "r", None, "root")) + "\n")
            f.write(json.dumps(_span("t1", "r", None, "root")) + "\n")  # dup
            f.write('{"kind": "span", "trace": "t1", TORN')  # killed writer
        merged = tracemerge.merge(tracemerge.load_spans([p]))
        assert len(merged["traces"]["t1"]["spans"]) == 1

    def test_glob_loading(self, tmp_path):
        for i in range(3):
            self._write(str(tmp_path / f"s.{i}.jsonl"),
                        [_span("t1", f"x{i}", None, f"n{i}")])
        spans = tracemerge.load_spans([str(tmp_path / "s.*.jsonl")])
        assert len(spans) == 3

    def test_tree_rendering(self, tmp_path):
        p = str(tmp_path / "x.jsonl")
        self._write(p, [
            _span("t1", "r", None, "root", ts=1.0),
            _span("t1", "c", "r", "child", ts=1.1, pid=2),
        ])
        merged = tracemerge.merge(tracemerge.load_spans([p]))
        text = tracemerge.render_tree(merged)
        assert "root" in text and "child" in text and "pid 2" in text


# ---------------------------------------------------------------------------
# layer instrumentation: op boundary, retry, memgov
# ---------------------------------------------------------------------------


class TestOpBoundary:
    def test_outermost_auto_roots_one_op_trace(self):
        @dispatch.op_boundary("trace_toy")
        def toy(x):
            return x * 2

        with tracing.enabled():
            assert toy(3) == 6
        rec = trace_sink.recorder().worst()
        assert rec["name"] == "op.trace_toy" and rec["status"] == "ok"

    def test_nested_boundary_is_a_child_span(self):
        @dispatch.op_boundary("trace_inner")
        def inner(x):
            return x + 1

        @dispatch.op_boundary("trace_outer")
        def outer(x):
            return inner(x)

        with tracing.enabled():
            assert outer(1) == 2
        rec = trace_sink.recorder().worst()
        by_name = {s["name"]: s for s in rec["spans"]}
        assert (by_name["op.trace_inner"]["parent"]
                == by_name["op.trace_outer"]["span"])

    def test_retry_attempts_annotate_the_op_span(self):
        calls = {"n": 0}

        @dispatch.op_boundary("trace_flaky")
        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise RetryableError("transient")
            return "ok"

        with tracing.enabled():
            with retry.enabled(base_delay_ms=1, max_delay_ms=2):
                assert flaky() == "ok"
        rec = trace_sink.recorder().worst()
        op = next(s for s in rec["spans"] if s["name"] == "op.trace_flaky")
        assert op["annotations"]["retry_attempts"] == 2
        assert op["annotations"]["retry_error"] == "RetryableError"

    def test_split_recursion_is_child_spans(self):
        def fn(b):
            if len(b) > 2:
                raise RetryableError("RESOURCE_EXHAUSTED: batch too big")
            return list(b)

        with tracing.enabled():
            qt = tracing.start_trace("splitq")
            with qt.activate():
                out = retry.retry_with_split(
                    fn, [1, 2, 3, 4],
                    split=lambda b: (b[:len(b) // 2], b[len(b) // 2:]),
                    combine=lambda ps: sum(ps, []),
                    op_name="splitop",
                )
            qt.finish("ok")
        assert out == [1, 2, 3, 4]
        rec = next(r for r in trace_sink.recorder().last(5)
                   if r["name"] == "splitq")
        splits = [s for s in rec["spans"] if s["name"] == "retry.split"]
        assert len(splits) == 2
        assert all(s["annotations"]["depth"] == 1 for s in splits)

    def test_disabled_tracing_records_nothing(self):
        @dispatch.op_boundary("trace_quiet")
        def quiet():
            return 1

        assert quiet() == 1
        assert trace_sink.recorder().last(5) == []
        assert _log_spans() == []


class TestMemgovSpans:
    def test_admission_wait_span(self):
        ctrl = memgov.AdmissionController(capacity_fn=lambda: 1 << 30)
        with tracing.enabled():
            qt = tracing.start_trace("memq")
            with qt.activate():
                with ctrl.acquire(4096, name="toy"):
                    pass
            qt.finish("ok")
        rec = next(r for r in trace_sink.recorder().last(5)
                   if r["name"] == "memq")
        adm = next(s for s in rec["spans"]
                   if s["name"] == "memgov.admission_wait")
        assert adm["annotations"] == {"op": "toy", "nbytes": 4096}

    def test_spill_and_rematerialize_spans(self):
        import jax.numpy as jnp

        cat = memgov.BufferCatalog()
        h = cat.register("trace.buf", jnp.arange(64, dtype=jnp.int32))
        with tracing.enabled():
            qt = tracing.start_trace("spillq")
            with qt.activate():
                h.spill()
                got = h.get()
            qt.finish("ok")
        assert np.array_equal(np.asarray(got), np.arange(64))
        rec = next(r for r in trace_sink.recorder().last(5)
                   if r["name"] == "spillq")
        names = [s["name"] for s in rec["spans"]]
        assert "memgov.spill" in names and "memgov.rematerialize" in names
        cat.close()


# ---------------------------------------------------------------------------
# serve scheduler: roots, queue spans, shed/expire capture
# ---------------------------------------------------------------------------


class TestSchedulerTracing:
    def test_completed_query_has_queue_and_run_spans(self):
        with tracing.enabled():
            with serve.Scheduler(max_concurrent=1, name="tr1") as sched:
                h = sched.submit(lambda: 7, tenant="a", deadline_s=10)
                assert h.result(10) == 7
        recs = [r for r in trace_sink.recorder().last(10)
                if r["name"] == "serve.query"]
        assert recs and recs[-1]["status"] == "ok"
        names = [s["name"] for s in recs[-1]["spans"]]
        assert "serve.queue_wait" in names and "serve.run" in names
        ann = recs[-1]["annotations"]
        assert ann["tenant"] == "a" and "query" in ann

    def test_shed_at_admission_reaches_the_recorder(self):
        with tracing.enabled():
            sched = serve.Scheduler(max_concurrent=1, queue_depth=1,
                                    name="tr2")
            try:
                gate = threading.Event()
                blk = sched.submit(gate.wait, tenant="b")
                for _ in range(500):
                    if blk.status() == "running":
                        break
                    time.sleep(0.005)
                q1 = sched.submit(lambda: 1, tenant="b")
                with pytest.raises(Overloaded):
                    sched.submit(lambda: 2, tenant="b")
                gate.set()
                q1.result(10)
                blk.result(10)
            finally:
                sched.shutdown()
        sheds = [r for r in trace_sink.recorder().last(20)
                 if r["status"] == "shed"]
        assert sheds, "shed query never reached the flight recorder"
        assert sheds[-1]["annotations"]["shed_cause"] == "queue_full"
        assert sheds[-1].get("flushed")

    def test_injected_shed_is_captured(self):
        faultinj.configure({"faults": {"serve.admit": {"type": "reject"}}})
        with tracing.enabled():
            sched = serve.Scheduler(max_concurrent=1, name="tr3")
            try:
                with pytest.raises(Overloaded):
                    sched.submit(lambda: 1, tenant="x")
            finally:
                faultinj.disable()
                sched.shutdown()
        sheds = [r for r in trace_sink.recorder().last(10)
                 if r["status"] == "shed"]
        assert sheds and sheds[-1]["annotations"]["shed_cause"] == "injected"

    def test_failed_query_flushes_with_metrics_delta(self):
        def boom():
            raise ValueError("query exploded")

        with tracing.enabled():
            with serve.Scheduler(max_concurrent=1, name="tr4") as sched:
                h = sched.submit(boom, tenant="a")
                with pytest.raises(ValueError):
                    h.result(10)
        rec = next(r for r in reversed(trace_sink.recorder().last(10))
                   if r["status"] == "failed")
        assert rec.get("flushed")
        assert rec["metrics_delta"].get("serve.failed", 0) >= 1

    def test_cancel_in_queue_is_captured(self):
        with tracing.enabled():
            sched = serve.Scheduler(max_concurrent=1, queue_depth=4,
                                    name="tr5")
            try:
                gate = threading.Event()
                blk = sched.submit(gate.wait, tenant="a")
                for _ in range(500):
                    if blk.status() == "running":
                        break
                    time.sleep(0.005)
                q = sched.submit(lambda: 1, tenant="a")
                assert q.cancel("operator said so")
                gate.set()
                blk.result(10)
            finally:
                sched.shutdown()
        recs = [r for r in trace_sink.recorder().last(10)
                if r["status"] == "cancelled"]
        assert recs
        assert recs[-1]["annotations"]["cancel_reason"] == "operator said so"


# ---------------------------------------------------------------------------
# cross-process wire propagation (in-process worker / exchange pair)
# ---------------------------------------------------------------------------


def _groupby_payload(n=200, k=8, seed=3):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, k, n).astype(np.int64)
    vals = rng.standard_normal(n).astype(np.float32)
    return struct.pack("<IQ", k, n) + keys.tobytes() + vals.tobytes()


class _InProcWorker:
    """Serves sidecar._handle_conn from threads in THIS process (the
    test_sidecar_pool pattern) — the real protocol loop, no subprocess."""

    def __init__(self):
        self.sock_path = tempfile.mktemp(prefix="srjt-trace-") + ".sock"
        self.pid = os.getpid()
        self.returncode = None
        self._conns = []
        self._srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._srv.bind(self.sock_path)
        self._srv.listen(8)
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _accept_loop(self):
        while True:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            self._conns.append(conn)

            def _serve(c=conn):
                try:
                    sidecar._handle_conn(c, "cpu", lambda: None)
                except OSError:
                    pass

            threading.Thread(target=_serve, daemon=True).start()

    def poll(self):
        return self.returncode

    def wait(self, timeout=None):
        return self.returncode if self.returncode is not None else 0

    def terminate(self):
        self.kill()

    def kill(self):
        if self.returncode is None:
            self.returncode = -signal.SIGKILL
        try:
            self._srv.close()
        except OSError:
            pass
        for c in self._conns:
            try:
                c.close()
            except OSError:
                pass
        try:
            os.unlink(self.sock_path)
        except OSError:
            pass


def _inproc_spawn(startup_timeout_s=None, env=None):
    w = _InProcWorker()
    return w, w.sock_path


class TestSidecarWirePropagation:
    def test_worker_span_parents_to_client_request_span(self):
        w = _InProcWorker()
        payload = _groupby_payload()
        want = sidecar._dispatch(sidecar.OP_GROUPBY_SUM_F32, payload, "cpu")
        c = sidecar.SupervisedClient(w.sock_path, deadline_s=20,
                                     heartbeat_s=1e9)
        try:
            with tracing.enabled():
                qt = tracing.start_trace("wireq")
                with qt.activate():
                    resp = c.request(sidecar.OP_GROUPBY_SUM_F32, payload)
                qt.finish("ok")
            assert resp == want
            spans = _wait_for_span("sidecar.worker_op")
            req = _wait_for_span("sidecar.request")[0]
            wrk = spans[0]
            assert wrk["parent"] == req["span"]
            assert wrk["trace"] == req["trace"]
            assert wrk["annotations"]["op"] == "GROUPBY_SUM_F32"
        finally:
            c.close()
            w.kill()

    def test_untraced_request_keeps_legacy_framing(self):
        w = _InProcWorker()
        payload = _groupby_payload()
        want = sidecar._dispatch(sidecar.OP_GROUPBY_SUM_F32, payload, "cpu")
        c = sidecar.SupervisedClient(w.sock_path, deadline_s=20,
                                     heartbeat_s=1e9)
        try:
            # tracing disabled: no TRACE flag, no blob, answers intact
            assert c.request(sidecar.OP_GROUPBY_SUM_F32, payload) == want
            # armed but NO active context: still no flag on the wire
            with tracing.enabled():
                assert (
                    c.request(sidecar.OP_GROUPBY_SUM_F32, payload) == want
                )
            assert _log_spans() == []
        finally:
            c.close()
            w.kill()

    def test_pool_failover_retry_is_child_of_the_op_span(self):
        pool = sidecar_pool.SidecarPool(
            size=2, deadline_s=20, heartbeat_s=1e9, spawn_fn=_inproc_spawn
        )
        payload = _groupby_payload()
        want = sidecar._dispatch(sidecar.OP_GROUPBY_SUM_F32, payload, "cpu")
        try:
            with tracing.enabled():
                with retry.enabled(base_delay_ms=1, max_delay_ms=2,
                                   max_attempts=6):
                    qt = tracing.start_trace("poolq")
                    with qt.activate():
                        assert pool.call_arena(
                            sidecar.OP_GROUPBY_SUM_F32, payload
                        ) == want
                        f0 = metrics.registry().value(
                            "sidecar.pool.failovers"
                        )
                        pool._workers[0].proc.kill()
                        for _ in range(4):
                            assert pool.call_arena(
                                sidecar.OP_GROUPBY_SUM_F32, payload
                            ) == want
                            if metrics.registry().value(
                                "sidecar.pool.failovers"
                            ) > f0:
                                break
                    qt.finish("ok")
        finally:
            pool.shutdown()
        rec = next(r for r in trace_sink.recorder().last(5)
                   if r["name"] == "poolq")
        spans = rec["spans"]
        failover_calls = []
        for call in (s for s in spans if s["name"] == "pool.call"):
            kids = [s for s in spans
                    if s.get("parent") == call["span"]
                    and s["name"] == "pool.request"]
            wids = {s["annotations"]["wid"] for s in kids}
            if len(kids) >= 2 and len(wids) >= 2:
                failover_calls.append((call, kids))
        assert failover_calls, (
            "no pool.call span carries two pool.request attempts on "
            "distinct workers (the failover retry as a child of the "
            "original op span)"
        )
        _, kids = failover_calls[0]
        statuses = sorted(s["status"] for s in kids)
        assert statuses == ["error", "ok"]


class TestExchangePropagation:
    def test_serve_span_parents_to_fetch_span(self):
        import jax.numpy as jnp

        from spark_rapids_jni_tpu.columnar import Column, Table
        from spark_rapids_jni_tpu.columnar.dtype import DType, TypeId
        from spark_rapids_jni_tpu.parallel import shuffle

        t = Table(
            [Column(DType(TypeId.INT64),
                    data=jnp.arange(10, dtype=jnp.int64))],
            names=["a"],
        )
        a = shuffle.TcpExchange(rank=0)
        b = shuffle.TcpExchange(rank=1)
        try:
            b.publish(0, {0: t})
            with tracing.enabled():
                qt = tracing.start_trace("exq")
                with qt.activate():
                    got = a.fetch(b.address, 0, 0)
                qt.finish("ok")
            assert np.array_equal(
                np.asarray(got.columns[0].data), np.arange(10)
            )
            srv = _wait_for_span("exchange.serve")[0]
            fetch = _wait_for_span("exchange.fetch")[0]
            assert srv["parent"] == fetch["span"]
            assert srv["trace"] == fetch["trace"]
            # untraced fetch (no active context) keeps the plain verb
            got2 = a.fetch(b.address, 0, 0)
            assert got2.num_rows == 10
        finally:
            a.close()
            b.close()


class TestFullChain:
    def test_submit_queue_admission_op_wire_worker_chain(self):
        """The acceptance chain, in-process: a served query's trace
        nests serve.run -> op span -> memgov admission AND the pool's
        wire spans, connected by parent links end to end."""
        pool = sidecar_pool.SidecarPool(
            size=1, deadline_s=20, heartbeat_s=1e9, spawn_fn=_inproc_spawn
        )
        payload = _groupby_payload()
        want = sidecar._dispatch(sidecar.OP_GROUPBY_SUM_F32, payload, "cpu")

        @dispatch.op_boundary("chain_op")
        def chain_op():
            return pool.call_arena(sidecar.OP_GROUPBY_SUM_F32, payload)

        try:
            with tracing.enabled(), memgov.enabled():
                with serve.Scheduler(max_concurrent=1, name="chain") as s:
                    h = s.submit(chain_op, tenant="acme", deadline_s=30)
                    assert h.result(30) == want
        finally:
            pool.shutdown()
        rec = next(r for r in trace_sink.recorder().last(10)
                   if r["name"] == "serve.query")
        spans = {s["span"]: s for s in rec["spans"]}

        def ancestors(s):
            out = []
            while s.get("parent") in spans:
                s = spans[s["parent"]]
                out.append(s["name"])
            return out

        by_name = {}
        for s in rec["spans"]:
            by_name.setdefault(s["name"], s)
        assert "serve.run" in by_name
        op = by_name["op.chain_op"]
        assert "serve.run" in ancestors(op)
        adm = by_name["memgov.admission_wait"]
        assert "op.chain_op" in ancestors(adm)
        req = by_name["sidecar.request"]
        chain = ancestors(req)
        assert "pool.call" in chain and "op.chain_op" in chain \
            and "serve.query" in chain
        # the worker half ran in-process here; the real-pool acceptance
        # below proves the cross-pid link
        wrk = _wait_for_span("sidecar.worker_op")[0]
        assert wrk["trace"] == rec["trace"]


# ---------------------------------------------------------------------------
# the real-pool acceptance: hedge + kill -9 failover, merged cross-process
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestRealPoolCrossProcess:
    def test_hedge_and_failover_merge_across_processes(
        self, tmp_path, monkeypatch
    ):
        # per-worker chaos: w0's GROUPBY answers slowly (the hedge
        # trigger) and w0 self-SIGKILLs on its first STATS (the
        # failover); w1 runs the same profile clean. The delay holds
        # fire for the first 10 matching dispatches (`after`) so the
        # warm-up fills the op-class histogram with FAST samples — the
        # hedge trigger's p50 ceiling is a pollution guard, and a p50
        # that is itself the straggler's latency would (correctly)
        # never arm the defense.
        profile = {
            "faults": {
                "sidecar.worker.GROUPBY_SUM_F32@w0": {
                    "type": "delay", "delayMs": 400, "percent": 100,
                    "after": 10,
                },
                "sidecar.worker.STATS@w0": {
                    "type": "crash", "percent": 100,
                },
            },
            "seed": 7,
        }
        profile_path = str(tmp_path / "trace_chaos.json")
        with open(profile_path, "w") as f:
            json.dump(profile, f)
        # span-log base: the CI tier's env path when set (so the
        # premerge gate sees these spans), else test-local
        base = knobs.get_str("SRJT_TRACE_LOG") or str(
            tmp_path / "trace_spans.jsonl"
        )
        trace_sink.set_log_path(base)
        # hedging armed wide open; quarantine off so the delayed worker
        # stays routable (the hedge needs a slow primary to race)
        monkeypatch.setenv("SRJT_HEDGE_MIN_SAMPLES", "1")
        monkeypatch.setenv("SRJT_HEDGE_BUDGET_PCT", "100")
        monkeypatch.setenv("SRJT_HEDGE_SHED_WINDOW_S", "0.001")
        monkeypatch.setenv("SRJT_QUARANTINE_ENABLED", "0")
        payload = _groupby_payload()
        want = sidecar._dispatch(sidecar.OP_GROUPBY_SUM_F32, payload, "cpu")
        reg = metrics.registry()
        pool = sidecar_pool.SidecarPool(
            size=2, deadline_s=30, heartbeat_s=1e9,
            startup_timeout_s=180,
            env={
                "SRJT_FAULTINJ_CONFIG": profile_path,
                "SRJT_TRACE_ENABLED": "1",
                "SRJT_TRACE_LOG": base,
            },
        )
        client_pid = os.getpid()

        # the acceptance QUERY: one op_boundary-wrapped callable
        # submitted through the serve scheduler with memgov armed, so
        # the merged tree spans submit -> queue -> admission -> op ->
        # wire -> worker (cross-process) for ONE query
        @dispatch.op_boundary("acceptance_op")
        def acceptance_op():
            hedges0 = reg.value("sidecar.pool.hedges_won")
            for _ in range(10):
                assert pool.call_arena(
                    sidecar.OP_GROUPBY_SUM_F32, payload
                ) == want
                if reg.value("sidecar.pool.hedges_won") > hedges0:
                    break
            assert reg.value("sidecar.pool.hedges_won") > hedges0, \
                "hedged dispatch never won a race"
            fail0 = reg.value("sidecar.pool.failovers")
            for _ in range(6):
                pool.call(sidecar.OP_STATS)
                if reg.value("sidecar.pool.failovers") > fail0:
                    break
            assert reg.value("sidecar.pool.failovers") > fail0, \
                "kill -9 never produced a failover"
            return "done"

        try:
            with tracing.enabled(), memgov.enabled(), retry.enabled(
                base_delay_ms=1, max_delay_ms=4, max_attempts=8
            ):
                # warm the op class with FAST samples so the hedge
                # trigger arms well below the coming 400 ms straggler
                # (the delay rule's `after` keeps w0 clean here); the
                # workers' jax compiles also happen outside the trace
                for _ in range(24):
                    assert pool.call_arena(
                        sidecar.OP_GROUPBY_SUM_F32, payload
                    ) == want
                with serve.Scheduler(max_concurrent=1, name="acc") as s:
                    h = s.submit(acceptance_op, tenant="acme")
                    assert h.result(120) == "done"
        finally:
            pool.shutdown()
        rec = next(
            r for r in reversed(trace_sink.recorder().last(10))
            if r["name"] == "serve.query" and r["status"] == "ok"
        )
        trace_hex = rec["trace"]
        # merge every per-process log (client + both workers) and
        # assert the acceptance tree
        root, ext = os.path.splitext(base)
        pattern = f"{root}.*{ext or '.jsonl'}"

        def merged_trace():
            merged = tracemerge.merge(tracemerge.load_spans([pattern]))
            return merged["traces"].get(trace_hex)

        t = None
        end = time.monotonic() + 30
        while time.monotonic() < end:
            t = merged_trace()
            if t is not None and not t["orphans"] and any(
                s["name"] == "pool.hedge_leg" for s in t["spans"]
            ):
                legs = [s for s in t["spans"]
                        if s["name"] == "pool.hedge_leg"]
                if len(legs) % 2 == 0:
                    break
            time.sleep(0.25)
        assert t is not None, f"trace {trace_hex} missing from the merge"
        spans = t["spans"]
        # 1) zero orphans: every span's parent resolves in the trace
        assert t["orphans"] == [], t["orphans"]
        # 2) hedge legs are SIBLINGS and the winner is marked once
        legs = [s for s in spans if s["name"] == "pool.hedge_leg"]
        assert legs, "no hedge legs in the merged trace"
        by_parent = {}
        for s in legs:
            by_parent.setdefault(s["parent"], []).append(s)
        raced = [v for v in by_parent.values() if len(v) == 2]
        assert raced, "hedge legs are not siblings under one pool.call"
        winners = [s for pair in raced for s in pair
                   if (s.get("annotations") or {}).get("winner")]
        assert len(winners) == 1, (
            f"winner marked {len(winners)} times, expected exactly once"
        )
        winner_pair = next(p for p in raced if any(
            (s.get("annotations") or {}).get("winner") for s in p))
        assert {s["annotations"]["leg"] for s in winner_pair} == {
            "primary", "hedge"
        }
        # 3) the failover retry is a CHILD of the original op span
        by_id = {s["span"]: s for s in spans}
        failover = None
        for call in (s for s in spans if s["name"] == "pool.call"):
            kids = [s for s in spans
                    if s.get("parent") == call["span"]
                    and s["name"] == "pool.request"]
            if (len(kids) >= 2
                    and len({k["annotations"]["wid"] for k in kids}) >= 2):
                failover = (call, kids)
        assert failover is not None, (
            "no pool.call with a failed attempt and its retry on a "
            "different worker"
        )
        # 4) cross-process: a worker span from another pid resolves to
        # its client-side parent
        wrk = [s for s in spans if s["name"] == "sidecar.worker_op"
               and s["pid"] != client_pid]
        assert wrk, "no worker-process span joined the trace"
        for s in wrk:
            assert s["parent"] in by_id
            assert by_id[s["parent"]]["pid"] == client_pid
            assert by_id[s["parent"]]["name"] == "sidecar.request"
        # 5) the acceptance chain: submit -> queue -> admission -> op
        # -> wire -> worker, connected by parent links end to end
        def ancestor_names(s):
            out = []
            cur = s
            while cur.get("parent") in by_id:
                cur = by_id[cur["parent"]]
                out.append(cur["name"])
            return out

        chain = ancestor_names(wrk[0])
        for expected in ("sidecar.request", "pool.call",
                         "op.acceptance_op", "serve.run", "serve.query"):
            assert expected in chain, (expected, chain)
        names = {s["name"] for s in spans}
        assert "serve.queue_wait" in names
        assert "memgov.admission_wait" in names
        # 6) the tree renders
        text = tracemerge.render_tree(
            tracemerge.merge(tracemerge.load_spans([pattern])),
            only=trace_hex,
        )
        assert "pool.hedge_leg" in text and "sidecar.worker_op" in text

"""srjt-cache tests (cache/, ISSUE 17): parameterized-fingerprint
properties (literals-only-differ share a key, structure-differ never
collide across the fuzz corpus), plan-cache hit/rebind/evict economics
with bit-exactness against uncached oracles, single-flight
attach/cancel/failure isolation, memgov-governed subresult
spill-then-rematerialize, table-generation invalidation, the serve
integration (bad-estimate normalization, forecast shedding, chaos
eviction with zero wrong answers), and the stats surfaces."""

import threading
import time

import numpy as np
import pytest

from spark_rapids_jni_tpu import cache, memgov
from spark_rapids_jni_tpu import plan as P
from spark_rapids_jni_tpu.cache import plancache, tablegen
from spark_rapids_jni_tpu.cache.flight import SingleFlight
from spark_rapids_jni_tpu.columnar import Table
from spark_rapids_jni_tpu.columnar.column import Column
from spark_rapids_jni_tpu.plan.rewrites import (
    parameterized_fingerprint,
    rebind_literals,
)
from spark_rapids_jni_tpu.serve.scheduler import Scheduler
from spark_rapids_jni_tpu.utils import deadline, faultinj, metrics
from spark_rapids_jni_tpu.utils.errors import DeadlineExceeded, Overloaded

_COUNTERS = (
    "hits", "misses", "rebinds", "rebind_fallbacks", "insert_verified",
    "insert_rejected", "evictions", "evict_injected", "share",
    "share_fallback", "sub_hits", "sub_misses", "sub_evictions",
    "sub_corrupt", "invalidations",
)


def _vals():
    reg = metrics.registry()
    return {n: reg.value(f"cache.{n}") for n in _COUNTERS}


def _delta(before, after):
    return {n: after[n] - before[n] for n in _COUNTERS}


@pytest.fixture(autouse=True)
def _clean_cache(monkeypatch):
    # every test starts from the shipped OFF posture, even when the CI
    # tier exports the cache knobs process-wide (the premerge cache
    # tier does) — tests that want the caches armed say so via `armed`
    monkeypatch.delenv("SRJT_PLAN_CACHE", raising=False)
    monkeypatch.delenv("SRJT_SUBRESULT_CACHE", raising=False)
    cache.reset()
    faultinj.disable()
    yield
    cache.reset()
    faultinj.disable()


@pytest.fixture
def armed(monkeypatch):
    monkeypatch.setenv("SRJT_PLAN_CACHE", "1")
    monkeypatch.setenv("SRJT_SUBRESULT_CACHE", "1")


def _tables(rows=120):
    rng = np.random.default_rng(11)
    return {
        "fact": Table(
            [Column.from_numpy(np.arange(rows, dtype=np.int64)),
             Column.from_numpy(rng.integers(0, 7, rows).astype(np.int64)),
             Column.from_numpy(rng.random(rows))],
            ["v", "k", "p"],
        ),
    }


def _mk(cut, factor=2.0):
    return P.Aggregate(
        P.Filter(P.Scan("fact"),
                 (P.pcol("v") < P.plit(cut)) & (P.pcol("p") < P.plit(factor))),
        keys=("k",), aggs=(P.AggSpec("v", "sum", "s"),),
    )


# ---------------------------------------------------------------------------
# parameterized fingerprint properties
# ---------------------------------------------------------------------------


class TestParamFingerprint:
    def test_literals_only_differ_same_key(self):
        a, b = parameterized_fingerprint(_mk(10)), parameterized_fingerprint(_mk(99))
        assert a.key == b.key
        assert a.values != b.values
        # but the FULL fingerprints differ — the param key is coarser
        from spark_rapids_jni_tpu.plan.rewrites import fingerprint
        assert fingerprint(_mk(10)) != fingerprint(_mk(99))

    def test_structure_differ_different_key(self):
        plain = parameterized_fingerprint(_mk(10))
        sorted_ = parameterized_fingerprint(
            P.Sort(_mk(10), keys=(("s", False),)))
        assert plain.key != sorted_.key

    def test_literal_type_tags_distinct(self):
        # int vs float vs np.int32 in the same slot = different
        # structures: _PLit.dtype() infers INT64/FLOAT64/INT32 and a
        # rebind across them would change the compiled plan's schema
        f = lambda lit: parameterized_fingerprint(
            P.Filter(P.Scan("fact"), P.pcol("v") < P.plit(lit)))
        keys = {f(10).key, f(10.0).key, f(np.int32(10)).key}
        assert len(keys) == 3

    def test_rebind_reproduces_fresh_plan(self):
        from spark_rapids_jni_tpu.plan.rewrites import fingerprint

        orig = _mk(1998, 0.5)
        orig_fp = fingerprint(orig)
        pf = parameterized_fingerprint(orig)
        mapping = {}
        for tag, value, d in pf.bindings:
            if tag == "int":
                mapping[(tag, value, d)] = 2001
        rebound = rebind_literals(orig, mapping)
        assert fingerprint(rebound) == fingerprint(_mk(2001, 0.5))
        # the original is untouched (frozen nodes, rebuilt not mutated)
        assert fingerprint(orig) == orig_fp

    def test_fuzz_corpus_keys_track_slotted_structure(self):
        """Across the planfuzz seed corpus: equal param keys <=> equal
        literal-slotted structures (no collisions, no spurious splits)."""
        from spark_rapids_jni_tpu.analysis import planfuzz
        from spark_rapids_jni_tpu.plan import rewrites as RW

        by_key = {}
        for seed in range(555, 579):
            p, _ = planfuzz.gen_plan(np.random.default_rng(seed))
            pf = parameterized_fingerprint(p)
            slotted = repr(RW._slot_literals(P.structure(p), []))
            assert by_key.setdefault(pf.key, slotted) == slotted, (
                f"seed {seed}: param-key collision across different "
                f"slotted structures"
            )
        # the corpus is diverse enough for the property to mean something
        assert len(by_key) > 3


# ---------------------------------------------------------------------------
# compiled-plan cache
# ---------------------------------------------------------------------------


class TestPlanCache:
    def test_off_knob_is_plain_compile(self):
        fn = cache.compile_cached(_mk(10), _tables(), name="off")
        assert not isinstance(fn, cache.CachedQuery)
        assert type(fn).__name__ == "CompiledPlan"

    def test_miss_then_exact_hit(self, armed):
        tabs = _tables()
        before = _vals()
        q1 = cache.compile_cached(_mk(10), tabs, name="q")
        q2 = cache.compile_cached(_mk(10), tabs, name="q")
        d = _delta(before, _vals())
        assert d["misses"] == 1 and d["hits"] == 1 and d["rebinds"] == 0
        assert d["insert_verified"] == 1
        assert q2.compiled is q1.compiled  # the retained artifact itself
        assert q1().to_pydict() == q2().to_pydict()

    def test_rebind_hit_bit_exact(self, armed):
        tabs = _tables()
        cache.compile_cached(_mk(10), tabs, name="q")
        before = _vals()
        q = cache.compile_cached(_mk(77), tabs, name="q")
        d = _delta(before, _vals())
        assert d["hits"] == 1 and d["rebinds"] == 1 and d["misses"] == 0
        oracle = P.compile_ir(_mk(77), tabs, name="oracle")
        assert q().to_pydict() == oracle().to_pydict()

    def test_verifier_gate_blocks_insert(self, armed, monkeypatch):
        # a red verifier verdict must keep the artifact OUT of the
        # cache (still returned to run once) — next submission misses
        monkeypatch.setattr(plancache, "verify_for_cache",
                            lambda *a, **k: ["simulated violation"])
        tabs = _tables()
        before = _vals()
        q1 = cache.compile_cached(_mk(10), tabs, name="q")
        q2 = cache.compile_cached(_mk(10), tabs, name="q")
        d = _delta(before, _vals())
        assert d["insert_rejected"] == 2 and d["misses"] == 2
        assert d["hits"] == 0 and d["insert_verified"] == 0
        assert q1().to_pydict() == q2().to_pydict()

    def test_lru_eviction_counts(self, armed, monkeypatch):
        monkeypatch.setenv("SRJT_CACHE_PLAN_ENTRIES", "2")
        tabs = _tables()
        before = _vals()
        cache.compile_cached(_mk(1), tabs, name="a")
        cache.compile_cached(P.Sort(_mk(1), keys=(("s", False),)), tabs,
                             name="b")
        cache.compile_cached(P.Limit(P.Sort(_mk(1), keys=(("s", False),)), 3),
                             tabs, name="c")
        d = _delta(before, _vals())
        assert d["evictions"] == 1
        assert cache.plan_cache().snapshot()["entries"] == 2

    def test_cost_ewma_feeds_predicted_cost(self, armed):
        tabs = _tables()
        q = cache.compile_cached(_mk(10), tabs, name="q")
        assert q.predicted_cost_s is None  # no evidence yet
        q()
        assert q.predicted_cost_s is not None and q.predicted_cost_s > 0

    def test_catalog_signature_splits_schemas(self, armed):
        # same plan over a schema with different dtypes = different entry
        tabs = _tables()
        other = {"fact": Table(
            [Column.from_numpy(np.arange(8, dtype=np.int32)),
             Column.from_numpy(np.zeros(8, dtype=np.int64)),
             Column.from_numpy(np.zeros(8))],
            ["v", "k", "p"],
        )}
        before = _vals()
        cache.compile_cached(_mk(5), tabs, name="q")
        cache.compile_cached(_mk(5), other, name="q")
        d = _delta(before, _vals())
        assert d["misses"] == 2 and d["hits"] == 0


# ---------------------------------------------------------------------------
# single-flight latch
# ---------------------------------------------------------------------------


class TestSingleFlight:
    def test_fan_out_computes_once(self):
        sf = SingleFlight("t")
        gate = threading.Event()
        calls = []

        def thunk():
            gate.wait(5)
            calls.append(1)
            return {"x": 1}

        results = [None] * 6
        def run(i):
            results[i] = sf.run("k", thunk)
        ts = [threading.Thread(target=run, args=(i,)) for i in range(6)]
        for t in ts:
            t.start()
        # let everyone reach the latch, then release the leader
        time.sleep(0.1)
        before = _vals()
        gate.set()
        for t in ts:
            t.join(10)
        assert len(calls) == 1, "exactly one computation per key"
        assert all(r == {"x": 1} for r in results)
        # the waiters shared the leader's leg
        assert metrics.registry().value("cache.share") >= 5

    def test_waiter_cancel_never_cancels_leader(self):
        sf = SingleFlight("t")
        gate = threading.Event()
        out = {}

        def thunk():
            gate.wait(10)
            return 42

        def leader():
            out["leader"] = sf.run("k", thunk)

        def waiter():
            try:
                with deadline.scope(0.1):
                    sf.run("k", thunk)
                out["waiter"] = "no-raise"
            except DeadlineExceeded:
                out["waiter"] = "expired"

        tl = threading.Thread(target=leader)
        tl.start()
        time.sleep(0.05)
        tw = threading.Thread(target=waiter)
        tw.start()
        tw.join(10)
        assert out["waiter"] == "expired"  # the waiter's budget, its exit
        gate.set()
        tl.join(10)
        assert out["leader"] == 42  # the shared leg survived the cancel

    def test_leader_failure_not_fanned_out(self):
        sf = SingleFlight("t")
        gate = threading.Event()
        calls = []

        def thunk():
            calls.append(1)
            if len(calls) == 1:
                gate.wait(5)
                raise RuntimeError("leader crashed")
            return "recomputed"

        out = {}
        def leader():
            try:
                sf.run("k", thunk)
            except RuntimeError:
                out["leader"] = "raised"

        def waiter():
            out["waiter"] = sf.run("k", thunk)

        before = metrics.registry().value("cache.share_fallback")
        tl = threading.Thread(target=leader)
        tl.start()
        time.sleep(0.05)
        tw = threading.Thread(target=waiter)
        tw.start()
        time.sleep(0.05)
        gate.set()
        tl.join(10)
        tw.join(10)
        assert out["leader"] == "raised"
        assert out["waiter"] == "recomputed"  # per-leg fault isolation
        assert metrics.registry().value("cache.share_fallback") == before + 1


# ---------------------------------------------------------------------------
# subresult cache (memgov-governed)
# ---------------------------------------------------------------------------


class TestSubresultCache:
    def test_spill_then_rematerialize_hit_bit_exact(self, armed):
        tabs = _tables()
        q = cache.compile_cached(_mk(50), tabs, name="q")
        first = q().to_pydict()
        sc = cache.subresult_cache()
        assert sc.snapshot()["entries"] > 0
        # demote every cached subresult host-ward, behind the cache's
        # back — exactly what governor pressure does
        with sc._lock:
            handles = [e.handle for e in sc._entries.values()]
        for h in handles:
            h.spill()
        before = _vals()
        again = cache.compile_cached(_mk(50), tabs, name="q")().to_pydict()
        d = _delta(before, _vals())
        assert again == first  # CRC-checked rematerialization, bit-exact
        assert d["sub_hits"] > 0 and d["sub_corrupt"] == 0

    def test_governed_bytes_ride_the_catalog(self, armed):
        tabs = _tables()
        cache.compile_cached(_mk(50), tabs, name="q")()
        entries, nbytes = memgov.catalog().kind_stats("cache")
        assert entries > 0 and nbytes > 0
        snap = memgov.catalog().snapshot()
        assert snap["cache_entries"] == entries
        cache.reset()
        entries, nbytes = memgov.catalog().kind_stats("cache")
        assert entries == 0 and nbytes == 0  # reset unregisters cleanly

    def test_corrupt_entry_degrades_to_recompute(self, armed):
        tabs = _tables()
        q = cache.compile_cached(_mk(50), tabs, name="q")
        first = q().to_pydict()
        sc = cache.subresult_cache()
        # yank the governed entries out from under the cache (the
        # closed-handle flavor of rot); hits must degrade to recompute
        with sc._lock:
            regkeys = [e.regkey for e in sc._entries.values()]
        for rk in regkeys:
            memgov.catalog().unregister(rk)
        before = _vals()
        again = cache.compile_cached(_mk(50), tabs, name="q")().to_pydict()
        d = _delta(before, _vals())
        assert again == first
        assert d["sub_corrupt"] > 0  # rot observed, answered by recompute

    def test_byte_cap_evicts_lru(self, armed, monkeypatch):
        monkeypatch.setenv("SRJT_CACHE_SUBRESULT_BYTES", "1")
        tabs = _tables()
        before = _vals()
        cache.compile_cached(_mk(50), tabs, name="q")()
        d = _delta(before, _vals())
        assert d["sub_evictions"] > 0
        assert cache.subresult_cache().snapshot()["entries"] <= 1

    def test_invalidate_table_drops_dependents(self, armed):
        tabs = _tables()
        q = cache.compile_cached(_mk(50), tabs, name="q")
        first = q().to_pydict()
        assert cache.subresult_cache().snapshot()["entries"] > 0
        before = _vals()
        cache.invalidate_table(tabs["fact"])
        d = _delta(before, _vals())
        assert d["invalidations"] > 0
        assert cache.subresult_cache().snapshot()["entries"] == 0
        # resubmission recomputes (new stamps -> new keys), same answer
        before = _vals()
        again = cache.compile_cached(_mk(50), tabs, name="q")().to_pydict()
        d = _delta(before, _vals())
        assert again == first and d["sub_hits"] == 0 and d["sub_misses"] > 0

    def test_new_table_object_never_aliases(self, armed):
        # a reloaded table (different object, same shape) must not hit
        # subresults computed over the old one — serial-based identity
        tabs1, tabs2 = _tables(), _tables()
        q1 = cache.compile_cached(_mk(50), tabs1, name="q")
        q1()
        before = _vals()
        cache.compile_cached(_mk(50), tabs2, name="q")()
        d = _delta(before, _vals())
        assert d["sub_hits"] == 0  # fresh serials, fresh keys


# ---------------------------------------------------------------------------
# serve integration
# ---------------------------------------------------------------------------


class TestServeIntegration:
    def test_cached_serving_bit_exact(self, armed):
        tabs = _tables()
        oracle = {
            cut: P.compile_ir(_mk(cut), tabs, name="oracle")().to_pydict()
            for cut in (10, 50, 90)
        }
        s = Scheduler(max_concurrent=2, queue_depth=32, name="csrv")
        try:
            handles = [
                (cut, s.submit(_mk(cut), tabs, tenant="t"))
                for cut in (10, 50, 90) for _ in range(4)
            ]
            for cut, h in handles:
                assert h.result(30).to_pydict() == oracle[cut]
        finally:
            assert s.shutdown(drain=False, timeout_s=30.0)
        v = _vals()
        assert v["hits"] >= 9  # 12 submissions, 3 structures-as-misses

    def test_bad_estimate_normalized(self, armed):
        reg = metrics.registry()
        s = Scheduler(max_concurrent=1, queue_depth=8, name="best")
        try:
            def fn():
                return 7
            fn.estimated_memory_bytes = 0  # "free query" lie
            before = reg.value("serve.bad_estimate")
            h = s.submit(fn, tenant="t")
            assert h.result(10) == 7
            assert reg.value("serve.bad_estimate") == before + 1
            assert h._memory_bytes is None  # normalized, not admitted as 0
            # explicit negative estimate normalizes the same way
            h2 = s.submit(lambda: 8, tenant="t", memory_bytes=-5)
            assert h2.result(10) == 8
            assert reg.value("serve.bad_estimate") == before + 2
        finally:
            assert s.shutdown(drain=False, timeout_s=30.0)

    def test_forecast_shed(self, monkeypatch):
        monkeypatch.setenv("SRJT_SERVE_FORECAST_BUDGET_SEC", "5")
        reg = metrics.registry()
        s = Scheduler(max_concurrent=1, queue_depth=8, name="fcst")
        try:
            ev = threading.Event()
            blocker = s.submit(ev.wait, 30, tenant="t")
            t0 = time.monotonic()
            while blocker.status() != "running":
                assert time.monotonic() - t0 < 5
                time.sleep(0.002)

            def pricey():
                return 1
            pricey.predicted_cost_s = 10.0  # EWMA says: 10s of work
            before = reg.value("serve.shed.forecast")
            with pytest.raises(Overloaded) as ei:
                s.submit(pricey, tenant="t")
            assert ei.value.cause == "forecast"
            assert reg.value("serve.shed.forecast") == before + 1
            # a query with NO cost evidence is never forecast-shed
            h = s.submit(lambda: 2, tenant="t")
            ev.set()
            assert h.result(10) == 2
        finally:
            ev.set()
            assert s.shutdown(drain=False, timeout_s=30.0)

    def test_chaos_cache_evict_zero_wrong_answers(self, armed):
        tabs = _tables()
        oracle = P.compile_ir(_mk(50), tabs, name="oracle")().to_pydict()
        faultinj.configure({
            "seed": 3,
            "faults": {"cache.*": {"type": "cache_evict", "percent": 100}},
        })
        before = _vals()
        try:
            for _ in range(5):
                got = cache.compile_cached(_mk(50), tabs, name="q")()
                assert got.to_pydict() == oracle
        finally:
            faultinj.disable()
        d = _delta(before, _vals())
        assert d["evict_injected"] > 0  # the storm really landed


# ---------------------------------------------------------------------------
# stats surfaces
# ---------------------------------------------------------------------------


class TestStats:
    def test_stats_report_cache_section(self, armed):
        from spark_rapids_jni_tpu import runtime

        cache.compile_cached(_mk(10), _tables(), name="q")()
        rep = runtime.stats_report()
        sec = rep["cache"]
        assert sec["enabled"]["plan"] is True
        assert sec["counters"]["misses"] >= 1
        assert sec["plan"]["entries"] >= 1
        assert "governed" in sec and sec["governed"]["entries"] >= 0
        # the pretty renderer walks the new section without choking
        assert "cache" in runtime.stats_report(pretty=True)

    def test_stage_report_cache_keys(self):
        rep = metrics.stage_report("s")
        assert set(rep["cache"]) == {
            "hits", "misses", "rebinds", "share", "sub_hits",
            "sub_misses", "evictions", "evict_injected",
        }

    def test_off_posture_stats_inert(self):
        sec = cache.stats_section()
        assert sec["enabled"]["plan"] is False
        assert "plan" not in sec  # no singleton was materialized

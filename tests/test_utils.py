"""Aux tier tests: fault injection (deterministic, budgeted, hot-reload),
error classification, tracing scopes, and the op_boundary preamble —
the chaos tier the reference drives via libcufaultinj.so + JSON configs
(SURVEY §2.4), here exercised hermetically in-process."""

import json
import os

import numpy as np
import pytest

import spark_rapids_jni_tpu  # noqa: F401
from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.columnar import dtype as dt
from spark_rapids_jni_tpu.ops.aggregate import groupby_aggregate
from spark_rapids_jni_tpu.utils import dispatch, errors, faultinj, tracing


@pytest.fixture(autouse=True)
def clean_faults():
    faultinj.disable()
    yield
    faultinj.disable()


def _small_table():
    k = Table([Column.from_pylist([1, 1, 2], dt.INT32)], ["k"])
    v = Table([Column.from_pylist([1, 2, 3], dt.INT64)], ["v"])
    return k, v


class TestFaultInj:
    def test_disabled_by_default(self):
        assert not faultinj.is_enabled()
        k, v = _small_table()
        groupby_aggregate(k, v, [("v", "sum")])  # no fault

    def test_named_fault_fires(self):
        faultinj.configure(
            {"seed": 1, "faults": {"groupby_aggregate": {"type": "retryable", "percent": 100}}}
        )
        k, v = _small_table()
        with pytest.raises(errors.RetryableError, match="injected"):
            groupby_aggregate(k, v, [("v", "sum")])

    def test_wildcard_and_fatal(self):
        faultinj.configure({"seed": 1, "faults": {"*": {"type": "fatal", "percent": 100}}})
        k, v = _small_table()
        with pytest.raises(errors.FatalDeviceError):
            groupby_aggregate(k, v, [("v", "sum")])

    def test_interception_budget(self):
        faultinj.configure(
            {
                "seed": 1,
                "faults": {
                    "groupby_aggregate": {
                        "type": "exception",
                        "percent": 100,
                        "interceptionCount": 2,
                    }
                },
            }
        )
        k, v = _small_table()
        for _ in range(2):
            with pytest.raises(RuntimeError):
                groupby_aggregate(k, v, [("v", "sum")])
        out = groupby_aggregate(k, v, [("v", "sum")])  # budget exhausted
        assert out.num_rows == 2

    def test_deterministic_seed(self):
        hits = []
        for _ in range(2):
            faultinj.configure(
                {"seed": 777, "faults": {"groupby_aggregate": {"type": "exception", "percent": 40}}}
            )
            k, v = _small_table()
            pattern = []
            for _ in range(20):
                try:
                    groupby_aggregate(k, v, [("v", "sum")])
                    pattern.append(0)
                except RuntimeError:
                    pattern.append(1)
            hits.append(pattern)
        assert hits[0] == hits[1]  # same seed -> same interception sequence
        assert sum(hits[0]) > 0

    def test_hot_reload(self, tmp_path):
        cfg = tmp_path / "faults.json"
        cfg.write_text(json.dumps({"faults": {}}))
        faultinj.configure_from_file(str(cfg))
        k, v = _small_table()
        groupby_aggregate(k, v, [("v", "sum")])  # no faults configured

        new = {"faults": {"groupby_aggregate": {"type": "retryable", "percent": 100}}}
        cfg.write_text(json.dumps(new))
        os.utime(cfg, (0, 0))  # force mtime change even on coarse clocks
        with pytest.raises(errors.RetryableError):
            groupby_aggregate(k, v, [("v", "sum")])

    def test_bad_config_rejected(self):
        with pytest.raises(ValueError, match="unknown fault type"):
            faultinj.configure({"faults": {"x": {"type": "nonsense"}}})


class TestErrors:
    def test_classify_retryable(self):
        e = errors.classify(RuntimeError("RESOURCE_EXHAUSTED: hbm oom"))
        assert isinstance(e, errors.RetryableError)

    def test_classify_fatal_unknown(self):
        e = errors.classify(RuntimeError("backend exploded in a new way"))
        assert isinstance(e, errors.FatalDeviceError)

    def test_classify_deadline_exceeded_retryable(self):
        # "DEAD" is word-bounded: it must not swallow DEADLINE_EXCEEDED
        e = errors.classify(RuntimeError("DEADLINE_EXCEEDED: op timed out"))
        assert isinstance(e, errors.RetryableError)

    def test_classify_mixed_markers_fatal_wins(self):
        # A dead accelerator often surfaces with a retryable-looking
        # suffix; retrying batches on a dead device strands the
        # executor, so fatal must win.
        e = errors.classify(
            RuntimeError("INTERNAL: Accelerator t5 channel UNAVAILABLE")
        )
        assert isinstance(e, errors.FatalDeviceError)

    def test_host_errors_pass_through(self):
        with pytest.raises(ValueError):
            errors.classify(ValueError("bad argument"))

    def test_op_boundary_classifies(self):
        @dispatch.op_boundary("boom_op")
        def boom():
            raise RuntimeError("UNAVAILABLE: link down")

        with pytest.raises(errors.RetryableError):
            boom()

    def test_op_boundary_host_error_unwrapped(self):
        @dispatch.op_boundary("val_op")
        def bad():
            raise ValueError("plain host error")

        with pytest.raises(ValueError):
            bad()


class TestTracing:
    def test_func_range_off_and_on(self):
        assert not tracing.is_enabled()
        with tracing.func_range("x"):
            pass
        tracing.set_enabled(True)
        try:
            k, v = _small_table()
            out = groupby_aggregate(k, v, [("v", "sum")])  # runs under named_scope
            assert out.num_rows == 2
        finally:
            tracing.set_enabled(False)

"""Tests for srjt-race (ISSUE 11): the static guarded-by inference
pass (SRJT008/009/010) and the dynamic vector-clock race detector.

- static rule fixtures: each rule FIRES on a seeded snippet and stays
  quiet on the guarded/suppressed/immutable variants; the suppression
  grammar (guarded-by / allow-unguarded) and its SRJT000 stale audit
  are part of the tool's contract.
- dynamic detector: a deliberately seeded unguarded write is REPORTED
  (with both stacks) under a thread storm — the gate-can-fail proof —
  while lock/Event/Thread.start-join/Semaphore/Barrier-ordered access
  is clean; the merge CLI fails on any race_pairs, same discipline as
  lockdep cycles.
- the integration gates: the REAL tree is statically clean, and the
  machine-readable formats carry exit-code parity with text mode.
"""

import json
import threading
import time

import pytest

from spark_rapids_jni_tpu.analysis import lint, lockdep, races

# ---------------------------------------------------------------------------
# static layer: fixtures
# ---------------------------------------------------------------------------


def scan(src, rel="serve/x.py", rules=None):
    vs = races.scan_source(src, path=f"<fixture:{rel}>", rel=rel)
    if rules is None:
        return vs
    return [v for v in vs if v.rule in rules]


MIXED = """\
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0

    def bump(self):
        with self._lock:
            self._count += 1

    def peek(self):
        return self._count
"""


def test_mixed_guarded_bare_access_fires():
    vs = scan(MIXED, rules={"SRJT008"})
    assert len(vs) == 1 and "C._count" in vs[0].message
    assert "guarded-by" in vs[0].message  # the fix-or-annotate hint


def test_fully_guarded_is_clean():
    src = MIXED.replace(
        "    def peek(self):\n        return self._count\n",
        "    def peek(self):\n        with self._lock:\n"
        "            return self._count\n",
    )
    assert scan(src) == []


def test_locked_suffix_method_counts_as_guarded():
    src = MIXED.replace("def peek(self):", "def peek_locked(self):")
    assert scan(src) == []


def test_init_only_writes_do_not_fire():
    # immutable-after-__init__ shape: guarded + bare READS are fine
    src = """\
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._workers = [1, 2, 3]

    def pick(self):
        with self._lock:
            return self._workers[0]

    def count(self):
        return len(self._workers)
"""
    assert scan(src) == []


def test_condition_alias_guards_the_same_state():
    # holding the Condition built OVER the lock == holding the lock
    src = """\
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._health = threading.Condition(self._lock)
        self._n = 0

    def a(self):
        with self._lock:
            self._n += 1

    def b(self):
        with self._health:
            self._n += 1
"""
    assert scan(src) == []


def test_own_condition_is_its_own_guard():
    src = """\
import threading

class C:
    def __init__(self):
        self._cond = threading.Condition()
        self._q = []

    def put(self, x):
        with self._cond:
            self._q.append(x)

    def depth(self):
        return len(self._q)
"""
    vs = scan(src, rules={"SRJT008"})
    assert len(vs) == 1 and "C._q" in vs[0].message


def test_nested_def_counts_as_bare_but_lambda_is_in_place():
    # a thread-target closure defined under the lock RUNS without it
    src = """\
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._m = {}

    def go(self):
        with self._lock:
            self._m["x"] = 1
            def later():
                self._m["x"] = 2
            threading.Thread(target=later).start()
"""
    vs = scan(src, rules={"SRJT008"})
    assert len(vs) == 1
    # ...but a sort-key lambda executes in place, under the lock
    src2 = """\
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._m = {}

    def evict(self):
        with self._lock:
            self._m.pop(min(self._m, key=lambda k: self._m[k]))
"""
    assert scan(src2) == []


def test_suppression_on_init_assignment_covers_the_attribute():
    src = MIXED.replace(
        "        self._count = 0",
        "        self._count = 0  "
        "# srjt-race: allow-unguarded(GIL-atomic word)",
    )
    assert scan(src) == []


def test_guarded_by_suppression_on_bare_line():
    src = MIXED.replace(
        "        return self._count",
        "        return self._count  # srjt-race: guarded-by(_lock)",
    )
    assert scan(src) == []


def test_empty_suppression_arg_is_srjt000():
    src = MIXED.replace(
        "        return self._count",
        "        return self._count  # srjt-race: allow-unguarded()",
    )
    vs = scan(src)
    assert [v.rule for v in vs] == ["SRJT000"]
    assert "needs a" in vs[0].message


def test_stale_suppression_is_srjt000():
    src = "x = 1  # srjt-race: guarded-by(_lock)\n"
    vs = scan(src)
    assert [v.rule for v in vs] == ["SRJT000"]
    assert "stale" in vs[0].message


# -- SRJT009: check-then-act -------------------------------------------------

CHECK_THEN_ACT = """\
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._slab = None

    def get(self):
        if self._slab is None:
            with self._lock:
                self._slab = object()
        return self._slab
"""


def test_check_then_act_fires():
    vs = scan(CHECK_THEN_ACT, rules={"SRJT009"})
    assert len(vs) == 1 and "check-then-act" in vs[0].message
    assert "C._slab" in vs[0].message


def test_check_under_a_different_lock_still_fires():
    src = """\
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._aux = threading.Lock()
        self._slab = None

    def get(self):
        with self._aux:
            if self._slab is None:
                pass
        with self._lock:
            self._slab = object()
"""
    vs = scan(src, rules={"SRJT009"})
    assert len(vs) == 1


def test_check_under_its_own_lock_is_clean():
    src = CHECK_THEN_ACT.replace(
        "        if self._slab is None:\n"
        "            with self._lock:\n"
        "                self._slab = object()\n"
        "        return self._slab\n",
        "        with self._lock:\n"
        "            if self._slab is None:\n"
        "                self._slab = object()\n"
        "            return self._slab\n",
    )
    assert scan(src, rules={"SRJT009"}) == []


def test_read_only_function_is_not_check_then_act():
    # a branch on a guarded attr in a function that never WRITES it is
    # a stale read at worst, not a lost update — SRJT009 stays quiet
    # (SRJT008 governs the mixed-access posture)
    src = CHECK_THEN_ACT.replace(
        "            with self._lock:\n"
        "                self._slab = object()\n",
        "            pass\n",
    )
    assert scan(src, rules={"SRJT009"}) == []


def test_check_then_act_suppressible():
    src = CHECK_THEN_ACT.replace(
        "        if self._slab is None:",
        "        if self._slab is None:  "
        "# srjt-race: allow-unguarded(idempotent lazy init; double build is benign)",
    )
    assert scan(src, rules={"SRJT009"}) == []


# -- SRJT010: bare module-global mutation ------------------------------------

GLOBAL_MUT = """\
import threading

_CACHE = {}
_CACHE_LOCK = threading.Lock()


def put(k, v):
    _CACHE[k] = v
"""


def test_bare_global_mutation_fires():
    vs = scan(GLOBAL_MUT, rules={"SRJT010"})
    assert len(vs) == 1 and "_CACHE" in vs[0].message


def test_global_mutation_under_lock_is_clean():
    src = GLOBAL_MUT.replace(
        "    _CACHE[k] = v",
        "    with _CACHE_LOCK:\n        _CACHE[k] = v",
    )
    assert scan(src, rules={"SRJT010"}) == []


def test_mutator_method_on_global_fires():
    src = "_SEEN = set()\n\n\ndef note(x):\n    _SEEN.add(x)\n"
    vs = scan(src, rules={"SRJT010"})
    assert len(vs) == 1


def test_local_shadowing_global_name_is_clean():
    src = "_CACHE = {}\n\n\ndef f():\n    _CACHE = {}\n    _CACHE['x'] = 1\n"
    assert scan(src, rules={"SRJT010"}) == []


def test_global_mutation_suppressible():
    src = GLOBAL_MUT.replace(
        "    _CACHE[k] = v",
        "    _CACHE[k] = v  "
        "# srjt-race: allow-unguarded(import-time only; single-threaded by construction)",
    )
    assert scan(src, rules={"SRJT010"}) == []


# -- scoping + integration gates ---------------------------------------------


def test_ungoverned_module_is_not_scanned():
    vs = races.scan_source(MIXED, path="<f>", rel="ops/x.py")
    # rel scoping happens in run(); scan_source itself scans anything —
    # prove run()'s governed filter instead
    assert races._governed("serve/scheduler.py")
    assert races._governed("sidecar_pool.py")
    assert races._governed("utils/metrics.py")
    assert not races._governed("ops/join.py")
    assert not races._governed("models/tpcds.py")
    assert vs  # the snippet itself still carries its finding


def test_real_tree_is_clean():
    vs = races.run()
    assert vs == [], "\n".join(repr(v) for v in vs)


def test_races_cli_exit_codes(tmp_path, capsys):
    assert races.main([]) == 0
    capsys.readouterr()
    out = tmp_path / "r.sarif"
    assert races.main(["--format=sarif", f"--out={out}"]) == 0
    capsys.readouterr()
    sarif = json.loads(out.read_text())
    assert sarif["version"] == "2.1.0"


# -- machine-readable formats (shared with srjt-lint) ------------------------


def test_format_findings_json_and_sarif_shapes():
    vs = [lint.Violation("a.py", 3, "SRJT008", "msg one"),
          lint.Violation("b.py", 7, "SRJT010", "msg two")]
    j = json.loads(lint.format_findings(vs, "json", tool="t"))
    assert j["tool"] == "t" and len(j["findings"]) == 2
    assert j["findings"][0] == {"path": "a.py", "line": 3,
                                "rule": "SRJT008", "message": "msg one"}
    s = json.loads(lint.format_findings(vs, "sarif", tool="t"))
    results = s["runs"][0]["results"]
    assert [r["ruleId"] for r in results] == ["SRJT008", "SRJT010"]
    assert results[0]["locations"][0]["physicalLocation"]["region"][
        "startLine"] == 3


@pytest.mark.parametrize("fmt", ["text", "json", "sarif"])
def test_exit_code_parity_across_formats(fmt, tmp_path, capsys):
    vs = [lint.Violation("a.py", 1, "SRJT008", "m")]
    rc_dirty = lint.write_findings(vs, fmt, str(tmp_path / f"d.{fmt}"), "t")
    rc_clean = lint.write_findings([], fmt, str(tmp_path / f"c.{fmt}"), "t")
    capsys.readouterr()
    assert rc_dirty == 1 and rc_clean == 0


def test_lint_cli_format_flag(tmp_path, capsys):
    out = tmp_path / "lint.sarif"
    assert lint.main(["--format=sarif", f"--out={out}"]) == 0
    capsys.readouterr()
    assert json.loads(out.read_text())["runs"][0]["tool"]["driver"][
        "name"] == "srjt-lint"


# ---------------------------------------------------------------------------
# dynamic layer: the vector-clock detector
# ---------------------------------------------------------------------------


@pytest.fixture
def armed_races():
    """Arm shim + detector for one test in an isolated universe —
    seeded races must never reach the session report the CI gate
    merges (the lockdep isolated_state discipline)."""
    was_installed = lockdep.is_installed()
    was_armed = lockdep.race_armed()
    lockdep.enable_race_detection()
    with lockdep.isolated_state() as st:
        yield st
    if not was_armed:
        lockdep.disable_race_detection()
    if not was_installed:
        lockdep.uninstall()


def _run_threads(*fns):
    ts = [threading.Thread(target=f) for f in fns]
    for t in ts:
        t.start()
    for t in ts:
        t.join(20)
    assert not any(t.is_alive() for t in ts)


def test_seeded_unguarded_write_is_reported_with_both_stacks(armed_races):
    d = lockdep.track({}, "seeded")

    def w1():
        for i in range(100):
            d["x"] = i

    _run_threads(w1, w1)
    rep = lockdep.report(armed_races)
    assert rep["race_total"] > 0 and rep["race_pairs"]
    pair = rep["race_pairs"][0]
    assert "seeded" in pair["location"]
    assert pair["a"]["stack"] and pair["b"]["stack"]  # both access stacks
    assert pair["a"]["thread"] != pair["b"]["thread"]


def test_lock_ordered_access_is_clean(armed_races):
    d = lockdep.track({}, "locked")
    mu = threading.Lock()

    def w():
        for i in range(100):
            with mu:
                d["x"] = i

    _run_threads(w, w)
    assert lockdep.report(armed_races)["race_total"] == 0


def test_event_set_wait_orders_accesses(armed_races):
    d = lockdep.track({}, "ev")
    ev = threading.Event()
    got = []

    def writer():
        d["k"] = 42
        ev.set()

    def reader():
        assert ev.wait(10)
        got.append(d.get("k"))

    _run_threads(reader, writer)
    assert got == [42]
    assert lockdep.report(armed_races)["race_total"] == 0


def test_thread_start_join_edges_order_accesses(armed_races):
    d = lockdep.track({}, "tj")
    d["a"] = 1  # parent write before start

    def child():
        d["a"] = d["a"] + 1  # ordered by the start edge

    t = threading.Thread(target=child)
    t.start()
    t.join(10)
    assert d["a"] == 2  # parent read after join: ordered by the join edge
    assert lockdep.report(armed_races)["race_total"] == 0


def test_semaphore_release_acquire_orders_accesses(armed_races):
    d = lockdep.track({}, "sem")
    sem = threading.Semaphore(0)
    got = []

    def producer():
        d["p"] = 7
        sem.release()

    def consumer():
        assert sem.acquire(timeout=10)
        got.append(d.get("p"))

    _run_threads(consumer, producer)
    assert got == [7]
    assert lockdep.report(armed_races)["race_total"] == 0


def test_barrier_cycle_orders_accesses(armed_races):
    d = lockdep.track({}, "bar")
    b = threading.Barrier(2, timeout=10)
    got = []

    def phase_writer():
        d["x"] = 9
        b.wait()

    def phase_reader():
        b.wait()
        got.append(d.get("x"))

    _run_threads(phase_reader, phase_writer)
    assert got == [9]
    assert lockdep.report(armed_races)["race_total"] == 0


def test_tracked_object_setattr_write_write_race(armed_races):
    class Slot:
        __slots__ = ("alive", "strikes")

        def __init__(self):
            self.alive = True
            self.strikes = 0

    s = lockdep.track(Slot(), "slot")

    def bump():
        for i in range(100):
            s.strikes = i

    _run_threads(bump, bump)
    rep = lockdep.report(armed_races)
    assert rep["race_total"] > 0
    assert any("strikes" in p["location"] for p in rep["race_pairs"])


def test_track_disarmed_returns_original_object():
    was = lockdep.race_armed()
    lockdep.disable_race_detection()
    try:
        d = {}
        assert lockdep.track(d, "noop") is d
        assert type(d) is dict
    finally:
        if was:
            lockdep.enable_race_detection()


def test_unordered_write_read_is_reported(armed_races):
    d = lockdep.track({}, "wr")
    hold = threading.Event()  # start gate only — orders nothing after

    def writer():
        hold.wait(10)
        for _ in range(50):
            d["k"] = 1
            time.sleep(0)

    def reader():
        hold.wait(10)
        for _ in range(50):
            d.get("k")
            time.sleep(0)

    ts = [threading.Thread(target=writer), threading.Thread(target=reader)]
    for t in ts:
        t.start()
    hold.set()
    for t in ts:
        t.join(20)
    rep = lockdep.report(armed_races)
    assert rep["race_total"] > 0


def test_seeded_race_under_chaos_storm(armed_races):
    """The acceptance shape: a storm of correctly-locked workers plus
    ONE deliberately unguarded writer — the detector must isolate the
    seeded location and stay quiet on the disciplined one."""
    good = lockdep.track({}, "disciplined")
    bad = lockdep.track({}, "seeded_bare")
    mu = threading.Lock()

    def disciplined(n):
        def run():
            for i in range(50):
                with mu:
                    good[f"k{n}"] = i
                    good.get(f"k{(n + 1) % 4}")
        return run

    def rogue():
        for i in range(50):
            bad["x"] = i
            time.sleep(0)

    _run_threads(disciplined(0), disciplined(1), disciplined(2),
                 disciplined(3), rogue, rogue)
    rep = lockdep.report(armed_races)
    assert rep["race_total"] > 0
    assert all("seeded_bare" in p["location"] for p in rep["race_pairs"])


def test_report_shape_and_merge_gate_can_fail(tmp_path, armed_races, capsys):
    d = lockdep.track({}, "gate")

    def w():
        for i in range(100):
            d["x"] = i

    _run_threads(w, w)
    rep = lockdep.report(armed_races)
    assert rep["race_armed"] is True
    assert rep["tracked_objects"] >= 1
    assert rep["race_total"] >= len(rep["race_pairs"]) >= 1
    # the per-process report with races must FAIL the merge gate —
    # proving ci/premerge.sh's race_pairs == [] assertion can trip
    (tmp_path / "lockdep_races.json").write_text(json.dumps(rep))
    out = str(tmp_path / "merged.json")
    rc = lockdep.main(["--merge", str(tmp_path), "--out", out])
    capsys.readouterr()
    assert rc == 1
    merged = json.loads(open(out).read())
    assert merged["race_pairs"] and merged["race_total"] == rep["race_total"]
    # scrubbed of races the same dir gates green
    clean = {k: ([] if k in ("race_pairs",) else v)
             for k, v in rep.items()}
    clean["race_total"] = 0
    (tmp_path / "lockdep_races.json").write_text(json.dumps(clean))
    assert lockdep.main(["--merge", str(tmp_path), "--out", out]) == 0
    capsys.readouterr()


def test_keyed_ewma_concurrent_update_during_eviction_is_race_free(
        armed_races):
    """ISSUE 11 satellite: KeyedEwma's LRU eviction races its updates
    by construction (new keys evict the oldest while other threads
    fold samples) — the internal lock must make that invisible, and
    the tracked-map detector proves it."""
    from spark_rapids_jni_tpu.utils.metrics import KeyedEwma

    e = KeyedEwma(alpha=0.5, max_keys=8)
    e._entries = lockdep.track(e._entries, "ewma_entries")

    def churner(base):
        def run():
            for i in range(200):
                e.update(f"{base}.{i % 16}", float(i))
                e.get(f"{base}.{(i + 3) % 16}")
        return run

    _run_threads(churner("a"), churner("b"), churner("c"))
    assert len(e) <= 8  # the LRU bound held under churn
    assert lockdep.report(armed_races)["race_total"] == 0

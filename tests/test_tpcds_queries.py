"""TPC-DS breadth queries (VERDICT r4 item 8): q7 (4-way star with
FLOAT64 AVG), q19 (5-way star with a cross-dimension inequality), q42 /
q52 (reporting shapes), each against a pandas/Fraction oracle, with
distributed variants asserted BIT-identical to single-chip."""

import math
from fractions import Fraction

import jax
import numpy as np
import pandas as pd
import pytest

from spark_rapids_jni_tpu.models import tpcds


def _f64(col):
    return np.asarray(col.data).view(np.float64)


def _wide_frames(tabs):
    ss = tabs["store_sales"]
    f = {}
    f["ss"] = pd.DataFrame({
        "d": np.asarray(ss.column("ss_sold_date_sk").data),
        "i": np.asarray(ss.column("ss_item_sk").data),
        "cd": np.asarray(ss.column("ss_cdemo_sk").data),
        "pr": np.asarray(ss.column("ss_promo_sk").data),
        "cu": np.asarray(ss.column("ss_customer_sk").data),
        "st": np.asarray(ss.column("ss_store_sk").data),
        "qty": np.asarray(ss.column("ss_quantity").data),
        "list": _f64(ss.column("ss_list_price")),
        "coup": _f64(ss.column("ss_coupon_amt")),
        "sales": _f64(ss.column("ss_sales_price")),
        "ext": _f64(ss.column("ss_ext_sales_price")),
    })
    dd = tabs["date_dim"]
    f["dd"] = pd.DataFrame({
        "d": np.asarray(dd.column("d_date_sk").data),
        "y": np.asarray(dd.column("d_year").data),
        "m": np.asarray(dd.column("d_moy").data),
    })
    it = tabs["item"]
    f["it"] = pd.DataFrame({
        "i": np.asarray(it.column("i_item_sk").data),
        "id": np.asarray(it.column("i_item_id").data),
        "b": np.asarray(it.column("i_brand_id").data),
        "mf": np.asarray(it.column("i_manufact_id").data),
        "mgr": np.asarray(it.column("i_manager_id").data),
    })
    return f


def _exact_fsum_of(vals):
    """Correctly rounded sum of already-rounded f64 values — the outer
    level of the two-level rounding the query performs."""
    return math.fsum(vals)


def _exact_mean(values) -> float:
    """Correctly rounded f64 of (exact sum / count) — the accumulator's
    contract; a float mean would double-round."""
    vals = list(values)
    return float(sum(Fraction(v) for v in vals) / len(vals))


class TestQ7:
    def test_matches_exact_oracle(self):
        tabs = tpcds.gen_store_wide(20_000, seed=5)
        out = tpcds.q7(tabs)

        f = _wide_frames(tabs)
        cd = tabs["customer_demographics"]
        cdf = pd.DataFrame({
            "cd": np.asarray(cd.column("cd_demo_sk").data),
            "g": np.asarray(cd.column("cd_gender").data),
            "ms": np.asarray(cd.column("cd_marital_status").data),
            "ed": np.asarray(cd.column("cd_education_status").data),
        })
        pr = tabs["promotion"]
        prf = pd.DataFrame({
            "pr": np.asarray(pr.column("p_promo_sk").data),
            "em": np.asarray(pr.column("p_channel_email").data),
            "ev": np.asarray(pr.column("p_channel_event").data),
        })
        j = (
            f["ss"]
            .merge(f["dd"][f["dd"].y == 2000], on="d")
            .merge(cdf[(cdf.g == 1) & (cdf.ms == 2) & (cdf.ed == 3)], on="cd")
            .merge(prf[(prf.em == 0) | (prf.ev == 0)], on="pr")
            .merge(f["it"][["i", "id"]], on="i")
        )
        want = j.groupby("id")
        ids = sorted(want.groups)
        assert np.asarray(out.column("i_item_id").data).tolist() == ids
        for name, src in (("agg1", "qty"), ("agg2", "list"), ("agg3", "coup"), ("agg4", "sales")):
            got = _f64(out.column(name))
            exp = [_exact_mean(want.get_group(g)[src].tolist()) for g in ids]
            np.testing.assert_array_equal(got, np.array(exp))

    def test_distributed_bit_identical(self):
        mesh = jax.sharding.Mesh(np.array(jax.devices()).reshape(-1), ("data",))
        tabs = tpcds.gen_store_wide(12_000, seed=6)
        single = tpcds.q7(tabs)
        dist = tpcds.q7_distributed(tabs, mesh)
        assert np.asarray(single.column("i_item_id").data).tolist() == \
            np.asarray(dist.column("i_item_id").data).tolist()
        for name in ("agg1", "agg2", "agg3", "agg4"):
            np.testing.assert_array_equal(
                np.asarray(single.column(name).data), np.asarray(dist.column(name).data)
            )


class TestQ19:
    def _oracle(self, tabs, manager_id=8, month=11, year=1998):
        f = _wide_frames(tabs)
        cu = tabs["customer"]
        cuf = pd.DataFrame({
            "cu": np.asarray(cu.column("c_customer_sk").data),
            "addr": np.asarray(cu.column("c_current_addr_sk").data),
        })
        ca = tabs["customer_address"]
        caf = pd.DataFrame({
            "addr": np.asarray(ca.column("ca_address_sk").data),
            "cz": np.asarray(ca.column("ca_zip5").data),
        })
        st = tabs["store"]
        stf = pd.DataFrame({
            "st": np.asarray(st.column("s_store_sk").data),
            "sz": np.asarray(st.column("s_zip5").data),
        })
        j = (
            f["ss"]
            .merge(f["dd"][(f["dd"].m == month) & (f["dd"].y == year)], on="d")
            .merge(f["it"][f["it"].mgr == manager_id][["i", "b", "mf"]], on="i")
            .merge(cuf, on="cu")
            .merge(caf, on="addr")
            .merge(stf, on="st")
        )
        j = j[j.cz != j.sz]
        g = j.groupby(["b", "mf"])
        rows = []
        for (b, mf), grp in g:
            rows.append((b, mf, math.fsum(grp.ext.tolist())))
        rows.sort(key=lambda r: (-r[2], r[0], r[1]))
        return rows

    def test_matches_exact_oracle(self):
        tabs = tpcds.gen_store_wide(20_000, seed=7)
        out = tpcds.q19(tabs)
        want = self._oracle(tabs)
        got = list(
            zip(
                np.asarray(out.column("i_brand_id").data).tolist(),
                np.asarray(out.column("i_manufact_id").data).tolist(),
                _f64(out.column("ext_price")).tolist(),
            )
        )
        assert [r[:2] for r in got] == [r[:2] for r in want]
        # fsum == windowed accumulator: both are the correctly rounded sum
        np.testing.assert_array_equal(
            np.array([r[2] for r in got]), np.array([r[2] for r in want])
        )

    def test_distributed_bit_identical(self):
        mesh = jax.sharding.Mesh(np.array(jax.devices()).reshape(-1), ("data",))
        tabs = tpcds.gen_store_wide(12_000, seed=8)
        single = tpcds.q19(tabs)
        dist = tpcds.q19_distributed(tabs, mesh)
        for name in ("i_brand_id", "i_manufact_id", "ext_price"):
            np.testing.assert_array_equal(
                np.asarray(single.column(name).data), np.asarray(dist.column(name).data)
            )


class TestReportingShapes:
    def _store_frames(self, tabs):
        ss = tabs["store_sales"]
        it = tabs["item"]
        dd = tabs["date_dim"]
        return (
            pd.DataFrame({
                "d": np.asarray(ss.column("ss_sold_date_sk").data),
                "i": np.asarray(ss.column("ss_item_sk").data),
                "p": _f64(ss.column("ss_ext_sales_price")),
            }),
            pd.DataFrame({
                "d": np.asarray(dd.column("d_date_sk").data),
                "y": np.asarray(dd.column("d_year").data),
                "m": np.asarray(dd.column("d_moy").data),
            }),
            pd.DataFrame({
                "i": np.asarray(it.column("i_item_sk").data),
                "b": np.asarray(it.column("i_brand_id").data),
                "mgr": np.asarray(it.column("i_manager_id").data),
                "cat": np.asarray(it.column("i_category_id").data),
            }),
        )

    def test_q42_matches_exact_oracle(self):
        tabs = tpcds.gen_store(30_000, seed=9)
        out = tpcds.q42(tabs, manager_id=1, month=11, year=2000)
        ssf, ddf, itf = self._store_frames(tabs)
        j = ssf.merge(ddf[(ddf.m == 11) & (ddf.y == 2000)], on="d").merge(
            itf[itf.mgr == 1][["i", "cat"]], on="i"
        )
        rows = [
            (cat, math.fsum(grp.p.tolist())) for cat, grp in j.groupby("cat")
        ]
        rows.sort(key=lambda r: (-r[1], r[0]))
        assert np.asarray(out.column("i_category_id").data).tolist() == [r[0] for r in rows]
        np.testing.assert_array_equal(
            _f64(out.column("ext_price")), np.array([r[1] for r in rows])
        )
        assert (np.asarray(out.column("d_year").data) == 2000).all()

    def test_q52_matches_exact_oracle(self):
        tabs = tpcds.gen_store(30_000, seed=10)
        out = tpcds.q52(tabs, manager_id=1, month=11, year=2000)
        ssf, ddf, itf = self._store_frames(tabs)
        j = ssf.merge(ddf[(ddf.m == 11) & (ddf.y == 2000)], on="d").merge(
            itf[itf.mgr == 1][["i", "b"]], on="i"
        )
        rows = [(b, math.fsum(grp.p.tolist())) for b, grp in j.groupby("b")]
        rows.sort(key=lambda r: (-r[1], r[0]))
        assert np.asarray(out.column("i_brand_id").data).tolist() == [r[0] for r in rows]
        np.testing.assert_array_equal(
            _f64(out.column("ext_price")), np.array([r[1] for r in rows])
        )

    def test_q52_distributed_bit_identical(self):
        mesh = jax.sharding.Mesh(np.array(jax.devices()).reshape(-1), ("data",))
        tabs = tpcds.gen_store(15_000, seed=11)
        single = tpcds.q52(tabs, manager_id=1, month=11, year=2000)
        dist = tpcds.q52_distributed(tabs, mesh, manager_id=1, month=11, year=2000)
        for name in ("d_year", "i_brand_id", "ext_price"):
            np.testing.assert_array_equal(
                np.asarray(single.column(name).data), np.asarray(dist.column(name).data)
            )


class TestQ94:
    def _oracle(self, tabs, lo=400, hi=460):
        ws = tabs["web_sales"]
        df = pd.DataFrame({
            "o": np.asarray(ws.column("ws_order_number").data),
            "w": np.asarray(ws.column("ws_warehouse_sk").data),
            "d": np.asarray(ws.column("ws_ship_date_sk").data),
            "c": np.asarray(ws.column("ws_ext_ship_cost").data).view(np.float64),
            "p": np.asarray(ws.column("ws_net_profit").data).view(np.float64),
        })
        wh = df.groupby("o")["w"].nunique()
        multi = set(wh[wh > 1].index)
        returned = set(np.asarray(tabs["web_returns"].column("wr_order_number").data).tolist())
        sel = df[(df.d >= lo) & (df.d <= hi) & df.o.isin(multi) & ~df.o.isin(returned)]
        # mirror q94's TWO-LEVEL rounding exactly: correctly rounded
        # per-order sums, then the exact total of those rounded sums —
        # a flat fsum would differ by accumulated per-group rounding
        per_order_c = [math.fsum(g.tolist()) for _, g in sel.groupby("o")["c"]]
        per_order_p = [math.fsum(g.tolist()) for _, g in sel.groupby("o")["p"]]
        return {
            "order_count": sel.o.nunique(),
            "total_shipping_cost": _exact_fsum_of(per_order_c),
            "total_net_profit": _exact_fsum_of(per_order_p),
        }

    def test_matches_exact_oracle(self):
        tabs = tpcds.gen_web(30_000, seed=13)
        got = tpcds.q94(tabs)
        want = self._oracle(tabs)
        assert got["order_count"] == want["order_count"]
        assert got["total_shipping_cost"] == want["total_shipping_cost"]
        assert got["total_net_profit"] == want["total_net_profit"]

    def test_distributed_identical(self):
        mesh = jax.sharding.Mesh(np.array(jax.devices()).reshape(-1), ("data",))
        tabs = tpcds.gen_web(12_000, seed=14)
        single = tpcds.q94(tabs)
        dist = tpcds.q94_distributed(tabs, mesh)
        assert single == dist


class TestQ98WindowRatio:
    def test_matches_oracle(self):
        tabs = tpcds.gen_store(30_000, seed=15)
        out = tpcds.q98(tabs, month=11, year=2000)
        ss = tabs["store_sales"]; it = tabs["item"]; dd = tabs["date_dim"]
        df = pd.DataFrame({
            "d": np.asarray(ss.column("ss_sold_date_sk").data),
            "i": np.asarray(ss.column("ss_item_sk").data),
            "p": _f64(ss.column("ss_ext_sales_price")),
        }).merge(pd.DataFrame({
            "d": np.asarray(dd.column("d_date_sk").data),
            "y": np.asarray(dd.column("d_year").data),
            "m": np.asarray(dd.column("d_moy").data),
        }), on="d").merge(pd.DataFrame({
            "i": np.asarray(it.column("i_item_sk").data),
            "cat": np.asarray(it.column("i_category_id").data),
            "b": np.asarray(it.column("i_brand_id").data),
        }), on="i")
        df = df[(df.m == 11) & (df.y == 2000)]
        rev = {}
        for (cat, b), grp in df.groupby(["cat", "b"]):
            rev[(cat, b)] = math.fsum(grp.p.tolist())
        cat_tot = {
            c: math.fsum(v for (cc, _), v in rev.items() if cc == c)
            for c in {c for c, _ in rev}
        }
        rows = [
            (cat, b, v, v * 100.0 / cat_tot[cat]) for (cat, b), v in rev.items()
        ]
        rows.sort(key=lambda r: (r[0], r[3], r[1]))
        got_cat = np.asarray(out.column("i_category_id").data).tolist()
        got_b = np.asarray(out.column("i_brand_id").data).tolist()
        got_rev = _f64(out.column("itemrevenue"))
        got_ratio = _f64(out.column("revenueratio"))
        assert got_cat == [r[0] for r in rows]
        assert got_b == [r[1] for r in rows]
        # itemrevenue is EXACT (windowed accumulator == fsum)
        np.testing.assert_array_equal(got_rev, np.array([r[2] for r in rows]))
        # the ratio divides two correctly rounded values; dd division
        # carries ~2^-48 relative error on the f64-less tier
        np.testing.assert_allclose(got_ratio, np.array([r[3] for r in rows]), rtol=1e-12)

"""srjt-cluster tier (ISSUE 16): N-rank membership, liveness, and
epoch-fenced recovery for the distributed data plane.

Covers the ClusterView state machine (ALIVE -> SUSPECT -> DEAD, the
miss ladder, wire generation adoption, quorum), the exchange's
generation fence (stale rejects on both sides, heal-on-resync), the
reset-mid-frame UNAVAILABLE classification, netsplit `@r<N>` rank
keying, per-peer breaker isolation, lineage recovery (failover_fetch /
recover_partition / recompute_dead_partition), the N-rank exchange
topologies (tree == all_to_all bit-identity, cluster pins all_to_all),
the plan compiler's Exchange stage, the scheduler's quorum-loss shed,
and the 4-process chaos acceptance: a rank kill -9'd mid-query under
ci/chaos_cluster.json with the distributed groupby still bit-identical
to the single-host oracle (heavy tests ride the slow tier;
ci/premerge.sh runs this file env-armed in the dedicated cluster
tier)."""

import os
import socket
import threading
import time

import numpy as np
import pytest

import spark_rapids_jni_tpu  # noqa: F401
import jax.numpy as jnp
from spark_rapids_jni_tpu import plan as P
from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.columnar import dtype as dt
from spark_rapids_jni_tpu.ops.copying import concatenate, slice_table
from spark_rapids_jni_tpu.parallel import shuffle
from spark_rapids_jni_tpu.parallel.cluster import (
    ALIVE,
    DEAD,
    SUSPECT,
    ClusterView,
)
from spark_rapids_jni_tpu.plan import nodes as pn
from spark_rapids_jni_tpu.utils import (
    deadline as deadline_mod,
    faultinj,
    metrics,
    retry,
)
from spark_rapids_jni_tpu.utils.errors import (
    DataCorruption,
    FatalDeviceError,
    Overloaded,
    RetryableError,
)


def _counter(name):
    return metrics.registry().value(name)


@pytest.fixture(autouse=True)
def _clean_state():
    faultinj.disable()
    retry.disable()
    retry.reset_stats()
    yield
    faultinj.disable()
    retry.disable()
    retry.reset_stats()
    shuffle.exchange_breaker().reset()


def _probe_err():
    return RetryableError("probe: connection refused")


# ---------------------------------------------------------------------------
# membership + liveness (the ClusterView state machine)
# ---------------------------------------------------------------------------


class TestMembership:
    def test_ctor_validation(self):
        ex = shuffle.TcpExchange(0)
        try:
            with pytest.raises(ValueError, match="must include this rank"):
                ClusterView(7, {0: ex.address, 1: "127.0.0.1:9"}, ex)
            with pytest.raises(ValueError, match="DEAD_MISSES"):
                ClusterView(0, {0: ex.address, 1: "127.0.0.1:9"}, ex,
                            suspect_misses=4, dead_misses=2)
        finally:
            ex.close()

    def test_miss_ladder_and_generation_fencing(self):
        ex = shuffle.TcpExchange(0)
        view = ClusterView(0, {0: ex.address, 1: "127.0.0.1:9"}, ex,
                           suspect_misses=2, dead_misses=4)
        deaths0 = _counter("cluster.deaths")
        trans0 = _counter("cluster.transitions")
        try:
            # construction installs generation 1 into the exchange
            assert view.generation() == 1 and ex.generation() == 1
            assert view.state(1) == ALIVE and view.state(0) == ALIVE
            view._record_miss(1, _probe_err())
            assert view.state(1) == ALIVE  # one miss is not suspicion
            view._record_miss(1, _probe_err())
            assert view.state(1) == SUSPECT
            view._record_miss(1, _probe_err())
            assert view.state(1) == SUSPECT  # dead needs the full ladder
            view._record_miss(1, _probe_err())
            assert view.state(1) == DEAD
            # death is a membership event: generation = 1 + deaths,
            # installed into the exchange fence immediately
            assert view.generation() == 2 and ex.generation() == 2
            assert view.dead_ranks() == [1]
            assert view.alive_ranks() == [0]
            assert not view.has_quorum()  # 1 alive of 2 fails > 0.5
            assert _counter("cluster.deaths") == deaths0 + 1
            assert _counter("cluster.transitions") == trans0 + 2
        finally:
            ex.close()

    def test_suspect_heals_to_alive_on_hit(self):
        ex = shuffle.TcpExchange(0)
        view = ClusterView(0, {0: ex.address, 1: "127.0.0.1:9"}, ex,
                           suspect_misses=2, dead_misses=4)
        try:
            view._record_miss(1, _probe_err())
            view._record_miss(1, _probe_err())
            assert view.state(1) == SUSPECT
            view._record_hit(1, peer_gen=1)
            assert view.state(1) == ALIVE
            # the miss count reset with the hit: one new miss is benign
            view._record_miss(1, _probe_err())
            assert view.state(1) == ALIVE
            assert view.generation() == 1
        finally:
            ex.close()

    def test_wire_generation_adoption(self):
        # a peer that already observed a death answers pings with a
        # higher generation; adopting it keeps our publishes servable
        ex = shuffle.TcpExchange(0)
        view = ClusterView(0, {0: ex.address, 1: "127.0.0.1:9"}, ex)
        try:
            view._record_hit(1, peer_gen=5)
            assert view.generation() == 5 and ex.generation() == 5
            view._record_hit(1, peer_gen=3)  # never adopt backwards
            assert view.generation() == 5
        finally:
            ex.close()

    def test_mark_dead_idempotent_and_await_dead(self):
        ex = shuffle.TcpExchange(0)
        view = ClusterView(0, {0: ex.address, 1: "127.0.0.1:9"}, ex)
        deaths0 = _counter("cluster.deaths")
        try:
            assert not view.await_dead(1, 0.05)  # alive: deadline passes
            t = threading.Timer(0.2, view.mark_dead, args=(1,))
            t.start()
            assert view.await_dead(1, 10.0)  # woken by the transition
            assert view.await_dead(1, 0.0)  # already dead: immediate
            view.mark_dead(1)  # idempotent: DEAD is terminal
            assert _counter("cluster.deaths") == deaths0 + 1
            assert view.generation() == 2
        finally:
            ex.close()

    def test_quorum_fraction(self):
        ex = shuffle.TcpExchange(0)
        addrs = {0: ex.address, 1: "127.0.0.1:9", 2: "127.0.0.1:9",
                 3: "127.0.0.1:9"}
        view = ClusterView(0, addrs, ex, quorum_fraction=0.5)
        try:
            assert view.has_quorum()
            view.mark_dead(1)
            assert view.has_quorum()  # 3 > 2
            view.mark_dead(2)
            assert not view.has_quorum()  # 2 > 2 is false
            # generation is a function of membership: 1 + deaths known
            assert view.generation() == 3
        finally:
            ex.close()

    def test_heartbeat_detects_death_and_views_converge(self):
        # two live observers, one peer killed: both detectors must walk
        # it ALIVE -> SUSPECT -> DEAD independently and land on the
        # SAME generation (generation is a function of membership, not
        # a per-observer counter)
        ex0, ex1, ex2 = (shuffle.TcpExchange(r) for r in range(3))
        addrs = {0: ex0.address, 1: ex1.address, 2: ex2.address}
        kw = dict(heartbeat_s=0.05, heartbeat_timeout_s=0.25,
                  suspect_misses=1, dead_misses=2)
        view0 = ClusterView(0, addrs, ex0, **kw)
        view1 = ClusterView(1, addrs, ex1, **kw)
        try:
            view0.start()
            view1.start()
            ex2.close()  # kill the peer: connects now refused
            t_end = time.monotonic() + 15.0
            while time.monotonic() < t_end:
                if view0.state(2) == DEAD and view1.state(2) == DEAD:
                    break
                time.sleep(0.02)
            assert view0.state(2) == DEAD, "view0 never declared death"
            assert view1.state(2) == DEAD, "view1 never declared death"
            assert view0.generation() == view1.generation() == 2
            assert ex0.generation() == ex1.generation() == 2
            # the live pair kept each other ALIVE throughout
            assert view0.state(1) == ALIVE and view1.state(0) == ALIVE
            assert view0.snapshot()["states"] == {1: ALIVE, 2: DEAD}
        finally:
            view0.stop()
            view1.stop()
            for ex in (ex0, ex1, ex2):
                ex.close()


# ---------------------------------------------------------------------------
# the epoch fence + wire failure classification
# ---------------------------------------------------------------------------


def _small_table(n=64):
    return Table(
        [Column(dt.INT64, data=jnp.arange(n, dtype=jnp.int64))], ["x"]
    )


class TestFencing:
    def test_ping_returns_generation(self):
        ex0, ex1 = shuffle.TcpExchange(0), shuffle.TcpExchange(1)
        try:
            assert ex0.ping(ex1.address, 2.0) == 0  # unfenced peer
            ex1.set_generation(7)
            assert ex0.ping(ex1.address, 2.0) == 7
            ex1.close()
            # a connect racing the close can still land in the kernel
            # backlog and be served; the refusal is eventual
            for _ in range(50):
                try:
                    ex0.ping(ex1.address, 0.5)
                except (RetryableError, OSError):
                    break
                time.sleep(0.05)
            else:
                pytest.fail("ping never failed after the peer closed")
        finally:
            ex0.close()
            ex1.close()

    def test_stale_generation_rejected_both_sides_then_heals(self):
        ex0, ex1 = shuffle.TcpExchange(0), shuffle.TcpExchange(1)
        try:
            ex1.publish(3, {0: _small_table()})
            ex1.set_generation(2)
            ex0.set_generation(1)
            refused0 = _counter("cluster.stale_generation_refused")
            rejects0 = _counter("cluster.stale_generation_rejects")
            with pytest.raises(RetryableError, match="DESYNC"):
                ex0._fetch_once(ex1.address, 3, 0)
            # the server refused undecoded, the client counted a desync
            assert _counter("cluster.stale_generation_refused") == refused0 + 1
            assert _counter("cluster.stale_generation_rejects") == rejects0 + 1
            # resync heals: same fetch, bumped fence
            ex0.set_generation(2)
            out = ex0._fetch_once(ex1.address, 3, 0)
            assert np.array_equal(
                np.asarray(out.columns[0].data), np.arange(64)
            )
            # an unfenced client never engages the fence (plain GET)
            ex0.set_generation(None)
            out = ex0._fetch_once(ex1.address, 3, 0)
            assert out.num_rows == 64
        finally:
            ex0.close()
            ex1.close()

    def test_reset_mid_frame_is_unavailable_not_corruption(self):
        # a peer that dies between the response header and the payload:
        # the header promised bytes that never arrive. No frame was
        # accepted, so nothing exists for a CRC to vouch for — the
        # fetch must classify UNAVAILABLE (the recovery path's signal),
        # never DataCorruption (ISSUE 16 satellite regression).
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        addr = f"127.0.0.1:{srv.getsockname()[1]}"

        def serve_half_frame():
            conn, _ = srv.accept()
            conn.recv(shuffle._EXC_REQ.size)
            # a valid OK header promising 4096 payload bytes, then die
            conn.sendall(shuffle._EXC_RESP.pack(shuffle._EXC_OK, 4096))
            conn.close()

        t = threading.Thread(target=serve_half_frame, daemon=True)
        t.start()
        ex0 = shuffle.TcpExchange(0)
        try:
            with pytest.raises(RetryableError) as ei:
                ex0._fetch_once(addr, 0, 0)
            assert not isinstance(ei.value, DataCorruption)
            msg = str(ei.value)
            assert "UNAVAILABLE" in msg and "reset" in msg
            assert "payload" in msg  # the phase the peer died in
        finally:
            ex0.close()
            srv.close()
            t.join(5)

    def test_netsplit_rank_tag_scopes_to_tagged_rank(self, monkeypatch):
        cfg = {"faults": {"exchange.connect@r1": {
            "type": "netsplit", "percent": 100}}}
        # this process is rank 1: the partition rule fires at the
        # connect choke as the REAL refused-connect OSError subclass
        monkeypatch.setenv("SRJT_FAULTINJ_RANK", "r1")
        faultinj.configure(cfg)
        with pytest.raises(ConnectionRefusedError):
            faultinj.maybe_inject("exchange.connect")
        # ... which the fetch path classifies retryable-UNAVAILABLE
        ex0, ex1 = shuffle.TcpExchange(0), shuffle.TcpExchange(1)
        try:
            ex1.publish(0, {0: _small_table()})
            with pytest.raises(RetryableError, match="UNAVAILABLE"):
                ex0._fetch_once(ex1.address, 0, 0)
            # a foreign tag never matches: rank 2 runs the same
            # profile clean and the fetch flows
            monkeypatch.setenv("SRJT_FAULTINJ_RANK", "r2")
            faultinj.configure(cfg)
            faultinj.maybe_inject("exchange.connect")  # no raise
            out = ex0._fetch_once(ex1.address, 0, 0)
            assert out.num_rows == 64
        finally:
            ex0.close()
            ex1.close()

    def test_per_peer_breaker_isolation(self):
        # one dead peer's open breaker must not fail fetches from the
        # live peers — breakers are per-address, the facade fans out
        dead_addr = "127.0.0.1:9"
        br = shuffle.exchange_breaker(dead_addr)
        br.configure(threshold=1, cooldown_s=60.0)
        br.record_failure(cause="unavailable")
        assert not br.allow()
        ex0, ex1 = shuffle.TcpExchange(0), shuffle.TcpExchange(1)
        try:
            with pytest.raises(RetryableError, match="breaker open"):
                ex0.fetch(dead_addr, 0, 0)
            ex1.publish(0, {0: _small_table()})
            out = ex0.fetch(ex1.address, 0, 0)  # live peer unaffected
            assert out.num_rows == 64
            snap = shuffle.exchange_breaker().snapshot()
            assert len(snap) >= 2  # one machine per peer address
            assert shuffle.exchange_breaker(dead_addr) is br  # stable
        finally:
            ex0.close()
            ex1.close()


# ---------------------------------------------------------------------------
# lineage recovery
# ---------------------------------------------------------------------------


def _shard_of(full, rows, world, r):
    lo, hi = shuffle._shard_bounds(rows, world, r)
    return slice_table(full, lo, hi)


def _expected_partition(src, world, dest):
    partitioned, offsets = shuffle.hash_partition(src, world, ["k"])
    bounds = list(offsets) + [partitioned.num_rows]
    return slice_table(partitioned, bounds[dest], bounds[dest + 1])


def _assert_tables_equal(got, want, names=("k", "v")):
    assert got.num_rows == want.num_rows
    for name in names:
        assert np.array_equal(
            np.asarray(got.column(name).data),
            np.asarray(want.column(name).data),
        ), name


class TestRecovery:
    ROWS = 900
    SEED = 3

    def _view3(self, ex, **kw):
        full = shuffle._demo_table(self.ROWS, seed=self.SEED)
        addrs = {0: ex.address, 1: "127.0.0.1:9", 2: "127.0.0.1:9"}
        kw.setdefault("heartbeat_s", 0.02)
        kw.setdefault("heartbeat_timeout_s", 0.05)
        kw.setdefault("suspect_misses", 1)
        kw.setdefault("dead_misses", 1)
        view = ClusterView(
            0, addrs, ex,
            lineage=lambda r: _shard_of(full, self.ROWS, 3, r), **kw
        )
        return full, view

    def test_failover_requires_confirmed_death_and_lineage(self):
        ex = shuffle.TcpExchange(0)
        try:
            full, view = self._view3(ex)
            # not dead within the grace: the pull keeps its own error
            assert view.failover_fetch(1, 0, ["k"], 3, 0) is None
            view.mark_dead(1)
            no_lineage = ClusterView(
                0, {0: ex.address, 1: "127.0.0.1:9"}, ex,
                heartbeat_s=0.02, heartbeat_timeout_s=0.05,
                suspect_misses=1, dead_misses=1,
            )
            no_lineage.mark_dead(1)
            assert no_lineage.failover_fetch(1, 0, ["k"], 2, 0) is None
            with pytest.raises(FatalDeviceError, match="no lineage"):
                no_lineage.recover_partition(1, 0, ["k"], 2, 0)
            # confirmed dead + lineage: the recomputed partition flows
            got = view.failover_fetch(1, 0, ["k"], 3, 0)
            want = _expected_partition(
                _shard_of(full, self.ROWS, 3, 1), 3, 0)
            _assert_tables_equal(got, want)
        finally:
            ex.close()

    def test_recover_partition_republishes_idempotently(self):
        ex = shuffle.TcpExchange(0)
        try:
            full, view = self._view3(ex)
            view.mark_dead(1)
            recov0 = _counter("cluster.recoveries")
            got = view.recover_partition(1, 0, ["k"], 3, 2)
            want = _expected_partition(
                _shard_of(full, self.ROWS, 3, 1), 3, 2)
            _assert_tables_equal(got, want)
            assert _counter("cluster.recoveries") == recov0 + 1
            # the dead rank's outgoing partitions are republished under
            # the derived recovery epoch so ANY survivor can fetch them
            recovery_epoch = 2 * shuffle._RECOVERY_EPOCH_STRIDE
            with ex._published:
                assert (recovery_epoch, 0) in ex._frames
                assert (recovery_epoch, 2) in ex._frames
                assert (recovery_epoch, 1) not in ex._frames
            # idempotent per (dead_rank, epoch): later callers reuse it
            again = view.recover_partition(1, 0, ["k"], 3, 2)
            _assert_tables_equal(again, want)
            assert _counter("cluster.recoveries") == recov0 + 1
        finally:
            ex.close()

    def test_recompute_dead_partition_matches_direct(self):
        # the destination-side hole: the partition headed TO the dead
        # rank, rebuilt from every rank's lineage, must equal the same
        # partition computed directly over the whole input
        ex = shuffle.TcpExchange(0)
        try:
            full, view = self._view3(ex)
            view.mark_dead(1)
            got = view.recompute_dead_partition(1, ["k"], 3)
            want = _expected_partition(full, 3, 1)
            _assert_tables_equal(got, want)
        finally:
            ex.close()

    def test_exchange_failover_bit_identical_in_process(self):
        # world 3 with rank 1 dead from the start: both survivors'
        # pulls from it exhaust retries, rendezvous with the heartbeat
        # detector, and fail over to the lineage-recomputed copy — the
        # three-way groupby (survivors + the coordinator-recomputed
        # dead partition) must equal the single-host oracle exactly
        rows, seed, world = 1200, 5, 3
        full = shuffle._demo_table(rows, seed=seed)
        ref = shuffle._local_groupby_sum(full)
        ex0, ex2 = shuffle.TcpExchange(0), shuffle.TcpExchange(2)
        addrs = {0: ex0.address, 1: "127.0.0.1:9", 2: ex2.address}
        kw = dict(
            lineage=lambda r: _shard_of(full, rows, world, r),
            heartbeat_s=0.05, heartbeat_timeout_s=0.2,
            suspect_misses=1, dead_misses=2,
        )
        view0 = ClusterView(0, addrs, ex0, **kw)
        view2 = ClusterView(2, addrs, ex2, **kw)
        recov0 = _counter("cluster.recoveries")
        res, errs = {}, []

        def run_rank(rank, ex, view):
            try:
                peers = {r: a for r, a in addrs.items() if r != rank}
                with retry.enabled(max_attempts=20, base_delay_ms=5,
                                   max_delay_ms=50):
                    local = ex.exchange_table(
                        _shard_of(full, rows, world, rank), ["k"], peers,
                        epoch=0, cluster=view,
                    )
                res[rank] = shuffle._local_groupby_sum(local)
            except BaseException as e:  # noqa: BLE001 - surfaced below
                errs.append(e)

        try:
            view0.start()
            view2.start()
            threads = [
                threading.Thread(target=run_rank, args=(0, ex0, view0)),
                threading.Thread(target=run_rank, args=(2, ex2, view2)),
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(120)
            assert not errs, errs
            assert set(res) == {0, 2}
            # the coordinator rebuilds the dead rank's share of the
            # answer from lineage — no network, pure replay
            res[1] = shuffle._local_groupby_sum(
                view0.recompute_dead_partition(1, ["k"], world))
            got = concatenate([res[0], res[1], res[2]])
            order = np.argsort(np.asarray(got.column("k").data))
            for name in ("k", "s", "c"):
                assert np.array_equal(
                    np.asarray(got.column(name).data)[order],
                    np.asarray(ref.column(name).data),
                ), f"{name} diverged from the single-host oracle"
            # both views observed the death, agreed on the generation,
            # and at least one recovery republish happened
            assert view0.dead_ranks() == [1]
            assert view2.dead_ranks() == [1]
            assert view0.generation() == view2.generation() == 2
            assert _counter("cluster.recoveries") >= recov0 + 1
        finally:
            view0.stop()
            view2.stop()
            ex0.close()
            ex2.close()


# ---------------------------------------------------------------------------
# N-rank exchange topologies
# ---------------------------------------------------------------------------


class TestTopology:
    def test_topology_validation(self, monkeypatch):
        ex = shuffle.TcpExchange(0)
        t = shuffle._demo_table(64, seed=1)
        try:
            with pytest.raises(ValueError, match="must cover ranks"):
                ex.exchange_table(t, ["k"], {5: "127.0.0.1:9"})
            with pytest.raises(ValueError, match="power-of-two"):
                ex.exchange_table(
                    t, ["k"], {1: "x", 2: "y"}, topology="tree")
            with pytest.raises(ValueError, match="unknown exchange topology"):
                ex.exchange_table(t, ["k"], {1: "x"}, topology="ring")
            # topology=None reads the SRJT_CLUSTER_TOPOLOGY knob per
            # call: pinning "tree" at a non-power-of-two world hits the
            # tree plan's own validation (the knob layer itself rejects
            # unknown values with a warning and falls back to auto)
            monkeypatch.setenv("SRJT_CLUSTER_TOPOLOGY", "tree")
            with pytest.raises(ValueError, match="power-of-two"):
                ex.exchange_table(t, ["k"], {1: "x", 2: "y"})
        finally:
            ex.close()

    def test_cluster_pins_all_to_all_over_tree(self):
        # recovery needs single-hop lineage (a tree round forwards
        # OTHER ranks' rows), so an attached cluster pins the direct
        # plan even when tree is requested: frames land under the real
        # epoch, never the tree's derived sub-epoch namespace
        rows, seed = 400, 9
        full = shuffle._demo_table(rows, seed=seed)
        ref = shuffle._local_groupby_sum(full)
        ex0, ex1 = shuffle.TcpExchange(0), shuffle.TcpExchange(1)
        addrs = {0: ex0.address, 1: ex1.address}
        view0 = ClusterView(0, addrs, ex0)
        view1 = ClusterView(1, addrs, ex1)
        res, errs = {}, []

        def run_rank(rank, ex, view):
            try:
                peers = {r: a for r, a in addrs.items() if r != rank}
                with retry.enabled(max_attempts=20, base_delay_ms=5):
                    local = ex.exchange_table(
                        _shard_of(full, rows, 2, rank), ["k"], peers,
                        epoch=0, topology="tree", cluster=view,
                    )
                res[rank] = shuffle._local_groupby_sum(local)
            except BaseException as e:  # noqa: BLE001 - surfaced below
                errs.append(e)

        try:
            threads = [
                threading.Thread(target=run_rank, args=(0, ex0, view0)),
                threading.Thread(target=run_rank, args=(1, ex1, view1)),
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(60)
            assert not errs, errs
            with ex0._published:
                epochs = sorted({e for e, _ in ex0._frames})
            assert 0 in epochs, "all_to_all publish missing"
            assert all(e < shuffle._TREE_EPOCH_STRIDE for e in epochs), \
                "tree sub-epoch frames found despite an attached cluster"
            got = concatenate([res[0], res[1]])
            order = np.argsort(np.asarray(got.column("k").data))
            for name in ("k", "s", "c"):
                assert np.array_equal(
                    np.asarray(got.column(name).data)[order],
                    np.asarray(ref.column(name).data),
                ), name
        finally:
            ex0.close()
            ex1.close()

    def test_tree_equals_all_to_all_world4(self):
        # the two exchange plans move rows differently but must
        # aggregate identically: world-4 in-process fabric, one round
        # per plan (auto topology picks tree at a power-of-two world,
        # proven by its derived sub-epoch frames)
        rows, seed, world = 1600, 21, 4
        full = shuffle._demo_table(rows, seed=seed)
        ref = shuffle._local_groupby_sum(full)
        exs = [shuffle.TcpExchange(r) for r in range(world)]
        addrs = {r: exs[r].address for r in range(world)}

        def run_round(epoch, topology, out):
            errs = []

            def run_rank(rank):
                try:
                    peers = {r: a for r, a in addrs.items() if r != rank}
                    with retry.enabled(max_attempts=40, base_delay_ms=5,
                                       max_delay_ms=50):
                        local = exs[rank].exchange_table(
                            _shard_of(full, rows, world, rank), ["k"],
                            peers, epoch=epoch, topology=topology,
                        )
                    out[rank] = shuffle._local_groupby_sum(local)
                except BaseException as e:  # noqa: BLE001 - surfaced below
                    errs.append(e)

            threads = [threading.Thread(target=run_rank, args=(r,))
                       for r in range(world)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(180)
            assert not errs, errs
            assert set(out) == set(range(world))

        def check(out):
            got = concatenate([out[r] for r in range(world)])
            order = np.argsort(np.asarray(got.column("k").data))
            for name in ("k", "s", "c"):
                assert np.array_equal(
                    np.asarray(got.column(name).data)[order],
                    np.asarray(ref.column(name).data),
                ), name

        try:
            direct, tree = {}, {}
            run_round(0, "all_to_all", direct)
            check(direct)
            run_round(2, None, tree)  # auto: tree at world 4, no cluster
            check(tree)
            # the auto round really took the hypercube plan: its
            # coalesced frames live in the derived sub-epoch namespace
            with exs[0]._published:
                epochs = {e for e, _ in exs[0]._frames}
            assert any(e >= shuffle._TREE_EPOCH_STRIDE for e in epochs), \
                "auto topology never engaged the tree plan at world 4"
        finally:
            for ex in exs:
                ex.close()


# ---------------------------------------------------------------------------
# the plan compiler's Exchange stage
# ---------------------------------------------------------------------------


class TestPlanExchange:
    def test_exchange_node_validation(self):
        with pytest.raises(P.PlanError, match="at least one key"):
            pn.Exchange(pn.Scan("t"), (), 2)
        with pytest.raises(P.PlanError, match="world must be >= 1"):
            pn.Exchange(pn.Scan("t"), ("k",), 0)
        agg = pn.Aggregate(
            pn.Scan("t"), keys=("k",),
            aggs=(pn.AggSpec("v", "sum", "s"),),
        )
        with pytest.raises(P.PlanError, match="world must be >= 1"):
            P.insert_exchanges(agg, 0)

    def test_insert_exchanges_wraps_keyed_aggregates_only(self):
        keyed = pn.Aggregate(
            pn.Scan("fact"), keys=("f_key",),
            aggs=(pn.AggSpec("f_qty", "sum", "s"),),
        )
        out = P.insert_exchanges(keyed, 4)
        assert isinstance(out, pn.Aggregate)
        exch = out.input
        assert isinstance(exch, pn.Exchange)
        assert exch.keys == ("f_key",) and exch.world == 4
        assert isinstance(exch.input, pn.Scan)
        # a global aggregate has no partitioning to exploit: untouched
        glob = pn.Aggregate(
            pn.Scan("fact"), aggs=(pn.AggSpec("f_qty", "sum", "s"),),
        )
        out2 = P.insert_exchanges(glob, 4)
        assert isinstance(out2.input, pn.Scan)

    def test_exchange_stage_is_identity_without_binding(self):
        # the SAME distributed plan runs single-host: outside any
        # exchange binding the stage lowers to the identity, so the
        # compiled result matches the exchange-free plan exactly
        rng = np.random.default_rng(31)
        n = 256
        tables = {"fact": Table(
            [Column(dt.INT64, data=jnp.asarray(
                rng.integers(0, 16, n).astype(np.int64))),
             Column(dt.INT64, data=jnp.asarray(
                 rng.integers(-50, 50, n).astype(np.int64)))],
            ["k", "v"],
        )}
        plan = pn.Aggregate(
            pn.Scan("fact"), keys=("k",),
            aggs=(pn.AggSpec("v", "sum", "s"),),
        )
        single = P.compile_ir(plan, tables, name="cluster-single")()
        dist = P.compile_ir(
            P.insert_exchanges(plan, 4), tables, name="cluster-dist")()
        for got in (single, dist):
            assert set(got.names) == {"k", "s"}
        o1 = np.argsort(np.asarray(single.column("k").data))
        o2 = np.argsort(np.asarray(dist.column("k").data))
        for name in ("k", "s"):
            assert np.array_equal(
                np.asarray(single.column(name).data)[o1],
                np.asarray(dist.column(name).data)[o2],
            ), name


# ---------------------------------------------------------------------------
# a real TPC-DS plan across 4 ranks with one rank dead (the plan-layer
# half of the ISSUE 16 acceptance; the process-level kill -9 variant
# runs in TestClusterChaosFourRank below)
# ---------------------------------------------------------------------------


class TestDistributedPlanQuery:
    def test_q55x4_bit_identical_with_dead_rank(self):
        """The q55 plan with exchange stages inserted runs on a 4-rank
        fabric with rank 1 dead: the SAME compiled plan produces the
        single-host oracle unbound (exchange = identity), each live
        rank aggregates its key partition under an exchange binding
        (fact table sharded, dims replicated — broadcast join), the
        dead rank's exchange input is replayed from the lineage the
        stage itself installed, the coordinator rebuilds the
        destination-side hole, and merge_partials re-applies the
        plan's total-order Sort — bit-identical end to end."""
        from spark_rapids_jni_tpu.models import tpcds, tpcds_plans as tp
        from spark_rapids_jni_tpu.plan.distribute import merge_partials

        world, rows = 4, 8000
        tables = tpcds.gen_store(rows, seed=12)
        plan = P.insert_exchanges(tp.q55_plan(), world)
        sort_keys = (("ext_price", False), ("i_brand_id", True))
        # unbound, the exchange stages lower to the identity: the
        # distributed plan IS its own single-host oracle
        ref = P.compile_ir(plan, tables, name="q55x4-oracle")()
        assert ref.num_rows > 0

        fact_rows = tables["store_sales"].num_rows

        def shard_tables(r):
            lo, hi = shuffle._shard_bounds(fact_rows, world, r)
            return {
                "store_sales": slice_table(tables["store_sales"], lo, hi),
                "date_dim": tables["date_dim"],
                "item": tables["item"],
            }

        exs = {r: shuffle.TcpExchange(r) for r in (0, 2, 3)}
        addrs = {r: (exs[r].address if r in exs else "127.0.0.1:9")
                 for r in range(world)}
        kw = dict(heartbeat_s=0.05, heartbeat_timeout_s=0.2,
                  suspect_misses=1, dead_misses=2)
        views = {r: ClusterView(r, addrs, exs[r], **kw) for r in exs}
        recov0 = _counter("cluster.recoveries")
        res, errs = {}, []

        def run_rank(rank):
            try:
                peers = {r: a for r, a in addrs.items() if r != rank}
                with P.exchange_context(
                    exs[rank], peers, cluster=views[rank],
                    shard_tables=shard_tables,
                ), retry.enabled(max_attempts=20, base_delay_ms=5,
                                 max_delay_ms=50):
                    res[rank] = P.compile_ir(
                        plan, shard_tables(rank), name=f"q55x4-r{rank}")()
            except BaseException as e:  # noqa: BLE001 - surfaced below
                errs.append(e)

        try:
            for v in views.values():
                v.start()
            threads = [threading.Thread(target=run_rank, args=(r,))
                       for r in exs]
            for t in threads:
                t.start()
            for t in threads:
                t.join(300)
            assert not errs, errs
            assert set(res) == set(exs)
            # the destination-side hole: rank 1's key partition,
            # rebuilt from the lineage the exchange stage installed on
            # rank 0's view, then aggregated by the same plan shape
            hole = views[0].recompute_dead_partition(
                1, ["i_brand_id"], world)
            res[1] = P.compile_ir(
                pn.Aggregate(
                    pn.Scan("hole"), keys=("i_brand_id",),
                    aggs=(pn.AggSpec(
                        "ss_ext_sales_price", "sum", "ext_price"),),
                ),
                {"hole": hole}, name="q55x4-hole")()
            got = merge_partials(
                [res[r] for r in range(world)], sort_keys)
            assert got.num_rows == ref.num_rows
            for name in ("i_brand_id", "ext_price"):
                assert np.array_equal(
                    np.asarray(got.column(name).data),
                    np.asarray(ref.column(name).data),
                ), f"{name} diverged from the single-host oracle"
            # membership converged on one death; at least one survivor
            # recovered the dead rank's partitions from lineage
            for v in views.values():
                assert v.dead_ranks() == [1]
                assert v.generation() == 2
            assert _counter("cluster.recoveries") >= recov0 + 1
        finally:
            for v in views.values():
                v.stop()
            for ex in exs.values():
                ex.close()


# ---------------------------------------------------------------------------
# the serving layer's quorum-loss shed
# ---------------------------------------------------------------------------


class TestSchedulerQuorumShed:
    def test_scheduler_sheds_below_quorum(self):
        from spark_rapids_jni_tpu.serve.scheduler import Scheduler

        ex = shuffle.TcpExchange(0)
        view = ClusterView(0, {0: ex.address, 1: "127.0.0.1:9"}, ex)
        s = Scheduler(max_concurrent=1, queue_depth=4, name="cluster-shed")
        try:
            s.attach_cluster(view)
            h = s.submit(lambda: 7, tenant="t")
            assert h.result(30) == 7  # at quorum: admitted normally
            view.mark_dead(1)  # 1 of 2 alive: below the > 0.5 bar
            with pytest.raises(Overloaded) as ei:
                s.submit(lambda: 8, tenant="t")
            assert ei.value.cause == "cluster_degraded"
        finally:
            assert s.shutdown(drain=False, timeout_s=30.0)
            ex.close()


# ---------------------------------------------------------------------------
# the 4-process chaos acceptance (slow tier; ci/premerge.sh cluster
# tier runs it env-armed with the event log archived)
# ---------------------------------------------------------------------------


class TestClusterChaosFourRank:
    def test_four_rank_groupby_survives_rank_kill(self):
        """The ISSUE 16 acceptance: a 4-rank distributed groupby over
        the TCP exchange with ci/chaos_cluster.json armed in the
        children — rank 2 SIGKILLs itself mid-frame on its first
        payload serve (`crash` keyed ``exchange.serve.payload@r2``),
        rank 3 rides a transient netsplit, rank 1 serves with latency
        jitter — and the final answer is STILL bit-identical to the
        single-host oracle: exactly one membership death, the dead
        rank's partitions recomputed from lineage under the bumped
        generation, the destination-side hole rebuilt by the
        coordinator, zero stale bytes decoded (fence-verified before
        the decoder on every fetch)."""
        rows, seed, world = 4000, 13, 4
        cfg = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "ci", "chaos_cluster.json",
        )
        full = shuffle._demo_table(rows, seed=seed)
        ref = shuffle._local_groupby_sum(full)

        def shard(r):
            return _shard_of(full, rows, world, r)

        ex0 = shuffle.TcpExchange(0)
        procs, view = {}, None
        deaths0 = _counter("cluster.deaths")
        trans0 = _counter("cluster.transitions")
        recov0 = _counter("cluster.recoveries")
        try:
            with metrics.enabled():
                procs, peers = shuffle.spawn_exchange_fleet(
                    ex0.address, rows, seed, world=world, cluster=True,
                    extra_env_by_rank={
                        r: {"JAX_PLATFORMS": "cpu",
                            "SRJT_FAULTINJ_CONFIG": cfg}
                        for r in range(1, world)
                    },
                )
                view = ClusterView(0, dict(peers), ex0, lineage=shard)
                view.start()
                res = {}
                with deadline_mod.scope(300), retry.enabled(
                    max_attempts=40, base_delay_ms=25, max_delay_ms=250
                ):
                    local0 = ex0.exchange_table(
                        shard(0), ["k"],
                        {r: a for r, a in peers.items() if r != 0},
                        epoch=0, cluster=view,
                    )
                    res[0] = shuffle._local_groupby_sum(local0)
                    # the crash rule fired on rank 2's first payload
                    # serve: the membership layer must confirm the
                    # death (SIGKILL, no cleanup — rc != 0)
                    assert view.await_dead(2, 120), \
                        "rank 2 never declared dead"
                    assert procs[2].wait(timeout=120) != 0
                    # survivors finish their rounds and publish their
                    # partials under the bumped generation
                    for r in (1, 3):
                        got = ex0.fetch(peers[r], 1, r)
                        res[r] = Table(got.columns, ["k", "s", "c"])
                    # the destination-side hole: rank 2's share of the
                    # answer, rebuilt from lineage by the coordinator
                    res[2] = shuffle._local_groupby_sum(
                        view.recompute_dead_partition(2, ["k"], world))
                got = concatenate([res[r] for r in range(world)])
                order = np.argsort(np.asarray(got.column("k").data))
                for name in ("k", "s", "c"):
                    assert np.array_equal(
                        np.asarray(got.column(name).data)[order],
                        np.asarray(ref.column(name).data),
                    ), f"{name} diverged from the single-host oracle"
                # exactly ONE membership death (alive->suspect->dead is
                # the one allowed transition pair), generation bumped
                # once, and this rank's own failover republished the
                # dead rank's partitions at least once
                assert view.dead_ranks() == [2]
                assert view.generation() == 2 and ex0.generation() == 2
                assert _counter("cluster.deaths") == deaths0 + 1
                assert _counter("cluster.transitions") == trans0 + 2
                assert _counter("cluster.recoveries") >= recov0 + 1
        finally:
            if view is not None:
                view.stop()
            for p in procs.values():
                if p.poll() is None:
                    try:
                        p.stdin.close()
                        p.wait(timeout=20)
                    except Exception:
                        p.kill()
            ex0.close()
            shuffle.exchange_breaker().reset()

"""Test harness: hermetic, no-TPU-required tier the reference lacks (SURVEY §4.4).

All tests run on a virtual 8-device CPU mesh so multi-chip sharding paths
(parallel/shuffle) are exercised without hardware. Set SRJT_TEST_TPU=1 to run
the same suite against real devices.
"""

import os

if os.environ.get("SRJT_TEST_TPU", "0") != "1":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(12345)

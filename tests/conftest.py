"""Test harness: hermetic, no-TPU-required tier the reference lacks (SURVEY §4.4).

All tests run on a virtual 8-device CPU mesh so multi-chip sharding paths
(parallel/shuffle) are exercised without hardware. Set SRJT_TEST_TPU=1 to run
the same suite against real devices.
"""

import os

if os.environ.get("SRJT_TEST_TPU", "0") != "1":
    # jax is preloaded at interpreter startup in this image with
    # JAX_PLATFORMS=axon, so the env var alone is too late — update the
    # live config before any backend initializes.
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(12345)

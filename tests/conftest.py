"""Test harness: hermetic, no-TPU-required tier the reference lacks (SURVEY §4.4).

All tests run on a virtual 8-device CPU mesh so multi-chip sharding paths
(parallel/shuffle) are exercised without hardware. Set SRJT_TEST_TPU=1 to run
the same suite against real devices.
"""

import os

if os.environ.get("SRJT_TEST_TPU", "0") != "1":  # srjt-lint: allow-environ(bootstrap: JAX_PLATFORMS must be set BEFORE any package import, and importing utils/knobs imports the package which imports jax)
    # jax is preloaded at interpreter startup in this image with
    # JAX_PLATFORMS=axon, so the env var alone is too late — update the
    # live config before any backend initializes.
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(12345)


@pytest.fixture(autouse=True, scope="session")
def _assert_no_arena_slab_leak():
    """ISSUE 6 leak tripwire: every slab-arena memfd opened during the
    session must be closed (SidecarPool.shutdown / set_arena / explicit
    ArenaSlab.close) by session end — an open slab is leaked pinned
    host pages plus a leaked fd. Lazy sys.modules lookup: runs only
    when the suite actually touched the pool."""
    yield
    import sys as _sys

    pool_mod = _sys.modules.get("spark_rapids_jni_tpu.sidecar_pool")
    if pool_mod is not None:
        leaked = pool_mod.open_slab_count()
        assert leaked == 0, (
            f"{leaked} arena slab(s) leaked past session teardown: "
            + "; ".join(pool_mod.arena_leak_report())
        )


@pytest.fixture(autouse=True, scope="session")
def _assert_no_scheduler_thread_leak():
    """ISSUE 8 leak tripwire (mirrors the slab-leak check): every serve
    Scheduler started during the session must have joined all its
    dispatch-slot threads (Scheduler.shutdown) by session end — a live
    scheduler is leaked daemon threads still able to dispatch queries
    into torn-down fixtures. Lazy sys.modules lookup: runs only when
    the suite actually touched the serving layer."""
    yield
    import sys as _sys
    import threading as _threading

    serve_mod = _sys.modules.get("spark_rapids_jni_tpu.serve")
    if serve_mod is not None:
        serve_mod.shutdown_scheduler(drain=False, timeout_s=10.0)
        leaked = serve_mod.live_scheduler_count()
        assert leaked == 0, (
            f"{leaked} serve scheduler(s) leaked past session teardown: "
            + "; ".join(serve_mod.leak_report())
        )
        stragglers = [
            t.name for t in _threading.enumerate()
            if t.name.startswith("srjt-serve-") and t.is_alive()
        ]
        assert not stragglers, (
            f"serve dispatch threads leaked past session teardown: "
            f"{stragglers}"
        )


@pytest.fixture(autouse=True, scope="session")
def _assert_no_spill_file_leak():
    """ISSUE 20 leak tripwire (the spill-file lifecycle satellite):
    the test session must leave the spill dir empty — every disk-spilled
    frame (and its durable manifest sidecar) written during the session
    is unlinked by catalog close/unregister/re-materialization by
    session end. A surviving .frm is leaked disk bytes no process will
    reclaim until the next manifest-armed startup sweep. Lazy
    sys.modules lookup: runs only when the suite touched memgov."""
    yield
    import glob as _glob
    import sys as _sys
    import tempfile as _tempfile

    memgov_mod = _sys.modules.get("spark_rapids_jni_tpu.memgov")
    if memgov_mod is None:
        return
    # close any surviving catalog first: its own teardown is the
    # mechanism under test, not the tripwire's job to replicate
    memgov_mod.reset()
    dirs = {os.path.join(_tempfile.gettempdir(), f"srjt-spill-{os.getpid()}")}
    spill_dir = os.environ.get("SRJT_SPILL_DIR")  # srjt-lint: allow-environ(session-teardown tripwire: knobs may already be monkeypatch-reverted; the raw env var is exactly what the CI tier armed)
    if spill_dir:
        dirs.add(spill_dir)
    leaked = []
    for d in dirs:
        leaked += _glob.glob(os.path.join(d, "*.frm"))
        leaked += _glob.glob(os.path.join(d, "*.mf"))
    assert not leaked, (
        f"{len(leaked)} spill file(s) leaked past session teardown: "
        f"{sorted(leaked)[:10]}"
    )


@pytest.fixture(autouse=True, scope="session")
def _assert_no_partition_entry_leak():
    """ISSUE 18 leak tripwire (mirrors the slab/scheduler checks): every
    out-of-core partition catalog entry (kind="partition") registered
    during the session must be unregistered by session end — success,
    failure, deadline expiry, and chaos paths all release them
    (OutOfCorePlan._release). A surviving entry is leaked spill bytes
    plus a stale checkpoint a later run could wrongly resume from. Lazy
    sys.modules lookup: runs only when the suite touched memgov."""
    yield
    import sys as _sys

    memgov_mod = _sys.modules.get("spark_rapids_jni_tpu.memgov")
    if memgov_mod is not None and memgov_mod._catalog is not None:
        entries, nbytes = memgov_mod._catalog.kind_stats("partition")
        assert (entries, nbytes) == (0, 0), (
            f"{entries} out-of-core partition catalog entrie(s) "
            f"({nbytes} bytes) leaked past session teardown"
        )


# ---------------------------------------------------------------------------
# premerge fast tier (VERDICT r3 item 9)
# ---------------------------------------------------------------------------
# The full hermetic suite takes ~25 min on this 1-core box; ci/premerge.sh
# runs `-m "not slow"` (<~8 min) and ci/nightly.sh runs everything. The
# set below is the measured top of the duration report (>=10 s each;
# calibrated round 4, re-calibrated round 8 when the accumulated tail
# pushed the fast tier past the 870 s harness ceiling — ~345 s moved
# out); a renamed test silently drops back into the fast tier, which
# is the safe failure mode.
_SLOW_TESTS = {
    # round-8 re-calibration: the >=10 s tail accumulated since round 4
    # (tpcds distributed/oracle pairs, decimal128 long multiplies, the
    # chaos parity storm, ragged encode parity, the two-process
    # exchange chaos acceptance — the last two still run premerge in
    # their dedicated env-armed tiers, everything runs nightly)
    "test_tpcds_queries.py::TestQ94::test_distributed_identical",
    "test_tpcds_queries.py::TestQ94::test_matches_exact_oracle",
    "test_tpcds_queries.py::TestQ7::test_distributed_bit_identical",
    "test_tpcds_queries.py::TestQ7::test_matches_exact_oracle",
    "test_tpcds_queries.py::TestQ19::test_distributed_bit_identical",
    "test_tpcds_queries.py::TestQ98WindowRatio::test_matches_oracle",
    "test_tpcds_queries.py::TestReportingShapes::"
    "test_q52_distributed_bit_identical",
    "test_models.py::TestQ55::test_q55_distributed_matches_single_chip",
    "test_decimal_utils.py::test_overflow_mult",
    "test_decimal_utils.py::test_simple_neg_multiply",
    "test_decimal_utils.py::test_null_propagation",
    "test_chaos.py::test_chaos_parity_retryable_storm",
    "test_ragged_bytes.py::test_pallas_kernels_interpret_parity",
    "test_ragged_bytes.py::test_padded_vs_scatter_encode_parity",
    "test_data_plane.py::TestTcpExchangeTwoProcess::"
    "test_two_process_groupby_bit_identical_under_chaos",
    # srjt-cluster (ISSUE 16): the 4-process chaos acceptance, the
    # world-4 topology bit-identity pair, and the in-process failover
    # rendezvous all burn heartbeat/retry wall-clock by design;
    # ci/premerge.sh runs the whole file env-armed in the dedicated
    # cluster tier (no slow filter there), nightly runs them too
    "test_cluster.py::TestClusterChaosFourRank::"
    "test_four_rank_groupby_survives_rank_kill",
    "test_cluster.py::TestTopology::test_tree_equals_all_to_all_world4",
    "test_cluster.py::TestDistributedPlanQuery::"
    "test_q55x4_bit_identical_with_dead_rank",
    "test_cluster.py::TestRecovery::"
    "test_exchange_failover_bit_identical_in_process",
    "test_table_ops.py::test_distributed_groupby_table_int_keys",
    # the hang-storm acceptance burns ~6 budget expiries of wall-clock
    # by design; ci/premerge.sh runs it env-armed in the dedicated
    # deadline tier (no slow filter there), nightly runs it too
    "test_deadline.py::TestChaosHangStorm::"
    "test_every_query_completes_or_raises_deadline_exceeded_in_budget",
    "test_cast_decimal.py::test_edges",
    "test_cast_decimal.py::test_type_dispatch_by_precision",
    "test_concurrency.py::test_concurrent_executor_threads_isolated",
    "test_decimal_utils.py::test_large_pos_multiply_ten_by_ten",
    "test_decimal_utils.py::test_simple_neg_multiply_one_by_one",
    "test_decimal_utils.py::test_simple_pos_multiply_one_by_one",
    "test_decimal_utils.py::test_simple_pos_multiply_one_by_zero",
    "test_decimal_utils.py::test_simple_pos_multiply_zero_by_neg_one",
    "test_decimal_utils.py::test_spark_compat_multiply",
    "test_f64acc.py::TestDD::test_exact_f32_values_roundtrip_exactly",
    "test_f64acc.py::TestDD::test_mod",
    "test_f64acc.py::TestDD::test_roundtrip_bits",
    "test_f64acc.py::TestExactMean::test_correctly_rounded_mean",
    "test_f64acc.py::TestExactSum::test_bit_identical_small_span",
    "test_f64acc.py::TestExactSum::test_wide_span_relative_bound",
    "test_graft_entry.py::test_dryrun_multichip_from_unforced_process",
    # the memgov squeeze/escalation tier compiles several per-capacity
    # exchange programs and spawns a sidecar worker; ci/premerge.sh runs
    # the whole file env-armed in the dedicated low-budget tier (no slow
    # filter there), nightly runs it too
    "test_memgov.py::TestShuffleEscalation::"
    "test_escalation_that_cannot_fit_raises_retryable",
    "test_memgov.py::TestShuffleEscalation::"
    "test_escalation_admitted_under_ample_budget",
    "test_memgov.py::TestSqueeze::"
    "test_groupby_squeeze_spills_and_splits_interleave",
    "test_memgov.py::TestSqueeze::test_q1_bit_identical_under_squeeze",
    "test_memgov.py::test_sidecar_arena_registers_with_catalog",
    "test_models.py::TestFusedPipelines::test_q1_fused_matches_op_tier",
    "test_models.py::TestFusedPipelines::test_q6_fused_matches_op_tier",
    "test_models.py::TestTpcds::test_q95_matches_pandas",
    "test_models.py::TestTpch::test_q1_exact_f64_adversarial_magnitudes",
    "test_models.py::TestTpch::test_q1_matches_pandas",
    "test_native_columnar.py::test_cast_to_decimal_matches_python_op",
    "test_native_columnar.py::test_decimal128_native_matches_python[mul--1]",
    "test_native_columnar.py::test_decimal128_native_matches_python[mul--20]",
    "test_native_columnar.py::test_decimal128_native_matches_python[mul--6]",
    "test_operators.py::test_full_join_string_keys_matches_pandas",
    "test_parquet_reader.py::test_deep_nesting_row_groups",
    "test_parquet_reader.py::test_multiple_row_groups",
    "test_ragged_bytes.py::TestRaggedCompact::test_aligned_and_unaligned_mix",
    "test_regex.py::test_replace_re[\\d+-#]",
    "test_row_conversion.py::test_grouped_decode_matches_per_column",
    "test_row_conversion.py::test_roundtrip_wide",
    "test_sidecar.py::test_convert_to_rows_dispatches_device_and_matches_host",
    # the real-subprocess pool tier spawns 2-3 jax workers each;
    # ci/premerge.sh runs the whole file env-armed in the dedicated
    # crash-storm tier (no slow filter there), nightly runs them too
    # the chaos-under-load serving acceptance runs 40 concurrent TPC
    # queries under a retryable+reject storm (and the pipeline
    # submission test pays a q6 compile); ci/premerge.sh runs the
    # whole file env-armed in the dedicated serve tier (no slow filter
    # there), nightly runs it too
    "test_serve.py::TestChaosUnderLoad::"
    "test_storm_while_serving_yields_bit_identical_results",
    "test_serve.py::TestSubmit::test_compiled_pipeline_is_submittable",
    "test_sidecar_pool.py::TestRealWorkerPool::"
    "test_q1_bit_identical_through_kill9_failover",
    "test_sidecar_pool.py::TestRealWorkerPool::"
    "test_crash_and_corrupt_storm_survives",
    "test_table_ops.py::test_distributed_join_semi_anti[left_anti]",
    "test_table_ops.py::test_distributed_join_semi_anti[left_semi]",
    "test_table_ops.py::test_distributed_join_string_key",
    "test_table_ops.py::test_memory_budget_split_retry",
    "test_table_ops.py::test_q95_distributed_matches_single_chip",
    # the plan-compiler oracle tier's heavy tail (each test pays one
    # or more fused-pipeline XLA compiles; the 5-8 s trio rides along
    # because round 14 measured the fast tier at 842 s of the 870 s
    # harness ceiling — margin beats calibration purity there);
    # ci/premerge.sh runs the whole file env-armed in the dedicated
    # compiler tier (no slow filter there), nightly runs it too
    "test_plan_queries.py::TestRollupHaving::test_q27_rollup_matches_oracle",
    "test_plan_queries.py::TestSetOpsExists::test_q38_intersect_chain",
    "test_plan_queries.py::TestDecorrelation::test_q1_matches_oracle",
    "test_plan_queries.py::TestFusedStars::test_q43_case_pivot_matches_oracle",
    "test_plan_queries.py::TestFusedStars::test_q26_matches_exact_oracle",
    "test_plan_queries.py::TestSetOpsExists::test_q69_exists_chain_matches_oracle",
    "test_plan_queries.py::TestWindowRatio::test_q20_matches_oracle",
    # srjt-cbo (ISSUE 19): the mass-green campaign's oracle tier (each
    # test pays a fused-pipeline compile; measured 104 s total) and the
    # OOC model-chosen-K acceptance (pays two q1-shape executions);
    # ci/premerge.sh runs both files env-armed in their dedicated
    # compiler/ooc tiers (no slow filter there), nightly runs them too
    "test_plan_queries.py::TestCboCampaign::test_q8_zip_intersect_matches_oracle",
    "test_plan_queries.py::TestCboCampaign::test_q9_bucketed_case_matches_oracle",
    "test_plan_queries.py::TestCboCampaign::test_q10_or_exists_matches_oracle",
    "test_plan_queries.py::TestCboCampaign::test_q15_zip_band_star_matches_oracle",
    "test_plan_queries.py::TestCboCampaign::test_q28_band_aggregates_match_oracle",
    "test_plan_queries.py::TestCboCampaign::test_q30_state_decorrelation_matches_oracle",
    "test_plan_queries.py::TestCboCampaign::test_q32_catalog_excess_discount_matches_oracle",
    "test_plan_queries.py::TestCboCampaign::test_q34_having_band_matches_oracle",
    "test_plan_queries.py::TestCboCampaign::test_q35_state_demo_stats_match_oracle",
    "test_plan_queries.py::TestCboCampaign::test_q39_std_over_mean_matches_oracle",
    "test_ooc.py::TestCostModelPartitions::test_model_chosen_k_overhead_bounded",
    # srjt-durable (ISSUE 20): the kill -9 acceptance spawns a child
    # coordinator (jax import + two plan compiles) and SIGKILLs it;
    # ci/premerge.sh covers the restart posture in the dedicated
    # restart tier (bench_restart-driven), nightly runs this too
    "test_durable.py::TestKillNineAcceptance::"
    "test_restart_answers_journaled_queries_bit_identical",
}


# parametrized ids with regex metacharacters escape unpredictably in
# nodeids — match those families by prefix instead of exact id
_SLOW_PREFIXES = (
    "test_regex.py::test_replace_re[",
    # round-8: the java-semantics split family runs 9-16 s per pattern
    "test_regex.py::test_split_re_vs_java_semantics[",
)


def pytest_collection_modifyitems(config, items):
    for item in items:
        nodeid = item.nodeid.replace("tests/", "")
        if nodeid in _SLOW_TESTS or nodeid.startswith(_SLOW_PREFIXES):
            item.add_marker(pytest.mark.slow)

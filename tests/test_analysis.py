"""Tests for the correctness tooling tier (ISSUE 7):

- srjt-lint rule fixtures: one seeded-violation snippet per rule
  asserting the rule FIRES, and one suppressed/compliant variant
  asserting it doesn't (the suppression contract is part of the tool).
- the knob registry: typed accessors, malformed-input degradation, the
  undeclared-read failure mode, doc-table rendering.
- runtime lockdep: a deliberate two-lock inversion proving the cycle
  is reported, self-deadlock + blocking-while-locked detection, the
  Condition integration, and the merge/gate CLI.
- the integration gate: the REAL repo lints clean (so a violation a PR
  introduces fails here before it fails premerge).
"""

import json
import os
import threading

import pytest

from spark_rapids_jni_tpu.analysis import lint, lockdep
from spark_rapids_jni_tpu.utils import knobs

# a hermetic registry view for snippet tests: rule scoping must not
# drift when real knobs are added/removed
KNOBS = frozenset({"SRJT_RETRY_ENABLED", "SRJT_DEADLINE_SEC"})
SENTINELS = frozenset({"SRJT_SIDECAR_READY"})


def run_lint(src, rel, rules=None):
    vs = lint.lint_source(src, path=f"<fixture:{rel}>", rel=rel,
                          knob_names=KNOBS, sentinels=SENTINELS)
    if rules is None:
        return vs
    return [v for v in vs if v.rule in rules]


# ---------------------------------------------------------------------------
# SRJT001: undeclared knob literals
# ---------------------------------------------------------------------------


def test_undeclared_knob_literal_fires():
    vs = run_lint('x = os.environ\nk = "SRJT_BOGUS_KNOB"\n', "utils/x.py",  # srjt-lint: allow-knob(lint-suite fixture literal)
                  {"SRJT001"})
    assert len(vs) == 1 and "SRJT_BOGUS_KNOB" in vs[0].message  # srjt-lint: allow-knob(lint-suite fixture literal)


def test_declared_knob_and_sentinel_pass():
    src = 'a = "SRJT_RETRY_ENABLED"\nb = "SRJT_SIDECAR_READY"\n'
    assert run_lint(src, "utils/x.py", {"SRJT001"}) == []


def test_family_glob_in_prose_passes():
    # "SRJT_RETRY_*" names a declared family, not an undeclared knob
    assert run_lint('doc = "set SRJT_RETRY_* to tune"\n', "utils/x.py",
                    {"SRJT001"}) == []


def test_knob_suppression_works():
    src = 'k = "SRJT_BOGUS"  # srjt-lint: allow-knob(doc example)\n'  # srjt-lint: allow-knob(lint-suite fixture literal)
    assert run_lint(src, "utils/x.py", {"SRJT001"}) == []


def test_knobs_module_itself_is_exempt():
    assert run_lint('declare("SRJT_NEW_ONE", "int", 1, "d")\n',  # srjt-lint: allow-knob(lint-suite fixture literal)
                    "utils/knobs.py", {"SRJT001"}) == []


# ---------------------------------------------------------------------------
# SRJT002: direct environ reads
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("src", [
    'import os\nv = os.environ.get("SRJT_RETRY_ENABLED")\n',
    'import os\nv = os.environ["SRJT_RETRY_ENABLED"]\n',
    'import os\nv = os.getenv("SRJT_RETRY_ENABLED")\n',
    'import os\nk = "SRJT_" + name\nv = os.environ.get(k)\n',  # dynamic key
])
def test_direct_environ_read_fires(src):
    assert len(run_lint(src, "memgov/x.py", {"SRJT002"})) == 1


def test_non_srjt_reads_and_writes_pass():
    src = ('import os\n'
           'v = os.environ.get("JAX_PLATFORMS")\n'
           'os.environ["SRJT_RETRY_ENABLED"] = "1"\n')
    assert run_lint(src, "memgov/x.py", {"SRJT002"}) == []


def test_environ_suppression_works():
    src = ('import os\n'
           'v = os.environ.get("SRJT_RETRY_ENABLED")  '
           '# srjt-lint: allow-environ(bootstrap read)\n')
    assert run_lint(src, "x.py", {"SRJT002"}) == []


# ---------------------------------------------------------------------------
# SRJT003: banned raises in governed modules
# ---------------------------------------------------------------------------


def test_raise_runtimeerror_in_governed_module_fires():
    src = 'def f():\n    raise RuntimeError("boom")\n'
    for rel in ("ops/x.py", "memgov/x.py", "parallel/x.py", "sidecar.py"):
        assert len(run_lint(src, rel, {"SRJT003"})) == 1, rel


def test_raise_outside_governed_scope_passes():
    src = 'def f():\n    raise RuntimeError("boom")\n'
    assert run_lint(src, "io/x.py", {"SRJT003"}) == []


def test_taxonomy_raise_passes():
    src = 'def f():\n    raise RetryableError("transient")\n'
    assert run_lint(src, "ops/x.py", {"SRJT003"}) == []


def test_raise_suppression_works():
    src = ('def f():\n'
           '    raise RuntimeError("wire")  '
           '# srjt-lint: allow-raise(semantic wire error)\n')
    assert run_lint(src, "ops/x.py", {"SRJT003"}) == []


# ---------------------------------------------------------------------------
# SRJT004: broad excepts
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("handler", [
    "except Exception:\n        pass",
    "except:\n        pass",
    "except (ValueError, Exception):\n        pass",
    "except BaseException:\n        pass",
])
def test_swallowing_broad_except_fires(handler):
    src = f"def f():\n    try:\n        g()\n    {handler}\n"
    assert len(run_lint(src, "utils/x.py", {"SRJT004"})) == 1


@pytest.mark.parametrize("handler", [
    "except Exception:\n        raise",                       # re-raise
    "except Exception as e:\n        raise classify(e)",      # wrap
    "except Exception as e:\n        raise DataCorruption(str(e))",
    "except ValueError:\n        pass",                       # narrow is fine
])
def test_compliant_broad_except_passes(handler):
    src = f"def f():\n    try:\n        g()\n    {handler}\n"
    assert run_lint(src, "utils/x.py", {"SRJT004"}) == []


def test_broad_except_suppression_inline_and_above():
    inline = ("def f():\n    try:\n        g()\n"
              "    except Exception:  "
              "# srjt-lint: allow-broad-except(best effort)\n        pass\n")
    above = ("def f():\n    try:\n        g()\n"
             "    # srjt-lint: allow-broad-except(best effort)\n"
             "    except Exception:\n        pass\n")
    assert run_lint(inline, "utils/x.py", {"SRJT004"}) == []
    assert run_lint(above, "utils/x.py", {"SRJT004"}) == []


def test_suppression_without_reason_is_its_own_violation():
    src = ("def f():\n    try:\n        g()\n"
           "    except Exception:  # srjt-lint: allow-broad-except()\n"
           "        pass\n")
    vs = run_lint(src, "utils/x.py")
    assert [v.rule for v in vs] == ["SRJT000"]
    assert "needs a reason" in vs[0].message


def test_unknown_suppression_kind_is_flagged():
    src = "x = 1  # srjt-lint: allow-wat(huh)\n"
    vs = run_lint(src, "utils/x.py")
    assert [v.rule for v in vs] == ["SRJT000"]


def test_stale_suppression_is_flagged():
    # a reasoned suppression on a line where the rule never fires is
    # rot: the code it excused is gone
    src = "x = 1  # srjt-lint: allow-blocking(was a sleep once)\n"
    vs = run_lint(src, "sidecar.py")
    assert [v.rule for v in vs] == ["SRJT000"]
    assert "stale" in vs[0].message


def test_aliased_environ_read_fires():
    # `import os as _os` does not launder a direct read
    src = 'import os as _os\nv = _os.environ.get("SRJT_RETRY_ENABLED")\n'
    assert len(run_lint(src, "x.py", {"SRJT002"})) == 1


# ---------------------------------------------------------------------------
# SRJT005: hot-path stub discipline
# ---------------------------------------------------------------------------


def test_work_before_gate_fires():
    src = ('def counter(name):\n'
           '    label = f"metric:{name}"\n'
           '    if not _enabled:\n'
           '        return _STUB\n'
           '    return _real(label)\n')
    vs = run_lint(src, "utils/metrics.py", {"SRJT005"})
    assert len(vs) == 1 and "f-string" in vs[0].message


def test_work_after_gate_passes():
    src = ('def counter(name):\n'
           '    if not _enabled:\n'
           '        return _STUB\n'
           '    return _real(f"metric:{name}")\n')
    assert run_lint(src, "utils/metrics.py", {"SRJT005"}) == []


def test_stub_rule_only_governs_stub_modules():
    src = ('def f(name):\n'
           '    label = f"x:{name}"\n'
           '    if not _enabled:\n'
           '        return None\n'
           '    return label\n')
    assert run_lint(src, "ops/x.py", {"SRJT005"}) == []


# ---------------------------------------------------------------------------
# SRJT006: blocking calls must be deadline-aware
# ---------------------------------------------------------------------------


def test_blind_sleep_in_governed_module_fires():
    src = 'import time\ndef f():\n    time.sleep(1)\n'
    assert len(run_lint(src, "sidecar.py", {"SRJT006"})) == 1


def test_deadline_aware_function_passes():
    src = ('import time\n'
           'def f(deadline):\n'
           '    time.sleep(min(1, deadline.remaining()))\n')
    assert run_lint(src, "sidecar.py", {"SRJT006"}) == []


def test_blocking_rule_scoped_to_governed_modules():
    src = 'import time\ndef f():\n    time.sleep(1)\n'
    assert run_lint(src, "models/x.py", {"SRJT006"}) == []


def test_blocking_suppression_works():
    src = ('import time\n'
           'def f():\n'
           '    time.sleep(1)  # srjt-lint: allow-blocking(no budget)\n')
    assert run_lint(src, "sidecar.py", {"SRJT006"}) == []


def test_settimeout_and_recv_governed():
    src = ('def f(sock):\n'
           '    sock.settimeout(5)\n'
           '    return sock.recv(4)\n')
    assert len(run_lint(src, "parallel/x.py", {"SRJT006"})) == 2


# ---------------------------------------------------------------------------
# SRJT007: registry <-> doc-table drift
# ---------------------------------------------------------------------------


def test_doc_drift_both_directions(tmp_path):
    (tmp_path / "README.md").write_text(
        "| `SRJT_RETRY_ENABLED` | arm retry |\n"  # srjt-lint: allow-knob(lint-suite fixture literal)
        "| `SRJT_GHOST_KNOB` | documented but gone |\n")
    vs = lint.check_docs(str(tmp_path), knob_names=KNOBS,
                         sentinels=SENTINELS)
    rules = sorted((v.rule, v.message.split()[2]) for v in vs)
    # SRJT_GHOST_KNOB documented-but-undeclared + SRJT_DEADLINE_SEC
    # declared-but-undocumented
    assert ("SRJT007", "SRJT_GHOST_KNOB") in rules  # srjt-lint: allow-knob(lint-suite fixture literal)
    assert any("SRJT_DEADLINE_SEC" in v.message for v in vs)
    assert all(v.rule == "SRJT007" for v in vs)


def test_prose_mention_is_not_documentation(tmp_path):
    # SRJT_DEADLINE_SEC only in prose, never in a table row: the
    # "documented" direction requires a knob-table row
    (tmp_path / "README.md").write_text(
        "| `SRJT_RETRY_ENABLED` | arm retry |\n"
        "Set SRJT_DEADLINE_SEC for budgets.\n")
    vs = lint.check_docs(str(tmp_path), knob_names=KNOBS,
                         sentinels=SENTINELS)
    assert len(vs) == 1 and "SRJT_DEADLINE_SEC" in vs[0].message
    assert "knob-table row" in vs[0].message


def test_truncated_name_in_table_row_is_drift(tmp_path):
    # prefix allowance is for wrapped ASCII diagrams in prose only; a
    # truncated name inside a table row is exactly the drift to catch
    (tmp_path / "README.md").write_text(
        "| `SRJT_RETRY` | truncated row |\n"  # srjt-lint: allow-knob(lint-suite fixture literal)
        "  diagram: SRJT_RETRY (wrapped)\n"
        "| `SRJT_RETRY_ENABLED` | ok |\n"
        "| `SRJT_DEADLINE_SEC` | ok |\n")
    vs = lint.check_docs(str(tmp_path), knob_names=KNOBS,
                         sentinels=SENTINELS)
    assert len(vs) == 1 and vs[0].line == 1


def test_syntax_error_reports_not_crashes():
    vs = run_lint("def f(:\n", "utils/x.py")
    assert [v.rule for v in vs] == ["SRJT999"]


# ---------------------------------------------------------------------------
# the integration gate: the real repo is clean
# ---------------------------------------------------------------------------


def test_repo_lints_clean():
    vs = lint.run()
    assert vs == [], "\n".join(repr(v) for v in vs)


def test_knob_table_cli_renders(capsys):
    assert lint.main(["--knob-table"]) == 0
    out = capsys.readouterr().out
    assert "| `SRJT_RETRY_ENABLED` | bool |" in out


# ---------------------------------------------------------------------------
# the knob registry
# ---------------------------------------------------------------------------


def test_undeclared_knob_read_fails_loudly():
    with pytest.raises(KeyError, match="undeclared knob"):
        knobs.get_raw("SRJT_NOT_A_KNOB")  # srjt-lint: allow-knob(lint-suite fixture literal)


def test_typed_accessors_and_defaults(monkeypatch):
    monkeypatch.delenv("SRJT_RETRY_MAX_ATTEMPTS", raising=False)
    assert knobs.get_int("SRJT_RETRY_MAX_ATTEMPTS") == 4
    monkeypatch.setenv("SRJT_RETRY_MAX_ATTEMPTS", "7")
    assert knobs.get_int("SRJT_RETRY_MAX_ATTEMPTS") == 7


def test_malformed_value_warns_and_degrades(monkeypatch):
    monkeypatch.setenv("SRJT_RETRY_MAX_ATTEMPTS", "banana")
    with pytest.warns(UserWarning, match="malformed"):
        assert knobs.get_int("SRJT_RETRY_MAX_ATTEMPTS") == 4


def test_positive_knob_rejects_nonpositive(monkeypatch):
    monkeypatch.setenv("SRJT_SIDECAR_TIMEOUT_SEC", "-3")
    with pytest.warns(UserWarning, match="must be > 0"):
        assert knobs.get_float("SRJT_SIDECAR_TIMEOUT_SEC") == 600.0


def test_bool_tristate(monkeypatch):
    # default-on knob only disarms on an explicit false spelling
    for raw, expect in (("0", False), ("false", False), ("no", False),
                        ("1", True), ("", True)):
        monkeypatch.setenv("SRJT_INTEGRITY_CHECKS", raw)
        assert knobs.get_bool("SRJT_INTEGRITY_CHECKS") is expect, raw
    # unrecognized spellings warn and keep the default (never a silent
    # arm/disarm surprise)
    monkeypatch.setenv("SRJT_INTEGRITY_CHECKS", "weird")
    with pytest.warns(UserWarning, match="malformed"):
        assert knobs.get_bool("SRJT_INTEGRITY_CHECKS") is True


def test_minimum_clamp(monkeypatch):
    monkeypatch.setenv("SRJT_SIDECAR_POOL_SIZE", "0")
    assert knobs.get_int("SRJT_SIDECAR_POOL_SIZE") == 1


def test_choices_knob(monkeypatch):
    monkeypatch.setenv("SRJT_EXCHANGE_MODE", "TCP")
    assert knobs.get_str("SRJT_EXCHANGE_MODE") == "tcp"
    monkeypatch.setenv("SRJT_EXCHANGE_MODE", "carrier-pigeon")
    with pytest.warns(UserWarning, match="unknown"):
        assert knobs.get_str("SRJT_EXCHANGE_MODE") == "mesh"


def test_explicit_zero_budget_is_respected(monkeypatch):
    # "0" is a real operator contract (force everything over-budget),
    # not "unset": the int accessor must not be truth-tested away
    from spark_rapids_jni_tpu.utils import memory

    monkeypatch.setenv("SRJT_DEVICE_MEMORY_BUDGET", "0")
    assert memory.device_memory_budget() == 0


def test_double_declare_fails():
    with pytest.raises(ValueError, match="declared twice"):
        knobs.declare("SRJT_RETRY_ENABLED", "bool", False, "dup")


def test_markdown_table_covers_registry():
    table = knobs.markdown_table()
    for k in knobs.all_knobs():
        assert f"`{k.name}`" in table


# ---------------------------------------------------------------------------
# runtime lockdep
# ---------------------------------------------------------------------------


@pytest.fixture
def armed_lockdep():
    """Arm the shim for one test without disturbing a session that was
    already armed via SRJT_LOCKDEP=1 (premerge runs exactly that)."""
    was = lockdep.is_installed()
    lockdep.install()
    with lockdep.isolated_state() as st:
        yield st
    if not was:
        lockdep.uninstall()


def test_two_lock_inversion_reports_cycle(armed_lockdep):
    a, b = threading.Lock(), threading.Lock()
    assert type(a).__name__ == "_TrackedLock", "factory not patched"
    with a:
        with b:
            pass
    with b:
        with a:  # the deliberate inversion: B -> A after A -> B
            pass
    rep = lockdep.report(armed_lockdep)
    assert len(rep["cycles"]) == 1
    locks = rep["cycles"][0]["locks"]
    assert len(locks) == 2 and all("test_analysis.py" in s for s in locks)
    # both directed edges exist and carry a sample stack
    assert {(e["from_key"], e["to_key"]) for e in rep["edges"]} == {
        (rep["cycles"][0]["keys"][0], rep["cycles"][0]["keys"][1]),
        (rep["cycles"][0]["keys"][1], rep["cycles"][0]["keys"][0]),
    }
    assert all(e["stack"] for e in rep["edges"])


def test_consistent_order_reports_no_cycle(armed_lockdep):
    a, b = threading.Lock(), threading.Lock()
    for _ in range(3):
        with a:
            with b:
                pass
    rep = lockdep.report(armed_lockdep)
    assert rep["cycles"] == [] and len(rep["edges"]) == 1
    assert rep["edges"][0]["count"] == 3


def test_self_deadlock_detected(armed_lockdep):
    lk = threading.Lock()
    with lk:
        # second acquisition of a held non-reentrant lock: recorded,
        # then attempted non-blocking so the test cannot hang
        assert lk.acquire(blocking=False) is False
    rep = lockdep.report(armed_lockdep)
    assert len(rep["self_deadlocks"]) == 1


def test_rlock_reentry_is_not_a_self_deadlock(armed_lockdep):
    lk = threading.RLock()
    with lk:
        with lk:
            pass
    rep = lockdep.report(armed_lockdep)
    assert rep["self_deadlocks"] == [] and rep["cycles"] == []


def test_sleep_while_locked_recorded(armed_lockdep):
    import time

    lk = threading.Lock()
    time.sleep(0)  # unlocked: not an event
    with lk:
        time.sleep(0)
    rep = lockdep.report(armed_lockdep)
    assert rep["blocking_total"] == 1
    assert rep["blocking_events"][0]["locks_held"]


def test_condition_wait_keeps_held_stack_exact(armed_lockdep):
    cond = threading.Condition(threading.Lock())
    outer = threading.Lock()
    hits = []

    def waiter():
        with cond:
            cond.wait(timeout=5)
            # post-wait the lock is re-held: a nested acquire must
            # record the cond -> outer edge from a correct held stack
            with outer:
                hits.append(1)

    t = threading.Thread(target=waiter)
    t.start()
    # let the waiter block, then notify under the condition: if wait()
    # leaked a stale held entry this acquisition would self-report
    import time

    time.sleep(0.05)
    with cond:
        cond.notify()
    t.join(10)
    assert hits == [1]
    rep = lockdep.report(armed_lockdep)
    assert rep["cycles"] == [] and rep["self_deadlocks"] == []


def test_threads_have_independent_held_stacks(armed_lockdep):
    a, b = threading.Lock(), threading.Lock()
    barrier = threading.Barrier(2, timeout=10)

    def hold(lock):
        with lock:
            barrier.wait()  # both locks held, in different threads
            barrier.wait()

    t1 = threading.Thread(target=hold, args=(a,))
    t2 = threading.Thread(target=hold, args=(b,))
    t1.start(), t2.start()
    t1.join(10), t2.join(10)
    # concurrent holders in separate threads are NOT an ordering edge
    assert lockdep.report(armed_lockdep)["edges"] == []


def test_find_cycles_unit():
    assert lockdep.find_cycles({(1, 2), (2, 3)}) == []
    assert lockdep.find_cycles({(1, 2), (2, 1), (3, 4)}) == [[1, 2]]
    assert lockdep.find_cycles({(5, 5)}) == [[5]]
    assert lockdep.find_cycles({(1, 2), (2, 3), (3, 1)}) == [[1, 2, 3]]


def test_write_merge_and_gate_cli(tmp_path, armed_lockdep, capsys):
    a, b = threading.Lock(), threading.Lock()
    with a:
        with b:
            pass
    p = lockdep.write_report(str(tmp_path / "lockdep_1.json"))
    rep = json.loads(open(p).read())
    assert rep["edges"] and rep["cycles"] == []
    # a second process's report carrying a cycle must fail the gate
    (tmp_path / "lockdep_2.json").write_text(json.dumps({
        "pid": 99, "locks": {}, "edges": [],
        "cycles": [{"locks": ["x.py:1", "y.py:2"], "keys": [1, 2]}],
        "self_deadlocks": [], "blocking_events": [], "blocking_total": 2,
    }))
    out = str(tmp_path / "merged.json")
    rc = lockdep.main(["--merge", str(tmp_path), "--out", out])
    capsys.readouterr()
    assert rc == 1
    merged = json.loads(open(out).read())
    assert merged["reports"] == 2 and len(merged["cycles"]) == 1
    assert merged["blocking_total"] == 2
    # clean reports gate green
    (tmp_path / "lockdep_2.json").unlink()
    assert lockdep.main(["--merge", str(tmp_path), "--out", out]) == 0
    capsys.readouterr()


def test_flush_report_never_writes_from_isolated_state(
        armed_lockdep, tmp_path, monkeypatch):
    # the worker-shutdown flush must not let a test universe scribble
    # artifacts the CI gate would merge
    monkeypatch.setenv("SRJT_LOCKDEP_DIR", str(tmp_path / "ld"))
    lockdep.flush_report()
    assert not (tmp_path / "ld").exists()


def test_cross_process_inversion_fails_merge_gate(tmp_path, capsys):
    # each process is acyclic per-instance, but tier A took X before Y
    # and tier B took Y before X: only the merged SITE graph shows it
    def rep(frm, to):
        return {"pid": 1, "locks": {}, "cycles": [], "self_deadlocks": [],
                "blocking_events": [], "blocking_total": 0,
                "edges": [{"from": frm, "to": to, "from_key": 1,
                           "to_key": 2, "count": 1}]}
    (tmp_path / "lockdep_a.json").write_text(json.dumps(rep("x.py:1", "y.py:2")))
    (tmp_path / "lockdep_b.json").write_text(json.dumps(rep("y.py:2", "x.py:1")))
    merged = lockdep.merge_reports(str(tmp_path))
    assert merged["cycles"] == []  # no per-process cycle anywhere...
    assert len(merged["site_cycles"]) == 1  # ...but the inversion is real
    assert sorted(merged["site_cycles"][0]["locks"]) == ["x.py:1", "y.py:2"]
    assert lockdep.main(["--merge", str(tmp_path)]) == 1
    capsys.readouterr()
    # same-site self-edges are advisory, never a cycle
    (tmp_path / "lockdep_b.json").write_text(json.dumps(rep("x.py:1", "x.py:1")))
    merged = lockdep.merge_reports(str(tmp_path))
    assert merged["site_cycles"] == []
    assert merged["site_self_edges"] == ["x.py:1"]
    assert lockdep.main(["--merge", str(tmp_path)]) == 0
    capsys.readouterr()


def test_gate_fails_on_missing_reports(tmp_path, capsys):
    assert lockdep.main(["--merge", str(tmp_path / "nope")]) == 2
    os.makedirs(tmp_path / "empty")
    assert lockdep.main(["--merge", str(tmp_path / "empty")]) == 2
    assert lockdep.main(
        ["--merge", str(tmp_path / "empty"), "--allow-empty"]) == 0
    capsys.readouterr()


def test_disarmed_package_leaves_threading_untouched():
    # this suite may run armed (premerge) or not; the invariant either
    # way: patched iff installed
    patched = threading.Lock is not lockdep._ORIG_LOCK
    assert patched == lockdep.is_installed()

import decimal

import numpy as np
import pytest

from spark_rapids_jni_tpu import columnar as c
from spark_rapids_jni_tpu.columnar import Column, Table


def test_fixed_width_roundtrip():
    vals = [1, -2, 3, None, 5]
    col = Column.from_pylist(vals, c.INT32)
    assert len(col) == 5
    assert col.null_count == 1
    assert col.to_pylist() == vals


@pytest.mark.parametrize(
    "dt", [c.INT8, c.INT16, c.INT32, c.INT64, c.UINT8, c.UINT64, c.FLOAT32, c.FLOAT64]
)
def test_all_fixed_types(dt):
    vals = [0, 1, 2, 3]
    col = Column.from_pylist(vals, dt)
    assert col.to_pylist() == [0, 1, 2, 3]


def test_bool8():
    col = Column.from_pylist([True, False, None, True], c.BOOL8)
    assert col.to_pylist() == [True, False, None, True]


def test_string_roundtrip():
    vals = ["hello", "", None, "wörld", "a" * 100]
    col = Column.from_pylist(vals, c.STRING)
    assert col.to_pylist() == vals
    assert col.null_count == 1


def test_decimal128_roundtrip():
    vals = [0, 1, -1, (1 << 126), -(1 << 126), None, 12345678901234567890123456789]
    col = Column.from_pylist(vals, c.decimal128(-2))
    assert col.to_pylist() == vals
    decs = col.to_decimal_pylist()
    assert decs[1] == decimal.Decimal("0.01")
    assert decs[2] == decimal.Decimal("-0.01")


def test_decimal_from_decimal_values():
    col = Column.from_pylist(
        [decimal.Decimal("1.23"), decimal.Decimal("-4.56")], c.decimal64(-2)
    )
    assert col.to_pylist() == [123, -456]


def test_table_basic():
    t = Table(
        [Column.from_pylist([1, 2], c.INT32), Column.from_pylist(["a", "b"], c.STRING)],
        names=["x", "s"],
    )
    assert t.num_rows == 2
    assert t.num_columns == 2
    assert t["s"].to_pylist() == ["a", "b"]
    assert t.to_pydict() == {"x": [1, 2], "s": ["a", "b"]}


def test_table_unequal_lengths_rejected():
    with pytest.raises(ValueError):
        Table([Column.from_pylist([1], c.INT32), Column.from_pylist([1, 2], c.INT32)])


def test_column_pytree():
    import jax

    col = Column.from_pylist([1, 2, None], c.INT32)
    leaves, treedef = jax.tree_util.tree_flatten(col)
    col2 = jax.tree_util.tree_unflatten(treedef, leaves)
    assert col2.to_pylist() == [1, 2, None]


def test_from_numpy():
    arr = np.arange(10, dtype=np.int64)
    col = Column.from_numpy(arr)
    assert col.dtype == c.INT64
    assert col.to_pylist() == list(range(10))

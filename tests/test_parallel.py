"""Parallel tier tests on the virtual 8-device CPU mesh.

Exercises mesh construction, executor binding, the hash partitioner,
the all_to_all bucket exchange (rows land on their hash shard), and the
fully-distributed GROUP BY SUM against a pandas oracle.
"""

import numpy as np
import pandas as pd
import pytest

import spark_rapids_jni_tpu  # noqa: F401
import jax
import jax.numpy as jnp

from spark_rapids_jni_tpu.columnar import dtype as dt
from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.parallel import device, mesh as mesh_mod, shuffle
from spark_rapids_jni_tpu.parallel.distributed import (
    distributed_groupby_sum,
    shard_groupby_sum,
)


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) == 8, "conftest must force the 8-device CPU mesh"
    return mesh_mod.make_mesh({"data": 8})


def test_make_mesh_shapes(mesh8):
    assert mesh8.shape["data"] == 8
    m2 = mesh_mod.make_mesh({"dcn": 2, "data": 4})
    assert m2.shape == {"dcn": 2, "data": 4}
    with pytest.raises(ValueError, match="devices"):
        mesh_mod.make_mesh({"data": 3})


def test_executor_binding():
    d0 = device.device_for_executor(0)
    d1 = device.device_for_executor(1)
    assert d0 != d1
    with device.bind_executor(3) as dev:
        x = jnp.zeros((4,))
        assert x.devices() == {dev}


def test_hash_partition_contiguous(rng):
    t = Table(
        [Column.from_pylist([int(x) for x in rng.integers(0, 50, 300)], dt.INT64)],
        ["k"],
    )
    out, offsets = shuffle.hash_partition(t, 4, ["k"])
    from spark_rapids_jni_tpu.ops.hashing import hash_partition_map

    parts = np.asarray(hash_partition_map([out.column("k")], 4))
    assert (np.diff(parts) >= 0).all()  # contiguous partitions
    assert offsets[0] == 0 and len(offsets) == 4


def test_all_to_all_rows_land_on_dest_shard(mesh8, rng):
    n = 8 * 64
    vals = jnp.asarray(rng.integers(0, 1_000_000, n), dtype=jnp.int64)
    dest = jnp.asarray(rng.integers(0, 8, n), dtype=jnp.int32)
    sh = mesh_mod.row_sharding(mesh8)
    vals_s = jax.device_put(vals, sh)
    dest_s = jax.device_put(dest, sh)

    (recv,), mask, overflow = shuffle.all_to_all_exchange([vals_s], dest_s, mesh8)
    assert not bool(np.asarray(overflow).any())

    # reshape global result to [shard, src, capacity]
    cap = 64
    r = np.asarray(recv).reshape(8, 8, cap)
    m = np.asarray(mask).reshape(8, 8, cap)
    got_per_shard = [sorted(r[s][m[s]].tolist()) for s in range(8)]
    expect_per_shard = [
        sorted(np.asarray(vals)[np.asarray(dest) == s].tolist()) for s in range(8)
    ]
    assert got_per_shard == expect_per_shard


def test_exchange_overflow_raises_retryable(mesh8):
    # VERDICT r3 item 8: a skewed destination exceeding a caller-chosen
    # capacity must ESCALATE, never hand back silently truncated data
    import pytest

    from spark_rapids_jni_tpu.utils.errors import RetryableError

    n = 8 * 8
    vals = jnp.arange(n, dtype=jnp.int64)
    dest = jnp.zeros((n,), jnp.int32)  # everything to shard 0
    sh = mesh_mod.row_sharding(mesh8)
    with pytest.raises(RetryableError):
        shuffle.all_to_all_exchange(
            [jax.device_put(vals, sh)], jax.device_put(dest, sh), mesh8, capacity=4
        )
    # capacity-managing callers opt into the flag contract explicitly
    (recv,), mask, overflow = shuffle.all_to_all_exchange(
        [jax.device_put(vals, sh)], jax.device_put(dest, sh), mesh8,
        capacity=4, on_overflow="flag",
    )
    assert bool(np.asarray(overflow).any())
    # retrying at the escalated capacity succeeds with every row intact
    (recv,), mask, overflow = shuffle.all_to_all_exchange(
        [jax.device_put(vals, sh)], jax.device_put(dest, sh), mesh8, capacity=n
    )
    assert not bool(np.asarray(overflow).any())
    got = sorted(np.asarray(recv)[np.asarray(mask)].tolist())
    assert got == list(range(n))


def test_shard_groupby_sum_static():
    keys = jnp.asarray([5, 3, 5, 3, 9, 5, 0], jnp.int64)
    vals = jnp.asarray([1, 2, 3, 4, 5, 6, 100], jnp.int64)
    present = jnp.asarray([1, 1, 1, 1, 1, 1, 0], bool)
    k, s, valid, ovf = shard_groupby_sum(keys, vals, present, capacity=8)
    k, s, valid = np.asarray(k), np.asarray(s), np.asarray(valid)
    got = dict(zip(k[valid].tolist(), s[valid].tolist()))
    assert got == {3: 6, 5: 10, 9: 5}
    assert not bool(ovf)


def test_distributed_groupby_sum_matches_pandas(mesh8, rng):
    n = 8 * 512
    keys = rng.integers(0, 97, n).astype(np.int64)
    vals = rng.integers(-1000, 1000, n).astype(np.int64)
    sh = mesh_mod.row_sharding(mesh8)
    k_s = jax.device_put(jnp.asarray(keys), sh)
    v_s = jax.device_put(jnp.asarray(vals), sh)

    gk, gs, overflow = distributed_groupby_sum(k_s, v_s, mesh8, capacity=512)
    assert not overflow

    exp = pd.DataFrame({"k": keys, "v": vals}).groupby("k")["v"].sum()
    got = dict(zip(gk.tolist(), gs.tolist()))
    assert got == exp.to_dict()


def test_hash_dest_parity_with_partitioner(rng):
    # the shard_map raw-array partitioner must route identically to the
    # Column-level hash_partition_map for both 4- and 8-byte keys
    from spark_rapids_jni_tpu.ops.hashing import hash_partition_map
    from spark_rapids_jni_tpu.parallel.distributed import _hash_dest

    for np_dt, d in ((np.int32, dt.INT32), (np.int64, dt.INT64)):
        keys = rng.integers(-1000, 1000, 200).astype(np_dt)
        want = np.asarray(hash_partition_map([Column(d, data=jnp.asarray(keys))], 8))
        got = np.asarray(_hash_dest(jnp.asarray(keys), 8))
        np.testing.assert_array_equal(got, want)


def test_shard_groupby_sum_max_key_sentinel():
    # a real key equal to iinfo.max must not collide with exchange padding
    big = np.iinfo(np.int64).max
    keys = jnp.asarray([big, 3, big, 3], jnp.int64)
    vals = jnp.asarray([1, 2, 4, 8], jnp.int64)
    present = jnp.asarray([1, 1, 0, 1], bool)
    k, s, valid, ovf = shard_groupby_sum(keys, vals, present, capacity=4)
    k, s, valid = np.asarray(k), np.asarray(s), np.asarray(valid)
    got = dict(zip(k[valid].tolist(), s[valid].tolist()))
    assert got == {3: 10, big: 1}
    assert not bool(ovf)


def test_shard_groupby_sum_int32_no_wrap():
    # integral sums accumulate in int64 (Spark semantics), not the input width
    keys = jnp.zeros((4,), jnp.int32)
    vals = jnp.full((4,), 2_000_000_000, jnp.int32)
    present = jnp.ones((4,), bool)
    k, s, valid, _ = shard_groupby_sum(keys, vals, present, capacity=2)
    assert int(np.asarray(s)[0]) == 8_000_000_000


def test_bucketize_overflow_drops_not_corrupts():
    # overflow rows must be dropped, never alias the last slot's occupant
    vals = jnp.asarray([10, 20, 30], jnp.int64)
    dest = jnp.zeros((3,), jnp.int32)
    buckets, mask, ovf = shuffle._bucketize(vals, dest, n_parts=2, capacity=2)
    assert bool(ovf)
    b, m = np.asarray(buckets), np.asarray(mask)
    assert m[0].sum() == 2 and m[1].sum() == 0
    assert sorted(b[0][m[0]].tolist()) == [10, 20]


def test_exchange_by_key_carries_validity(mesh8):
    n = 8 * 16
    keys = np.arange(n, dtype=np.int64) % 13
    vals = np.arange(n, dtype=np.int64)
    validity = (np.arange(n) % 3 != 0)
    t = Table(
        [
            Column(dt.INT64, data=jnp.asarray(keys)),
            Column(dt.INT64, data=jnp.asarray(vals), validity=jnp.asarray(validity)),
        ],
        ["k", "v"],
    )
    t_s = mesh_mod.shard_table_rows(t, mesh8)
    pairs, recv_mask, overflow = shuffle.exchange_by_key(t_s, ["k"], mesh8)
    assert not bool(np.asarray(overflow).any())
    (k_data, k_valid), (v_data, v_valid) = pairs
    assert k_valid is None and v_valid is not None
    m = np.asarray(recv_mask).reshape(-1)
    got = sorted(
        (int(v), bool(ok))
        for v, ok in zip(np.asarray(v_data).reshape(-1)[m], np.asarray(v_valid).reshape(-1)[m])
    )
    want = sorted((int(v), bool(ok)) for v, ok in zip(vals, validity))
    assert got == want


def test_distributed_groupby_keys_disjoint_across_shards(mesh8, rng):
    # each key must be reduced on exactly one shard: totals already checked,
    # here check no key appears in two shard partials
    n = 8 * 128
    keys = rng.integers(0, 31, n).astype(np.int64)
    vals = np.ones(n, np.int64)
    sh = mesh_mod.row_sharding(mesh8)
    gk, gs, _ = distributed_groupby_sum(
        jax.device_put(jnp.asarray(keys), sh), jax.device_put(jnp.asarray(vals), sh), mesh8
    )
    assert len(gk) == len(set(gk.tolist()))  # no duplicates after compaction


def test_distributed_groupby_multi_key_matches_pandas(mesh8, rng):
    from spark_rapids_jni_tpu.parallel.distributed import distributed_groupby_sum_multi

    n = 8 * 256
    k1 = rng.integers(0, 9, n).astype(np.int64)
    k2 = rng.integers(0, 7, n).astype(np.int32)
    vals = rng.integers(-1000, 1000, n).astype(np.int64)
    sh = mesh_mod.row_sharding(mesh8)
    put = lambda a: jax.device_put(jnp.asarray(a), sh)
    (g1, g2), sums, ovf = distributed_groupby_sum_multi([put(k1), put(k2)], put(vals), mesh8)
    assert not ovf

    exp = pd.DataFrame({"a": k1, "b": k2, "v": vals}).groupby(["a", "b"])["v"].sum()
    got = {(int(a), int(b)): int(s) for a, b, s in zip(g1, g2, sums)}
    assert got == {k: int(v) for k, v in exp.to_dict().items()}


def test_hash_dest_multi_parity_with_partitioner(rng):
    from spark_rapids_jni_tpu.ops.hashing import hash_partition_map
    from spark_rapids_jni_tpu.parallel.distributed import _hash_dest_multi

    k1 = rng.integers(-(10**9), 10**9, 200).astype(np.int64)
    k2 = rng.integers(-1000, 1000, 200).astype(np.int32)
    want = np.asarray(
        hash_partition_map(
            [Column(dt.INT64, data=jnp.asarray(k1)), Column(dt.INT32, data=jnp.asarray(k2))], 8
        )
    )
    got = np.asarray(_hash_dest_multi([jnp.asarray(k1), jnp.asarray(k2)], 8))
    np.testing.assert_array_equal(got, want)

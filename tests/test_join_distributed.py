"""Distributed hash join tests on the 8-device CPU mesh; pandas merge
is the oracle."""

import numpy as np
import pandas as pd
import pytest

import spark_rapids_jni_tpu  # noqa: F401
import jax
import jax.numpy as jnp

from spark_rapids_jni_tpu.parallel import mesh as mesh_mod
from spark_rapids_jni_tpu.parallel.join_distributed import (
    distributed_inner_join,
    shard_join_pairs,
)


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) == 8
    return mesh_mod.make_mesh({"data": 8})


def test_shard_join_pairs_basic():
    lk = jnp.asarray([1, 2, 3, 2], jnp.int64)
    lp = jnp.asarray([1, 1, 1, 1], bool)
    rk = jnp.asarray([2, 9, 2, 1], jnp.int64)
    rp = jnp.asarray([1, 1, 1, 1], bool)
    li, ri, pv, ovf = shard_join_pairs(lk, lp, rk, rp, out_capacity=16)
    li, ri, pv = np.asarray(li), np.asarray(ri), np.asarray(pv)
    got = sorted((int(lk[a]), int(rk[b])) for a, b in zip(li[pv], ri[pv]))
    # 1 matches once; each left 2 matches right rows {0, 2}; 3 matches none
    assert got == [(1, 1), (2, 2), (2, 2), (2, 2), (2, 2)]
    assert not bool(ovf)


def test_shard_join_pairs_absent_and_empty_runs():
    lk = jnp.asarray([5, 5, 7], jnp.int64)
    lp = jnp.asarray([1, 0, 1], bool)  # middle row is exchange padding
    rk = jnp.asarray([5, 7, 7], jnp.int64)
    rp = jnp.asarray([1, 1, 0], bool)  # last right row padding
    li, ri, pv, ovf = shard_join_pairs(lk, lp, rk, rp, out_capacity=8)
    li, ri, pv = np.asarray(li), np.asarray(ri), np.asarray(pv)
    got = sorted((int(lk[a]), int(rk[b])) for a, b in zip(li[pv], ri[pv]))
    assert got == [(5, 5), (7, 7)]
    assert not bool(ovf)


def test_shard_join_pairs_overflow_flag():
    lk = jnp.zeros((4,), jnp.int64)
    rk = jnp.zeros((4,), jnp.int64)
    ones = jnp.ones((4,), bool)
    _, _, pv, ovf = shard_join_pairs(lk, ones, rk, ones, out_capacity=8)
    assert bool(ovf)  # 16 pairs > 8
    assert int(np.asarray(pv).sum()) == 8  # capped, flagged


def test_distributed_join_matches_pandas(mesh8, rng):
    n = 8 * 128
    lk = rng.integers(0, 50, n).astype(np.int64)
    lv = rng.integers(0, 1000, n).astype(np.int64)
    rk = rng.integers(0, 50, n).astype(np.int64)
    rv = rng.integers(0, 1000, n).astype(np.int64)
    sh = mesh_mod.row_sharding(mesh8)
    put = lambda a: jax.device_put(jnp.asarray(a), sh)

    k, lvo, rvo, ovf = distributed_inner_join(
        put(lk), put(lv), put(rk), put(rv), mesh8, capacity=n, out_capacity=64 * n // 8
    )
    assert not ovf

    want = pd.DataFrame({"k": lk, "lv": lv}).merge(pd.DataFrame({"k": rk, "rv": rv}), on="k")
    got = sorted(zip(k.tolist(), lvo.tolist(), rvo.tolist()))
    expect = sorted(zip(want.k.tolist(), want.lv.tolist(), want.rv.tolist()))
    assert got == expect


def test_distributed_join_disjoint_keys(mesh8, rng):
    n = 8 * 32
    lk = np.arange(n, dtype=np.int64)
    rk = np.arange(n, 2 * n, dtype=np.int64)  # no overlap
    v = np.ones(n, np.int64)
    sh = mesh_mod.row_sharding(mesh8)
    put = lambda a: jax.device_put(jnp.asarray(a), sh)
    k, lvo, rvo, ovf = distributed_inner_join(put(lk), put(v), put(rk), put(v), mesh8)
    assert len(k) == 0 and not ovf

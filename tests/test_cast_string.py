"""String -> integer cast tests.

Ports the golden batteries from reference src/main/cpp/tests/
cast_string.cpp (Simple :37, Ansi :50, Overflow :107, Empty :233) and the
JNI-level assertions of CastStringsTest.java:35-99.
"""

import numpy as np
import pytest

import spark_rapids_jni_tpu  # noqa: F401  (enables x64)
from spark_rapids_jni_tpu.columnar import dtype as dt
from spark_rapids_jni_tpu.columnar import Column
from spark_rapids_jni_tpu.ops.cast_string import CastError, string_to_integer

SIGNED = [dt.INT8, dt.INT16, dt.INT32, dt.INT64]
UNSIGNED = [dt.UINT8, dt.UINT16, dt.UINT32, dt.UINT64]


def run(strings, d, ansi=False, in_validity=None):
    col = Column.from_pylist(strings, dt.STRING)
    if in_validity is not None:
        import jax.numpy as jnp

        col = Column(dt.STRING, validity=jnp.asarray(np.array(in_validity, bool)),
                     offsets=col.offsets, chars=col.chars)
    return string_to_integer(col, ansi, d)


def check(result, values, validity):
    got = result.to_pylist()
    expected = [v if ok else None for v, ok in zip(values, validity)]
    assert got == expected


ANSI_STRINGS = [
    "", "null", "+1", "-0", "4.2",
    "asdf", "98fe", "  00012", ".--e-37602.n", "\r\r\t\n11.12380",
    "-.2", ".3", ".", "+1.2", "\n123\n456\n",
    "1 2", "123", "", "1. 2", "+    7.6",
    "  12  ", "7.6.2", "15  ", "7  2  ", " 8.2  ",
    "3..14", "c0", "\r\r", "    ", "+\n",
]
ANSI_IN_VALIDITY = [0, 0] + [1] * 28


@pytest.mark.parametrize("d", SIGNED + UNSIGNED)
def test_simple(d):
    check(run(["1", "0", "42"], d), [1, 0, 42], [1, 1, 1])


@pytest.mark.parametrize("d", SIGNED)
def test_ansi_battery_signed(d):
    r = run(ANSI_STRINGS, d, ansi=False, in_validity=ANSI_IN_VALIDITY)
    check(
        r,
        [0, 0, 1, 0, 4, 0, 0, 12, 0, 11, 0, 0, 0, 1, 0,
         0, 123, 0, 0, 0, 12, 0, 15, 0, 8, 0, 0, 0, 0, 0],
        [0, 0, 1, 1, 1, 0, 0, 1, 0, 1, 1, 1, 1, 1, 0,
         0, 1, 0, 0, 0, 1, 0, 1, 0, 1, 0, 0, 0, 0, 0],
    )


@pytest.mark.parametrize("d", UNSIGNED)
def test_ansi_battery_unsigned(d):
    r = run(ANSI_STRINGS, d, ansi=False, in_validity=ANSI_IN_VALIDITY)
    check(
        r,
        [0, 0, 0, 0, 4, 0, 0, 12, 0, 11, 0, 0, 0, 0, 0,
         0, 123, 0, 0, 0, 12, 0, 15, 0, 8, 0, 0, 0, 0, 0],
        [0, 0, 0, 0, 1, 0, 0, 1, 0, 1, 0, 1, 1, 0, 0,
         0, 1, 0, 0, 0, 1, 0, 1, 0, 1, 0, 0, 0, 0, 0],
    )


@pytest.mark.parametrize("d,row,s", [(dt.INT32, 4, "4.2"), (dt.UINT32, 2, "+1")])
def test_ansi_throws_first_error(d, row, s):
    with pytest.raises(CastError) as ei:
        run(ANSI_STRINGS, d, ansi=True, in_validity=ANSI_IN_VALIDITY)
    assert ei.value.row_with_error == row
    assert ei.value.string_with_error == s


OVERFLOW_STRINGS = [
    "127", "128", "-128", "-129", "255", "256", "32767", "32768", "-32768",
    "-32769", "65525", "65536", "2147483647", "2147483648", "-2147483648",
    "-2147483649", "4294967295", "4294967296", "-9223372036854775808",
    "-9223372036854775809", "9223372036854775807", "9223372036854775808",
    "18446744073709551615", "18446744073709551616",
]

OVERFLOW_EXPECTED = {
    dt.TypeId.INT8: (
        [127, 0, -128] + [0] * 21,
        [1, 0, 1] + [0] * 21,
    ),
    dt.TypeId.UINT8: (
        [127, 128, 0, 0, 255] + [0] * 19,
        [1, 1, 0, 0, 1] + [0] * 19,
    ),
    dt.TypeId.INT16: (
        [127, 128, -128, -129, 255, 256, 32767, 0, -32768] + [0] * 15,
        [1, 1, 1, 1, 1, 1, 1, 0, 1] + [0] * 15,
    ),
    dt.TypeId.UINT16: (
        [127, 128, 0, 0, 255, 256, 32767, 32768, 0, 0, 65525] + [0] * 13,
        [1, 1, 0, 0, 1, 1, 1, 1, 0, 0, 1] + [0] * 13,
    ),
    dt.TypeId.INT32: (
        [127, 128, -128, -129, 255, 256, 32767, 32768, -32768, -32769, 65525,
         65536, 2147483647, 0, -2147483648] + [0] * 9,
        [1] * 13 + [0, 1] + [0] * 9,
    ),
    dt.TypeId.UINT32: (
        [127, 128, 0, 0, 255, 256, 32767, 32768, 0, 0, 65525, 65536,
         2147483647, 2147483648, 0, 0, 4294967295] + [0] * 7,
        [1, 1, 0, 0, 1, 1, 1, 1, 0, 0, 1, 1, 1, 1, 0, 0, 1] + [0] * 7,
    ),
    dt.TypeId.INT64: (
        [127, 128, -128, -129, 255, 256, 32767, 32768, -32768, -32769, 65525,
         65536, 2147483647, 2147483648, -2147483648, -2147483649, 4294967295,
         4294967296, -9223372036854775808, 0, 9223372036854775807, 0, 0, 0],
        [1] * 19 + [0, 1, 0, 0, 0],
    ),
    dt.TypeId.UINT64: (
        [127, 128, 0, 0, 255, 256, 32767, 32768, 0, 0, 65525, 65536,
         2147483647, 2147483648, 0, 0, 4294967295, 4294967296, 0, 0,
         9223372036854775807, 9223372036854775808, 18446744073709551615, 0],
        [1, 1, 0, 0, 1, 1, 1, 1, 0, 0, 1, 1, 1, 1, 0, 0, 1, 1, 0, 0, 1, 1, 1, 0],
    ),
}


@pytest.mark.parametrize("d", SIGNED + UNSIGNED)
def test_overflow(d):
    values, validity = OVERFLOW_EXPECTED[d.id]
    check(run(OVERFLOW_STRINGS, d), values, validity)


@pytest.mark.parametrize("d", [dt.INT32, dt.UINT64])
def test_empty(d):
    r = run([], d)
    assert len(r) == 0
    assert r.dtype.id == d.id


def test_incoming_nulls_not_ansi_errors():
    # rows that were already null must not trigger ANSI errors
    r = run(["1", "bad", "3"], dt.INT32, ansi=True, in_validity=[1, 0, 1])
    check(r, [1, 0, 3], [1, 0, 1])

"""String operator tier tests, Python str methods as the oracle."""

import numpy as np
import pytest

import spark_rapids_jni_tpu  # noqa: F401
from spark_rapids_jni_tpu.columnar import Column
from spark_rapids_jni_tpu.columnar import dtype as dt
from spark_rapids_jni_tpu.ops import strings as ss

SAMPLES = ["hello", "", "World", "MiXeD Case 123", "  padded  ", "a", "xyzzy plugh", None, "Zz"]


def col(vals=SAMPLES):
    return Column.from_pylist(vals, dt.STRING)


def got_strings(c):
    out = []
    offs = np.asarray(c.offsets)
    chars = np.asarray(c.chars).tobytes()
    valid = None if c.validity is None else np.asarray(c.validity)
    for i in range(len(offs) - 1):
        if valid is not None and not valid[i]:
            out.append(None)
        else:
            out.append(chars[offs[i] : offs[i + 1]].decode())
    return out


def oracle(fn):
    return [None if s is None else fn(s) for s in SAMPLES]


def test_length():
    out = ss.length(col())
    want = [None if s is None else len(s) for s in SAMPLES]
    data = np.asarray(out.data)
    for i, w in enumerate(want):
        if w is not None:
            assert data[i] == w


def test_upper_lower():
    assert got_strings(ss.upper(col())) == oracle(str.upper)
    assert got_strings(ss.lower(col())) == oracle(str.lower)


@pytest.mark.parametrize(
    "start,slen",
    [(1, 3), (2, None), (0, 2), (-3, 2), (-100, None), (5, 100), (100, 5), (-10, 3), (-6, 3)],
)
def test_substring(start, slen):
    out = ss.substring(col(), start, slen)

    def py_sub(s):
        # Spark UTF8String.substringSQL: window computed pre-clamp, so a
        # negative start spends its length budget before the string
        if start > 0:
            b0 = start - 1
        elif start == 0:
            b0 = 0
        else:
            b0 = len(s) + start
        e0 = len(s) if slen is None else b0 + max(slen, 0)
        b = min(max(b0, 0), len(s))
        e = min(max(e0, 0), len(s))
        return s[b:e] if e > b else ""

    assert got_strings(out) == oracle(py_sub)


def test_concat_with_separator():
    a = Column.from_pylist(["x", "hello", "", None], dt.STRING)
    b = Column.from_pylist(["y", "world", "z", "q"], dt.STRING)
    out = ss.concat([a, b], b"--")
    assert got_strings(out) == ["x--y", "hello--world", "--z", None]


def test_concat_no_separator():
    a = Column.from_pylist(["ab", ""], dt.STRING)
    b = Column.from_pylist(["cd", "ef"], dt.STRING)
    assert got_strings(ss.concat([a, b])) == ["abcd", "ef"]


def test_concat_ws_skips_nulls():
    # Spark concat_ws: null inputs are skipped (no separator slot), and
    # the result is never null for a non-null separator.
    a = Column.from_pylist(["x", None, "", None], dt.STRING)
    b = Column.from_pylist(["y", "mid", None, None], dt.STRING)
    c = Column.from_pylist(["z", "end", "tail", None], dt.STRING)
    out = ss.concat_ws([a, b, c], b"-")
    assert got_strings(out) == ["x-y-z", "mid-end", "-tail", ""]
    # same inputs under concat semantics: any null row nulls the output
    out2 = ss.concat([a, b, c], b"-")
    assert got_strings(out2) == ["x-y-z", None, None, None]


@pytest.mark.parametrize("pat", [b"l", b"Case", b"", b"zz", b"notthere", b"xyzzy plugh!"])
def test_contains(pat):
    out = ss.contains(col(), pat)
    want = oracle(lambda s: pat.decode() in s)
    data = np.asarray(out.data).astype(bool)
    for i, w in enumerate(want):
        if w is not None:
            assert bool(data[i]) == w, (i, pat)


@pytest.mark.parametrize("pat", [b"he", b"", b"World", b"  "])
def test_startswith_endswith(pat):
    sw = np.asarray(ss.startswith(col(), pat).data).astype(bool)
    ew = np.asarray(ss.endswith(col(), pat).data).astype(bool)
    want_s = oracle(lambda s: s.startswith(pat.decode()))
    want_e = oracle(lambda s: s.endswith(pat.decode()))
    for i in range(len(SAMPLES)):
        if want_s[i] is not None:
            assert bool(sw[i]) == want_s[i]
            assert bool(ew[i]) == want_e[i]


def test_strip():
    vals = ["  hi  ", "nospace", "   ", "", " x", "y ", None]
    out = ss.strip(Column.from_pylist(vals, dt.STRING))
    assert got_strings(out) == [None if v is None else v.strip(" ") for v in vals]


def test_empty_column():
    c = Column.from_pylist([], dt.STRING)
    assert got_strings(ss.upper(c)) == []
    assert got_strings(ss.substring(c, 1, 2)) == []

"""End-to-end chaos tier (ISSUE 1 acceptance): a distributed pipeline
(hash_partition -> exchange_by_key -> groupby aggregate) runs under an
injected fault storm — retryable faults at 30%, delay faults included —
and must complete THROUGH the retry orchestrator with results
bit-identical to the fault-free run. Sidecar supervision: injected
fatal faults / a killed worker degrade to the in-process host-CPU
engine within the configured deadline — no hang, no silent drop.

ci/premerge.sh runs this file with SRJT_FAULTINJ_CONFIG pointing at
ci/chaos_storm.json (the env-file activation path); standalone runs
fall back to the same profile configured programmatically.
"""

import os
import struct
import time

import numpy as np
import pytest

import spark_rapids_jni_tpu  # noqa: F401
import jax
import jax.numpy as jnp

from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.columnar import dtype as dt
from spark_rapids_jni_tpu.utils import errors, faultinj, knobs, retry

# the premerge storm profile: retryable faults at 30% on every pipeline
# stage, an injected-latency fault on the all-to-all, `after`/`ramp`
# scheduling in the mix. ONE source of truth — standalone runs load the
# same file premerge points SRJT_FAULTINJ_CONFIG at, so the two paths
# cannot drift.
_STORM_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "ci", "chaos_storm.json",
)


@pytest.fixture(autouse=True)
def _clean_state():
    faultinj.disable()
    retry.disable()
    retry.reset_stats()
    yield
    faultinj.disable()
    retry.disable()
    retry.reset_stats()


@pytest.fixture(scope="module")
def mesh8():
    from spark_rapids_jni_tpu.parallel import mesh as mesh_mod

    assert len(jax.devices()) == 8, "conftest must force the 8-device CPU mesh"
    return mesh_mod.make_mesh({"data": 8})


def _pipeline(keys, vi, vf_bits, mesh):
    """hash_partition -> exchange_by_key (capacity re-try) -> groupby
    agg; returns the key-sorted result table's raw bytes per column so
    parity checks are BIT-identical, not approx."""
    from spark_rapids_jni_tpu.ops.aggregate import groupby_aggregate
    from spark_rapids_jni_tpu.parallel import mesh as mesh_mod, shuffle

    t = Table(
        [
            Column(dt.INT64, data=jnp.asarray(keys)),
            Column(dt.INT64, data=jnp.asarray(vi)),
            Column(dt.FLOAT64, data=jnp.asarray(vf_bits)),
        ],
        ["k", "vi", "vf"],
    )
    part, _offsets = shuffle.hash_partition(t, mesh.shape["data"], ["k"])
    t_s = mesh_mod.shard_table_rows(part, mesh)
    # deliberately undersized capacity: the storm run AND the clean run
    # both exercise the geometric capacity re-try loop
    pairs, mask, overflow = shuffle.exchange_by_key(
        t_s, ["k"], mesh, capacity=8, on_overflow="retry"
    )
    assert not bool(np.asarray(overflow).any())
    m = np.asarray(mask).reshape(-1)
    k = np.asarray(pairs[0][0]).reshape(-1)[m]
    rvi = np.asarray(pairs[1][0]).reshape(-1)[m]
    rvf = np.asarray(pairs[2][0]).reshape(-1)[m]
    tr = Table(
        [
            Column(dt.INT64, data=jnp.asarray(k)),
            Column(dt.INT64, data=jnp.asarray(rvi)),
            Column(dt.FLOAT64, data=jnp.asarray(rvf)),
        ],
        ["k", "vi", "vf"],
    )
    out = groupby_aggregate(
        tr.select(["k"]), tr, [("vi", "sum"), ("vf", "sum"), ("vi", "count")]
    )
    # key-sorted output + exact (order-independent) aggregates ->
    # byte-level comparison is meaningful
    return {
        name: np.asarray(out.column(name).data).tobytes()
        for name in ["k", "vi_sum", "vf_sum", "vi_count"]
    }


def _inputs():
    rng = np.random.default_rng(424242)
    n = 8 * 64
    keys = rng.integers(0, 13, n).astype(np.int64)  # skewed: forces capacity re-try
    vi = rng.integers(-1000, 1000, n).astype(np.int64)
    vf_bits = rng.standard_normal(n).astype(np.float64).view(np.uint64)
    return keys, vi, vf_bits


def test_chaos_parity_retryable_storm(mesh8):
    """The acceptance pipeline: fault-free result == fault-storm result,
    bit for bit, with the orchestrator doing real work (retries and
    capacity escalations both observed). Three storm passes give the
    `after`/`ramp` schedules room to arm and the 30% rules enough
    dispatches to fire deterministically under the profile seed."""
    keys, vi, vf_bits = _inputs()
    clean = _pipeline(keys, vi, vf_bits, mesh8)
    retry.reset_stats()

    faultinj.configure_from_file(
        knobs.get_str("SRJT_FAULTINJ_CONFIG") or _STORM_PATH
    )
    if knobs.get_bool("SRJT_RETRY_ENABLED"):
        # premerge path: honor the operator's SRJT_RETRY_* env knobs
        # (ci/premerge.sh sets attempts/delays for the gate)
        arm = retry.enabled()
    else:
        arm = retry.enabled(max_attempts=10, base_delay_ms=1, max_delay_ms=8, seed=99)
    with arm:
        for _ in range(3):
            stormy = _pipeline(keys, vi, vf_bits, mesh8)
            assert stormy == clean  # bit-identical through the storm
    faultinj.disable()

    s = retry.stats()
    assert s["capacity_retries"] >= 1  # skew forced 8 -> ... escalation
    assert s["retries"] >= 1  # the storm actually fired and was recovered
    assert s["fatal"] == 0


def test_chaos_storm_without_orchestrator_fails(mesh8):
    """Counterfactual: the same storm with the orchestrator DISARMED
    kills the pipeline — proving the parity above is the orchestrator's
    doing, not storm under-configuration."""
    keys, vi, vf_bits = _inputs()
    faultinj.configure(
        {"seed": 7, "faults": {"hash_partition": {"type": "retryable", "percent": 100}}}
    )
    with pytest.raises(errors.RetryableError):
        _pipeline(keys, vi, vf_bits, mesh8)


def test_delay_storm_completes_identically(mesh8):
    """A pure latency storm (the wedged-kernel analog) must change
    timing only — results stay bit-identical with NO retries needed."""
    keys, vi, vf_bits = _inputs()
    clean = _pipeline(keys, vi, vf_bits, mesh8)
    faultinj.configure(
        {"seed": 5,
         "faults": {"*": {"type": "delay", "percent": 50, "delayMs": 2}}}
    )
    slow = _pipeline(keys, vi, vf_bits, mesh8)
    assert slow == clean


# ---------------------------------------------------------------------------
# sidecar connection supervision: degrade-to-host under fatal faults
# ---------------------------------------------------------------------------


class TestSidecarSupervision:
    """One spawned worker, three supervision scenarios in sequence:
    heartbeat, worker-side fatal fault -> host degrade (worker
    survives), chaos worker death mid-op -> host degrade (bounded by
    the deadline, no hang)."""

    @pytest.fixture(scope="class")
    def worker(self, tmp_path_factory):
        from spark_rapids_jni_tpu import sidecar

        tmp = tmp_path_factory.mktemp("chaos")
        cfg = tmp / "worker_faults.json"
        cfg.write_text(
            '{"faults": {"convert_to_rows": {"type": "fatal", "percent": 100}}}'
        )
        proc, sock = sidecar.spawn_worker(
            startup_timeout_s=120,
            env={
                "SRJT_FAULTINJ_CONFIG": str(cfg),
                # GROUPBY_SUM (op 1) murders the worker mid-op
                "SRJT_CHAOS_EXIT_ON_OP": "1",
            },
        )
        yield proc, sock
        if proc.poll() is None:
            proc.terminate()
        proc.wait(timeout=30)
        try:
            os.unlink(sock)
        except FileNotFoundError:
            pass

    def test_supervised_degrade_sequence(self, worker):
        from spark_rapids_jni_tpu import sidecar

        proc, sock = worker
        client = sidecar.SupervisedClient(sock, deadline_s=60, heartbeat_s=0.0)
        with client:
            # 1) heartbeat: PING round-trips and reports the backend
            assert client.ping() == "cpu"

            # 2) worker-side FATAL fault on convert_to_rows: the client
            # must NOT retry a fatal — it degrades straight to the
            # in-process host engine, and the worker stays up
            tbl = Table(
                [Column(dt.INT32, data=jnp.arange(64, dtype=jnp.int32))], ["a"]
            )
            payload = sidecar._write_table(tbl)
            t0 = time.monotonic()
            with retry.enabled(max_attempts=3, base_delay_ms=1):
                resp = client.call(sidecar.OP_CONVERT_TO_ROWS, payload)
            elapsed = time.monotonic() - t0
            host = sidecar._dispatch(sidecar.OP_CONVERT_TO_ROWS, payload, "cpu")
            assert resp == host  # host fallback produced the real result
            assert client.host_fallbacks == 1
            assert retry.stats()["retries"] == 0  # fatal: zero retries
            assert elapsed < 60  # bounded, no hang
            assert proc.poll() is None  # fatal fault != dead worker
            assert client.ping() == "cpu"  # connection still healthy

            # 3) chaos exit mid-op: the worker dies after consuming the
            # GROUPBY_SUM request; the client sees a dead transport,
            # retries against a dead socket, and degrades to host
            n, nk = 256, 17
            keys = (np.arange(n) % nk).astype(np.int64)
            vals = np.ones(n, np.float32)
            gp = (
                struct.pack("<IQ", nk, n) + keys.tobytes() + vals.tobytes()
            )
            t0 = time.monotonic()
            with retry.enabled(max_attempts=3, base_delay_ms=1):
                resp = client.call(sidecar.OP_GROUPBY_SUM_F32, gp)
            elapsed = time.monotonic() - t0
            sums = np.frombuffer(resp, np.float32, nk)
            counts = np.frombuffer(resp, np.int64, nk, 4 * nk)
            np.testing.assert_array_equal(counts, np.bincount(keys, minlength=nk))
            np.testing.assert_allclose(sums, np.bincount(keys, weights=vals,
                                                         minlength=nk), rtol=1e-6)
            assert client.host_fallbacks == 2
            assert elapsed < 120  # bounded by deadline x attempts, not a hang
            assert proc.wait(timeout=30) == 42  # the chaos _exit fired

    def test_request_deadline_fires(self, tmp_path):
        """Per-request deadline: a worker WEDGED by an injected delay
        fault (the new `delay` kind, exactly this scenario's tool)
        surfaces DEADLINE_EXCEEDED (retryable) at the client's deadline
        — never an indefinite block — and the desynced connection is
        closed for a fresh redial."""
        from spark_rapids_jni_tpu import sidecar

        cfg = tmp_path / "wedge.json"
        cfg.write_text(
            '{"faults": {"convert_to_rows": '
            '{"type": "delay", "percent": 100, "delayMs": 30000}}}'
        )
        proc, sock = sidecar.spawn_worker(
            startup_timeout_s=120, env={"SRJT_FAULTINJ_CONFIG": str(cfg)}
        )
        try:
            client = sidecar.SupervisedClient(sock, deadline_s=2.0, heartbeat_s=1e9)
            with client:
                assert client.ping() == "cpu"  # PING skips the wedged op
                tbl = Table(
                    [Column(dt.INT32, data=jnp.arange(8, dtype=jnp.int32))], ["a"]
                )
                payload = sidecar._write_table(tbl)
                t0 = time.monotonic()
                with pytest.raises(errors.RetryableError, match="DEADLINE_EXCEEDED"):
                    client.request(sidecar.OP_CONVERT_TO_ROWS, payload)
                elapsed = time.monotonic() - t0
                assert elapsed < 15  # the deadline fired, not the 30s wedge
                assert client._sock is None  # desync discipline: closed
        finally:
            proc.terminate()
            proc.wait(timeout=30)
            try:
                os.unlink(sock)
            except FileNotFoundError:
                pass

"""Tail-tolerant execution tier (ISSUE 9): gray-failure quarantine,
hedged dispatch, and adaptive timeouts.

Fast-tier coverage of the three defenses:

- HEALTH SCORER + QUARANTINE: streaming quantiles off the log2
  histograms, per-worker EWMA/jitter, strike-based gray detection,
  background probes, K-clean reinstatement, quarantine-aware routing
  (_pick preference + all-gray fallback) and the notify-backed
  quarantine-aware wait_healthy.
- HEDGED DISPATCH: the both-responses race (winner counted once, the
  loser's region released, no double completion), the global budget,
  and the memgov/shed pressure disarm.
- ADAPTIVE TIMEOUTS: clamp bounds (never above the static knob, never
  below the floor, cold classes keep the knob) at both the helper and
  the SupervisedClient.

The in-process worker trick is the test_sidecar_pool one: real
protocol traffic served by sidecar._handle_conn threads in this
process — no jax child boot per test. The real-pool gray storm runs in
ci/premerge.sh's gray tier (bench_serve --gray against 3 spawned
workers).
"""

import os
import signal
import socket
import struct
import tempfile
import threading
import time

import numpy as np
import pytest

import spark_rapids_jni_tpu  # noqa: F401
from spark_rapids_jni_tpu import serve, sidecar, sidecar_pool
from spark_rapids_jni_tpu.utils import deadline as deadline_mod
from spark_rapids_jni_tpu.utils import faultinj, knobs, metrics, retry
from spark_rapids_jni_tpu.utils.errors import (
    FatalDeviceError,
    Overloaded,
    RetryableError,
)


def _counter(name):
    return metrics.registry().value(name)


def _scrub_worker_namespace():
    """Same two-way scrub as test_sidecar_pool: the in-proc worker's
    always-on counters must not type-clash with sidecar.worker.* gauges
    folded by other suite files (and vice versa)."""
    reg = metrics.registry()
    with reg._lock:
        for name in list(reg._metrics):
            if name.startswith("sidecar.worker."):
                del reg._metrics[name]


@pytest.fixture(autouse=True)
def _clean_state():
    faultinj.disable()
    retry.disable()
    retry.reset_stats()
    _scrub_worker_namespace()
    yield
    faultinj.disable()
    retry.disable()
    retry.reset_stats()
    _scrub_worker_namespace()


class _InProcWorker:
    """Minimal Popen-shaped in-process worker (the test_sidecar_pool
    trick): sidecar._handle_conn served from threads in this process."""

    def __init__(self):
        self.sock_path = tempfile.mktemp(prefix="srjt-tail-") + ".sock"
        self.pid = os.getpid()
        self.returncode = None
        self._conns = []
        self._srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._srv.bind(self.sock_path)
        self._srv.listen(8)
        self._t = threading.Thread(target=self._accept_loop, daemon=True)
        self._t.start()

    def _accept_loop(self):
        while True:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            self._conns.append(conn)

            def _serve(c=conn):
                try:
                    sidecar._handle_conn(c, "cpu", lambda: None)
                except OSError:
                    pass

            threading.Thread(target=_serve, daemon=True).start()

    def poll(self):
        return self.returncode

    def wait(self, timeout=None):
        return self.returncode if self.returncode is not None else 0

    def terminate(self):
        self.kill()

    def kill(self):
        if self.returncode is None:
            self.returncode = -signal.SIGKILL
        try:
            self._srv.close()
        except OSError:
            pass
        for c in self._conns:
            try:
                c.close()
            except OSError:
                pass
        try:
            os.unlink(self.sock_path)
        except OSError:
            pass


def _inproc_spawn(startup_timeout_s=None, env=None):
    w = _InProcWorker()
    return w, w.sock_path


def _groupby_payload(n=600, k=16, seed=3):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, k, n).astype(np.int64)
    vals = rng.standard_normal(n).astype(np.float32)
    return struct.pack("<IQ", k, n) + keys.tobytes() + vals.tobytes()


def _seed_hist(name, values_us):
    h = metrics.registry().histogram(name)
    for v in values_us:
        h.record(v)
    return h


# ---------------------------------------------------------------------------
# metrics primitives: quantile + KeyedEwma
# ---------------------------------------------------------------------------


class TestHistogramQuantile:
    def test_empty_is_none(self):
        assert metrics.Histogram().quantile(0.5) is None

    def test_bad_q_raises(self):
        with pytest.raises(ValueError):
            metrics.Histogram().quantile(1.5)

    def test_single_value(self):
        h = metrics.Histogram()
        h.record(42)
        assert h.quantile(0.0) == 42
        assert h.quantile(0.5) == 42
        assert h.quantile(1.0) == 42

    def test_bounds_and_monotonicity(self):
        h = metrics.Histogram()
        vals = [1, 3, 7, 20, 100, 900, 5000] * 20
        for v in vals:
            h.record(v)
        qs = [h.quantile(q) for q in (0.0, 0.25, 0.5, 0.9, 0.99, 1.0)]
        assert qs[0] == 1 and qs[-1] == 5000
        assert all(a <= b for a, b in zip(qs, qs[1:]))

    def test_log2_factor_accuracy(self):
        # a quantile read off log2 buckets is good to a factor of 2
        h = metrics.Histogram()
        for _ in range(1000):
            h.record(1000)
        for _ in range(10):
            h.record(64000)
        p50 = h.quantile(0.5)
        assert 500 <= p50 <= 2000
        p999 = h.quantile(0.999)
        assert p999 >= 32000

    def test_bucket_zero(self):
        h = metrics.Histogram()
        for _ in range(10):
            h.record(0)
        assert h.quantile(0.5) == 0

    def test_single_bucket_mass(self):
        # ISSUE 11 satellite: every sample in ONE log2 bucket ([16,32))
        # — interpolation must stay inside the bucket AND inside the
        # recorded min/max for every q, including the exact edges
        h = metrics.Histogram()
        for v in (17, 19, 23, 29, 31) * 40:
            h.record(v)
        for q in (0.0, 0.01, 0.25, 0.5, 0.75, 0.99, 1.0):
            est = h.quantile(q)
            assert 17 <= est <= 31, (q, est)
        assert h.quantile(0.0) == 17
        assert h.quantile(1.0) == 31

    def test_q0_q1_exact_bounds(self):
        # q=0 is the recorded min and q=1 the recorded max, never an
        # interpolated bucket edge — the clamp contract callers of
        # p0/p100 rely on
        h = metrics.Histogram()
        for v in (5, 100, 3000, 70000):
            h.record(v)
        assert h.quantile(0.0) == 5
        assert h.quantile(1.0) == 70000


class TestKeyedEwma:
    def test_update_and_jitter(self):
        e = metrics.KeyedEwma(alpha=0.5)
        assert e.update("a", 10.0) == 10.0
        assert e.update("a", 20.0) == 15.0
        assert e.jitter("a") == 5.0  # 0.5 * |20-10|
        assert e.count("a") == 2
        assert e.get("missing", -1) == -1

    def test_bounded_eviction_is_lru(self):
        e = metrics.KeyedEwma(max_keys=2)
        e.update("a", 1.0)
        e.update("b", 2.0)
        e.update("a", 1.0)  # refresh a; b is now the oldest
        e.update("c", 3.0)  # evicts b
        assert len(e) == 2
        assert e.get("b") is None
        assert e.get("a") is not None and e.get("c") is not None

    def test_validation(self):
        with pytest.raises(ValueError):
            metrics.KeyedEwma(alpha=0.0)
        with pytest.raises(ValueError):
            metrics.KeyedEwma(max_keys=0)

    def test_concurrent_update_during_lru_eviction(self):
        # ISSUE 11 satellite: updates that force LRU evictions while
        # other threads read/snapshot the same map — the bound must
        # hold, nothing may raise, and every surviving entry must be a
        # coherent [ewma, jitter, count, seq] record. The
        # race-detector-armed variant (tracked map, vector clocks)
        # lives in tests/test_races.py.
        import threading

        e = metrics.KeyedEwma(alpha=0.4, max_keys=8)
        stop = threading.Event()
        errors = []

        def churn(base):
            try:
                for i in range(400):
                    e.update(f"{base}.{i % 16}", float(i))
            except Exception as exc:  # pragma: no cover - failure path
                errors.append(exc)

        def read():
            try:
                while not stop.is_set():
                    e.get("a.0")
                    e.jitter("b.1")
                    snap = e.snapshot()
                    for rec in snap.values():
                        assert set(rec) == {"ewma", "jitter", "count"}
            except Exception as exc:  # pragma: no cover - failure path
                errors.append(exc)

        ts = [threading.Thread(target=churn, args=(b,)) for b in "abc"]
        r = threading.Thread(target=read)
        r.start()
        for t in ts:
            t.start()
        for t in ts:
            t.join(20)
        stop.set()
        r.join(20)
        assert not errors
        assert len(e) <= 8


# ---------------------------------------------------------------------------
# adaptive timeouts
# ---------------------------------------------------------------------------


class TestAdaptiveTimeout:
    def test_cold_class_keeps_static(self, monkeypatch):
        monkeypatch.setenv("SRJT_ADAPTIVE_TIMEOUT_MIN_SAMPLES", "40")
        _seed_hist("test.adapt.cold_us", [100] * 10)
        budget, clamped = metrics.adaptive_timeout_s("test.adapt.cold_us", 600.0)
        assert budget == 600.0 and not clamped

    def test_warm_fast_class_clamps_to_floor(self, monkeypatch):
        monkeypatch.setenv("SRJT_ADAPTIVE_TIMEOUT_MIN_SAMPLES", "40")
        monkeypatch.setenv("SRJT_ADAPTIVE_TIMEOUT_FLOOR_S", "2.0")
        _seed_hist("test.adapt.fast_us", [1000] * 50)  # 1 ms op
        budget, clamped = metrics.adaptive_timeout_s("test.adapt.fast_us", 600.0)
        assert budget == 2.0 and clamped

    def test_never_exceeds_static(self, monkeypatch):
        monkeypatch.setenv("SRJT_ADAPTIVE_TIMEOUT_MIN_SAMPLES", "40")
        _seed_hist("test.adapt.slow_us", [int(500e6)] * 50)  # 500 s op
        budget, clamped = metrics.adaptive_timeout_s("test.adapt.slow_us", 600.0)
        assert budget == 600.0 and not clamped

    def test_disabled_keeps_static(self, monkeypatch):
        monkeypatch.setenv("SRJT_ADAPTIVE_TIMEOUT_ENABLED", "0")
        _seed_hist("test.adapt.off_us", [1000] * 200)
        budget, clamped = metrics.adaptive_timeout_s("test.adapt.off_us", 600.0)
        assert budget == 600.0 and not clamped

    def test_client_op_budget_counts_clamps(self, monkeypatch):
        monkeypatch.setenv("SRJT_ADAPTIVE_TIMEOUT_MIN_SAMPLES", "40")
        monkeypatch.setenv("SRJT_ADAPTIVE_TIMEOUT_FLOOR_S", "1.0")
        c = sidecar.SupervisedClient("/nonexistent.sock", deadline_s=600.0,
                                     heartbeat_s=1e9)
        name = f"sidecar.op_lat_us.{sidecar.op_name(sidecar.OP_ZORDER)}"
        _seed_hist(name, [2000] * 60)  # 2 ms q99 -> 8 ms, floored to 1 s
        before = _counter("sidecar.adaptive_timeout_clamps")
        budget = c._op_budget_s(sidecar.OP_ZORDER)
        assert budget == 1.0
        assert _counter("sidecar.adaptive_timeout_clamps") == before + 1
        # cold classes keep the static knob and count nothing
        budget = c._op_budget_s(sidecar.OP_DECIMAL128_DIV)
        assert budget == 600.0
        assert _counter("sidecar.adaptive_timeout_clamps") == before + 1

    def test_request_budget_never_exceeds_remaining_deadline(self, monkeypatch):
        """The adaptive budget composes UNDER the query budget: a
        nearly-dead deadline scope bounds the socket deadline below
        whatever the quantiles say (the old clamp, unchanged)."""
        monkeypatch.setenv("SRJT_ADAPTIVE_TIMEOUT_MIN_SAMPLES", "1")
        monkeypatch.setenv("SRJT_ADAPTIVE_TIMEOUT_FLOOR_S", "50.0")
        w = _InProcWorker()
        try:
            c = sidecar.SupervisedClient(w.sock_path, deadline_s=600.0,
                                         heartbeat_s=1e9)
            name = f"sidecar.op_lat_us.{sidecar.op_name(sidecar.OP_PING)}"
            _seed_hist(name, [100] * 10)
            t0 = time.monotonic()
            with deadline_mod.scope(0.25):
                # a live worker answers instantly; the point is the
                # request cannot park past the 0.25 s budget even
                # though the adaptive floor is 50 s
                assert c.ping() == "cpu"
            assert time.monotonic() - t0 < 5.0
            c.close()
        finally:
            w.kill()


# ---------------------------------------------------------------------------
# faultinj per-worker rule keys
# ---------------------------------------------------------------------------


class TestFaultinjWorkerKeys:
    CFG = {
        "seed": 7,
        "faults": {
            "myop@w1": {"type": "fatal", "percent": 100},
            "myop": {"type": "retryable", "percent": 100},
            "fam.*@w1": {"type": "fatal", "percent": 100},
            "fam.*": {"type": "retryable", "percent": 100},
            "*@w1": {"type": "fatal", "percent": 100},
            "*": {"type": "retryable", "percent": 100},
        },
    }

    def test_tagged_process_prefers_worker_keys(self, monkeypatch):
        monkeypatch.setenv("SRJT_FAULTINJ_WORKER", "w1")
        faultinj.configure(self.CFG)
        with pytest.raises(FatalDeviceError):
            faultinj.maybe_inject("myop")  # exact@tag beats exact
        with pytest.raises(FatalDeviceError):
            faultinj.maybe_inject("fam.x")  # prefix@tag beats prefix
        with pytest.raises(FatalDeviceError):
            faultinj.maybe_inject("other")  # *@tag beats *

    def test_untagged_process_ignores_worker_keys(self, monkeypatch):
        monkeypatch.delenv("SRJT_FAULTINJ_WORKER", raising=False)
        faultinj.configure(self.CFG)
        with pytest.raises(RetryableError):
            faultinj.maybe_inject("myop")
        with pytest.raises(RetryableError):
            faultinj.maybe_inject("fam.x")
        with pytest.raises(RetryableError):
            faultinj.maybe_inject("other")

    def test_foreign_tag_never_matches(self, monkeypatch):
        monkeypatch.setenv("SRJT_FAULTINJ_WORKER", "w2")
        faultinj.configure({
            "seed": 7,
            "faults": {"gray@w1": {"type": "fatal", "percent": 100}},
        })
        faultinj.maybe_inject("gray")  # no rule for w2: clean dispatch

    def test_single_gray_worker_profile_shape(self, monkeypatch):
        """The chaos_gray.json shape: a delay ramp keyed to one worker
        fires there and ONLY there."""
        cfg = {
            "seed": 7,
            "faults": {
                "sidecar.worker.PING@w1": {
                    "type": "fatal", "percent": 100,
                },
            },
        }
        monkeypatch.setenv("SRJT_FAULTINJ_WORKER", "w0")
        faultinj.configure(cfg)
        faultinj.maybe_inject("sidecar.worker.PING")  # clean on w0
        monkeypatch.setenv("SRJT_FAULTINJ_WORKER", "w1")
        faultinj.configure(cfg)
        with pytest.raises(FatalDeviceError):
            faultinj.maybe_inject("sidecar.worker.PING")

    def test_pool_stamps_worker_tags(self):
        seen = {}

        def spawn_fn(startup_timeout_s=None, env=None):
            w = _InProcWorker()
            seen[len(seen)] = dict(env or {})
            return w, w.sock_path

        pool = sidecar_pool.SidecarPool(
            size=2, deadline_s=10, heartbeat_s=1e9, spawn_fn=spawn_fn
        )
        try:
            tags = sorted(e.get("SRJT_FAULTINJ_WORKER") for e in seen.values())
            assert tags == ["w0", "w1"]
        finally:
            pool.shutdown()


# ---------------------------------------------------------------------------
# gray-failure quarantine
# ---------------------------------------------------------------------------


def _warm_op(name_us, fast_us=1000, n=40):
    metrics.reset()
    _seed_hist(name_us, [fast_us] * n)


class TestQuarantine:
    def test_strikes_quarantine_and_probe_reinstates(self, monkeypatch):
        # the first probe sleeps a whole second, leaving a quiet window
        # for the quarantined-state asserts; the live-read knob then
        # drops to 50 ms for a fast reinstatement run
        monkeypatch.setenv("SRJT_QUARANTINE_PROBE_INTERVAL_S", "1.0")
        monkeypatch.setenv("SRJT_QUARANTINE_STRIKES", "3")
        pool = sidecar_pool.SidecarPool(
            size=2, deadline_s=10, heartbeat_s=1e9, spawn_fn=_inproc_spawn
        )
        try:
            name = f"sidecar.op_lat_us.{sidecar.op_name(sidecar.OP_PING)}"
            _warm_op(name)  # pool-wide p50 ~ 1 ms
            w1 = pool._workers[1]
            for _ in range(3):  # 3 samples at 100x the p50
                pool._note_latency(w1, sidecar.OP_PING, 0.1)
            assert w1.quarantined
            assert pool.routable_count() == 1
            assert _counter("sidecar.pool.quarantines") == 1
            assert metrics.registry().value("sidecar.pool.quarantined") == 1
            # routing prefers the healthy peer exclusively
            for _ in range(8):
                assert pool._pick() is pool._workers[0]
            # quarantine-aware wait_healthy: a gray worker is unhealthy
            assert pool.wait_healthy(timeout_s=0.2) is False
            monkeypatch.setenv("SRJT_QUARANTINE_PROBE_INTERVAL_S", "0.05")
            # the in-proc worker answers probes in microseconds: after
            # K clean probes the slot is reinstated (notify-backed wait
            # wakes the instant it happens)
            assert pool.wait_healthy(timeout_s=10.0) is True
            assert not w1.quarantined
            assert w1.strikes == 0
            assert _counter("sidecar.pool.reinstatements") == 1
            assert _counter("sidecar.pool.quarantine_probes") >= 3
            picked = {pool._pick().wid for _ in range(4)}
            assert picked == {0, 1}  # back in the rotation
        finally:
            pool.shutdown()

    def test_dirty_probes_hold_quarantine(self, monkeypatch):
        monkeypatch.setenv("SRJT_QUARANTINE_PROBE_INTERVAL_S", "0.05")
        monkeypatch.setenv("SRJT_QUARANTINE_STRIKES", "2")
        # a probe threshold no real round-trip can meet: every probe is
        # dirty, the clean run never starts
        monkeypatch.setenv("SRJT_QUARANTINE_PROBE_SLOW_S", "0.000000001")
        pool = sidecar_pool.SidecarPool(
            size=2, deadline_s=10, heartbeat_s=1e9, spawn_fn=_inproc_spawn
        )
        try:
            name = f"sidecar.op_lat_us.{sidecar.op_name(sidecar.OP_PING)}"
            _warm_op(name)
            w1 = pool._workers[1]
            for _ in range(2):
                pool._note_latency(w1, sidecar.OP_PING, 0.1)
            assert w1.quarantined
            deadline = time.monotonic() + 0.6
            while time.monotonic() < deadline:
                time.sleep(0.05)
            assert w1.quarantined  # probes ran, none was clean
            assert _counter("sidecar.pool.quarantine_probes") >= 2
            assert w1.clean_probes == 0
            # restoring a reachable threshold lets the run complete
            monkeypatch.setenv("SRJT_QUARANTINE_PROBE_SLOW_S", "0.25")
            assert pool.wait_healthy(timeout_s=10.0) is True
        finally:
            pool.shutdown()

    def test_timeouts_strike_even_cold(self, monkeypatch):
        """A request timeout is the unambiguous slow signal: it strikes
        even before the op class has any baseline samples."""
        monkeypatch.setenv("SRJT_QUARANTINE_STRIKES", "2")
        monkeypatch.setenv("SRJT_QUARANTINE_PROBE_INTERVAL_S", "5")
        pool = sidecar_pool.SidecarPool(
            size=2, deadline_s=10, heartbeat_s=1e9, spawn_fn=_inproc_spawn
        )
        try:
            metrics.reset()
            w0 = pool._workers[0]
            pool._note_latency(w0, sidecar.OP_ZORDER, 10.0, timed_out=True)
            assert not w0.quarantined
            pool._note_latency(w0, sidecar.OP_ZORDER, 10.0, timed_out=True)
            assert w0.quarantined
        finally:
            pool.shutdown()

    def test_clean_samples_pay_strikes_back(self, monkeypatch):
        monkeypatch.setenv("SRJT_QUARANTINE_STRIKES", "3")
        pool = sidecar_pool.SidecarPool(
            size=2, deadline_s=10, heartbeat_s=1e9, spawn_fn=_inproc_spawn
        )
        try:
            name = f"sidecar.op_lat_us.{sidecar.op_name(sidecar.OP_PING)}"
            _warm_op(name)
            w1 = pool._workers[1]
            pool._note_latency(w1, sidecar.OP_PING, 0.1)
            pool._note_latency(w1, sidecar.OP_PING, 0.1)
            assert w1.strikes == 2
            pool._note_latency(w1, sidecar.OP_PING, 0.001)  # clean
            assert w1.strikes == 1
            pool._note_latency(w1, sidecar.OP_PING, 0.1)
            assert not w1.quarantined  # 2 < 3: the flap never tripped
        finally:
            pool.shutdown()

    def test_all_quarantined_falls_back_not_dark(self, monkeypatch):
        """Degraded routing beats a dark pool: with every live worker
        gray, _pick falls back (counted) and calls still complete."""
        monkeypatch.setenv("SRJT_QUARANTINE_PROBE_INTERVAL_S", "60")
        pool = sidecar_pool.SidecarPool(
            size=2, deadline_s=10, heartbeat_s=1e9, spawn_fn=_inproc_spawn
        )
        try:
            metrics.reset()
            with pool._lock:
                for w in pool._workers:
                    pool._quarantine_locked(w, "test")
            assert pool.routable_count() == 0
            assert pool.live_count() == 2
            before = _counter("sidecar.pool.quarantine_fallbacks")
            assert pool._pick() is not None
            assert _counter("sidecar.pool.quarantine_fallbacks") == before + 1
            assert pool.call(sidecar.OP_PING) == b"cpu"
        finally:
            pool.shutdown()

    def test_death_clears_quarantine_state(self, monkeypatch):
        monkeypatch.setenv("SRJT_QUARANTINE_PROBE_INTERVAL_S", "60")
        pool = sidecar_pool.SidecarPool(
            size=2, deadline_s=10, heartbeat_s=1e9, spawn_fn=_inproc_spawn
        )
        try:
            metrics.reset()
            w1 = pool._workers[1]
            with pool._lock:
                pool._quarantine_locked(w1, "test")
            w1.proc.kill()
            pool._on_worker_failure(w1, RetryableError("UNAVAILABLE"))
            # gray -> dead: the respawned slot starts with a clean record
            assert not w1.quarantined
            assert metrics.registry().value("sidecar.pool.quarantined") == 0
            assert pool.wait_healthy(timeout_s=10.0) is True
        finally:
            pool.shutdown()


# ---------------------------------------------------------------------------
# hedged dispatch
# ---------------------------------------------------------------------------


class TestHedgedDispatch:
    def test_both_responses_arrive_winner_counted_once(self, monkeypatch):
        """The hedge race where BOTH legs answer: exactly one response
        reaches the caller, the loser's region is released, counters
        reconcile (one launched, at most one won, one cancelled)."""
        monkeypatch.setenv("SRJT_HEDGE_BUDGET_PCT", "100")
        pool = sidecar_pool.SidecarPool(
            size=2, deadline_s=20, heartbeat_s=1e9, spawn_fn=_inproc_spawn
        )
        try:
            metrics.reset()
            payload = _groupby_payload()
            want = sidecar._dispatch(sidecar.OP_GROUPBY_SUM_F32, payload, "cpu")
            # both in-proc workers serve the op ~50 ms slow, so both
            # legs are in flight when the race settles
            faultinj.configure({
                "seed": 11,
                "faults": {
                    "sidecar.worker.GROUPBY_SUM_F32": {
                        "type": "delay", "percent": 100, "delayMs": 60,
                    },
                },
            })
            # force the hedge trigger: fire the duplicate immediately
            monkeypatch.setattr(
                pool, "_hedge_delay_s", lambda op, primary: 0.001
            )
            got = pool.call_arena(sidecar.OP_GROUPBY_SUM_F32, payload)
            assert got == want
            assert _counter("sidecar.pool.hedges_launched") == 1
            assert _counter("sidecar.pool.hedges_cancelled") == 1
            assert _counter("sidecar.pool.hedges_won") in (0, 1)
            # the loser leg (bounded by the 60 ms injected delay)
            # releases its distinct region: no leases survive
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if pool._slab is not None and pool._slab.outstanding == 0:
                    break
                time.sleep(0.02)
            assert pool._slab.outstanding == 0
            assert _counter("sidecar.pool.region_leaks") == 0
        finally:
            pool.shutdown()

    def test_hedge_wins_when_primary_is_slow(self, monkeypatch):
        """The tail-defense contract: one gray worker's slow leg loses
        to the hedge on the healthy peer, and the answer is correct."""
        monkeypatch.setenv("SRJT_HEDGE_BUDGET_PCT", "100")
        monkeypatch.setenv("SRJT_FAULTINJ_WORKER", "w9")  # inert tag
        pool = sidecar_pool.SidecarPool(
            size=2, deadline_s=20, heartbeat_s=1e9, spawn_fn=_inproc_spawn
        )
        try:
            metrics.reset()
            payload = _groupby_payload()
            want = sidecar._dispatch(sidecar.OP_GROUPBY_SUM_F32, payload, "cpu")
            # the first GROUPBY dispatch hangs 2 s (the in-proc workers
            # share this process's injector, so the budget of 1 means
            # only the primary leg pays it; the hedge runs clean)
            faultinj.configure({
                "seed": 11,
                "faults": {
                    "sidecar.worker.GROUPBY_SUM_F32": {
                        "type": "delay", "percent": 100, "delayMs": 2000,
                        "interceptionCount": 1,
                    },
                },
            })
            monkeypatch.setattr(
                pool, "_hedge_delay_s", lambda op, primary: 0.05
            )
            t0 = time.monotonic()
            got = pool.call_arena(sidecar.OP_GROUPBY_SUM_F32, payload)
            elapsed = time.monotonic() - t0
            assert got == want
            assert _counter("sidecar.pool.hedges_launched") == 1
            assert _counter("sidecar.pool.hedges_won") == 1
            assert elapsed < 1.5, (
                f"hedge should beat the 2 s straggler, took {elapsed:.2f}s"
            )
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if pool._slab.outstanding == 0:
                    break
                time.sleep(0.05)
            assert pool._slab.outstanding == 0
        finally:
            pool.shutdown()

    def test_budget_arithmetic(self, monkeypatch):
        monkeypatch.setenv("SRJT_HEDGE_BUDGET_PCT", "10")
        pool = sidecar_pool.SidecarPool(
            size=2, deadline_s=10, heartbeat_s=1e9, spawn_fn=_inproc_spawn
        )
        try:
            metrics.reset()
            reg = metrics.registry()
            reg.counter("sidecar.pool.calls").inc(100)
            reg.counter("sidecar.pool.hedges_launched").inc(9)
            assert pool._hedge_budget_ok()  # 10th hedge of 100 calls: at budget
            reg.counter("sidecar.pool.hedges_launched").inc(1)
            assert not pool._hedge_budget_ok()  # 11th would exceed 10%
        finally:
            pool.shutdown()

    def test_disarmed_under_memgov_pressure(self, monkeypatch):
        """The acceptance contract: hedging drops to zero while memgov
        pressure is active — metrics-asserted via the suppression
        counter, with the trigger conditions otherwise satisfied."""
        monkeypatch.setenv("SRJT_HEDGE_MIN_SAMPLES", "10")
        pool = sidecar_pool.SidecarPool(
            size=2, deadline_s=10, heartbeat_s=1e9, spawn_fn=_inproc_spawn
        )
        try:
            metrics.reset()
            name = f"sidecar.op_lat_us.{sidecar.op_name(sidecar.OP_PING)}"
            _seed_hist(name, [1000] * 20)
            w0 = pool._workers[0]
            # warm + healthy peer: hedging would arm...
            assert pool._hedge_delay_s(sidecar.OP_PING, w0) is not None
            # ...until injected memgov pressure disarms it
            from spark_rapids_jni_tpu import memgov

            monkeypatch.setattr(memgov, "is_enabled", lambda: True)
            metrics.registry().gauge("memgov.queue_depth").set(1)
            before = _counter("sidecar.pool.hedges_suppressed")
            assert pool._hedge_delay_s(sidecar.OP_PING, w0) is None
            assert _counter("sidecar.pool.hedges_suppressed") == before + 1
            launched = _counter("sidecar.pool.hedges_launched")
            assert pool.call(sidecar.OP_PING) == b"cpu"
            assert _counter("sidecar.pool.hedges_launched") == launched
        finally:
            pool.shutdown()

    def test_disarmed_inside_shed_window(self, monkeypatch):
        monkeypatch.setenv("SRJT_HEDGE_MIN_SAMPLES", "10")
        monkeypatch.setenv("SRJT_HEDGE_SHED_WINDOW_S", "5.0")
        pool = sidecar_pool.SidecarPool(
            size=2, deadline_s=10, heartbeat_s=1e9, spawn_fn=_inproc_spawn
        )
        try:
            metrics.reset()
            name = f"sidecar.op_lat_us.{sidecar.op_name(sidecar.OP_PING)}"
            _seed_hist(name, [1000] * 20)
            w0 = pool._workers[0]
            reg = metrics.registry()
            reg.gauge("serve.last_shed_s").set(time.monotonic())
            assert pool._hedge_delay_s(sidecar.OP_PING, w0) is None
            # an old shed is outside the window: hedging re-arms
            reg.gauge("serve.last_shed_s").set(time.monotonic() - 60.0)
            assert pool._hedge_delay_s(sidecar.OP_PING, w0) is not None
        finally:
            pool.shutdown()

    def test_cold_class_and_single_worker_never_hedge(self, monkeypatch):
        monkeypatch.setenv("SRJT_HEDGE_MIN_SAMPLES", "10")
        pool = sidecar_pool.SidecarPool(
            size=1, deadline_s=10, heartbeat_s=1e9, spawn_fn=_inproc_spawn
        )
        try:
            metrics.reset()
            w0 = pool._workers[0]
            # single worker: no peer for the duplicate
            name = f"sidecar.op_lat_us.{sidecar.op_name(sidecar.OP_PING)}"
            _seed_hist(name, [1000] * 20)
            assert pool._hedge_delay_s(sidecar.OP_PING, w0) is None
        finally:
            pool.shutdown()
        pool = sidecar_pool.SidecarPool(
            size=2, deadline_s=10, heartbeat_s=1e9, spawn_fn=_inproc_spawn
        )
        try:
            metrics.reset()  # cold class: no samples at all
            w0 = pool._workers[0]
            assert pool._hedge_delay_s(sidecar.OP_ZORDER, w0) is None
        finally:
            pool.shutdown()


# ---------------------------------------------------------------------------
# quarantine-aware serving + stats plumbing
# ---------------------------------------------------------------------------


class TestServeQuarantineRouting:
    def test_all_gray_pool_sheds_device_only_work(self, monkeypatch):
        monkeypatch.setenv("SRJT_QUARANTINE_PROBE_INTERVAL_S", "60")
        pool = sidecar_pool.connect_pool(
            size=1, deadline_s=10, heartbeat_s=1e9, spawn_fn=_inproc_spawn
        )
        sched = serve.Scheduler(max_concurrent=1, name="tail-test")
        try:
            with pool._lock:
                pool._quarantine_locked(pool._workers[0], "test")
            with pytest.raises(Overloaded) as ei:
                sched.submit(lambda: 1, host_eligible=False)
            assert ei.value.cause == "quarantine"
            assert _counter("serve.shed.quarantine") >= 1
            # host-eligible work keeps flowing through the same pool
            assert sched.submit(lambda: 41 + 1).result(10) == 42
            # reinstatement restores device-only admission
            with pool._lock:
                pool._reinstate_locked(pool._workers[0])
            assert sched.submit(lambda: 7, host_eligible=False).result(10) == 7
        finally:
            sched.shutdown(drain=False, timeout_s=10)
            sidecar_pool.shutdown_pool()

    def test_shed_stamps_hedge_disarm_gauge(self):
        sched = serve.Scheduler(max_concurrent=1, queue_depth=1,
                                name="tail-stamp")
        try:
            faultinj.configure({
                "seed": 3,
                "faults": {"serve.admit": {"type": "reject", "percent": 100,
                                            "interceptionCount": 1}},
            })
            with pytest.raises(Overloaded):
                sched.submit(lambda: 1)
            stamp = metrics.registry().value("serve.last_shed_s", None)
            assert stamp is not None
            assert time.monotonic() - stamp < 10.0
        finally:
            faultinj.disable()
            sched.shutdown(drain=False, timeout_s=10)


class TestStatsSections:
    def test_report_sections_present(self):
        from spark_rapids_jni_tpu import runtime

        rep = runtime.stats_report()
        assert set(rep["health"]) >= {
            "quarantines", "reinstatements", "probes", "quarantined_now",
        }
        assert set(rep["hedge"]) >= {
            "launched", "won", "cancelled", "suppressed",
            "adaptive_timeout_clamps",
        }
        stage = metrics.stage_report("tail")
        assert "health" in stage and "hedge" in stage
        assert "adaptive_timeout_clamps" in stage["hedge"]

    def test_knobs_declared(self):
        for k in (
            "SRJT_QUARANTINE_ENABLED", "SRJT_QUARANTINE_SLOW_FACTOR",
            "SRJT_QUARANTINE_STRIKES", "SRJT_QUARANTINE_MIN_SAMPLES",
            "SRJT_QUARANTINE_PROBES", "SRJT_QUARANTINE_PROBE_INTERVAL_S",
            "SRJT_QUARANTINE_PROBE_SLOW_S", "SRJT_HEDGE_ENABLED",
            "SRJT_HEDGE_BUDGET_PCT", "SRJT_HEDGE_MIN_SAMPLES",
            "SRJT_HEDGE_MIN_DELAY_S", "SRJT_HEDGE_SHED_WINDOW_S",
            "SRJT_ADAPTIVE_TIMEOUT_ENABLED", "SRJT_ADAPTIVE_TIMEOUT_MULT",
            "SRJT_ADAPTIVE_TIMEOUT_FLOOR_S",
            "SRJT_ADAPTIVE_TIMEOUT_MIN_SAMPLES", "SRJT_FAULTINJ_WORKER",
        ):
            assert knobs.is_declared(k), k

#!/usr/bin/env bash
# Nightly gate: the FULL hermetic suite (premerge runs the fast tier
# only) plus the driver entries. Run from the repo root.
set -euo pipefail

cmake -S native -B native/build -G Ninja
ninja -C native/build

python -m pytest tests/ -q

JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python __graft_entry__.py

python benchmarks/microbench.py --bench groupby --rows 65536 --reps 3

#!/usr/bin/env bash
# JVM test tier (SURVEY §4.2 analog of the reference's surefire JUnit
# run, reference pom.xml:480-534): compile the Java API + tests and run
# each test main against the real libsrjt.so over a live JNI boundary.
#
# Requires a JDK (javac + java). The CI image this repo is built on has
# none, so the script degrades to an explicit SKIP — the hermetic proxy
# for this tier is the ctypes suite (tests/test_native_columnar.py),
# which drives the same C ABI the JNI veneer marshals into. Run this on
# any JDK host to execute the Java tier for real:
#
#   ci/java-tests.sh            # build native (with real jni.h) + run
set -euo pipefail
cd "$(dirname "$0")/.."

if ! command -v javac >/dev/null 2>&1 || ! command -v java >/dev/null 2>&1; then
  echo "java-tests: SKIP (no JDK on PATH; the ctypes tier covers the C ABI)"
  exit 0
fi

# 1) native lib built against the REAL JNI headers
JAVA_BIN=$(command -v javac)
JAVA_HOME_GUESS=$(dirname "$(dirname "$(readlink -f "$JAVA_BIN")")")
export JAVA_HOME=${JAVA_HOME:-$JAVA_HOME_GUESS}
cmake -S native -B native/build-jni -G Ninja -DSRJT_BUILD_JNI=ON >/dev/null
ninja -C native/build-jni >/dev/null

# 2) compile API + tests
OUT=build/java-tests
rm -rf "$OUT" && mkdir -p "$OUT/classes"
find java/src/main/java java/src/test/java -name '*.java' > "$OUT/sources.txt"
javac -d "$OUT/classes" @"$OUT/sources.txt"

# 3) run each suite main (fresh JVM per suite, like surefire's fork —
# a poisoned native state cannot contaminate the next suite; the
# reference isolates CudaFatalTest the same way, pom.xml:523-532)
export SRJT_NATIVE_LIB="$PWD/native/build-jni/libsrjt.so"
FAIL=0
for suite in RowConversionTest CastStringsTest DecimalUtilsTest ZOrderTest ScalarTest; do
  echo "== $suite"
  if ! java -cp "$OUT/classes" "com.nvidia.spark.rapids.jni.$suite"; then
    FAIL=1
  fi
done
exit $FAIL

#!/usr/bin/env bash
# Artifact packaging: the pom.xml copy-native-libs / jar analog
# (reference pom.xml:443-474) for the TPU build.
#
# Produces dist/spark-rapids-jni-tpu-<rev>.tar.gz laid out exactly like
# the reference jar's runtime expectations:
#
#   classes/                      # Java sources, compiled HERE when a
#                                 # JDK is present (jar layout), else
#                                 # shipped as source for the consumer
#                                 # build to compile
#   <os.arch>/<os.name>/libsrjt.so   # native lib at the path
#                                 # NativeDepsLoader probes (same
#                                 # ${os.arch}/${os.name} convention as
#                                 # the reference's copy-native-libs)
#   python/spark_rapids_jni_tpu/  # the TPU compute path (wheel-style)
#   build-info.properties         # provenance (ci/build-info)
#
# A JDK is optional: with javac+jar on PATH the script emits a real
# .jar next to the tarball; without one (this CI image) it stages the
# same layout and the tarball is the deployable unit. See PACKAGING.md.
set -euo pipefail
cd "$(dirname "$0")/.."

REV=$(git rev-parse --short HEAD 2>/dev/null || echo dev)
STAGE=dist/stage
rm -rf dist && mkdir -p "$STAGE"

# 1) native lib
cmake -S native -B native/build -G Ninja >/dev/null
ninja -C native/build >/dev/null
ARCH=$(uname -m)
OS=$(uname -s)
mkdir -p "$STAGE/$ARCH/$OS"
cp native/build/libsrjt.so "$STAGE/$ARCH/$OS/"

# 2) Java contract classes: compile if a JDK exists, else ship source
mkdir -p "$STAGE/classes"
if command -v javac >/dev/null 2>&1; then
  find java/src/main/java -name '*.java' > /tmp/srjt_sources.txt
  javac -d "$STAGE/classes" @/tmp/srjt_sources.txt
  JAR_READY=1
else
  cp -r java/src/main/java/* "$STAGE/classes/"
  JAR_READY=0
fi

# 3) python package (the compute path the JNI layer drives)
mkdir -p "$STAGE/python"
cp -r spark_rapids_jni_tpu "$STAGE/python/"
find "$STAGE/python" -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null || true

# 4) provenance
bash build/build-info > "$STAGE/build-info.properties"

# 5) emit artifacts
mkdir -p dist
tar -C "$STAGE" -czf "dist/spark-rapids-jni-tpu-$REV.tar.gz" .
if [ "$JAR_READY" = 1 ] && command -v jar >/dev/null 2>&1; then
  (cd "$STAGE" && jar cf "../spark-rapids-jni-tpu-$REV.jar" .)
fi
echo "packaged: $(ls dist/*.tar.gz dist/*.jar 2>/dev/null | tr '\n' ' ')"

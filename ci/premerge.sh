#!/usr/bin/env bash
# Premerge gate (reference ci/premerge-build.sh analog): native build,
# hermetic test suite on the virtual CPU mesh, driver entry compile
# check, and a bench smoke. Run from the repo root.
set -euo pipefail

cmake -S native -B native/build -G Ninja
ninja -C native/build

# JNI tier executed without a JVM: fabricated-JNIEnv harness drives the
# Java_* entry points in libsrjt_jnitest.so (engine + veneer, the
# single-.so jar shape) end to end — marshalling, CastException
# construction, handle registry, leak accounting (VERDICT r4 item 2)
python - <<'EOF'
import pyarrow as pa, pyarrow.parquet as pq
t = pa.table({"a": pa.array(range(1000), pa.int32()),
              "b": pa.array([f"s{i}" for i in range(1000)]),
              "c": pa.array([float(i) for i in range(1000)])})
pq.write_table(t, "/tmp/srjt_jni_harness.parquet")
EOF
./native/build/jni_harness ./native/build/libsrjt_jnitest.so \
  /tmp/srjt_jni_harness.parquet 1000

# correctness-tooling tier (ISSUEs 7 + 11, layer 1): srjt-lint AND the
# srjt-race static pass must be clean — undeclared/undocumented SRJT
# knobs (now including tests/ and benchmarks/), taxonomy-violating
# raises, unsuppressed broad excepts, stub-pattern regressions, blind
# blocking calls, mixed guarded/unguarded attribute access (SRJT008),
# check-then-act splits (SRJT009), and bare mutable-global mutation
# (SRJT010) all fail the merge here, before any test runs. Findings
# are archived as SARIF next to the other artifacts (exit-code parity
# with text mode, so the gate semantics are unchanged).
mkdir -p artifacts
python -m spark_rapids_jni_tpu.analysis.lint --format=sarif --out artifacts/srjt_lint.sarif
python -m spark_rapids_jni_tpu.analysis.races --format=sarif --out artifacts/srjt_race.sarif

# srjt-plancheck tier (ISSUE 15): the plan-IR verifier over EVERY
# checked-in plan (well-formedness, every fired rewrite's
# translation-validation obligation discharged, per-stage estimate
# monotonicity), then the fixed-seed random-plan differential fuzzer —
# >= 50 generated plans run rewrite->compile->execute against a
# direct-plan-interpretation oracle, any mismatch bisected to the
# first semantics-breaking rewrite. The gate is artifact-based:
# artifacts/plan_verify.jsonl must carry every registry plan with
# zero violations AND the fuzz record with zero mismatches;
# artifacts/plancheck.sarif is archived next to the other SARIF.
rm -f artifacts/plan_verify.jsonl
JAX_PLATFORMS=cpu python -m spark_rapids_jni_tpu.analysis.plancheck \
  --format=sarif --out artifacts/plancheck.sarif \
  --report artifacts/plan_verify.jsonl
timeout -k 10 600 env JAX_PLATFORMS=cpu \
  python -m spark_rapids_jni_tpu.analysis.planfuzz \
  --report artifacts/plan_verify.jsonl
python - <<'EOF'
import json
rows = [json.loads(s) for s in open("artifacts/plan_verify.jsonl")]
plans = {r["query"]: r for r in rows if r["kind"] == "plan"}
fuzz = [r for r in rows if r["kind"] == "fuzz"]
from spark_rapids_jni_tpu.models.tpcds_plans import PLAN_QUERIES
want = set(PLAN_QUERIES) | {"q3", "q55", "q3x4", "q55x4"}
missing = sorted(want - set(plans))
assert not missing, f"plans missing from plan_verify.jsonl: {missing}"
bad = {q: r for q, r in plans.items() if r["violations"]}
assert not bad, f"plancheck violations: {bad}"
assert all(r["obligations"] >= 1 for r in plans.values()), \
    "a checked-in plan emitted no rewrite obligations (prune at minimum)"
assert fuzz, "no fuzz record archived"
total = sum(r["plans"] for r in fuzz)
assert total >= 50, f"fuzz smoke covered only {total} plans (need >= 50)"
assert all(r["mismatches"] == 0 and r["violations"] == 0 for r in fuzz), fuzz
fired = {}
for r in fuzz:
    for rule, n in r["rewrites"].items():
        fired[rule] = fired.get(rule, 0) + n
# srjt-cbo (ISSUE 19): the fixed-seed corpus deterministically drives
# the cost-based search — all three enumeration rules must fire (and
# therefore discharge) across the fuzzed plans
for rule in ("cbo_reorder_joins", "cbo_build_side", "cbo_join_strategy"):
    assert fired.get(rule), f"CBO rule {rule} never fired across the fuzz corpus"
print(f"plancheck tier: {len(plans)} plans verified "
      f"({sum(r['obligations'] for r in plans.values())} obligations "
      f"discharged), {total} fuzzed plans / 0 mismatches, "
      f"fuzz rewrites {fired} -> artifacts/plan_verify.jsonl")
EOF

# fast tier: the measured heavy tail (tests/conftest.py _SLOW_TESTS)
# runs nightly (ci/nightly.sh); this keeps the premerge gate usable on
# a 1-core box (VERDICT r3 item 9). SRJT_LOCKDEP=1 (ISSUE 7, layer 2)
# arms the lock-order instrumentation so every concurrency test in the
# tier doubles as a deadlock probe, and SRJT_RACE=1 (ISSUE 11, layer 2)
# rides the same shim: per-thread vector clocks over every
# lock/Event/Thread/Semaphore/Barrier edge, with the scheduler's
# tenant lanes, the pool's worker-health records and hedge budget, the
# memgov catalog map, and the metrics registry all tracked — an
# unordered access lands as race_pairs in the same per-process report
# and fails the same merge gate. The armed tier must stay within 1.5x
# its unarmed wall-clock (the shim is proportional to sync-op count,
# not data volume). Each process (incl. spawned sidecar workers, which
# inherit the env) drops artifacts/lockdep/lockdep_<pid>.json at exit,
# merged and gated after the chaos tiers.
rm -rf artifacts/lockdep
SRJT_LOCKDEP=1 SRJT_RACE=1 python -m pytest tests/ -q -m "not slow"

# robustness + observability tier: the chaos suite re-runs the
# end-to-end distributed pipeline under the storm profile (retryable +
# delay faults at 30%) with the retry orchestrator armed THROUGH the
# env knobs (the parity test honors SRJT_RETRY_* when
# SRJT_RETRY_ENABLED is set), asserting results bit-identical to
# fault-free runs — a retry/backoff/supervision regression fails
# premerge, not production (ISSUE 1). ISSUE 2 runs the same storm with
# the METRICS subsystem armed: the metrics suite's chaos-integration
# tests assert counter values match injected fault counts bit-exactly,
# and the structured JSON-lines event log is archived as a premerge
# artifact next to the BENCH rows.
mkdir -p artifacts
rm -f artifacts/chaos_metrics.jsonl
SRJT_LOCKDEP=1 SRJT_FAULTINJ_CONFIG=ci/chaos_storm.json SRJT_RETRY_ENABLED=1 \
  SRJT_RETRY_MAX_ATTEMPTS=10 SRJT_RETRY_BASE_DELAY_MS=1 \
  SRJT_RETRY_MAX_DELAY_MS=8 SRJT_RETRY_SEED=99 \
  SRJT_METRICS_ENABLED=1 SRJT_METRICS_LOG=artifacts/chaos_metrics.jsonl \
  python -m pytest tests/test_chaos.py tests/test_metrics.py -q
# the event log must exist and parse as JSON lines (artifact contract)
python - <<'EOF'
import json, sys
lines = [json.loads(s) for s in open("artifacts/chaos_metrics.jsonl")]
assert lines, "chaos run produced no metrics events"
assert all("ts" in r and "event" in r for r in lines)
print(f"archived {len(lines)} metrics events -> artifacts/chaos_metrics.jsonl")
EOF
# deadline + circuit-breaker tier (ISSUE 3): the hang-storm profile
# wedges hash_partition for 30 s at a time — far past the tight
# SRJT_DEADLINE_SEC below — so every query must either complete or
# raise DeadlineExceeded within budget. The hard `timeout` wrapper IS
# the assertion that the subsystem works: a single uninterrupted hang
# (or a wedged/leaked worker) blows the harness ceiling and fails the
# gate. Runs the full deadline suite: budget propagation, backoff
# truncation, breaker open->half-open->closed, spawn reaping, and the
# storm acceptance test (which honors these env knobs).
timeout -k 10 600 env SRJT_LOCKDEP=1 SRJT_FAULTINJ_CONFIG=ci/chaos_hang.json \
  SRJT_DEADLINE_SEC=3 SRJT_RETRY_ENABLED=1 SRJT_RETRY_MAX_ATTEMPTS=10 \
  SRJT_RETRY_BASE_DELAY_MS=1 SRJT_RETRY_MAX_DELAY_MS=8 SRJT_RETRY_SEED=99 \
  SRJT_METRICS_ENABLED=1 \
  python -m pytest tests/test_deadline.py -q

# memory-governor tier (ISSUE 4): the full memgov suite under a TIGHT
# ambient device budget with metrics + the event log armed — admission
# FIFO/byte-exactness, spill round-trips, deadline-truncated waits, and
# the squeeze acceptance (spills + retry splits interleave, TPC-H q1
# bit-identical). The chaos test inside loads ci/chaos_memgov.json
# (spill_fail storm on the demotion choke point). Afterwards the
# archived event log must PROVE forced spills happened: nonzero
# memgov.spill volume is the artifact contract, mirroring the
# chaos_metrics.jsonl gate above.
rm -f artifacts/memgov_events.jsonl
SRJT_LOCKDEP=1 SRJT_DEVICE_MEMORY_BUDGET=400000 SRJT_SPILL_ENABLED=1 \
  SRJT_RETRY_ENABLED=0 \
  SRJT_METRICS_ENABLED=1 SRJT_METRICS_LOG=artifacts/memgov_events.jsonl \
  python -m pytest tests/test_memgov.py -q
python - <<'EOF'
import json
lines = [json.loads(s) for s in open("artifacts/memgov_events.jsonl")]
assert lines, "memgov tier produced no events"
spilled = sum(r.get("nbytes", 0) for r in lines if r["event"] == "memgov.spill")
assert spilled > 0, "low-budget tier forced no spills (memgov.spilled_bytes == 0)"
kinds = {r["event"] for r in lines}
assert "memgov.pressure" in kinds, "no pressure-loop events recorded"
print(f"archived {len(lines)} memgov events ({spilled} bytes spilled) "
      "-> artifacts/memgov_events.jsonl")
EOF

# out-of-core tier (srjt-ooc, ISSUE 18): the full ooc suite with the
# strategy armed and the ambient device budget PINCHED below the
# q1-shape working set — selection, verifier discharge of the
# partitioning rewrite, the >=4x-budget bit-identity acceptance, the
# ci/chaos_ooc.json storm on a real 2-worker pool (failed/corrupt
# partition spills + a kill -9 mid-partition), pin discipline against
# the pressure loop, and per-partition serve admission. The artifact
# gate reads the run reports every completed OOC run appends to
# SRJT_OOC_METRICS: degraded runs really streamed >1 spill-backed
# partition (partitions>1, spills>0) and the storm really resumed from
# a checkpoint (resumes>0) — with zero test failures (= zero wrong
# answers) above it. The BENCH row prices the degradation: an
# out-of-core pass over an in-core-feasible dataset must stay within
# 2x of the unconstrained wall (the row carries its own gate_max so
# the number and its bar travel together).
rm -f artifacts/ooc_metrics.jsonl artifacts/bench_ooc.jsonl
timeout -k 10 900 env SRJT_LOCKDEP=1 SRJT_OOC_ENABLED=1 \
  SRJT_DEVICE_MEMORY_BUDGET=36864 \
  SRJT_OOC_METRICS=artifacts/ooc_metrics.jsonl \
  python -m pytest tests/test_ooc.py -q
python bench.py --ooc | tee artifacts/bench_ooc.jsonl
python - <<'EOF'
import json
rows = [json.loads(s) for s in open("artifacts/ooc_metrics.jsonl")]
assert rows, "ooc tier produced no run reports"
assert all(r["ooc"] for r in rows)
assert any(r["partitions"] > 1 for r in rows), "no partitioned run recorded"
spills = sum(r["spills"] for r in rows)
assert spills > 0, "pinched-budget tier forced no partition spills"
resumes = sum(r["resumes"] for r in rows)
assert resumes > 0, "no partition resume recorded under the chaos storm"
bench = [json.loads(s) for s in open("artifacts/bench_ooc.jsonl")
         if s.strip()]
row = next(r for r in bench if r.get("metric") == "ooc_overhead")
assert row["raw"]["bit_identical"], "ooc bench diverged"
assert row["value"] <= row["gate_max"], (
    f"out-of-core overhead {row['value']}x exceeds the "
    f"{row['gate_max']}x degradation bar")
print(f"ooc tier: {len(rows)} degraded runs ({spills} spills, "
      f"{resumes} resumes) -> artifacts/ooc_metrics.jsonl; "
      f"ooc_overhead {row['value']}x (gate {row['gate_max']}x) "
      "-> artifacts/bench_ooc.jsonl")
EOF

# restart tier (srjt-durable, ISSUE 20): a child coordinator serves a
# journaled mixed-plan storm (journal + spill manifests + durable OOC
# checkpoints armed against shared dirs), checkpoints two of four OOC
# partitions, arms ci/chaos_restart.json — the next manifest write and
# the next journal append TORN mid-frame, what a kill -9 racing the
# disk produces — and SIGKILLs itself mid-storm. The recovered process
# (the bench parent) must replay the journal past the torn tail,
# answer every DONE query from its recorded digest (verified against
# a recomputed oracle's bits, zero re-executions), refuse to invent
# the torn submission, resubmit the surviving incomplete query through
# the rebind path, and resume the OOC query past the re-attached
# checkpoints — ooc.partition_resumes crossing PROCESSES. The artifact
# gate re-asserts the row's own verdict: replays/reattached/resumes
# all nonzero, a truncated record, manifest rot counted on the torn
# sidecar, zero duplicate executions, bit-identical throughout.
rm -f artifacts/restart_metrics.jsonl
timeout -k 10 900 env JAX_PLATFORMS=cpu SRJT_LOCKDEP=1 \
  SRJT_METRICS_ENABLED=1 \
  SRJT_RESULTS=artifacts/restart_metrics.jsonl \
  python benchmarks/bench_restart.py
python - <<'EOF'
import json
rows = [json.loads(s) for s in open("artifacts/restart_metrics.jsonl")
        if s.strip()]
row = next(r for r in rows if r.get("metric") == "restart_recovery")
assert row["bit_identical"], "restart tier recovered a wrong answer"
assert row["replays"] > 0, "recovered process never replayed the journal"
assert row["truncated_records"] > 0, "the torn journal tail never landed"
assert row["reattached"] > 0, "no checkpoint re-attached across the restart"
assert row["resumes"] > 0, "no cross-process partition resume recorded"
assert row["manifest_rot"] > 0, "the torn manifest was never caught"
assert row["duplicate_executions"] == 0, (
    f"{row['duplicate_executions']} DONE queries re-executed after restart")
assert row["recovered_resubmits"] > 0, "incomplete work never resubmitted"
print(f"restart tier: {row['replayed_records']} records replayed "
      f"({row['truncated_records']} truncated), {row['reattached']} "
      f"checkpoints re-attached, {row['resumes']} partition resumes, "
      f"{row['idempotent_hits']} digest answers, 0 duplicate executions "
      "-> artifacts/restart_metrics.jsonl")
EOF

# crash-storm tier (ISSUE 5): the full sidecar-pool + integrity suite
# with the crash/corrupt chaos profile armed INSIDE real workers — a
# pool of 2 survives kill -9 mid-query (failover + arena re-hydration)
# and every injected corruption surfaces as DataCorruption, never a
# wrong answer. The hard timeout is the leaked/wedged-worker assertion;
# the archived event log must PROVE the storm fired: nonzero
# sidecar.pool.failovers (worker deaths failed over) and nonzero
# sidecar.integrity.crc_mismatch (corruptions caught) are the artifact
# contract, with zero test failures above them.
rm -f artifacts/crash_metrics.jsonl
timeout -k 10 900 env SRJT_LOCKDEP=1 SRJT_RETRY_ENABLED=1 SRJT_RETRY_MAX_ATTEMPTS=10 \
  SRJT_RETRY_BASE_DELAY_MS=1 SRJT_RETRY_MAX_DELAY_MS=8 SRJT_RETRY_SEED=99 \
  SRJT_METRICS_ENABLED=1 SRJT_METRICS_LOG=artifacts/crash_metrics.jsonl \
  python -m pytest tests/test_sidecar_pool.py -q
python - <<'EOF'
import json
lines = [json.loads(s) for s in open("artifacts/crash_metrics.jsonl")]
assert lines, "crash-storm tier produced no events"
kinds = {r["event"] for r in lines}
assert "sidecar.pool.worker_death" in kinds, "no worker death recorded"
assert "sidecar.pool.respawn" in kinds, "no respawn recorded"
assert "sidecar.pool.rehydrate" in kinds, "no arena re-hydration recorded"
assert "integrity.crc_mismatch" in kinds, "no corruption caught"
deaths = sum(1 for r in lines if r["event"] == "sidecar.pool.worker_death")
failovers = sum(1 for r in lines
                if r["event"] == "sidecar.pool.worker_death" and r.get("live", 0) > 0)
mismatches = sum(1 for r in lines if r["event"] == "integrity.crc_mismatch")
assert failovers > 0, "no failover observed (every death left the pool dark)"
assert mismatches > 0, "no crc_mismatch observed"
print(f"archived {len(lines)} crash events ({deaths} deaths, "
      f"{failovers} failovers, {mismatches} corruptions caught) "
      "-> artifacts/crash_metrics.jsonl")
EOF

# data-plane tier (ISSUE 6): the slab-arena / frame-codec / TCP-exchange
# suite env-armed (retry + metrics + event log) under a hard timeout.
# The two-REAL-process acceptance inside arms ci/chaos_crash.json's
# exchange keys in the peer: one kill -9 mid-serve and one frame
# corruption, final distributed groupby bit-identical. The archived
# event log must PROVE the storm fired — a caught frame corruption
# (integrity.crc_mismatch) and a peer respawn are the artifact
# contract. The session-scoped slab-leak assertion in tests/conftest.py
# rides every pytest invocation in this file.
rm -f artifacts/data_plane_metrics.jsonl
timeout -k 10 900 env SRJT_LOCKDEP=1 SRJT_RETRY_ENABLED=1 SRJT_RETRY_MAX_ATTEMPTS=10 \
  SRJT_RETRY_BASE_DELAY_MS=1 SRJT_RETRY_MAX_DELAY_MS=8 SRJT_RETRY_SEED=99 \
  SRJT_METRICS_ENABLED=1 SRJT_METRICS_LOG=artifacts/data_plane_metrics.jsonl \
  python -m pytest tests/test_data_plane.py -q
python - <<'EOF'
import json
lines = [json.loads(s) for s in open("artifacts/data_plane_metrics.jsonl")]
assert lines, "data-plane tier produced no events"
kinds = {r["event"] for r in lines}
assert "integrity.crc_mismatch" in kinds, "no frame corruption caught"
assert "exchange.peer_respawn" in kinds, "no peer crash/respawn recorded"
print(f"archived {len(lines)} data-plane events -> "
      "artifacts/data_plane_metrics.jsonl")
EOF

# cluster tier (ISSUE 16): the N-rank membership / fencing / recovery
# suite env-armed under a hard timeout. The 4-process acceptance inside
# arms ci/chaos_cluster.json in the children: rank 2 SIGKILLs itself
# mid-frame on its first payload serve, rank 3 rides a transient
# netsplit, rank 1 serves with latency jitter — and the distributed
# groupby must stay bit-identical to the single-host oracle with
# exactly one membership death. The archived event log must PROVE the
# machinery engaged, not just that tests passed: a cluster.transition
# into DEAD and a cluster.recovery republish under the bumped
# generation are the artifact contract.
rm -f artifacts/cluster_metrics.jsonl
timeout -k 10 900 env SRJT_LOCKDEP=1 SRJT_RETRY_ENABLED=1 SRJT_RETRY_MAX_ATTEMPTS=10 \
  SRJT_RETRY_BASE_DELAY_MS=1 SRJT_RETRY_MAX_DELAY_MS=8 SRJT_RETRY_SEED=99 \
  SRJT_METRICS_ENABLED=1 SRJT_METRICS_LOG=artifacts/cluster_metrics.jsonl \
  python -m pytest tests/test_cluster.py -q
python - <<'EOF'
import json
lines = [json.loads(s) for s in open("artifacts/cluster_metrics.jsonl")]
assert lines, "cluster tier produced no events"
deaths = [r for r in lines
          if r["event"] == "cluster.transition" and r.get("new") == "dead"]
assert deaths, "no membership transition into DEAD recorded"
recoveries = [r for r in lines if r["event"] == "cluster.recovery"]
assert recoveries, "no lineage recovery republish recorded"
assert all(r["generation"] >= 2 for r in recoveries), \
    "a recovery ran under the pre-death generation (fence not bumped)"
print(f"archived {len(lines)} cluster events ({len(deaths)} deaths, "
      f"{len(recoveries)} recoveries) -> artifacts/cluster_metrics.jsonl")
EOF

# serving tier (ISSUE 8): the full serve suite (incl. the slow
# chaos-under-load acceptance) env-armed, then bench_serve's chaos
# gate — a crash+hang+reject storm WHILE serving a mixed q1/q6/q98
# workload through a REAL worker pool of 2. The bench exits nonzero
# unless every completed query is bit-identical to its sequential
# oracle, every shed surfaced as retryable Overloaded (never a
# timeout), and p999 stays under the per-query deadline; the archived
# artifacts must additionally PROVE the storm fired — failovers > 0
# (kill -9 healed by a living peer) and shed_total > 0 are the
# artifact contract. SRJT_LOCKDEP=1 rides along: the dispatcher's new
# lock sites feed the merged zero-cycle gate below.
rm -f artifacts/serve_metrics.jsonl artifacts/bench_serve.jsonl
timeout -k 10 900 env SRJT_LOCKDEP=1 SRJT_RACE=1 SRJT_RETRY_ENABLED=1 SRJT_RETRY_MAX_ATTEMPTS=10 \
  SRJT_RETRY_BASE_DELAY_MS=1 SRJT_RETRY_MAX_DELAY_MS=8 SRJT_RETRY_SEED=99 \
  SRJT_METRICS_ENABLED=1 SRJT_METRICS_LOG=artifacts/serve_metrics.jsonl \
  python -m pytest tests/test_serve.py -q
timeout -k 10 900 env SRJT_LOCKDEP=1 SRJT_RACE=1 SRJT_RETRY_ENABLED=1 SRJT_RETRY_MAX_ATTEMPTS=10 \
  SRJT_RETRY_BASE_DELAY_MS=2 SRJT_RETRY_MAX_DELAY_MS=50 SRJT_RETRY_SEED=99 \
  SRJT_METRICS_ENABLED=1 SRJT_METRICS_LOG=artifacts/serve_metrics.jsonl \
  SRJT_RESULTS=artifacts/bench_serve.jsonl \
  python benchmarks/bench_serve.py --chaos --rows 5000 --queries 24 \
  --offered-qps 2 --deadline-s 60 --max-concurrent 3 --pool-size 2
python - <<'EOF'
import json
rows = [json.loads(s) for s in open("artifacts/bench_serve.jsonl")]
bench = [r for r in rows if r.get("metric") == "serve_mixed_qps"]
assert bench, "no serve BENCH row emitted"
b = bench[-1]
assert b["wrong_answers"] == 0 and b["bit_identical"], b
assert b["failovers"] > 0, "crash storm produced no pool failover"
assert b["shed_total_counter"] > 0, "no shed recorded (serve.shed_total == 0)"
assert b["completed"] > 0 and b["value"] > 0, "no sustained throughput"
assert b["p999_ms"] <= b["deadline_s"] * 1000, "p999 exceeds the deadline"
lines = [json.loads(s) for s in open("artifacts/serve_metrics.jsonl")]
kinds = {r["event"] for r in lines}
assert "serve.shed" in kinds, "no shed event archived"
assert "serve.submit" in kinds and "serve.done" in kinds
failovers = sum(1 for r in lines
                if r["event"] == "sidecar.pool.worker_death"
                and r.get("live", 0) > 0)
assert failovers > 0, "no failover-with-living-peers in the event log"
print(f"serve tier: {b['completed']} queries at {b['value']} qps "
      f"(p50 {b['p50_ms']} / p99 {b['p99_ms']} / p999 {b['p999_ms']} ms), "
      f"{b['shed_total_counter']} sheds, {b['failovers']} failovers "
      "-> artifacts/serve_metrics.jsonl")
EOF

# gray-failure tier (ISSUE 9): the serve bench against a REAL pool of
# 3 with ONE worker ramped into persistent slowness (ci/chaos_gray.json
# keys its delay ramp to w1's SRJT_FAULTINJ_WORKER tag — a gray
# failure, not a crash). The tail-tolerance contract is gated from the
# archived artifacts, not test-self-certified: every completed query
# bit-identical to its sequential oracle, p999 <= the deadline, the
# slow worker QUARANTINED and later REINSTATED after the ramp ends,
# hedged dispatch WON at least one race, and the hedge volume within
# its budget. SRJT_LOCKDEP=1 rides along: the quarantine/hedge lock
# sites feed the merged zero-cycle gate below.
rm -f artifacts/gray_metrics.jsonl artifacts/bench_gray.jsonl
timeout -k 10 900 env SRJT_LOCKDEP=1 SRJT_RACE=1 SRJT_RETRY_ENABLED=1 SRJT_RETRY_MAX_ATTEMPTS=10 \
  SRJT_RETRY_BASE_DELAY_MS=2 SRJT_RETRY_MAX_DELAY_MS=50 SRJT_RETRY_SEED=99 \
  SRJT_METRICS_ENABLED=1 SRJT_METRICS_LOG=artifacts/gray_metrics.jsonl \
  SRJT_RESULTS=artifacts/bench_gray.jsonl \
  SRJT_HEDGE_BUDGET_PCT=25 SRJT_ADAPTIVE_TIMEOUT_FLOOR_S=2 \
  SRJT_QUARANTINE_PROBE_INTERVAL_S=0.2 \
  python benchmarks/bench_serve.py --gray --rows 4000 --queries 36 \
  --offered-qps 2 --deadline-s 90 --max-concurrent 3 --pool-size 3 \
  --pool-ops 3
python - <<'EOF'
import json
rows = [json.loads(s) for s in open("artifacts/bench_gray.jsonl")]
bench = [r for r in rows if r.get("metric") == "serve_gray_qps"]
assert bench, "no gray BENCH row emitted"
b = bench[-1]
assert b["wrong_answers"] == 0 and b["bit_identical"], b
assert b["quarantines"] > 0, "slow worker never quarantined"
assert b["reinstatements"] > 0, "quarantined worker never reinstated"
assert b["hedges_won"] > 0, "hedged dispatch won no race"
assert b["completed"] > 0 and b["value"] > 0, "no sustained throughput"
assert b["p999_ms"] <= b["deadline_s"] * 1000, "p999 exceeds the deadline"
assert b["hedges_launched"] * 100.0 <= (
    b["hedge_budget_pct"] * max(b["pool_calls"], 1)
), "hedge volume exceeded its budget"
lines = [json.loads(s) for s in open("artifacts/gray_metrics.jsonl")]
kinds = {r["event"] for r in lines}
assert "sidecar.pool.quarantine" in kinds, "no quarantine event archived"
assert "sidecar.pool.reinstate" in kinds, "no reinstate event archived"
assert "sidecar.pool.hedge_won" in kinds, "no hedge_won event archived"
print(f"gray tier: {b['completed']} queries at {b['value']} qps "
      f"(p50 {b['p50_ms']} / p99 {b['p99_ms']} / p999 {b['p999_ms']} ms), "
      f"{b['quarantines']} quarantines, {b['reinstatements']} reinstated, "
      f"{b['hedges_won']}/{b['hedges_launched']} hedges won/launched "
      "-> artifacts/gray_metrics.jsonl")
EOF

# trace tier (ISSUE 12): the distributed-tracing suite env-armed —
# including the real-pool acceptance, which runs a crash-profile storm
# (per-worker delay ramp + kill -9 mid-STATS) through a REAL pool of 2
# with SRJT_TRACE_ENABLED=1 and per-process span logs. The merge gate
# is artifact-based, not test-self-certified: tracemerge joins the
# client's and both workers' logs and must show a trace containing the
# FAILOVER (two pool.request attempts on distinct workers under one
# pool.call span), the HEDGED sibling pair with the winner marked
# exactly once, a cross-process worker span, and ZERO orphan spans
# (every span's parent resolves within its trace — --gate-orphans
# exits 1 otherwise). The Perfetto-loadable export is archived too.
rm -f artifacts/trace_spans*.jsonl artifacts/trace_merged.json \
  artifacts/trace_perfetto.json
timeout -k 10 900 env SRJT_LOCKDEP=1 SRJT_RACE=1 \
  SRJT_TRACE_ENABLED=1 SRJT_TRACE_LOG=artifacts/trace_spans.jsonl \
  SRJT_METRICS_ENABLED=1 SRJT_RETRY_ENABLED=1 SRJT_RETRY_MAX_ATTEMPTS=10 \
  SRJT_RETRY_BASE_DELAY_MS=1 SRJT_RETRY_MAX_DELAY_MS=8 SRJT_RETRY_SEED=99 \
  python -m pytest tests/test_tracing.py -q
python -m spark_rapids_jni_tpu.analysis.tracemerge \
  "artifacts/trace_spans*.jsonl" --format json \
  --out artifacts/trace_merged.json --gate-orphans
python -m spark_rapids_jni_tpu.analysis.tracemerge \
  "artifacts/trace_spans*.jsonl" --format chrome \
  --out artifacts/trace_perfetto.json
python - <<'EOF'
import json
rep = json.load(open("artifacts/trace_merged.json"))
assert rep["traces"], "trace tier archived no merged traces"
assert rep["orphans"] == 0, f"{rep['orphans']} orphan spans"
failover = hedged = chain = False
for t in rep["traces"].values():
    spans = t["spans"]
    by_id = {s["span"]: s for s in spans}
    for call in (s for s in spans if s["name"] == "pool.call"):
        kids = [s for s in spans
                if s.get("parent") == call["span"]
                and s["name"] == "pool.request"]
        if len(kids) >= 2 and len({
            (s.get("annotations") or {}).get("wid") for s in kids
        }) >= 2:
            failover = True
    legs = [s for s in spans if s["name"] == "pool.hedge_leg"]
    by_parent = {}
    for s in legs:
        by_parent.setdefault(s["parent"], []).append(s)
    for pair in by_parent.values():
        if len(pair) == 2 and sum(
            1 for s in pair if (s.get("annotations") or {}).get("winner")
        ) == 1:
            hedged = True
    # the acceptance chain: a CROSS-PROCESS worker span whose ancestor
    # walk reaches submit -> queue -> admission -> op -> wire -> worker
    client_pids = {s["pid"] for s in spans if s["name"] == "serve.query"}
    for w in (s for s in spans if s["name"] == "sidecar.worker_op"
              and client_pids and s["pid"] not in client_pids):
        names, cur = set(), w
        while cur.get("parent") in by_id:
            cur = by_id[cur["parent"]]
            names.add(cur["name"])
        if {"sidecar.request", "pool.call", "serve.run",
                "serve.query"} <= names and any(
            n.startswith("op.") for n in names
        ) and {"serve.queue_wait", "memgov.admission_wait"} <= {
            s["name"] for s in spans
        }:
            chain = True
doc = json.load(open("artifacts/trace_perfetto.json"))
assert doc["traceEvents"], "empty Perfetto export"
assert failover, "merged traces show no failover (two attempts, two workers)"
assert hedged, "merged traces show no hedged sibling pair with one winner"
assert chain, ("no cross-process query tree spans submit -> queue -> "
               "admission -> op -> wire -> worker")
print(f"trace tier: {len(rep['traces'])} traces, 0 orphans, "
      f"failover+hedge spans and the cross-process submit->worker "
      "chain present -> artifacts/trace_merged.json / trace_perfetto.json")
EOF

# plan-compiler tier (ISSUE 14): the srjt-plan suite — IR/rewrite unit
# tier plus EVERY green plan query against its pandas oracle, the two
# hand-built greens (q3/q55) re-expressed as plans and asserted
# bit-identical to their fused originals, rewrite idempotence, and the
# schema contract (inferred dtypes == executed dtypes) — runs env-armed
# with the MEMORY GOVERNOR ON (a generous budget: the point is that
# admission runs, not that it starves) and the per-query report knob
# set. The merge gate is artifact-based: artifacts/plan_compile.jsonl
# must carry every registry query with node counts and rewrites fired,
# ZERO estimate-vs-actual peak-byte blowups over 2.5x (4x -> 3x in
# ISSUE 15 when the width model gained the per-row validity lane; 3x
# -> 2.5x in ISSUE 19 with the sketch-calibrated row estimates), every
# multi-join green's cost-chosen order at or below the author order on
# modeled cost, and the metrics log must PROVE memgov admission
# consumed nonzero plan-derived estimates (the ISSUE 14 acceptance
# assertion). SRJT_LOCKDEP/RACE ride along and feed the merged
# zero-cycle gate below.
rm -f artifacts/plan_compile.jsonl artifacts/plan_metrics.jsonl
timeout -k 10 900 env SRJT_LOCKDEP=1 SRJT_RACE=1 \
  SRJT_DEVICE_MEMORY_BUDGET=268435456 SRJT_SPILL_ENABLED=1 \
  SRJT_METRICS_ENABLED=1 SRJT_METRICS_LOG=artifacts/plan_metrics.jsonl \
  SRJT_PLAN_REPORT=artifacts/plan_compile.jsonl \
  python -m pytest tests/test_plan.py tests/test_plan_queries.py -q
python - <<'EOF'
import json
rows = [json.loads(s) for s in open("artifacts/plan_compile.jsonl")]
assert rows, "compiler tier produced no plan reports"
by = {}
for r in rows:
    by[r["query"]] = r  # last execution per query wins
from spark_rapids_jni_tpu.models.tpcds_plans import PLAN_QUERIES
missing = sorted(set(PLAN_QUERIES) - set(by))
assert not missing, f"green plan queries missing from the report: {missing}"
assert len(PLAN_QUERIES) >= 15, "fewer than 15 compiler-green queries"
for name in ("q3", "q55"):
    assert name in by, f"re-expressed green {name} not exercised"
blowups = {}
for q, r in by.items():
    assert r["nodes_raw"] > 0 and r["nodes_optimized"] > 0, r
    assert isinstance(r["rewrites"], dict), r
    assert r["est_peak_bytes"] > 0, r
    if r["peak_blowup"] is not None and r["peak_blowup"] > 2.5:
        blowups[q] = r["peak_blowup"]
assert not blowups, f"estimate-vs-actual peak blowups > 2.5x: {blowups}"
# srjt-cbo (ISSUE 19): on every checked-in multi-join plan the
# cost-based search ran, and the order it chose beats or ties the
# author order under the same model (the search records the author
# cost BEFORE enumerating, so a regression here means the search
# actively picked a worse plan)
multi = {q: r for q, r in by.items() if (r.get("join_count") or 0) >= 2}
assert multi, "no multi-join green carried a modeled cost (CBO never ran)"
cost_regressions = {
    q: (r["modeled_cost_author"], r["modeled_cost_chosen"])
    for q, r in multi.items()
    if r["modeled_cost_chosen"] is not None
    and r["modeled_cost_chosen"] > r["modeled_cost_author"] + 1e-6
}
assert not cost_regressions, \
    f"cost-chosen order worse than author order: {cost_regressions}"
fired = {}
for q in PLAN_QUERIES:
    for rule, n in by[q]["rewrites"].items():
        fired[rule] = fired.get(rule, 0) + n
for rule in ("decorrelate_scalar_agg", "expand_grouping_sets",
             "setop_to_joins", "exists_to_semijoin", "having_to_filter"):
    assert fired.get(rule), f"rewrite {rule} never fired across the greens"
fused = sum(by[q]["fused_stages"] for q in PLAN_QUERIES)
assert fused > 0, "no query lowered through the fused pipeline tier"
events = [json.loads(s) for s in open("artifacts/plan_metrics.jsonl")]
admits = [e for e in events if e["event"] == "plan.admit"]
assert admits and all(e["nbytes"] > 0 for e in admits), \
    "memgov admission saw no nonzero plan-derived estimates"
print(f"plan tier: {len(PLAN_QUERIES)} compiler-green queries "
      f"({fused} fused stages), rewrites {fired}, "
      f"{len(multi)} multi-join plans cost-checked, "
      f"{len(admits)} plan-derived admissions, 0 blowups "
      "-> artifacts/plan_compile.jsonl")
EOF

# cache tier (ISSUE 17): the srjt-cache suite with BOTH cache layers
# armed (plan cache + memgov-governed subresult cache) and the race /
# lockdep shims riding along — param-fingerprint properties over the
# planfuzz corpus, single-flight attach/cancel/leader-failure,
# spill-then-rematerialize bit-exactness, generation-bump
# invalidation, and the serve integration (bad-estimate normalization,
# forecast shed, chaos storm). Then bench_serve --cache runs the
# cold/warm economics gate (its OWN exit code enforces warm hit rate
# >= 0.8, >= 3x warm QPS at equal-or-better p99, in-flight sharing
# > 0, and bit-exactness vs uncached oracles) and --cache --chaos
# re-runs both passes under the ci/chaos_cache.json eviction/spill/
# reject storm (zero wrong answers while entries are shot down
# mid-lookup). The merge gate is artifact-based on top of the exit
# codes: the archived BENCH rows must SHOW the warm hit rate, the
# sharing, and zero wrong answers, and the metrics log must carry
# cache events.
rm -f artifacts/cache_metrics.jsonl artifacts/bench_cache.jsonl
timeout -k 10 900 env JAX_PLATFORMS=cpu SRJT_LOCKDEP=1 SRJT_RACE=1 \
  SRJT_PLAN_CACHE=1 SRJT_SUBRESULT_CACHE=1 \
  SRJT_METRICS_ENABLED=1 SRJT_METRICS_LOG=artifacts/cache_metrics.jsonl \
  python -m pytest tests/test_cache.py -q
timeout -k 10 900 env JAX_PLATFORMS=cpu \
  SRJT_METRICS_ENABLED=1 SRJT_METRICS_LOG=artifacts/cache_metrics.jsonl \
  SRJT_RESULTS=artifacts/bench_cache.jsonl \
  python benchmarks/bench_serve.py --cache --rows 20000
timeout -k 10 900 env JAX_PLATFORMS=cpu \
  SRJT_METRICS_ENABLED=1 SRJT_METRICS_LOG=artifacts/cache_metrics.jsonl \
  SRJT_RESULTS=artifacts/bench_cache.jsonl \
  python benchmarks/bench_serve.py --cache --chaos --rows 20000
python - <<'EOF'
import json
rows = [json.loads(s) for s in open("artifacts/bench_cache.jsonl")]
bench = [r for r in rows if r.get("metric") == "serve_cached_qps"]
plain = [r for r in bench if not r["chaos"]]
storm = [r for r in bench if r["chaos"]]
assert plain and storm, f"missing cache BENCH rows: {len(bench)}"
b = plain[-1]
assert b["wrong_answers"] == 0 and b["bit_identical"], b
assert b["hit_rate"] >= 0.8, f"warm hit rate {b['hit_rate']} < 0.8"
assert b["share"] > 0, "no in-flight sharing recorded (cache.share == 0)"
assert b["value"] >= 3.0 * b["cold_qps"], \
    f"warm {b['value']} qps < 3x cold {b['cold_qps']} qps"
assert b["warm_p99_ms"] <= b["cold_p99_ms"], b
s = storm[-1]
assert s["wrong_answers"] == 0 and s["bit_identical"], s
ev = (s["cold_counters"]["cache.evict_injected"]
      + s["warm_counters"]["cache.evict_injected"])
assert ev > 0, "chaos storm injected no cache eviction"
lines = [json.loads(l) for l in open("artifacts/cache_metrics.jsonl")]
assert lines, "cache tier produced no metrics events"
print(f"cache tier: warm {b['value']} qps ({b['speedup']}x cold, "
      f"hit rate {b['hit_rate']}, {b['share']} shares), storm survived "
      f"{ev} injected evictions / 0 wrong answers "
      "-> artifacts/cache_metrics.jsonl")
EOF

# lockdep + race gate (ISSUEs 7 + 11, layer 2): merge every
# per-process report the armed tiers above dropped (fast tier + the
# chaos tiers + the serve and gray tiers, incl. spawned
# sidecar/exchange workers — the env rides into children) and fail on
# any lock-order cycle, self-deadlock, OR race pair. The fast + serve
# + gray tiers ran with SRJT_RACE=1, so the merged report must show
# the detector was armed and found ZERO unordered accesses to the
# tracked state (tests/test_races.py proves the same gate trips on a
# seeded race). The merged graph is archived as
# artifacts/lockdep_report.json; blocking-while-locked events are
# reported but advisory (the deadline tier owns that risk).
python -m spark_rapids_jni_tpu.analysis.lockdep \
  --merge artifacts/lockdep --out artifacts/lockdep_report.json
python - <<'EOF'
import json
rep = json.load(open("artifacts/lockdep_report.json"))
assert rep["reports"] > 0, "lockdep armed but no process wrote a report"
assert not rep["cycles"] and not rep["self_deadlocks"], rep["cycles"]
assert not rep["site_cycles"], rep["site_cycles"]  # cross-process inversions
assert rep["race_armed"], "race tiers ran but no report carries race_armed"
assert not rep["race_pairs"], rep["race_pairs"]  # srjt-race layer 2
assert rep["race_total"] == 0, rep["race_total"]
print(f"lockdep: {rep['reports']} reports, {len(rep['locks'])} lock sites, "
      f"{len(rep['edges'])} edges, 0 cycles, 0 races "
      "-> artifacts/lockdep_report.json")
EOF

# pool-scaling gate (ISSUE 6 acceptance): arena-resident ops/s at pool
# size 2 must be >= 1.5x pool size 1 on the bench_pool workload (REAL
# spawned workers, 20 ms worker-side latency floor, 8 client threads).
# Under the PR 5 single-buffer arena this ratio was ~1.0 by
# construction; the per-request slab regions are what buy the overlap.
# The 2-process exchange MB/s row rides along and must verify the
# distributed groupby bit-identical before it is emitted.
rm -f artifacts/bench_pool.jsonl
timeout -k 10 600 env SRJT_RESULTS=artifacts/bench_pool.jsonl \
  python benchmarks/bench_pool.py --sizes 1,2 --ops 40 --threads 8 \
  --delay-ms 20 --exchange-rows 150000
python - <<'EOF'
import json
rows = [json.loads(s) for s in open("artifacts/bench_pool.jsonl")]
pool = {r["pool_size"]: r["value"] for r in rows
        if r.get("metric") == "pool_arena_ops_per_s"}
assert 1 in pool and 2 in pool, f"missing pool sizes in BENCH rows: {pool}"
ratio = pool[2] / pool[1]
assert ratio >= 1.5, (
    f"pool 2 scaling {ratio:.2f}x < 1.5x over pool 1 "
    f"({pool[2]:.1f} vs {pool[1]:.1f} ops/s): arena ops serialized?")
exch = [r for r in rows if r.get("metric") == "exchange_2proc_mb_per_s"]
assert exch and exch[0].get("bit_identical"), "no verified exchange BENCH row"
print(f"pool scaling {ratio:.2f}x (1={pool[1]:.1f}, 2={pool[2]:.1f} ops/s), "
      f"exchange {exch[0]['value']} MB/s -> artifacts/bench_pool.jsonl")
EOF

# N-rank exchange scaling gate (ISSUE 16 acceptance): aggregate
# exchange MB/s at world 4 must be >= 2.5x world 2 on the nrank stage
# (REAL spawned peer ranks, an injected per-serve latency floor so the
# ratio measures pull CONCURRENCY, not socket bandwidth — perfect
# scaling doubles both the payload and the parallel pulls hiding the
# floor). Each row is emitted only after the distributed groupby
# verified bit-identical to the single-host oracle at that world.
timeout -k 10 600 env SRJT_RESULTS=artifacts/bench_pool.jsonl \
  python benchmarks/bench_pool.py --stage nrank --nrank-worlds 2,4 \
  --nrank-rows-per-rank 20000
python - <<'EOF'
import json
rows = [json.loads(s) for s in open("artifacts/bench_pool.jsonl")]
nrank = {r["world"]: r for r in rows
         if r.get("metric") == "exchange_nrank_mb_per_s"}
assert 2 in nrank and 4 in nrank, f"missing nrank worlds: {sorted(nrank)}"
assert all(r["bit_identical"] for r in nrank.values()), \
    "an nrank row was emitted without oracle verification"
ratio = nrank[4]["value"] / nrank[2]["value"]
assert ratio >= 2.5, (
    f"world-4 exchange scaling {ratio:.2f}x < 2.5x over world 2 "
    f"({nrank[4]['value']} vs {nrank[2]['value']} MB/s): pulls serialized?")
print(f"nrank exchange scaling {ratio:.2f}x "
      f"(world2={nrank[2]['value']}, world4={nrank[4]['value']} MB/s) "
      "-> artifacts/bench_pool.jsonl")
EOF

# kernel tier (ISSUE 13): the join/decode parity suite re-runs with
# the Pallas tier FORCED through the interpreter (the exact kernel
# bodies the chip runs, hermetic on CPU) and the event log armed, then
# both kernel-tier microbench axes run env-armed. The gate is
# artifact-based: the dispatch.tier events and BENCH-row tier fields
# must PROVE the pallas path actually engaged (a silently-dead tier
# that falls back everywhere passes tests but fails here), every row
# must be bit-identical to its XLA twin, and vs_baseline_worst must
# not regress — informational (> 0, recorded) on CPU where the
# interpreter is the executor, and >= 2.0x on a real TPU backend (the
# ISSUE 13 acceptance bar, enforced by the same gate when premerge
# runs on-chip).
rm -f artifacts/kernel_tier_metrics.jsonl artifacts/bench_kernel_tier.jsonl
SRJT_PALLAS_INTERPRET=1 SRJT_METRICS_ENABLED=1 \
  SRJT_METRICS_LOG=artifacts/kernel_tier_metrics.jsonl \
  python -m pytest tests/test_pallas_kernels.py -q
SRJT_PALLAS_INTERPRET=1 SRJT_RESULTS=artifacts/bench_kernel_tier.jsonl \
  python benchmarks/microbench.py --bench join --rows 20000 --reps 2
SRJT_PALLAS_INTERPRET=1 SRJT_RESULTS=artifacts/bench_kernel_tier.jsonl \
  python benchmarks/microbench.py --bench ragged_decode --rows 20000 --reps 2
python - <<'EOF'
import json
events = [json.loads(s) for s in open("artifacts/kernel_tier_metrics.jsonl")]
tiers = [r for r in events if r["event"] == "dispatch.tier"]
assert any(r.get("tier") == "pallas" for r in tiers), \
    "parity suite ran but no dispatch served from the pallas tier"
assert any(r.get("tier") == "xla" for r in tiers), \
    "forced-fallback tests recorded no xla-tier dispatch"
rows = [json.loads(s) for s in open("artifacts/bench_kernel_tier.jsonl")]
by = {r["bench"]: r for r in rows if "bench" in r}
for name in ("join_inner_paged", "ragged_decode_fused"):
    b = by.get(name)
    assert b, f"no {name} BENCH row emitted"
    assert b["tier"] == "pallas", f"{name}: pallas tier did not engage ({b['tier']})"
    assert b["bit_identical"], f"{name}: kernel result diverged from the XLA twin"
    assert b["vs_baseline_worst"] > 0, b
    if b["fingerprint"]["backend"] == "tpu":
        assert b["vs_baseline_worst"] >= 2.0, (
            f"{name}: on-chip kernel tier regressed below the 2x acceptance "
            f"bar (vs_baseline_worst={b['vs_baseline_worst']})")
print("kernel tier: pallas engaged in parity suite; " + "; ".join(
    f"{n} {by[n]['vs_baseline']}x vs XLA (worst {by[n]['vs_baseline_worst']}x, "
    f"bit-identical)" for n in ("join_inner_paged", "ragged_decode_fused")))
EOF

# (the disabled-mode overhead guard —
# tests/test_metrics.py::test_disabled_mode_is_noop — runs in the fast
# tier above with SRJT_METRICS_ENABLED unset, i.e. exactly the
# production posture it guards; no separate invocation needed)

JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python __graft_entry__.py

python benchmarks/microbench.py --bench groupby --rows 65536 --reps 3

#!/usr/bin/env bash
# Premerge gate (reference ci/premerge-build.sh analog): native build,
# hermetic test suite on the virtual CPU mesh, driver entry compile
# check, and a bench smoke. Run from the repo root.
set -euo pipefail

cmake -S native -B native/build -G Ninja
ninja -C native/build

# fast tier: the measured heavy tail (tests/conftest.py _SLOW_TESTS)
# runs nightly (ci/nightly.sh); this keeps the premerge gate usable on
# a 1-core box (VERDICT r3 item 9)
python -m pytest tests/ -q -m "not slow"

JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python __graft_entry__.py

python benchmarks/microbench.py --bench groupby --rows 65536 --reps 3

"""ctypes bindings to the native runtime (native/libsrjt.so).

NativeDepsLoader analog (reference RowConversion.java:23-25 +
pom.xml:443-474 packaging): locate and load the shared library once,
expose the handle-based C ABI as Python classes with explicit close()
ownership — the same discipline the reference's Java API uses over
jlong handles. Falls back gracefully: ``native_available()`` is False
when the library isn't built, and callers (tests, the pure-Python
footer service) keep working.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import List, Optional

from .io.parquet_footer import StructElement, flatten_schema
from .utils import knobs

__all__ = [
    "native_available",
    "native_lib",
    "live_handles",
    "stats_report",
    "device_stats",
    "snappy_uncompress",
    "lz4_decompress_block",
    "lzo1x_decompress",
    "zstd_decompress",
    "zstd_frame_content_size",
    "NativeParquetFooter",
    "NativeHostBuffer",
]


def snappy_uncompress(data: bytes, expected_size: Optional[int] = None) -> bytes:
    """Decompress a snappy block via the native codec tier (nvcomp
    analog). Raises RuntimeError if the native library is missing or the
    stream is malformed."""
    lib = native_lib()
    if lib is None:
        raise RuntimeError("native runtime not built (run cmake in native/)")
    n = lib.srjt_snappy_uncompressed_length(data, len(data))
    if n < 0:
        _raise_last(lib)
    if expected_size is not None and n != expected_size:
        raise RuntimeError(f"snappy: preamble size {n} != expected {expected_size}")
    if expected_size is None and n > max(len(data), 1) * 128:
        # the format can't expand anywhere near this much: an attacker-
        # controlled preamble must not drive a giant allocation
        raise RuntimeError(f"snappy: implausible uncompressed size {n}")
    out = ctypes.create_string_buffer(int(n))
    if lib.srjt_snappy_uncompress(data, len(data), out, n) != 0:
        _raise_last(lib)
    return out.raw

_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _candidate_paths() -> List[str]:
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(here)
    cands = []
    env = knobs.get_str("SRJT_NATIVE_LIB")
    if env:
        cands.append(env)
    cands.append(os.path.join(here, "libsrjt.so"))  # packaged next to the module
    cands.append(os.path.join(repo, "native", "build", "libsrjt.so"))  # dev build
    return cands


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.srjt_last_error.restype = ctypes.c_char_p
    lib.srjt_live_handles.restype = ctypes.c_int64
    lib.srjt_footer_read_and_filter.restype = ctypes.c_int64
    lib.srjt_footer_read_and_filter.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int32,
        ctypes.c_int32,
        ctypes.c_int32,
    ]
    lib.srjt_footer_num_rows.restype = ctypes.c_int64
    lib.srjt_footer_num_rows.argtypes = [ctypes.c_int64]
    lib.srjt_footer_num_columns.restype = ctypes.c_int32
    lib.srjt_footer_num_columns.argtypes = [ctypes.c_int64]
    lib.srjt_footer_serialize.restype = ctypes.c_int64
    lib.srjt_footer_serialize.argtypes = [ctypes.c_int64, ctypes.POINTER(ctypes.c_int64)]
    lib.srjt_blob_copy.restype = ctypes.c_int32
    lib.srjt_blob_copy.argtypes = [ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64]
    lib.srjt_blob_free.argtypes = [ctypes.c_int64]
    lib.srjt_footer_close.argtypes = [ctypes.c_int64]
    lib.srjt_host_alloc.restype = ctypes.c_int64
    lib.srjt_host_alloc.argtypes = [ctypes.c_int64, ctypes.c_int64]
    lib.srjt_host_ptr.restype = ctypes.c_void_p
    lib.srjt_host_ptr.argtypes = [ctypes.c_int64]
    lib.srjt_host_size.restype = ctypes.c_int64
    lib.srjt_host_size.argtypes = [ctypes.c_int64]
    lib.srjt_host_free.argtypes = [ctypes.c_int64]
    lib.srjt_host_bytes_in_use.restype = ctypes.c_int64
    lib.srjt_snappy_uncompressed_length.restype = ctypes.c_int64
    lib.srjt_snappy_uncompressed_length.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.srjt_snappy_uncompress.restype = ctypes.c_int32
    lib.srjt_snappy_uncompress.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_char_p,
        ctypes.c_int64,
    ]
    # columnar engine
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.srjt_column_create.restype = ctypes.c_int64
    lib.srjt_column_create.argtypes = [
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
        u8p, ctypes.c_int64, u8p, i32p, u8p, ctypes.c_int64,
    ]
    for name, res in [
        ("srjt_column_type", ctypes.c_int32),
        ("srjt_column_scale", ctypes.c_int32),
        ("srjt_column_size", ctypes.c_int64),
        ("srjt_column_data_bytes", ctypes.c_int64),
        ("srjt_column_chars_bytes", ctypes.c_int64),
        ("srjt_column_has_validity", ctypes.c_int32),
    ]:
        fn = getattr(lib, name)
        fn.restype = res
        fn.argtypes = [ctypes.c_int64]
    for name, ptr_t in [
        ("srjt_column_copy_data", u8p),
        ("srjt_column_copy_validity", u8p),
        ("srjt_column_copy_offsets", i32p),
        ("srjt_column_copy_chars", u8p),
    ]:
        fn = getattr(lib, name)
        fn.restype = ctypes.c_int32
        fn.argtypes = [ctypes.c_int64, ptr_t, ctypes.c_int64]
    lib.srjt_column_close.argtypes = [ctypes.c_int64]
    lib.srjt_table_create.restype = ctypes.c_int64
    lib.srjt_table_create.argtypes = [ctypes.POINTER(ctypes.c_int64), ctypes.c_int32]
    lib.srjt_table_num_columns.restype = ctypes.c_int32
    lib.srjt_table_num_columns.argtypes = [ctypes.c_int64]
    lib.srjt_table_num_rows.restype = ctypes.c_int64
    lib.srjt_table_num_rows.argtypes = [ctypes.c_int64]
    lib.srjt_table_column.restype = ctypes.c_int64
    lib.srjt_table_column.argtypes = [ctypes.c_int64, ctypes.c_int32]
    lib.srjt_table_close.argtypes = [ctypes.c_int64]
    lib.srjt_convert_to_rows.restype = ctypes.c_int64
    lib.srjt_convert_to_rows.argtypes = [ctypes.c_int64]
    lib.srjt_convert_to_rows_batched.restype = ctypes.c_int32
    lib.srjt_convert_to_rows_batched.argtypes = [
        ctypes.c_int64, ctypes.c_int64, ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
    ]
    lib.srjt_convert_from_rows.restype = ctypes.c_int64
    lib.srjt_convert_from_rows.argtypes = [ctypes.c_int64, i32p, i32p, ctypes.c_int32]
    lib.srjt_cast_string_to_integer.restype = ctypes.c_int64
    lib.srjt_cast_string_to_integer.argtypes = [ctypes.c_int64, ctypes.c_int32, ctypes.c_int32]
    lib.srjt_cast_string_to_decimal.restype = ctypes.c_int64
    lib.srjt_cast_string_to_decimal.argtypes = [
        ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
    ]
    lib.srjt_last_cast_error_pending.restype = ctypes.c_int32
    lib.srjt_last_cast_row.restype = ctypes.c_int64
    lib.srjt_last_cast_string.restype = ctypes.c_char_p
    lib.srjt_zorder_interleave_bits.restype = ctypes.c_int64
    lib.srjt_zorder_interleave_bits.argtypes = [ctypes.c_int64]
    lib.srjt_live_columnar_handles.restype = ctypes.c_int64
    lib.srjt_multiply_decimal128.restype = ctypes.c_int64
    lib.srjt_multiply_decimal128.argtypes = [ctypes.c_int64, ctypes.c_int64, ctypes.c_int32]
    lib.srjt_divide_decimal128.restype = ctypes.c_int64
    lib.srjt_divide_decimal128.argtypes = [ctypes.c_int64, ctypes.c_int64, ctypes.c_int32]
    lib.srjt_byte_array_lens.restype = ctypes.c_int64
    lib.srjt_byte_array_lens.argtypes = [u8p, ctypes.c_int64, i32p, ctypes.c_int64]
    lib.srjt_lz4_decompress_block.restype = ctypes.c_int64
    lib.srjt_lz4_decompress_block.argtypes = [u8p, ctypes.c_int64, u8p, ctypes.c_int64]
    lib.srjt_lzo1x_decompress.restype = ctypes.c_int64
    lib.srjt_lzo1x_decompress.argtypes = [u8p, ctypes.c_int64, u8p, ctypes.c_int64]
    lib.srjt_zstd_decompress.restype = ctypes.c_int64
    lib.srjt_zstd_decompress.argtypes = [u8p, ctypes.c_int64, u8p, ctypes.c_int64]
    lib.srjt_zstd_frame_content_size.restype = ctypes.c_int64
    lib.srjt_zstd_frame_content_size.argtypes = [u8p, ctypes.c_int64]
    lib.srjt_faultinj_configure.restype = ctypes.c_int32
    lib.srjt_faultinj_configure.argtypes = [ctypes.c_char_p]
    lib.srjt_faultinj_disable.restype = None
    lib.srjt_faultinj_enabled.restype = ctypes.c_int32
    lib.srjt_device_connect.restype = ctypes.c_int32
    lib.srjt_device_connect.argtypes = [ctypes.c_char_p, ctypes.c_int32]
    lib.srjt_device_platform.restype = ctypes.c_char_p
    lib.srjt_device_shutdown.restype = None
    try:
        lib.srjt_device_heartbeat.restype = ctypes.c_int32
    except AttributeError:
        # a stale libsrjt.so predating the supervision tier: the rest
        # of the ABI keeps working; device_heartbeat() reports False
        pass
    try:
        lib.srjt_device_stats_json.restype = ctypes.c_char_p
    except AttributeError:
        # pre-metrics .so: device_stats() reports None
        pass
    lib.srjt_device_groupby_sum.restype = ctypes.c_int32
    lib.srjt_device_groupby_sum.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int64),
    ]
    return lib


def native_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        for path in _candidate_paths():
            if os.path.exists(path):
                try:
                    _LIB = _bind(ctypes.CDLL(path))
                    break
                except (OSError, AttributeError):
                    # unloadable, or a stale build missing newer symbols:
                    # fall through to the next candidate / pure-Python path
                    _LIB = None
                    continue
        return _LIB


def native_available() -> bool:
    return native_lib() is not None


def lz4_decompress_block(data: bytes, dst_capacity: int) -> bytes:
    """Decompress one LZ4 block via the native codec tier; the exact
    output size need not be known (ORC/parquet only bound it)."""
    import numpy as np

    lib = native_lib()
    if lib is None:
        raise RuntimeError("native runtime not built (run cmake in native/)")
    out = np.empty(max(dst_capacity, 1), np.uint8)
    src = ctypes.cast(ctypes.c_char_p(data), ctypes.POINTER(ctypes.c_uint8))
    n = lib.srjt_lz4_decompress_block(
        src, len(data), out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(out)
    )
    if n < 0:
        _raise_last(lib)
    return out[:n].tobytes()


def lzo1x_decompress(data: bytes, dst_capacity: int) -> bytes:
    """Decompress one LZO1X stream via the native codec tier (ORC LZO
    chunks, Hadoop-framed parquet LZO blocks)."""
    import numpy as np

    lib = native_lib()
    if lib is None:
        raise RuntimeError("native runtime not built (run cmake in native/)")
    out = np.empty(max(dst_capacity, 1), np.uint8)
    src = ctypes.cast(ctypes.c_char_p(data), ctypes.POINTER(ctypes.c_uint8))
    n = lib.srjt_lzo1x_decompress(
        src, len(data), out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(out)
    )
    if n < 0:
        _raise_last(lib)
    return out[:n].tobytes()


def zstd_frame_content_size(data: bytes) -> int:
    """Declared decompressed size of a zstd frame, or -1 if unknown."""
    lib = native_lib()
    if lib is None:
        raise RuntimeError("native runtime not built (run cmake in native/)")
    src = ctypes.cast(ctypes.c_char_p(data), ctypes.POINTER(ctypes.c_uint8))
    n = lib.srjt_zstd_frame_content_size(src, len(data))
    if n == -2:
        _raise_last(lib)
    return int(n)


def zstd_decompress(data: bytes, uncompressed_size: int) -> bytes:
    """Decompress one zstd frame via the native codec tier."""
    import numpy as np

    lib = native_lib()
    if lib is None:
        raise RuntimeError("native runtime not built (run cmake in native/)")
    out = np.empty(max(uncompressed_size, 1), np.uint8)
    src = ctypes.cast(ctypes.c_char_p(data), ctypes.POINTER(ctypes.c_uint8))
    n = lib.srjt_zstd_decompress(
        src, len(data), out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), uncompressed_size
    )
    if n < 0:
        _raise_last(lib)
    return out[:n].tobytes()


def byte_array_lens(page: bytes):
    """Walk a parquet PLAIN BYTE_ARRAY page in C: per-value lengths.
    The upper bound on values is size/4 (each costs a 4-byte prefix)."""
    import numpy as np

    lib = native_lib()
    if lib is None:
        raise RuntimeError("native runtime not built (run cmake in native/)")
    cap = max(len(page) // 4 + 1, 1)
    out = np.empty(cap, np.int32)
    # borrow the bytes object's buffer (C side only reads) — no memcpy
    src = ctypes.cast(ctypes.c_char_p(page), ctypes.POINTER(ctypes.c_uint8))
    n = lib.srjt_byte_array_lens(
        src,
        len(page),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        cap,
    )
    if n < 0:
        raise RuntimeError("byte_array_lens: malformed page (truncated value or overflow)")
    return out[:n].copy()


def _raise_last(lib) -> None:
    msg = lib.srjt_last_error().decode("utf-8", "replace")
    # the native faultinj tier (faultinj.cc) prefixes its injected
    # errors so the failure-classification taxonomy sees them the same
    # way the Python tier's injected faults are seen
    if msg.startswith("RETRYABLE:"):
        from .utils.errors import RetryableError

        raise RetryableError(f"native runtime error: {msg}")
    if msg.startswith("FATAL:"):
        from .utils.errors import FatalDeviceError

        raise FatalDeviceError(f"native runtime error: {msg}")
    raise RuntimeError(f"native runtime error: {msg}")


def faultinj_configure(path: str) -> None:
    """Install a fault-injection config at the NATIVE C-ABI boundary
    (faultinj.cc; same JSON schema as utils/faultinj.py)."""
    lib = native_lib()
    if lib is None:
        raise RuntimeError("native runtime not built")
    if lib.srjt_faultinj_configure(path.encode()) != 0:
        _raise_last(lib)


def faultinj_disable() -> None:
    lib = native_lib()
    if lib is not None:
        lib.srjt_faultinj_disable()


def live_handles() -> int:
    """Leak accounting across all native handle types."""
    lib = native_lib()
    return 0 if lib is None else int(lib.srjt_live_handles())


class NativeParquetFooter:
    """Handle to a natively parsed+pruned footer — the ParquetFooter.java
    surface (readAndFilter :200, getNumRows :113, getNumColumns :120,
    serializeThriftFile :106, close :124) over the C ABI."""

    def __init__(self, handle: int, lib: ctypes.CDLL):
        self._handle = handle
        self._lib = lib

    @classmethod
    def read_and_filter(
        cls,
        buf: bytes,
        part_offset: int,
        part_length: int,
        schema: StructElement,
        ignore_case: bool = False,
    ) -> "NativeParquetFooter":
        lib = native_lib()
        if lib is None:
            raise RuntimeError("native runtime not built (run cmake in native/)")
        names, num_children, tags, parent_n = flatten_schema(schema)
        if ignore_case:
            # requested names fold API-side, like ParquetFooter.java:207
            names = [s.lower() for s in names]
        n = len(names)
        names_arr = (ctypes.c_char_p * n)(*[s.encode() for s in names])
        nc_arr = (ctypes.c_int32 * n)(*num_children)
        tag_arr = (ctypes.c_int32 * n)(*tags)
        h = lib.srjt_footer_read_and_filter(
            buf,
            len(buf),
            part_offset,
            part_length,
            ctypes.cast(names_arr, ctypes.POINTER(ctypes.c_char_p)),
            ctypes.cast(nc_arr, ctypes.POINTER(ctypes.c_int32)),
            ctypes.cast(tag_arr, ctypes.POINTER(ctypes.c_int32)),
            n,
            parent_n,
            1 if ignore_case else 0,
        )
        if h == 0:
            _raise_last(lib)
        return cls(h, lib)

    def get_num_rows(self) -> int:
        v = self._lib.srjt_footer_num_rows(self._handle)
        if v < 0:
            _raise_last(self._lib)
        return int(v)

    def get_num_columns(self) -> int:
        v = self._lib.srjt_footer_num_columns(self._handle)
        if v < 0:
            _raise_last(self._lib)
        return int(v)

    def serialize_thrift_file(self) -> bytes:
        size = ctypes.c_int64(0)
        blob = self._lib.srjt_footer_serialize(self._handle, ctypes.byref(size))
        if blob == 0:
            _raise_last(self._lib)
        try:
            out = ctypes.create_string_buffer(size.value)
            if self._lib.srjt_blob_copy(blob, out, size.value) != 0:
                _raise_last(self._lib)
            return out.raw
        finally:
            self._lib.srjt_blob_free(blob)

    def close(self) -> None:
        if self._handle:
            self._lib.srjt_footer_close(self._handle)
            self._handle = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class NativeHostBuffer:
    """Aligned host staging buffer (HostMemoryBuffer analog)."""

    def __init__(self, size: int, alignment: int = 64):
        lib = native_lib()
        if lib is None:
            raise RuntimeError("native runtime not built (run cmake in native/)")
        self._lib = lib
        self._handle = lib.srjt_host_alloc(size, alignment)
        if self._handle == 0:
            _raise_last(lib)
        self.size = size

    @property
    def address(self) -> int:
        return int(self._lib.srjt_host_ptr(self._handle) or 0)

    def write(self, data: bytes, offset: int = 0) -> None:
        if offset < 0 or offset + len(data) > self.size:
            raise ValueError("write out of bounds")
        ctypes.memmove(self.address + offset, data, len(data))

    def read(self, length: int, offset: int = 0) -> bytes:
        if length < 0 or offset < 0 or offset + length > self.size:
            raise ValueError("read out of bounds")
        return ctypes.string_at(self.address + offset, length)

    def close(self) -> None:
        if self._handle:
            self._lib.srjt_host_free(self._handle)
            self._handle = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    @staticmethod
    def bytes_in_use() -> int:
        lib = native_lib()
        return 0 if lib is None else int(lib.srjt_host_bytes_in_use())


# ---------------------------------------------------------------------------
# columnar engine bindings (JVM-facing contract, ctypes-testable)
# ---------------------------------------------------------------------------


class NativeCastError(RuntimeError):
    """CastException shape (reference CastException.java:25-36)."""

    def __init__(self, row_with_error: int, string_with_error: str):
        super().__init__(
            f"Error casting data on row {row_with_error}: {string_with_error!r}"
        )
        self.row_with_error = int(row_with_error)
        self.string_with_error = string_with_error


class NativeColumn:
    """Owned handle to a native column (ai.rapids.cudf.ColumnVector
    analog over the srjt C ABI)."""

    def __init__(self, handle: int, lib):
        self._handle = handle
        self._lib = lib

    @property
    def handle(self) -> int:
        return self._handle

    @classmethod
    def from_python(cls, col) -> "NativeColumn":
        """Build from a spark_rapids_jni_tpu.columnar.Column (host copy)."""
        import numpy as np

        from .columnar.dtype import TypeId

        lib = native_lib()
        if lib is None:
            raise RuntimeError("native runtime not built (run cmake in native/)")
        n = len(col)
        d = col.dtype
        validity = None
        if col.validity is not None:
            validity = np.asarray(col.validity).astype(np.uint8)
        vp = validity.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)) if validity is not None else None
        if d.id in (TypeId.STRING, TypeId.LIST):
            offsets = np.ascontiguousarray(np.asarray(col.offsets), dtype=np.int32)
            payload = col.chars if d.id == TypeId.STRING else col.child.data
            chars = np.ascontiguousarray(np.asarray(payload)).view(np.uint8)
            h = lib.srjt_column_create(
                int(d.id), getattr(d, "scale", 0) or 0, n, None, 0, vp,
                offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                chars.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)) if chars.size else None,
                int(chars.size),
            )
        else:
            data = np.ascontiguousarray(np.asarray(col.data))
            raw = data.view(np.uint8).reshape(-1)
            h = lib.srjt_column_create(
                int(d.id), getattr(d, "scale", 0) or 0, n,
                raw.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), int(raw.size),
                vp, None, None, 0,
            )
        if h == 0:
            _raise_last(lib)
        return cls(h, lib)

    def to_python(self, dtype):
        """Copy back as a spark_rapids_jni_tpu.columnar.Column."""
        import numpy as np

        import jax.numpy as jnp

        from .columnar import Column
        from .columnar.dtype import TypeId

        lib, h = self._lib, self._handle
        n = int(lib.srjt_column_size(h))
        valid = None
        if lib.srjt_column_has_validity(h):
            vbuf = np.empty(n, np.uint8)
            if lib.srjt_column_copy_validity(h, vbuf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n) != 0:
                _raise_last(lib)
            valid = jnp.asarray(vbuf.astype(bool))
        if dtype.id in (TypeId.STRING, TypeId.LIST):
            obuf = np.empty(n + 1, np.int32)
            if lib.srjt_column_copy_offsets(h, obuf.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), n + 1) != 0:
                _raise_last(lib)
            nchars = int(lib.srjt_column_chars_bytes(h))
            cbuf = np.empty(max(nchars, 1), np.uint8)
            if nchars and lib.srjt_column_copy_chars(h, cbuf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), nchars) != 0:
                _raise_last(lib)
            cbuf = cbuf[:nchars]
            if dtype.id == TypeId.STRING:
                return Column(dtype, validity=valid, offsets=jnp.asarray(obuf), chars=jnp.asarray(cbuf))
            from .columnar import dtype as dt_mod

            child = Column(dt_mod.INT8, data=jnp.asarray(cbuf.view(np.int8)))
            return Column(dtype, validity=valid, offsets=jnp.asarray(obuf), child=child)
        nbytes = int(lib.srjt_column_data_bytes(h))
        dbuf = np.empty(max(nbytes, 1), np.uint8)
        if nbytes and lib.srjt_column_copy_data(h, dbuf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), nbytes) != 0:
            _raise_last(lib)
        dbuf = dbuf[:nbytes]
        if dtype.id == TypeId.DECIMAL128:
            data = jnp.asarray(dbuf.view(np.uint32).reshape(n, 4))
        else:
            data = jnp.asarray(dbuf.view(np.dtype(dtype.np_dtype)))
        return Column(dtype, data=data, validity=valid)

    def close(self) -> None:
        if self._handle:
            self._lib.srjt_column_close(self._handle)
            self._handle = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class NativeTable:
    """Owned handle to a native table (ai.rapids.cudf.Table analog)."""

    def __init__(self, handle: int, lib):
        self._handle = handle
        self._lib = lib

    @property
    def handle(self) -> int:
        return self._handle

    @classmethod
    def from_python(cls, table) -> "NativeTable":
        lib = native_lib()
        if lib is None:
            raise RuntimeError("native runtime not built (run cmake in native/)")
        ncols = []
        try:
            for c in table.columns:
                ncols.append(NativeColumn.from_python(c))
            arr = (ctypes.c_int64 * len(ncols))(*[c.handle for c in ncols])
            h = lib.srjt_table_create(arr, len(ncols))
            if h == 0:
                _raise_last(lib)
            return cls(h, lib)
        finally:
            for c in ncols:
                c.close()

    @property
    def num_rows(self) -> int:
        return int(self._lib.srjt_table_num_rows(self._handle))

    @property
    def num_columns(self) -> int:
        return int(self._lib.srjt_table_num_columns(self._handle))

    def column(self, i: int) -> NativeColumn:
        h = self._lib.srjt_table_column(self._handle, i)
        if h == 0:
            _raise_last(self._lib)
        return NativeColumn(h, self._lib)

    def close(self) -> None:
        if self._handle:
            self._lib.srjt_table_close(self._handle)
            self._handle = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def device_connect(python_exe: Optional[str] = None, timeout_sec: int = 120) -> str:
    """Spawn + connect the device sidecar worker (the JNI->TPU path,
    PACKAGING.md): after this, eligible C-ABI ops execute on the
    worker's jax backend. Returns the backend platform name."""
    lib = native_lib()
    if lib is None:
        raise RuntimeError("native runtime not built (run cmake in native/)")
    # the forked worker resolves the package through PYTHONPATH — make
    # sure this package's parent directory is on it (a JVM deployment
    # sets this in the executor launch env; see PACKAGING.md)
    pkg_parent = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pp = os.environ.get("PYTHONPATH", "")
    if pkg_parent not in pp.split(os.pathsep):
        os.environ["PYTHONPATH"] = f"{pkg_parent}{os.pathsep}{pp}" if pp else pkg_parent
    exe = (python_exe or "").encode()
    if lib.srjt_device_connect(exe, timeout_sec) != 0:
        _raise_last(lib)
    return device_platform()


def device_platform() -> str:
    """Connected sidecar's jax backend name, or '' when disconnected."""
    lib = native_lib()
    if lib is None:
        return ""
    return lib.srjt_device_platform().decode()


def device_shutdown() -> None:
    lib = native_lib()
    if lib is not None:
        lib.srjt_device_shutdown()


def device_heartbeat() -> bool:
    """Liveness probe of the connected sidecar worker: True iff a PING
    round-trips on a throwaway connection under the short probe
    deadline (SRJT_SIDECAR_HEARTBEAT_TIMEOUT_SEC, default 5 s — NOT
    the heavy-op SRJT_SIDECAR_TIMEOUT_SEC). False means no sidecar, a
    wedged worker, or a libsrjt.so predating the supervision ABI."""
    lib = native_lib()
    if lib is None or not hasattr(lib, "srjt_device_heartbeat"):
        return False
    return bool(lib.srjt_device_heartbeat())


def device_stats(fold: bool = True):
    """Observability snapshot of the device sidecar path(s): the C++
    client's supervision counters (requests, request_failures,
    reconnects, heartbeats) plus the worker's metrics-registry
    snapshot fetched over the STATS protocol verb — and, when a
    Python-side worker POOL is connected (sidecar_pool.py, ISSUE 5),
    the snapshots of EVERY live pool worker merged in keyed per worker
    id (``pool_workers: {"w0": ..., "w1": ...}``) instead of assuming
    one connection. None when no native library/sidecar AND no pool.

    With ``fold`` (default) the numbers land in this process's
    utils/metrics registry as gauges — ``sidecar.native.*`` for the
    client counters, the single native worker through the shared
    utils/metrics.fold_worker_counters policy (``sidecar.worker.*``),
    and each pool worker under ``sidecar.worker.w<id>.*``."""
    import json

    from . import sidecar_pool
    from .utils import metrics

    stats = None
    lib = native_lib()
    if lib is not None and hasattr(lib, "srjt_device_stats_json"):
        raw = lib.srjt_device_stats_json()
        if raw:
            try:
                stats = json.loads(raw.decode("utf-8", "replace"))
            except ValueError:
                stats = None
    if stats is not None and fold:
        reg = metrics.registry()
        for k, v in (stats.get("client") or {}).items():
            reg.gauge(f"sidecar.native.{k}").set(v)
        worker = stats.get("worker")
        if isinstance(worker, dict):
            metrics.fold_worker_counters(
                (worker.get("snapshot") or {}).get("counters")
            )
    pool = sidecar_pool.current_pool()
    if pool is not None:
        merged = stats if stats is not None else {}
        merged["pool_workers"] = pool.worker_stats(fold=fold)
        return merged
    return stats


def stats_report(pretty: bool = False):
    """End-to-end pipeline stats: ONE snapshot assembling every
    observability tier — the metrics registry (per-op timings, shuffle
    movement, sidecar supervision, event counts), the retry
    orchestrator's counters, the memory tier's split count, and the
    native sidecar's STATS report when one is connected (folded into
    the registry first so the ``metrics`` section is complete).

    The ``retry`` section carries the deadline outcomes
    (``deadline_exceeded`` — gave up on budget — vs ``exhausted`` —
    gave up on attempts — plus ``backoff_truncated``); ``breaker`` is
    the sidecar circuit breaker's state machine (state,
    open/half-open/closed transition counts, fast-fails, last trip
    cause); ``deadline`` reports the ambient SRJT_DEADLINE_SEC budget
    and whether a scope is active at snapshot time; ``memgov`` is the
    memory governor (ISSUE 4): admission counters and queue-wait
    histogram, spilled/re-materialized bytes, and the catalog's
    per-tier occupancy including sidecar arena registrations.

    ``pool`` is the sidecar worker pool's state (sidecar_pool.py,
    ISSUE 5: per-worker liveness, failovers, respawns, re-hydrations —
    None until a pool is connected) and ``integrity`` the CRC layer's
    verdicts (frames/spills/exchanges checked, ``crc_mismatch`` — the
    count that separates "corruption caught" from "wrong answer").

    ``health`` and ``hedge`` are the tail-tolerance layer (ISSUE 9):
    gray-failure quarantine verdicts (quarantines, probe counts,
    reinstatements, per-worker latency EWMAs when a pool is live) and
    hedged-dispatch accounting (launched/won/cancelled/suppressed plus
    adaptive-timeout clamp counts from both the sidecar client and the
    TCP exchange).

    ``serve`` is the concurrent serving runtime (serve/, ISSUE 8:
    submissions/completions, shed counts per cause, expired-in-queue,
    and every live scheduler's tenant/queue snapshot — None until a
    scheduler has ever been created).

    ``durability`` is the crash-recovery tier (ISSUE 20): the query
    journal's append/replay/truncation/idempotent-hit counters (None
    until a journal was ever active) and the spill-manifest layer's
    written/rot/re-attached/orphans-reclaimed counters.

    Returns a JSON-serializable dict; ``pretty=True`` returns the
    aligned text rendering (utils/metrics.render_report) instead —
    the one-command artifact VERDICT items 5/7/8 ask for."""
    from . import cache, memgov, serve, sidecar, sidecar_pool
    from .memgov import persist as _persist  # noqa: F401 (binds memgov.persist)
    from .utils import deadline as deadline_mod
    from .utils import integrity, memory, metrics, retry, trace_sink

    native = device_stats(fold=True)
    report = {
        "metrics": metrics.snapshot(),
        # ISSUE 12: srjt-trace — span/trace volume, sampling, and the
        # flight recorder's ring state (the worst recent query itself
        # renders via runtime.explain_last())
        "trace": trace_sink.stats_section(),
        "retry": retry.stats(),
        "memory": {"split_retries": memory.split_retry_count()},
        "memgov": memgov.stats_section(),
        "breaker": sidecar.breaker().snapshot(),
        "pool": sidecar_pool.stats_section(),
        "health": sidecar_pool.health_section(),
        "hedge": sidecar_pool.hedge_section(),
        "serve": serve.stats_section(),
        # ISSUE 17: srjt-cache — plan-cache hit economics, governed
        # subresult footprint, in-flight sharing, knob posture
        "cache": cache.stats_section(),
        # ISSUE 20: srjt-durable — the journal half is None until a
        # journal was ever active this process; the persist half is
        # registry-direct (zeros) so the sweep/re-attach counters
        # answer even when manifests never armed
        "durability": {
            "journal": serve.journal.stats_section(),
            "persist": memgov.persist.stats_counters(),
        },
        "integrity": integrity.stats_section(),
        "deadline": {
            "default_budget_s": deadline_mod.default_budget(),
            "active_scope": deadline_mod.current() is not None,
        },
        "native_sidecar": native,
    }
    if pretty:
        return metrics.render_report(report)
    return report


def explain_last():
    """Render the WORST recent traced query (failures and sheds first,
    then duration) as an annotated span tree — the flight recorder's
    one-command answer to "why was THAT query slow" (ISSUE 12). Returns
    None when tracing never recorded a query in this process. The
    rendering is this process's view; cross-process spans (sidecar
    workers, exchange peers) live in the per-process span logs, joined
    by ``python -m spark_rapids_jni_tpu.analysis.tracemerge``."""
    from .utils import trace_sink

    return trace_sink.explain_last()


def device_groupby_sum(keys, vals, num_keys: int, deadline_s: Optional[float] = None):
    """GROUP BY SUM executed on the sidecar's device (the MXU Pallas
    kernel when the backend is a TPU). keys int64[n], vals float32[n].

    With the retry orchestrator armed (SRJT_RETRY_ENABLED=1 /
    utils.retry.enable()), RETRYABLE-classified native failures —
    including the native fault injector's ``RETRYABLE:``-prefixed
    storms — re-run under bounded backoff before surfacing.

    ``deadline_s`` opens a per-call deadline scope (utils/deadline.py;
    an ambient SRJT_DEADLINE_SEC applies when unset and no scope is
    active): the orchestrator's backoffs truncate to the budget and
    attempts stop with DeadlineExceeded when it is gone. The native
    call itself blocks under the C++ client's own socket deadline
    (SRJT_SIDECAR_TIMEOUT_SEC) — the budget bounds when attempts may
    START; the socket deadline bounds how long one can run."""
    import numpy as np

    from .utils import deadline as deadline_mod
    from .utils import retry

    lib = native_lib()
    if lib is None:
        raise RuntimeError("native runtime not built (run cmake in native/)")
    keys = np.ascontiguousarray(keys, np.int64)
    vals = np.ascontiguousarray(vals, np.float32)
    if len(keys) != len(vals):
        raise ValueError(f"keys/vals length mismatch: {len(keys)} vs {len(vals)}")
    sums = np.empty(num_keys, np.float32)
    counts = np.empty(num_keys, np.int64)

    def attempt():
        rc = lib.srjt_device_groupby_sum(
            keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            len(keys), num_keys,
            sums.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        if rc != 0:
            _raise_last(lib)

    # same nesting guard as utils/dispatch.py: when an enclosing armed
    # boundary already owns a retry loop, this op must not multiply it
    with deadline_mod.op_scope(deadline_s) as d:
        if d is not None:
            d.check("device_groupby_sum")
        if retry.is_enabled() and not retry.in_attempt():
            retry.call_with_retry(attempt, op_name="device_groupby_sum")
        else:
            attempt()
    return sums, counts


def native_convert_to_rows(table: "NativeTable") -> NativeColumn:
    """RowConversion.convertToRows through the C ABI."""
    lib = table._lib
    h = lib.srjt_convert_to_rows(table.handle)
    if h == 0:
        _raise_last(lib)
    return NativeColumn(h, lib)


def native_convert_to_rows_batched(
    table: "NativeTable", max_batch_bytes: int = 0
) -> List[NativeColumn]:
    """RowConversion.convertToRows with internal batch splitting: one
    LIST<INT8> column per <= max_batch_bytes batch (0 = the 2 GiB
    size_type default). The injectable limit is the test hook for the
    reference's build_batches discipline."""
    lib = table._lib
    cap = 1024
    handles = (ctypes.c_int64 * cap)()
    n = lib.srjt_convert_to_rows_batched(table.handle, max_batch_bytes, handles, cap)
    if n < 0:
        _raise_last(lib)
    return [NativeColumn(handles[i], lib) for i in range(n)]


def native_convert_from_rows(rows: NativeColumn, dtypes) -> NativeTable:
    """RowConversion.convertFromRows through the C ABI."""
    lib = rows._lib
    ids = (ctypes.c_int32 * len(dtypes))(*[int(d.id) for d in dtypes])
    scales = (ctypes.c_int32 * len(dtypes))(*[getattr(d, "scale", 0) or 0 for d in dtypes])
    h = lib.srjt_convert_from_rows(rows.handle, ids, scales, len(dtypes))
    if h == 0:
        _raise_last(lib)
    return NativeTable(h, lib)


def _raise_cast_or_last(lib) -> None:
    """ANSI cast-error protocol (CATCH_CAST_EXCEPTION shape): raise
    NativeCastError with the first failing row when one is pending,
    else the generic native error."""
    if lib.srjt_last_cast_error_pending():
        raise NativeCastError(
            int(lib.srjt_last_cast_row()),
            lib.srjt_last_cast_string().decode("utf-8", "replace"),
        )
    _raise_last(lib)


def native_cast_string_to_integer(col: NativeColumn, ansi_mode: bool, out_dtype) -> NativeColumn:
    """CastStrings.toInteger through the C ABI; raises NativeCastError
    in ANSI mode on the first failing row."""
    lib = col._lib
    h = lib.srjt_cast_string_to_integer(col.handle, 1 if ansi_mode else 0, int(out_dtype.id))
    if h == 0:
        _raise_cast_or_last(lib)
    return NativeColumn(h, lib)


def native_cast_string_to_decimal(
    col: NativeColumn, ansi_mode: bool, precision: int, scale: int
) -> NativeColumn:
    """CastStrings.toDecimal through the C ABI; raises NativeCastError
    in ANSI mode on the first failing row."""
    lib = col._lib
    h = lib.srjt_cast_string_to_decimal(col.handle, 1 if ansi_mode else 0, precision, scale)
    if h == 0:
        _raise_cast_or_last(lib)
    return NativeColumn(h, lib)


def native_zorder_interleave_bits(table: NativeTable) -> NativeColumn:
    """ZOrder.interleaveBits through the C ABI."""
    lib = table._lib
    h = lib.srjt_zorder_interleave_bits(table.handle)
    if h == 0:
        _raise_last(lib)
    return NativeColumn(h, lib)


def live_columnar_handles() -> int:
    lib = native_lib()
    return 0 if lib is None else int(lib.srjt_live_columnar_handles())


def native_multiply_decimal128(a: NativeColumn, b: NativeColumn, product_scale: int) -> NativeTable:
    """DecimalUtils.multiply128 through the C ABI."""
    lib = a._lib
    h = lib.srjt_multiply_decimal128(a.handle, b.handle, product_scale)
    if h == 0:
        _raise_last(lib)
    return NativeTable(h, lib)


def native_divide_decimal128(a: NativeColumn, b: NativeColumn, quotient_scale: int) -> NativeTable:
    """DecimalUtils.divide128 through the C ABI."""
    lib = a._lib
    h = lib.srjt_divide_decimal128(a.handle, b.handle, quotient_scale)
    if h == 0:
        _raise_last(lib)
    return NativeTable(h, lib)

"""ctypes bindings to the native runtime (native/libsrjt.so).

NativeDepsLoader analog (reference RowConversion.java:23-25 +
pom.xml:443-474 packaging): locate and load the shared library once,
expose the handle-based C ABI as Python classes with explicit close()
ownership — the same discipline the reference's Java API uses over
jlong handles. Falls back gracefully: ``native_available()`` is False
when the library isn't built, and callers (tests, the pure-Python
footer service) keep working.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import List, Optional

from .io.parquet_footer import StructElement, flatten_schema

__all__ = [
    "native_available",
    "native_lib",
    "live_handles",
    "snappy_uncompress",
    "NativeParquetFooter",
    "NativeHostBuffer",
]


def snappy_uncompress(data: bytes, expected_size: Optional[int] = None) -> bytes:
    """Decompress a snappy block via the native codec tier (nvcomp
    analog). Raises RuntimeError if the native library is missing or the
    stream is malformed."""
    lib = native_lib()
    if lib is None:
        raise RuntimeError("native runtime not built (run cmake in native/)")
    n = lib.srjt_snappy_uncompressed_length(data, len(data))
    if n < 0:
        _raise_last(lib)
    if expected_size is not None and n != expected_size:
        raise RuntimeError(f"snappy: preamble size {n} != expected {expected_size}")
    if expected_size is None and n > max(len(data), 1) * 128:
        # the format can't expand anywhere near this much: an attacker-
        # controlled preamble must not drive a giant allocation
        raise RuntimeError(f"snappy: implausible uncompressed size {n}")
    out = ctypes.create_string_buffer(int(n))
    if lib.srjt_snappy_uncompress(data, len(data), out, n) != 0:
        _raise_last(lib)
    return out.raw

_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _candidate_paths() -> List[str]:
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(here)
    cands = []
    env = os.environ.get("SRJT_NATIVE_LIB")
    if env:
        cands.append(env)
    cands.append(os.path.join(here, "libsrjt.so"))  # packaged next to the module
    cands.append(os.path.join(repo, "native", "build", "libsrjt.so"))  # dev build
    return cands


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.srjt_last_error.restype = ctypes.c_char_p
    lib.srjt_live_handles.restype = ctypes.c_int64
    lib.srjt_footer_read_and_filter.restype = ctypes.c_int64
    lib.srjt_footer_read_and_filter.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int32,
        ctypes.c_int32,
        ctypes.c_int32,
    ]
    lib.srjt_footer_num_rows.restype = ctypes.c_int64
    lib.srjt_footer_num_rows.argtypes = [ctypes.c_int64]
    lib.srjt_footer_num_columns.restype = ctypes.c_int32
    lib.srjt_footer_num_columns.argtypes = [ctypes.c_int64]
    lib.srjt_footer_serialize.restype = ctypes.c_int64
    lib.srjt_footer_serialize.argtypes = [ctypes.c_int64, ctypes.POINTER(ctypes.c_int64)]
    lib.srjt_blob_copy.restype = ctypes.c_int32
    lib.srjt_blob_copy.argtypes = [ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64]
    lib.srjt_blob_free.argtypes = [ctypes.c_int64]
    lib.srjt_footer_close.argtypes = [ctypes.c_int64]
    lib.srjt_host_alloc.restype = ctypes.c_int64
    lib.srjt_host_alloc.argtypes = [ctypes.c_int64, ctypes.c_int64]
    lib.srjt_host_ptr.restype = ctypes.c_void_p
    lib.srjt_host_ptr.argtypes = [ctypes.c_int64]
    lib.srjt_host_size.restype = ctypes.c_int64
    lib.srjt_host_size.argtypes = [ctypes.c_int64]
    lib.srjt_host_free.argtypes = [ctypes.c_int64]
    lib.srjt_host_bytes_in_use.restype = ctypes.c_int64
    lib.srjt_snappy_uncompressed_length.restype = ctypes.c_int64
    lib.srjt_snappy_uncompressed_length.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.srjt_snappy_uncompress.restype = ctypes.c_int32
    lib.srjt_snappy_uncompress.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_char_p,
        ctypes.c_int64,
    ]
    return lib


def native_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        for path in _candidate_paths():
            if os.path.exists(path):
                try:
                    _LIB = _bind(ctypes.CDLL(path))
                    break
                except (OSError, AttributeError):
                    # unloadable, or a stale build missing newer symbols:
                    # fall through to the next candidate / pure-Python path
                    _LIB = None
                    continue
        return _LIB


def native_available() -> bool:
    return native_lib() is not None


def _raise_last(lib) -> None:
    msg = lib.srjt_last_error().decode("utf-8", "replace")
    raise RuntimeError(f"native runtime error: {msg}")


def live_handles() -> int:
    """Leak accounting across all native handle types."""
    lib = native_lib()
    return 0 if lib is None else int(lib.srjt_live_handles())


class NativeParquetFooter:
    """Handle to a natively parsed+pruned footer — the ParquetFooter.java
    surface (readAndFilter :200, getNumRows :113, getNumColumns :120,
    serializeThriftFile :106, close :124) over the C ABI."""

    def __init__(self, handle: int, lib: ctypes.CDLL):
        self._handle = handle
        self._lib = lib

    @classmethod
    def read_and_filter(
        cls,
        buf: bytes,
        part_offset: int,
        part_length: int,
        schema: StructElement,
        ignore_case: bool = False,
    ) -> "NativeParquetFooter":
        lib = native_lib()
        if lib is None:
            raise RuntimeError("native runtime not built (run cmake in native/)")
        names, num_children, tags, parent_n = flatten_schema(schema)
        if ignore_case:
            # requested names fold API-side, like ParquetFooter.java:207
            names = [s.lower() for s in names]
        n = len(names)
        names_arr = (ctypes.c_char_p * n)(*[s.encode() for s in names])
        nc_arr = (ctypes.c_int32 * n)(*num_children)
        tag_arr = (ctypes.c_int32 * n)(*tags)
        h = lib.srjt_footer_read_and_filter(
            buf,
            len(buf),
            part_offset,
            part_length,
            ctypes.cast(names_arr, ctypes.POINTER(ctypes.c_char_p)),
            ctypes.cast(nc_arr, ctypes.POINTER(ctypes.c_int32)),
            ctypes.cast(tag_arr, ctypes.POINTER(ctypes.c_int32)),
            n,
            parent_n,
            1 if ignore_case else 0,
        )
        if h == 0:
            _raise_last(lib)
        return cls(h, lib)

    def get_num_rows(self) -> int:
        v = self._lib.srjt_footer_num_rows(self._handle)
        if v < 0:
            _raise_last(self._lib)
        return int(v)

    def get_num_columns(self) -> int:
        v = self._lib.srjt_footer_num_columns(self._handle)
        if v < 0:
            _raise_last(self._lib)
        return int(v)

    def serialize_thrift_file(self) -> bytes:
        size = ctypes.c_int64(0)
        blob = self._lib.srjt_footer_serialize(self._handle, ctypes.byref(size))
        if blob == 0:
            _raise_last(self._lib)
        try:
            out = ctypes.create_string_buffer(size.value)
            if self._lib.srjt_blob_copy(blob, out, size.value) != 0:
                _raise_last(self._lib)
            return out.raw
        finally:
            self._lib.srjt_blob_free(blob)

    def close(self) -> None:
        if self._handle:
            self._lib.srjt_footer_close(self._handle)
            self._handle = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class NativeHostBuffer:
    """Aligned host staging buffer (HostMemoryBuffer analog)."""

    def __init__(self, size: int, alignment: int = 64):
        lib = native_lib()
        if lib is None:
            raise RuntimeError("native runtime not built (run cmake in native/)")
        self._lib = lib
        self._handle = lib.srjt_host_alloc(size, alignment)
        if self._handle == 0:
            _raise_last(lib)
        self.size = size

    @property
    def address(self) -> int:
        return int(self._lib.srjt_host_ptr(self._handle) or 0)

    def write(self, data: bytes, offset: int = 0) -> None:
        if offset < 0 or offset + len(data) > self.size:
            raise ValueError("write out of bounds")
        ctypes.memmove(self.address + offset, data, len(data))

    def read(self, length: int, offset: int = 0) -> bytes:
        if length < 0 or offset < 0 or offset + length > self.size:
            raise ValueError("read out of bounds")
        return ctypes.string_at(self.address + offset, length)

    def close(self) -> None:
        if self._handle:
            self._lib.srjt_host_free(self._handle)
            self._handle = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    @staticmethod
    def bytes_in_use() -> int:
        lib = native_lib()
        return 0 if lib is None else int(lib.srjt_host_bytes_in_use())

"""Device sidecar worker: the JNI->TPU execution path.

The reference's JNI entry points land directly on device kernels
(RowConversionJni.cpp:42 -> row_conversion.cu:1903) because CUDA lives
in-process. The TPU runtime here is JAX/XLA, whose Python front end
cannot be embedded in a JVM executor process; the deployment model
(PACKAGING.md) is therefore a SIDECAR: ``libsrjt.so`` spawns this
module as a child process that owns the chip, and dispatches ops over a
Unix-domain socket with a length-prefixed binary protocol. The JVM
process never hosts a Python interpreter; the native library falls back
to its host-CPU engine when no sidecar/chip is available.

Wire protocol (little-endian):
  request:  [u32 op] [u64 payload_len] [payload]
  response: [u32 status(0=ok)] [u64 payload_len] [payload | utf-8 error]

Ops:
  0 PING              -> payload = jax backend name (b"tpu"/b"cpu"/...)
  1 GROUPBY_SUM_F32   in:  u32 num_keys, u64 n, i64[n] keys, f32[n] vals
                      out: f32[num_keys] sums, i64[num_keys] counts
                      (groupby_sum_bounded: the MXU outer-product kernel
                      on TPU)
  2 CONVERT_TO_ROWS   in:  serialized table (see _read_table)
                      out: u32 nbatches, per batch: u64 nrows,
                           i32[nrows+1] offsets, u64 blob_len, u8 blob
  255 SHUTDOWN        -> empty ok, then the server exits
"""

from __future__ import annotations

import argparse
import os
import socket
import struct
import sys

OP_PING = 0
OP_GROUPBY_SUM_F32 = 1
OP_CONVERT_TO_ROWS = 2
OP_SHUTDOWN = 255


def _recv_exact(conn: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("sidecar: peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _read_table(payload: bytes):
    """Deserialize: u32 ncols; per col: i32 type_id, i32 scale, u64 n,
    u8 has_validity, [n] u8 validity, then either (u64 data_len, bytes)
    for fixed width or (i32[n+1] offsets, u64 chars_len, bytes) for
    STRING."""
    import jax.numpy as jnp
    import numpy as np

    from .columnar import Column, Table
    from .columnar.dtype import DType, TypeId

    pos = 0
    (ncols,) = struct.unpack_from("<I", payload, pos)
    pos += 4
    cols = []
    for _ in range(ncols):
        type_id, scale = struct.unpack_from("<ii", payload, pos)
        pos += 8
        (n,) = struct.unpack_from("<Q", payload, pos)
        pos += 8
        has_validity = payload[pos]
        pos += 1
        validity = None
        if has_validity:
            validity = jnp.asarray(np.frombuffer(payload, np.uint8, n, pos).astype(bool))
            pos += n
        tid = TypeId(type_id)
        d = DType(tid, scale if tid.name.startswith("DECIMAL") else 0)
        if tid == TypeId.STRING:
            offs = np.frombuffer(payload, np.int32, n + 1, pos)
            pos += 4 * (n + 1)
            (clen,) = struct.unpack_from("<Q", payload, pos)
            pos += 8
            chars = np.frombuffer(payload, np.uint8, clen, pos)
            pos += clen
            cols.append(
                Column(d, validity=validity, offsets=jnp.asarray(offs), chars=jnp.asarray(chars))
            )
        else:
            (dlen,) = struct.unpack_from("<Q", payload, pos)
            pos += 8
            raw = payload[pos : pos + dlen]
            pos += dlen
            if tid == TypeId.DECIMAL128:
                data = np.frombuffer(raw, np.uint32).reshape(n, 4)
            else:
                data = np.frombuffer(raw, np.dtype(d.np_dtype))
            cols.append(Column(d, data=jnp.asarray(data), validity=validity))
    return Table(cols)


def _op_groupby_sum(payload: bytes) -> bytes:
    import numpy as np

    from .ops.aggregate import groupby_sum_bounded

    (num_keys,) = struct.unpack_from("<I", payload, 0)
    (n,) = struct.unpack_from("<Q", payload, 4)
    keys = np.frombuffer(payload, np.int64, n, 12)
    vals = np.frombuffer(payload, np.float32, n, 12 + 8 * n)
    import jax.numpy as jnp

    sums, counts = groupby_sum_bounded(
        jnp.asarray(keys), jnp.asarray(vals), int(num_keys)
    )
    return np.asarray(sums, np.float32).tobytes() + np.asarray(counts, np.int64).tobytes()


def _op_convert_to_rows(payload: bytes) -> bytes:
    import numpy as np

    from .ops.row_conversion import convert_to_rows

    table = _read_table(payload)
    batches = convert_to_rows(table)
    out = [struct.pack("<I", len(batches))]
    for col in batches:
        offs = np.asarray(col.offsets, np.int32)
        blob = np.asarray(col.child.data).view(np.uint8)
        out.append(struct.pack("<Q", len(col)))
        out.append(offs.tobytes())
        out.append(struct.pack("<Q", blob.size))
        out.append(blob.tobytes())
    return b"".join(out)


def serve(sock_path: str) -> None:
    # the import defines the device backend (axon TPU when available).
    # This image preloads jax at interpreter startup with the TPU
    # platform, so an inherited JAX_PLATFORMS must be re-asserted on
    # the live config before any backend initializes (the hermetic test
    # tier pins "cpu" this way; conftest.py does the same).
    import jax

    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)

    import spark_rapids_jni_tpu  # noqa: F401  (x64 flag before arrays)

    backend = jax.default_backend()

    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        os.unlink(sock_path)
    except FileNotFoundError:
        pass
    srv.bind(sock_path)
    srv.listen(1)
    # the parent polls for this line to know the device is up
    print(f"SRJT_SIDECAR_READY backend={backend}", flush=True)
    conn, _ = srv.accept()
    try:
        while True:
            hdr = _recv_exact(conn, 12)
            op, plen = struct.unpack("<IQ", hdr)
            payload = _recv_exact(conn, plen) if plen else b""
            try:
                if op == OP_PING:
                    resp = backend.encode()
                elif op == OP_GROUPBY_SUM_F32:
                    resp = _op_groupby_sum(payload)
                elif op == OP_CONVERT_TO_ROWS:
                    resp = _op_convert_to_rows(payload)
                elif op == OP_SHUTDOWN:
                    conn.sendall(struct.pack("<IQ", 0, 0))
                    return
                else:
                    raise ValueError(f"unknown op {op}")
                conn.sendall(struct.pack("<IQ", 0, len(resp)) + resp)
            except Exception as e:  # report, keep serving
                msg = f"{type(e).__name__}: {e}".encode()
                conn.sendall(struct.pack("<IQ", 1, len(msg)) + msg)
    finally:
        conn.close()
        srv.close()
        try:
            os.unlink(sock_path)
        except FileNotFoundError:
            pass


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--socket", required=True)
    args = ap.parse_args()
    serve(args.socket)


if __name__ == "__main__":
    sys.exit(main())

"""Device sidecar worker: the JNI->TPU execution path.

The reference's JNI entry points land directly on device kernels
(RowConversionJni.cpp:42 -> row_conversion.cu:1903) because CUDA lives
in-process. The TPU runtime here is JAX/XLA, whose Python front end
cannot be embedded in a JVM executor process; the deployment model
(PACKAGING.md) is therefore a SIDECAR: ``libsrjt.so`` spawns this
module as a child process that owns the chip, and dispatches ops over a
Unix-domain socket with a length-prefixed binary protocol. The JVM
process never hosts a Python interpreter; the native library falls back
to its host-CPU engine when no sidecar/chip is available.

Wire protocol (little-endian):
  request:  [u32 op] [u64 payload_len] [u32 crc?] [payload]
  response: [u32 status(0=ok)] [u64 payload_len] [u32 crc?] [payload | utf-8 error]

Integrity (ISSUE 5): a client that sets the CRC_FLAG bit (0x40000000)
of ``op`` appends a 4-byte CRC trailer (utils/integrity.py) right
after the 12-byte header, covering the payload wherever it lives
(socket stream or arena); the worker verifies it — a mismatch answers
``status 1`` with a ``DataCorruption:`` message (retryable: the client
re-sends) — and echoes the flag back on the response with a trailer of
its own, which the client verifies before trusting a byte. The flag is
negotiated PER FRAME, so the native C++ client (which never sets it)
keeps the legacy framing byte for byte, and ``SRJT_INTEGRITY_CHECKS=0``
restores the seed posture with zero extra syscalls.

Round 5 shared-memory data plane (VERDICT r4 missing #2): a client may
send OP_SET_ARENA (9, payload = u64 size) with a memfd attached via
SCM_RIGHTS; the worker mmaps it. Afterwards either side may flag the
HIGH BIT of op/status to mean "payload lives at arena offset 0, only
the header crossed the socket". Clients that never set an arena get the
plain streaming protocol unchanged. The worker also accepts MULTIPLE
concurrent connections (one thread each, own arena each) — the
connection-pool client overlaps in-flight ops instead of serializing
under one mutex (reference PTDS posture, CMakeLists.txt:189-193).

Ops (round 4 extends the surface so every reference JNI entry can land
on the device — RowConversionJni.cpp:42, CastStringJni.cpp:48,
DecimalUtilsJni.cpp:22, ZOrderJni.cpp:24 all reach device kernels;
VERDICT r3 item 2):
  0 PING              -> payload = jax backend name (b"tpu"/b"cpu"/...)
  1 GROUPBY_SUM_F32   in:  u32 num_keys, u64 n, i64[n] keys, f32[n] vals
                      out: f32[num_keys] sums, i64[num_keys] counts
                      (groupby_sum_bounded: the MXU outer-product kernel
                      on TPU)
  2 CONVERT_TO_ROWS   in:  serialized table (see _read_table)
                      out: u32 nbatches, per batch: u64 nrows,
                           i32[nrows+1] offsets, u64 blob_len, u8 blob
  3 CONVERT_FROM_ROWS in:  u32 ncols, i32[ncols] type_ids, i32[ncols]
                           scales, u64 nrows, i32[nrows+1] offsets,
                           u64 blob_len, u8 blob
                      out: serialized table (_write_table)
  4 CAST_TO_INTEGER   in:  u8 ansi, i32 out_type_id, serialized table
                           (one STRING column)
                      out: serialized table (one column); ANSI failures
                           return status 2: i64 row, u8 is_null,
                           utf-8 value
  5 CAST_TO_DECIMAL   in:  u8 ansi, i32 precision, i32 scale,
                           serialized table (one STRING column)
                      out: as op 4
  6 ZORDER            in:  serialized table
                      out: serialized table (one LIST<UINT8> column:
                           offsets + bytes ride the STRING framing)
  7 DECIMAL128_MUL    in:  i32 product_scale, serialized table (a, b)
                      out: serialized table (overflow BOOL8, product)
  8 DECIMAL128_DIV    in:  i32 quotient_scale, serialized table (a, b)
                      out: as op 7
  10 STATS            -> utf-8 JSON: {"backend", "snapshot"} — the
                         worker's metrics-registry snapshot
                         (utils/metrics.py): per-op request counts,
                         error counts, op timings. The observability
                         verb both clients (SupervisedClient.worker_stats,
                         native sidecar.cc stats_json) poll to fold
                         worker-side counters into their own registry.
  255 SHUTDOWN        -> empty ok, then the server exits

Response status codes: 0 ok, 1 generic error (utf-8 message; the C++
client falls back to the host engine), 2 CAST ERROR (semantic ANSI
failure — the client re-raises through the g_cast_error protocol, it
must NOT fall back and silently re-run on the CPU).

Supervision (this round): ``SupervisedClient`` is the Python-side
client with the robustness contract a wedged worker demands —
per-request DEADLINE (``SRJT_SIDECAR_DEADLINE_S``, falling back to
the C++ client's ``SRJT_SIDECAR_TIMEOUT_SEC`` so one knob tunes both
twins; socket timeout, so a hung worker surfaces as RetryableError
instead of blocking the executor forever), heartbeat PING (``SRJT_SIDECAR_HEARTBEAT_S``: a
connection idle past the interval is probed with a cheap PING before
carrying a heavy op), reconnect-on-desync (any transport fault or
malformed frame closes the socket; the next request dials fresh), and
host degrade: ``call()`` runs the op through the retry orchestrator
(utils/retry.py) and, when the worker is truly gone (fatal
classification or retry exhaustion), executes the SAME op in-process
via ``_dispatch`` — the host-CPU engine — so results keep flowing.
``worker_errors_are_classified``: a worker-side error message
prefixed ``RetryableError:`` / ``FatalDeviceError:`` (the worker's
op_boundary taxonomy stringified over the wire) is re-raised as that
class on the client, which is what makes remote faults retryable.

Crash tolerance (ISSUE 5): a SINGLE worker is a single point of
failure for all device state — ``sidecar_pool.SidecarPool`` supervises
N of these workers with health-checked routing, failover, automatic
respawn, and SET_ARENA re-hydration (the pool owns the arena memfd, so
a replacement worker re-maps the same pages). The circuit breaker
below then guards the POOL: it records failures only when every worker
is unhealthy.

Deadlines + circuit breaker (ISSUE 3): under an active deadline scope
(utils/deadline.py) every request's socket deadline is
``min(SRJT_SIDECAR_TIMEOUT_SEC, remaining budget)`` and reconnect
loops abort the moment the budget is gone — an expired budget raises
``DeadlineExceeded`` (non-retryable), never a raw socket timeout. The
process-global circuit breaker (``breaker()``; states/knobs in
utils/deadline.py, ``SRJT_BREAKER_THRESHOLD`` /
``SRJT_BREAKER_COOLDOWN_SEC``) opens after consecutive supervision
failures: while open, ``call()`` degrades to the host engine
immediately — no dial, no timeout wait — and after the cooldown one
half-open probe rides the device path; success restores device mode.
Transitions are registry-direct metrics, visible in
``runtime.stats_report()``.
"""

from __future__ import annotations

import argparse
import os
import socket
import struct
import sys
import threading
import time

OP_PING = 0
OP_GROUPBY_SUM_F32 = 1
OP_CONVERT_TO_ROWS = 2
OP_CONVERT_FROM_ROWS = 3
OP_CAST_TO_INTEGER = 4
OP_CAST_TO_DECIMAL = 5
OP_ZORDER = 6
OP_DECIMAL128_MUL = 7
OP_DECIMAL128_DIV = 8
OP_SET_ARENA = 9
OP_STATS = 10
OP_SHUTDOWN = 255

# readable per-op metric names (worker-side request counters)
_OP_NAMES = {
    OP_PING: "PING",
    OP_GROUPBY_SUM_F32: "GROUPBY_SUM_F32",
    OP_CONVERT_TO_ROWS: "CONVERT_TO_ROWS",
    OP_CONVERT_FROM_ROWS: "CONVERT_FROM_ROWS",
    OP_CAST_TO_INTEGER: "CAST_TO_INTEGER",
    OP_CAST_TO_DECIMAL: "CAST_TO_DECIMAL",
    OP_ZORDER: "ZORDER",
    OP_DECIMAL128_MUL: "DECIMAL128_MUL",
    OP_DECIMAL128_DIV: "DECIMAL128_DIV",
    OP_SET_ARENA: "SET_ARENA",
    OP_STATS: "STATS",
    OP_SHUTDOWN: "SHUTDOWN",
}


def op_name(op: int) -> str:
    return _OP_NAMES.get(op, f"OP_{op}")

ARENA_FLAG = 0x80000000  # high bit of op/status: payload at arena[0:len]
CRC_FLAG = 0x40000000  # op/status bit: a u32 CRC trailer follows the header
# srjt-trace (ISSUE 12): op bit negotiated per request exactly like
# CRC_FLAG — when set, a fixed 17-byte trace-context blob (trace id,
# parent span id, flags; utils/tracing.wire_context) rides the socket
# right after the CRC trailer (or the header when CRC is off), BEFORE
# the payload/region descriptor. The worker installs the context for
# the request's dynamic extent so its spans parent to the caller's
# span in its own per-process span log. The native C++ client never
# sets it, so the legacy walker stays byte-for-byte; responses never
# carry it.
TRACE_FLAG = 0x20000000
_FLAG_MASK = ARENA_FLAG | CRC_FLAG | TRACE_FLAG

# slab-arena data plane (ISSUE 6): a SET_ARENA payload of >= 16 bytes
# carries a u64 mode word after the size; mode bit 0 marks the arena a
# SLAB of per-request regions (sidecar_pool.ArenaSlab). On a slab-mode
# connection an ARENA_FLAG request's stream payload is a REGION
# DESCRIPTOR naming where the real payload lives — the worker validates
# it against the 32-byte region header the client wrote into the slab
# (magic + generation + request id + capacity + payload length), so a
# stale or clobbered region surfaces as a retryable desync, never as
# somebody else's bytes. Responses land back inside the same region
# (header-only frame) when they fit, else stream. Legacy 8-byte
# SET_ARENA payloads (the native C++ client) keep the single-buffer
# offset-0 protocol byte for byte.
ARENA_MODE_LEGACY = 0
ARENA_MODE_SLAB = 1
REGION_MAGIC = 0x524A5253  # b"SRJR" little-endian
REGION_HDR = struct.Struct("<IIQQQ")  # magic, generation, request_id, capacity, payload_len
REGION_HDR_LEN = REGION_HDR.size  # 32
REGION_DESC = struct.Struct("<QQI")  # offset, request_id, generation

STATUS_OK = 0
STATUS_ERROR = 1
STATUS_CAST_ERROR = 2


def _recv_exact(conn: socket.socket, n: int, fds: list = None) -> bytes:
    """Read exactly n bytes. With ``fds`` given, capture any SCM_RIGHTS
    file descriptors that arrive attached to the stream (the
    OP_SET_ARENA memfd travels with its header bytes) into it; without,
    plain recv (client-side use, where no fds ever arrive)."""
    import array

    buf = bytearray()
    while len(buf) < n:
        if fds is None:
            chunk = conn.recv(n - len(buf))  # srjt-lint: allow-blocking(worker/probe-side request wait: the CLIENT owns every deadline; the server parks here between requests by design)
        else:
            chunk, ancdata, _flags, _addr = conn.recvmsg(  # srjt-lint: allow-blocking(worker-side request wait, SCM_RIGHTS variant; the client owns the deadline)
                n - len(buf), socket.CMSG_SPACE(4 * array.array("i").itemsize)
            )
            for level, ctype, cdata in ancdata:
                if level == socket.SOL_SOCKET and ctype == socket.SCM_RIGHTS:
                    a = array.array("i")
                    a.frombytes(cdata[: len(cdata) - (len(cdata) % a.itemsize)])
                    fds.extend(a)
        if not chunk:
            raise ConnectionError("sidecar: peer closed")
        buf.extend(chunk)
    return bytes(buf)


# wire table format negotiation (ISSUE 6): the worker answers each
# request in the table layout the REQUEST used. ``_read_table`` records
# the sniffed format here (one slot per connection thread — each
# connection is handled on its own thread and ops are synchronous), and
# ``_write_table`` consults it, so the native C++ client's legacy
# walker layout round-trips byte for byte while framed clients get the
# versioned columnar frame codec (columnar/frames.py) back.
_REQ_FMT = threading.local()


def _read_table(payload: bytes, pos: int = 0):
    """Deserialize a table from ``payload[pos:]``. Sniffs the versioned
    columnar frame magic (columnar/frames.py) first — framed payloads
    decode through the shared codec (per-column CRC verified); anything
    else is the legacy walker layout: u32 ncols; per col: i32
    type_id, i32 scale, u64 n, u8 has_validity, [n] u8 validity, then
    either (u64 data_len, bytes) for fixed width or (i32[n+1] offsets,
    u64 chars_len, bytes) for STRING and LIST (byte child). The offset
    parameter avoids copying multi-hundred-MB payloads just to skip an
    op header."""
    import jax.numpy as jnp
    import numpy as np

    from .columnar import Column, Table, frames
    from .columnar.dtype import DType, TypeId

    if frames.is_frame(payload, pos):
        _REQ_FMT.framed = True
        return frames.decode_table(payload, where="sidecar.table_frame", offset=pos)
    _REQ_FMT.framed = False
    (ncols,) = struct.unpack_from("<I", payload, pos)
    pos += 4
    cols = []
    for _ in range(ncols):
        type_id, scale = struct.unpack_from("<ii", payload, pos)
        pos += 8
        (n,) = struct.unpack_from("<Q", payload, pos)
        pos += 8
        has_validity = payload[pos]
        pos += 1
        validity = None
        if has_validity:
            validity = jnp.asarray(np.frombuffer(payload, np.uint8, n, pos).astype(bool))
            pos += n
        tid = TypeId(type_id)
        d = DType(tid, scale if tid.name.startswith("DECIMAL") else 0)
        if tid in (TypeId.STRING, TypeId.LIST):
            offs = np.frombuffer(payload, np.int32, n + 1, pos)
            pos += 4 * (n + 1)
            (clen,) = struct.unpack_from("<Q", payload, pos)
            pos += 8
            chars = np.frombuffer(payload, np.uint8, clen, pos)
            pos += clen
            if tid == TypeId.LIST:
                cols.append(
                    Column(
                        d,
                        validity=validity,
                        offsets=jnp.asarray(offs),
                        child=Column(
                            DType(TypeId.INT8), data=jnp.asarray(chars).view(jnp.int8)
                        ),
                    )
                )
            else:
                cols.append(
                    Column(d, validity=validity, offsets=jnp.asarray(offs), chars=jnp.asarray(chars))
                )
        else:
            (dlen,) = struct.unpack_from("<Q", payload, pos)
            pos += 8
            raw = payload[pos : pos + dlen]
            pos += dlen
            if tid == TypeId.DECIMAL128:
                data = np.frombuffer(raw, np.uint32).reshape(n, 4)
            else:
                data = np.frombuffer(raw, np.dtype(d.np_dtype))
            cols.append(Column(d, data=jnp.asarray(data), validity=validity))
    return Table(cols)


def _op_groupby_sum(payload: bytes) -> bytes:
    import numpy as np

    from .ops.aggregate import groupby_sum_bounded

    (num_keys,) = struct.unpack_from("<I", payload, 0)
    (n,) = struct.unpack_from("<Q", payload, 4)
    keys = np.frombuffer(payload, np.int64, n, 12)
    vals = np.frombuffer(payload, np.float32, n, 12 + 8 * n)
    import jax.numpy as jnp

    sums, counts = groupby_sum_bounded(
        jnp.asarray(keys), jnp.asarray(vals), int(num_keys)
    )
    return np.asarray(sums, np.float32).tobytes() + np.asarray(counts, np.int64).tobytes()


def _write_table(table, framed: bool = None) -> bytes:
    """Serialize a Table for the wire. ``framed=None`` (the worker's
    posture) echoes the format the current request's ``_read_table``
    sniffed, so the C++ client parses responses with the same legacy
    walker it serializes requests with, and framed clients decode the
    shared codec. LIST<INT8|UINT8> columns reuse the STRING framing
    (offsets + byte child) in the legacy form."""
    import numpy as np

    from .columnar.dtype import TypeId

    if framed is None:
        framed = getattr(_REQ_FMT, "framed", False)
    if framed:
        from .columnar import frames

        return frames.encode_table(table)
    out = [struct.pack("<I", len(table.columns))]
    for col in table.columns:
        d = col.dtype
        n = len(col)
        out.append(struct.pack("<ii", int(d.id.value), int(d.scale)))
        out.append(struct.pack("<Q", n))
        if col.validity is not None:
            out.append(b"\x01")
            out.append(np.asarray(col.validity, np.uint8).tobytes())
        else:
            out.append(b"\x00")
        if d.id in (TypeId.STRING, TypeId.LIST):
            offs = np.asarray(col.offsets, np.int32)
            chars = (
                np.asarray(col.chars, np.uint8)
                if d.id == TypeId.STRING
                else np.asarray(col.child.data).view(np.uint8)
            )
            out.append(offs.tobytes())
            out.append(struct.pack("<Q", chars.size))
            out.append(chars.tobytes())
        else:
            raw = np.asarray(col.data)
            out.append(struct.pack("<Q", raw.nbytes))
            out.append(raw.tobytes())
    return b"".join(out)


def _op_convert_to_rows(payload: bytes) -> bytes:
    import numpy as np

    from .ops.row_conversion import convert_to_rows

    table = _read_table(payload)
    batches = convert_to_rows(table)
    out = [struct.pack("<I", len(batches))]
    for col in batches:
        offs = np.asarray(col.offsets, np.int32)
        blob = np.asarray(col.child.data).view(np.uint8)
        out.append(struct.pack("<Q", len(col)))
        out.append(offs.tobytes())
        out.append(struct.pack("<Q", blob.size))
        out.append(blob.tobytes())
    return b"".join(out)


def _op_convert_from_rows(payload: bytes) -> bytes:
    import jax.numpy as jnp
    import numpy as np

    from .columnar import Column
    from .columnar.dtype import DType, TypeId
    from .ops.row_conversion import convert_from_rows

    pos = 0
    (ncols,) = struct.unpack_from("<I", payload, pos)
    pos += 4
    type_ids = np.frombuffer(payload, np.int32, ncols, pos)
    pos += 4 * ncols
    scales = np.frombuffer(payload, np.int32, ncols, pos)
    pos += 4 * ncols
    (nrows,) = struct.unpack_from("<Q", payload, pos)
    pos += 8
    offs = np.frombuffer(payload, np.int32, nrows + 1, pos)
    pos += 4 * (nrows + 1)
    (blen,) = struct.unpack_from("<Q", payload, pos)
    pos += 8
    blob = np.frombuffer(payload, np.uint8, blen, pos)
    dtypes = [
        DType(TypeId(int(t)), int(s) if TypeId(int(t)).name.startswith("DECIMAL") else 0)
        for t, s in zip(type_ids, scales)
    ]
    rows = Column(
        DType(TypeId.LIST),
        offsets=jnp.asarray(offs),
        child=Column(DType(TypeId.INT8), data=jnp.asarray(blob).view(jnp.int8)),
    )
    return _write_table(convert_from_rows(rows, dtypes))


def _op_cast_to_integer(payload: bytes) -> bytes:
    from .columnar import Table
    from .columnar.dtype import DType, TypeId
    from .ops.cast_string import string_to_integer

    ansi = payload[0]
    (out_type,) = struct.unpack_from("<i", payload, 1)
    table = _read_table(payload, 5)
    out = string_to_integer(
        table.columns[0], ansi_mode=ansi != 0, out_dtype=DType(TypeId(out_type))
    )
    return _write_table(Table([out]))


def _op_cast_to_decimal(payload: bytes) -> bytes:
    from .columnar import Table
    from .ops.cast_decimal import string_to_decimal

    ansi = payload[0]
    precision, scale = struct.unpack_from("<ii", payload, 1)
    table = _read_table(payload, 9)
    out = string_to_decimal(table.columns[0], ansi != 0, precision, scale)
    return _write_table(Table([out]))


def _op_zorder(payload: bytes) -> bytes:
    from .columnar import Table
    from .ops.zorder import interleave_bits_table

    table = _read_table(payload)
    return _write_table(Table([interleave_bits_table(table)]))


def _op_decimal128(payload: bytes, div: bool) -> bytes:
    from .ops.decimal_utils import divide128, multiply128

    (out_scale,) = struct.unpack_from("<i", payload, 0)
    table = _read_table(payload, 4)
    a, b = table.columns[0], table.columns[1]
    res = divide128(a, b, out_scale) if div else multiply128(a, b, out_scale)
    return _write_table(res)


def _op_stats(backend: str) -> bytes:
    """STATS verb: the worker's metrics-registry snapshot as JSON plus
    the memory governor's section (admission + catalog state — arena
    registrations surface here AND as ``memgov.arena*`` gauges in the
    snapshot). The worker counts per-op requests/errors registry-direct
    (always on, independent of SRJT_METRICS_ENABLED — the verb must
    answer even when hot-path instrumentation is disarmed)."""
    import json

    from . import memgov
    from .utils import metrics

    return json.dumps(
        {
            "backend": backend,
            "snapshot": metrics.snapshot(),
            "memgov": memgov.stats_section(),
        }
    ).encode()


def _dispatch(op: int, payload: bytes, backend: str) -> bytes:
    # fresh wire-format slot per dispatch: host-fallback callers reuse
    # threads, and a stale `framed` sniff from an earlier request would
    # make an op that never reads a table echo the wrong table layout
    _REQ_FMT.framed = False
    if op == OP_PING:
        return backend.encode()
    if op == OP_STATS:
        return _op_stats(backend)
    if op == OP_GROUPBY_SUM_F32:
        return _op_groupby_sum(payload)
    if op == OP_CONVERT_TO_ROWS:
        return _op_convert_to_rows(payload)
    if op == OP_CONVERT_FROM_ROWS:
        return _op_convert_from_rows(payload)
    if op == OP_CAST_TO_INTEGER:
        return _op_cast_to_integer(payload)
    if op == OP_CAST_TO_DECIMAL:
        return _op_cast_to_decimal(payload)
    if op == OP_ZORDER:
        return _op_zorder(payload)
    if op == OP_DECIMAL128_MUL:
        return _op_decimal128(payload, div=False)
    if op == OP_DECIMAL128_DIV:
        return _op_decimal128(payload, div=True)
    raise ValueError(f"unknown op {op}")


def _handle_conn(conn: socket.socket, backend: str, shutdown) -> None:
    """One client connection: its own optional arena, its own thread."""
    import mmap

    from . import memgov
    from .utils import faultinj, integrity, metrics, tracing
    from .utils.errors import DataCorruption

    reg = metrics.registry()  # worker-side counters: always-on
    arena = None  # mmap over the client's memfd
    arena_mode = ARENA_MODE_LEGACY  # SET_ARENA mode word (slab vs legacy)
    # memory-governor bookkeeping (always-on, like the request counters):
    # the mmap'd arena is host memory no budget would otherwise see —
    # it registers as a host-tier PINNED catalog entry, keyed per
    # connection, and surfaces in the STATS verb / stats_report()
    arena_key = f"sidecar.arena.conn{id(conn)}"
    fds: list = []

    def reply(status: int, body: bytes, with_crc: bool, crc_body: bytes = None,
              region=None):
        """One response frame. ``crc_body`` is what the trailer covers
        when it differs from the bytes on the wire — the injected
        ``corrupt`` chaos flips bytes AFTER checksumming, exactly like
        a transport fault, so the client's CRC check MUST fail.
        ``region`` is the (offset, capacity, request_id, generation) of
        a slab-mode region request: a fitting OK response lands back
        inside that region (header-only frame) after the in-slab header
        is re-validated against the request's id+generation; slab-mode
        connections never answer through the arena otherwise — the
        legacy single-buffer opportunism is exactly what serialized the
        whole pool on one lock."""
        trailer = b""
        if with_crc and integrity.is_enabled():
            status |= CRC_FLAG
            trailer = integrity.pack_crc(
                integrity.checksum(body if crc_body is None else crc_body)
            )
        ok = (status & ~_FLAG_MASK) == STATUS_OK
        if ok and region is not None and 0 < len(body) <= region[1]:
            # re-validate the in-slab header IMMEDIATELY before writing:
            # a slow-but-alive worker whose client already timed out and
            # failed over would otherwise clobber the region under the
            # retry attempt (the client bumps the generation on every
            # rewrite, so a stale attempt sees a mismatch here). The
            # check and the write are not atomic — a write straddling
            # the retry's rewrite can still tear the pages — but both
            # sides checksum IN-HAND bytes (never an mmap re-read), so
            # a tear fails CRC verification and heals retryably. On
            # mismatch fall through to the stream answer — this socket
            # is the only place this attempt's client could still be
            # listening, and the slab stays untouched.
            off = region[0]
            magic, hgen, hrid, _cap, _plen = REGION_HDR.unpack_from(arena, off)
            if magic == REGION_MAGIC and hrid == region[2] and hgen == region[3]:
                start = off + REGION_HDR_LEN
                arena[start : start + len(body)] = body
                conn.sendall(
                    struct.pack("<IQ", status | ARENA_FLAG, len(body)) + trailer
                )
                return
        if (
            ok and arena is not None and arena_mode == ARENA_MODE_LEGACY
            and 0 < len(body) <= len(arena)
        ):
            arena[: len(body)] = body
            conn.sendall(struct.pack("<IQ", status | ARENA_FLAG, len(body)) + trailer)
        else:
            conn.sendall(struct.pack("<IQ", status, len(body)) + trailer + body)

    try:
        while True:
            try:
                hdr = _recv_exact(conn, 12, fds)
            except ConnectionError:
                return  # client went away: this connection only
            wire_op, plen = struct.unpack("<IQ", hdr)
            op = wire_op & ~_FLAG_MASK
            in_arena = bool(wire_op & ARENA_FLAG)
            with_crc = bool(wire_op & CRC_FLAG)
            reg.counter(f"sidecar.worker.requests.{op_name(op)}").inc()
            # the CRC trailer rides the SOCKET right after the header,
            # even for arena-resident payloads — read it before any
            # early-out so the stream stays framed
            req_crc = (
                integrity.unpack_crc(_recv_exact(conn, 4, fds)) if with_crc else None
            )
            # srjt-trace (ISSUE 12): the trace-context blob follows the
            # trailer, before the payload/descriptor — read it
            # unconditionally when flagged so the stream stays framed
            # even if tracing is disarmed on this side
            tctx = (
                tracing.decode_wire_context(
                    _recv_exact(conn, tracing.TRACE_CTX_LEN, fds)
                )
                if wire_op & TRACE_FLAG
                else None
            )
            region = None  # (offset, capacity) of a slab-mode region request
            if in_arena and arena_mode == ARENA_MODE_SLAB:
                # slab mode: the stream payload is a region DESCRIPTOR;
                # the real payload sits behind the region header the
                # client wrote into the shared slab. Every mismatch —
                # stale generation, foreign request id, bad geometry —
                # answers retryably so the client rewrites the region
                # (or replays SET_ARENA) and re-sends.
                desc = _recv_exact(conn, plen, fds) if plen else b""
                err = None
                if len(desc) != REGION_DESC.size:
                    err = f"bad region descriptor length {len(desc)}"
                elif arena is None:
                    err = "no uploaded arena (re-send SET_ARENA)"
                else:
                    off, rid, gen = REGION_DESC.unpack(desc)
                    if off + REGION_HDR_LEN > len(arena):
                        err = f"region offset {off} out of bounds"
                    else:
                        magic, hgen, hrid, cap, pl = REGION_HDR.unpack_from(arena, off)
                        if magic != REGION_MAGIC or hrid != rid or hgen != gen:
                            err = (
                                f"region header desync at {off} "
                                f"(rid {hrid} != {rid} or gen {hgen} != {gen})"
                            )
                        elif pl > cap or off + REGION_HDR_LEN + cap > len(arena):
                            err = f"region geometry invalid (len {pl} cap {cap})"
                        else:
                            region = (off, cap, rid, gen)
                            start = off + REGION_HDR_LEN
                            payload = bytes(arena[start : start + pl])
                if err is not None:
                    reply(
                        STATUS_ERROR,
                        f"RetryableError: arena region: {err}".encode(),
                        with_crc,
                    )
                    continue
            elif in_arena:
                if arena is None or plen > len(arena):
                    # retryable by prefix: a redialed connection lost its
                    # per-connection arena — the client replays SET_ARENA
                    # and re-sends (sidecar_pool._ensure_arena)
                    reply(
                        STATUS_ERROR,
                        b"RetryableError: arena request without an uploaded"
                        b" arena (re-send SET_ARENA)",
                        with_crc,
                    )
                    continue
                payload = bytes(arena[:plen])
            else:
                payload = _recv_exact(conn, plen, fds) if plen else b""
            _REQ_FMT.framed = False  # set by _read_table when it sniffs a frame
            if req_crc is not None and integrity.is_enabled():
                reg.counter("sidecar.integrity.frames_checked").inc()
                try:
                    integrity.verify(payload, req_crc, "sidecar.request")
                except DataCorruption as e:
                    # taxonomy prefix on the wire: the client re-raises
                    # DataCorruption (retryable) and re-sends the frame
                    reply(STATUS_ERROR, f"{type(e).__name__}: {e}".encode(), with_crc)
                    continue
            # chaos mode (VERDICT r4 item 7): SRJT_CHAOS_EXIT_ON_OP=<n>
            # makes the worker DIE mid-op — after consuming the request,
            # before any response — modeling the round-4 "kernel fault"
            # worker crash. Clients must classify the dead transport,
            # fall back to the host engine, and reconnect cleanly.
            from .utils import knobs

            chaos = knobs.get_int("SRJT_CHAOS_EXIT_ON_OP")
            if chaos is not None and op == chaos:
                os._exit(42)
            try:
                # per-request fault hook (ISSUE 5): `crash` rules keyed
                # `sidecar.worker.<OP>` SIGKILL the worker here — after
                # consuming the request, before any response — and
                # error kinds surface as status-1 replies
                if faultinj.is_enabled():
                    faultinj.maybe_inject(f"sidecar.worker.{op_name(op)}")
                if op == OP_SET_ARENA:
                    (size,) = struct.unpack_from("<Q", payload, 0)
                    # >= 16-byte payloads carry the arena MODE word
                    # (bit 0 = slab of per-request regions); the native
                    # client's 8-byte payload keeps the legacy protocol
                    mode = (
                        struct.unpack_from("<Q", payload, 8)[0]
                        if len(payload) >= 16
                        else ARENA_MODE_LEGACY
                    )
                    if not fds:
                        raise ValueError("SET_ARENA without an fd")
                    fd = fds.pop(0)
                    for extra in fds:
                        os.close(extra)
                    fds.clear()
                    if arena is not None:
                        # replace = unregister-then-register: close the
                        # old mapping AND retire its accounting entry
                        # before the new map exists, so a failed re-map
                        # can't leave stale host-tier bytes and a
                        # successful one never double-counts
                        # (regression: memgov.arena* gauges stay flat
                        # across re-uploads)
                        arena.close()
                        arena = None
                        memgov.catalog().unregister(arena_key)
                    arena = mmap.mmap(fd, size)
                    arena_mode = (
                        ARENA_MODE_SLAB
                        if (mode & ARENA_MODE_SLAB)
                        else ARENA_MODE_LEGACY
                    )
                    os.close(fd)
                    memgov.catalog().register_host_bytes(
                        arena_key, size, pinned=True, kind="arena"
                    )
                    reply(STATUS_OK, b"", with_crc)
                    continue
                if op == OP_SHUTDOWN:
                    conn.sendall(struct.pack("<IQ", 0, 0))
                    shutdown()
                    return
                # per-op wall time is hot-path instrumentation: gated
                # (SRJT_METRICS_ENABLED), unlike the always-on request
                # COUNTERS above — disarmed, no clock is touched
                timed = metrics.is_enabled()
                t0 = time.perf_counter() if timed else 0.0
                if tctx is not None and tracing.is_enabled():
                    # the worker's half of the cross-process trace: one
                    # span per dispatched op, parented (via the wire
                    # context) to the client's request span, streamed
                    # to THIS process's span log for tracemerge to join
                    with tracing.remote_scope(*tctx):
                        with tracing.span(
                            "sidecar.worker_op", op=op_name(op),
                            backend=backend,
                        ):
                            resp = _dispatch(op, payload, backend)
                else:
                    resp = _dispatch(op, payload, backend)
                if timed:
                    reg.histogram(f"sidecar.worker.op_us.{op_name(op)}").record(
                        (time.perf_counter() - t0) * 1e6
                    )
                wire_resp = resp
                if faultinj.is_enabled():
                    # `corrupt` chaos: flips bytes BELOW the checksum
                    wire_resp = faultinj.maybe_corrupt(
                        f"sidecar.worker.{op_name(op)}", resp
                    )
                reply(STATUS_OK, wire_resp, with_crc, crc_body=resp, region=region)
            except Exception as e:  # srjt-lint: allow-broad-except(worker request loop: every failure must become a status-1 reply carrying the taxonomy prefix — the client re-raises the right class across the wire; the worker keeps serving)
                from .ops.cast_string import CastError

                reg.counter("sidecar.worker.errors").inc()
                if isinstance(e, CastError):
                    # semantic ANSI failure: ships row + null-flag +
                    # value so the client re-raises instead of
                    # re-running on the host
                    sv = e.string_with_error
                    val = sv.encode() if isinstance(sv, str) else (bytes(sv) if sv else b"")
                    msg = struct.pack("<qB", int(e.row_with_error), 1 if sv is None else 0) + val
                    reply(STATUS_CAST_ERROR, msg, with_crc)
                else:
                    reply(STATUS_ERROR, f"{type(e).__name__}: {e}".encode(), with_crc)
    finally:
        if arena is not None:
            arena.close()
            memgov.catalog().unregister(arena_key)
        for fd in fds:
            os.close(fd)
        conn.close()


# ---------------------------------------------------------------------------
# supervised Python client (the executor-side path; C++ twin: sidecar.cc)
# ---------------------------------------------------------------------------


def _env_seconds(name: str, default: float = ...) -> float:
    # typed registry accessor (utils/knobs.py): malformed or <= 0
    # values warn and keep the default — a zero deadline would make
    # the socket non-blocking, not timeout-free (the C++ twin applies
    # the same v > 0 rule)
    from .utils import knobs

    return knobs.get_float(name, default=default)


class SupervisedClient:
    """Sidecar client with connection supervision.

    Robustness contract (ISSUE: sidecar connection supervision):

    - every socket operation runs under a per-request DEADLINE; a
      wedged worker yields ``RetryableError("DEADLINE_EXCEEDED...")``
      — never an indefinite block holding the executor,
    - a connection idle longer than ``heartbeat_s`` is probed with a
      PING before carrying a real op, so a silently dead worker is
      detected by a 12-byte round-trip instead of a multi-second op
      timing out,
    - any transport fault or malformed frame DESYNCS the byte stream:
      the socket is closed immediately and the next request reconnects
      fresh (a desynced stream must never carry another frame),
    - ``call()`` wraps ``request()`` in the retry orchestrator and
      degrades to the in-process host-CPU engine (``_dispatch``) when
      the worker is fatally gone — bounded by the deadline, no hang,
      no silent drop.
    """

    def __init__(
        self,
        sock_path: str,
        deadline_s: float = None,
        heartbeat_s: float = None,
    ):
        self.sock_path = sock_path
        if deadline_s is None:
            # one deadline knob across both clients: the C++ twin
            # (native/src/sidecar.cc) reads SRJT_SIDECAR_TIMEOUT_SEC,
            # honored here too; SRJT_SIDECAR_DEADLINE_S (float) wins
            # when both are set
            deadline_s = _env_seconds(
                "SRJT_SIDECAR_DEADLINE_S",
                _env_seconds("SRJT_SIDECAR_TIMEOUT_SEC"),
            )
        self.deadline_s = float(deadline_s)
        self.heartbeat_s = (
            _env_seconds("SRJT_SIDECAR_HEARTBEAT_S")
            if heartbeat_s is None
            else float(heartbeat_s)
        )
        self._sock: socket.socket = None
        self._last_io = 0.0
        self._ever_connected = False
        self.reconnects = 0  # supervision observability: REDIALS only
        self.host_fallbacks = 0
        # shared-memory data plane (set by the pool after SET_ARENA):
        # the worker opportunistically answers through the arena once a
        # connection has one, so the client must be able to READ
        # ARENA_FLAG responses even for stream requests
        self.arena_mm = None

    # -- connection lifecycle ------------------------------------------------

    def connect(self) -> None:
        from .utils import deadline as deadline_mod, metrics
        from .utils.errors import RetryableError

        # reconnect loops abort the moment the query budget is gone:
        # DeadlineExceeded here, never a dial that cannot finish
        d = deadline_mod.current()
        timeout = self.deadline_s
        if d is not None:
            d.check("sidecar.connect")
            timeout = min(timeout, max(d.remaining(), 1e-3))
        self.close()
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(timeout)
        try:
            s.connect(self.sock_path)
        except (OSError, socket.timeout) as e:
            s.close()
            raise RetryableError(f"sidecar: UNAVAILABLE: connect failed ({e})") from e
        if self._ever_connected:
            self.reconnects += 1  # a redial, not the initial dial
            metrics.counter("sidecar.reconnects").inc()
            metrics.event("sidecar.reconnect", sock=self.sock_path)
        self._ever_connected = True
        self._sock = s
        self._last_io = time.monotonic()

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- framed request/response under a deadline ----------------------------

    def _op_budget_s(self, op: int) -> float:
        """ADAPTIVE per-op socket deadline (ISSUE 9): once an op class
        has enough observed samples (``sidecar.op_lat_us.<OP>``,
        recorded registry-direct by ``request()``), the deadline is its
        q99 × ``SRJT_ADAPTIVE_TIMEOUT_MULT``, clamped into
        [``SRJT_ADAPTIVE_TIMEOUT_FLOOR_S``, the static
        ``SRJT_SIDECAR_TIMEOUT_SEC``] — a hung worker is detected in
        seconds instead of the static knob's minutes, while cold-start
        ops (first compile, first dial) keep the conservative static
        deadline. The caller still clamps to the remaining query
        budget, so an adaptive deadline can never outlive the query.
        Clamps are counted (``sidecar.adaptive_timeout_clamps``)."""
        from .utils import metrics

        budget, clamped = metrics.adaptive_timeout_s(
            f"sidecar.op_lat_us.{op_name(op)}", self.deadline_s
        )
        if clamped:
            metrics.registry().counter("sidecar.adaptive_timeout_clamps").inc()
        return budget

    def _recv_deadline(self, n: int, deadline: float) -> bytes:
        """Read exactly n bytes under a WHOLE-REQUEST deadline: the
        socket timeout shrinks to the remaining budget each iteration,
        so a slow-dripping worker (one chunk per almost-deadline) cannot
        stretch one request past ``deadline_s`` total — the bound the
        supervision contract advertises, not a per-recv idle timeout."""
        buf = bytearray()
        while len(buf) < n:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise socket.timeout("request deadline exhausted")
            self._sock.settimeout(remaining)
            chunk = self._sock.recv(min(n - len(buf), 1 << 20))
            if not chunk:
                raise ConnectionError("sidecar: peer closed")
            buf.extend(chunk)
        return bytes(buf)

    def _raw_request(self, op: int, payload: bytes, arena_len: int = None,
                     region=None):
        """One request/response exchange on the live socket, bounded by
        one per-request deadline end to end — under an active deadline
        scope that is ``min(deadline_s, remaining budget)``, so a hung
        worker can never cost more than the query has left. Any
        transport fault closes the connection (desync discipline) and
        raises RetryableError; an exhausted BUDGET raises
        DeadlineExceeded instead (the caller must see the query
        deadline, never a raw socket timeout).

        With ``arena_len`` the request payload is RESIDENT at
        ``arena_mm[0:arena_len]`` (the legacy single-buffer data
        plane): only the header — and the CRC trailer, computed over
        the ARENA bytes — crosses the socket, under
        ``wire_op | ARENA_FLAG``. With ``region`` (an
        ``sidecar_pool.ArenaRegion``, the slab data plane) the payload
        is resident inside the leased region and only the 20-byte
        region descriptor crosses the socket — N such requests ride N
        workers concurrently, nothing shared but the allocator."""
        from .utils import deadline as deadline_mod, integrity
        from .utils.errors import DataCorruption, RetryableError

        d = deadline_mod.current()
        budget_s = self._op_budget_s(op)
        if d is not None:
            d.check(f"sidecar_op_{op}")
            budget_s = min(budget_s, max(d.remaining(), 1e-3))
        deadline = time.monotonic() + budget_s
        # integrity (ISSUE 5): one boolean read when off — the frame is
        # byte-identical to the legacy protocol, same single sendall.
        # When on, the 4-byte CRC trailer rides the SAME sendall and the
        # worker echoes the flag back with a trailer this side verifies.
        use_crc = integrity.is_enabled()
        wire_op = (op | CRC_FLAG) if use_crc else op
        if region is not None:
            wire_op |= ARENA_FLAG
            # checksum the IN-HAND request bytes, never an mmap re-read:
            # a slow stale worker's slab write straddling the caller's
            # rewrite can tear the shared pages, and a CRC computed over
            # a re-read would bless the torn bytes — computed over the
            # snapshot, any tear fails the worker-side verify and heals
            # as retryable DataCorruption
            body = region.snapshot_bytes()
            payload = REGION_DESC.pack(
                region.offset, region.request_id, region.generation
            )
            plen = len(payload)
        elif arena_len is None:
            body, plen = payload, len(payload)
        else:
            if self.arena_mm is None:
                raise ValueError(
                    "arena_len given but no client-side arena is mapped"
                )
            if arena_len > len(self.arena_mm):
                # enforcement of the PR 5 hardening note (ISSUE 6): an
                # oversized arena request must engage retry-with-split,
                # never truncate — RESOURCE_EXHAUSTED is the class the
                # split machinery keys on, and the message carries the
                # needed size
                raise RetryableError(
                    f"sidecar: RESOURCE_EXHAUSTED: arena request needs "
                    f"{arena_len} bytes but the mapped arena holds "
                    f"{len(self.arena_mm)} — split the batch or lease a "
                    "larger region"
                )
            wire_op |= ARENA_FLAG
            body, plen, payload = bytes(self.arena_mm[:arena_len]), arena_len, b""
        trailer = (
            integrity.pack_crc(integrity.checksum(body)) if use_crc else b""
        )
        # srjt-trace (ISSUE 12): the active sampled context rides the
        # SAME sendall under the TRACE flag bit (negotiated per request
        # exactly like CRC_FLAG — one boolean read when tracing is off,
        # frame byte-identical); the worker's spans then parent to this
        # request's span across the process boundary
        from .utils import tracing

        tblob = tracing.wire_context()
        if tblob is not None:
            wire_op |= TRACE_FLAG
        else:
            tblob = b""
        try:
            self._sock.settimeout(budget_s)
            self._sock.sendall(
                struct.pack("<IQ", wire_op, plen) + trailer + tblob + payload
            )
            hdr = self._recv_deadline(12, deadline)
            status, rlen = struct.unpack("<IQ", hdr)
            resp_crc = (
                integrity.unpack_crc(self._recv_deadline(4, deadline))
                if status & CRC_FLAG
                else None
            )
            if status & ARENA_FLAG:
                # the worker answered through the shared arena: only the
                # header (and CRC trailer) crossed the socket — a client
                # without the mapping cannot honor the frame (desync)
                if region is not None:
                    if rlen > region.capacity:
                        raise ConnectionError(
                            "region-flagged response exceeds the leased region"
                        )
                    resp = region.read(rlen)
                elif self.arena_mm is None or rlen > len(self.arena_mm):
                    raise ConnectionError(
                        "arena-flagged response without a client-side arena"
                    )
                else:
                    resp = bytes(self.arena_mm[:rlen])
            else:
                resp = self._recv_deadline(rlen, deadline) if rlen else b""
        except socket.timeout as e:
            self.close()
            if d is not None and d.done():
                raise d.exceeded(f"sidecar op {op}") from e
            raise RetryableError(
                f"sidecar: DEADLINE_EXCEEDED: op {op} exceeded "
                f"{budget_s:g}s request deadline"
            ) from e
        except (ConnectionError, OSError) as e:
            self.close()
            raise RetryableError(f"sidecar: Socket closed mid-request ({e})") from e
        if resp_crc is not None and integrity.is_enabled():
            from .utils import metrics

            metrics.registry().counter("sidecar.integrity.frames_checked").inc()
            try:
                integrity.verify(resp, resp_crc, "sidecar.response")
            except DataCorruption:
                # the stream is still framed (full frame consumed) but a
                # link that corrupts one frame gets the desync treatment:
                # close now, dial fresh on the retry that re-fetches
                self.close()
                raise
        self._last_io = time.monotonic()
        return status & ~_FLAG_MASK, resp

    def ping(self) -> str:
        """Heartbeat round-trip; returns the worker's backend name."""
        from .utils import metrics

        metrics.counter("sidecar.heartbeats").inc()
        if self._sock is None:
            self.connect()
        status, resp = self._raw_request(OP_PING, b"")
        if status != STATUS_OK:
            from .utils.errors import RetryableError

            self.close()
            raise RetryableError("sidecar: PING failed (worker unhealthy)")
        return resp.decode()

    def request(self, op: int, payload: bytes, arena_len: int = None,
                region=None) -> bytes:
        """Supervised exchange: reconnect when needed, heartbeat stale
        connections, classify worker-side errors into the
        fatal/retryable taxonomy. With metrics armed, every exchange
        records a latency histogram (``sidecar.request_us``) and
        failures count under ``sidecar.request_failures``.
        ``arena_len`` routes the request through the legacy
        single-buffer data plane and ``region`` through a leased slab
        region (see ``_raw_request``) — both under the SAME deadline
        clamp, CRC protocol, and taxonomy as a stream frame.

        srjt-trace (ISSUE 12): one ``sidecar.request`` span per
        exchange (heartbeat + redial included) when a traced query is
        active — this span is what the worker's cross-process span
        parents to, since ``_raw_request`` packs the CURRENT span id
        into the wire context."""
        from .utils import tracing

        with tracing.span("sidecar.request", op=op_name(op)):
            return self._request(op, payload, arena_len, region)

    def _request(self, op: int, payload: bytes, arena_len: int = None,
                 region=None) -> bytes:
        from .utils import metrics
        from .utils.errors import (
            DataCorruption,
            DeadlineExceeded,
            FatalDeviceError,
            RetryableError,
        )

        if self._sock is None:
            # connect() owns the reconnect accounting (attribute +
            # metric, REDIALS only) — counting here too double-counted
            # every redial and mislabeled the initial dial
            self.connect()
        elif time.monotonic() - self._last_io > self.heartbeat_s:
            try:
                self.ping()
            except RetryableError:
                # stale connection died quietly: one immediate redial,
                # then the request proceeds (or fails retryably)
                self.connect()
        armed = metrics.is_enabled()
        # the clock is read unconditionally (one perf_counter pair per
        # socket round-trip): the per-op latency histogram below is
        # PRODUCT state — adaptive deadlines (ISSUE 9) derive from it —
        # not gated instrumentation
        t0 = time.perf_counter()
        try:
            status, resp = self._raw_request(op, payload, arena_len, region)
        except Exception as e:
            metrics.counter("sidecar.request_failures").inc()
            if isinstance(e, RetryableError) and "DEADLINE_EXCEEDED" in str(e):
                # a timed-out request is the strongest latency sample
                # there is: recording the elapsed budget keeps the
                # adaptive quantile self-correcting (an over-tight
                # clamp pushes q99 back up instead of repeating)
                metrics.registry().histogram(
                    f"sidecar.op_lat_us.{op_name(op)}"
                ).record((time.perf_counter() - t0) * 1e6)
            raise
        if status == STATUS_OK:
            # only SUCCESSFUL exchanges feed the adaptive/quarantine
            # baselines (timeouts feed them above, as the strong slow
            # signal): a storm of fast worker-side ERROR replies —
            # Overloaded sheds, corruption rejects — must not collapse
            # the op-class p50 and turn healthy latencies into strikes
            metrics.registry().histogram(
                f"sidecar.op_lat_us.{op_name(op)}"
            ).record((time.perf_counter() - t0) * 1e6)
        if armed:
            metrics.counter("sidecar.requests").inc()
            metrics.histogram("sidecar.request_us").record(
                (time.perf_counter() - t0) * 1e6
            )
        if status == STATUS_OK:
            return resp
        msg = resp.decode("utf-8", "replace")
        if status == STATUS_CAST_ERROR:
            # semantic ANSI failure: transport healthy, not retryable —
            # surface the protocol payload to the caller unchanged
            raise _cast_error_from_wire(resp)
        # worker-side failure text carries the taxonomy prefix from the
        # worker's own op_boundary classification
        if msg.startswith("DataCorruption:"):
            # the WORKER's CRC check rejected our request frame: the
            # payload rotted in flight — retryable, the retry re-sends
            # (checked before the RetryableError prefix: corruption is
            # its own class so chaos assertions can tell them apart)
            raise DataCorruption(f"sidecar worker: {msg}")
        if msg.startswith("Overloaded:"):
            # the WORKER's serving layer shed at admission (ISSUE 8):
            # the scheduler there is saturated, not broken — same
            # retryable Overloaded class on this side (checked before
            # the generic RetryableError prefix so shed accounting can
            # tell admission pressure from transport faults; the
            # retry_after_s field does not survive the wire — the
            # class and cause text do)
            from .utils.errors import Overloaded

            raise Overloaded(f"sidecar worker: {msg}")
        if msg.startswith("RetryableError:"):
            raise RetryableError(f"sidecar worker: {msg}")
        if msg.startswith("FatalDeviceError:"):
            raise FatalDeviceError(f"sidecar worker: {msg}")
        if msg.startswith("DeadlineExceeded:"):
            # the WORKER's own budget died (it inherits SRJT_DEADLINE_SEC
            # through spawn_worker's env): same non-retryable class on
            # this side, so the breaker records a failure, never a
            # success, and the caller sees the deadline — not a raw
            # RuntimeError
            raise DeadlineExceeded(f"sidecar worker: {msg}")
        # worker-side SEMANTIC error (bad payload, worker API misuse)
        # that round-tripped a healthy transport: deliberately NOT a
        # taxonomy member — the breaker must record success and neither
        # retry nor host-fallback may engage for it
        raise RuntimeError(f"sidecar worker: {msg}")  # srjt-lint: allow-raise(semantic wire error on a healthy transport; taxonomy-wrapping would trip the breaker or retry a non-transient failure)

    # -- degrade-to-host orchestration ---------------------------------------

    def call(self, op: int, payload: bytes) -> bytes:
        """Run ``op`` on the worker under the retry orchestrator;
        degrade to the in-process host-CPU engine when the worker is
        gone. The degrade is BOUNDED three ways (ISSUE 3): the worst
        retry case is max_attempts x (deadline + backoff) — with every
        socket deadline and backoff truncated to the remaining query
        budget; an already-exhausted budget raises DeadlineExceeded up
        front (the host engine cannot run in zero time either); and the
        process-global circuit BREAKER fast-fails straight to the host
        engine while open — no dial, no timeout wait — restoring device
        mode via one half-open probe after the cooldown."""
        from .utils import deadline as deadline_mod, metrics, retry
        from .utils.errors import DeadlineExceeded, DeviceError

        deadline_mod.check(f"sidecar_op_{op}")
        br = breaker()
        if not br.allow():
            # open breaker: the device path is known-bad — degrade
            # immediately, without paying a dial or a timeout wait
            self.host_fallbacks += 1
            metrics.counter("sidecar.host_fallbacks").inc()
            metrics.event("sidecar.breaker_fast_fail", op=op_name(op))
            return _dispatch(op, payload, "host-fallback")
        try:
            resp = retry.call_with_retry(
                self.request, op, payload, op_name=f"sidecar_op_{op}"
            )
        except DeadlineExceeded:
            # the budget died waiting on the device path: a supervision
            # failure for breaker accounting, but the caller gets the
            # deadline error — there is no time left to degrade into.
            # DELIBERATE conflation: a device path that cannot answer
            # within the budgets the workload actually uses is, for
            # breaker purposes, unavailable — opening means later calls
            # get the host engine's answer inside their budget instead
            # of burning it waiting, and the half-open probe restores
            # device mode the moment it keeps up again. A COOPERATIVE
            # CANCEL is different: a user stopping their query says
            # nothing about device health, so it releases the probe
            # slot with no verdict instead of counting a failure.
            d = deadline_mod.current()
            if d is not None and d.cancelled() and not d.expired():
                br.abort_probe()
            else:
                br.record_failure(cause="deadline")
            self.close()
            raise
        except DeviceError as e:
            # fatal worker (or retry exhaustion): the op still completes
            # — same kernels, host backend, in-process
            br.record_failure(cause=type(e).__name__)
            self.host_fallbacks += 1
            metrics.counter("sidecar.host_fallbacks").inc()
            metrics.event(
                "sidecar.degrade_to_host", op=op_name(op), cls=type(e).__name__
            )
            self.close()
            return _dispatch(op, payload, "host-fallback")
        except Exception:
            # semantic errors (ANSI cast failures, worker API errors)
            # round-tripped the transport: a healthy device path
            br.record_success()
            raise
        except BaseException:
            # interrupt/exit mid-request: no health verdict either way —
            # just release a half-open probe slot so the breaker cannot
            # wedge in half-open with a probe that never settles
            br.abort_probe()
            raise
        br.record_success()
        return resp

    # -- observability -------------------------------------------------------

    def worker_stats(self, fold: bool = True, timeout_s: float = None) -> dict:
        """Poll the worker's STATS verb: returns the worker's metrics
        snapshot ({"backend", "snapshot"}). With ``fold`` (default) the
        worker's counters land in THIS process's registry via
        utils/metrics.fold_worker_counters (gauges under
        ``sidecar.worker.*``).

        The poll rides a THROWAWAY connection under its own short
        probe deadline (``SRJT_SIDECAR_STATS_TIMEOUT_SEC``, default
        5 s — the native stats_json contract): it never touches the
        supervised socket (no frame interleaving with an in-flight
        data op), never waits out the heavy-op deadline on a wedged
        worker, and never counts itself into ``sidecar.requests`` or
        the ``sidecar.request_us`` latency histogram it exists to
        report."""
        import json

        from .utils import metrics
        from .utils.errors import RetryableError

        if timeout_s is None:
            timeout_s = _env_seconds("SRJT_SIDECAR_STATS_TIMEOUT_SEC")
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(float(timeout_s))
        try:
            s.connect(self.sock_path)
            s.sendall(struct.pack("<IQ", OP_STATS, 0))
            hdr = _recv_exact(s, 12)
            status, rlen = struct.unpack("<IQ", hdr)
            if rlen > (4 << 20):
                # same guard as the native twin: a desynced stream's
                # garbage length must not drive a giant allocation (a
                # registry snapshot is KBs, not GBs)
                raise ConnectionError(f"implausible STATS length {rlen}")
            resp = _recv_exact(s, rlen) if rlen else b""
        except (OSError, ConnectionError) as e:
            raise RetryableError(
                f"sidecar: UNAVAILABLE: STATS probe failed ({e})"
            ) from e
        finally:
            s.close()
        if (status & ~_FLAG_MASK) != STATUS_OK:
            raise RetryableError("sidecar: STATS failed (worker unhealthy)")
        try:
            stats = json.loads(resp.decode("utf-8", "replace"))
        except ValueError as e:
            # a desynced stream / non-worker peer answering garbage
            # stays inside the probe's retryable contract — the stats
            # poll must outlive its subject, never crash the caller
            raise RetryableError(
                f"sidecar: malformed STATS payload ({e})"
            ) from e
        if fold:
            metrics.fold_worker_counters(
                (stats.get("snapshot") or {}).get("counters")
            )
        return stats


# ---------------------------------------------------------------------------
# the sidecar path's circuit breaker (process-global: one device path,
# one health verdict — every SupervisedClient shares it)
# ---------------------------------------------------------------------------

_BREAKER = None
_BREAKER_LOCK = threading.Lock()


def breaker():
    """The process-global sidecar CircuitBreaker (utils/deadline.py):
    after ``SRJT_BREAKER_THRESHOLD`` consecutive supervision failures
    it opens and ``SupervisedClient.call`` degrades to the host engine
    without dialing; a half-open probe after
    ``SRJT_BREAKER_COOLDOWN_SEC`` restores device mode on success.
    Lazy so env knobs are read at first use, not import."""
    global _BREAKER
    if _BREAKER is None:
        with _BREAKER_LOCK:
            if _BREAKER is None:
                from .utils.deadline import CircuitBreaker

                _BREAKER = CircuitBreaker("sidecar.breaker")
    return _BREAKER


def _cast_error_from_wire(resp: bytes):
    from .ops.cast_string import CastError

    if len(resp) < 9:
        from .utils.errors import RetryableError

        return RetryableError("sidecar: malformed cast-error frame (desync)")
    (row,) = struct.unpack_from("<q", resp, 0)
    is_null = resp[8] != 0
    val = None if is_null else resp[9:].decode("utf-8", "replace")
    return CastError(int(row), val)


def _reap_worker(proc) -> None:
    """Terminate and REAP a worker on a failed spawn: a leaked child
    holds the chip (and a process-table slot) for the executor's
    lifetime; a dead-but-unwaited one is a zombie. Best-effort — spawn
    cleanup must never mask the original startup error."""
    try:
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except Exception:  # srjt-lint: allow-broad-except(best-effort escalation to SIGKILL; reaping must never mask the original startup error)
                proc.kill()
                proc.wait(timeout=10)
        else:
            proc.wait()  # already exited: reap immediately
    except Exception:  # srjt-lint: allow-broad-except(best-effort reap of a dying child; the caller re-raises the original startup error)
        pass


def spawn_worker(
    sock_path: str = None,
    python_exe: str = None,
    startup_timeout_s: float = 60.0,
    env: dict = None,
):
    """Spawn ``python -m spark_rapids_jni_tpu.sidecar``, wait for its
    socket, and verify a PING handshake round-trips (the pure-Python
    twin of SidecarClient's fork/exec path in native/src/sidecar.cc).
    Returns (Popen, sock_path). Caller owns shutdown (OP_SHUTDOWN or
    terminate()). EVERY failure path — connect refused until timeout,
    worker exit during startup, a failed handshake, even an interrupt
    mid-wait — terminates and reaps the child before re-raising."""
    import subprocess
    import tempfile

    from .utils.errors import FatalDeviceError

    if sock_path is None:
        fd, tmp = tempfile.mkstemp(prefix="srjt-sidecar-")
        os.close(fd)
        os.unlink(tmp)
        sock_path = tmp + ".sock"
    full_env = dict(os.environ)
    pkg_parent = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pp = full_env.get("PYTHONPATH", "")
    if pkg_parent not in pp.split(os.pathsep):
        full_env["PYTHONPATH"] = f"{pkg_parent}{os.pathsep}{pp}" if pp else pkg_parent
    if env:
        full_env.update(env)
    proc = subprocess.Popen(
        [python_exe or sys.executable, "-m", "spark_rapids_jni_tpu.sidecar",
         "--socket", sock_path],
        env=full_env,
    )
    try:
        t_deadline = time.monotonic() + startup_timeout_s
        while True:
            probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            # generous per-probe timeout (bounded by the startup budget):
            # the worker only listens once its backend is up, so a
            # connected PING answers immediately — a short timeout here
            # would re-PING on scheduling stalls and skew the worker's
            # exact per-op request accounting
            probe.settimeout(min(10.0, max(1.0, t_deadline - time.monotonic())))
            try:
                probe.connect(sock_path)
                # the socket existing is not the worker being healthy:
                # a PING must round-trip before the caller gets the
                # process (the C++ twin's connect-then-PING discipline)
                probe.sendall(struct.pack("<IQ", OP_PING, 0))
                hdr = _recv_exact(probe, 12)
                status, rlen = struct.unpack("<IQ", hdr)
                if rlen:
                    _recv_exact(probe, rlen)
                if (status & ~_FLAG_MASK) != STATUS_OK:
                    raise FatalDeviceError(
                        "sidecar worker failed the startup PING handshake"
                    )
                return proc, sock_path
            except (OSError, ConnectionError):
                pass  # not listening / not answering yet: keep waiting
            finally:
                probe.close()
            if proc.poll() is not None:
                raise FatalDeviceError(
                    f"sidecar worker exited during startup (rc={proc.returncode})"
                )
            if time.monotonic() > t_deadline:
                raise FatalDeviceError("sidecar worker startup timed out")
            time.sleep(0.05)
    except BaseException:
        _reap_worker(proc)
        raise


def serve(sock_path: str) -> None:
    # the import defines the device backend (axon TPU when available).
    # This image preloads jax at interpreter startup with the TPU
    # platform, so an inherited JAX_PLATFORMS must be re-asserted on
    # the live config before any backend initializes (the hermetic test
    # tier pins "cpu" this way; conftest.py does the same).
    import threading

    import jax

    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)

    import spark_rapids_jni_tpu  # noqa: F401  (x64 flag before arrays)

    backend = jax.default_backend()

    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        os.unlink(sock_path)
    except FileNotFoundError:
        pass
    srv.bind(sock_path)
    srv.listen(16)
    # the parent polls for this line to know the device is up
    print(f"SRJT_SIDECAR_READY backend={backend}", flush=True)

    def shutdown():
        # client-initiated: unlink before the hard exit so no stale
        # socket file outlives the worker
        try:
            os.unlink(sock_path)
        except FileNotFoundError:
            pass
        # os._exit skips atexit: an armed lockdep must persist the
        # worker's lock-order graph NOW or the CI gate never sees the
        # worker side of the package's locks
        from .analysis import lockdep as _lockdep

        _lockdep.flush_report()
        os._exit(0)

    try:
        while True:
            conn, _ = srv.accept()
            t = threading.Thread(
                target=_handle_conn, args=(conn, backend, shutdown), daemon=True
            )
            t.start()
    finally:
        srv.close()
        try:
            os.unlink(sock_path)
        except FileNotFoundError:
            pass


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--socket", required=True)
    args = ap.parse_args()
    serve(args.socket)


if __name__ == "__main__":
    sys.exit(main())

"""The pressure loop: turn blocked admissions into demotions.

When an admission would block (admission.py) the governor does not just
wait — it RECLAIMS: unpinned catalog entries demote device->host in LRU
order until the request fits (catalog.spill_until). That is the
Theseus/reference-spill-framework discipline: cold cached state yields
to hot in-flight work, and the request only queues for demand the
catalog cannot absorb.

Last resort (off by default, ``SRJT_MEMGOV_DROP_SMCACHE=1`` arms it):
when spilling freed nothing and nothing spillable remains, drop the
memoized jit(shard_map) executables (parallel/_smcache) — compiled
programs hold device constants the accounting never sees. The entries
recompile on next use, so this trades latency for headroom; it is the
valve an operator opens on a genuinely HBM-starved fleet, not a
default. The cache is only touched when its module is already loaded —
a process that never compiled a distributed op has nothing to drop.

Metrics are registry-direct: ``memgov.pressure_events`` counts
invocations, ``memgov.smcache_dropped`` the executables dropped; the
per-spill counters/histograms live with the catalog.
"""

from __future__ import annotations

import sys

from ..utils import knobs

__all__ = ["relieve"]


def _drop_smcache_armed() -> bool:
    return knobs.get_bool("SRJT_MEMGOV_DROP_SMCACHE")


def relieve(need_bytes: int, catalog, name: str = "op") -> int:
    """Free up to ``need_bytes`` of accounted device bytes by demoting
    catalog entries (LRU, unpinned first — only, ever). Returns the
    bytes reclaimed; the caller re-checks its admission condition —
    relieve never raises for coming up short."""
    from ..utils import metrics

    reg = metrics.registry()
    reg.counter("memgov.pressure_events").inc()
    freed = catalog.spill_until(need_bytes, name=name)
    if (
        freed < need_bytes
        and catalog.spillable_device_bytes() == 0
        and _drop_smcache_armed()
    ):
        # sys.modules lookup, not an import: never pay for (or trigger)
        # the parallel tier just to find an empty cache
        smc = sys.modules.get("spark_rapids_jni_tpu.parallel._smcache")
        if smc is not None:
            n = smc.clear()
            if n:
                reg.counter("memgov.smcache_dropped").inc(n)
                metrics.event("memgov.smcache_dropped", entries=n, op=name)
    metrics.event(
        "memgov.pressure", op=name, need=int(need_bytes), freed=freed
    )
    return freed

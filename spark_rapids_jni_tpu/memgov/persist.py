"""Durable spill metadata: manifests, re-attach, orphan GC (ISSUE 20).

The catalog's disk tier already writes CRC-framed spill files, but the
metadata that makes them usable — key, kind, treedef, leaf count —
lived only in the owning process: a coordinator ``kill -9`` left every
``.frm`` under ``SRJT_SPILL_DIR`` orphaned (leaked bytes no process
would ever reclaim) and every deliberately-checkpointed OOC partition
(plan/ooc.py writes them under fingerprint-stable keys precisely so a
retry can resume) unreachable. This module closes both halves:

- **Manifests**: with ``SRJT_SPILL_MANIFESTS=1``, every disk demotion
  also writes ``<frame>.mf`` — a CRC-framed pickle of the entry's
  identity (key/kind/nbytes/n_leaves/owning pid/treedef). The payload
  crosses ``faultinj.maybe_torn("memgov.manifest", ...)`` so torn
  manifests are deterministically testable; a torn or rotted manifest
  reads back as None and the frame is treated as unprovable. The frame
  itself keeps its own per-leaf CRCs — re-attached entries verify
  LAZILY on first ``get()``, and rot retires the entry with retryable
  ``DataCorruption`` exactly as today (the OOC lineage recompute path).
- **Startup** (``startup``, hooked into ``memgov.catalog()``): sweep +
  re-attach. Frames whose manifest names a provably-dead owning PID are
  either ADOPTED — durable checkpoint kinds (``partition``, ``cache``)
  re-register into the fresh catalog at the disk tier, manifest
  rewritten under the adopting PID (``memgov.reattached``) — or
  RECLAIMED: a dead process's working-set spills (``buffer`` kind) back
  no catalog and never re-materialize, so they unlink
  (``memgov.orphans_reclaimed``). Live owners' files are never touched.
  Default per-process spill dirs (``srjt-spill-<pid>``) of dead PIDs
  are swept wholesale — the dir name itself proves ownership there.

Everything is inert with ``SRJT_SPILL_MANIFESTS`` unset: no sidecar
writes, no startup scan, zero new files — the off posture is bit-for-
bit the pre-PR catalog.
"""

from __future__ import annotations

import os
import pickle
import re
import struct
import tempfile
import threading
from typing import Optional

from ..utils import faultinj, integrity, knobs, metrics

__all__ = [
    "manifests_enabled",
    "manifest_path",
    "write_manifest",
    "read_manifest",
    "remove_manifest",
    "startup",
    "sweep_default_dirs",
    "stats_counters",
]

_MAGIC = b"SRJTMF01"
_HDR = struct.Struct("<II")  # payload len, payload crc

# kinds a fresh process ADOPTS from a dead owner: deliberately-durable
# checkpoints worth resuming. Everything else (working-set "buffer"
# spills, accounting kinds) is reclaimed — its catalog died with the
# process and nothing will ever re-materialize it.
ADOPT_KINDS = ("partition", "cache")

_DEFAULT_DIR_RE = re.compile(r"^srjt-spill-(\d+)$")


def _registry():
    return metrics.registry()


def manifests_enabled() -> bool:
    return knobs.get_bool("SRJT_SPILL_MANIFESTS")


def manifest_path(frame_path: str) -> str:
    return frame_path + ".mf"


def _pid_alive(pid: int) -> bool:
    """Liveness probe on an owning PID. Only ProcessLookupError proves
    death; EPERM (a live process we may not signal) and any other
    surprise count as alive — the sweep must never reclaim a live
    process's spill."""
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        return True
    return True


# ---------------------------------------------------------------------------
# manifest read/write
# ---------------------------------------------------------------------------


def write_manifest(frame_path: str, key: str, kind: str, nbytes: int,
                   n_leaves: int, treedef) -> bool:
    """Write the sidecar manifest for one disk frame (caller holds the
    catalog lock — same discipline as the frame write it follows).
    Failure is counted and absorbed: a manifest the volume refused
    costs re-attachability, never the spill."""
    try:
        payload = pickle.dumps(
            {
                "key": key,
                "kind": kind,
                "nbytes": int(nbytes),
                "n_leaves": int(n_leaves),
                "pid": os.getpid(),
                # pickled treedef: producer and consumer are the same
                # codebase (the spill frames themselves already assume
                # that), so cross-process unflatten is sound
                "treedef": treedef,
            },
            protocol=pickle.HIGHEST_PROTOCOL,
        )
    except Exception:  # srjt-lint: allow-broad-except(an unpicklable treedef costs re-attachability of this one entry, never the spill that is already on disk)
        _registry().counter("memgov.manifest_failures").inc()
        return False
    frame = _MAGIC + _HDR.pack(len(payload), integrity.checksum(payload)) + payload
    # torn-write chaos crossing: replay must treat a torn manifest as
    # absent (unprovable ownership), never as a crash
    frame = faultinj.maybe_torn("memgov.manifest", frame)
    path = manifest_path(frame_path)
    try:
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(frame)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except OSError:
        _registry().counter("memgov.manifest_failures").inc()
        return False
    _registry().counter("memgov.manifests_written").inc()
    return True


def read_manifest(frame_path: str) -> Optional[dict]:
    """The manifest dict for one frame, or None on ANY defect — magic,
    length, CRC, unpickle. A torn/rotted manifest means the frame's
    ownership and identity are unprovable; the caller leaves it."""
    path = manifest_path(frame_path)
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return None
    if raw[: len(_MAGIC)] != _MAGIC:
        _registry().counter("memgov.manifest_rot").inc()
        return None
    if len(raw) < len(_MAGIC) + _HDR.size:
        _registry().counter("memgov.manifest_rot").inc()
        return None
    ln, crc = _HDR.unpack_from(raw, len(_MAGIC))
    payload = raw[len(_MAGIC) + _HDR.size:]
    if len(payload) != ln or integrity.checksum(payload) != crc:
        _registry().counter("memgov.manifest_rot").inc()
        return None
    try:
        man = pickle.loads(payload)
    except Exception:  # srjt-lint: allow-broad-except(a CRC-valid but unloadable manifest is rot with a fancier disease — same absence contract)
        _registry().counter("memgov.manifest_rot").inc()
        return None
    return man if isinstance(man, dict) else None


def remove_manifest(frame_path: str) -> None:
    """Best-effort sidecar unlink, riding every frame unlink
    (catalog close / re-materialization consume)."""
    try:
        os.unlink(manifest_path(frame_path))
    except OSError:
        pass


# ---------------------------------------------------------------------------
# startup: orphan sweep + catalog re-attach
# ---------------------------------------------------------------------------

_startup_lock = threading.Lock()


def sweep_default_dirs() -> int:
    """Reclaim default per-process spill dirs (``srjt-spill-<pid>``
    under the system tempdir) whose PID is provably dead — the
    satellite leak: a SIGKILL'd process using the default dir never
    reclaimed its files. The dir NAME proves ownership, so unmanifested
    frames reclaim too. Returns files reclaimed."""
    reclaimed = 0
    base = tempfile.gettempdir()
    try:
        names = os.listdir(base)
    except OSError:
        return 0
    for name in names:
        m = _DEFAULT_DIR_RE.match(name)
        if m is None:
            continue
        pid = int(m.group(1))
        if pid == os.getpid() or _pid_alive(pid):
            continue
        d = os.path.join(base, name)
        try:
            entries = os.listdir(d)
        except OSError:
            continue
        for fn in entries:
            if not (fn.endswith(".frm") or fn.endswith(".mf")
                    or fn.endswith(".mf.tmp")):
                continue  # never touch a file shape the catalog didn't write
            try:
                os.unlink(os.path.join(d, fn))
            except OSError:
                continue
            if fn.endswith(".frm"):
                reclaimed += 1
                _registry().counter("memgov.orphans_reclaimed").inc()
        try:
            os.rmdir(d)
        except OSError:
            pass
    return reclaimed


def startup(catalog) -> dict:
    """The recovery scan, hooked into ``memgov.catalog()`` when
    manifests are enabled: sweep dead default dirs, then walk the
    configured spill dir — adopt durable checkpoint frames from dead
    owners into ``catalog`` (disk tier, lazily CRC-verified) and
    reclaim their working-set frames. Never raises: a sick spill volume
    degrades recovery, not catalog construction."""
    report = {"reattached": 0, "orphans_reclaimed": 0, "skipped_live": 0,
              "unprovable": 0}
    try:
        report["orphans_reclaimed"] += sweep_default_dirs()
        spill_dir = knobs.get_str("SRJT_SPILL_DIR")
        if spill_dir and os.path.isdir(spill_dir):
            _scan_shared_dir(spill_dir, catalog, report)
    except Exception as e:  # srjt-lint: allow-broad-except(recovery-scan failure degrades to the volatile posture; catalog construction must survive any disk disease)
        _registry().counter("memgov.persist_startup_failures").inc()
        metrics.event("memgov.persist_startup_failed", error=str(e))
    metrics.event("memgov.persist_startup", **report)
    return report


def _scan_shared_dir(spill_dir: str, catalog, report: dict) -> None:
    reg = _registry()
    try:
        names = sorted(os.listdir(spill_dir))
    except OSError:
        return
    for name in names:
        if name.endswith(".mf.tmp"):
            # an interrupted manifest replace: always safe to drop
            try:
                os.unlink(os.path.join(spill_dir, name))
            except OSError:
                pass
            continue
        if name.endswith(".mf"):
            # a sidecar whose frame is gone (crash between frame unlink
            # and sidecar unlink): drop it
            if not os.path.exists(os.path.join(spill_dir, name[:-3])):
                try:
                    os.unlink(os.path.join(spill_dir, name))
                except OSError:
                    pass
            continue
        if not name.endswith(".frm"):
            continue
        frame = os.path.join(spill_dir, name)
        man = read_manifest(frame)
        if man is None:
            # no/torn manifest: ownership unprovable, leave the frame
            # (pre-manifest processes and live writers both land here)
            report["unprovable"] += 1
            continue
        pid = int(man.get("pid", 0))
        if pid == os.getpid() or _pid_alive(pid):
            report["skipped_live"] += 1
            continue
        if (man.get("kind") in ADOPT_KINDS
                and man.get("treedef") is not None
                and _reattach(catalog, frame, man)):
            report["reattached"] += 1
            reg.counter("memgov.reattached").inc()
            metrics.event("memgov.reattach", key=man.get("key"),
                          kind=man.get("kind"), from_pid=pid)
        else:
            try:
                os.unlink(frame)
            except OSError:
                report["unprovable"] += 1
                continue
            remove_manifest(frame)
            report["orphans_reclaimed"] += 1
            reg.counter("memgov.orphans_reclaimed").inc()
            metrics.event("memgov.orphan_reclaimed", key=man.get("key"),
                          kind=man.get("kind"), from_pid=pid)


def _reattach(catalog, frame: str, man: dict) -> bool:
    """Re-register one surviving disk frame into a fresh catalog at the
    disk tier. The frame's own CRCs verify lazily on first ``get()``;
    rot there retires the entry and raises retryable DataCorruption —
    the caller's lineage recompute engages exactly as for same-process
    rot. The manifest is rewritten under the adopting PID first, so a
    second recoverer probing later sees a live owner."""
    from .catalog import SpillableHandle

    key = man.get("key")
    if not key:
        return False
    if not write_manifest(frame, key, man["kind"], man["nbytes"],
                          man["n_leaves"], man["treedef"]):
        return False
    with catalog._lock:
        if key in catalog._entries:
            return False  # a live entry always wins over a dead twin
        h = SpillableHandle(catalog, key, man["kind"], man["nbytes"],
                            man["treedef"], None)
        h._n_leaves = int(man["n_leaves"])
        h._disk_path = frame
        catalog._seq += 1
        h._seq = catalog._seq
        catalog._entries[key] = h
        catalog._update_gauges_locked()
    return True


def stats_counters() -> dict:
    """The persist half of the ``durability`` stats section."""
    reg = _registry()
    return {
        "manifests_written": reg.value("memgov.manifests_written"),
        "manifest_rot": reg.value("memgov.manifest_rot"),
        "manifest_failures": reg.value("memgov.manifest_failures"),
        "reattached": reg.value("memgov.reattached"),
        "orphans_reclaimed": reg.value("memgov.orphans_reclaimed"),
    }

"""Device memory governor: byte-weighted admission control + spillable
buffer catalog + the pressure loop that connects them (ISSUE 4).

The reference stack never lets tasks race each other into device OOM:
the plugin gates concurrent tasks on the GPU with a semaphore and backs
every cached batch with a spill framework (device->host->disk). Until
this subsystem, the TPU tier had only the *predictive* estimator in
utils/memory.py — per-op refusal, nothing limiting the AGGREGATE
concurrent footprint, and over-budget data simply re-split or dropped.
Theseus (PAPERS.md) shows a memory-hierarchy-aware catalog that demotes
cold buffers to host is what scales query processing past HBM; Thallus
motivates keeping the demoted representation transport-ready. This
package is that subsystem, in three cooperating parts:

- **admission** (`admission.py`): a byte-weighted semaphore over
  ``memory.device_memory_budget()``. ``op_boundary``
  (utils/dispatch.py) acquires it with each op's footprint estimate
  before dispatch (only the OUTERMOST boundary per thread — the retry
  nesting discipline). FIFO fairness, an optional
  ``SRJT_ADMISSION_MAX_CONCURRENT`` cap, and waits that cooperate with
  utils/deadline.py: a wait never outlives the query budget
  (denial-on-dead-budget raises ``DeadlineExceeded``), and sustained
  over-budget demand raises the existing retryable
  ``MemoryBudgetExceeded`` so the retry orchestrator's split path
  engages.
- **catalog** (`catalog.py`): ``SpillableHandle``s wrapping device
  arrays (pipeline build tables, shuffle exchange buffers, sidecar
  arena registrations) with pin/unpin semantics, LRU-ordered demotion
  device->host (numpy) ->disk under pressure, and transparent
  re-materialization on access — bit-identical round-trips.
- **pressure** (`pressure.py`): invoked by the admission controller
  when an acquire would block — spills unpinned catalog entries until
  the request fits, with the compiled-executable cache
  (parallel/_smcache) as an opt-in last resort.

Activation mirrors the metrics-stub pattern: ``SRJT_SPILL_ENABLED``
arms the governor explicitly; unset, it arms exactly when an operator
declared a budget (``SRJT_DEVICE_MEMORY_BUDGET``). Disabled (the seed
posture), the only hot-path cost in ``op_boundary`` is one reserved-
kwarg pop plus one boolean read — no estimate, no locks, no registry
touch. Observability is registry-direct (utils/metrics durable-counter
contract): ``memgov.admitted/queued/rejected/spilled_bytes/respilled``
counters, ``memgov.queue_wait_us`` / ``memgov.spill_us`` histograms,
and a ``memgov`` section in ``runtime.stats_report()``.

Environment:

    SRJT_SPILL_ENABLED            "1"/"true" arms the governor ("0"
                                  disarms even with a budget set);
                                  unset: armed iff
                                  SRJT_DEVICE_MEMORY_BUDGET is set.
                                  The arming decision is frozen at
                                  import (hot path = one boolean
                                  read); arm a live process with
                                  enable()
    SRJT_DEVICE_MEMORY_BUDGET     device byte budget (utils/memory.py;
                                  read live)
    SRJT_ADMISSION_MAX_CONCURRENT admitted-op cap (default 0: bytes
                                  only)
    SRJT_ADMISSION_MAX_WAIT_SEC   queue wait before the retryable
                                  MemoryBudgetExceeded (default 30)
    SRJT_SPILL_DIR                disk-tier directory (default: a
                                  per-process dir under the system
                                  tempdir)
    SRJT_HOST_MEMORY_BUDGET       host-tier byte budget; past it,
                                  host entries demote to disk
                                  (default 0: unlimited)
    SRJT_MEMGOV_HEADROOM          input-bytes -> footprint multiplier
                                  for the default op estimate
                                  (default 2.0: XLA temps)
    SRJT_MEMGOV_DROP_SMCACHE      "1" lets the pressure loop clear the
                                  compiled-executable cache as a last
                                  resort (default off: recompiles are
                                  expensive)
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

from ..utils import knobs
from .admission import Admission, AdmissionController
from .catalog import (
    TIER_DEVICE,
    TIER_DISK,
    TIER_HOST,
    BufferCatalog,
    SpillableHandle,
)

__all__ = [
    "Admission",
    "AdmissionController",
    "BufferCatalog",
    "SpillableHandle",
    "TIER_DEVICE",
    "TIER_HOST",
    "TIER_DISK",
    "controller",
    "catalog",
    "admit",
    "ensure_fits",
    "estimate_call_bytes",
    "enable",
    "disable",
    "is_enabled",
    "enabled",
    "in_admission",
    "stats_section",
    "reset",
]


def _env_enabled() -> bool:
    # no explicit arming: govern exactly when an operator declared a
    # budget — a declared budget with no enforcement is the seed bug
    # this subsystem exists to close
    return knobs.get_bool(
        "SRJT_SPILL_ENABLED",
        default=knobs.is_set("SRJT_DEVICE_MEMORY_BUDGET"),
    )


_enabled = _env_enabled()


def enable() -> None:
    """Arm the governor (op_boundary admission + pressure spilling)."""
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def is_enabled() -> bool:
    return _enabled


@contextlib.contextmanager
def enabled():
    """Scoped arming for tests (pair with SRJT_DEVICE_MEMORY_BUDGET via
    monkeypatch for a deterministic capacity)."""
    global _enabled
    prev = _enabled
    _enabled = True
    try:
        yield
    finally:
        _enabled = prev


# ---------------------------------------------------------------------------
# process-wide singletons (one device, one budget, one catalog)
# ---------------------------------------------------------------------------

# RLock: controller() builds its catalog via catalog() while holding it
_lock = threading.RLock()
_catalog: Optional[BufferCatalog] = None
_controller: Optional[AdmissionController] = None


def catalog() -> BufferCatalog:
    """The process-wide spillable buffer catalog."""
    global _catalog
    if _catalog is None:
        with _lock:
            if _catalog is None:
                cat = BufferCatalog()
                # srjt-durable (ISSUE 20): with manifests armed, a fresh
                # catalog re-attaches surviving spill files from dead
                # owners and GCs the unidentifiable rest. startup()
                # never raises (counted memgov.persist_startup_failures)
                from . import persist
                if persist.manifests_enabled():
                    persist.startup(cat)
                _catalog = cat
    return _catalog


def controller() -> AdmissionController:
    """The process-wide admission controller (shares the catalog so the
    pressure loop spills what the process actually cached)."""
    global _controller
    if _controller is None:
        with _lock:
            if _controller is None:
                _controller = AdmissionController(catalog=catalog())
    return _controller


def reset() -> None:
    """Fresh singletons (tests): closes the catalog — dropping every
    entry and its spill files — and discards queued admission state.
    The enable gate is left as-is."""
    global _catalog, _controller
    with _lock:
        cat, _catalog, _controller = _catalog, None, None
    if cat is not None:
        cat.close()
    _tls.depth = 0
    _tls.current = None


# ---------------------------------------------------------------------------
# op-boundary integration (utils/dispatch.py)
# ---------------------------------------------------------------------------

# per-thread nesting guard, mirroring utils/retry.py: only the
# OUTERMOST op_boundary on a thread owns an admission — a nested op's
# footprint is part of its parent's, and double-admitting would
# deadlock the byte semaphore against itself
_tls = threading.local()


def in_admission() -> bool:
    """True while this thread holds an op_boundary admission."""
    return getattr(_tls, "depth", 0) > 0


def _headroom() -> float:
    return knobs.get_float("SRJT_MEMGOV_HEADROOM")


def estimate_call_bytes(args=(), kwargs=None) -> int:
    """Default per-op footprint: the summed nbytes of every array leaf
    in the call (Tables and Columns are jax pytrees, so their lanes
    flatten out) times SRJT_MEMGOV_HEADROOM — XLA temps routinely need
    a small multiple of the declared inputs. Ops with data-dependent
    buffer growth pass an explicit ``memory_bytes=`` instead."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves((tuple(args), kwargs or {})):
        nb = getattr(leaf, "nbytes", None)
        if nb is not None:
            total += int(nb)
    return int(total * _headroom())


def admit(name: str, args=(), kwargs=None, nbytes=None) -> Optional[Admission]:
    """Acquire the byte-weighted admission for one op dispatch, or None
    when the governor is disarmed / an enclosing boundary already holds
    one. The caller MUST release the returned Admission (op_boundary
    does so in a finally)."""
    if not _enabled or getattr(_tls, "depth", 0) > 0:
        return None
    if nbytes is None:
        nbytes = estimate_call_bytes(args, kwargs)
    adm = controller().acquire(int(nbytes), name=name)
    _tls.depth = 1
    _tls.current = adm
    adm._on_release = _clear_tls
    return adm


def _clear_tls() -> None:
    _tls.depth = 0
    _tls.current = None


def ensure_fits(nbytes: int, name: str = "op") -> None:
    """Non-queueing fit check for IN-OP footprint escalations (the
    shuffle capacity-doubling loop): run the pressure loop until
    ``nbytes`` fits the budget, else raise the retryable
    ``MemoryBudgetExceeded`` so the caller splits instead of driving
    XLA into an OOM. No-op when the governor is disarmed. The thread's
    held op admission (if any) does not count against its own
    escalation — instead it GROWS to the escalated footprint, so
    concurrent admissions see the doubled buffers as reserved."""
    if not _enabled:
        return
    controller().ensure_fits(
        int(nbytes), name=name, admission=getattr(_tls, "current", None)
    )


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------


def stats_section() -> dict:
    """The ``memgov`` section of runtime.stats_report(): registry
    counters (always-on) plus admission/catalog snapshots when the
    singletons exist — a stats poll never instantiates them."""
    from ..utils import metrics

    reg = metrics.registry()
    out = {
        "enabled": _enabled,
        "admitted": reg.value("memgov.admitted"),
        "queued": reg.value("memgov.queued"),
        "rejected": reg.value("memgov.rejected"),
        "spilled_bytes": reg.value("memgov.spilled_bytes"),
        "spills": reg.value("memgov.spills"),
        "respilled": reg.value("memgov.respilled"),
        "rematerialized_bytes": reg.value("memgov.rematerialized_bytes"),
        "spill_failures": reg.value("memgov.spill_failures"),
        "queue_wait_us": reg.value("memgov.queue_wait_us", default=None),
        "spill_us": reg.value("memgov.spill_us", default=None),
    }
    if _controller is not None:
        out["admission"] = _controller.snapshot()
    if _catalog is not None:
        out["catalog"] = _catalog.snapshot()
    return out

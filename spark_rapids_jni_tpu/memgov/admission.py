"""Byte-weighted admission control over the device memory budget.

The reference plugin's GpuSemaphore gates concurrent tasks on the GPU
so they cannot race each other into OOM; this is its TPU analog, in
BYTES rather than task slots (XLA owns the allocator, so the governor
gates on predicted footprints): every outermost ``op_boundary``
dispatch acquires ``nbytes`` from a budget-sized semaphore before
running, and releases on completion.

Semantics:

- **FIFO fairness**: waiters queue in arrival order; only the HEAD
  waiter may admit, so a stream of small requests cannot starve a
  large one indefinitely.
- **Occupancy** counts admitted op footprints PLUS the catalog's
  device-resident bytes — cached buffers and in-flight ops share one
  budget, which is the whole point.
- **Pressure before queueing**: an acquire that would block first runs
  the pressure loop (pressure.py) to demote unpinned catalog entries;
  only demand the catalog cannot absorb waits.
- **Deadline-cooperative waits** (utils/deadline.py): a wait never
  outlives the query budget — denial-on-dead-budget raises
  ``DeadlineExceeded``.
- **Bounded waits**: a request that cannot be admitted within
  ``SRJT_ADMISSION_MAX_WAIT_SEC`` — or that could NEVER fit (larger
  than the whole budget net of unspillable residents, or nothing left
  to spill and nothing in flight to release) — raises the existing
  retryable ``MemoryBudgetExceeded``, so the retry orchestrator's
  split path engages exactly as it does for the predictive estimator.
- **Concurrency cap**: ``SRJT_ADMISSION_MAX_CONCURRENT`` (default 0 =
  bytes-only) additionally bounds admitted ops, the GpuSemaphore's
  task-slot dimension.

The pressure loop runs while holding the admission lock — a release
arriving mid-spill waits out the (host-copy-sized) demotion; lock
ordering is admission -> catalog, and the catalog never calls back
into admission, so the pair cannot deadlock.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

from ..utils.memory import MemoryBudgetExceeded, device_memory_budget

__all__ = ["Admission", "AdmissionController"]


def _registry():
    from ..utils import metrics

    return metrics.registry()


class Admission:
    """A held byte reservation; release exactly once (idempotent)."""

    __slots__ = ("nbytes", "name", "_controller", "_released", "_on_release")

    def __init__(self, controller: "AdmissionController", nbytes: int, name: str):
        self.nbytes = nbytes
        self.name = name
        self._controller = controller
        self._released = False
        self._on_release: Optional[Callable[[], None]] = None

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        try:
            self._controller._release(self)
        finally:
            if self._on_release is not None:
                self._on_release()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


class AdmissionController:
    """The byte-weighted FIFO semaphore. ``capacity_fn`` resolves the
    live budget on every admission decision (the env override stays a
    live test hook; utils/memory.py memoizes the backend probe)."""

    def __init__(
        self,
        capacity_fn: Optional[Callable[[], int]] = None,
        catalog=None,
        max_concurrent: Optional[int] = None,
        max_wait_s: Optional[float] = None,
        clock=time.monotonic,
    ):
        from ..utils import knobs

        self._capacity_fn = capacity_fn or device_memory_budget
        if catalog is None:
            from .catalog import BufferCatalog

            catalog = BufferCatalog()
        self._catalog = catalog
        if max_concurrent is None:
            max_concurrent = knobs.get_int("SRJT_ADMISSION_MAX_CONCURRENT")
        self._max_concurrent = int(max_concurrent)
        self._max_wait_s = (
            knobs.get_float("SRJT_ADMISSION_MAX_WAIT_SEC")
            if max_wait_s is None
            else float(max_wait_s)
        )
        self._clock = clock
        self._cond = threading.Condition()
        self._queue: deque = deque()
        self._in_use = 0
        self._active = 0

    # -- introspection -------------------------------------------------------

    @property
    def catalog(self):
        return self._catalog

    def capacity(self) -> int:
        return int(self._capacity_fn())

    def in_use(self) -> int:
        return self._in_use  # srjt-race: allow-unguarded(single machine-word stats read; GIL-atomic, monitoring only — admission decisions re-read under _cond)

    def snapshot(self) -> dict:
        with self._cond:
            return {
                "capacity": self.capacity(),
                "in_use_bytes": self._in_use,
                "catalog_device_bytes": self._catalog.device_bytes(),
                "active": self._active,
                "queue_depth": len(self._queue),
                "max_concurrent": self._max_concurrent,
                "max_wait_s": self._max_wait_s,
            }

    def _occupancy_locked(self) -> int:
        return self._in_use + self._catalog.device_bytes()

    def _update_gauges_locked(self) -> None:
        reg = _registry()
        reg.gauge("memgov.in_use_bytes").set(self._in_use)
        reg.gauge("memgov.active_ops").set(self._active)
        reg.gauge("memgov.queue_depth").set(len(self._queue))

    # -- the semaphore -------------------------------------------------------

    def acquire(self, nbytes: int, name: str = "op") -> Admission:
        """Block until ``nbytes`` fits (FIFO order), spilling catalog
        entries under pressure. Raises ``DeadlineExceeded`` when the
        active query budget dies first, ``MemoryBudgetExceeded`` when
        the demand is hopeless or outwaits the admission bound.

        srjt-trace (ISSUE 12): the whole acquire — queue wait, pressure
        spills, and the admit/reject verdict — is one
        ``memgov.admission_wait`` span when a traced query is active,
        so a query stuck behind the byte semaphore shows the wait as a
        span, not as unexplained time inside its op."""
        from ..utils import tracing

        with tracing.span(
            "memgov.admission_wait", op=name, nbytes=int(nbytes)
        ):
            return self._acquire(int(nbytes), name)

    def _acquire(self, nbytes: int, name: str) -> Admission:
        from ..utils import deadline as deadline_mod
        from ..utils import metrics

        nbytes = max(int(nbytes), 0)
        reg = _registry()
        t0 = self._clock()
        ticket = object()
        queued = False
        tried_pressure = False
        with self._cond:
            self._queue.append(ticket)
            try:
                while True:
                    cap = self.capacity()
                    at_head = self._queue[0] is ticket
                    conc_ok = (
                        self._max_concurrent <= 0
                        or self._active < self._max_concurrent
                    )
                    if at_head and conc_ok:
                        need = self._occupancy_locked() + nbytes - cap
                        # relieve when there is something to spill (or
                        # once, for the last-resort valve) — a blocked
                        # waiter must not spin the pressure loop on an
                        # already-drained catalog every poll slice
                        if need > 0 and (
                            self._catalog.spillable_device_bytes() > 0
                            or not tried_pressure
                        ):
                            tried_pressure = True
                            from . import pressure

                            pressure.relieve(need, self._catalog, name=name)
                            need = self._occupancy_locked() + nbytes - cap
                        if need <= 0:
                            self._queue.popleft()
                            self._in_use += nbytes
                            self._active += 1
                            reg.counter("memgov.admitted").inc()
                            reg.histogram("memgov.queue_wait_us").record(
                                (self._clock() - t0) * 1e6
                            )
                            self._update_gauges_locked()
                            self._cond.notify_all()
                            return Admission(self, nbytes, name)
                        # hopeless demand never waits: either the request
                        # can't fit even with every spillable gone, or
                        # nothing is left to spill and nothing in flight
                        # could release — split now (retryable)
                        spillable = self._catalog.spillable_device_bytes()
                        if (
                            nbytes + self._in_use - cap > spillable
                            and self._active == 0
                        ) or (spillable == 0 and self._active == 0):
                            reg.counter("memgov.rejected").inc()
                            metrics.event(
                                "memgov.reject", op=name, nbytes=nbytes,
                                capacity=cap, in_use=self._in_use,
                            )
                            raise MemoryBudgetExceeded(
                                f"admission: {name} needs {nbytes} device bytes "
                                f"(budget {cap}, {self._occupancy_locked()} occupied, "
                                f"nothing left to spill or release); split the "
                                f"batch"
                            )
                    if not queued:
                        queued = True
                        reg.counter("memgov.queued").inc()
                        metrics.event(
                            "memgov.queue", op=name, nbytes=nbytes,
                            in_use=self._in_use,
                        )
                    d = deadline_mod.current()
                    if d is not None and d.done():
                        reg.counter("memgov.deadline_denied").inc()
                        raise d.exceeded(f"memgov admission ({name})")
                    waited = self._clock() - t0
                    if waited >= self._max_wait_s:
                        reg.counter("memgov.rejected").inc()
                        metrics.event(
                            "memgov.reject", op=name, nbytes=nbytes,
                            waited_s=round(waited, 3),
                        )
                        raise MemoryBudgetExceeded(
                            f"admission: {name} waited {waited:.2f}s for "
                            f"{nbytes} device bytes (budget {self.capacity()}, "
                            f"{self._in_use} admitted); sustained over-budget "
                            f"demand — split the batch"
                        )
                    step = min(0.02, self._max_wait_s - waited)
                    if d is not None:
                        # wake just past the deadline edge, not a poll late
                        step = min(step, max(d.remaining(), 0.0) + 0.001)
                    self._cond.wait(max(step, 0.001))
            finally:
                try:
                    self._queue.remove(ticket)
                except ValueError:
                    pass  # admitted (popped) — the success path
                self._update_gauges_locked()
                self._cond.notify_all()

    def _release(self, adm: Admission) -> None:
        with self._cond:
            self._in_use -= adm.nbytes
            self._active -= 1
            self._update_gauges_locked()
            self._cond.notify_all()

    def ensure_fits(self, nbytes: int, name: str = "op",
                    admission: Optional[Admission] = None) -> None:
        """Non-queueing fit check for an IN-OP footprint escalation
        (the shuffle capacity-doubling loop): verifies the ESCALATED
        footprint fits the budget — spilling under pressure — and
        raises the retryable ``MemoryBudgetExceeded`` when it cannot,
        so the caller splits instead of driving XLA into an OOM.

        ``admission`` is the escalating op's OWN held reservation: the
        escalated footprint REPLACES its estimate, so on success the
        reservation GROWS to ``nbytes`` in the semaphore's accounting —
        a concurrent admission cannot slip into bytes the escalated
        exchange is about to use (the held share never shrinks: the
        original buffers stay live while the bigger program builds)."""
        from ..utils import metrics

        reg = _registry()
        nbytes = max(int(nbytes), 0)
        with self._cond:
            cap = self.capacity()
            held = 0
            if admission is not None and not admission._released:
                held = min(admission.nbytes, self._in_use)
            need = self._occupancy_locked() - held + nbytes - cap
            if need > 0:
                from . import pressure

                pressure.relieve(need, self._catalog, name=name)
                need = self._occupancy_locked() - held + nbytes - cap
            if need > 0:
                reg.counter("memgov.rejected").inc()
                metrics.event(
                    "memgov.reject", op=name, nbytes=nbytes, capacity=cap,
                    escalation=True,
                )
                raise MemoryBudgetExceeded(
                    f"{name}: escalated footprint {nbytes} bytes cannot fit "
                    f"the device budget ({cap} bytes, "
                    f"{self._occupancy_locked()} occupied); split the batch"
                )
            if admission is not None and not admission._released and \
                    nbytes > admission.nbytes:
                self._in_use += nbytes - admission.nbytes
                admission.nbytes = nbytes
                self._update_gauges_locked()

    def drain_for_tests(self) -> None:
        """Zero the semaphore (tests recovering from a leaked
        admission; production code releases via Admission)."""
        with self._cond:
            self._in_use = 0
            self._active = 0
            self._queue.clear()
            self._update_gauges_locked()
            self._cond.notify_all()

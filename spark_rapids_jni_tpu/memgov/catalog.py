"""Spillable buffer catalog: the memory hierarchy's demotion tier.

The reference plugin backs every cached batch with its spill framework
(device buffers demote to host, host to disk, everything
re-materializes on access) — which is what lets it cache aggressively
without racing into OOM. ``BufferCatalog`` is that framework for the
TPU tier: a ``SpillableHandle`` wraps any jax pytree of arrays (a bare
array, a columnar ``Table``, a pipeline build table) with pin/unpin
semantics, LRU-ordered demotion device->host (numpy) ->disk
(``SRJT_SPILL_DIR``) under pressure, and transparent re-materialization
on ``get()``. Demoted leaves are exact byte copies (numpy round-trips
IEEE bit patterns and integer lanes unchanged), so a
spill->re-materialize cycle is bit-identical — the invariant
tests/test_memgov.py round-trips. Disk spills are CRC-framed
(utils/integrity.py; ISSUE 5): the container carries a checksum
verified on re-materialization, so a bit-rotted or truncated spill
raises retryable ``DataCorruption`` (the caller re-computes via the
retry/split machinery) instead of silently feeding wrong bytes back
into a query.

Accounting-only entries (``register_host_bytes``: sidecar arena memfds)
carry a size but no payload; they make host-tier consumers visible to
the budget, ``runtime.stats_report()``, and the sidecar STATS verb
without ever spilling.

A spill frees the CATALOG's reference; arrays a caller already holds
from ``get()`` stay valid (refcounted) — the governor's accounting is
advisory until the last reference drops, like every cache-eviction
scheme over shared buffers.

Observability is registry-direct (utils/metrics; the durable-counter
contract — a spill is a rare recovery event, not a hot path):
``memgov.spills`` / ``memgov.spilled_bytes`` / ``memgov.respilled`` /
``memgov.rematerialized`` / ``memgov.rematerialized_bytes`` /
``memgov.spill_failures`` counters, ``memgov.spill_us`` /
``memgov.rematerialize_us`` histograms, ``memgov.catalog.*_bytes`` and
``memgov.arena_bytes``/``memgov.arenas`` gauges. Chaos hook: every
demotion crosses ``faultinj.maybe_inject("memgov.spill")``, so a
``spill_fail`` rule keyed on ``memgov.spill`` makes spills fail
injectably — a failed spill leaves the entry resident and is counted,
never raised past the pressure loop.
"""

from __future__ import annotations

import os
import re
import tempfile
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..utils.errors import RetryableError

__all__ = [
    "TIER_DEVICE",
    "TIER_HOST",
    "TIER_DISK",
    "SpillableHandle",
    "BufferCatalog",
]

TIER_DEVICE = "device"
TIER_HOST = "host"
TIER_DISK = "disk"

# disk-spill container magic (ISSUE 5): [magic 8][u32 crc][u64 len][npz]
_SPILL_MAGIC = b"SRJTSPL1"


def _registry():
    from ..utils import metrics

    return metrics.registry()


class SpillableHandle:
    """One catalog entry: a pytree of array leaves at exactly one tier.

    Mutations happen under the owning catalog's lock (the public
    methods delegate); holders touch only ``get``/``pin``/``unpin``/
    ``spill``/``close`` and the read-only properties.
    """

    __slots__ = (
        "key",
        "kind",
        "nbytes",
        "spill_count",
        "_catalog",
        "_treedef",
        "_n_leaves",
        "_device",
        "_host",
        "_disk_path",
        "_pins",
        "_seq",
        "_closed",
    )

    def __init__(self, catalog: "BufferCatalog", key: str, kind: str,
                 nbytes: int, treedef, device_leaves: Optional[List]):
        self.key = key
        self.kind = kind
        self.nbytes = int(nbytes)
        self.spill_count = 0
        self._catalog = catalog
        self._treedef = treedef
        self._n_leaves = 0 if device_leaves is None else len(device_leaves)
        self._device = device_leaves
        self._host: Optional[List[np.ndarray]] = None
        self._disk_path: Optional[str] = None
        self._pins = 0
        self._seq = 0
        self._closed = False

    @property
    def tier(self) -> str:
        if self._device is not None:
            return TIER_DEVICE
        if self._disk_path is not None:
            return TIER_DISK
        return TIER_HOST

    @property
    def pinned(self) -> bool:
        return self._pins > 0

    @property
    def spillable(self) -> bool:
        """Payload-carrying, unpinned, and still device-resident."""
        return (
            not self._closed
            and self._treedef is not None
            and self._pins == 0
            and self._device is not None
        )

    def pin(self) -> "SpillableHandle":
        """Hold the entry at its current tier (a pinned device entry
        never spills; re-materialization still works on get)."""
        with self._catalog._lock:
            self._pins += 1
        return self

    def unpin(self) -> None:
        with self._catalog._lock:
            if self._pins > 0:
                self._pins -= 1

    def get(self):
        """The wrapped value, re-materialized to the device tier if it
        was demoted — transparent access, LRU-refreshing."""
        return self._catalog._get(self)

    def spill(self, to_disk: bool = False) -> None:
        """Force a demotion (tests / explicit cold-set management); a
        pinned entry raises ValueError."""
        self._catalog._force_spill(self, to_disk=to_disk)

    def close(self) -> None:
        self._catalog.unregister(self.key)


class BufferCatalog:
    """key -> SpillableHandle map with LRU demotion under one lock."""

    def __init__(
        self,
        spill_dir: Optional[str] = None,
        host_budget: Optional[int] = None,
        clock=time.monotonic,
    ):
        self._lock = threading.RLock()
        # srjt-race layer 2: the LRU map is tracked when SRJT_RACE=1
        # (every register/spill/get crosses it; a plain dict otherwise)
        from ..analysis.lockdep import track as _race_track

        self._entries: Dict[str, SpillableHandle] = _race_track(
            {}, "memgov.catalog.entries"
        )
        self._seq = 0
        self._clock = clock
        self._spill_dir = spill_dir  # resolved lazily on first disk spill
        if host_budget is None:
            from ..utils import knobs

            host_budget = knobs.get_int("SRJT_HOST_MEMORY_BUDGET")
        self._host_budget = int(host_budget)  # 0 == unlimited

    # -- registration --------------------------------------------------------

    def register(self, key: str, value, pinned: bool = False,
                 kind: str = "buffer") -> SpillableHandle:
        """Wrap ``value`` (any jax pytree of arrays: jnp array, Table,
        tuple of lanes) as a spillable device-tier entry. Re-registering
        a key replaces (and closes) the previous entry."""
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(value)
        nbytes = sum(int(getattr(x, "nbytes", 0)) for x in leaves)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._close_locked(old)
            h = SpillableHandle(self, key, kind, nbytes, treedef, list(leaves))
            h._pins = 1 if pinned else 0
            self._seq += 1
            h._seq = self._seq
            self._entries[key] = h
            self._update_gauges_locked()
        return h

    def register_host_bytes(self, key: str, nbytes: int, pinned: bool = True,
                            kind: str = "arena") -> SpillableHandle:
        """Accounting-only HOST-tier entry: a size with no payload (the
        sidecar's mmap'd arena memfds). Pinned by default — the bytes
        are owned elsewhere; the catalog only makes them visible."""
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._close_locked(old)
            h = SpillableHandle(self, key, kind, int(nbytes), None, None)
            h._pins = 1 if pinned else 0
            self._seq += 1
            h._seq = self._seq
            self._entries[key] = h
            self._update_gauges_locked()
        return h

    def lookup(self, key: str) -> Optional[SpillableHandle]:
        """The live handle registered under ``key``, or None — how the
        out-of-core partition loop (plan/ooc.py, ISSUE 18) finds a prior
        attempt's checkpointed partials to resume from."""
        with self._lock:
            h = self._entries.get(key)
            if h is None or h._closed:
                return None
            return h

    def unregister(self, key: str) -> bool:
        with self._lock:
            h = self._entries.pop(key, None)
            if h is None:
                return False
            self._close_locked(h)
            self._update_gauges_locked()
            return True

    def close(self) -> None:
        """Drop every entry (removing disk-spill files)."""
        with self._lock:
            for h in list(self._entries.values()):
                self._close_locked(h)
            self._entries.clear()
            self._update_gauges_locked()

    def _close_locked(self, h: SpillableHandle) -> None:
        h._closed = True
        h._device = None
        h._host = None
        if h._disk_path is not None:
            try:
                os.unlink(h._disk_path)
            except OSError:
                pass
            from . import persist
            persist.remove_manifest(h._disk_path)
            h._disk_path = None

    # -- accounting ----------------------------------------------------------

    def _tier_bytes_locked(self, tier: str) -> int:
        return sum(h.nbytes for h in self._entries.values() if h.tier == tier)

    def device_bytes(self) -> int:
        with self._lock:
            return self._tier_bytes_locked(TIER_DEVICE)

    def host_bytes(self) -> int:
        with self._lock:
            return self._tier_bytes_locked(TIER_HOST)

    def disk_bytes(self) -> int:
        with self._lock:
            return self._tier_bytes_locked(TIER_DISK)

    def spillable_device_bytes(self) -> int:
        """Device bytes the pressure loop may still reclaim."""
        with self._lock:
            return sum(h.nbytes for h in self._entries.values() if h.spillable)

    def pinned_device_bytes(self) -> int:
        with self._lock:
            return sum(
                h.nbytes
                for h in self._entries.values()
                if h.tier == TIER_DEVICE and not h.spillable
            )

    def kind_stats(self, kind: str) -> Tuple[int, int]:
        """(entries, bytes) of one registration kind — how the
        subresult cache (srjt-cache, kind="cache") reads its own
        governed footprint back out of the catalog."""
        with self._lock:
            hs = [h for h in self._entries.values() if h.kind == kind]
            return len(hs), sum(h.nbytes for h in hs)

    def _update_gauges_locked(self) -> None:
        reg = _registry()
        reg.gauge("memgov.catalog.entries").set(len(self._entries))
        for tier in (TIER_DEVICE, TIER_HOST, TIER_DISK):
            reg.gauge(f"memgov.catalog.{tier}_bytes").set(
                self._tier_bytes_locked(tier)
            )
        arenas = [h for h in self._entries.values() if h.kind == "arena"]
        reg.gauge("memgov.arenas").set(len(arenas))
        reg.gauge("memgov.arena_bytes").set(sum(h.nbytes for h in arenas))
        # srjt-cache (ISSUE 17): the subresult cache's governed
        # footprint — rides the same eviction/spill machinery, visible
        # as its own pair so squeeze artifacts can tell cache bytes
        # from working-set bytes
        cached = [h for h in self._entries.values() if h.kind == "cache"]
        reg.gauge("memgov.cache_entries").set(len(cached))
        reg.gauge("memgov.cache_bytes").set(sum(h.nbytes for h in cached))

    def snapshot(self) -> dict:
        """JSON-clean shape for runtime.stats_report()."""
        with self._lock:
            arenas = [h for h in self._entries.values() if h.kind == "arena"]
            return {
                "entries": len(self._entries),
                "device_bytes": self._tier_bytes_locked(TIER_DEVICE),
                "host_bytes": self._tier_bytes_locked(TIER_HOST),
                "disk_bytes": self._tier_bytes_locked(TIER_DISK),
                "pinned_device_bytes": sum(
                    h.nbytes
                    for h in self._entries.values()
                    if h.tier == TIER_DEVICE and h._pins > 0
                ),
                "arenas": len(arenas),
                "arena_bytes": sum(h.nbytes for h in arenas),
                "cache_entries": sum(
                    1 for h in self._entries.values() if h.kind == "cache"
                ),
                "cache_bytes": sum(
                    h.nbytes
                    for h in self._entries.values()
                    if h.kind == "cache"
                ),
            }

    # -- demotion ------------------------------------------------------------

    def _resolve_spill_dir(self) -> str:
        if self._spill_dir is None:
            from ..utils import knobs

            self._spill_dir = knobs.get_str("SRJT_SPILL_DIR") or os.path.join(
                tempfile.gettempdir(), f"srjt-spill-{os.getpid()}"
            )
        os.makedirs(self._spill_dir, exist_ok=True)
        return self._spill_dir

    def _spill_locked(self, h: SpillableHandle) -> None:
        """device -> host. Raises RetryableError when the chaos
        ``spill_fail`` rule fires (caller skips the entry); afterwards
        enforces the host budget by demoting LRU host entries to disk."""
        from ..utils import faultinj, metrics, tracing

        reg = _registry()
        t0 = time.perf_counter()
        # srjt-trace (ISSUE 12): a traced query that pays for a spill
        # (its own pressure, or a neighbor's data) sees the demotion as
        # a span — like metrics.event below, the record is written
        # under the catalog lock the spill itself already holds
        with tracing.span("memgov.spill", key=h.key, nbytes=h.nbytes):
            faultinj.maybe_inject("memgov.spill")
            h._host = [np.asarray(x) for x in h._device]
        h._device = None
        if h.spill_count:
            reg.counter("memgov.respilled").inc()
        h.spill_count += 1
        reg.counter("memgov.spills").inc()
        reg.counter("memgov.spilled_bytes").inc(h.nbytes)
        reg.histogram("memgov.spill_us").record((time.perf_counter() - t0) * 1e6)
        metrics.event("memgov.spill", key=h.key, nbytes=h.nbytes, tier=TIER_HOST)
        if self._host_budget > 0:
            try:
                self._enforce_host_budget_locked()
            except OSError:
                # disk tier unavailable (full disk, bad SRJT_SPILL_DIR):
                # the host copy above already stands — degrade to an
                # over-budget host tier, never fail the device spill
                reg.counter("memgov.spill_failures").inc()
                metrics.event("memgov.spill_failed", key=h.key, tier=TIER_DISK)

    def _demote_disk_locked(self, h: SpillableHandle) -> None:
        """host -> disk: one versioned columnar FRAME per entry under
        SRJT_SPILL_DIR (columnar/frames.py: magic + schema header +
        per-leaf CRC, verified on re-materialization — a bit-rotted or
        truncated spill surfaces as retryable DataCorruption, never as
        wrong rows). The same codec the sidecar wire and the TCP
        exchange emit (ISSUE 6); with integrity checks off the frame is
        written unchecked (flags clear, no hashing anywhere). Legacy
        spill containers (SRJTSPL1 envelope, plain npz) written before
        this layout still load — see ``_load_disk_locked``."""
        from ..columnar import frames
        from ..utils import faultinj, metrics

        reg = _registry()
        t0 = time.perf_counter()
        safe = re.sub(r"[^A-Za-z0-9_.-]", "_", h.key)
        path = os.path.join(
            self._resolve_spill_dir(), f"{safe}-{h._seq}.frm"
        )
        # chaos crossing (ISSUE 18): a `corrupt` rule keyed
        # "memgov.spill.frame" flips bytes AFTER the frame's CRCs were
        # computed — the bit-rot-on-disk model; re-materialization must
        # surface it as DataCorruption, never as wrong rows
        blob = faultinj.maybe_corrupt("memgov.spill.frame",
                                      frames.encode_leaves(h._host))
        with open(path, "wb") as f:
            f.write(blob)
        h._disk_path = path
        h._host = None
        # srjt-durable (ISSUE 20): a sidecar manifest makes the spill
        # file survivable — a fresh process re-registers it instead of
        # GC'ing an unidentifiable .frm. Write failure degrades to
        # today's volatile posture (counted), never fails the demotion.
        from . import persist
        if persist.manifests_enabled():
            persist.write_manifest(
                path, h.key, h.kind, h.nbytes, h._n_leaves, h._treedef
            )
        reg.counter("memgov.disk_spills").inc()
        reg.counter("memgov.disk_spilled_bytes").inc(h.nbytes)
        reg.histogram("memgov.spill_us").record((time.perf_counter() - t0) * 1e6)
        metrics.event("memgov.spill", key=h.key, nbytes=h.nbytes, tier=TIER_DISK)

    def _enforce_host_budget_locked(self) -> None:
        over = self._tier_bytes_locked(TIER_HOST) - self._host_budget
        if over <= 0:
            return
        victims = sorted(
            (
                h
                for h in self._entries.values()
                if h.tier == TIER_HOST and h._pins == 0 and h._treedef is not None
            ),
            key=lambda h: h._seq,
        )
        for h in victims:
            if over <= 0:
                break
            self._demote_disk_locked(h)
            over -= h.nbytes

    def _force_spill(self, h: SpillableHandle, to_disk: bool = False) -> None:
        with self._lock:
            if h._closed:
                raise ValueError(f"catalog entry {h.key!r} is closed")
            if h._pins > 0:
                raise ValueError(f"catalog entry {h.key!r} is pinned")
            if h._device is not None:
                self._spill_locked(h)
            if to_disk and h._host is not None:
                self._demote_disk_locked(h)
            self._update_gauges_locked()

    def spill_until(self, need_bytes: int, name: str = "pressure") -> int:
        """Demote LRU-ordered unpinned device entries until at least
        ``need_bytes`` are reclaimed (or nothing spillable remains).
        Returns the bytes freed. An injected spill failure skips that
        entry (counted ``memgov.spill_failures``) and moves on — the
        pressure loop degrades, never crashes the admission path."""
        from ..utils import metrics

        reg = _registry()
        freed = 0
        with self._lock:
            victims = sorted(
                (h for h in self._entries.values() if h.spillable),
                key=lambda h: h._seq,
            )
            for h in victims:
                if freed >= need_bytes:
                    break
                try:
                    self._spill_locked(h)
                except (RetryableError, OSError):
                    # injected spill_fail, or a real I/O failure: either
                    # way the entry stays resident and the loop degrades
                    # — admission must never crash on a sick spill tier
                    reg.counter("memgov.spill_failures").inc()
                    metrics.event("memgov.spill_failed", key=h.key)
                    continue
                freed += h.nbytes
            self._update_gauges_locked()
        return freed

    # -- access / re-materialization -----------------------------------------

    def _load_disk_locked(self, h: SpillableHandle) -> None:
        """disk -> host half of re-materialization: decode the columnar
        frame and VERIFY before trusting a byte (ISSUE 5/6). A mismatch
        — bit rot, truncation, a torn write — closes the entry (the
        only copy is bad; keeping it would serve the corruption again)
        and raises retryable ``DataCorruption`` so the caller's
        retry/split machinery re-computes from source instead of
        returning wrong rows. Migration (ISSUE 6 satellite): spill
        containers written before the frame layout — the SRJTSPL1
        CRC-envelope around npz, and plain unframed npz — still load
        through their original paths, so a process upgrade never
        strands a spill."""
        import io

        from ..columnar import frames
        from ..utils import integrity, metrics

        path = h._disk_path
        try:
            with open(path, "rb") as f:
                raw = f.read()
            if frames.is_frame(raw):
                # count a CHECKED re-materialization only when the
                # frame carries CRCs AND checks are armed — a frame
                # written under SRJT_INTEGRITY_CHECKS=0 decodes
                # unverified even if checks were re-enabled since
                if integrity.is_enabled() and frames.is_checked(raw):
                    _registry().counter("sidecar.integrity.spills_checked").inc()
                # per-leaf CRCs verified inside the codec (when armed);
                # a tampered leaf raises DataCorruption counted under
                # memgov.spill like the legacy envelope did
                h._host = frames.decode_leaves(raw, where="memgov.spill")
                if len(h._host) != h._n_leaves:
                    raise integrity.raise_corruption(
                        "memgov.spill",
                        f"{h.key}: leaf count {len(h._host)} != {h._n_leaves}",
                    )
            else:
                if raw[: len(_SPILL_MAGIC)] == _SPILL_MAGIC:
                    crc = integrity.unpack_crc(raw, len(_SPILL_MAGIC))
                    blen = int.from_bytes(
                        raw[len(_SPILL_MAGIC) + 4 : len(_SPILL_MAGIC) + 12], "little"
                    )
                    blob = raw[len(_SPILL_MAGIC) + 12 :]
                    if integrity.is_enabled():
                        _registry().counter("sidecar.integrity.spills_checked").inc()
                        if len(blob) != blen:
                            raise integrity.raise_corruption(
                                "memgov.spill", f"{h.key}: truncated ({len(blob)} != {blen})"
                            )
                        integrity.verify(blob, crc, "memgov.spill")
                else:
                    blob = raw  # pre-integrity spill file: no trailer to check
                with np.load(io.BytesIO(blob)) as z:
                    h._host = [z[f"a{i}"] for i in range(h._n_leaves)]
        except Exception as e:
            # corrupt (DataCorruption) or unreadable (zipfile/KeyError/
            # OSError — the same disease without a checksum to name it):
            # retire the entry and its file, then surface the corruption
            from ..utils.errors import DataCorruption

            metrics.event("memgov.spill_corrupt", key=h.key, path=path)
            self._entries.pop(h.key, None)
            self._close_locked(h)
            self._update_gauges_locked()
            if isinstance(e, DataCorruption):
                raise
            raise integrity.raise_corruption(
                "memgov.spill", f"{h.key}: unreadable spill file ({e})"
            ) from e
        try:
            os.unlink(path)
        except OSError:
            pass
        from . import persist
        persist.remove_manifest(path)
        h._disk_path = None

    def _get(self, h: SpillableHandle):
        import jax
        from ..utils import metrics

        reg = _registry()
        with self._lock:
            if h._closed:
                raise ValueError(f"catalog entry {h.key!r} is closed")
            if h._treedef is None:
                raise ValueError(
                    f"catalog entry {h.key!r} is accounting-only (no payload)"
                )
            self._seq += 1
            h._seq = self._seq  # LRU refresh
            if h._device is None:
                from ..utils import tracing

                t0 = time.perf_counter()
                # srjt-trace (ISSUE 12): re-materialization is the
                # other half of the spill cost a traced query pays
                with tracing.span(
                    "memgov.rematerialize", key=h.key, nbytes=h.nbytes,
                    tier=h.tier,
                ):
                    if h._disk_path is not None:
                        self._load_disk_locked(h)
                    import jax.numpy as jnp

                    h._device = [jnp.asarray(x) for x in h._host]
                h._host = None
                reg.counter("memgov.rematerialized").inc()
                reg.counter("memgov.rematerialized_bytes").inc(h.nbytes)
                reg.histogram("memgov.rematerialize_us").record(
                    (time.perf_counter() - t0) * 1e6
                )
                metrics.event(
                    "memgov.rematerialize", key=h.key, nbytes=h.nbytes
                )
                self._update_gauges_locked()
            return jax.tree_util.tree_unflatten(h._treedef, h._device)

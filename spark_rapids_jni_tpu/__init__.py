"""spark_rapids_jni_tpu — a TPU-native acceleration layer for Apache Spark.

Brand-new framework with the capability surface of spark-rapids-jni
(surveyed in SURVEY.md): JCUDF row<->column transcode, ANSI string casts,
Spark-bug-compatible DECIMAL128 arithmetic, DeltaLake Z-order, parquet
footer pruning, plus the cuDF-tier operator set (sort, filter, hash
aggregate, join, expression eval) — all re-designed for TPU: jax/XLA for
the compute path, ``shard_map`` + ICI collectives for exchange, and a C++
runtime for handles/host-buffers/JNI.

int64 lanes are required throughout (Spark longs, DECIMAL64, JCUDF row
offsets), so x64 mode is enabled at import, before any tracing happens.
"""

import os as _os

if (
    _os.environ.get("SRJT_LOCKDEP", "").lower() in ("1", "true", "yes")  # srjt-lint: allow-environ(bootstrap: lockdep must patch threading before ANY package module creates a lock; importing utils.knobs here would import the whole utils tree first)
    or _os.environ.get("SRJT_RACE", "").lower() in ("1", "true", "yes")  # srjt-lint: allow-environ(bootstrap: the race detector rides the lockdep shim and has the same patch-before-any-lock constraint)
):
    from .analysis import lockdep as _lockdep

    _lockdep.install()

import jax

jax.config.update("jax_enable_x64", True)

from . import columnar  # noqa: E402,F401
from .columnar import Column, DType, Table, TypeId  # noqa: E402,F401

__version__ = "0.1.0"

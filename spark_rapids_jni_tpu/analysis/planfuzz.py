"""``srjt-planfuzz``: random-plan differential fuzzer (ISSUE 15).

The third srjt-plancheck layer. The verifier (``plan/verifier.py``)
checks STRUCTURE — well-formed IR, discharged rewrite obligations,
consistent estimates — but a structural check cannot prove a rewrite
chain computes the right ANSWER on data. This tool closes that gap:

1. **Generate** small typed plans over the TPC-DS generator schemas
   (``models/tpcds.gen_store_wide``), seeded and fully deterministic —
   no wall clock, no ambient randomness (the workflow discipline): every
   plan is a pure function of ``(base seed, plan index)``. Templates
   cover the rewrite catalog: star joins + filters + projections +
   (grouped/global/ROLLUP) aggregates + HAVING + sort/limit, correlated
   scalar-aggregate filters (the q1 decorrelation family), INTERSECT/
   EXCEPT chains, EXISTS/NOT EXISTS, UNION ALL of fused count stars, and
   DISTINCT + semi/anti operator-tier chains.

2. **Execute** each plan through the real pipeline — rewrite fixpoint →
   compile → run — and against a DIRECT-PLAN-INTERPRETATION oracle: a
   node-by-node evaluator over plain Python rows that understands the
   sugar nodes natively (no rewriting), computes aggregates exactly
   (``fractions.Fraction`` sums/means — the engine's exact-FLOAT64
   contract), and speaks the same 3VL the runtime tier does. Results
   ALWAYS compare as multisets — ordering is deliberately out of scope
   here (the per-query oracle tests pin ORDER BY); the generator still
   places a total-order Sort under every Limit so the retained row SET
   is deterministic on both sides.

3. **Bisect** any mismatch to the first rewrite application in the
   chain: the rewrite engine's fire sequence is deterministic, so
   replaying it with ``rewrite(..., max_fires=k, prune=False)`` and
   re-interpreting the partially-rewritten plan (the oracle interprets
   sugar directly, so EVERY prefix is interpretable) localizes the first
   semantics-breaking fire — reported with its rule name and subtree
   fingerprints. A chain whose every prefix is oracle-clean blames the
   lowering instead.

Run ``python -m spark_rapids_jni_tpu.analysis.planfuzz``: seeds default
to ``SRJT_PLANCHECK_FUZZ_SEEDS``, plans-per-seed to
``SRJT_PLANCHECK_FUZZ_PLANS``; exit 1 on any mismatch (PLAN007) or
verifier violation, ``--format/--out`` through the shared lint emitters,
``--report`` appends per-seed JSON lines to the
``artifacts/plan_verify.jsonl`` contract.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from fractions import Fraction
from typing import Dict, List, Optional, Tuple

from .lint import write_findings
from .plancheck import catalog_of

__all__ = ["gen_plan", "interpret", "bisect_mismatch", "fuzz_one", "run",
           "main"]


# ---------------------------------------------------------------------------
# the oracle: direct plan interpretation over python rows
# ---------------------------------------------------------------------------

Rel = Tuple[List[str], List[tuple]]  # (column names, row tuples; None=NULL)


def rel_of_table(t) -> Rel:
    """Engine Table -> plain python rows (FLOAT64 bit lanes viewed back
    to floats, validity folded to None)."""
    import numpy as np

    from ..columnar.dtype import TypeId

    cols = []
    for name, c in zip(t.names, t.columns):
        arr = np.asarray(c.data)
        if c.dtype.id == TypeId.FLOAT64:
            vals = arr.view(np.float64).tolist()
        else:
            vals = arr.tolist()
        if c.validity is not None:
            m = np.asarray(c.validity)
            vals = [v if ok else None for v, ok in zip(vals, m)]
        cols.append(vals)
    return list(t.names), [tuple(r) for r in zip(*cols)] if cols else []


def canon(rows: List[tuple]) -> List[tuple]:
    """Multiset-canonical row order (None sorts first per column)."""
    return sorted(rows, key=lambda r: tuple(
        (v is None, 0 if v is None else v) for v in r))


def _ev(e, idx: Dict[str, int], row: tuple):
    """Evaluate one plan expression over one row, 3VL (None = NULL).
    Mirrors the runtime tier's semantics for everything the generator
    emits; unsupported expression kinds raise."""
    from ..plan import exprs as pex

    if isinstance(e, pex._PCol):
        return row[idx[e.name]]
    if isinstance(e, pex._PLit):
        v = e.value
        if v is None:
            return None
        if isinstance(v, bool):
            return bool(v)
        if isinstance(v, float):
            return float(v)
        return int(v)
    if isinstance(e, pex._PNot):
        a = _ev(e.a, idx, row)
        return None if a is None else (not a)
    if isinstance(e, pex._PIsNull):
        a = _ev(e.a, idx, row)
        return (a is None) if e.want_null else (a is not None)
    if isinstance(e, pex._PCast):
        a = _ev(e.a, idx, row)
        if a is None:
            return None
        return float(a) if e.d.is_floating else int(a)
    if isinstance(e, pex._PWhen):
        c = _ev(e.cond, idx, row)
        return _ev(e.then, idx, row) if c is True else _ev(e.other, idx, row)
    if isinstance(e, pex._PBin):
        a = _ev(e.a, idx, row)
        b = _ev(e.b, idx, row)
        op = e.op
        if op == "and":  # Kleene
            if a is False or b is False:
                return False
            if a is None or b is None:
                return None
            return True
        if op == "or":
            if a is True or b is True:
                return True
            if a is None or b is None:
                return None
            return False
        if a is None or b is None:
            return None
        if op == "add":
            return a + b
        if op == "sub":
            return a - b
        if op == "mul":
            return a * b
        if op == "div":
            return a / b
        if op == "mod":
            return a % b
        return {"eq": a == b, "ne": a != b, "lt": a < b, "le": a <= b,
                "gt": a > b, "ge": a >= b}[op]
    raise ValueError(f"oracle cannot evaluate {type(e).__name__}")


def _exact_sum(vals) -> float:
    return float(sum(Fraction(v) for v in vals))


def _agg_value(vals: list, group_size: int, how: str):
    """One aggregate over one group's non-null values — exact, matching
    the engine's materialization contract (counts int, everything else
    FLOAT64 value)."""
    if how == "count_all":
        return group_size
    if how == "count":
        return len(vals)
    if how == "nunique":
        return len(set(vals))
    if not vals:
        return None
    if how == "sum":
        return _exact_sum(vals)
    if how == "mean":
        return float(sum(Fraction(v) for v in vals) / len(vals))
    if how == "min":
        return float(min(vals))
    if how == "max":
        return float(max(vals))
    raise ValueError(f"oracle cannot compute aggregate {how!r}")


def _group(rows: List[tuple], key_idx: List[int]) -> Dict[tuple, List[tuple]]:
    out: Dict[tuple, List[tuple]] = {}
    for r in rows:
        out.setdefault(tuple(r[i] for i in key_idx), []).append(r)
    return out


def _agg_rows(rows, names, keys, aggs) -> Rel:
    out_names = list(keys) + [a.name for a in aggs]
    key_idx = [names.index(k) for k in keys]
    src_idx = {a.name: (None if a.source is None else names.index(a.source))
               for a in aggs}
    if not keys:
        if not rows and not aggs:
            return out_names, []
        if not rows:
            # SQL global aggregate over empty input: ONE row, counts 0
            row = tuple(_agg_value([], 0, a.how) for a in aggs)
            return out_names, [row]
        groups = {(): rows}
    else:
        groups = _group(rows, key_idx)
    out = []
    for key, grows in groups.items():
        vals_of = {}
        for a in aggs:
            si = src_idx[a.name]
            vals_of[a.name] = ([] if si is None
                               else [r[si] for r in grows if r[si] is not None])
        out.append(tuple(key) + tuple(
            _agg_value(vals_of[a.name], len(grows), a.how) for a in aggs))
    return out_names, out


def interpret(node, rels: Dict[str, Rel], _memo=None) -> Rel:
    """Direct plan interpretation: the differential oracle. Handles the
    sugar nodes NATIVELY (per their documented semantics), so any prefix
    of the rewrite chain — including the unrewritten plan — is
    interpretable; node sharing is memoized like the compiler does."""
    from ..plan import nodes as pn

    memo = {} if _memo is None else _memo
    key = id(node)
    if key in memo:
        return memo[key]
    out = _interp(node, rels, memo)
    memo[key] = out
    return out


def _interp(node, rels, memo) -> Rel:
    from ..plan import exprs as pex
    from ..plan import nodes as pn

    if isinstance(node, pn.Scan):
        names, rows = rels[node.table]
        if node.columns is None:
            return list(names), list(rows)
        sel = [names.index(c) for c in node.columns]
        return list(node.columns), [tuple(r[i] for i in sel) for r in rows]

    if isinstance(node, (pn.Filter, pn.Having)):
        names, rows = interpret(node.input, rels, memo)
        idx = {n: i for i, n in enumerate(names)}
        return names, [r for r in rows
                       if _ev(node.predicate, idx, r) is True]

    if isinstance(node, pn.Project):
        names, rows = interpret(node.input, rels, memo)
        idx = {n: i for i, n in enumerate(names)}
        out_names = [n for n, _ in node.exprs]
        return out_names, [tuple(_ev(e, idx, r) for _, e in node.exprs)
                           for r in rows]

    if isinstance(node, pn.Join):
        lnames, lrows = interpret(node.left, rels, memo)
        rnames, rrows = interpret(node.right, rels, memo)
        lk = [lnames.index(l) for l, _ in node.on]
        rk = [rnames.index(r) for _, r in node.on]
        rkeys = {r for _, r in node.on}
        keep_r = [i for i, n in enumerate(rnames) if n not in rkeys]
        index: Dict[tuple, list] = {}
        for r in rrows:
            k = tuple(r[i] for i in rk)
            if any(v is None for v in k):
                continue  # NULL keys never match
            index.setdefault(k, []).append(r)
        out_names = list(lnames) + [rnames[i] for i in keep_r]
        out = []
        if node.how in ("semi", "anti"):
            want = node.how == "semi"
            return list(lnames), [
                r for r in lrows
                if (tuple(r[i] for i in lk) in index) == want
            ]
        for lr in lrows:
            k = tuple(lr[i] for i in lk)
            matches = index.get(k, []) if not any(v is None for v in k) else []
            for rr in matches:
                out.append(lr + tuple(rr[i] for i in keep_r))
            if not matches and node.how in ("left", "full"):
                out.append(lr + tuple(None for _ in keep_r))
        if node.how == "full":
            matched = {id(rr) for m in index.values() for rr in m
                       if any(tuple(lr[i] for i in lk) ==
                              tuple(rr[i] for i in rk) for lr in lrows)}
            for rr in rrows:
                if id(rr) not in matched:
                    row = [None] * len(lnames)
                    for (l, _), i in zip(node.on, rk):
                        row[lnames.index(l)] = rr[i]
                    out.append(tuple(row) + tuple(rr[i] for i in keep_r))
        return out_names, out

    if isinstance(node, pn.Aggregate):
        names, rows = interpret(node.input, rels, memo)
        if node.grouping_sets is not None:
            out_names = list(node.keys) + [a.name for a in node.aggs]
            out: List[tuple] = []
            for gs in node.grouping_sets:
                _, grows = _agg_rows(rows, names, gs, node.aggs)
                # re-order onto the full key list, rolled keys NULL
                for r in grows:
                    kmap = dict(zip(gs, r[:len(gs)]))
                    out.append(tuple(kmap.get(k) for k in node.keys)
                               + r[len(gs):])
            return out_names, out
        return _agg_rows(rows, names, node.keys, node.aggs)

    if isinstance(node, pn.Sort):
        names, rows = interpret(node.input, rels, memo)
        rows = list(rows)
        for col, asc in reversed(node.keys):
            i = names.index(col)
            rows.sort(key=lambda r: r[i], reverse=not asc)
        return names, rows

    if isinstance(node, pn.Limit):
        names, rows = interpret(node.input, rels, memo)
        return names, rows[:node.n]

    if isinstance(node, pn.UnionAll):
        first_names, out = interpret(node.branches[0], rels, memo)
        out = list(out)
        for b in node.branches[1:]:
            names, rows = interpret(b, rels, memo)
            if names != first_names:
                raise ValueError("oracle: union branch names differ")
            out += rows
        return first_names, out

    # -- sugar nodes, interpreted natively ---------------------------------

    if isinstance(node, pn.SetOp):
        lnames, lrows = interpret(node.left, rels, memo)
        _, rrows = interpret(node.right, rels, memo)
        rset = set(rrows)
        seen = set()
        out = []
        for r in lrows:  # set semantics: dedup the left side
            if r in seen:
                continue
            seen.add(r)
            if (r in rset) == (node.kind == "intersect"):
                out.append(r)
        return lnames, out

    if isinstance(node, pn.Exists):
        names, rows = interpret(node.input, rels, memo)
        snames, srows = interpret(node.sub, rels, memo)
        li = [names.index(l) for l, _ in node.on]
        si = [snames.index(r) for _, r in node.on]
        sset = {tuple(r[i] for i in si) for r in srows}
        want = not node.negated
        return names, [r for r in rows
                       if (tuple(r[i] for i in li) in sset) == want]

    if isinstance(node, pn.CorrelatedAggFilter):
        names, rows = interpret(node.input, rels, memo)
        snames, srows = interpret(node.sub, rels, memo)
        pk, bk = node.on
        groups = _group(srows, [snames.index(bk)])
        a = node.agg
        si = None if a.source is None else snames.index(a.source)
        aggval = {}
        for k, grows in groups.items():
            vals = ([] if si is None
                    else [r[si] for r in grows if r[si] is not None])
            aggval[k[0]] = _agg_value(vals, len(grows), a.how)
        out_names = list(names) + [a.name]
        idx = {n: i for i, n in enumerate(out_names)}
        pi = names.index(pk)
        out = []
        for r in rows:
            if r[pi] not in aggval:
                continue  # empty subquery group: the inner join drops it
            ext = r + (aggval[r[pi]],)
            if _ev(node.predicate, idx, ext) is True:
                out.append(ext)
        return out_names, out

    raise ValueError(f"oracle cannot interpret {type(node).__name__}")


# ---------------------------------------------------------------------------
# the generator: seeded typed plans over the gen_store_wide star
# ---------------------------------------------------------------------------

# (table, fact FK, dim PK, filterable int columns with [lo, hi) domains)
_DIMS = (
    ("date_dim", "ss_sold_date_sk", "d_date_sk",
     (("d_year", 1998, 2003), ("d_moy", 1, 13), ("d_dow", 0, 7))),
    ("store", "ss_store_sk", "s_store_sk", (("s_state", 0, 10),)),
    ("household_demographics", "ss_hdemo_sk", "hd_demo_sk",
     (("hd_dep_count", 0, 10), ("hd_vehicle_count", 0, 5))),
    ("customer_demographics", "ss_cdemo_sk", "cd_demo_sk",
     (("cd_gender", 0, 2), ("cd_marital_status", 0, 5))),
    ("time_dim", "ss_sold_time_sk", "t_time_sk", (("t_hour", 0, 24),)),
)
_MEASURES = ("ss_quantity", "ss_list_price", "ss_coupon_amt",
             "ss_sales_price", "ss_ext_sales_price")
_FACT_KEYS = ("ss_store_sk", "ss_hdemo_sk", "ss_cdemo_sk")
_AGG_HOWS = ("sum", "mean", "min", "max", "count")


def _int_pred(rng, col: str, lo: int, hi: int):
    from ..plan import pcol, plit

    kind = rng.random()
    if kind < 0.3:
        a = int(rng.integers(lo, hi))
        b = int(rng.integers(lo, hi))
        return (pcol(col) == plit(a)) | (pcol(col) == plit(b))
    if kind < 0.55:
        return pcol(col) == plit(int(rng.integers(lo, hi)))
    if kind < 0.8:
        return pcol(col) >= plit(int(rng.integers(lo, hi)))
    return pcol(col) <= plit(int(rng.integers(lo, hi)))


def _dim_pred(rng, cols):
    col, lo, hi = cols[int(rng.integers(0, len(cols)))]
    return _int_pred(rng, col, lo, hi)


def _fact_pred(rng):
    from ..plan import pcol, plit

    if rng.random() < 0.5:
        return _int_pred(rng, "ss_quantity", 1, 100)
    lo = round(float(rng.uniform(1, 150)), 1)
    return pcol("ss_list_price") >= plit(lo)


def _star_chain(rng, max_dims: int = 3):
    """Fact scan + 1..max_dims dim joins (each optionally filtered) +
    optional fact filter. Returns (node, payload column names)."""
    from ..plan import Filter, Join, Scan

    x = Scan("store_sales")
    ndims = int(rng.integers(1, max_dims + 1))
    picks = sorted(int(i) for i in
                   rng.choice(len(_DIMS), size=ndims, replace=False))
    payloads: List[str] = []
    for di in picks:
        tbl, fk, pk, cols = _DIMS[di]
        right = Scan(tbl)
        if rng.random() < 0.8:
            right = Filter(right, _dim_pred(rng, cols))
        x = Join(x, right, on=((fk, pk),),
                 bounded=bool(rng.random() < 0.5))
        payloads += [c for c, _, _ in cols]
    if rng.random() < 0.5:
        x = Filter(x, _fact_pred(rng))
        if rng.random() < 0.4:  # stacked filters: merge_filters fires
            x = Filter(x, _fact_pred(rng))
    return x, payloads


def _t_star(rng):
    from ..plan import AggSpec, Aggregate, Having, Limit, Project, Sort
    from ..plan import pcol, plit, rollup

    x, payloads = _star_chain(rng)
    measures = list(_MEASURES)
    if rng.random() < 0.25:
        # computed measure: passthrough everything + one derived column
        m = str(rng.choice(("ss_list_price", "ss_sales_price")))
        factor = round(float(rng.uniform(0.5, 2.0)), 2)
        exprs = [(c, pcol(c)) for c in
                 ("ss_sold_date_sk", "ss_item_sk", "ss_cdemo_sk",
                  "ss_hdemo_sk", "ss_store_sk", "ss_sold_time_sk",
                  "ss_quantity", "ss_list_price", "ss_coupon_amt",
                  "ss_sales_price", "ss_ext_sales_price")]
        exprs += [(c, pcol(c)) for c in payloads]
        exprs.append(("m0", pcol(m) * plit(factor)))
        x = Project(x, tuple(exprs))
        measures.append("m0")
        if rng.random() < 0.5:
            # filter over a passthrough column above the project:
            # push_filter_through_project fires
            from ..plan import Filter

            x = Filter(x, _fact_pred(rng))
    nkeys = int(rng.integers(0, 3))
    keypool = list(_FACT_KEYS) + payloads
    keys: tuple = ()
    if nkeys:
        keys = tuple(str(k) for k in
                     rng.choice(keypool, size=nkeys, replace=False))
    naggs = int(rng.integers(1, 4))
    picks = sorted(int(i) for i in
                   rng.choice(len(measures), size=min(naggs, len(measures)),
                              replace=False))
    aggs = [AggSpec(measures[mi], str(rng.choice(_AGG_HOWS)), f"a{j}")
            for j, mi in enumerate(picks)]
    if rng.random() < 0.3:
        aggs.append(AggSpec(None, "count_all", "cnt"))
    gs = rollup(*keys) if (keys and rng.random() < 0.25) else None
    out = Aggregate(x, keys=keys, aggs=tuple(aggs), grouping_sets=gs)
    if gs is None:
        if rng.random() < 0.35:
            out = Having(out, pcol(aggs[0].name)
                         > plit(round(float(rng.uniform(0, 40)), 1)))
        if rng.random() < 0.4:
            out_cols = list(keys) + [a.name for a in aggs]
            out = Limit(
                Sort(out, tuple((c, bool(rng.random() < 0.7))
                                for c in out_cols)),
                int(rng.integers(1, 25)))
    return out


def _t_corr(rng):
    from ..plan import AggSpec, Aggregate, CorrelatedAggFilter, pcol, plit

    x, _ = _star_chain(rng, max_dims=1)
    k1, k2 = (str(k) for k in rng.choice(_FACT_KEYS, size=2, replace=False))
    m = str(rng.choice(_MEASURES))
    ctr = Aggregate(x, keys=(k1, k2), aggs=(AggSpec(m, "sum", "rev"),))
    factor = float(rng.choice((0.5, 0.8, 1.0, 1.2)))
    caf = CorrelatedAggFilter(
        ctr, ctr, on=(k2, k2), agg=AggSpec("rev", "mean", "ave"),
        predicate=pcol("rev") > plit(factor) * pcol("ave"))
    return Aggregate(caf, keys=(k2,),
                     aggs=(AggSpec(None, "count_all", "cnt"),))


def _t_setop(rng):
    from ..plan import (AggSpec, Aggregate, Filter, Join, Project, Scan,
                        SetOp, pcol)

    def branch():
        x = Join(
            Scan("store_sales"),
            Filter(Scan("date_dim"), _dim_pred(rng, _DIMS[0][3])),
            on=(("ss_sold_date_sk", "d_date_sk"),), bounded=True)
        return Project(x, (("k", pcol("ss_customer_sk")),))

    kind = str(rng.choice(("intersect", "except")))
    chain = SetOp(branch(), branch(), kind)
    if rng.random() < 0.4:
        chain = SetOp(chain, branch(), str(rng.choice(("intersect",
                                                       "except"))))
    return Aggregate(chain, keys=(),
                     aggs=(AggSpec(None, "count_all", "cnt"),))


def _t_exists(rng):
    from ..plan import AggSpec, Aggregate, Exists, Filter, Join, Scan

    sub = Join(Scan("store_sales"),
               Filter(Scan("date_dim"), _dim_pred(rng, _DIMS[0][3])),
               on=(("ss_sold_date_sk", "d_date_sk"),), bounded=True)
    x = Exists(Scan("customer"), sub,
               on=(("c_customer_sk", "ss_customer_sk"),),
               negated=bool(rng.random() < 0.5))
    keys = ("c_current_addr_sk",) if rng.random() < 0.4 else ()
    return Aggregate(x, keys=keys,
                     aggs=(AggSpec(None, "count_all", "cnt"),))


def _t_union(rng):
    from ..plan import (AggSpec, Aggregate, Filter, Join, Project, Scan,
                        UnionAll, pcol, plit)
    import numpy as np

    branches = []
    for b in range(int(rng.integers(2, 4))):
        x = Join(Scan("store_sales"),
                 Filter(Scan("time_dim"), _int_pred(rng, "t_hour", 0, 24)),
                 on=(("ss_sold_time_sk", "t_time_sk"),), bounded=True)
        agg = Aggregate(x, keys=(),
                        aggs=(AggSpec(None, "count_all", "cnt"),))
        branches.append(Project(agg, (
            ("band", plit(np.int32(b))), ("cnt", pcol("cnt")))))
    out = UnionAll(tuple(branches))
    if rng.random() < 0.5:
        # filter above the union: push_filter_through_union fires
        out = Filter(out, pcol("cnt") >= plit(int(rng.integers(0, 12))))
    return out


def _t_optier(rng):
    from ..plan import Aggregate, Filter, Join, Limit, Scan, Sort

    keys = ("ss_store_sk", "ss_hdemo_sk") if rng.random() < 0.5 \
        else ("ss_store_sk",)
    dedup = Aggregate(Scan("store_sales"), keys=keys, aggs=())
    j = Join(dedup, Filter(Scan("store"), _dim_pred(rng, _DIMS[1][3])),
             on=(("ss_store_sk", "s_store_sk"),),
             how=str(rng.choice(("semi", "anti"))))
    return Limit(Sort(j, tuple((k, True) for k in keys)),
                 int(rng.integers(1, 30)))


def _t_multijoin(rng):
    """srjt-cbo (ISSUE 19): 3-5 dim star joined in the generator's
    (arbitrary) order, with strategy hints drawn from the full
    {None, True, False} tri-state, optionally extended by a fact ->
    customer [-> customer_address] chain hop. This is every CBO rule's
    habitat: ``cbo_reorder_joins`` (multi-dim inner star),
    ``cbo_join_strategy`` (``bounded=None`` abstentions), and
    ``cbo_build_side`` (the backwards-authored PK->FK variant probes
    from the unique-keyed side into a 4x bigger build, so the commute
    fires)."""
    from ..plan import AggSpec, Aggregate, Filter, Join, Scan

    if rng.random() < 0.3:
        # backwards-authored: customer_address (unique ca_address_sk,
        # <= 500 rows) probes into customer (2000 rows) — exactly the
        # shape cbo_build_side exists to flip
        y = Scan("customer_address")
        if rng.random() < 0.6:
            y = Filter(y, _int_pred(rng, "ca_zip5", 0, 300))
        y = Join(y, Scan("customer"),
                 on=(("ca_address_sk", "c_current_addr_sk"),))
        how = str(rng.choice(_AGG_HOWS[:2]))  # int measure: sum/mean
        return Aggregate(y, keys=("ca_zip5",),
                         aggs=(AggSpec("c_customer_id", how, "a0"),
                               AggSpec(None, "count_all", "cnt")))
    x = Scan("store_sales")
    ndims = int(rng.integers(3, 6))
    picks = [int(i) for i in
             rng.choice(len(_DIMS), size=min(ndims, len(_DIMS)),
                        replace=False)]
    payloads: List[str] = []
    for di in picks:
        tbl, fk, pk, cols = _DIMS[di]
        right = Scan(tbl)
        if rng.random() < 0.6:
            right = Filter(right, _dim_pred(rng, cols))
        hint = (None, True, False)[int(rng.integers(0, 3))]
        x = Join(x, right, on=((fk, pk),), bounded=hint)
        payloads += [c for c, _, _ in cols]
    if rng.random() < 0.5:
        x = Join(x, Scan("customer"),
                 on=(("ss_customer_sk", "c_customer_sk"),))
        payloads += ["c_current_addr_sk", "c_customer_id"]
        if rng.random() < 0.5:
            # snowflake hop: probe key is customer payload, not a fact
            # column — the reorder rule must leave this chain alone
            x = Join(x, Scan("customer_address"),
                     on=(("c_current_addr_sk", "ca_address_sk"),))
            payloads += ["ca_zip5"]
    nkeys = int(rng.integers(1, 3))
    keypool = list(_FACT_KEYS) + payloads
    keys = tuple(str(k) for k in
                 rng.choice(keypool, size=nkeys, replace=False))
    m = str(rng.choice(_MEASURES))
    aggs = (AggSpec(m, str(rng.choice(_AGG_HOWS)), "a0"),
            AggSpec(None, "count_all", "cnt"))
    return Aggregate(x, keys=keys, aggs=aggs)


_TEMPLATES = (
    ("star", _t_star, 0.30),
    ("corr", _t_corr, 0.12),
    ("setop", _t_setop, 0.12),
    ("exists", _t_exists, 0.12),
    ("union", _t_union, 0.14),
    ("optier", _t_optier, 0.10),
    ("multijoin", _t_multijoin, 0.10),
)


def gen_plan(rng) -> Tuple[object, str]:
    """One seeded plan. Deterministic in the generator state — the
    fuzzer's whole chain (generate -> rewrite -> compile -> oracle ->
    bisect) is a pure function of the seed."""
    r = rng.random()
    acc = 0.0
    for name, fn, w in _TEMPLATES:
        acc += w
        if r < acc:
            return fn(rng), name
    name, fn, _ = _TEMPLATES[-1]
    return fn(rng), name


# ---------------------------------------------------------------------------
# differential run + bisection
# ---------------------------------------------------------------------------


def bisect_mismatch(ir, rels, catalog, rules=None) -> dict:
    """Localize a compiler-vs-oracle mismatch to the FIRST rewrite
    application that changes the plan's interpreted result. Replays the
    engine's deterministic fire sequence prefix by prefix (the oracle
    interprets sugar natively, so every prefix is interpretable); a
    chain whose prefixes are all clean blames the lowering."""
    from ..plan import rewrites as rw

    base_names, base_rows = interpret(ir, rels)
    base = (base_names, canon(base_rows))
    full = rw.rewrite(ir, catalog, rules=rules, prune=False)
    for k in range(1, len(full.obligations) + 1):
        pk = rw.rewrite(ir, catalog, rules=rules, max_fires=k, prune=False)
        names, rows = interpret(pk.plan, rels)
        if (names, canon(rows)) != base:
            ob = pk.obligations[-1]
            return {"first_bad_fire": k, "rule": ob.rule,
                    "before_fp": ob.before_fp, "after_fp": ob.after_fp}
    pruned = rw.rewrite(ir, catalog, rules=rules, prune=True)
    names, rows = interpret(pruned.plan, rels)
    if (names, canon(rows)) != base:
        return {"first_bad_fire": len(full.obligations) + 1,
                "rule": "prune_columns"}
    return {"first_bad_fire": None, "rule": "lowering",
            "detail": "every rewrite prefix is oracle-clean; the "
                      "divergence is in compile/execute"}


def fuzz_one(plan_seed: int, tables, rels, catalog,
             where: str) -> Tuple[list, dict]:
    """Generate + verify + differentially execute ONE plan. Returns
    (findings, {template, rewrites, mismatch})."""
    import numpy as np

    from .. import plan as P

    rng = np.random.default_rng(plan_seed)
    ir, template = gen_plan(rng)
    info = {"template": template, "rewrites": {}, "mismatch": False}
    findings = P.verify_plan(ir, catalog, desugared=False, where=where)
    if findings:
        return findings, info
    cp = P.compile_ir(ir, tables, name=where.replace(":", "_"))
    findings += P.verify_plan(cp.optimized, catalog, desugared=True,
                              where=where)
    findings += P.verify_obligations(cp.obligations, catalog, where=where)
    findings += P.verify_estimates(cp, where=where)
    info["rewrites"] = cp.rewrites_fired
    got_names, got_rows = rel_of_table(cp())
    want_names, want_rows = interpret(ir, rels)
    if got_names != want_names or canon(got_rows) != canon(want_rows):
        from ..plan.verifier import PlanViolation

        info["mismatch"] = True
        blame = bisect_mismatch(ir, rels, catalog)
        findings.append(PlanViolation(
            where, "PLAN007",
            f"compiler-vs-oracle mismatch on a generated {template!r} plan "
            f"(engine {len(got_rows)} rows / columns {got_names} vs oracle "
            f"{len(want_rows)} rows / columns {want_names}); bisected to "
            f"{blame}"))
    return findings, info


def run(seeds: List[int], plans: int, rows: int = 160,
        report: Optional[str] = None) -> Tuple[list, List[dict]]:
    from ..models.tpcds import gen_store_wide

    tables = gen_store_wide(rows, seed=97)
    rels = {t: rel_of_table(tbl) for t, tbl in tables.items()}
    catalog = catalog_of(tables)
    findings: list = []
    records: List[dict] = []
    for seed in seeds:
        mismatches = violations = 0
        fired: Dict[str, int] = {}
        templates: Dict[str, int] = {}
        for i in range(plans):
            fs, info = fuzz_one(seed * 100003 + i, tables, rels, catalog,
                                where=f"fuzz:{seed}/{i}")
            findings += fs
            mismatches += int(info["mismatch"])
            violations += sum(1 for v in fs if v.rule != "PLAN007")
            templates[info["template"]] = templates.get(info["template"], 0) + 1
            for r, n in info["rewrites"].items():
                fired[r] = fired.get(r, 0) + n
        records.append({"kind": "fuzz", "seed": seed, "plans": plans,
                        "rows": rows, "mismatches": mismatches,
                        "violations": violations, "rewrites": fired,
                        "templates": templates})
    if report:
        d = os.path.dirname(report)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(report, "a", encoding="utf-8") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
    return findings, records


def main(argv=None) -> int:
    from ..utils import knobs

    ap = argparse.ArgumentParser(
        prog="python -m spark_rapids_jni_tpu.analysis.planfuzz",
        description="srjt-planfuzz: seeded random-plan differential "
                    "fuzzer — rewrite+compile+execute vs direct plan "
                    "interpretation, with first-bad-rewrite bisection "
                    "(ISSUE 15)")
    ap.add_argument("--seed", type=int, default=None,
                    help="single base seed (overrides --seeds / the knob)")
    ap.add_argument("--seeds", default=knobs.get_str("SRJT_PLANCHECK_FUZZ_SEEDS"),
                    help="comma-separated base seeds")
    ap.add_argument("--plans", type=int,
                    default=knobs.get_int("SRJT_PLANCHECK_FUZZ_PLANS"),
                    help="plans generated per seed")
    ap.add_argument("--rows", type=int, default=160,
                    help="fact rows in the bound generator tables")
    ap.add_argument("--report", default=None,
                    help="append one JSON line per seed to this path "
                    "(the artifacts/plan_verify.jsonl contract)")
    ap.add_argument("--format", default="text",
                    choices=("text", "json", "sarif"))
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    seeds = ([args.seed] if args.seed is not None
             else [int(s) for s in str(args.seeds).split(",") if s.strip()])
    findings, records = run(seeds, args.plans, rows=args.rows,
                            report=args.report)
    total = sum(r["plans"] for r in records)
    mism = sum(r["mismatches"] for r in records)
    print(f"srjt-planfuzz: {total} plans over seeds {seeds}: "
          f"{mism} mismatch(es), "
          f"{sum(r['violations'] for r in records)} violation(s)",
          file=sys.stderr)
    return write_findings(findings, args.format, args.out, "srjt-planfuzz")


if __name__ == "__main__":
    sys.exit(main())

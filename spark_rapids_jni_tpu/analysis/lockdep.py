"""Runtime lockdep + the srjt-race dynamic detector for the
concurrent substrate (ISSUE 7 layer 2 + ISSUE 11 layer 2; the Linux
kernel lockdep idea plus FastTrack-shaped vector-clock race
detection, scoped to this package's locks and tracked state).

Armed with ``SRJT_LOCKDEP=1`` (or ``SRJT_RACE=1``, which implies it),
the package ``__init__`` calls ``install()`` BEFORE any other package
import, so every ``threading.Lock/RLock/Condition`` (and, since
ISSUE 11, ``Event/Semaphore/BoundedSemaphore/Barrier`` plus
``Thread.start/join``) created by package (or repo test) code
afterwards is a tracked shim. With ``SRJT_RACE=1`` each thread also
carries a vector clock advanced on every sync edge, and state
registered via ``track(obj, name)`` has its accesses checked for
happens-before ordering — two accesses to one location, at least one
a write, with unordered clocks, are reported as ``race_pairs`` with
both stacks and fail the same merge gate as cycles (ANALYSIS.md has
the full contract). Per thread, the shim keeps the stack
of currently-held tracked locks; every successful-or-attempted
acquisition of lock B while holding lock A records the directed edge
A -> B (per lock INSTANCE — two specific locks taken in both orders is
a real potential deadlock, never a same-class false positive) with one
sample stack per edge. ``time.sleep`` is wrapped too: sleeping while
holding any tracked lock is recorded as a blocking-while-locked event
(the latency-bomb the deadline tier exists to prevent). Sockets guarded
by a per-connection io_lock are the DESIGN on the sidecar data path, so
recv is deliberately not instrumented — the lint layer (SRJT006)
polices blocking calls statically instead.

At process exit each armed process writes
``<SRJT_LOCKDEP_DIR>/lockdep_<pid>.json`` — lock sites, the order
graph, cycles (strongly connected components), self-deadlocks
(re-acquiring a held non-reentrant lock), and blocking events. Armed
for the full tier-1 + chaos suites in ci/premerge.sh, every existing
concurrency test doubles as a lockdep probe; the merge gate::

    python -m spark_rapids_jni_tpu.analysis.lockdep \
        --merge artifacts/lockdep --out artifacts/lockdep_report.json

fails on any cycle or self-deadlock across every report.

Bootstrap constraint: this module reads its env knobs directly —
importing utils/knobs.py here would drag in the whole utils tree
before the shim is installed, leaving every utils lock untracked. The
knob names stay declared in the registry like any other.

Known limits (documented, not bugs): locks created before ``install()``
(or by code that did ``from threading import Lock`` at import time) are
untracked; a lock acquired in one thread and released in another leaves
a stale held entry on the acquirer. Neither shape exists in this
package.
"""

from __future__ import annotations

import atexit
import contextlib
import json
import os
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

__all__ = [
    "install",
    "uninstall",
    "is_installed",
    "isolated_state",
    "report",
    "write_report",
    "flush_report",
    "find_cycles",
    "merge_reports",
    "main",
    "track",
    "race_armed",
    "enable_race_detection",
    "disable_race_detection",
]

# originals captured at import, before any patching
_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock
_ORIG_CONDITION = threading.Condition
_ORIG_SLEEP = time.sleep
_ORIG_EVENT = threading.Event
_ORIG_SEMAPHORE = threading.Semaphore
_ORIG_BOUNDED_SEMAPHORE = threading.BoundedSemaphore
_ORIG_BARRIER = threading.Barrier
_ORIG_THREAD_START = threading.Thread.start
_ORIG_THREAD_JOIN = threading.Thread.join

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_ROOT = os.path.dirname(_PKG_ROOT)

_MAX_BLOCKING_EVENTS = 200  # sample cap; the total is counted exactly
# a blocking acquisition that stalls this long while other locks are
# held persists the report EARLY: a real deadlock never reaches exit's
# atexit writer (CI SIGKILLs it), but the stalled report carries the
# inverted edges the postmortem needs
_STALL_REPORT_S = 60.0


class _State:
    """One lockdep universe: the order graph + event tallies + the
    race detector's access cells (ISSUE 11 layer 2). Swappable via
    ``isolated_state()`` so the deliberate-inversion unit test does
    not poison the session report the CI gate asserts on."""

    def __init__(self):
        self.mu = _ORIG_LOCK()
        self.locks: Dict[int, dict] = {}  # key -> {"site", "kind"}
        self.edges: Dict[Tuple[int, int], dict] = {}
        self.blocking: List[dict] = []
        self.blocking_total = 0
        self.self_deadlocks: List[dict] = []
        # race detection: per tracked location, the last write and the
        # reads since it — each stamped (tid, vc copy, stack, thread)
        self.cells: Dict[tuple, dict] = {}
        self.races: List[dict] = []  # sample cap; total counted exactly
        self.race_total = 0
        self.race_seen: set = set()
        self.tracked_objects = 0


_state = _State()
_session_state = _state  # the universe the CI gate asserts on
_tls = threading.local()
_installed = False
_seq_lock = _ORIG_LOCK()
_seq = 0


def _next_key() -> int:
    global _seq
    with _seq_lock:
        _seq += 1
        return _seq


def _creation_site(depth: int) -> Optional[str]:
    try:
        f = sys._getframe(depth)
    except ValueError:
        return None
    fn = f.f_code.co_filename
    # package files are ALWAYS tracked — including wheel installs where
    # the package (and so _REPO_ROOT) lives inside site-packages; the
    # site-packages rejection only filters third-party code picked up
    # via the repo-root prefix in dev checkouts (tests/, benchmarks/)
    if not fn.startswith(_PKG_ROOT + os.sep):
        if not fn.startswith(_REPO_ROOT) or "site-packages" in fn:
            return None
    if os.sep + "analysis" + os.sep in fn:
        return None  # never track our own machinery
    return f"{os.path.relpath(fn, _REPO_ROOT)}:{f.f_lineno}"


def _short_stack() -> str:
    # drop the two lockdep-internal frames at the tail
    return "".join(traceback.format_stack(limit=10)[:-2])


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


# ---------------------------------------------------------------------------
# vector clocks (srjt-race layer 2, ISSUE 11)
#
# Armed with SRJT_RACE=1 (riding the SRJT_LOCKDEP shim), every thread
# carries a vector clock {tid: counter}. Happens-before edges advance
# it at every sync operation the shim already sees — lock release ->
# acquire, Condition wait, Thread.start/join, Event.set/wait,
# Semaphore release -> acquire, Barrier cycles — so detector cost is
# proportional to SYNC-OP count, never to data volume. Two accesses to
# the same tracked location (see track()), at least one a write, whose
# clocks are UNORDERED, are a data race: no lock, event, join, or
# barrier ordered them, so the scheduler is free to interleave the
# bytes. Both access stacks are reported.
# ---------------------------------------------------------------------------

_race_armed = False
_MAX_RACE_SAMPLES = 50
_hb_guard = _ORIG_LOCK()  # guards _srjt_hb dicts on events/barriers/sems


def _cur_vc() -> Tuple[dict, int]:
    """This thread's (vector clock, tid); the clock is mutated only by
    its own thread. Threads started through the shim get seeded with
    their parent's clock by the wrapped run() (_tracked_thread_start);
    anything else starts fresh. Deliberately NEVER calls
    threading.current_thread(): that constructor path itself touches a
    (tracked) Event and would recurse."""
    vc = getattr(_tls, "vc", None)
    if vc is None:
        tid = _next_key()
        _tls.tid = tid
        vc = _tls.vc = {tid: 1}
    return vc, _tls.tid


def _join_into(dst: dict, src: dict) -> None:
    for k, v in src.items():
        if dst.get(k, 0) < v:
            dst[k] = v


def _publish_hb(obj) -> None:
    """release/set/arrive edge: merge this thread's clock into the sync
    object's clock, then tick own component (later local events are
    strictly after the published point)."""
    vc, tid = _cur_vc()
    with _hb_guard:
        hb = getattr(obj, "_srjt_hb", None)
        if hb is None:
            try:
                obj._srjt_hb = hb = {}
            except AttributeError:
                return  # slotted foreign object: no HB channel
        _join_into(hb, vc)
    vc[tid] = vc.get(tid, 0) + 1


def _absorb_hb(obj) -> None:
    """acquire/wait/depart edge: adopt everything the sync object has
    accumulated from earlier publishers."""
    hb = getattr(obj, "_srjt_hb", None)
    if hb:
        vc, _ = _cur_vc()
        with _hb_guard:
            _join_into(vc, hb)


def _access_stack() -> str:
    # drop the two detector-internal frames at the tail
    return "".join(traceback.format_stack(limit=8)[:-2])


def _ordered_before(prev, vc: dict) -> bool:
    """Did the recorded access ``prev`` happen-before the current clock
    ``vc``? True iff prev's own-component timestamp is included in vc —
    the standard vector-clock ordering test."""
    ptid, pvc = prev[0], prev[1]
    return pvc.get(ptid, 0) <= vc.get(ptid, 0)


def _report_race(st: _State, loc: tuple, prev, cur, kind: str) -> None:
    st.race_total += 1
    key = (loc, kind)
    if key in st.race_seen:
        return
    st.race_seen.add(key)
    if len(st.races) < _MAX_RACE_SAMPLES:
        st.races.append({
            "location": f"{loc[0]}[{loc[1]!r}]",
            "kind": kind,
            "a": {"thread": prev[3], "stack": prev[2]},
            "b": {"thread": cur[3], "stack": cur[2]},
        })


def _record_access(loc: tuple, is_write: bool) -> None:
    """One access to a tracked location: check happens-before against
    the cell's last write (and, for writes, the reads since it), then
    become part of the cell. FastTrack-shaped: last-write + read-set
    per location, so memory is bounded by live locations, not access
    count."""
    if not _race_armed:
        return
    vc, tid = _cur_vc()
    cur = (tid, dict(vc), _access_stack(), threading.current_thread().name)
    st = _state
    with st.mu:
        cell = st.cells.get(loc)
        if cell is None:
            cell = st.cells[loc] = {"w": None, "r": {}}
        w = cell["w"]
        if w is not None and w[0] != tid and not _ordered_before(w, vc):
            _report_race(st, loc, w, cur,
                         "write-write" if is_write else "write-read")
        if is_write:
            for rtid, r in cell["r"].items():
                if rtid != tid and not _ordered_before(r, vc):
                    _report_race(st, loc, r, cur, "read-write")
            cell["w"] = cur
            cell["r"].clear()
        else:
            # bound the read set per cell: read-mostly locations (a
            # metric created once, read by every thread forever) must
            # not accumulate one stamped record per thread EVER — the
            # oldest reader's record goes; losing it can only miss a
            # race against that one stale read, never invent one
            if tid not in cell["r"] and len(cell["r"]) >= 16:
                cell["r"].pop(next(iter(cell["r"])))
            cell["r"][tid] = cur


# -- the track() registration API --------------------------------------------

_tracked_classes: Dict[type, type] = {}
_track_names: Dict[int, str] = {}
_STRUCT_KEY = "<keys>"


class _TrackedDict(dict):
    """dict proxy recording per-key reads/writes (plus a synthetic
    ``<keys>`` location for structural mutations vs. iteration). A
    drop-in replacement: callers install it in place of the original
    (``self._tenants = lockdep.track(self._tenants, "...")``)."""

    __slots__ = ("_srjt_name",)

    def _rec(self, key, write: bool) -> None:
        _record_access((self._srjt_name, key), write)

    def __getitem__(self, key):
        self._rec(key, False)
        return dict.__getitem__(self, key)

    def get(self, key, default=None):
        self._rec(key, False)
        return dict.get(self, key, default)

    def __contains__(self, key):
        self._rec(key, False)
        return dict.__contains__(self, key)

    def __setitem__(self, key, value):
        self._rec(key, True)
        self._rec(_STRUCT_KEY, True)
        dict.__setitem__(self, key, value)

    def setdefault(self, key, default=None):
        self._rec(key, True)
        self._rec(_STRUCT_KEY, True)
        return dict.setdefault(self, key, default)

    def __delitem__(self, key):
        self._rec(key, True)
        self._rec(_STRUCT_KEY, True)
        dict.__delitem__(self, key)

    def pop(self, key, *default):
        self._rec(key, True)
        self._rec(_STRUCT_KEY, True)
        return dict.pop(self, key, *default)

    def popitem(self):
        self._rec(_STRUCT_KEY, True)
        k, v = dict.popitem(self)
        # the removed key is only known post-hoc; its per-key write
        # must still land so a concurrent keyed read can conflict
        self._rec(k, True)
        return k, v

    def clear(self):
        self._rec(_STRUCT_KEY, True)
        for k in list(dict.keys(self)):
            self._rec(k, True)
        dict.clear(self)

    def update(self, *args, **kw):
        # record per-KEY writes, not just the structural location — a
        # bare update() racing a keyed read must share a location with
        # it or the detector never compares their clocks
        self._rec(_STRUCT_KEY, True)
        staged = dict(*args, **kw)
        for k in staged:
            self._rec(k, True)
        dict.update(self, staged)

    def __iter__(self):
        self._rec(_STRUCT_KEY, False)
        return dict.__iter__(self)

    def __len__(self):
        self._rec(_STRUCT_KEY, False)
        return dict.__len__(self)

    def keys(self):
        self._rec(_STRUCT_KEY, False)
        return dict.keys(self)

    def values(self):
        self._rec(_STRUCT_KEY, False)
        return dict.values(self)

    def items(self):
        self._rec(_STRUCT_KEY, False)
        return dict.items(self)


def _make_tracked_class(cls: type) -> type:
    orig_set = cls.__setattr__

    def __setattr__(self, key, value):
        orig_set(self, key, value)
        nm = _track_names.get(id(self))
        if nm is not None:
            _record_access((nm, key), True)

    # an empty-slots subclass keeps the layout identical, so
    # instance.__class__ reassignment works for slotted classes too;
    # the marker is what makes track() idempotent
    return type(cls.__name__, (cls,), {
        "__slots__": (), "__setattr__": __setattr__,
        "_srjt_tracked_class": True,
    })


def track(obj, name: str):
    """Register ``obj`` for dynamic race tracking (srjt-race layer 2).

    Disarmed (the default), returns ``obj`` untouched at the cost of
    one boolean read. Armed: dicts are replaced by a recording proxy
    (install the RETURN VALUE in place of the original); other objects
    have their class swapped to a subclass whose ``__setattr__``
    records every field WRITE (object tracking is write-only — it
    catches unguarded concurrent writes; per-key read/write coverage
    needs the dict proxy). Applied at construction time to the
    scheduler's tenant-lane table, the pool's worker-health records
    and hedge budget, the memgov catalog map, and the metrics-registry
    internals."""
    if not _race_armed:
        return obj
    # idempotent: re-tracking an already-tracked object (the global
    # hedge counter on every pool construction) must neither stack
    # another recording subclass NOR rename its locations — a rename
    # would split the access history a race could span. The class
    # marker (not an id() lookup) survives pid-style id recycling.
    if isinstance(obj, _TrackedDict) or getattr(
            type(obj), "_srjt_tracked_class", False):
        return obj
    st = _state
    with st.mu:
        st.tracked_objects += 1
    # per-registration unique suffix: two INSTANCES tracked under one
    # name (two pools, a test's private catalog beside the global one)
    # must never share locations — unordered accesses to different
    # objects are not a race
    name = f"{name}#{_next_key()}"
    if isinstance(obj, dict):
        d = _TrackedDict(obj)
        d._srjt_name = name
        return d
    cls = type(obj)
    sub = _tracked_classes.get(cls)
    if sub is None:
        sub = _tracked_classes[cls] = _make_tracked_class(cls)
    _track_names[id(obj)] = name
    obj.__class__ = sub
    return obj


def race_armed() -> bool:
    return _race_armed


def enable_race_detection() -> None:
    """Arm the vector-clock detector (installs the shim if needed) —
    the in-process switch tests use; production arms via SRJT_RACE=1
    so the patch lands before any package lock exists."""
    global _race_armed
    install()
    _race_armed = True


def disable_race_detection() -> None:
    global _race_armed
    _race_armed = False


def _note_order_edges(node, held: list) -> None:
    """Record held -> node order-graph edges (node is a _TrackedLock or
    a semaphore's _GraphNode — anything with _key/site/_register)."""
    if not held:
        return
    st = _state
    with st.mu:
        node._register(st)
        for entry in held:
            other = entry[0]
            if other._key == node._key:
                continue
            other._register(st)
            key = (other._key, node._key)
            rec = st.edges.get(key)
            if rec is None:
                st.edges[key] = {"count": 1, "stack": _short_stack()}
            else:
                rec["count"] += 1


class _GraphNode:
    """Order-graph identity for a non-lock sync primitive (Semaphore):
    the minimal protocol _note_order_edges and the held stack need."""

    __slots__ = ("_key", "site", "kind", "_registered")

    def __init__(self, site: str, kind: str):
        self._key = _next_key()
        self.site = site
        self.kind = kind
        self._registered = False

    def _register(self, st: _State) -> None:
        if not self._registered or self._key not in st.locks:
            st.locks[self._key] = {"site": self.site, "kind": self.kind}
            self._registered = True


class _TrackedLock:
    """Shim over one Lock/RLock instance. Implements the full lock
    protocol plus the private trio (``_release_save`` /
    ``_acquire_restore`` / ``_is_owned``) threading.Condition probes
    for, so a Condition built over a tracked lock keeps the held-stack
    exact across ``wait()``. ``_hb`` is the lock's happens-before
    clock (srjt-race): releases publish into it, acquires absorb it."""

    __slots__ = ("_inner", "_key", "site", "_reentrant", "_registered",
                 "_hb")

    def __init__(self, inner, site: str, reentrant: bool):
        self._inner = inner
        self._key = _next_key()
        self.site = site
        self._reentrant = reentrant
        self._registered = False
        self._hb: Optional[dict] = None

    # -- bookkeeping ---------------------------------------------------------

    def _register(self, st: _State) -> None:
        if not self._registered or self._key not in st.locks:
            st.locks[self._key] = {
                "site": self.site,
                "kind": "RLock" if self._reentrant else "Lock",
            }
            self._registered = True

    def _note_edges(self, held: list) -> None:
        _note_order_edges(self, held)

    # -- happens-before (srjt-race layer 2) ----------------------------------

    def _hb_absorb(self) -> None:
        """Post-acquire: adopt the clock of everything released under
        this lock before us. Reads _hb while HOLDING the lock, which is
        exactly the ordering that makes the bare read safe."""
        if _race_armed and self._hb:
            vc, _ = _cur_vc()
            _join_into(vc, self._hb)

    def _hb_publish(self) -> None:
        """Pre-release (still holding): publish our clock into the
        lock, tick our own component."""
        if _race_armed:
            vc, tid = _cur_vc()
            hb = self._hb
            if hb is None:
                hb = self._hb = {}
            _join_into(hb, vc)
            vc[tid] = vc.get(tid, 0) + 1

    # -- the lock protocol ---------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        held = _held()
        for entry in held:
            if entry[0] is self:
                if self._reentrant:
                    got = self._inner.acquire(blocking, timeout)
                    if got:
                        entry[1] += 1
                    return got
                # re-acquiring a held non-reentrant lock: guaranteed
                # deadlock — record it AND persist the report BEFORE
                # blocking forever (atexit never runs for a process the
                # harness has to SIGKILL)
                st = _state
                with st.mu:
                    self._register(st)
                    st.self_deadlocks.append({
                        "site": self.site,
                        "thread": threading.current_thread().name,
                        "stack": _short_stack(),
                    })
                if blocking and timeout == -1:
                    _persist_early()  # about to block forever
                return self._inner.acquire(blocking, timeout)
        # edges record the ATTEMPTED order, before any blocking: a true
        # deadlock never reaches the post-acquire line
        self._note_edges(held)
        if held and blocking and timeout == -1:
            # a wedged acquisition while other locks are held is the
            # deadlock shape: give it _STALL_REPORT_S, then persist the
            # report (both inverted edges are already recorded) and
            # keep waiting so the harness timeout stays the arbiter
            got = self._inner.acquire(True, _STALL_REPORT_S)
            if not got:
                _persist_early()
                got = self._inner.acquire(True, -1)
        else:
            got = self._inner.acquire(blocking, timeout)
        if got:
            held.append([self, 1])
            self._hb_absorb()
        return got

    def release(self):
        held = _held()
        if _race_armed:
            # publish BEFORE the inner release: the next acquirer must
            # see our full clock the instant the lock is free. Only the
            # FINAL release of a reentrant hold publishes.
            final = True
            for e in held:
                if e[0] is self and e[1] > 1:
                    final = False
                    break
            if final:
                self._hb_publish()
        self._inner.release()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self:
                held[i][1] -= 1
                if held[i][1] == 0:
                    del held[i]
                return

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc):
        self.release()

    def locked(self):
        return self._inner.locked()

    def __repr__(self):
        return f"<lockdep {self.site} over {self._inner!r}>"

    # -- threading.Condition integration -------------------------------------

    def _release_save(self):
        # Condition.wait fully releases the lock whatever its depth:
        # publish first (the notifier that acquires next must inherit
        # our clock)
        self._hb_publish()
        if self._reentrant:
            state = self._inner._release_save()
        else:
            self._inner.release()
            state = None
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self:
                del held[i]
                break
        return state

    def _acquire_restore(self, state):
        if self._reentrant:
            self._inner._acquire_restore(state)
            depth = state[0] if isinstance(state, tuple) else 1
        else:
            self._inner.acquire()
            depth = 1
        held = _held()
        self._note_edges(held)
        held.append([self, depth])
        self._hb_absorb()

    def _is_owned(self):
        if self._reentrant:
            return self._inner._is_owned()
        # plain-Lock heuristic, same as threading.Condition's fallback
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True


# -- the patched factories ---------------------------------------------------


def _make_lock():
    inner = _ORIG_LOCK()
    site = _creation_site(2)
    return inner if site is None else _TrackedLock(inner, site, False)


def _make_rlock():
    inner = _ORIG_RLOCK()
    site = _creation_site(2)
    return inner if site is None else _TrackedLock(inner, site, True)


def _make_condition(lock=None):
    if lock is None:
        site = _creation_site(2)
        if site is not None:
            lock = _TrackedLock(_ORIG_RLOCK(), site, True)
    return _ORIG_CONDITION(lock) if lock is not None else _ORIG_CONDITION()


# -- Event / Semaphore / Barrier shims (ISSUE 11 satellite) ------------------
#
# PR 7 tracked only Lock/RLock/Condition. These subclasses keep the
# full stdlib behavior (and stay subclass-safe for third-party code:
# `class Foo(threading.Event)` under the patch subclasses the shim,
# which IS the original plus hooks) while feeding the two analyses:
# Semaphores join the lock-ORDER graph (an acquire while holding locks
# is a deadlock-shaped edge; a semaphore released by another thread
# leaves a stale held entry — the same documented limit as locks), and
# all three feed HAPPENS-BEFORE edges when the race detector is armed
# (set->wait, release->acquire, barrier cycles).


class _TrackedEvent(_ORIG_EVENT):
    def set(self):
        if _race_armed:
            _publish_hb(self)
        _ORIG_EVENT.set(self)

    def wait(self, timeout=None):
        got = _ORIG_EVENT.wait(self, timeout)
        if got and _race_armed:
            _absorb_hb(self)
        return got

    def is_set(self):
        got = _ORIG_EVENT.is_set(self)
        if got and _race_armed:
            # an observed True IS a synchronizing read: the caller will
            # act on state the setter published before set()
            _absorb_hb(self)
        return got


class _SemaphoreShim:
    """Mixin for Semaphore/BoundedSemaphore: order-graph edges on
    acquire-while-holding plus HB release->acquire edges."""

    def __init__(self, value=1):
        super().__init__(value)
        site = _creation_site(2)
        # only package-created semaphores join the order graph; HB
        # edges are recorded for every instance (cheap, sound)
        self._srjt_token = (
            _GraphNode(site, "Semaphore") if site is not None else None
        )

    def acquire(self, blocking=True, timeout=None):
        tok = getattr(self, "_srjt_token", None)
        held = _held()
        if tok is not None:
            _note_order_edges(tok, held)  # attempted order, pre-block
        got = super().acquire(blocking, timeout)
        if got:
            if tok is not None:
                held.append([tok, 1])
            if _race_armed:
                _absorb_hb(self)
        return got

    __enter__ = acquire

    def release(self, n=1):
        if _race_armed:
            _publish_hb(self)
        super().release(n)
        tok = getattr(self, "_srjt_token", None)
        if tok is not None:
            held = _held()
            for i in range(len(held) - 1, -1, -1):
                if held[i][0] is tok:
                    del held[i]
                    break

    def __exit__(self, *exc):
        self.release()


class _TrackedSemaphore(_SemaphoreShim, _ORIG_SEMAPHORE):
    pass


class _TrackedBoundedSemaphore(_SemaphoreShim, _ORIG_BOUNDED_SEMAPHORE):
    pass


class _TrackedBarrier(_ORIG_BARRIER):
    def wait(self, timeout=None):
        if _race_armed:
            # arrival: merge into the cycle clock — every thread's
            # pre-barrier work is ordered before every thread's
            # post-barrier work once all have arrived
            _publish_hb(self)
        idx = _ORIG_BARRIER.wait(self, timeout)
        if _race_armed:
            _absorb_hb(self)
        return idx


def _tracked_thread_start(self):
    if _race_armed:
        vc, tid = _cur_vc()
        start_clock = dict(vc)
        vc[tid] = vc.get(tid, 0) + 1
        orig_run = self.run

        def _run_and_stamp():
            # seed the child's clock from the parent's start snapshot
            # (the start edge), replacing any stub clock bootstrap
            # Event traffic may have minted before run()
            ctid = _next_key()
            _tls.tid = ctid
            cvc = dict(start_clock)
            cvc[ctid] = cvc.get(ctid, 0) + 1
            _tls.vc = cvc
            try:
                orig_run()
            finally:
                self._srjt_final_clock = dict(_tls.vc)

        self.run = _run_and_stamp
    return _ORIG_THREAD_START(self)


def _tracked_thread_join(self, timeout=None):
    r = _ORIG_THREAD_JOIN(self, timeout)
    if _race_armed and not self.is_alive():
        fin = getattr(self, "_srjt_final_clock", None)
        if fin:
            vc, _ = _cur_vc()
            _join_into(vc, fin)
    return r


def _tracked_sleep(secs):
    held = getattr(_tls, "held", None)
    if held:
        st = _state
        with st.mu:
            st.blocking_total += 1
            if len(st.blocking) < _MAX_BLOCKING_EVENTS:
                st.blocking.append({
                    "syscall": "sleep",
                    "seconds": float(secs),
                    "thread": threading.current_thread().name,
                    "locks_held": [e[0].site for e in held],
                    "stack": _short_stack(),
                })
    _ORIG_SLEEP(secs)


# -- lifecycle ---------------------------------------------------------------


def install() -> None:
    """Patch threading (Lock/RLock/Condition + Event/Semaphore/
    Barrier/Thread.start/join) and time.sleep, and register the
    exit-time report writer. Idempotent. Must run before the modules
    whose locks it should see are imported — the package ``__init__``
    does this when SRJT_LOCKDEP=1 or SRJT_RACE=1 (the race detector
    rides this shim: arming it arms lockdep)."""
    global _installed, _race_armed
    if os.environ.get("SRJT_RACE", "").lower() in ("1", "true", "yes"):  # srjt-lint: allow-environ(bootstrap: utils/knobs must not be imported from the lockdep layer)
        _race_armed = True
    if _installed:
        return
    threading.Lock = _make_lock
    threading.RLock = _make_rlock
    threading.Condition = _make_condition
    threading.Event = _TrackedEvent
    threading.Semaphore = _TrackedSemaphore
    threading.BoundedSemaphore = _TrackedBoundedSemaphore
    threading.Barrier = _TrackedBarrier
    threading.Thread.start = _tracked_thread_start
    threading.Thread.join = _tracked_thread_join
    time.sleep = _tracked_sleep
    atexit.register(_atexit_report)
    _installed = True


def uninstall() -> None:
    global _installed, _race_armed
    _race_armed = False
    if not _installed:
        return
    threading.Lock = _ORIG_LOCK
    threading.RLock = _ORIG_RLOCK
    threading.Condition = _ORIG_CONDITION
    threading.Event = _ORIG_EVENT
    threading.Semaphore = _ORIG_SEMAPHORE
    threading.BoundedSemaphore = _ORIG_BOUNDED_SEMAPHORE
    threading.Barrier = _ORIG_BARRIER
    threading.Thread.start = _ORIG_THREAD_START
    threading.Thread.join = _ORIG_THREAD_JOIN
    time.sleep = _ORIG_SLEEP
    _installed = False


def is_installed() -> bool:
    return _installed


@contextlib.contextmanager
def isolated_state():
    """Swap in a throwaway graph for the dynamic extent of the block
    (the deliberate-inversion unit test's tool: its cycle must never
    reach the session report the CI gate asserts on)."""
    global _state
    prev = _state
    _state = _State()
    try:
        yield _state
    finally:
        _state = prev


# -- reporting ---------------------------------------------------------------


def find_cycles(edges) -> List[List[int]]:
    """Strongly connected components with >1 node (or a self-edge) in
    the key graph — each is a set of locks with circular ordering, i.e.
    a potential deadlock. Iterative Tarjan: lock graphs are small but
    stacks under chaos tests need not be."""
    graph: Dict[int, List[int]] = {}
    for a, b in edges:
        graph.setdefault(a, []).append(b)
        graph.setdefault(b, [])
    index: Dict[int, int] = {}
    low: Dict[int, int] = {}
    on_stack: Dict[int, bool] = {}
    stack: List[int] = []
    counter = [0]
    sccs: List[List[int]] = []

    for root in graph:
        if root in index:
            continue
        work = [(root, iter(graph[root]))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack[root] = True
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack[nxt] = True
                    work.append((nxt, iter(graph[nxt])))
                    advanced = True
                    break
                elif on_stack.get(nxt):
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    v = stack.pop()
                    on_stack[v] = False
                    comp.append(v)
                    if v == node:
                        break
                if len(comp) > 1 or (node, node) in edges:
                    sccs.append(sorted(comp))
    return sccs


def report(state: Optional[_State] = None) -> dict:
    st = _state if state is None else state
    with st.mu:
        locks = {str(k): dict(v) for k, v in st.locks.items()}
        edge_items = [
            (a, b, dict(rec)) for (a, b), rec in st.edges.items()
        ]
        blocking = list(st.blocking)
        blocking_total = st.blocking_total
        self_deadlocks = list(st.self_deadlocks)
        race_pairs = [dict(r) for r in st.races]
        race_total = st.race_total
        tracked_objects = st.tracked_objects
    site = lambda k: locks.get(str(k), {}).get("site", f"key{k}")  # noqa: E731
    cycles = [
        {"locks": [site(k) for k in comp], "keys": comp}
        for comp in find_cycles({(a, b) for a, b, _ in edge_items})
    ]
    return {
        "pid": os.getpid(),
        "argv": sys.argv,
        "locks": locks,
        "edges": [
            {"from": site(a), "to": site(b),
             "from_key": a, "to_key": b, **rec}
            for a, b, rec in edge_items
        ],
        "cycles": cycles,
        "self_deadlocks": self_deadlocks,
        "blocking_events": blocking,
        "blocking_total": blocking_total,
        # srjt-race layer 2 (ISSUE 11): unordered access pairs on
        # tracked state, each with both stacks — the merge gate fails
        # on ANY of these, same discipline as cycles
        "race_pairs": race_pairs,
        "race_total": race_total,
        "race_armed": _race_armed,
        "tracked_objects": tracked_objects,
    }


def _report_dir() -> str:
    # direct env read by design: see the bootstrap note in the module
    # docstring (both names ARE declared in utils/knobs.py)
    return os.environ.get("SRJT_LOCKDEP_DIR") or "artifacts/lockdep"  # srjt-lint: allow-environ(bootstrap: utils/knobs must not be imported from the lockdep layer)


_report_name: Optional[str] = None


def _default_report_path() -> str:
    # one name per process, random-suffixed: Linux recycles pids, and a
    # later CI tier's process must never overwrite an earlier tier's
    # report (a lost cycle = a false pass at the merge gate). An early
    # persist and the atexit write share the name — the later write is
    # a superset of the earlier, never a duplicate report in the merge.
    global _report_name
    if _report_name is None:
        _report_name = f"lockdep_{os.getpid()}_{os.urandom(4).hex()}.json"
    d = _report_dir()
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, _report_name)


def write_report(path: Optional[str] = None) -> str:
    if path is None:
        path = _default_report_path()
    with open(path, "w", encoding="utf-8") as f:
        json.dump(report(), f, indent=1, default=str)
    return path


def flush_report() -> None:
    """Persist the session report NOW, best-effort — for processes
    that exit via ``os._exit`` (the sidecar worker's shutdown verb),
    which skips atexit. No-op when disarmed or inside an
    ``isolated_state`` test universe."""
    _persist_early()


def _persist_early() -> None:
    """Best-effort report write from INSIDE a detected/suspected
    deadlock, so the artifact exists even when the process never
    reaches atexit (harness SIGKILL). Only when armed: unit tests that
    poke _TrackedLock directly must not scribble artifacts."""
    if not _installed or _state is not _session_state:
        return  # disarmed, or an isolated_state() test universe
    try:
        write_report()
    except OSError:
        pass


def _atexit_report() -> None:
    if not _installed:
        return
    try:
        write_report()
    except OSError:
        pass  # a read-only CI sandbox degrades the artifact, not exit


# -- merge + gate (the premerge CLI) -----------------------------------------


def merge_reports(dir_path: str) -> dict:
    reports = []
    for fn in sorted(os.listdir(dir_path)):
        if fn.startswith("lockdep_") and fn.endswith(".json"):
            with open(os.path.join(dir_path, fn), encoding="utf-8") as f:
                reports.append(json.load(f))
    merged_edges: Dict[Tuple[str, str], dict] = {}
    cycles, self_deadlocks, race_pairs = [], [], []
    locks_seen = set()
    blocking_total = 0
    race_total = 0
    race_armed_any = False
    for r in reports:
        for lk in r.get("locks", {}).values():
            locks_seen.add(lk.get("site"))
        for e in r.get("edges", []):
            key = (e["from"], e["to"])
            rec = merged_edges.setdefault(
                key, {"from": e["from"], "to": e["to"], "count": 0})
            rec["count"] += e.get("count", 1)
        for c in r.get("cycles", []):
            cycles.append({"pid": r.get("pid"), **c})
        for sd in r.get("self_deadlocks", []):
            self_deadlocks.append({"pid": r.get("pid"), **sd})
        for rp in r.get("race_pairs", []):
            race_pairs.append({"pid": r.get("pid"), **rp})
        blocking_total += r.get("blocking_total", 0)
        race_total += r.get("race_total", 0)
        race_armed_any = race_armed_any or r.get("race_armed", False)
    # cross-process inversion check: per-process cycles are
    # per-INSTANCE, so an A->B order in tier 1 and B->A in tier 2 shows
    # up only here, on the merged SITE graph. Same-site self-edges
    # (two instances from one creation site nested — the per-connection
    # io_lock pattern) are excluded from the cycle test and surfaced
    # separately: per-instance tracking already proved them acyclic
    # within every process that ran them.
    sites = sorted({s for e in merged_edges for s in e})
    idx = {s: i for i, s in enumerate(sites)}
    site_cycles = [
        {"locks": [sites[k] for k in comp]}
        for comp in find_cycles(
            {(idx[a], idx[b]) for a, b in merged_edges if a != b})
    ]
    return {
        "reports": len(reports),
        "locks": sorted(x for x in locks_seen if x),
        "edges": sorted(merged_edges.values(),
                        key=lambda e: (e["from"], e["to"])),
        "cycles": cycles,
        "site_cycles": site_cycles,
        "site_self_edges": sorted(a for a, b in merged_edges if a == b),
        "self_deadlocks": self_deadlocks,
        "blocking_total": blocking_total,
        "race_pairs": race_pairs,
        "race_total": race_total,
        "race_armed": race_armed_any,
    }


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m spark_rapids_jni_tpu.analysis.lockdep",
        description="merge per-process lockdep reports and gate on "
        "zero lock-order cycles (ISSUE 7)")
    ap.add_argument("--merge", default=None,
                    help="directory of lockdep_<pid>.json reports "
                    "(default: SRJT_LOCKDEP_DIR)")
    ap.add_argument("--out", default=None,
                    help="write the merged report here")
    ap.add_argument("--allow-empty", action="store_true",
                    help="do not fail when no reports were found")
    args = ap.parse_args(argv)
    d = args.merge or _report_dir()
    if not os.path.isdir(d):
        if args.allow_empty:
            print(f"lockdep: no report dir {d}")
            return 0
        print(f"lockdep: report dir {d} missing — was SRJT_LOCKDEP=1 "
              "armed?", file=sys.stderr)
        return 2
    merged = merge_reports(d)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(merged, f, indent=1)
    if merged["reports"] == 0 and not args.allow_empty:
        print("lockdep: zero reports found — was SRJT_LOCKDEP=1 armed?",
              file=sys.stderr)
        return 2
    bad = (merged["cycles"] or merged["self_deadlocks"]
           or merged["site_cycles"] or merged["race_pairs"])
    print(f"lockdep: {merged['reports']} report(s), "
          f"{len(merged['locks'])} lock site(s), "
          f"{len(merged['edges'])} edge(s), "
          f"{len(merged['cycles'])} cycle(s), "
          f"{len(merged['site_cycles'])} cross-process site cycle(s), "
          f"{len(merged['self_deadlocks'])} self-deadlock(s), "
          f"{merged['blocking_total']} blocking-while-locked event(s), "
          f"{merged['race_total']} race(s)"
          + ("" if merged["race_armed"] else " (race detector unarmed)"))
    for c in merged["cycles"]:
        print(f"  CYCLE (pid {c.get('pid')}): " + " -> ".join(c["locks"]),
              file=sys.stderr)
    for c in merged["site_cycles"]:
        print("  SITE CYCLE (cross-process): " + " -> ".join(c["locks"]),
              file=sys.stderr)
    for sd in merged["self_deadlocks"]:
        print(f"  SELF-DEADLOCK (pid {sd.get('pid')}): {sd.get('site')}",
              file=sys.stderr)
    for rp in merged["race_pairs"]:
        print(f"  RACE (pid {rp.get('pid')}): {rp.get('kind')} on "
              f"{rp.get('location')} [{rp['a'].get('thread')} vs "
              f"{rp['b'].get('thread')}]", file=sys.stderr)
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())

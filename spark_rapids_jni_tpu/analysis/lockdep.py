"""Runtime lockdep: lock-order cycle detection for the concurrent
substrate (ISSUE 7 layer 2; the Linux kernel lockdep idea, scoped to
this package's ~25 locks).

Armed with ``SRJT_LOCKDEP=1``, the package ``__init__`` calls
``install()`` BEFORE any other package import, so every
``threading.Lock/RLock/Condition`` created by package (or repo test)
code afterwards is a tracked shim. Per thread, the shim keeps the stack
of currently-held tracked locks; every successful-or-attempted
acquisition of lock B while holding lock A records the directed edge
A -> B (per lock INSTANCE — two specific locks taken in both orders is
a real potential deadlock, never a same-class false positive) with one
sample stack per edge. ``time.sleep`` is wrapped too: sleeping while
holding any tracked lock is recorded as a blocking-while-locked event
(the latency-bomb the deadline tier exists to prevent). Sockets guarded
by a per-connection io_lock are the DESIGN on the sidecar data path, so
recv is deliberately not instrumented — the lint layer (SRJT006)
polices blocking calls statically instead.

At process exit each armed process writes
``<SRJT_LOCKDEP_DIR>/lockdep_<pid>.json`` — lock sites, the order
graph, cycles (strongly connected components), self-deadlocks
(re-acquiring a held non-reentrant lock), and blocking events. Armed
for the full tier-1 + chaos suites in ci/premerge.sh, every existing
concurrency test doubles as a lockdep probe; the merge gate::

    python -m spark_rapids_jni_tpu.analysis.lockdep \
        --merge artifacts/lockdep --out artifacts/lockdep_report.json

fails on any cycle or self-deadlock across every report.

Bootstrap constraint: this module reads its env knobs directly —
importing utils/knobs.py here would drag in the whole utils tree
before the shim is installed, leaving every utils lock untracked. The
knob names stay declared in the registry like any other.

Known limits (documented, not bugs): locks created before ``install()``
(or by code that did ``from threading import Lock`` at import time) are
untracked; a lock acquired in one thread and released in another leaves
a stale held entry on the acquirer. Neither shape exists in this
package.
"""

from __future__ import annotations

import atexit
import contextlib
import json
import os
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

__all__ = [
    "install",
    "uninstall",
    "is_installed",
    "isolated_state",
    "report",
    "write_report",
    "flush_report",
    "find_cycles",
    "merge_reports",
    "main",
]

# originals captured at import, before any patching
_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock
_ORIG_CONDITION = threading.Condition
_ORIG_SLEEP = time.sleep

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_ROOT = os.path.dirname(_PKG_ROOT)

_MAX_BLOCKING_EVENTS = 200  # sample cap; the total is counted exactly
# a blocking acquisition that stalls this long while other locks are
# held persists the report EARLY: a real deadlock never reaches exit's
# atexit writer (CI SIGKILLs it), but the stalled report carries the
# inverted edges the postmortem needs
_STALL_REPORT_S = 60.0


class _State:
    """One lockdep universe: the order graph + event tallies. Swappable
    via ``isolated_state()`` so the deliberate-inversion unit test does
    not poison the session report the CI gate asserts on."""

    def __init__(self):
        self.mu = _ORIG_LOCK()
        self.locks: Dict[int, dict] = {}  # key -> {"site", "kind"}
        self.edges: Dict[Tuple[int, int], dict] = {}
        self.blocking: List[dict] = []
        self.blocking_total = 0
        self.self_deadlocks: List[dict] = []


_state = _State()
_session_state = _state  # the universe the CI gate asserts on
_tls = threading.local()
_installed = False
_seq_lock = _ORIG_LOCK()
_seq = 0


def _next_key() -> int:
    global _seq
    with _seq_lock:
        _seq += 1
        return _seq


def _creation_site(depth: int) -> Optional[str]:
    try:
        f = sys._getframe(depth)
    except ValueError:
        return None
    fn = f.f_code.co_filename
    # package files are ALWAYS tracked — including wheel installs where
    # the package (and so _REPO_ROOT) lives inside site-packages; the
    # site-packages rejection only filters third-party code picked up
    # via the repo-root prefix in dev checkouts (tests/, benchmarks/)
    if not fn.startswith(_PKG_ROOT + os.sep):
        if not fn.startswith(_REPO_ROOT) or "site-packages" in fn:
            return None
    if os.sep + "analysis" + os.sep in fn:
        return None  # never track our own machinery
    return f"{os.path.relpath(fn, _REPO_ROOT)}:{f.f_lineno}"


def _short_stack() -> str:
    # drop the two lockdep-internal frames at the tail
    return "".join(traceback.format_stack(limit=10)[:-2])


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


class _TrackedLock:
    """Shim over one Lock/RLock instance. Implements the full lock
    protocol plus the private trio (``_release_save`` /
    ``_acquire_restore`` / ``_is_owned``) threading.Condition probes
    for, so a Condition built over a tracked lock keeps the held-stack
    exact across ``wait()``."""

    __slots__ = ("_inner", "_key", "site", "_reentrant", "_registered")

    def __init__(self, inner, site: str, reentrant: bool):
        self._inner = inner
        self._key = _next_key()
        self.site = site
        self._reentrant = reentrant
        self._registered = False

    # -- bookkeeping ---------------------------------------------------------

    def _register(self, st: _State) -> None:
        if not self._registered or self._key not in st.locks:
            st.locks[self._key] = {
                "site": self.site,
                "kind": "RLock" if self._reentrant else "Lock",
            }
            self._registered = True

    def _note_edges(self, held: list) -> None:
        if not held:
            return
        st = _state
        with st.mu:
            self._register(st)
            for entry in held:
                other = entry[0]
                if other._key == self._key:
                    continue
                other._register(st)
                key = (other._key, self._key)
                rec = st.edges.get(key)
                if rec is None:
                    st.edges[key] = {"count": 1, "stack": _short_stack()}
                else:
                    rec["count"] += 1

    # -- the lock protocol ---------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        held = _held()
        for entry in held:
            if entry[0] is self:
                if self._reentrant:
                    got = self._inner.acquire(blocking, timeout)
                    if got:
                        entry[1] += 1
                    return got
                # re-acquiring a held non-reentrant lock: guaranteed
                # deadlock — record it AND persist the report BEFORE
                # blocking forever (atexit never runs for a process the
                # harness has to SIGKILL)
                st = _state
                with st.mu:
                    self._register(st)
                    st.self_deadlocks.append({
                        "site": self.site,
                        "thread": threading.current_thread().name,
                        "stack": _short_stack(),
                    })
                if blocking and timeout == -1:
                    _persist_early()  # about to block forever
                return self._inner.acquire(blocking, timeout)
        # edges record the ATTEMPTED order, before any blocking: a true
        # deadlock never reaches the post-acquire line
        self._note_edges(held)
        if held and blocking and timeout == -1:
            # a wedged acquisition while other locks are held is the
            # deadlock shape: give it _STALL_REPORT_S, then persist the
            # report (both inverted edges are already recorded) and
            # keep waiting so the harness timeout stays the arbiter
            got = self._inner.acquire(True, _STALL_REPORT_S)
            if not got:
                _persist_early()
                got = self._inner.acquire(True, -1)
        else:
            got = self._inner.acquire(blocking, timeout)
        if got:
            held.append([self, 1])
        return got

    def release(self):
        self._inner.release()
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self:
                held[i][1] -= 1
                if held[i][1] == 0:
                    del held[i]
                return

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc):
        self.release()

    def locked(self):
        return self._inner.locked()

    def __repr__(self):
        return f"<lockdep {self.site} over {self._inner!r}>"

    # -- threading.Condition integration -------------------------------------

    def _release_save(self):
        if self._reentrant:
            state = self._inner._release_save()
        else:
            self._inner.release()
            state = None
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self:
                del held[i]
                break
        return state

    def _acquire_restore(self, state):
        if self._reentrant:
            self._inner._acquire_restore(state)
            depth = state[0] if isinstance(state, tuple) else 1
        else:
            self._inner.acquire()
            depth = 1
        held = _held()
        self._note_edges(held)
        held.append([self, depth])

    def _is_owned(self):
        if self._reentrant:
            return self._inner._is_owned()
        # plain-Lock heuristic, same as threading.Condition's fallback
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True


# -- the patched factories ---------------------------------------------------


def _make_lock():
    inner = _ORIG_LOCK()
    site = _creation_site(2)
    return inner if site is None else _TrackedLock(inner, site, False)


def _make_rlock():
    inner = _ORIG_RLOCK()
    site = _creation_site(2)
    return inner if site is None else _TrackedLock(inner, site, True)


def _make_condition(lock=None):
    if lock is None:
        site = _creation_site(2)
        if site is not None:
            lock = _TrackedLock(_ORIG_RLOCK(), site, True)
    return _ORIG_CONDITION(lock) if lock is not None else _ORIG_CONDITION()


def _tracked_sleep(secs):
    held = getattr(_tls, "held", None)
    if held:
        st = _state
        with st.mu:
            st.blocking_total += 1
            if len(st.blocking) < _MAX_BLOCKING_EVENTS:
                st.blocking.append({
                    "syscall": "sleep",
                    "seconds": float(secs),
                    "thread": threading.current_thread().name,
                    "locks_held": [e[0].site for e in held],
                    "stack": _short_stack(),
                })
    _ORIG_SLEEP(secs)


# -- lifecycle ---------------------------------------------------------------


def install() -> None:
    """Patch threading.Lock/RLock/Condition + time.sleep and register
    the exit-time report writer. Idempotent. Must run before the
    modules whose locks it should see are imported — the package
    ``__init__`` does this when SRJT_LOCKDEP=1."""
    global _installed
    if _installed:
        return
    threading.Lock = _make_lock
    threading.RLock = _make_rlock
    threading.Condition = _make_condition
    time.sleep = _tracked_sleep
    atexit.register(_atexit_report)
    _installed = True


def uninstall() -> None:
    global _installed
    if not _installed:
        return
    threading.Lock = _ORIG_LOCK
    threading.RLock = _ORIG_RLOCK
    threading.Condition = _ORIG_CONDITION
    time.sleep = _ORIG_SLEEP
    _installed = False


def is_installed() -> bool:
    return _installed


@contextlib.contextmanager
def isolated_state():
    """Swap in a throwaway graph for the dynamic extent of the block
    (the deliberate-inversion unit test's tool: its cycle must never
    reach the session report the CI gate asserts on)."""
    global _state
    prev = _state
    _state = _State()
    try:
        yield _state
    finally:
        _state = prev


# -- reporting ---------------------------------------------------------------


def find_cycles(edges) -> List[List[int]]:
    """Strongly connected components with >1 node (or a self-edge) in
    the key graph — each is a set of locks with circular ordering, i.e.
    a potential deadlock. Iterative Tarjan: lock graphs are small but
    stacks under chaos tests need not be."""
    graph: Dict[int, List[int]] = {}
    for a, b in edges:
        graph.setdefault(a, []).append(b)
        graph.setdefault(b, [])
    index: Dict[int, int] = {}
    low: Dict[int, int] = {}
    on_stack: Dict[int, bool] = {}
    stack: List[int] = []
    counter = [0]
    sccs: List[List[int]] = []

    for root in graph:
        if root in index:
            continue
        work = [(root, iter(graph[root]))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack[root] = True
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack[nxt] = True
                    work.append((nxt, iter(graph[nxt])))
                    advanced = True
                    break
                elif on_stack.get(nxt):
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    v = stack.pop()
                    on_stack[v] = False
                    comp.append(v)
                    if v == node:
                        break
                if len(comp) > 1 or (node, node) in edges:
                    sccs.append(sorted(comp))
    return sccs


def report(state: Optional[_State] = None) -> dict:
    st = _state if state is None else state
    with st.mu:
        locks = {str(k): dict(v) for k, v in st.locks.items()}
        edge_items = [
            (a, b, dict(rec)) for (a, b), rec in st.edges.items()
        ]
        blocking = list(st.blocking)
        blocking_total = st.blocking_total
        self_deadlocks = list(st.self_deadlocks)
    site = lambda k: locks.get(str(k), {}).get("site", f"key{k}")  # noqa: E731
    cycles = [
        {"locks": [site(k) for k in comp], "keys": comp}
        for comp in find_cycles({(a, b) for a, b, _ in edge_items})
    ]
    return {
        "pid": os.getpid(),
        "argv": sys.argv,
        "locks": locks,
        "edges": [
            {"from": site(a), "to": site(b),
             "from_key": a, "to_key": b, **rec}
            for a, b, rec in edge_items
        ],
        "cycles": cycles,
        "self_deadlocks": self_deadlocks,
        "blocking_events": blocking,
        "blocking_total": blocking_total,
    }


def _report_dir() -> str:
    # direct env read by design: see the bootstrap note in the module
    # docstring (both names ARE declared in utils/knobs.py)
    return os.environ.get("SRJT_LOCKDEP_DIR") or "artifacts/lockdep"  # srjt-lint: allow-environ(bootstrap: utils/knobs must not be imported from the lockdep layer)


_report_name: Optional[str] = None


def _default_report_path() -> str:
    # one name per process, random-suffixed: Linux recycles pids, and a
    # later CI tier's process must never overwrite an earlier tier's
    # report (a lost cycle = a false pass at the merge gate). An early
    # persist and the atexit write share the name — the later write is
    # a superset of the earlier, never a duplicate report in the merge.
    global _report_name
    if _report_name is None:
        _report_name = f"lockdep_{os.getpid()}_{os.urandom(4).hex()}.json"
    d = _report_dir()
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, _report_name)


def write_report(path: Optional[str] = None) -> str:
    if path is None:
        path = _default_report_path()
    with open(path, "w", encoding="utf-8") as f:
        json.dump(report(), f, indent=1, default=str)
    return path


def flush_report() -> None:
    """Persist the session report NOW, best-effort — for processes
    that exit via ``os._exit`` (the sidecar worker's shutdown verb),
    which skips atexit. No-op when disarmed or inside an
    ``isolated_state`` test universe."""
    _persist_early()


def _persist_early() -> None:
    """Best-effort report write from INSIDE a detected/suspected
    deadlock, so the artifact exists even when the process never
    reaches atexit (harness SIGKILL). Only when armed: unit tests that
    poke _TrackedLock directly must not scribble artifacts."""
    if not _installed or _state is not _session_state:
        return  # disarmed, or an isolated_state() test universe
    try:
        write_report()
    except OSError:
        pass


def _atexit_report() -> None:
    if not _installed:
        return
    try:
        write_report()
    except OSError:
        pass  # a read-only CI sandbox degrades the artifact, not exit


# -- merge + gate (the premerge CLI) -----------------------------------------


def merge_reports(dir_path: str) -> dict:
    reports = []
    for fn in sorted(os.listdir(dir_path)):
        if fn.startswith("lockdep_") and fn.endswith(".json"):
            with open(os.path.join(dir_path, fn), encoding="utf-8") as f:
                reports.append(json.load(f))
    merged_edges: Dict[Tuple[str, str], dict] = {}
    cycles, self_deadlocks = [], []
    locks_seen = set()
    blocking_total = 0
    for r in reports:
        for lk in r.get("locks", {}).values():
            locks_seen.add(lk.get("site"))
        for e in r.get("edges", []):
            key = (e["from"], e["to"])
            rec = merged_edges.setdefault(
                key, {"from": e["from"], "to": e["to"], "count": 0})
            rec["count"] += e.get("count", 1)
        for c in r.get("cycles", []):
            cycles.append({"pid": r.get("pid"), **c})
        for sd in r.get("self_deadlocks", []):
            self_deadlocks.append({"pid": r.get("pid"), **sd})
        blocking_total += r.get("blocking_total", 0)
    # cross-process inversion check: per-process cycles are
    # per-INSTANCE, so an A->B order in tier 1 and B->A in tier 2 shows
    # up only here, on the merged SITE graph. Same-site self-edges
    # (two instances from one creation site nested — the per-connection
    # io_lock pattern) are excluded from the cycle test and surfaced
    # separately: per-instance tracking already proved them acyclic
    # within every process that ran them.
    sites = sorted({s for e in merged_edges for s in e})
    idx = {s: i for i, s in enumerate(sites)}
    site_cycles = [
        {"locks": [sites[k] for k in comp]}
        for comp in find_cycles(
            {(idx[a], idx[b]) for a, b in merged_edges if a != b})
    ]
    return {
        "reports": len(reports),
        "locks": sorted(x for x in locks_seen if x),
        "edges": sorted(merged_edges.values(),
                        key=lambda e: (e["from"], e["to"])),
        "cycles": cycles,
        "site_cycles": site_cycles,
        "site_self_edges": sorted(a for a, b in merged_edges if a == b),
        "self_deadlocks": self_deadlocks,
        "blocking_total": blocking_total,
    }


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m spark_rapids_jni_tpu.analysis.lockdep",
        description="merge per-process lockdep reports and gate on "
        "zero lock-order cycles (ISSUE 7)")
    ap.add_argument("--merge", default=None,
                    help="directory of lockdep_<pid>.json reports "
                    "(default: SRJT_LOCKDEP_DIR)")
    ap.add_argument("--out", default=None,
                    help="write the merged report here")
    ap.add_argument("--allow-empty", action="store_true",
                    help="do not fail when no reports were found")
    args = ap.parse_args(argv)
    d = args.merge or _report_dir()
    if not os.path.isdir(d):
        if args.allow_empty:
            print(f"lockdep: no report dir {d}")
            return 0
        print(f"lockdep: report dir {d} missing — was SRJT_LOCKDEP=1 "
              "armed?", file=sys.stderr)
        return 2
    merged = merge_reports(d)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(merged, f, indent=1)
    if merged["reports"] == 0 and not args.allow_empty:
        print("lockdep: zero reports found — was SRJT_LOCKDEP=1 armed?",
              file=sys.stderr)
        return 2
    bad = (merged["cycles"] or merged["self_deadlocks"]
           or merged["site_cycles"])
    print(f"lockdep: {merged['reports']} report(s), "
          f"{len(merged['locks'])} lock site(s), "
          f"{len(merged['edges'])} edge(s), "
          f"{len(merged['cycles'])} cycle(s), "
          f"{len(merged['site_cycles'])} cross-process site cycle(s), "
          f"{len(merged['self_deadlocks'])} self-deadlock(s), "
          f"{merged['blocking_total']} blocking-while-locked event(s)")
    for c in merged["cycles"]:
        print(f"  CYCLE (pid {c.get('pid')}): " + " -> ".join(c["locks"]),
              file=sys.stderr)
    for c in merged["site_cycles"]:
        print("  SITE CYCLE (cross-process): " + " -> ".join(c["locks"]),
              file=sys.stderr)
    for sd in merged["self_deadlocks"]:
        print(f"  SELF-DEADLOCK (pid {sd.get('pid')}): {sd.get('site')}",
              file=sys.stderr)
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())

"""``srjt-lint``: the invariant lint suite for the concurrent substrate
(ISSUE 7 layer 1; stdlib ``ast`` only, no new dependencies).

PRs 1-6 built five threaded subsystems whose correctness rests on
conventions a reviewer had to re-check by hand on every change. Each
rule here machine-checks one of them:

    SRJT001 undeclared-knob      every ``SRJT_*`` string literal in the
                                 package must be declared in the
                                 utils/knobs.py registry (or be a knobs
                                 SENTINEL — a stdout handshake line).
    SRJT002 direct-environ-read  ``os.environ`` / ``os.getenv`` READS
                                 of SRJT keys (or of dynamic keys that
                                 cannot be proven non-SRJT) are only
                                 legal inside utils/knobs.py — the
                                 typed accessors are the one front
                                 door. Non-SRJT literal keys
                                 (PYTHONPATH, JAX_PLATFORMS) and
                                 environ WRITES are fine.
    SRJT003 banned-raise         no ``raise RuntimeError``/bare
                                 ``raise Exception`` inside the
                                 governed dirs (ops/, memgov/,
                                 parallel/, sidecar*.py): failures
                                 crossing those boundaries must speak
                                 the utils/errors.py taxonomy.
    SRJT004 broad-except         every ``except Exception`` /bare
                                 ``except:`` in the package must
                                 re-raise, wrap into the taxonomy
                                 (classify / raise_corruption / a
                                 taxonomy class), or carry an explicit
                                 suppression with a reason.
    SRJT005 stub-discipline      in the stub-pattern modules (metrics /
                                 tracing / integrity / faultinj /
                                 memgov gates) no string formatting or
                                 allocation-ish work may execute before
                                 the function's enabled-gate check —
                                 the disabled hot path stays one
                                 boolean read.
    SRJT006 blocking-call        ``time.sleep`` / ``socket.settimeout``
                                 / ``recv`` in the governed concurrent
                                 modules must live in functions that
                                 are deadline-aware (reference a
                                 deadline / remaining / budget /
                                 timeout) — a blocking call no deadline
                                 can interrupt is how queries hang
                                 forever.
    SRJT007 doc-drift            the knob registry and the
                                 README/PACKAGING knob tables must
                                 agree both ways: every declared knob
                                 documented, every documented token
                                 declared.
    SRJT011 unverified-rewrite   every rewrite rule registered in
                                 plan/rewrites.py (plus prune_columns)
                                 must have a translation-validation
                                 discharger in plan/verifier.py's
                                 OBLIGATION_DISCHARGERS, or carry
                                 ``# srjt-plan: allow-unverified(<reason>)``
                                 inside its function body; a
                                 suppression on a rule that IS
                                 discharged is stale (SRJT000).
    SRJT000 bad-suppression      a suppression comment with an empty /
                                 missing reason is itself a violation.

Suppression syntax (reason REQUIRED), on the flagged line or alone on
the line directly above it::

    except Exception:  # srjt-lint: allow-broad-except(best-effort reap; spawn cleanup must never mask the startup error)
    time.sleep(d)      # srjt-lint: allow-blocking(detached respawn thread; owns no query budget)
    os.environ.get(k)  # srjt-lint: allow-environ(bootstrap read before utils can import)
    raise RuntimeError(m)  # srjt-lint: allow-raise(semantic wire error; breaker must record success)

Run ``python -m spark_rapids_jni_tpu.analysis.lint`` from the repo
root (exit 1 on any violation); ``--knob-table`` renders the registry
as the markdown table the docs embed.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

__all__ = [
    "Violation",
    "lint_source",
    "lint_file",
    "run",
    "main",
    "format_findings",
    "write_findings",
    "check_rewrite_obligations",
]

_KNOB_RE = re.compile(r"SRJT_[A-Z0-9_]*[A-Z0-9]")

# taxonomy names whose raise (or wrap) satisfies SRJT004; `classify`
# and `raise_corruption` are the two canonical wrap helpers
_TAXONOMY = {
    "DeviceError", "FatalDeviceError", "RetryableError", "DataCorruption",
    "DeadlineExceeded", "MemoryBudgetExceeded", "Overloaded", "classify",
    "raise_corruption",
}

# rule scopes, as path fragments relative to the package root
_RAISE_GOVERNED = ("ops/", "memgov/", "parallel/", "serve/", "plan/",
                   "sidecar.py", "sidecar_pool.py")
_BLOCKING_GOVERNED = ("sidecar.py", "sidecar_pool.py", "parallel/",
                      "memgov/", "serve/", "utils/retry.py",
                      "utils/faultinj.py", "utils/tracing.py",
                      "utils/trace_sink.py")
_STUB_MODULES = ("utils/metrics.py", "utils/tracing.py",
                 "utils/integrity.py", "utils/faultinj.py",
                 "memgov/__init__.py", "utils/trace_sink.py")

# identifiers marking the enabled-gate (SRJT005) ...
_GATE_NAMES = {"_enabled", "is_enabled", "enabled", "is_armed"}
# ... and, for SRJT006, the substrings marking a deadline-aware function
_DEADLINE_MARKS = ("deadline", "remaining", "budget", "timeout")

_SUPPRESS_RE = re.compile(r"#\s*srjt-lint:\s*allow-([a-z-]+)\s*\((.*)\)\s*$")
_RULE_SUPPRESSIONS = {
    "SRJT001": "knob",
    "SRJT002": "environ",
    "SRJT003": "raise",
    "SRJT004": "broad-except",
    "SRJT005": "stub",
    "SRJT006": "blocking",
}


class Violation:
    __slots__ = ("path", "line", "rule", "message")

    def __init__(self, path: str, line: int, rule: str, message: str):
        self.path = path
        self.line = line
        self.rule = rule
        self.message = message

    def __repr__(self):
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


def _knob_names() -> Tuple[frozenset, frozenset]:
    from ..utils import knobs

    return knobs.names(), knobs.SENTINELS


def _suppressions(src: str) -> Dict[int, Tuple[str, str, int]]:
    """line -> (kind, reason, comment_line) for every line a suppression
    comment covers: its own line, and — for a standalone comment — the
    next line."""
    out: Dict[int, Tuple[str, str, int]] = {}
    for i, text in enumerate(src.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        kind, reason = m.group(1), m.group(2).strip()
        out[i] = (kind, reason, i)
        if text.lstrip().startswith("#"):  # standalone: covers the next line
            out[i + 1] = (kind, reason, i)
    return out


class _FileLinter(ast.NodeVisitor):
    def __init__(self, path: str, rel: str, src: str,
                 knob_names: frozenset, sentinels: frozenset,
                 knob_rules_only: bool = False):
        self.path = path
        self.rel = rel  # package-relative path ("utils/retry.py")
        self.src = src
        self.knob_names = knob_names
        self.sentinels = sentinels
        self.suppress = _suppressions(src)
        self.used_suppressions: set = set()
        self.violations: List[Violation] = []
        self.is_knobs = rel == "utils/knobs.py"
        self.is_analysis = rel.startswith("analysis/")
        # tests/ and benchmarks/ ride the KNOB rules only (SRJT001/002):
        # the package-convention rules (taxonomy raises, stub pattern,
        # broad excepts) deliberately do not govern test harness code,
        # and the stale-suppression audit is skipped there too (test
        # fixtures carry suppression syntax inside string literals,
        # which the line scanner cannot tell from live comments)
        self.knob_rules_only = knob_rules_only
        self._func_stack: List[ast.AST] = []

    # -- plumbing ------------------------------------------------------------

    def _flag(self, node, rule: str, message: str) -> None:
        if self.knob_rules_only and rule not in ("SRJT001", "SRJT002"):
            return
        line = getattr(node, "lineno", 1)
        kind = _RULE_SUPPRESSIONS.get(rule)
        sup = self.suppress.get(line)
        if sup is not None and kind is not None and sup[0] == kind:
            _, reason, comment_line = sup
            self.used_suppressions.add(comment_line)
            if not reason:
                self.violations.append(Violation(
                    self.path, comment_line, "SRJT000",
                    f"suppression allow-{kind}() needs a reason",
                ))
            return
        self.violations.append(Violation(self.path, line, rule, message))

    def finish(self) -> None:
        # a suppression nothing matched is stale — reasons rot fast.
        # analysis/ is exempt from the staleness audit only: its
        # docstrings carry the syntax examples.
        if self.knob_rules_only:
            return
        for line, (kind, reason, comment_line) in self.suppress.items():
            if line != comment_line:
                continue  # only audit each comment once
            if kind not in _RULE_SUPPRESSIONS.values():
                self.violations.append(Violation(
                    self.path, comment_line, "SRJT000",
                    f"unknown suppression kind allow-{kind}",
                ))
            elif comment_line in self.used_suppressions:
                continue
            elif not reason:
                self.violations.append(Violation(
                    self.path, comment_line, "SRJT000",
                    f"suppression allow-{kind}() needs a reason",
                ))
            elif not self.is_analysis:
                self.violations.append(Violation(
                    self.path, comment_line, "SRJT000",
                    f"stale suppression allow-{kind}: no suppressible "
                    "violation on this or the next line (the code it "
                    "excused is gone — delete the comment)",
                ))

    # -- SRJT001: undeclared knob literals -----------------------------------

    def _check_knob_literal(self, node, value: str) -> None:
        if self.is_knobs:
            return
        for m in _KNOB_RE.finditer(value):
            tok = m.group(0)
            if tok in self.knob_names or tok in self.sentinels:
                continue
            # "SRJT_RETRY_*" in prose is a family glob over declared
            # knobs, not an undeclared knob
            if (value[m.end():m.end() + 2] in ("_*",)
                    or value[m.end():m.end() + 1] == "*"):
                if any(k.startswith(tok) for k in self.knob_names):
                    continue
            self._flag(node, "SRJT001",
                       f"undeclared knob {tok}: declare it in "
                       "utils/knobs.py (name, type, default, doc)")

    def visit_Constant(self, node: ast.Constant):
        if isinstance(node.value, str) and "SRJT_" in node.value:
            self._check_knob_literal(node, node.value)
        self.generic_visit(node)

    # -- SRJT002: direct environ reads ---------------------------------------

    @staticmethod
    def _is_os_environ(node) -> bool:
        # "_os" covers the `import os as _os` bootstrap idiom — an
        # aliased read is still a direct read
        return (isinstance(node, ast.Attribute) and node.attr == "environ"
                and isinstance(node.value, ast.Name)
                and node.value.id in ("os", "_os")) or (
                    isinstance(node, ast.Name) and node.id == "environ")

    def _environ_read(self, node, key_node) -> None:
        if self.is_knobs:
            return
        if isinstance(key_node, ast.Constant) and isinstance(key_node.value, str):
            if not key_node.value.startswith("SRJT_"):
                return  # PYTHONPATH / JAX_PLATFORMS etc: not ours
            what = f"of {key_node.value}"
        else:
            what = "with a dynamic key"
        self._flag(node, "SRJT002",
                   f"direct os.environ read {what}: SRJT knobs are read "
                   "through utils/knobs.py typed accessors only")

    def visit_Call(self, node: ast.Call):
        f = node.func
        # os.environ.get(...) / environ.get(...)
        if (isinstance(f, ast.Attribute) and f.attr == "get"
                and self._is_os_environ(f.value)):
            self._environ_read(node, node.args[0] if node.args else None)
        # os.getenv(...)
        elif (isinstance(f, ast.Attribute) and f.attr == "getenv"
                and isinstance(f.value, ast.Name) and f.value.id == "os"):
            self._environ_read(node, node.args[0] if node.args else None)
        elif isinstance(f, ast.Name) and f.id == "getenv":
            self._environ_read(node, node.args[0] if node.args else None)
        else:
            self._check_blocking_call(node)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript):
        if isinstance(node.ctx, ast.Load) and self._is_os_environ(node.value):
            self._environ_read(node, node.slice)
        self.generic_visit(node)

    # -- SRJT003: banned raises ----------------------------------------------

    def visit_Raise(self, node: ast.Raise):
        if any(self.rel.startswith(p) or self.rel == p
               for p in _RAISE_GOVERNED):
            exc = node.exc
            name = None
            if isinstance(exc, ast.Call) and isinstance(exc.func, ast.Name):
                name = exc.func.id
            elif isinstance(exc, ast.Name):
                name = exc.id
            if name in ("RuntimeError", "Exception"):
                self._flag(node, "SRJT003",
                           f"raise {name} in a governed module: use the "
                           "utils/errors.py taxonomy (FatalDeviceError / "
                           "RetryableError / DataCorruption / "
                           "DeadlineExceeded) so retry, breaker, and "
                           "failover classification stay correct")
        self.generic_visit(node)

    # -- SRJT004: broad excepts ----------------------------------------------

    @staticmethod
    def _catches_broad(handler: ast.ExceptHandler) -> bool:
        t = handler.type
        if t is None:
            return True  # bare except:
        names = []
        for n in (t.elts if isinstance(t, ast.Tuple) else [t]):
            if isinstance(n, ast.Name):
                names.append(n.id)
        return any(n in ("Exception", "BaseException") for n in names)

    @staticmethod
    def _handler_complies(handler: ast.ExceptHandler) -> bool:
        for sub in ast.walk(handler):
            if isinstance(sub, ast.Raise):
                if sub.exc is None:
                    return True  # bare re-raise
                f = sub.exc
                name = None
                if isinstance(f, ast.Call):
                    fn = f.func
                    if isinstance(fn, ast.Name):
                        name = fn.id
                    elif isinstance(fn, ast.Attribute):
                        name = fn.attr
                elif isinstance(f, ast.Name):
                    name = f.id
                if name in _TAXONOMY:
                    return True
        return False

    def visit_ExceptHandler(self, node: ast.ExceptHandler):
        if self._catches_broad(node) and not self._handler_complies(node):
            self._flag(node, "SRJT004",
                       "broad except must re-raise, wrap into the error "
                       "taxonomy, or carry "
                       "# srjt-lint: allow-broad-except(<reason>)")
        self.generic_visit(node)

    # -- SRJT005: stub discipline --------------------------------------------

    @staticmethod
    def _mentions_gate(stmt) -> bool:
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Name) and sub.id in _GATE_NAMES:
                return True
            if isinstance(sub, ast.Attribute) and sub.attr in _GATE_NAMES:
                return True
        return False

    @staticmethod
    def _alloc_nodes(stmt):
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.JoinedStr):
                yield sub, "f-string"
            elif (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "format"):
                yield sub, ".format() call"
            elif (isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Mod)
                    and isinstance(sub.left, ast.Constant)
                    and isinstance(sub.left.value, str)):
                yield sub, "%-format"
            elif (isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name)
                    and sub.func.id == "open"):
                yield sub, "open() call"

    def _check_stub_function(self, node) -> None:
        gate_idx = None
        for i, stmt in enumerate(node.body):
            if self._mentions_gate(stmt):
                gate_idx = i
                break
        if gate_idx is None:
            return
        for stmt in node.body[:gate_idx]:
            for sub, what in self._alloc_nodes(stmt):
                self._flag(sub, "SRJT005",
                           f"{what} before the enabled-gate check: the "
                           "disabled hot path must stay one boolean "
                           "read (the metrics-stub pattern)")

    # -- SRJT006: blocking calls ---------------------------------------------

    def _check_blocking_call(self, node: ast.Call) -> None:
        if not any(self.rel.startswith(p) or self.rel == p
                   for p in _BLOCKING_GOVERNED):
            return
        f = node.func
        if not isinstance(f, ast.Attribute):
            return
        blocking = (
            (f.attr == "sleep" and isinstance(f.value, ast.Name)
             and f.value.id == "time")
            or f.attr in ("settimeout", "recv", "recvmsg")
        )
        if not blocking:
            return
        fn = self._func_stack[-1] if self._func_stack else None
        if fn is not None and self._deadline_aware(fn):
            return
        self._flag(node, "SRJT006",
                   f"blocking {f.attr}() outside a deadline-aware "
                   "function: route it through the deadline/timeout "
                   "wrappers (utils/deadline.py discipline) or carry "
                   "# srjt-lint: allow-blocking(<reason>)")

    @staticmethod
    def _deadline_aware(fn) -> bool:
        for sub in ast.walk(fn):
            ident = None
            if isinstance(sub, ast.Name):
                ident = sub.id
            elif isinstance(sub, ast.Attribute):
                # the blocking call itself ("settimeout") must not mark
                # its own function deadline-aware
                ident = None if sub.attr == "settimeout" else sub.attr
            elif isinstance(sub, ast.arg):
                ident = sub.arg
            if ident and any(m in ident.lower() for m in _DEADLINE_MARKS):
                return True
        return False

    # -- function scoping ----------------------------------------------------

    def _visit_func(self, node):
        if self.rel in _STUB_MODULES:
            self._check_stub_function(node)
        self._func_stack.append(node)
        self.generic_visit(node)
        self._func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func


def lint_source(src: str, path: str, rel: Optional[str] = None,
                knob_names: Optional[frozenset] = None,
                sentinels: Optional[frozenset] = None,
                knob_rules_only: bool = False) -> List[Violation]:
    """Lint one source blob. ``rel`` is its package-relative path (rule
    scoping); tests pass fixture snippets with a synthetic ``rel``."""
    if knob_names is None or sentinels is None:
        knob_names, sentinels = _knob_names()
    if rel is None:
        rel = os.path.basename(path)
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Violation(path, e.lineno or 1, "SRJT999",
                          f"syntax error: {e.msg}")]
    linter = _FileLinter(path, rel, src, knob_names, sentinels,
                         knob_rules_only=knob_rules_only)
    linter.visit(tree)
    linter.finish()
    return linter.violations


def lint_file(path: str, pkg_root: str, knob_names, sentinels,
              knob_rules_only: bool = False):
    rel = os.path.relpath(path, pkg_root).replace(os.sep, "/")
    with open(path, encoding="utf-8") as f:
        src = f.read()
    return lint_source(src, path, rel, knob_names, sentinels,
                       knob_rules_only=knob_rules_only)


def _discover(pkg_root: str) -> List[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        # __pycache__ is scanner noise, never source (ISSUE 7 satellite)
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                out.append(os.path.join(dirpath, fn))
    return out


# -- SRJT011: rewrite rules must emit verifiable obligations -----------------


_PLAN_SUPPRESS_RE = re.compile(r"#\s*srjt-plan:\s*allow-unverified\s*\((.*)\)")


def _registry_value(tree: ast.AST, name: str):
    """The value expression assigned to module-level ``name`` (plain or
    annotated assignment), or None."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == name
                for t in node.targets):
            return node.value
        if isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.target.id == name:
            return node.value
    return None


def _parse_rules_registry(src: str) -> List[Tuple[str, str]]:
    """(rule name, function name) pairs off the RULES tuple literal."""
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return []
    val = _registry_value(tree, "RULES")
    out: List[Tuple[str, str]] = []
    for elt in getattr(val, "elts", ()):
        if (isinstance(elt, (ast.Tuple, ast.List)) and len(elt.elts) == 2
                and isinstance(elt.elts[0], ast.Constant)
                and isinstance(elt.elts[1], ast.Name)):
            out.append((elt.elts[0].value, elt.elts[1].id))
    return out


def _parse_discharger_registry(src: str) -> frozenset:
    """String keys of the OBLIGATION_DISCHARGERS dict literal."""
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return frozenset()
    val = _registry_value(tree, "OBLIGATION_DISCHARGERS")
    return frozenset(
        k.value for k in getattr(val, "keys", ())
        if isinstance(k, ast.Constant) and isinstance(k.value, str))


def check_rewrite_obligations(rules=None, dischargers=None,
                              src: Optional[str] = None,
                              path: Optional[str] = None) -> List[Violation]:
    """SRJT011 (ISSUE 15): every rewrite function registered in
    ``plan/rewrites.py`` (``RULES`` plus ``prune_columns``) must be
    covered by a translation-validation discharger in
    ``plan/verifier.py`` — i.e. its firings emit obligations the
    verifier can actually discharge — or carry a reasoned
    ``# srjt-plan: allow-unverified(<reason>)`` inside its function
    body. An empty reason is SRJT000; a suppression on a rule that IS
    discharged is a stale SRJT000 (the PR 7 audit discipline).

    The default path is PURELY STATIC: both registries are read off the
    two files' ASTs (``RULES``' literal (name, fn) tuple and
    ``OBLIGATION_DISCHARGERS``' literal dict keys) — importing the plan
    package would drag jax into every lint run, and the analysis tier
    stays import-light by contract. A registry the parse cannot locate
    is itself a violation, so a refactor that breaks the static read
    fails loudly instead of silently passing. The parameters exist for
    fixture injection in tests (``rules`` entries may carry callables
    or function-name strings)."""
    if rules is None:
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        path = os.path.join(pkg, "plan", "rewrites.py")
        pv_path = os.path.join(pkg, "plan", "verifier.py")
        with open(path, encoding="utf-8") as f:
            src = f.read()
        rules = _parse_rules_registry(src)
        if not rules:
            return [Violation(
                path, 1, "SRJT011",
                "could not locate the RULES registry literal — the "
                "SRJT011 static parse needs RULES = ((name, fn), ...) "
                "at module scope")]
        rules = rules + [("prune_columns", "prune_columns")]
        with open(pv_path, encoding="utf-8") as f:
            pv_src = f.read()
        dischargers = _parse_discharger_registry(pv_src)
        if not dischargers:
            return [Violation(
                pv_path, 1, "SRJT011",
                "could not locate the OBLIGATION_DISCHARGERS dict "
                "literal — the SRJT011 static parse needs its string "
                "keys at module scope")]
    dischargers = frozenset(dischargers or ())
    try:
        tree = ast.parse(src, filename=path or "<rewrites>")
    except SyntaxError as e:
        return [Violation(path or "<rewrites>", e.lineno or 1, "SRJT999",
                          f"syntax error: {e.msg}")]
    lines = src.splitlines()
    funcs: Dict[str, Tuple[int, Optional[str], int]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            reason, rline = None, node.lineno
            for ln in range(node.lineno, (node.end_lineno or node.lineno) + 1):
                m = _PLAN_SUPPRESS_RE.search(lines[ln - 1])
                if m:
                    reason, rline = m.group(1).strip(), ln
                    break
            funcs[node.name] = (node.lineno, reason, rline)
    out: List[Violation] = []
    where = path or "<rewrites>"
    for name, fn in rules:
        fname = fn if isinstance(fn, str) else getattr(fn, "__name__", str(fn))
        lineno, reason, rline = funcs.get(fname, (1, None, 1))
        if name in dischargers:
            if reason is not None:
                out.append(Violation(
                    where, rline, "SRJT000",
                    f"stale suppression allow-unverified on rule {name!r}: "
                    "a discharger IS registered in plan/verifier.py — "
                    "delete the comment"))
            continue
        if reason is None:
            out.append(Violation(
                where, lineno, "SRJT011",
                f"rewrite rule {name!r} has no translation-validation "
                "discharger in plan/verifier.py OBLIGATION_DISCHARGERS: "
                "its firings are unverifiable — register one or carry "
                "# srjt-plan: allow-unverified(<reason>)"))
        elif not reason:
            out.append(Violation(
                where, rline, "SRJT000",
                f"suppression allow-unverified() on rule {name!r} needs "
                "a reason"))
    return out


# -- SRJT007: registry <-> doc-table drift ----------------------------------


def check_docs(repo_root: str, knob_names: Optional[frozenset] = None,
               sentinels: Optional[frozenset] = None) -> List[Violation]:
    if knob_names is None or sentinels is None:
        knob_names, sentinels = _knob_names()
    docs = [p for p in ("README.md", "PACKAGING.md")
            if os.path.exists(os.path.join(repo_root, p))]
    out: List[Violation] = []
    if not docs:
        return out
    tabled: set = set()  # knobs appearing in an actual table row
    for doc in docs:
        path = os.path.join(repo_root, doc)
        with open(path, encoding="utf-8") as f:
            for lineno, line in enumerate(f, start=1):
                is_row = line.lstrip().startswith("|")
                for tok in _KNOB_RE.findall(line):
                    if is_row:
                        tabled.add(tok)
                    if tok in knob_names or tok in sentinels:
                        continue
                    # prose/diagram allowance only: ASCII diagrams wrap
                    # long names, so a strict prefix of a declared knob
                    # is a wrapped reference there — inside a knob
                    # TABLE row the name must be exact (a truncated
                    # name in the table IS the drift this rule exists
                    # to catch)
                    if not is_row and any(k.startswith(tok)
                                          for k in knob_names):
                        continue
                    out.append(Violation(
                        path, lineno, "SRJT007",
                        f"documented knob {tok} is not declared in "
                        "utils/knobs.py (typo, or a knob that was "
                        "removed from the code?)"))
    for name in sorted(knob_names):
        # a prose mention is not documentation: the knob must sit in a
        # markdown table row (the operator-facing knob tables)
        if name not in tabled:
            out.append(Violation(
                os.path.join(repo_root, "README.md"), 1, "SRJT007",
                f"declared knob {name} appears in no README.md/"
                "PACKAGING.md knob-table row (add it to a knob table; "
                "--knob-table renders the registry)"))
    return out


def run(pkg_root: Optional[str] = None,
        with_docs: bool = True,
        with_harness: bool = True,
        with_plan: bool = True) -> List[Violation]:
    real_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if pkg_root is None:
        pkg_root = real_root
    knob_names, sentinels = _knob_names()
    violations: List[Violation] = []
    if with_plan and os.path.abspath(pkg_root) == real_root:
        # SRJT011 is a cross-file check over the REAL plan modules; a
        # fixture pkg_root must not drag the live tree into its run
        violations.extend(check_rewrite_obligations())
    for path in _discover(pkg_root):
        violations.extend(lint_file(path, pkg_root, knob_names, sentinels))
    if with_harness:
        # ISSUE 11 satellite: tests/ and benchmarks/ honor the knob
        # registry too (SRJT001/002 only — see _FileLinter) so a test
        # reading an SRJT env var directly, or inventing an undeclared
        # knob name, fails the same gate the package does
        repo_root = os.path.dirname(pkg_root)
        for sub in ("tests", "benchmarks"):
            d = os.path.join(repo_root, sub)
            if not os.path.isdir(d):
                continue
            for path in _discover(d):
                rel = sub + "/" + os.path.relpath(path, d).replace(os.sep, "/")
                with open(path, encoding="utf-8") as f:
                    src = f.read()
                violations.extend(lint_source(
                    src, path, rel, knob_names, sentinels,
                    knob_rules_only=True,
                ))
    if with_docs:
        violations.extend(check_docs(os.path.dirname(pkg_root),
                                     knob_names, sentinels))
    violations.sort(key=lambda v: (v.path, v.line))
    return violations


# -- machine-readable findings (ISSUE 11 satellite) ---------------------------


def format_findings(violations: List[Violation], fmt: str,
                    tool: str = "srjt-lint") -> str:
    """Render findings as ``text`` / ``json`` / ``sarif``. Every format
    carries the same (path, line, rule, message) tuples; premerge
    archives the sarif next to the other artifacts."""
    if fmt == "text":
        return "\n".join(repr(v) for v in violations)
    if fmt == "json":
        return json.dumps({
            "tool": tool,
            "findings": [
                {"path": v.path, "line": v.line, "rule": v.rule,
                 "message": v.message}
                for v in violations
            ],
        }, indent=1)
    if fmt == "sarif":
        # SARIF consumers anchor results by RELATIVE uri: strip the
        # repo root off the absolute paths run() produces (paths from
        # elsewhere — tmpdirs, fixtures — pass through unchanged)
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))) + os.sep

        def _uri(path: str) -> str:
            if path.startswith(repo_root):
                path = path[len(repo_root):]
            return path.replace(os.sep, "/")

        rules = sorted({v.rule for v in violations})
        return json.dumps({
            "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                        "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
            "version": "2.1.0",
            "runs": [{
                "tool": {"driver": {
                    "name": tool,
                    "rules": [{"id": r} for r in rules],
                }},
                "results": [
                    {
                        "ruleId": v.rule,
                        "level": "error",
                        "message": {"text": v.message},
                        "locations": [{
                            "physicalLocation": {
                                "artifactLocation": {
                                    "uri": _uri(v.path),
                                },
                                "region": {"startLine": max(v.line, 1)},
                            },
                        }],
                    }
                    for v in violations
                ],
            }],
        }, indent=1)
    raise ValueError(f"unknown findings format {fmt!r}")


def write_findings(violations: List[Violation], fmt: str,
                   out: Optional[str], tool: str) -> int:
    """Emit findings and return the EXIT CODE — identical across every
    format (the text-mode contract: 1 on any violation, else 0). With
    ``--out`` the formatted findings land in the file and stdout gets
    the one-line summary; without it they go to stdout."""
    body = format_findings(violations, fmt, tool)
    if out:
        d = os.path.dirname(out)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(out, "w", encoding="utf-8") as f:
            f.write(body + "\n")
    elif body:
        print(body)
    if violations:
        print(f"{tool}: {len(violations)} violation(s)"
              + (f" -> {out}" if out else ""), file=sys.stderr)
        return 1
    print(f"{tool}: clean" + (f" -> {out}" if out else ""))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m spark_rapids_jni_tpu.analysis.lint",
        description="srjt-lint: invariant lint suite (ISSUE 7)")
    ap.add_argument("--root", default=None,
                    help="package root to lint (default: the installed "
                    "spark_rapids_jni_tpu directory)")
    ap.add_argument("--no-docs", action="store_true",
                    help="skip the README/PACKAGING knob-table drift check")
    ap.add_argument("--no-harness", action="store_true",
                    help="skip the tests/ + benchmarks/ knob-rule scan")
    ap.add_argument("--no-plan", action="store_true",
                    help="skip the SRJT011 rewrite-obligation coverage "
                    "check over plan/rewrites.py")
    ap.add_argument("--knob-table", action="store_true",
                    help="print the registry as a markdown table and exit")
    ap.add_argument("--format", default="text",
                    choices=("text", "json", "sarif"),
                    help="findings format (exit code is identical in "
                    "every mode)")
    ap.add_argument("--out", default=None,
                    help="also write the formatted findings to this path "
                    "(stdout then carries the one-line summary)")
    args = ap.parse_args(argv)
    if args.knob_table:
        from ..utils import knobs

        print(knobs.markdown_table())
        return 0
    violations = run(args.root, with_docs=not args.no_docs,
                     with_harness=not args.no_harness,
                     with_plan=not args.no_plan)
    return write_findings(violations, args.format, args.out, "srjt-lint")


if __name__ == "__main__":
    sys.exit(main())

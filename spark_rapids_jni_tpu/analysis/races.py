"""``srjt-race`` layer 1: static guarded-by inference for the
concurrent substrate (ISSUE 11; stdlib ``ast`` only, like srjt-lint).

srjt-lint (SRJT001-007) checks conventions and lockdep proves the lock
GRAPH is acyclic — but nothing proved that shared fields are actually
*guarded*: a field read under ``self._lock`` in one method and bare in
another was invisible. This pass infers, per class in the governed
concurrent modules, which ``self._*`` attributes are accessed inside
``with self._lock:`` (or condition) blocks vs. bare, and enforces:

    SRJT008 mixed-guard        an attribute with at least one guarded
                               access, at least one bare access, and at
                               least one write outside ``__init__`` is
                               a data race waiting for a scheduler:
                               guard every access or annotate why not.
    SRJT009 check-then-act     a branch test reads a guarded attribute
                               WITHOUT its lock and the same function
                               writes that attribute: the classic
                               read->branch->write split across lock
                               boundaries (the check is stale by the
                               time the act runs).
    SRJT010 bare-global-mutate a mutable module global (dict/list/set
                               assigned at module scope) mutated from a
                               function body with no lock in scope.

Inference rules (documented limits, not bugs):

- A lock attribute is one assigned ``threading.Lock/RLock/Condition``
  in the class (``self._lock = threading.Lock()``). A Condition built
  OVER another lock attribute (``threading.Condition(self._lock)``)
  aliases it: holding either guards the same state.
- A method whose name ends in ``_locked`` is the repo's caller-holds-
  the-lock convention: its accesses count as guarded (by the caller).
- ``__init__``/``__new__`` accesses never count toward the mix — the
  constructor happens-before every reader by construction — but they
  do anchor suppression comments for the whole attribute.
- Accesses inside nested functions/lambdas count as BARE (they execute
  later, outside the lexical with-block).
- Attribute state reached through other names (``w.alive`` from pool
  methods, class attrs via the class name) is layer 2's job — the
  dynamic detector in lockdep.py tracks those objects at runtime.

Suppression syntax (on the flagged line, the line above it, or ANY
access line of the attribute — including its ``__init__`` assignment,
the canonical spot for attribute-wide annotations)::

    self._flag = False  # srjt-race: allow-unguarded(single machine-word poll; GIL-atomic)
    self._entries       # srjt-race: guarded-by(_lock)

``guarded-by(<lock>)`` documents a discipline the inference cannot see
(caller-held locks, cross-object conditions); ``allow-unguarded``
documents why no lock is needed. An empty reason/lock name is SRJT000,
and a suppression matching no violation is a stale SRJT000, exactly as
in srjt-lint (analysis/ is exempt from the stale audit only: these
docstrings carry the syntax examples).

Run ``python -m spark_rapids_jni_tpu.analysis.races`` from the repo
root (exit 1 on any violation); ``--format=json`` / ``--format=sarif``
emit machine-readable findings with the same exit code.
"""

from __future__ import annotations

import argparse
import ast
import os
import re
import sys
from typing import Dict, List, Optional, Set, Tuple

from .lint import Violation, _discover, format_findings, write_findings

__all__ = ["run", "scan_source", "main", "RACE_GOVERNED"]

# the governed concurrent modules (package-relative path fragments):
# exactly the substrate PRs 4-9 built — everything with a lock worth
# proving
RACE_GOVERNED = (
    # srjt-durable (ISSUE 20): serve/journal.py (the QueryJournal
    # _lock serializing append/replay against scheduler worker
    # threads) and memgov/persist.py (manifest writes under the
    # catalog lock, the startup re-attach scan) ride these two
    # prefixes — no new entries needed
    "serve/",
    "sidecar_pool.py",
    "sidecar.py",
    "memgov/",
    "parallel/shuffle.py",
    # ISSUE 16: the cluster membership layer — ClusterView's state map,
    # generation, and recovery-dedup set are written by the heartbeat
    # thread and read by every exchanging thread; the _lock discipline
    # is worth proving
    "parallel/cluster.py",
    "utils/metrics.py",
    "utils/deadline.py",
    # ISSUE 12: the srjt-trace span layer — TraceContext's span buffer
    # and the sink's recorder/log state are cross-thread (hedge legs,
    # slot threads) and carry their own locks worth proving
    "utils/tracing.py",
    "utils/trace_sink.py",
    # ISSUE 14: the plan compiler — CompiledPlan objects are submitted
    # to the concurrent serving runtime, so their state discipline
    # (per-run contexts, no shared mutable caches) is worth proving
    "plan/",
    # ISSUE 17: the serving-tier caches — the single-flight map, the
    # plan-cache LRU, and the subresult LRU are crossed by every serve
    # slot racing on one key; their lock discipline is worth proving
    "cache/",
)

_SUPPRESS_RE = re.compile(
    r"#\s*srjt-race:\s*(guarded-by|allow-unguarded)\s*\((.*?)\)\s*(#.*)?$"
)

# container methods that MUTATE their receiver: a call through a
# guarded attribute is a write to the guarded structure
_MUTATORS = frozenset({
    "append", "appendleft", "add", "update", "pop", "popleft", "popitem",
    "remove", "discard", "clear", "extend", "insert", "setdefault",
    "__setitem__", "__delitem__",
})

_MUTABLE_CALLS = frozenset({
    "dict", "list", "set", "deque", "defaultdict", "OrderedDict", "Counter",
})

# methods whose accesses count as guarded-by-the-caller (repo
# convention); matched by suffix
_LOCKED_SUFFIX = "_locked"
_CALLER_GUARD = "<caller>"


class _Access:
    __slots__ = ("attr", "line", "write", "guards", "func", "in_init",
                 "in_branch_test")

    def __init__(self, attr, line, write, guards, func, in_init,
                 in_branch_test=False):
        self.attr = attr
        self.line = line
        self.write = write
        self.guards = guards  # frozenset of canonical guard names held
        self.func = func
        self.in_init = in_init
        self.in_branch_test = in_branch_test


def _suppressions(src: str) -> Dict[int, Tuple[str, str, int]]:
    """line -> (kind, text, comment_line); a standalone comment also
    covers the next line (same contract as srjt-lint)."""
    out: Dict[int, Tuple[str, str, int]] = {}
    for i, text in enumerate(src.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        kind, arg = m.group(1), m.group(2).strip()
        out[i] = (kind, arg, i)
        if text.lstrip().startswith("#"):
            out[i + 1] = (kind, arg, i)
    return out


def _is_self_attr(node) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and node.attr.startswith("_")
            and not node.attr.startswith("__")):
        return node.attr
    return None


def _is_lock_ctor(node) -> Optional[str]:
    """'lock' | 'condition' when node is threading.Lock()/RLock()/
    Condition(...) (or the bare-name import spelling)."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    name = None
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id == "threading":
        name = f.attr
    elif isinstance(f, ast.Name):
        name = f.id
    if name in ("Lock", "RLock"):
        return "lock"
    if name == "Condition":
        return "condition"
    return None


class _ClassScan:
    """One class's inferred guard map: lock attrs, condition aliases,
    and every self._* access with its held-guard context."""

    def __init__(self, name: str):
        self.name = name
        self.locks: Set[str] = set()
        self.alias: Dict[str, str] = {}  # condition attr -> canonical lock
        self.accesses: List[_Access] = []

    def canonical(self, attr: str) -> str:
        return self.alias.get(attr, attr)


class _FuncWalker(ast.NodeVisitor):
    """Walk one method body tracking the lexically-held self-locks."""

    def __init__(self, scan: _ClassScan, func_name: str):
        self.scan = scan
        self.func = func_name
        self.in_init = func_name in ("__init__", "__new__")
        base = {_CALLER_GUARD} if func_name.endswith(_LOCKED_SUFFIX) else set()
        self.held: Set[str] = base
        self._skip: Set[int] = set()  # Attribute nodes already classified
        self._test_depth = 0

    def _record(self, attr: str, line: int, write: bool) -> None:
        if attr in self.scan.locks or attr in self.scan.alias:
            return  # the locks themselves are not guarded state
        self.scan.accesses.append(_Access(
            attr, line, write, frozenset(self.held), self.func,
            self.in_init, in_branch_test=self._test_depth > 0,
        ))

    # -- guard context -------------------------------------------------------

    def _with_guards(self, node):
        added = []
        for item in node.items:
            attr = _is_self_attr(item.context_expr)
            if attr and (attr in self.scan.locks or attr in self.scan.alias):
                g = self.scan.canonical(attr)
                if g not in self.held:
                    self.held.add(g)
                    added.append(g)
        for item in node.items:
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        for g in added:
            self.held.discard(g)

    visit_With = _with_guards
    visit_AsyncWith = _with_guards

    def _nested_func(self, node):
        # a def-closure defined here EXECUTES later (thread targets,
        # callbacks), outside this lexical lock context: its accesses
        # count as bare
        saved, self.held = self.held, set()
        for stmt in node.body:
            self.visit(stmt)
        self.held = saved

    visit_FunctionDef = _nested_func
    visit_AsyncFunctionDef = _nested_func

    def visit_Lambda(self, node: ast.Lambda):
        # lambdas in this codebase are sort/min keys and default
        # factories that run IN PLACE — they keep the held context
        # (a lambda stashed for deferred execution is rare enough to
        # annotate by hand)
        self.visit(node.body)

    # -- branch tests (SRJT009 raw material) ---------------------------------

    def _branch(self, node):
        self._test_depth += 1
        self.visit(node.test)
        self._test_depth -= 1
        for stmt in node.body:
            self.visit(stmt)
        for stmt in node.orelse:
            self.visit(stmt)

    visit_If = _branch
    visit_While = _branch

    def visit_IfExp(self, node: ast.IfExp):
        self._test_depth += 1
        self.visit(node.test)
        self._test_depth -= 1
        self.visit(node.body)
        self.visit(node.orelse)

    # -- access classification -----------------------------------------------

    def visit_Subscript(self, node: ast.Subscript):
        attr = _is_self_attr(node.value)
        if attr is not None and isinstance(node.ctx, (ast.Store, ast.Del)):
            # self._x[k] = v / del self._x[k]: a write to the structure
            self._record(attr, node.value.lineno, True)
            self._skip.add(id(node.value))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
            attr = _is_self_attr(f.value)
            if attr is not None:
                self._record(attr, f.value.lineno, True)
                self._skip.add(id(f.value))
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute):
        if id(node) not in self._skip:
            attr = _is_self_attr(node)
            if attr is not None:
                self._record(
                    attr, node.lineno,
                    isinstance(node.ctx, (ast.Store, ast.Del)),
                )
        self.generic_visit(node)


class _ModuleScan(ast.NodeVisitor):
    """Collect per-class access maps + module-global mutation sites."""

    def __init__(self):
        self.classes: List[_ClassScan] = []
        self.globals: Dict[str, int] = {}  # name -> declaration line
        self.global_mutations: List[Tuple[str, int, bool]] = []  # (name, line, locked)

    def visit_ClassDef(self, node: ast.ClassDef):
        scan = _ClassScan(node.name)
        # pass 1: find the lock attributes (any method, usually __init__)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                attr = _is_self_attr(sub.targets[0])
                if attr is None:
                    continue
                kind = _is_lock_ctor(sub.value)
                if kind == "lock":
                    scan.locks.add(attr)
                elif kind == "condition":
                    over = (sub.value.args[0] if sub.value.args else None)
                    over_attr = _is_self_attr(over)
                    if over_attr:
                        scan.alias[attr] = over_attr
                        scan.locks.add(over_attr)
                    else:
                        scan.locks.add(attr)  # Condition() owns its lock
        # pass 2: walk each method with guard context
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walker = _FuncWalker(scan, stmt.name)
                for s in stmt.body:
                    walker.visit(s)
        self.classes.append(scan)
        # nested classes are rare; don't recurse into them twice

    def visit_Module(self, node: ast.Module):
        for stmt in node.body:
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target]
                value = stmt.value
                if value is None:
                    continue
                mutable = isinstance(value, (
                    ast.Dict, ast.List, ast.Set, ast.DictComp, ast.ListComp,
                    ast.SetComp,
                )) or (
                    isinstance(value, ast.Call)
                    and isinstance(value.func, ast.Name)
                    and value.func.id in _MUTABLE_CALLS
                )
                if mutable:
                    for t in targets:
                        if isinstance(t, ast.Name):
                            self.globals[t.id] = stmt.lineno
            self.visit(stmt)
        # find mutations of those names inside every function body
        if self.globals:
            for stmt in ast.walk(node):
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._scan_func_for_global_mutations(stmt)

    def _scan_func_for_global_mutations(self, fn) -> None:
        local = {a.arg for a in fn.args.args + fn.args.kwonlyargs}
        if fn.args.vararg:
            local.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            local.add(fn.args.kwarg.arg)
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign):
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        local.add(t.id)
        mutated: List[Tuple[str, ast.AST]] = []
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Subscript) \
                    and isinstance(sub.ctx, (ast.Store, ast.Del)) \
                    and isinstance(sub.value, ast.Name):
                mutated.append((sub.value.id, sub))
            elif isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in _MUTATORS \
                    and isinstance(sub.func.value, ast.Name):
                mutated.append((sub.func.value.id, sub))
        if not mutated:
            return
        locked_lines = self._locked_lines(fn)
        for name, node in mutated:
            if name in self.globals and name not in local:
                self.global_mutations.append(
                    (name, node.lineno, node.lineno in locked_lines)
                )

    @staticmethod
    def _locked_lines(fn) -> Set[int]:
        """Source lines inside any with-block whose context manager is
        a bare name/attribute (the lock-ish heuristic: ``with _lock:``,
        ``with self._cond:`` — never ``with open(...)``)."""
        lines: Set[int] = set()
        for sub in ast.walk(fn):
            if isinstance(sub, (ast.With, ast.AsyncWith)) and any(
                isinstance(i.context_expr, (ast.Name, ast.Attribute))
                for i in sub.items
            ):
                end = getattr(sub, "end_lineno", None) or sub.lineno
                lines.update(range(sub.lineno, end + 1))
        return lines


class _SourceRaceLinter:
    def __init__(self, path: str, rel: str, src: str):
        self.path = path
        self.rel = rel
        self.src = src
        self.suppress = _suppressions(src)
        self.used: Set[int] = set()
        self.violations: List[Violation] = []
        self.is_analysis = rel.startswith("analysis/")

    # -- suppression plumbing ------------------------------------------------

    def _suppression_for(self, lines) -> Optional[Tuple[str, str, int]]:
        """The first matching srjt-race suppression covering any of
        ``lines`` (each line is covered by a comment on it or directly
        above it — _suppressions already encodes that)."""
        for ln in lines:
            sup = self.suppress.get(ln)
            if sup is not None:
                return sup
        return None

    def _flag(self, line: int, rule: str, message: str,
              anchor_lines=None) -> None:
        sup = self._suppression_for([line] + list(anchor_lines or []))
        if sup is not None:
            kind, arg, comment_line = sup
            self.used.add(comment_line)
            if not arg:
                self.violations.append(Violation(
                    self.path, comment_line, "SRJT000",
                    f"suppression {kind}() needs a "
                    + ("lock name" if kind == "guarded-by" else "reason"),
                ))
            return
        self.violations.append(Violation(self.path, line, rule, message))

    def finish(self) -> None:
        for line, (kind, arg, comment_line) in self.suppress.items():
            if line != comment_line or comment_line in self.used:
                continue
            if not arg:
                self.violations.append(Violation(
                    self.path, comment_line, "SRJT000",
                    f"suppression {kind}() needs a "
                    + ("lock name" if kind == "guarded-by" else "reason"),
                ))
            elif not self.is_analysis:
                self.violations.append(Violation(
                    self.path, comment_line, "SRJT000",
                    f"stale suppression srjt-race: {kind}: no "
                    "suppressible violation anchors here (the access "
                    "pattern it excused is gone — delete the comment)",
                ))

    # -- the rules -----------------------------------------------------------

    def scan(self) -> List[Violation]:
        try:
            tree = ast.parse(self.src, filename=self.path)
        except SyntaxError as e:
            return [Violation(self.path, e.lineno or 1, "SRJT999",
                              f"syntax error: {e.msg}")]
        mod = _ModuleScan()
        mod.visit(tree)
        for scan in mod.classes:
            self._check_class(scan)
        self._check_globals(mod)
        self.finish()
        return self.violations

    def _check_class(self, scan: _ClassScan) -> None:
        by_attr: Dict[str, List[_Access]] = {}
        for a in scan.accesses:
            by_attr.setdefault(a.attr, []).append(a)
        for attr, accs in sorted(by_attr.items()):
            anchor = sorted({a.line for a in accs})
            live = [a for a in accs if not a.in_init]
            guarded = [a for a in live if a.guards]
            bare = [a for a in live if not a.guards]
            writes = [a for a in live if a.write]
            # SRJT008: mixed guarded/bare with a real (post-init) write
            if guarded and bare and writes:
                guards = sorted({g for a in guarded for g in a.guards})
                bare_lines = sorted({a.line for a in bare})
                shown = ", ".join(str(x) for x in bare_lines[:4])
                if len(bare_lines) > 4:
                    shown += ", ..."
                self._flag(
                    bare_lines[0], "SRJT008",
                    f"{scan.name}.{attr}: mixed guarded/unguarded access "
                    f"— {len(guarded)} access(es) under "
                    f"{'/'.join(guards)} but {len(bare_lines)} bare line(s) "
                    f"({shown}) and the attribute is written after "
                    "__init__: guard every access, or annotate "
                    "# srjt-race: guarded-by(<lock>) / "
                    "allow-unguarded(<reason>)",
                    anchor_lines=anchor,
                )
            # SRJT009: check-then-act — a branch test reads the guarded
            # attribute without its PROTECTING lock while the same
            # function writes it. The protecting set is inferred from
            # the locks held at WRITE sites (a read under some other
            # lock is still an unprotected check); caller-held guards
            # (<caller>, the _locked convention) cannot be named, so
            # any-locked reads pass there.
            if not guarded:
                continue
            write_guards = {g for a in guarded if a.write for g in a.guards}
            guard_set = write_guards or {g for a in guarded for g in a.guards}
            writer_funcs = {a.func for a in accs if a.write}
            for a in live:
                if not a.in_branch_test or a.write:
                    continue
                if a.guards and (a.guards & guard_set
                                 or _CALLER_GUARD in guard_set):
                    continue  # checked under (one of) its locks
                if a.func not in writer_funcs:
                    continue  # read-only function: no act to race the check
                self._flag(
                    a.line, "SRJT009",
                    f"{scan.name}.{attr}: check-then-act — branch test "
                    f"reads this {'/'.join(sorted(guard_set))}-guarded "
                    f"attribute without the lock while {a.func}() also "
                    "writes it; by the time the branch acts the check is "
                    "stale. Take the lock around the read-decide-write "
                    "sequence, or annotate "
                    "# srjt-race: allow-unguarded(<reason>)",
                    anchor_lines=anchor,
                )

    def _check_globals(self, mod: _ModuleScan) -> None:
        for name, line, locked in sorted(mod.global_mutations,
                                         key=lambda x: x[1]):
            if locked:
                continue
            decl = mod.globals[name]
            self._flag(
                line, "SRJT010",
                f"module global {name!r} (a mutable container declared at "
                f"line {decl}) is mutated here with no lock in scope: any "
                "two threads through this function race the container. "
                "Wrap the mutation in its lock, or annotate "
                "# srjt-race: guarded-by(<lock>) / "
                "allow-unguarded(<reason>)",
                anchor_lines=[decl],
            )


def scan_source(src: str, path: str, rel: Optional[str] = None
                ) -> List[Violation]:
    """Race-lint one source blob; ``rel`` scopes it (tests pass
    synthetic fixture paths)."""
    if rel is None:
        rel = os.path.basename(path)
    return _SourceRaceLinter(path, rel, src).scan()


def _governed(rel: str) -> bool:
    return any(rel.startswith(p) or rel == p for p in RACE_GOVERNED)


def run(pkg_root: Optional[str] = None) -> List[Violation]:
    if pkg_root is None:
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    violations: List[Violation] = []
    for path in _discover(pkg_root):
        rel = os.path.relpath(path, pkg_root).replace(os.sep, "/")
        if not _governed(rel):
            continue
        with open(path, encoding="utf-8") as f:
            src = f.read()
        violations.extend(scan_source(src, path, rel))
    violations.sort(key=lambda v: (v.path, v.line))
    return violations


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m spark_rapids_jni_tpu.analysis.races",
        description="srjt-race layer 1: static guarded-by inference "
        "(SRJT008/009/010) over the governed concurrent modules")
    ap.add_argument("--root", default=None,
                    help="package root to scan (default: the installed "
                    "spark_rapids_jni_tpu directory)")
    ap.add_argument("--format", default="text",
                    choices=("text", "json", "sarif"),
                    help="findings format (exit code is identical in "
                    "every mode)")
    ap.add_argument("--out", default=None,
                    help="also write the formatted findings to this path "
                    "(stdout then carries the one-line summary)")
    args = ap.parse_args(argv)
    violations = run(args.root)
    return write_findings(violations, args.format, args.out, "srjt-race")


if __name__ == "__main__":
    sys.exit(main())

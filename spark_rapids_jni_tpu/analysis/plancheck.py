"""``srjt-plancheck``: verify every checked-in plan (ISSUE 15).

The CLI front door of the plan-verification tier (the verifier itself
lives in ``plan/verifier.py`` — see that docstring for the PLAN00x rule
catalog). For every query in the ``models/tpcds_plans.py`` registry,
plus the hand-built greens re-expressed as plans (q3/q55), this tool:

1. binds small generator tables and checks the RAW plan's
   well-formedness (sugar nodes allowed — the optimizer owns them),
2. compiles it (rewrite fixpoint + lowering, no execution) and checks
   the OPTIMIZED plan with sugar banned (PLAN004),
3. discharges every rewrite obligation the engine emitted
   (translation validation, PLAN006),
4. checks per-stage ``memory_bytes`` estimate presence/monotonicity and
   the plan-level peak (PLAN005).

Run ``python -m spark_rapids_jni_tpu.analysis.plancheck`` from the repo
root: exit 1 on any violation, ``--format=json|sarif`` through the
shared emitters in ``lint.py`` (exit-code parity with text mode), and
``--report <path>`` appends one JSON line per verified plan — the
``artifacts/plan_verify.jsonl`` contract the ci/premerge.sh static tier
gates on. The differential fuzzer is the sibling CLI,
``python -m spark_rapids_jni_tpu.analysis.planfuzz``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

from .lint import write_findings

__all__ = ["run", "main", "catalog_of"]


def catalog_of(tables) -> Dict[str, dict]:
    return {t: {n: c.dtype for n, c in zip(tbl.names, tbl.columns)}
            for t, tbl in tables.items()}


def _targets(rows: int, queries: Optional[List[str]]) -> List[Tuple[str, dict, object]]:
    """(name, bound tables, raw plan) for every checked-in plan: the
    whole PLAN_QUERIES registry plus the two re-expressed hand-built
    greens. Imports are lazy — the analysis package must stay
    import-light (jax only loads when a plan check actually runs)."""
    from ..models import tpcds
    from ..models import tpcds_plans as tp

    known = set(tp.PLAN_QUERIES) | {"q3", "q55", "q3x4", "q55x4"}
    unknown = sorted(set(queries or ()) - known)
    if unknown:
        # a typo'd --queries must fail loudly, never verify an empty
        # set and report clean
        raise SystemExit(
            f"srjt-plancheck: unknown plan name(s) {unknown}; the "
            f"registry has {sorted(known)}")
    out = []
    for name, d in tp.PLAN_QUERIES.items():
        if queries and name not in queries:
            continue
        out.append((name, d.gen(rows), d.plan()))
    if not queries or "q3" in (queries or ()):
        out.append(("q3", tpcds.gen_store(rows, seed=11), tp.q3_plan()))
    if not queries or "q55" in (queries or ()):
        out.append(("q55", tpcds.gen_store(rows, seed=12), tp.q55_plan()))
    # the 4-rank distributed variants (ISSUE 16): same plans with
    # exchange stages inserted, verified like any other stage — the
    # verifier must accept what the cluster tier actually runs
    from ..plan.distribute import insert_exchanges

    if not queries or "q3x4" in (queries or ()):
        out.append(("q3x4", tpcds.gen_store(rows, seed=11),
                    insert_exchanges(tp.q3_plan(), 4)))
    if not queries or "q55x4" in (queries or ()):
        out.append(("q55x4", tpcds.gen_store(rows, seed=12),
                    insert_exchanges(tp.q55_plan(), 4)))
    return out


def check_plan(name: str, tables, ir) -> Tuple[list, dict]:
    """Run all three verification layers over one bound plan. Returns
    (violations, report-record). Compilation is skipped when the raw
    plan is already malformed (one defect, one finding)."""
    from .. import plan as P

    where = f"plan:{name}"
    catalog = catalog_of(tables)
    violations = P.verify_plan(ir, catalog, desugared=False, where=where)
    record = {"kind": "plan", "query": name, "obligations": 0,
              "rewrites": {}, "est_peak_bytes": 0, "stages": 0}
    if not violations:
        cp = P.compile_ir(ir, tables, name=name)
        violations += P.verify_plan(cp.optimized, catalog, desugared=True,
                                    where=where)
        violations += P.verify_obligations(cp.obligations, catalog,
                                           where=where)
        violations += P.verify_estimates(cp, where=where)
        record.update(
            obligations=len(cp.obligations),
            rewrites=cp.rewrites_fired,
            est_peak_bytes=cp.estimated_memory_bytes,
            stages=len(cp.stages),
        )
    record["violations"] = len(violations)
    record["rules"] = sorted({v.rule for v in violations})
    return violations, record


def run(rows: int = 256, queries: Optional[List[str]] = None,
        report: Optional[str] = None) -> Tuple[list, List[dict]]:
    violations: list = []
    records: List[dict] = []
    for name, tables, ir in _targets(rows, queries):
        vs, rec = check_plan(name, tables, ir)
        violations += vs
        records.append(rec)
    if report:
        d = os.path.dirname(report)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(report, "a", encoding="utf-8") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
    return violations, records


def main(argv=None) -> int:
    from ..utils import knobs

    ap = argparse.ArgumentParser(
        prog="python -m spark_rapids_jni_tpu.analysis.plancheck",
        description="srjt-plancheck: plan-IR verifier + per-rewrite "
                    "translation validation over every checked-in plan "
                    "(ISSUE 15)")
    ap.add_argument("--rows", type=int,
                    default=knobs.get_int("SRJT_PLANCHECK_ROWS"),
                    help="rows bound per generator when compiling the "
                    "checked-in plans (no execution happens)")
    ap.add_argument("--queries", default=None,
                    help="comma-separated subset of plan names "
                    "(default: the whole registry + q3/q55)")
    ap.add_argument("--report", default=None,
                    help="append one JSON line per verified plan to this "
                    "path (the artifacts/plan_verify.jsonl contract)")
    ap.add_argument("--format", default="text",
                    choices=("text", "json", "sarif"),
                    help="findings format (exit code is identical in "
                    "every mode)")
    ap.add_argument("--out", default=None,
                    help="also write the formatted findings to this path")
    args = ap.parse_args(argv)
    queries = args.queries.split(",") if args.queries else None
    violations, _ = run(rows=args.rows, queries=queries, report=args.report)
    return write_findings(violations, args.format, args.out,
                          "srjt-plancheck")


if __name__ == "__main__":
    sys.exit(main())

"""Correctness tooling for the concurrent substrate (ISSUEs 7 + 11).

Three layers, all gated in ci/premerge.sh (full reference:
ANALYSIS.md at the repo root):

- ``lint.py`` — ``srjt-lint``, an AST static pass (stdlib ``ast``, no
  new deps) enforcing the repo's hand-enforced conventions
  (SRJT000-007): the central knob registry (utils/knobs.py, scanned
  across the package PLUS tests/ and benchmarks/), the error-taxonomy
  raise/except discipline, the metrics/spill hot-path stub pattern,
  deadline cooperation for blocking calls, and registry<->doc drift.
  Run as ``python -m spark_rapids_jni_tpu.analysis.lint``.
- ``races.py`` — ``srjt-race`` layer 1 (SRJT008-010): static
  guarded-by inference over the concurrent modules — per class, which
  ``self._*`` attributes are accessed under ``with self._lock:`` vs
  bare — flagging mixed-guard access, check-then-act splits, and bare
  mutable-global mutation. Run as
  ``python -m spark_rapids_jni_tpu.analysis.races``.
- ``plancheck.py`` / ``planfuzz.py`` — ``srjt-plancheck`` (ISSUE 15):
  the plan-verification tier's CLIs. plancheck runs the
  ``plan/verifier.py`` rules (PLAN001-006: well-formedness,
  per-rewrite translation-validation obligations, estimate
  consistency) over every checked-in plan in
  ``models/tpcds_plans.py``; planfuzz generates seeded typed plans
  over the TPC-DS generator schemas, executes them through
  rewrite->compile->run against a direct-plan-interpretation oracle,
  and bisects any mismatch (PLAN007) to the first semantics-breaking
  rewrite in the chain. Run as
  ``python -m spark_rapids_jni_tpu.analysis.plancheck`` /
  ``...planfuzz``.
- ``lockdep.py`` — opt-in runtime instrumentation over ``threading``:
  ``SRJT_LOCKDEP=1`` records per-thread acquisition stacks, the
  lock-order graph, cycles, and blocking-while-locked events;
  ``SRJT_RACE=1`` additionally arms srjt-race layer 2 — per-thread
  vector clocks advanced on every sync edge (locks, Condition waits,
  Thread.start/join, Event.set/wait, Semaphore, Barrier) with a
  ``track(obj)`` registration API over the scheduler/pool/memgov/
  metrics shared state; unordered access pairs land in the same
  per-process JSON report. Merge/gate everything with
  ``python -m spark_rapids_jni_tpu.analysis.lockdep``.

Both static CLIs emit ``--format=json|sarif`` with text-mode
exit-code parity. This package must stay import-light (stdlib only at
import time): the package ``__init__`` installs lockdep BEFORE any
other module — and so before any package lock exists — when either
runtime knob is armed.
"""

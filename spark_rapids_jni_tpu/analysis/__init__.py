"""Correctness tooling for the concurrent substrate (ISSUE 7).

Two layers, both gated in ci/premerge.sh:

- ``lint.py`` — ``srjt-lint``, an AST static pass (stdlib ``ast``, no
  new deps) enforcing the repo's hand-enforced invariants: the central
  knob registry (utils/knobs.py), the error-taxonomy raise/except
  discipline, the metrics/spill hot-path stub pattern, and deadline
  cooperation for blocking calls. Run as
  ``python -m spark_rapids_jni_tpu.analysis.lint``.
- ``lockdep.py`` — opt-in (``SRJT_LOCKDEP=1``) runtime lock-order
  instrumentation over ``threading.Lock/RLock/Condition``: per-thread
  acquisition stacks, the global lock-order graph, cycle (potential
  deadlock) and blocking-while-locked reporting as a JSON artifact at
  process exit. Merge/gate the per-process reports with
  ``python -m spark_rapids_jni_tpu.analysis.lockdep``.

This package must stay import-light (stdlib only at import time): the
package ``__init__`` installs lockdep BEFORE any other module — and so
before any package lock exists — when the knob is armed.
"""

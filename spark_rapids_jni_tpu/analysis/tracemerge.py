"""srjt-trace merge CLI: join per-process span logs into per-trace
trees and export Chrome trace-event / Perfetto JSON (ISSUE 12).

Every traced process appends its finished spans to its own
``<SRJT_TRACE_LOG base>.<pid>.jsonl`` (utils/trace_sink.py) — the
client, each sidecar worker, each exchange peer. This tool joins those
logs by ``trace`` id and reconstructs the cross-process causality the
wire-propagated context (utils/tracing.py ``wire_context`` /
``remote_scope``) recorded:

    python -m spark_rapids_jni_tpu.analysis.tracemerge \
        "artifacts/trace_spans*.jsonl" --format chrome \
        --out artifacts/trace_perfetto.json

Formats:

- ``chrome`` (default): ``{"traceEvents": [...]}`` complete-event
  ("ph": "X") JSON — loadable by Perfetto (ui.perfetto.dev) and
  chrome://tracing; spans keep their real pid/tid so the cross-process
  structure is visible as separate tracks.
- ``json``: the merged structure itself — per-trace span lists, root
  counts, and orphan diagnostics — the shape CI gates assert against.
- ``tree``: human-readable per-trace span trees (the explain_last
  rendering, cross-process).

``--gate-orphans`` exits 1 when any span's parent does not resolve
within its trace (the premerge trace tier's zero-orphan contract: a
dropped parent means a propagation or emission bug, not chaos — chaos
kills whole processes, and a killed process's unfinished spans were
never written at all).
"""

from __future__ import annotations

import argparse
import glob as glob_mod
import json
import os
import sys
from typing import Dict, Iterable, List, Optional

__all__ = ["load_spans", "merge", "to_chrome", "render_tree", "main"]


def load_spans(paths: Iterable[str]) -> List[dict]:
    """Read span records (``"kind": "span"`` lines) from files and/or
    glob patterns. Unreadable files and non-JSON lines are skipped —
    a half-written final line from a SIGKILLed process must not sink
    the merge of everything else."""
    files: List[str] = []
    for p in paths:
        hits = sorted(glob_mod.glob(p))
        files.extend(hits if hits else [p])
    out: List[dict] = []
    for path in files:
        try:
            with open(path, encoding="utf-8") as f:
                lines = f.readlines()
        except OSError:
            continue
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn final line of a killed process
            if isinstance(rec, dict) and rec.get("kind") == "span":
                out.append(rec)
    return out


def merge(spans: List[dict]) -> dict:
    """Group spans by trace id and resolve parentage.

    Returns ``{"traces": {tid: {...}}, "orphans": total}`` where each
    trace carries ``spans`` (ts-ordered), ``roots`` (parentless span
    ids), ``orphans`` (spans whose parent id resolves to no span in
    the trace), ``pids``, and ``duration_s`` (root span span-width).
    Duplicate span ids (a retried emission) keep the first record."""
    traces: Dict[str, dict] = {}
    for s in spans:
        tid = s.get("trace")
        if not tid:
            continue
        t = traces.setdefault(tid, {"spans": [], "_ids": set()})
        sid = s.get("span")
        if sid in t["_ids"]:
            continue
        t["_ids"].add(sid)
        t["spans"].append(s)
    total_orphans = 0
    for tid, t in traces.items():
        ids = t.pop("_ids")
        t["spans"].sort(key=lambda s: s.get("ts", 0.0))
        roots = [s["span"] for s in t["spans"] if s.get("parent") is None]
        orphans = [
            s["span"] for s in t["spans"]
            if s.get("parent") is not None and s["parent"] not in ids
        ]
        t["roots"] = roots
        t["orphans"] = orphans
        t["pids"] = sorted({s.get("pid") for s in t["spans"]})
        root_spans = [s for s in t["spans"] if s.get("parent") is None]
        t["duration_s"] = max(
            (s.get("dur_us", 0.0) / 1e6 for s in root_spans), default=0.0
        )
        total_orphans += len(orphans)
    return {"traces": traces, "orphans": total_orphans}


def to_chrome(merged: dict) -> dict:
    """Chrome trace-event JSON (Perfetto-loadable): one complete event
    per span, real pid/tid tracks, annotations as ``args``."""
    events = []
    for tid, t in sorted(merged["traces"].items()):
        for s in t["spans"]:
            events.append({
                "ph": "X",
                "name": s.get("name"),
                "cat": f"trace:{tid}",
                "ts": round(s.get("ts", 0.0) * 1e6, 1),
                "dur": s.get("dur_us", 0.0),
                "pid": s.get("pid", 0),
                "tid": s.get("tid", 0),
                "args": {
                    "trace": tid,
                    "span": s.get("span"),
                    "parent": s.get("parent"),
                    "status": s.get("status", "ok"),
                    **(s.get("annotations") or {}),
                },
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def render_tree(merged: dict, only: Optional[str] = None) -> str:
    """Human rendering: one indented tree per trace (cross-process —
    a child span from another pid nests under its wire parent)."""
    from ..utils import trace_sink

    lines: List[str] = []
    for tid, t in sorted(merged["traces"].items()):
        if only is not None and tid != only:
            continue
        root = next(
            (s for s in t["spans"] if s.get("parent") is None), None
        )
        lines.append(trace_sink.render_trace({
            "trace": tid,
            "name": root.get("name") if root else "(no root span)",
            "status": root.get("status", "?") if root else "?",
            "duration_s": t["duration_s"],
            "spans": t["spans"],
        }))
        if t["orphans"]:
            lines.append(f"  !! {len(t['orphans'])} orphan span(s): "
                         + ", ".join(t["orphans"][:5]))
        lines.append("")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m spark_rapids_jni_tpu.analysis.tracemerge",
        description="join per-process srjt-trace span logs into "
                    "per-trace trees (ISSUE 12)")
    ap.add_argument("paths", nargs="+",
                    help="span-log files or glob patterns "
                    "(e.g. 'artifacts/trace_spans*.jsonl')")
    ap.add_argument("--format", default="chrome",
                    choices=("chrome", "json", "tree"),
                    help="chrome = Perfetto-loadable trace-event JSON "
                    "(default); json = the merged structure CI gates "
                    "read; tree = human span trees")
    ap.add_argument("--out", default=None,
                    help="write the output here instead of stdout")
    ap.add_argument("--trace", default=None,
                    help="restrict tree output to one trace id")
    ap.add_argument("--gate-orphans", action="store_true",
                    help="exit 1 when any span's parent does not "
                    "resolve within its trace")
    args = ap.parse_args(argv)
    spans = load_spans(args.paths)
    merged = merge(spans)
    if args.format == "chrome":
        body = json.dumps(to_chrome(merged), indent=1)
    elif args.format == "json":
        # merge() already popped its working keys: the structure is
        # the public shape as-is
        body = json.dumps(merged, indent=1)
    else:
        body = render_tree(merged, only=args.trace)
    if args.out:
        d = os.path.dirname(args.out)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(body + "\n")
    else:
        print(body)
    n_traces = len(merged["traces"])
    print(
        f"tracemerge: {len(spans)} spans across {n_traces} trace(s), "
        f"{merged['orphans']} orphan(s)"
        + (f" -> {args.out}" if args.out else ""),
        file=sys.stderr,
    )
    if args.gate_orphans and merged["orphans"]:
        print("tracemerge: orphan spans present (parent does not "
              "resolve within its trace) — propagation bug", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

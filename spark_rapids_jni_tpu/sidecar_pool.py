"""Crash-tolerant sidecar worker POOL over a SLAB-ARENA data plane.

The single-worker sidecar (sidecar.py) concentrates all device state in
one long-lived child; PR 5 (ISSUE 5) made that survivable with a
supervised pool of N workers — failover, background respawn, arena
re-hydration, pool-scoped breaker, CRC end to end. But its shared
arena was ONE buffer guarded by ONE lock: once an arena existed, every
pool request serialized on it, so ``SRJT_SIDECAR_POOL_SIZE=N`` bought
fault tolerance and zero throughput. This round (ISSUE 6) generalizes
the memfd arena into a **slab of per-request regions**:

- **ArenaSlab**: one memfd of ``SRJT_ARENA_SLAB_BYTES`` (power of two;
  every worker maps the same pages) carved by a buddy free-list
  allocator into power-of-two regions. Each in-flight request LEASES a
  region, writes its payload behind a 32-byte region header (magic +
  generation + request id + capacity + payload length), and the worker
  answers back into the same region — N workers carry N arena-resident
  ops concurrently, nothing shared but the allocator's short critical
  section.
- **Region header = re-hydration unit**: the header travels in the
  slab pages themselves, so a respawned worker that re-maps the memfd
  (SET_ARENA replay, exactly as PR 5 replayed the single buffer) sees
  every live region; the pool re-writes the request bytes (and bumps
  the generation) before every retry attempt, so a dead worker's
  partial response can never be what the failover re-sends — and a
  stale generation is a retryable desync at the worker, never
  somebody else's bytes.
- **Exhaustion is retryable-with-split**: a lease that cannot fit (or
  a write larger than its region) raises ``RetryableError`` carrying a
  ``RESOURCE_EXHAUSTED`` marker and the needed size, so the retry
  orchestrator's split path engages instead of a silent truncated
  write (the PR 5 hardening note, now enforced).
- **Leak discipline**: ``shutdown()`` (and ``set_arena()`` replacing a
  slab) releases and munmaps every region — force-released leases are
  counted (``sidecar.pool.region_leaks``) — and every open slab is
  registered so the test harness can assert none outlive a session
  (tests/conftest.py).

Everything PR 5 built rides along unchanged: supervised routing over
the LIVE set, one ``sidecar.pool.failovers`` per death-with-living-
peers, background respawn + SET_ARENA re-hydration, the pool-scoped
breaker (a failure is recorded only with ZERO live workers), host-
engine floor, and CRC trailers on every frame — region payloads
included.

Observability (registry-direct, durable-counter contract):
``sidecar.pool.size`` / ``sidecar.pool.live`` /
``sidecar.pool.slab_bytes`` / ``sidecar.pool.slab_regions`` gauges,
per-worker ``sidecar.pool.worker.w<id>.alive`` state gauges,
``sidecar.pool.failovers`` / ``sidecar.pool.worker_deaths`` /
``sidecar.pool.respawns`` / ``sidecar.pool.rehydrations`` /
``sidecar.pool.host_fallbacks`` / ``sidecar.pool.region_leases`` /
``sidecar.pool.region_leaks`` counters — all in
``runtime.stats_report()`` (``pool`` section), and ``worker_stats()``
merges every live worker's STATS snapshot keyed per worker id.

Environment:

    SRJT_SIDECAR_POOL_SIZE      workers to supervise (default 1)
    SRJT_POOL_RESPAWN_MAX       spawn attempts per death before the
                                worker is left dead (default 3)
    SRJT_POOL_RESPAWN_DELAY_S   pause between failed spawn attempts
                                (default 0.5)
    SRJT_ARENA_SLAB_BYTES       slab size (rounded up to a power of
                                two; default 64 MiB — virtual until
                                touched, memfd-backed)
"""

from __future__ import annotations

import mmap
import os
import struct
import threading
import time
from typing import Dict, List, Optional

from . import sidecar
from .sidecar import (
    ARENA_MODE_SLAB,
    OP_SET_ARENA,
    REGION_HDR,
    REGION_HDR_LEN,
    REGION_MAGIC,
    STATUS_OK,
    _FLAG_MASK,
    SupervisedClient,
    op_name,
    spawn_worker,
)

__all__ = [
    "ArenaRegion",
    "ArenaSlab",
    "SidecarPool",
    "connect_pool",
    "current_pool",
    "shutdown_pool",
    "stats_section",
    "open_slab_count",
    "arena_leak_report",
]

_MIN_REGION_BYTES = 4096  # smallest buddy block (header included)


def _env_int(name: str, default: int = ...) -> int:
    # typed registry accessor (utils/knobs.py): malformed values warn
    # and keep the declared default, and the per-knob minimum clamps
    from .utils import knobs

    return knobs.get_int(name, default=default)


def _pow2_ceil(n: int) -> int:
    return 1 << max(int(n) - 1, 1).bit_length()


# ---------------------------------------------------------------------------
# the slab-arena allocator (the per-request data plane)
# ---------------------------------------------------------------------------


class ArenaRegion:
    """One leased region of the slab: a power-of-two block whose first
    32 bytes are the region header (sidecar.REGION_HDR) and the rest is
    payload space. ``write()`` bumps the generation and rewrites header
    + payload in one go — the unit a retry attempt replays. Use as a
    context manager or ``release()`` explicitly; the slab counts every
    un-released lease at teardown as a leak."""

    __slots__ = (
        "slab", "offset", "capacity", "request_id", "generation",
        "payload_len", "_released", "_snapshot",
    )

    def __init__(self, slab: "ArenaSlab", offset: int, capacity: int,
                 request_id: int):
        self.slab = slab
        self.offset = offset
        self.capacity = capacity
        self.request_id = request_id
        self.generation = 0
        self.payload_len = 0
        self._released = False
        self._snapshot: Optional[bytes] = None
        self._write_header()

    def _write_header(self) -> None:
        self.slab._mm[self.offset : self.offset + REGION_HDR_LEN] = REGION_HDR.pack(
            REGION_MAGIC, self.generation, self.request_id,
            self.capacity, self.payload_len,
        )

    def write(self, data: bytes) -> None:
        """Place ``data`` in the region and stamp a fresh generation.
        Oversized payloads raise retryably with the needed size so
        retry-with-split engages, never a truncated write."""
        n = len(data)
        if n > self.capacity:
            from .utils.errors import RetryableError

            raise RetryableError(
                f"sidecar pool: RESOURCE_EXHAUSTED: region of "
                f"{self.capacity} bytes cannot hold a {n}-byte request "
                f"(need {n}) — split the batch or lease a larger region"
            )
        if self._released:
            raise ValueError("write to a released arena region")
        self.generation = (self.generation + 1) & 0xFFFFFFFF
        self.payload_len = n
        self._snapshot = bytes(data)
        start = self.offset + REGION_HDR_LEN
        self._write_header()
        self.slab._mm[start : start + n] = data

    def payload_bytes(self) -> bytes:
        start = self.offset + REGION_HDR_LEN
        return bytes(self.slab._mm[start : start + self.payload_len])

    def snapshot_bytes(self) -> bytes:
        """The request bytes as HANDED TO ``write()`` — never an mmap
        re-read. Request CRCs and retry replays must draw from here: a
        slow stale worker's slab write straddling a rewrite can tear
        the shared pages, and a checksum computed over a re-read would
        bless the torn bytes instead of catching them."""
        if self._snapshot is None:
            return self.payload_bytes()
        return self._snapshot

    def read(self, n: int) -> bytes:
        if n > self.capacity:
            raise ValueError(f"read of {n} bytes exceeds region capacity")
        start = self.offset + REGION_HDR_LEN
        return bytes(self.slab._mm[start : start + n])

    def release(self) -> None:
        if not self._released:
            self._released = True
            # scribble the in-slab header magic BEFORE the block goes
            # back to the free list: the worker re-validates the header
            # immediately before answering through the slab, and a
            # freed block coalesced into a larger re-lease keeps its
            # interior bytes — a stale-but-intact header there would
            # let a slow worker (whose client already gave up) pass
            # validation and clobber the new lease's payload
            try:
                REGION_HDR.pack_into(
                    self.slab._mm, self.offset,
                    0, self.generation, self.request_id, self.capacity, 0,
                )
            except (ValueError, IndexError):
                pass  # slab already closed/munmapped
            self._snapshot = None
            self.slab._release(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


class ArenaSlab:
    """memfd-backed slab carved by a buddy free-list into power-of-two
    regions. The allocator is the ONLY shared state on the slab data
    plane — leases are O(log size) under one short lock, and buddy
    coalescing on release keeps large leases possible after bursts of
    small ones."""

    _OPEN: Dict[int, "ArenaSlab"] = {}
    _OPEN_LOCK = threading.Lock()

    def __init__(self, size_bytes: Optional[int] = None):
        if size_bytes is None:
            # default + minimum clamp both live in the registry row
            size_bytes = _env_int("SRJT_ARENA_SLAB_BYTES")
        size = _pow2_ceil(max(int(size_bytes), _MIN_REGION_BYTES))
        self.size = size
        self.fd = os.memfd_create("srjt-pool-slab")
        os.ftruncate(self.fd, size)
        self._mm = mmap.mmap(self.fd, size)
        self._lock = threading.Lock()
        self._max_k = size.bit_length() - 1
        self._min_k = _MIN_REGION_BYTES.bit_length() - 1
        self._free: Dict[int, set] = {k: set() for k in range(self._min_k, self._max_k + 1)}
        self._free[self._max_k].add(0)
        self._leased: Dict[int, int] = {}  # offset -> block log2
        self._next_rid = 1
        self._closed = False
        with ArenaSlab._OPEN_LOCK:
            ArenaSlab._OPEN[id(self)] = self
        self._set_gauges()

    # -- accounting ----------------------------------------------------------

    def _reg(self):
        from .utils import metrics

        return metrics.registry()

    def _set_gauges(self) -> None:
        # the gauges are process-global: aggregate over every OPEN slab
        # so two live slabs (two pools, or a standalone slab beside a
        # pool's) don't clobber each other, and closing one slab
        # doesn't zero out the bytes another still has mapped
        with ArenaSlab._OPEN_LOCK:
            slabs = list(ArenaSlab._OPEN.values())
        reg = self._reg()
        reg.gauge("sidecar.pool.slab_bytes").set(sum(s.size for s in slabs))
        reg.gauge("sidecar.pool.slab_regions").set(
            sum(s.outstanding for s in slabs)
        )

    @property
    def outstanding(self) -> int:
        with self._lock:
            return len(self._leased)

    def leased_bytes(self) -> int:
        with self._lock:
            return sum(1 << k for k in self._leased.values())

    # -- lease / release -----------------------------------------------------

    def lease(self, nbytes: int) -> ArenaRegion:
        """Lease a region able to hold an ``nbytes`` payload (plus the
        32-byte header), rounded up to the block's power-of-two size
        class. Exhaustion — or a payload larger than the whole slab —
        raises retryably with a RESOURCE_EXHAUSTED marker so the retry
        orchestrator's split path engages."""
        from .utils.errors import RetryableError

        need = int(nbytes) + REGION_HDR_LEN
        k = max(need.bit_length() - 1, self._min_k)
        if (1 << k) < need:
            k += 1
        with self._lock:
            if self._closed:
                raise ValueError("lease on a closed arena slab")
            if k > self._max_k:
                raise RetryableError(
                    f"sidecar pool: RESOURCE_EXHAUSTED: a {nbytes}-byte "
                    f"request (need {need}) exceeds the {self.size}-byte "
                    "arena slab — split the batch or raise "
                    "SRJT_ARENA_SLAB_BYTES"
                )
            off = self._alloc_locked(k)
            if off is None:
                raise RetryableError(
                    f"sidecar pool: RESOURCE_EXHAUSTED: arena slab "
                    f"exhausted ({nbytes} bytes requested, "
                    f"{len(self._leased)} regions leased) — release "
                    "regions, split the batch, or raise "
                    "SRJT_ARENA_SLAB_BYTES"
                )
            self._leased[off] = k
            rid = self._next_rid
            self._next_rid += 1
        reg = self._reg()
        reg.counter("sidecar.pool.region_leases").inc()
        # delta update, NOT _set_gauges(): re-aggregating every open
        # slab (global lock + per-slab locks) on the per-op hot path
        # would re-serialize exactly the traffic the slab exists to
        # parallelize; full recomputes happen only at slab open/close
        reg.gauge("sidecar.pool.slab_regions").inc()
        return ArenaRegion(self, off, (1 << k) - REGION_HDR_LEN, rid)

    def _alloc_locked(self, k: int) -> Optional[int]:
        j = k
        while j <= self._max_k and not self._free[j]:
            j += 1
        if j > self._max_k:
            return None
        off = self._free[j].pop()
        while j > k:  # buddy split down to the requested class
            j -= 1
            self._free[j].add(off + (1 << j))
        return off

    def _release(self, region: ArenaRegion) -> None:
        with self._lock:
            if self._closed:
                return
            k = self._leased.pop(region.offset, None)
            if k is None:
                return
            off = region.offset
            while k < self._max_k:  # buddy coalescing
                buddy = off ^ (1 << k)
                if buddy not in self._free[k]:
                    break
                self._free[k].discard(buddy)
                off = min(off, buddy)
                k += 1
            self._free[k].add(off)
        reg = self._reg()
        reg.counter("sidecar.pool.region_releases").inc()
        reg.gauge("sidecar.pool.slab_regions").inc(-1)  # hot path: delta, see lease()

    # -- teardown ------------------------------------------------------------

    def close(self) -> int:
        """munmap + close the memfd. Returns the number of regions that
        were still leased (force-released, counted
        ``sidecar.pool.region_leaks``) — zero in a leak-free run, the
        invariant tests/conftest.py asserts."""
        with self._lock:
            if self._closed:
                return 0
            self._closed = True
            leaks = len(self._leased)
            self._leased.clear()
        if leaks:
            self._reg().counter("sidecar.pool.region_leaks").inc(leaks)
            from .utils import metrics

            metrics.event("sidecar.pool.region_leak", count=leaks)
        self._mm.close()
        os.close(self.fd)
        with ArenaSlab._OPEN_LOCK:
            ArenaSlab._OPEN.pop(id(self), None)
        self._set_gauges()
        return leaks


def open_slab_count() -> int:
    """Open (un-closed) slabs in this process — the leak tripwire the
    test harness checks at session end."""
    with ArenaSlab._OPEN_LOCK:
        return len(ArenaSlab._OPEN)


def arena_leak_report() -> List[str]:
    """Human-readable description of every open slab (empty when the
    teardown discipline held)."""
    with ArenaSlab._OPEN_LOCK:
        slabs = list(ArenaSlab._OPEN.values())
    return [
        f"slab of {s.size} bytes with {s.outstanding} leased regions"
        for s in slabs
    ]


# ---------------------------------------------------------------------------
# the supervised pool
# ---------------------------------------------------------------------------


class _Worker:
    """One supervised pool slot: the worker process, its socket, its
    client, and its liveness. The slot id (``wid``) is stable across
    respawns — metrics and routing name the SLOT, not the process.
    ``io_lock`` serializes frames on the worker's single supervised
    connection (concurrent callers of ``SidecarPool.call`` may route to
    the same slot); ``arena_conn`` remembers WHICH socket carried the
    last SET_ARENA — worker-side arena state is per-connection, so any
    reconnect invalidates it and the pool must replay."""

    __slots__ = (
        "wid", "proc", "sock_path", "client", "alive", "spawns",
        "io_lock", "arena_conn", "respawn_thread",
    )

    def __init__(self, wid: int):
        self.wid = wid
        self.proc = None
        self.sock_path: Optional[str] = None
        self.client: Optional[SupervisedClient] = None
        self.alive = False
        self.spawns = 0
        self.io_lock = threading.Lock()
        self.arena_conn = None
        self.respawn_thread: Optional[threading.Thread] = None


class SidecarPool:
    """Supervised pool of sidecar workers with health-checked routing,
    automatic respawn, slab re-hydration, and pool-scoped breaker
    accounting. ``call()`` is the public entry — same contract as
    ``SupervisedClient.call`` (results keep flowing: device path first,
    retry across workers, host engine as the floor), with worker death
    downgraded from "permanent degrade" to "one failover". The arena
    data plane is ``lease()`` + ``call(op, region=...)`` (or the
    one-shot ``call_arena``): per-request regions, so concurrent
    arena-resident ops on distinct workers genuinely overlap."""

    def __init__(
        self,
        size: Optional[int] = None,
        deadline_s: Optional[float] = None,
        heartbeat_s: Optional[float] = None,
        env: Optional[dict] = None,
        startup_timeout_s: float = 60.0,
        spawn_fn=spawn_worker,
        slab_bytes: Optional[int] = None,
    ):
        if size is None:
            size = _env_int("SRJT_SIDECAR_POOL_SIZE")
        if size < 1:
            raise ValueError(f"pool size must be >= 1, got {size}")
        self.size = int(size)
        self._deadline_s = deadline_s
        self._heartbeat_s = heartbeat_s
        self._env = dict(env) if env else None
        self._startup_timeout_s = float(startup_timeout_s)
        self._spawn_fn = spawn_fn
        self._respawn_max = _env_int("SRJT_POOL_RESPAWN_MAX")
        from .utils import knobs

        self._respawn_delay_s = knobs.get_float("SRJT_POOL_RESPAWN_DELAY_S")
        self._slab_bytes = slab_bytes
        self._lock = threading.RLock()
        self._rr = 0
        self._closed = False
        # the slab-arena data plane: ONE memfd shared by every worker
        # (they all map the same pages), surviving any of them; regions
        # are leased per request, so the only pool-wide arena state is
        # the allocator
        self._slab: Optional[ArenaSlab] = None
        self._workers = [_Worker(i) for i in range(self.size)]
        try:
            for w in self._workers:
                self._spawn_locked(w)
        except BaseException:
            self.shutdown()
            raise
        self._set_gauges()

    # -- lifecycle -----------------------------------------------------------

    def _reg(self):
        from .utils import metrics

        return metrics.registry()

    def _set_gauges(self) -> None:
        reg = self._reg()
        reg.gauge("sidecar.pool.size").set(self.size)
        reg.gauge("sidecar.pool.live").set(self.live_count())
        for w in self._workers:
            reg.gauge(f"sidecar.pool.worker.w{w.wid}.alive").set(
                1 if w.alive else 0
            )

    def _spawn_locked(self, w: _Worker) -> None:
        """Initial spawn of slot ``w`` (no arena exists yet; respawns
        go through ``_respawn``, which also re-hydrates state)."""
        proc, sock = self._spawn_fn(
            startup_timeout_s=self._startup_timeout_s, env=self._env
        )
        w.proc, w.sock_path = proc, sock
        w.client = SupervisedClient(
            sock, deadline_s=self._deadline_s, heartbeat_s=self._heartbeat_s
        )
        w.spawns += 1
        w.alive = True

    def shutdown(self) -> None:
        """Terminate every worker and release the slab (every region
        munmapped; leaked leases counted). Idempotent. Joins in-flight
        respawn threads FIRST (bounded by one spawn attempt): a daemon
        respawner killed at interpreter exit while inside spawn_fn
        orphans its half-born worker — the child would outlive the
        pool, holding the chip and (if stdio is a pipe) the parent's
        readers. Once ``_closed`` is set the respawner reaps whatever
        it spawned and returns, so after the join every live proc is in
        a slot where the sweep below can reach it."""
        with self._lock:
            self._closed = True
            workers = list(self._workers)
        join_s = self._startup_timeout_s + self._respawn_delay_s + 10
        for w in workers:
            t = w.respawn_thread
            if t is not None and t.is_alive():
                t.join(timeout=join_s)
        for w in workers:
            if w.client is not None:
                w.client.close()
            if w.proc is not None and w.proc.poll() is None:
                w.proc.terminate()
        for w in workers:
            if w.proc is not None:
                try:
                    w.proc.wait(timeout=10)
                except Exception:  # srjt-lint: allow-broad-except(best-effort shutdown: a worker that will not die in 10s gets SIGKILLed; teardown must reap every slot regardless)
                    w.proc.kill()
            if w.sock_path:
                try:
                    os.unlink(w.sock_path)
                except OSError:
                    pass
            w.alive = False
        self._close_slab()
        self._set_gauges()

    def _close_slab(self) -> None:
        # detach AND unregister in one critical section: unregistering
        # after dropping the lock races a concurrent ensure_slab()
        # registering its fresh slab — that registration would be the
        # one deleted, leaving live pinned pages invisible to memgov
        with self._lock:
            slab, self._slab = self._slab, None
            if slab is not None:
                from . import memgov

                memgov.catalog().unregister("sidecar.pool.arena")
        if slab is not None:
            slab.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()

    # -- routing -------------------------------------------------------------

    def live_count(self) -> int:
        return sum(1 for w in self._workers if w.alive)

    def _pick(self) -> Optional[_Worker]:
        """Round-robin over live workers; None when the pool is dark."""
        with self._lock:
            n = len(self._workers)
            for i in range(n):
                w = self._workers[(self._rr + i) % n]
                if w.alive:
                    self._rr = (self._rr + i + 1) % n
                    return w
        return None

    def _on_worker_failure(self, w: _Worker, exc: BaseException) -> None:
        """A request died with its worker: mark the slot dead ONCE,
        count the failover (when living peers remain to fail over TO),
        and hand the slot to the background respawner."""
        from .utils import metrics

        reg = self._reg()
        with self._lock:
            if not w.alive or self._closed:
                return
            w.alive = False
            if w.client is not None:
                w.client.close()
            reg.counter("sidecar.pool.worker_deaths").inc()
            reg.gauge(f"sidecar.pool.worker.w{w.wid}.alive").set(0)
            live = self.live_count()
            reg.gauge("sidecar.pool.live").set(live)
            if live > 0:
                reg.counter("sidecar.pool.failovers").inc()
            metrics.event(
                "sidecar.pool.worker_death",
                wid=w.wid,
                live=live,
                cls=type(exc).__name__,
            )
            t = threading.Thread(
                target=self._respawn, args=(w,), daemon=True,
                name=f"srjt-pool-respawn-w{w.wid}",
            )
            w.respawn_thread = t  # shutdown joins this before reaping
            t.start()

    def _respawn(self, w: _Worker) -> None:
        """Background supervisor for one dead slot: reap the corpse,
        spawn a replacement (bounded attempts), re-hydrate state. The
        SPAWN happens outside the pool lock — routing to the surviving
        workers must never queue behind a replacement booting jax."""
        from .utils import metrics

        if w.proc is not None:
            sidecar._reap_worker(w.proc)
        if w.sock_path:
            try:
                os.unlink(w.sock_path)
            except OSError:
                pass
        for attempt in range(self._respawn_max):
            if self._closed or w.alive:
                return
            try:
                proc, sock = self._spawn_fn(
                    startup_timeout_s=self._startup_timeout_s, env=self._env
                )
            except BaseException as e:  # srjt-lint: allow-broad-except(detached respawn supervisor: ANY spawn failure — incl. interpreter-teardown errors — is one counted attempt; escaping would kill the supervisor thread and strand the slot forever)
                metrics.event(
                    "sidecar.pool.respawn_failed",
                    wid=w.wid, attempt=attempt, err=str(e)[:200],
                )
                # detached respawn supervisor thread: owns no query
                # budget; bounded by SRJT_POOL_RESPAWN_MAX attempts and
                # joined by shutdown
                time.sleep(self._respawn_delay_s)
                continue
            with self._lock:
                if self._closed:
                    sidecar._reap_worker(proc)
                    return
                w.proc, w.sock_path = proc, sock
                w.client = SupervisedClient(
                    sock,
                    deadline_s=self._deadline_s,
                    heartbeat_s=self._heartbeat_s,
                )
                w.spawns += 1
                has_arena = self._slab is not None
            # state re-hydration OUTSIDE the pool lock (a wedged
            # replacement answering SET_ARENA slowly must not stall
            # routing to the survivors); nobody routes to this slot
            # until alive flips below, so its socket is private here.
            # The slab memfd is the SAME pages every other worker maps,
            # region headers included — the slab map IS the state.
            try:
                if has_arena:
                    self._send_arena(w)
                    self._reg().counter("sidecar.pool.rehydrations").inc()
                    metrics.event("sidecar.pool.rehydrate", wid=w.wid)
            except BaseException as e:  # srjt-lint: allow-broad-except(respawn re-hydration: a half-born worker that cannot take the arena is reaped and the attempt counted; escaping would strand the slot with a live unreachable child)
                metrics.event(
                    "sidecar.pool.respawn_failed",
                    wid=w.wid, attempt=attempt, err=str(e)[:200],
                )
                sidecar._reap_worker(proc)
                continue
            with self._lock:
                if self._closed:
                    sidecar._reap_worker(proc)
                    return
                w.alive = True
                self._reg().counter("sidecar.pool.respawns").inc()
                self._set_gauges()
            metrics.event("sidecar.pool.respawn", wid=w.wid)
            return

    def wait_healthy(self, timeout_s: float = 60.0) -> bool:
        """Block until every slot is live (tests / operators)."""
        end = time.monotonic() + timeout_s
        while time.monotonic() < end:
            if self.live_count() == self.size:
                return True
            time.sleep(0.05)
        return self.live_count() == self.size

    # -- the data path -------------------------------------------------------

    def _attempt(
        self,
        op: int,
        payload: bytes,
        region: Optional[ArenaRegion],
        region_req: Optional[bytes] = None,
    ):
        """One routed exchange — the unit the retry orchestrator
        re-runs. Worker death re-raises retryably AFTER marking the
        slot dead, so the next attempt routes around the corpse: that
        re-route IS the failover. Region requests REWRITE the request
        bytes (``region_req``, snapshotted by ``call``) into the leased
        region first, under a fresh generation: the worker answers into
        the same region, so a prior attempt's (possibly partial)
        response must never be what the retry re-sends — and a worker
        still holding the old generation gets a retryable desync, not
        stale bytes. Only the target worker's ``io_lock`` serializes:
        two region ops on two workers genuinely overlap (the whole
        point of the slab)."""
        from .utils.errors import DataCorruption, RetryableError

        w = self._pick()
        if w is None:
            raise RetryableError(
                "sidecar pool: UNAVAILABLE: no live workers "
                f"(size={self.size}; respawn in progress or exhausted)"
            )
        try:
            with w.io_lock:
                if region is None:
                    return w.client.request(op, payload)
                # worker-side arena state is per-CONNECTION: replay
                # SET_ARENA if the client reconnected since the last
                # upload (timeout redial, desync close, respawn)
                self._ensure_arena(w)
                region.write(region_req)
                return w.client.request(op, b"", region=region)
        except DataCorruption:
            # a corrupted FRAME is not a dead WORKER: the transport
            # round-tripped, the payload rotted. Retry re-sends; the
            # worker keeps its slot.
            raise
        except RetryableError as e:
            if self._worker_is_dead(w, e):
                self._on_worker_failure(w, e)
            raise

    @staticmethod
    def _worker_is_dead(w: _Worker, exc: BaseException) -> bool:
        """Transport faults and an exited process mean the WORKER is
        gone; a per-request deadline (DEADLINE_EXCEEDED) means it is
        slow — slow workers keep their slot (the breaker's deadline
        conflation stays a POOL-level verdict, not a slot eviction)."""
        if w.proc is not None and w.proc.poll() is not None:
            return True
        text = str(exc)
        return any(
            m in text
            for m in (
                "UNAVAILABLE",
                "Socket closed",
                "peer closed",
                "Connection refused",
                "Connection reset",
                "Broken pipe",
            )
        )

    def call(self, op: int, payload: bytes = b"",
             region: Optional[ArenaRegion] = None) -> bytes:
        """Run ``op`` on the pool under the retry orchestrator: routed
        to a live worker, failed over on worker death, degraded to the
        in-process host engine only when the device path truly cannot
        answer. Breaker discipline (ISSUE 5): the process-global
        breaker records a FAILURE only when the op failed with the
        WHOLE pool dark — one crashed worker among living peers is a
        failover, invisible to the breaker.

        Region contract: ``lease()`` a region, ``region.write()`` the
        request, pass ``region=``; the response replaces the region's
        payload. Within one call the pool snapshots the request up
        front and replays it (fresh generation) before every retry
        attempt — a dead worker's partial response can never be what
        the failover re-sends."""
        from .utils import deadline as deadline_mod, metrics, retry
        from .utils.errors import DeadlineExceeded, DeviceError

        deadline_mod.check(f"sidecar_pool_op_{op}")
        region_req = None
        if region is not None:
            # snapshot the request NOW, from the bytes the caller handed
            # write() — NOT an mmap re-read, which a stale worker's
            # straddling slab write could tear: every attempt (and the
            # host fallback) replays these bytes; the region itself is
            # scratch the previous attempt's response may have clobbered
            region_req = region.snapshot_bytes()
        br = sidecar.breaker()
        if not br.allow():
            self._host_fallback_count(op, "breaker_open")
            return sidecar._dispatch(
                op, payload if region_req is None else region_req, "host-fallback"
            )
        try:
            resp = retry.call_with_retry(
                self._attempt, op, payload, region, region_req,
                op_name=f"sidecar_pool_op_{op}",
            )
        except DeadlineExceeded:
            # same deliberate conflation as SupervisedClient.call: a
            # pool that cannot answer inside the budget is unavailable
            # for breaker purposes — unless the user cancelled
            d = deadline_mod.current()
            if d is not None and d.cancelled() and not d.expired():
                br.abort_probe()
            else:
                br.record_failure(cause="deadline")
            raise
        except DeviceError as e:
            if self.live_count() == 0:
                # the WHOLE pool is dark: this is what the breaker
                # exists to remember
                br.record_failure(cause=type(e).__name__)
            self._host_fallback_count(op, type(e).__name__)
            return sidecar._dispatch(
                op, payload if region_req is None else region_req, "host-fallback"
            )
        except Exception:
            br.record_success()  # semantic error: transport healthy
            raise
        except BaseException:
            br.abort_probe()
            raise
        br.record_success()
        return resp

    def call_arena(self, op: int, payload: bytes) -> bytes:
        """One-shot arena-resident exchange: lease a region, place the
        payload, run ``call``, release. The composable path is
        ``lease()`` + ``region.write()`` + ``call(op, region=...)`` for
        callers that reuse a region across requests."""
        region = self.lease(len(payload))
        try:
            region.write(payload)
            return self.call(op, region=region)
        finally:
            region.release()

    def _host_fallback_count(self, op: int, cause: str) -> None:
        from .utils import metrics

        self._reg().counter("sidecar.pool.host_fallbacks").inc()
        metrics.counter("sidecar.host_fallbacks").inc()
        metrics.event("sidecar.pool.degrade_to_host", op=op_name(op), cls=cause)

    # -- the shared-memory data plane ----------------------------------------

    def lease(self, nbytes: int) -> ArenaRegion:
        """Lease a per-request region able to hold ``nbytes``; creates
        the slab (and uploads it to every live worker) on first use.
        Exhaustion raises retryably (RESOURCE_EXHAUSTED) so the split
        machinery engages."""
        # lease off the slab ensure_slab RETURNED — re-reading
        # self._slab here races a concurrent set_arena()/shutdown()
        # nulling it (a closed slab raises cleanly; None would not)
        return self.ensure_slab(min_bytes=0).lease(nbytes)

    def ensure_slab(self, min_bytes: int = 0) -> ArenaSlab:
        """Create the pool's slab arena if none exists — sized
        ``max(SRJT_ARENA_SLAB_BYTES, min_bytes + header)`` AT CREATION
        only — and upload the memfd to every live worker in slab mode.
        An already-created slab is returned as-is regardless of
        ``min_bytes`` (growing it would mean a re-upload to every
        worker mid-traffic; an oversized lease instead raises
        RESOURCE_EXHAUSTED so retry-with-split engages). Returns the
        slab. The memfd outlives any single worker: respawns re-upload
        it (re-hydration), so a kill -9 never strands the data plane."""
        from . import memgov
        from .utils.errors import DeadlineExceeded

        with self._lock:
            if self._slab is not None:
                return self._slab
            if self._closed:
                # a lease after shutdown would mint a slab nobody ever
                # closes (the conftest leak tripwire would catch it at
                # session end; refuse up front instead)
                raise ValueError("ensure_slab on a shut-down pool")
            want = self._slab_bytes
            if want is None:
                want = _env_int("SRJT_ARENA_SLAB_BYTES")
            want = max(int(want), int(min_bytes) + REGION_HDR_LEN)
            slab = ArenaSlab(want)
            self._slab = slab
            memgov.catalog().register_host_bytes(
                "sidecar.pool.arena", slab.size, pinned=True, kind="arena"
            )
            live = [w for w in self._workers if w.alive]
        # the upload round-trips run OUTSIDE the pool lock (a slow
        # worker must not stall routing), serialized per worker
        for w in live:
            try:
                with w.io_lock:
                    self._send_arena(w)
            except DeadlineExceeded:
                # the QUERY's budget died mid-upload: the worker is
                # healthy — eating this (as the pre-ISSUE-7 code did)
                # killed a live worker and lost the deadline signal
                raise
            except Exception as e:  # srjt-lint: allow-broad-except(an upload failure marks THIS worker dead and routing continues on its peers; the slab itself stays valid for the survivors)
                self._on_worker_failure(w, e)
        return slab

    def set_arena(self, size: int) -> ArenaSlab:
        """Create — or REPLACE — the pool's slab arena at ``size``
        bytes (rounded up to a power of two) and upload it to every
        live worker. Replacing releases and munmaps the old slab first;
        a replace with regions still leased is a caller bug and raises
        (the old pages are about to vanish under those leases)."""
        # outstanding-check and slab detach must be ONE critical
        # section: dropping the lock between them lets a concurrent
        # lease() slip in and get its region munmapped out from under
        # it (counted as a region leak it never caused)
        with self._lock:
            slab = self._slab
            if slab is not None and slab.outstanding:
                raise ValueError(
                    "set_arena: cannot replace a slab with "
                    f"{slab.outstanding} regions still leased"
                )
            self._slab = None
            self._slab_bytes = int(size)
            if slab is not None:
                # unregister INSIDE the critical section, like
                # _close_slab — outside it, a concurrent ensure_slab's
                # fresh registration would be the one deleted
                from . import memgov

                memgov.catalog().unregister("sidecar.pool.arena")
        if slab is not None:
            slab.close()
        return self.ensure_slab()

    def _send_arena(self, w: _Worker) -> None:
        """OP_SET_ARENA with the slab memfd over SCM_RIGHTS on the
        worker's supervised socket (legacy framing: the fd transfer is
        control plane — 16 payload bytes, size + slab mode word).
        Records WHICH socket carried the upload (worker-side arena
        state is per-connection)."""
        import array
        import socket as socket_mod

        c = w.client
        if c._sock is None:
            c.connect()
        slab = self._slab
        hdr = struct.pack("<IQ", OP_SET_ARENA, 16) + struct.pack(
            "<QQ", slab.size, ARENA_MODE_SLAB
        )
        c._sock.sendmsg(
            [hdr],
            [(
                socket_mod.SOL_SOCKET,
                socket_mod.SCM_RIGHTS,
                array.array("i", [slab.fd]).tobytes(),
            )],
        )
        status, rlen = struct.unpack("<IQ", sidecar._recv_exact(c._sock, 12))
        body = sidecar._recv_exact(c._sock, rlen) if rlen else b""
        if (status & ~_FLAG_MASK) != STATUS_OK:
            from .utils.errors import RetryableError

            raise RetryableError(
                f"sidecar pool: SET_ARENA failed on w{w.wid}: "
                f"{body.decode('utf-8', 'replace')}"
            )
        w.arena_conn = c._sock

    def _ensure_arena(self, w: _Worker) -> None:
        """Replay SET_ARENA when the supervised connection is not the
        one that carried the last upload — a timeout redial, a desync
        close, or a fresh client all silently dropped the worker-side
        mapping, and a region op on such a connection would error (or
        worse, a stale client would trust stale pages)."""
        c = w.client
        if c._sock is not None and c._sock is w.arena_conn:
            return
        self._send_arena(w)
        self._reg().counter("sidecar.pool.rehydrations").inc()
        from .utils import metrics

        metrics.event("sidecar.pool.rehydrate", wid=w.wid, cause="reconnect")

    # -- observability -------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-clean pool state for runtime.stats_report()."""
        reg = self._reg()
        with self._lock:
            slab = self._slab
            return {
                "size": self.size,
                "live": self.live_count(),
                "workers": {
                    f"w{w.wid}": {
                        "alive": w.alive,
                        "spawns": w.spawns,
                        "pid": None if w.proc is None else w.proc.pid,
                    }
                    for w in self._workers
                },
                "failovers": reg.value("sidecar.pool.failovers"),
                "worker_deaths": reg.value("sidecar.pool.worker_deaths"),
                "respawns": reg.value("sidecar.pool.respawns"),
                "rehydrations": reg.value("sidecar.pool.rehydrations"),
                "host_fallbacks": reg.value("sidecar.pool.host_fallbacks"),
                "arena_bytes": 0 if slab is None else slab.size,
                "slab_regions": 0 if slab is None else slab.outstanding,
                "region_leases": reg.value("sidecar.pool.region_leases"),
                "region_leaks": reg.value("sidecar.pool.region_leaks"),
            }

    def worker_stats(self, fold: bool = True) -> Dict[str, dict]:
        """Poll every LIVE worker's STATS verb; returns snapshots keyed
        per worker id. With ``fold`` (default) each worker's counters
        land in this process's registry as ``sidecar.worker.w<id>.*``
        gauges — the per-worker keying runtime.device_stats merges
        instead of assuming one connection (ISSUE 5 satellite)."""
        from .utils import metrics
        from .utils.errors import RetryableError

        out: Dict[str, dict] = {}
        for w in list(self._workers):
            if not w.alive or w.client is None:
                continue
            try:
                # one frame at a time on the slot's supervised
                # connection; slab regions are private per request, so
                # a STATS poll never clobbers an in-flight data op
                with w.io_lock:
                    stats = w.client.worker_stats(fold=False)
            except RetryableError:
                continue  # died between the liveness check and the poll
            out[f"w{w.wid}"] = stats
            if fold:
                counters = (stats.get("snapshot") or {}).get("counters") or {}
                # worker counters already live under sidecar.worker.*;
                # strip that base before the per-worker prefix so the
                # fold lands at sidecar.worker.w<id>.requests.PING, not
                # a stuttered sidecar.worker.w0.sidecar.worker....
                base = "sidecar.worker."
                metrics.fold_worker_counters(
                    {
                        (k[len(base):] if k.startswith(base) else k): v
                        for k, v in counters.items()
                    },
                    prefix=f"sidecar.worker.w{w.wid}.",
                )
        return out


# ---------------------------------------------------------------------------
# process-global pool (one chip, one supervised pool — mirrors breaker())
# ---------------------------------------------------------------------------

_POOL: Optional[SidecarPool] = None
_POOL_LOCK = threading.Lock()


def connect_pool(**kwargs) -> SidecarPool:
    """Create (or return) the process-global pool. Keyword overrides
    apply only on first creation."""
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            _POOL = SidecarPool(**kwargs)
    return _POOL


def current_pool() -> Optional[SidecarPool]:
    """The process-global pool if one is connected, else None — stats
    paths (runtime.device_stats / stats_report) consult this without
    ever spawning workers as a side effect."""
    return _POOL


def shutdown_pool() -> None:
    global _POOL
    with _POOL_LOCK:
        p, _POOL = _POOL, None
    if p is not None:
        p.shutdown()


def stats_section() -> Optional[dict]:
    """The ``pool`` section of runtime.stats_report(): None when no
    pool has been connected (the seed posture)."""
    p = current_pool()
    return None if p is None else p.snapshot()

"""Crash-tolerant sidecar worker POOL over a SLAB-ARENA data plane.

The single-worker sidecar (sidecar.py) concentrates all device state in
one long-lived child; PR 5 (ISSUE 5) made that survivable with a
supervised pool of N workers — failover, background respawn, arena
re-hydration, pool-scoped breaker, CRC end to end. But its shared
arena was ONE buffer guarded by ONE lock: once an arena existed, every
pool request serialized on it, so ``SRJT_SIDECAR_POOL_SIZE=N`` bought
fault tolerance and zero throughput. This round (ISSUE 6) generalizes
the memfd arena into a **slab of per-request regions**:

- **ArenaSlab**: one memfd of ``SRJT_ARENA_SLAB_BYTES`` (power of two;
  every worker maps the same pages) carved by a buddy free-list
  allocator into power-of-two regions. Each in-flight request LEASES a
  region, writes its payload behind a 32-byte region header (magic +
  generation + request id + capacity + payload length), and the worker
  answers back into the same region — N workers carry N arena-resident
  ops concurrently, nothing shared but the allocator's short critical
  section.
- **Region header = re-hydration unit**: the header travels in the
  slab pages themselves, so a respawned worker that re-maps the memfd
  (SET_ARENA replay, exactly as PR 5 replayed the single buffer) sees
  every live region; the pool re-writes the request bytes (and bumps
  the generation) before every retry attempt, so a dead worker's
  partial response can never be what the failover re-sends — and a
  stale generation is a retryable desync at the worker, never
  somebody else's bytes.
- **Exhaustion is retryable-with-split**: a lease that cannot fit (or
  a write larger than its region) raises ``RetryableError`` carrying a
  ``RESOURCE_EXHAUSTED`` marker and the needed size, so the retry
  orchestrator's split path engages instead of a silent truncated
  write (the PR 5 hardening note, now enforced).
- **Leak discipline**: ``shutdown()`` (and ``set_arena()`` replacing a
  slab) releases and munmaps every region — force-released leases are
  counted (``sidecar.pool.region_leaks``) — and every open slab is
  registered so the test harness can assert none outlive a session
  (tests/conftest.py).

Everything PR 5 built rides along unchanged: supervised routing over
the LIVE set, one ``sidecar.pool.failovers`` per death-with-living-
peers, background respawn + SET_ARENA re-hydration, the pool-scoped
breaker (a failure is recorded only with ZERO live workers), host-
engine floor, and CRC trailers on every frame — region payloads
included.

**Tail tolerance (ISSUE 9).** PR 5 handled workers that DIE; a worker
that is merely SLOW — the gray failure that dominates tail latency —
kept its pool slot and poisoned every request round-robined onto it
until the static socket deadline expired. Three defenses now ride the
routing layer:

- **Gray-failure quarantine**: every routed exchange feeds a health
  scorer (per-worker per-op-class latency EWMA + jitter, against the
  pool-wide op-class p50 read off the always-on
  ``sidecar.op_lat_us.<OP>`` histograms). A worker collecting
  ``SRJT_QUARANTINE_STRIKES`` net slow samples (each >
  ``SRJT_QUARANTINE_SLOW_FACTOR`` × p50, or a request timeout) is
  QUARANTINED: out of ``_pick`` routing (unless every peer is also
  unroutable — degraded routing beats a dark pool), background-probed
  like respawn, and REINSTATED after ``SRJT_QUARANTINE_PROBES``
  consecutive clean probes. Distinct from death→failover (the worker
  is alive) and from the pool breaker (which only trips when the pool
  is dark). States: live → quarantined → reinstated | dead.
- **Hedged dispatch**: a request outliving the op-class p95 launches
  ONE duplicate on a different healthy worker; the first valid
  response wins, the loser is discarded (its region — hedges lease
  DISTINCT slab regions — releases in its own leg; the generation
  discipline already guarantees a stale worker can never bless bytes
  into the winner's region). Hedging carries a global budget
  (≤ ``SRJT_HEDGE_BUDGET_PCT``% of pool calls) and auto-disarms under
  memgov pressure or within ``SRJT_HEDGE_SHED_WINDOW_S`` of a
  serve-layer shed, so it never melts an overloaded pool.
- **Adaptive timeouts** live in ``SupervisedClient`` (sidecar.py):
  per-op socket deadlines derived from observed q99, so a hung worker
  surfaces in seconds and the failover/hedge machinery here engages.

Observability (registry-direct, durable-counter contract):
``sidecar.pool.size`` / ``sidecar.pool.live`` /
``sidecar.pool.slab_bytes`` / ``sidecar.pool.slab_regions`` gauges,
per-worker ``sidecar.pool.worker.w<id>.alive`` state gauges,
``sidecar.pool.failovers`` / ``sidecar.pool.worker_deaths`` /
``sidecar.pool.respawns`` / ``sidecar.pool.rehydrations`` /
``sidecar.pool.host_fallbacks`` / ``sidecar.pool.region_leases`` /
``sidecar.pool.region_leaks`` counters — all in
``runtime.stats_report()`` (``pool`` section), and ``worker_stats()``
merges every live worker's STATS snapshot keyed per worker id.

Environment:

    SRJT_SIDECAR_POOL_SIZE      workers to supervise (default 1)
    SRJT_POOL_RESPAWN_MAX       spawn attempts per death before the
                                worker is left dead (default 3)
    SRJT_POOL_RESPAWN_DELAY_S   pause between failed spawn attempts
                                (default 0.5)
    SRJT_ARENA_SLAB_BYTES       slab size (rounded up to a power of
                                two; default 64 MiB — virtual until
                                touched, memfd-backed)
    SRJT_QUARANTINE_*           gray-failure detector: slow factor,
                                strike count, min samples, probe
                                count/interval/slow threshold
    SRJT_HEDGE_*                hedged dispatch: budget percent, min
                                samples, trigger floor, shed window
"""

from __future__ import annotations

import mmap
import os
import struct
import threading
import time
from typing import Dict, List, Optional

from . import sidecar
from .sidecar import (
    ARENA_MODE_SLAB,
    OP_SET_ARENA,
    REGION_HDR,
    REGION_HDR_LEN,
    REGION_MAGIC,
    STATUS_OK,
    _FLAG_MASK,
    SupervisedClient,
    op_name,
    spawn_worker,
)

__all__ = [
    "ArenaRegion",
    "ArenaSlab",
    "SidecarPool",
    "connect_pool",
    "current_pool",
    "shutdown_pool",
    "stats_section",
    "health_section",
    "hedge_section",
    "open_slab_count",
    "arena_leak_report",
]

_MIN_REGION_BYTES = 4096  # smallest buddy block (header included)


def _env_int(name: str, default: int = ...) -> int:
    # typed registry accessor (utils/knobs.py): malformed values warn
    # and keep the declared default, and the per-knob minimum clamps
    from .utils import knobs

    return knobs.get_int(name, default=default)


def _pow2_ceil(n: int) -> int:
    return 1 << max(int(n) - 1, 1).bit_length()


# ---------------------------------------------------------------------------
# the slab-arena allocator (the per-request data plane)
# ---------------------------------------------------------------------------


class ArenaRegion:
    """One leased region of the slab: a power-of-two block whose first
    32 bytes are the region header (sidecar.REGION_HDR) and the rest is
    payload space. ``write()`` bumps the generation and rewrites header
    + payload in one go — the unit a retry attempt replays. Use as a
    context manager or ``release()`` explicitly; the slab counts every
    un-released lease at teardown as a leak."""

    __slots__ = (
        "slab", "offset", "capacity", "request_id", "generation",
        "payload_len", "_released", "_snapshot",
    )

    def __init__(self, slab: "ArenaSlab", offset: int, capacity: int,
                 request_id: int):
        self.slab = slab
        self.offset = offset
        self.capacity = capacity
        self.request_id = request_id
        self.generation = 0
        self.payload_len = 0
        self._released = False
        self._snapshot: Optional[bytes] = None
        self._write_header()

    def _write_header(self) -> None:
        self.slab._mm[self.offset : self.offset + REGION_HDR_LEN] = REGION_HDR.pack(
            REGION_MAGIC, self.generation, self.request_id,
            self.capacity, self.payload_len,
        )

    def write(self, data: bytes) -> None:
        """Place ``data`` in the region and stamp a fresh generation.
        Oversized payloads raise retryably with the needed size so
        retry-with-split engages, never a truncated write."""
        n = len(data)
        if n > self.capacity:
            from .utils.errors import RetryableError

            raise RetryableError(
                f"sidecar pool: RESOURCE_EXHAUSTED: region of "
                f"{self.capacity} bytes cannot hold a {n}-byte request "
                f"(need {n}) — split the batch or lease a larger region"
            )
        if self._released:
            raise ValueError("write to a released arena region")
        self.generation = (self.generation + 1) & 0xFFFFFFFF
        self.payload_len = n
        self._snapshot = bytes(data)
        start = self.offset + REGION_HDR_LEN
        self._write_header()
        self.slab._mm[start : start + n] = data

    def payload_bytes(self) -> bytes:
        start = self.offset + REGION_HDR_LEN
        return bytes(self.slab._mm[start : start + self.payload_len])

    def snapshot_bytes(self) -> bytes:
        """The request bytes as HANDED TO ``write()`` — never an mmap
        re-read. Request CRCs and retry replays must draw from here: a
        slow stale worker's slab write straddling a rewrite can tear
        the shared pages, and a checksum computed over a re-read would
        bless the torn bytes instead of catching them."""
        if self._snapshot is None:
            return self.payload_bytes()
        return self._snapshot

    def read(self, n: int) -> bytes:
        if n > self.capacity:
            raise ValueError(f"read of {n} bytes exceeds region capacity")
        start = self.offset + REGION_HDR_LEN
        return bytes(self.slab._mm[start : start + n])

    def release(self) -> None:
        if not self._released:
            self._released = True
            # scribble the in-slab header magic BEFORE the block goes
            # back to the free list: the worker re-validates the header
            # immediately before answering through the slab, and a
            # freed block coalesced into a larger re-lease keeps its
            # interior bytes — a stale-but-intact header there would
            # let a slow worker (whose client already gave up) pass
            # validation and clobber the new lease's payload
            try:
                REGION_HDR.pack_into(
                    self.slab._mm, self.offset,
                    0, self.generation, self.request_id, self.capacity, 0,
                )
            except (ValueError, IndexError):
                pass  # slab already closed/munmapped
            self._snapshot = None
            self.slab._release(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


class ArenaSlab:
    """memfd-backed slab carved by a buddy free-list into power-of-two
    regions. The allocator is the ONLY shared state on the slab data
    plane — leases are O(log size) under one short lock, and buddy
    coalescing on release keeps large leases possible after bursts of
    small ones."""

    _OPEN: Dict[int, "ArenaSlab"] = {}
    _OPEN_LOCK = threading.Lock()

    def __init__(self, size_bytes: Optional[int] = None):
        if size_bytes is None:
            # default + minimum clamp both live in the registry row
            size_bytes = _env_int("SRJT_ARENA_SLAB_BYTES")
        size = _pow2_ceil(max(int(size_bytes), _MIN_REGION_BYTES))
        self.size = size
        self.fd = os.memfd_create("srjt-pool-slab")
        os.ftruncate(self.fd, size)
        self._mm = mmap.mmap(self.fd, size)
        self._lock = threading.Lock()
        self._max_k = size.bit_length() - 1
        self._min_k = _MIN_REGION_BYTES.bit_length() - 1
        self._free: Dict[int, set] = {k: set() for k in range(self._min_k, self._max_k + 1)}
        self._free[self._max_k].add(0)
        self._leased: Dict[int, int] = {}  # offset -> block log2
        self._next_rid = 1
        self._closed = False
        with ArenaSlab._OPEN_LOCK:
            ArenaSlab._OPEN[id(self)] = self
        self._set_gauges()

    # -- accounting ----------------------------------------------------------

    def _reg(self):
        from .utils import metrics

        return metrics.registry()

    def _set_gauges(self) -> None:
        # the gauges are process-global: aggregate over every OPEN slab
        # so two live slabs (two pools, or a standalone slab beside a
        # pool's) don't clobber each other, and closing one slab
        # doesn't zero out the bytes another still has mapped
        with ArenaSlab._OPEN_LOCK:
            slabs = list(ArenaSlab._OPEN.values())
        reg = self._reg()
        reg.gauge("sidecar.pool.slab_bytes").set(sum(s.size for s in slabs))
        reg.gauge("sidecar.pool.slab_regions").set(
            sum(s.outstanding for s in slabs)
        )

    @property
    def outstanding(self) -> int:
        with self._lock:
            return len(self._leased)

    def leased_bytes(self) -> int:
        with self._lock:
            return sum(1 << k for k in self._leased.values())

    # -- lease / release -----------------------------------------------------

    def lease(self, nbytes: int) -> ArenaRegion:
        """Lease a region able to hold an ``nbytes`` payload (plus the
        32-byte header), rounded up to the block's power-of-two size
        class. Exhaustion — or a payload larger than the whole slab —
        raises retryably with a RESOURCE_EXHAUSTED marker so the retry
        orchestrator's split path engages."""
        from .utils.errors import RetryableError

        need = int(nbytes) + REGION_HDR_LEN
        k = max(need.bit_length() - 1, self._min_k)
        if (1 << k) < need:
            k += 1
        with self._lock:
            if self._closed:
                raise ValueError("lease on a closed arena slab")
            if k > self._max_k:
                raise RetryableError(
                    f"sidecar pool: RESOURCE_EXHAUSTED: a {nbytes}-byte "
                    f"request (need {need}) exceeds the {self.size}-byte "
                    "arena slab — split the batch or raise "
                    "SRJT_ARENA_SLAB_BYTES"
                )
            off = self._alloc_locked(k)
            if off is None:
                raise RetryableError(
                    f"sidecar pool: RESOURCE_EXHAUSTED: arena slab "
                    f"exhausted ({nbytes} bytes requested, "
                    f"{len(self._leased)} regions leased) — release "
                    "regions, split the batch, or raise "
                    "SRJT_ARENA_SLAB_BYTES"
                )
            self._leased[off] = k
            rid = self._next_rid
            self._next_rid += 1
        reg = self._reg()
        reg.counter("sidecar.pool.region_leases").inc()
        # delta update, NOT _set_gauges(): re-aggregating every open
        # slab (global lock + per-slab locks) on the per-op hot path
        # would re-serialize exactly the traffic the slab exists to
        # parallelize; full recomputes happen only at slab open/close
        reg.gauge("sidecar.pool.slab_regions").inc()
        return ArenaRegion(self, off, (1 << k) - REGION_HDR_LEN, rid)

    def _alloc_locked(self, k: int) -> Optional[int]:
        j = k
        while j <= self._max_k and not self._free[j]:
            j += 1
        if j > self._max_k:
            return None
        off = self._free[j].pop()
        while j > k:  # buddy split down to the requested class
            j -= 1
            self._free[j].add(off + (1 << j))
        return off

    def _release(self, region: ArenaRegion) -> None:
        with self._lock:
            if self._closed:
                return
            k = self._leased.pop(region.offset, None)
            if k is None:
                return
            off = region.offset
            while k < self._max_k:  # buddy coalescing
                buddy = off ^ (1 << k)
                if buddy not in self._free[k]:
                    break
                self._free[k].discard(buddy)
                off = min(off, buddy)
                k += 1
            self._free[k].add(off)
        reg = self._reg()
        reg.counter("sidecar.pool.region_releases").inc()
        reg.gauge("sidecar.pool.slab_regions").inc(-1)  # hot path: delta, see lease()

    # -- teardown ------------------------------------------------------------

    def close(self) -> int:
        """munmap + close the memfd. Returns the number of regions that
        were still leased (force-released, counted
        ``sidecar.pool.region_leaks``) — zero in a leak-free run, the
        invariant tests/conftest.py asserts."""
        with self._lock:
            if self._closed:
                return 0
            self._closed = True
            leaks = len(self._leased)
            self._leased.clear()
        if leaks:
            self._reg().counter("sidecar.pool.region_leaks").inc(leaks)
            from .utils import metrics

            metrics.event("sidecar.pool.region_leak", count=leaks)
        self._mm.close()
        os.close(self.fd)
        with ArenaSlab._OPEN_LOCK:
            ArenaSlab._OPEN.pop(id(self), None)
        self._set_gauges()
        return leaks


def open_slab_count() -> int:
    """Open (un-closed) slabs in this process — the leak tripwire the
    test harness checks at session end."""
    with ArenaSlab._OPEN_LOCK:
        return len(ArenaSlab._OPEN)


def arena_leak_report() -> List[str]:
    """Human-readable description of every open slab (empty when the
    teardown discipline held)."""
    with ArenaSlab._OPEN_LOCK:
        slabs = list(ArenaSlab._OPEN.values())
    return [
        f"slab of {s.size} bytes with {s.outstanding} leased regions"
        for s in slabs
    ]


# ---------------------------------------------------------------------------
# the supervised pool
# ---------------------------------------------------------------------------


class _Worker:
    """One supervised pool slot: the worker process, its socket, its
    client, and its liveness. The slot id (``wid``) is stable across
    respawns — metrics and routing name the SLOT, not the process.
    ``io_lock`` serializes frames on the worker's single supervised
    connection (concurrent callers of ``SidecarPool.call`` may route to
    the same slot); ``arena_conn`` remembers WHICH socket carried the
    last SET_ARENA — worker-side arena state is per-connection, so any
    reconnect invalidates it and the pool must replay.

    Tail-tolerance state (ISSUE 9): ``quarantined`` takes the slot out
    of preferred routing (the worker stays ALIVE — gray, not dead);
    ``strikes`` is the detector's net slow-sample count and
    ``clean_probes`` the reinstatement run; ``probe_thread`` is the
    background prober shutdown joins, like ``respawn_thread``."""

    __slots__ = (
        "wid", "proc", "sock_path", "client", "alive", "spawns",
        "io_lock", "arena_conn", "respawn_thread",
        "quarantined", "strikes", "clean_probes", "probe_thread",
    )

    def __init__(self, wid: int):
        self.wid = wid
        self.proc = None
        self.sock_path: Optional[str] = None
        self.client: Optional[SupervisedClient] = None
        self.alive = False
        self.spawns = 0
        self.io_lock = threading.Lock()
        self.arena_conn = None
        self.respawn_thread: Optional[threading.Thread] = None
        self.quarantined = False
        self.strikes = 0
        self.clean_probes = 0
        self.probe_thread: Optional[threading.Thread] = None


class SidecarPool:
    """Supervised pool of sidecar workers with health-checked routing,
    automatic respawn, slab re-hydration, and pool-scoped breaker
    accounting. ``call()`` is the public entry — same contract as
    ``SupervisedClient.call`` (results keep flowing: device path first,
    retry across workers, host engine as the floor), with worker death
    downgraded from "permanent degrade" to "one failover". The arena
    data plane is ``lease()`` + ``call(op, region=...)`` (or the
    one-shot ``call_arena``): per-request regions, so concurrent
    arena-resident ops on distinct workers genuinely overlap."""

    def __init__(
        self,
        size: Optional[int] = None,
        deadline_s: Optional[float] = None,
        heartbeat_s: Optional[float] = None,
        env: Optional[dict] = None,
        startup_timeout_s: float = 60.0,
        spawn_fn=spawn_worker,
        slab_bytes: Optional[int] = None,
    ):
        if size is None:
            size = _env_int("SRJT_SIDECAR_POOL_SIZE")
        if size < 1:
            raise ValueError(f"pool size must be >= 1, got {size}")
        self.size = int(size)
        self._deadline_s = deadline_s
        self._heartbeat_s = heartbeat_s
        self._env = dict(env) if env else None
        self._startup_timeout_s = float(startup_timeout_s)
        self._spawn_fn = spawn_fn
        self._respawn_max = _env_int("SRJT_POOL_RESPAWN_MAX")
        from .utils import knobs

        self._respawn_delay_s = knobs.get_float("SRJT_POOL_RESPAWN_DELAY_S")
        self._slab_bytes = slab_bytes
        self._lock = threading.RLock()
        # wait_healthy and the quarantine/respawn transitions meet on
        # this condition (notify-backed, ISSUE 9 — no sleep-polling)
        self._health = threading.Condition(self._lock)
        self._rr = 0
        self._closed = False
        # health scorer state: per-(worker, op-class) latency EWMA +
        # jitter, bounded (utils/metrics.KeyedEwma) — the pool-wide
        # baseline is the always-on sidecar.op_lat_us.<OP> histograms
        from .utils import metrics as _metrics

        self._ewma = _metrics.KeyedEwma(alpha=0.3, max_keys=512)
        # srjt-race layer 2 (ISSUE 11): the health/quarantine state is
        # dynamically tracked when SRJT_RACE=1 — per-worker records
        # (alive/quarantined/strikes/clean_probes writes), the scorer's
        # EWMA map, and the hedge-budget counter all feed the
        # vector-clock detector; disarmed, track() is one boolean read
        from .analysis.lockdep import track as _race_track

        self._ewma._entries = _race_track(
            self._ewma._entries, "pool.ewma_entries"
        )
        _race_track(
            self._reg().counter("sidecar.pool.hedges_launched"),
            "pool.hedge_budget",
        )
        # hedge-budget reservations are check-AND-increment under one
        # lock: two dispatch slots racing the same last budget slot
        # must not both launch (the premerge gate on hedge volume is a
        # hard ceiling, not a soft target)
        self._hedge_lock = threading.Lock()
        # the slab-arena data plane: ONE memfd shared by every worker
        # (they all map the same pages), surviving any of them; regions
        # are leased per request, so the only pool-wide arena state is
        # the allocator
        self._slab: Optional[ArenaSlab] = None
        self._workers = [
            _race_track(_Worker(i), f"pool.w{i}") for i in range(self.size)
        ]
        try:
            for w in self._workers:
                self._spawn_locked(w)
        except BaseException:
            self.shutdown()
            raise
        self._set_gauges()

    # -- lifecycle -----------------------------------------------------------

    def _reg(self):
        from .utils import metrics

        return metrics.registry()

    def _set_gauges(self) -> None:
        reg = self._reg()
        reg.gauge("sidecar.pool.size").set(self.size)
        reg.gauge("sidecar.pool.live").set(self.live_count())
        for w in self._workers:
            reg.gauge(f"sidecar.pool.worker.w{w.wid}.alive").set(
                1 if w.alive else 0
            )

    def _worker_env(self, w: _Worker) -> dict:
        """Spawn env for slot ``w``: the caller's overrides plus the
        slot's fault-injection tag (ISSUE 9) — per-worker rule keys
        like ``sidecar.worker.<OP>@w1`` resolve only inside the worker
        whose tag matches, so a chaos profile can gray exactly one
        worker of a real pool."""
        env = dict(self._env) if self._env else {}
        env.setdefault("SRJT_FAULTINJ_WORKER", f"w{w.wid}")
        return env

    def _spawn_locked(self, w: _Worker) -> None:
        """Initial spawn of slot ``w`` (no arena exists yet; respawns
        go through ``_respawn``, which also re-hydrates state)."""
        proc, sock = self._spawn_fn(
            startup_timeout_s=self._startup_timeout_s, env=self._worker_env(w)
        )
        w.proc, w.sock_path = proc, sock
        w.client = SupervisedClient(
            sock, deadline_s=self._deadline_s, heartbeat_s=self._heartbeat_s
        )
        w.spawns += 1
        w.alive = True

    def shutdown(self) -> None:
        """Terminate every worker and release the slab (every region
        munmapped; leaked leases counted). Idempotent. Joins in-flight
        respawn threads FIRST (bounded by one spawn attempt): a daemon
        respawner killed at interpreter exit while inside spawn_fn
        orphans its half-born worker — the child would outlive the
        pool, holding the chip and (if stdio is a pipe) the parent's
        readers. Once ``_closed`` is set the respawner reaps whatever
        it spawned and returns, so after the join every live proc is in
        a slot where the sweep below can reach it."""
        with self._lock:
            self._closed = True
            workers = list(self._workers)
            # wake parked quarantine probers (and wait_healthy callers)
            # so the joins below never ride out a full probe interval
            self._health.notify_all()
        join_s = self._startup_timeout_s + self._respawn_delay_s + 10
        for w in workers:
            t = w.respawn_thread
            if t is not None and t.is_alive():
                t.join(timeout=join_s)
        for w in workers:
            # quarantine probers poll _closed every interval and their
            # probe pings run under a short deadline scope: bounded join
            t = w.probe_thread
            if t is not None and t.is_alive():
                t.join(timeout=30)
        for w in workers:
            if w.client is not None:
                w.client.close()
            if w.proc is not None and w.proc.poll() is None:
                w.proc.terminate()
        for w in workers:
            if w.proc is not None:
                try:
                    w.proc.wait(timeout=10)
                except Exception:  # srjt-lint: allow-broad-except(best-effort shutdown: a worker that will not die in 10s gets SIGKILLed; teardown must reap every slot regardless)
                    w.proc.kill()
            if w.sock_path:
                try:
                    os.unlink(w.sock_path)
                except OSError:
                    pass
            w.alive = False
        self._close_slab()
        self._set_gauges()

    def _close_slab(self) -> None:
        # detach AND unregister in one critical section: unregistering
        # after dropping the lock races a concurrent ensure_slab()
        # registering its fresh slab — that registration would be the
        # one deleted, leaving live pinned pages invisible to memgov
        with self._lock:
            slab, self._slab = self._slab, None
            if slab is not None:
                from . import memgov

                memgov.catalog().unregister("sidecar.pool.arena")
        if slab is not None:
            slab.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()

    # -- routing -------------------------------------------------------------

    def live_count(self) -> int:
        return sum(1 for w in self._workers if w.alive)

    def routable_count(self) -> int:
        """Live AND unquarantined workers — the set fresh traffic
        prefers. The serving layer's quarantine-aware routing consults
        this (a pool whose every live worker is gray sheds
        non-host-eligible work instead of queueing onto stragglers)."""
        return sum(1 for w in self._workers if w.alive and not w.quarantined)

    def _pick(self, exclude: Optional[_Worker] = None,
              allow_quarantined: bool = True) -> Optional[_Worker]:
        """Round-robin over live workers, PREFERRING the unquarantined
        (ISSUE 9): a gray worker only takes fresh traffic when every
        peer is dead or equally gray — degraded routing beats a dark
        pool, and the fallback is counted so operators can see it.
        ``exclude`` lets hedged dispatch land the duplicate on a
        DIFFERENT worker, and ``allow_quarantined=False`` disables the
        gray fallback entirely (a hedge duplicated onto the known
        straggler would be pure waste); None when no eligible worker
        exists."""
        with self._lock:
            n = len(self._workers)
            fallback = None
            for i in range(n):
                w = self._workers[(self._rr + i) % n]
                if not w.alive or w is exclude:
                    continue
                if w.quarantined:
                    if allow_quarantined and fallback is None:
                        fallback = (w, i)
                    continue
                self._rr = (self._rr + i + 1) % n
                return w
            if fallback is not None:
                w, i = fallback
                self._rr = (self._rr + i + 1) % n
                self._reg().counter("sidecar.pool.quarantine_fallbacks").inc()
                return w
        return None

    def _on_worker_failure(self, w: _Worker, exc: BaseException) -> None:
        """A request died with its worker: mark the slot dead ONCE,
        count the failover (when living peers remain to fail over TO),
        and hand the slot to the background respawner."""
        from .utils import metrics

        reg = self._reg()
        with self._lock:
            if not w.alive or self._closed:
                return
            w.alive = False
            if w.quarantined:
                # quarantined → dead: the slot leaves the gray state
                # (the replacement process starts with a clean record);
                # the probe thread sees alive=False and exits
                w.quarantined = False
                reg.gauge(f"sidecar.pool.worker.w{w.wid}.quarantined").set(0)
                self._set_quarantined_gauge_locked()
            w.strikes = 0
            w.clean_probes = 0
            if w.client is not None:
                w.client.close()
            reg.counter("sidecar.pool.worker_deaths").inc()
            reg.gauge(f"sidecar.pool.worker.w{w.wid}.alive").set(0)
            live = self.live_count()
            reg.gauge("sidecar.pool.live").set(live)
            if live > 0:
                reg.counter("sidecar.pool.failovers").inc()
            self._health.notify_all()
            metrics.event(
                "sidecar.pool.worker_death",
                wid=w.wid,
                live=live,
                cls=type(exc).__name__,
            )
            t = threading.Thread(
                target=self._respawn, args=(w,), daemon=True,
                name=f"srjt-pool-respawn-w{w.wid}",
            )
            w.respawn_thread = t  # shutdown joins this before reaping
            t.start()

    def _respawn(self, w: _Worker) -> None:
        """Background supervisor for one dead slot: reap the corpse,
        spawn a replacement (bounded attempts), re-hydrate state. The
        SPAWN happens outside the pool lock — routing to the surviving
        workers must never queue behind a replacement booting jax."""
        from .utils import metrics

        if w.proc is not None:
            sidecar._reap_worker(w.proc)
        if w.sock_path:
            try:
                os.unlink(w.sock_path)
            except OSError:
                pass
        for attempt in range(self._respawn_max):
            # liveness check under the pool lock (srjt-race SRJT008): a
            # shutdown() racing this read must either be seen here or
            # see this respawner's subsequent spawn via the in-lock
            # re-checks below — a torn bare read could do neither
            with self._lock:
                if self._closed or w.alive:
                    return
            try:
                proc, sock = self._spawn_fn(
                    startup_timeout_s=self._startup_timeout_s,
                    env=self._worker_env(w),
                )
            except BaseException as e:  # srjt-lint: allow-broad-except(detached respawn supervisor: ANY spawn failure — incl. interpreter-teardown errors — is one counted attempt; escaping would kill the supervisor thread and strand the slot forever)
                metrics.event(
                    "sidecar.pool.respawn_failed",
                    wid=w.wid, attempt=attempt, err=str(e)[:200],
                )
                # detached respawn supervisor thread: owns no query
                # budget; bounded by SRJT_POOL_RESPAWN_MAX attempts and
                # joined by shutdown
                time.sleep(self._respawn_delay_s)
                continue
            with self._lock:
                if self._closed:
                    sidecar._reap_worker(proc)
                    return
                w.proc, w.sock_path = proc, sock
                w.client = SupervisedClient(
                    sock,
                    deadline_s=self._deadline_s,
                    heartbeat_s=self._heartbeat_s,
                )
                w.spawns += 1
                has_arena = self._slab is not None
            # state re-hydration OUTSIDE the pool lock (a wedged
            # replacement answering SET_ARENA slowly must not stall
            # routing to the survivors); nobody routes to this slot
            # until alive flips below, so its socket is private here.
            # The slab memfd is the SAME pages every other worker maps,
            # region headers included — the slab map IS the state.
            try:
                if has_arena:
                    self._send_arena(w)
                    self._reg().counter("sidecar.pool.rehydrations").inc()
                    metrics.event("sidecar.pool.rehydrate", wid=w.wid)
            except BaseException as e:  # srjt-lint: allow-broad-except(respawn re-hydration: a half-born worker that cannot take the arena is reaped and the attempt counted; escaping would strand the slot with a live unreachable child)
                metrics.event(
                    "sidecar.pool.respawn_failed",
                    wid=w.wid, attempt=attempt, err=str(e)[:200],
                )
                sidecar._reap_worker(proc)
                continue
            with self._lock:
                if self._closed:
                    sidecar._reap_worker(proc)
                    return
                w.alive = True
                self._reg().counter("sidecar.pool.respawns").inc()
                self._set_gauges()
                self._health.notify_all()
            metrics.event("sidecar.pool.respawn", wid=w.wid)
            return

    def _healthy_locked(self) -> bool:
        return not self._closed and all(
            w.alive and not w.quarantined for w in self._workers
        )

    def wait_healthy(self, timeout_s: float = 60.0) -> bool:
        """Block until every slot is live AND unquarantined (tests /
        operators). NOTIFY-backed (ISSUE 9): respawn completions,
        reinstatements, and deaths all signal the health condition, so
        the wait wakes the instant the pool turns healthy instead of
        on a poll tick — and it is quarantine-AWARE: a pool whose only
        live worker is gray is not healthy."""
        end = time.monotonic() + timeout_s
        with self._health:
            while not self._healthy_locked():
                remaining = end - time.monotonic()
                if remaining <= 0:
                    return self._healthy_locked()
                self._health.wait(remaining)
            return True

    # -- the health scorer + quarantine (gray-failure defense, ISSUE 9) ------

    def _set_quarantined_gauge_locked(self) -> None:
        self._reg().gauge("sidecar.pool.quarantined").set(
            sum(1 for w in self._workers if w.quarantined)
        )

    def _note_latency(self, w: _Worker, op: int, elapsed_s: float,
                      timed_out: bool = False) -> None:
        """One routed exchange's latency verdict: fold the sample into
        the worker's per-op-class EWMA/jitter and run the gray-failure
        detector — a sample slower than ``SRJT_QUARANTINE_SLOW_FACTOR``
        × the pool-wide op-class p50 (or any request TIMEOUT, the
        unambiguous slow signal) is a strike; a clean sample pays one
        back. ``SRJT_QUARANTINE_STRIKES`` net strikes quarantine the
        slot. Cold op classes (fewer than
        ``SRJT_QUARANTINE_MIN_SAMPLES`` pool-wide samples) yield no
        verdict either way: a first compile is slow, not gray."""
        from .utils import knobs

        if not knobs.get_bool("SRJT_QUARANTINE_ENABLED"):
            return
        name = op_name(op)
        self._ewma.update(f"w{w.wid}.{name}", elapsed_s)
        slow = timed_out
        if not slow:
            h = self._reg().histogram(f"sidecar.op_lat_us.{name}")
            if h.count < knobs.get_int("SRJT_QUARANTINE_MIN_SAMPLES"):
                return
            p50_us = h.quantile(0.5)
            if p50_us is None:
                return
            factor = knobs.get_float("SRJT_QUARANTINE_SLOW_FACTOR")
            slow = elapsed_s > max(p50_us / 1e6, 1e-5) * factor
        cause = None
        strikes = 0
        with self._lock:
            if self._closed or not w.alive:
                return
            if not slow:
                w.strikes = max(w.strikes - 1, 0)
                return
            w.strikes += 1
            if (not w.quarantined
                    and w.strikes >= knobs.get_int("SRJT_QUARANTINE_STRIKES")):
                cause = "timeout" if timed_out else "slow"
                strikes = w.strikes
                self._quarantine_locked(w, cause)
        if cause is not None:
            # event-log file I/O strictly OUTSIDE the routing lock (the
            # PR 8 discipline): a slow log write during a quarantine
            # transition must not stall _pick/wait_healthy
            from .utils import metrics

            metrics.event(
                "sidecar.pool.quarantine", wid=w.wid, cause=cause,
                strikes=strikes,
            )

    def _quarantine_locked(self, w: _Worker, cause: str) -> None:
        """Move a live-but-gray slot out of preferred routing and hand
        it to the background prober (caller holds self._lock; caller
        also owns emitting the quarantine EVENT after the lock drops —
        counters are in-lock-safe memory, file I/O is not). The worker
        process is NOT touched — in-flight requests drain on their own
        deadlines, and reinstatement is cheap."""
        w.quarantined = True
        w.clean_probes = 0
        reg = self._reg()
        reg.counter("sidecar.pool.quarantines").inc()
        reg.gauge(f"sidecar.pool.worker.w{w.wid}.quarantined").set(1)
        self._set_quarantined_gauge_locked()
        t = threading.Thread(
            target=self._probe_quarantined, args=(w,), daemon=True,
            name=f"srjt-pool-probe-w{w.wid}",
        )
        w.probe_thread = t  # shutdown joins this, like the respawner
        t.start()
        self._health.notify_all()

    def _probe_quarantined(self, w: _Worker) -> None:
        """Background prober for one quarantined slot: a PING every
        ``SRJT_QUARANTINE_PROBE_INTERVAL_S`` under a short deadline
        scope (utils/deadline.py — the probe can never hang on the
        wedge it is probing). A round-trip within
        ``SRJT_QUARANTINE_PROBE_SLOW_S`` is CLEAN; anything else —
        slow answer, expired probe budget, or the io_lock still held
        by a wedged data op — resets the run.
        ``SRJT_QUARANTINE_PROBES`` consecutive clean probes reinstate
        the slot; a dead transport hands it to the failover/respawn
        path instead (gray → dead is a real transition)."""
        from .utils import deadline as deadline_mod, knobs
        from .utils.errors import RetryableError

        reg = self._reg()
        while True:
            interval = knobs.get_float("SRJT_QUARANTINE_PROBE_INTERVAL_S")
            # detached prober cadence: the wait rides the health
            # condition so shutdown/death/reinstatement wake it
            # immediately instead of stranding a long interval — but a
            # spurious wakeup (any peer's health event notifies too)
            # re-waits the REMAINING interval, so probe spacing honors
            # the knob even under pool churn; each probe itself runs
            # under its own deadline scope below
            wake_at = time.monotonic() + interval
            with self._health:
                while True:
                    if self._closed or not w.alive or not w.quarantined:
                        return
                    left = wake_at - time.monotonic()
                    if left <= 0:
                        break
                    self._health.wait(left)
                client = w.client
            slow_s = knobs.get_float("SRJT_QUARANTINE_PROBE_SLOW_S")
            probe_budget = max(slow_s * 4, 1.0)
            ok = False
            dead_exc = None
            if w.io_lock.acquire(timeout=probe_budget):
                try:
                    t0 = time.monotonic()
                    try:
                        with deadline_mod.scope(probe_budget):
                            client.ping()
                        ok = (time.monotonic() - t0) <= slow_s
                    except RetryableError as e:
                        if self._worker_is_dead(w, e):
                            dead_exc = e
                    except Exception:  # srjt-lint: allow-broad-except(probe outcome is binary — an expired probe budget (DeadlineExceeded) or any semantic error is simply a dirty probe; the prober must outlive its subject)
                        pass
                finally:
                    w.io_lock.release()
            reg.counter("sidecar.pool.quarantine_probes").inc()
            if dead_exc is not None:
                self._on_worker_failure(w, dead_exc)
                return
            reinstated = False
            with self._lock:
                if self._closed or not w.alive or not w.quarantined:
                    return
                if not ok:
                    w.clean_probes = 0
                    continue
                w.clean_probes += 1
                if w.clean_probes >= knobs.get_int("SRJT_QUARANTINE_PROBES"):
                    self._reinstate_locked(w)
                    reinstated = True
            if reinstated:
                from .utils import metrics

                # event file I/O outside the routing lock, as above
                metrics.event("sidecar.pool.reinstate", wid=w.wid)
                return

    def _reinstate_locked(self, w: _Worker) -> None:
        """K clean probes: the slot rejoins preferred routing with a
        clean record (caller holds self._lock and owns emitting the
        reinstate EVENT after the lock drops)."""
        w.quarantined = False
        w.strikes = 0
        w.clean_probes = 0
        reg = self._reg()
        reg.counter("sidecar.pool.reinstatements").inc()
        reg.gauge(f"sidecar.pool.worker.w{w.wid}.quarantined").set(0)
        self._set_quarantined_gauge_locked()
        self._health.notify_all()

    # -- the data path -------------------------------------------------------

    def _attempt(
        self,
        op: int,
        payload: bytes,
        region: Optional[ArenaRegion],
        region_req: Optional[bytes] = None,
    ):
        """One routed — and possibly HEDGED (ISSUE 9) — exchange: the
        unit the retry orchestrator re-runs. When the op class is warm
        and hedging is armed, the primary leg runs with a hedge timer:
        past the op-class p95 a duplicate launches on a different
        healthy worker and the first valid response wins. Cold classes,
        single-worker pools, pressure, and budget exhaustion all fall
        back to the plain inline attempt."""
        from .utils.errors import RetryableError

        w = self._pick()
        if w is None:
            raise RetryableError(
                "sidecar pool: UNAVAILABLE: no live workers "
                f"(size={self.size}; respawn in progress or exhausted)"
            )
        delay_s = self._hedge_delay_s(op, w)
        if delay_s is None:
            return self._attempt_on(w, op, payload, region, region_req)
        return self._race(w, delay_s, op, payload, region, region_req)

    def _attempt_on(
        self,
        w: _Worker,
        op: int,
        payload: bytes,
        region: Optional[ArenaRegion],
        region_req: Optional[bytes] = None,
    ):
        """One exchange on a SPECIFIC worker. Worker death re-raises
        retryably AFTER marking the slot dead, so the next attempt
        routes around the corpse: that re-route IS the failover.
        Region requests REWRITE the request bytes (``region_req``,
        snapshotted by ``call``) into the leased region first, under a
        fresh generation: the worker answers into the same region, so
        a prior attempt's (possibly partial) response must never be
        what the retry re-sends — and a worker still holding the old
        generation gets a retryable desync, not stale bytes. Only the
        target worker's ``io_lock`` serializes: two region ops on two
        workers genuinely overlap (the whole point of the slab). Every
        exchange feeds the health scorer: successes and timeouts are
        latency samples (a timeout is the strongest), dead transports
        are the failover path's business. The sample clock starts AFTER
        the io_lock is acquired — the scorer judges the worker's
        SERVICE time, not time spent queued behind a peer caller on
        the same slot (contended routing must never quarantine a
        healthy worker).

        srjt-trace (ISSUE 12): each attempt is one ``pool.request``
        span annotated with the ROUTING DECISION — worker id and its
        quarantine state at pick time — so a failover reads as two
        sibling attempts under the same ``pool.call`` span, the second
        on a different worker."""
        from .utils import tracing

        with tracing.span(
            "pool.request", op=op_name(op), wid=w.wid,
            quarantined=w.quarantined,
        ):
            return self._attempt_on_impl(w, op, payload, region,
                                           region_req)

    def _attempt_on_impl(
        self,
        w: _Worker,
        op: int,
        payload: bytes,
        region: Optional[ArenaRegion],
        region_req: Optional[bytes] = None,
    ):
        from .utils.errors import DataCorruption, RetryableError

        t0 = time.monotonic()
        try:
            with w.io_lock:
                t0 = time.monotonic()
                if region is None:
                    resp = w.client.request(op, payload)
                else:
                    # worker-side arena state is per-CONNECTION: replay
                    # SET_ARENA if the client reconnected since the last
                    # upload (timeout redial, desync close, respawn)
                    self._ensure_arena(w)
                    region.write(region_req)
                    resp = w.client.request(op, b"", region=region)
        except DataCorruption:
            # a corrupted FRAME is not a dead WORKER: the transport
            # round-tripped, the payload rotted. Retry re-sends; the
            # worker keeps its slot.
            self._note_latency(w, op, time.monotonic() - t0)
            raise
        except RetryableError as e:
            if self._worker_is_dead(w, e):
                self._on_worker_failure(w, e)
            else:
                # every exchange the worker ANSWERED is a latency
                # observation, whatever the classification: a lost
                # hedge race's loser surfaces as a region desync (the
                # winner's caller released the lease), and before this
                # was scored a gray worker whose stragglers kept losing
                # races never accumulated strikes — the defense hid the
                # evidence. Timeouts stay the unambiguous strong signal.
                self._note_latency(
                    w, op, time.monotonic() - t0,
                    timed_out="DEADLINE_EXCEEDED" in str(e),
                )
            raise
        self._note_latency(w, op, time.monotonic() - t0)
        return resp

    # -- hedged dispatch (tail-latency defense, ISSUE 9) ---------------------

    def _hedge_pressure_cause(self) -> Optional[str]:
        """Hedging must never melt an overloaded pool: duplicates are
        withheld while the memory governor reports blocked admissions
        or within ``SRJT_HEDGE_SHED_WINDOW_S`` of a serve-layer shed
        (the scheduler stamps ``serve.last_shed_s`` registry-direct)."""
        from . import memgov
        from .utils import knobs

        reg = self._reg()
        if memgov.is_enabled() and reg.value("memgov.queue_depth", 0) > 0:
            return "memgov_pressure"
        last_shed = reg.value("serve.last_shed_s", None)
        if (
            last_shed is not None
            and time.monotonic() - last_shed
            < knobs.get_float("SRJT_HEDGE_SHED_WINDOW_S")
        ):
            return "shed_pressure"
        return None

    def _hedge_budget_ok(self) -> bool:
        """Global hedge budget: duplicates stay ≤
        ``SRJT_HEDGE_BUDGET_PCT`` percent of total pool calls."""
        from .utils import knobs

        reg = self._reg()
        pct = knobs.get_float("SRJT_HEDGE_BUDGET_PCT")
        launched = reg.value("sidecar.pool.hedges_launched", 0)
        calls = reg.value("sidecar.pool.calls", 0)
        return (launched + 1) * 100.0 <= pct * max(calls, 1)

    def _hedge_try_reserve(self) -> bool:
        """Atomically claim one hedge-budget slot (check + increment of
        ``sidecar.pool.hedges_launched`` under one lock): concurrent
        races at the budget margin get exactly one launch, never two —
        the gate on hedge volume is a hard ceiling."""
        with self._hedge_lock:
            if not self._hedge_budget_ok():
                return False
            self._reg().counter("sidecar.pool.hedges_launched").inc()
            return True

    def _hedge_delay_s(self, op: int, primary: _Worker) -> Optional[float]:
        """The hedge trigger for this attempt, or None to dispatch
        plainly inline: hedging needs the knob armed, a SECOND healthy
        worker to land on, a warm op class (≥ ``SRJT_HEDGE_MIN_SAMPLES``
        pool-wide samples), no pressure, and enough remaining budget
        for a second leg to matter. The delay itself is the op-class
        p95 floored at ``SRJT_HEDGE_MIN_DELAY_S`` — only the slow tail
        pays for a duplicate."""
        from .utils import deadline as deadline_mod, knobs, metrics

        if not knobs.get_bool("SRJT_HEDGE_ENABLED"):
            return None
        with self._lock:
            if not any(
                x.alive and not x.quarantined and x is not primary
                for x in self._workers
            ):
                return None
        reg = self._reg()
        h = reg.histogram(f"sidecar.op_lat_us.{op_name(op)}")
        if h.count < knobs.get_int("SRJT_HEDGE_MIN_SAMPLES"):
            return None
        cause = self._hedge_pressure_cause()
        if cause is not None:
            reg.counter("sidecar.pool.hedges_suppressed").inc()
            metrics.event(
                "sidecar.pool.hedge_suppressed", cause=cause, op=op_name(op)
            )
            return None
        p95_us = h.quantile(0.95)
        p50_us = h.quantile(0.5)
        if p95_us is None or p50_us is None:
            return None
        # pollution guard: one gray worker's slow samples inflate the
        # op-class p95 toward ITS latency — exactly the regime hedging
        # exists for — so the trigger is additionally ceilinged at the
        # quarantine slow threshold (factor × p50, median-robust). A
        # healthy tight distribution keeps p95 ≈ p50 and the ceiling
        # inert; a poisoned tail gets a trigger the stragglers still
        # cross.
        ceiling = max(p50_us / 1e6, 1e-5) * knobs.get_float(
            "SRJT_QUARANTINE_SLOW_FACTOR"
        )
        delay = max(
            min(p95_us / 1e6, ceiling),
            knobs.get_float("SRJT_HEDGE_MIN_DELAY_S"),
        )
        d = deadline_mod.current()
        if d is not None and delay >= d.remaining():
            return None  # no time left for a second leg to help
        return delay

    def _race(
        self,
        primary: _Worker,
        delay_s: float,
        op: int,
        payload: bytes,
        region: Optional[ArenaRegion],
        region_req: Optional[bytes],
    ):
        """Hedged dispatch: run the primary leg on its own thread (the
        ambient deadline scope rides contextvars into it); if it
        outlives ``delay_s``, launch ONE duplicate on a different
        healthy worker. FIRST VALID RESPONSE WINS — a winner is
        recorded exactly once under the race lock, the loser's eventual
        response (or error) is discarded. EVERY raced leg of a REGION
        request leases its own PRIVATE region, released in that leg's
        finally — the caller's lease is never handed to a thread that
        may outlive the race, so a straggling loser can neither write
        a released lease nor collide with the winner (and its full
        round-trip still lands in the health scorer: the gray evidence
        this race exists to collect). Both-legs-fail re-raises the
        primary's error so retry classification is unchanged from the
        unhedged path."""
        import contextvars

        from .utils import deadline as deadline_mod, metrics
        from .utils.errors import RetryableError

        reg = self._reg()
        primary_region = None
        if region is not None:
            try:
                # match the CALLER's capacity, not the request length:
                # the worker answers into the leg's region, and a
                # caller that leased big for a big response must keep
                # that headroom on every raced leg
                primary_region = self.lease(region.capacity)
            except RetryableError:
                # slab too tight for a private racing lease: dispatch
                # plainly inline on the caller's region instead
                return self._attempt_on(primary, op, payload, region,
                                        region_req)
        st_lock = threading.Lock()
        done = threading.Event()
        outcome = {"winner": None, "errors": {}, "legs": 1, "completed": 0}

        def leg(w, leg_region, is_hedge):
            # srjt-trace (ISSUE 12): each raced leg is its own span —
            # the two legs are SIBLINGS under the caller's pool.call
            # span (contextvars.copy_context carries the trace into the
            # leg threads), and the winner is annotated EXACTLY ONCE,
            # under the same race lock that settles the winner slot,
            # while its span is still open
            from .utils import tracing

            with tracing.span(
                "pool.hedge_leg", op=op_name(op), wid=w.wid,
                leg="hedge" if is_hedge else "primary",
            ) as leg_span:
                try:
                    r = self._attempt_on(w, op, payload, leg_region,
                                         region_req)
                except BaseException as e:  # srjt-lint: allow-broad-except(race leg: the error is stored for the settling thread to re-raise with full taxonomy; escaping would kill the leg thread and strand the race)
                    leg_span.annotate(error=type(e).__name__)
                    with st_lock:
                        outcome["errors"][is_hedge] = e
                        outcome["completed"] += 1
                        if (outcome["completed"] >= outcome["legs"]
                                and outcome["winner"] is None):
                            done.set()
                    return
                with st_lock:
                    outcome["completed"] += 1
                    if outcome["winner"] is None:
                        outcome["winner"] = (r, is_hedge)
                        leg_span.annotate(winner=True)
                    done.set()

        def primary_leg():
            try:
                leg(primary, primary_region, False)
            finally:
                if primary_region is not None:
                    primary_region.release()

        ctx = contextvars.copy_context()
        threading.Thread(
            target=ctx.run, args=(primary_leg,),
            daemon=True, name=f"srjt-pool-leg-w{primary.wid}",
        ).start()
        hedged = False
        if not done.wait(delay_s):
            # the duplicate must land on a HEALTHY peer — a hedge
            # routed onto a quarantined straggler is pure waste, so the
            # gray fallback is disabled for this pick
            w2 = self._pick(exclude=primary, allow_quarantined=False)
            hedge_region = None
            suppress_cause = None
            if w2 is None:
                suppress_cause = "no_peer"
            else:
                if region is not None:
                    try:
                        # hedges lease DISTINCT regions (caller-sized,
                        # as above): the duplicate must never write
                        # into the primary's lease
                        hedge_region = self.lease(region.capacity)
                    except RetryableError:
                        # slab exhausted: the hedge is a nicety, the
                        # primary leg is the request — suppress, don't
                        # fail the race
                        suppress_cause = "slab_exhausted"
                if suppress_cause is None and not self._hedge_try_reserve():
                    suppress_cause = "budget"
                    if hedge_region is not None:
                        hedge_region.release()
                        hedge_region = None
            if suppress_cause is not None:
                reg.counter("sidecar.pool.hedges_suppressed").inc()
                metrics.event(
                    "sidecar.pool.hedge_suppressed",
                    cause=suppress_cause, op=op_name(op),
                )
            else:
                with st_lock:
                    outcome["legs"] = 2
                    if outcome["winner"] is None and outcome["completed"]:
                        # the primary FAILED inside the launch window
                        # and settled a one-leg race: un-settle it —
                        # the hedge is now in play, and first valid
                        # response still wins (both-fail re-settles
                        # via the completed >= legs path)
                        done.clear()
                hedged = True
                metrics.event(
                    "sidecar.pool.hedge", op=op_name(op),
                    primary=primary.wid, hedge=w2.wid,
                    delay_ms=round(delay_s * 1e3, 3),
                )

                def hedge_leg(hr=hedge_region, w=w2):
                    try:
                        leg(w, hr, True)
                    finally:
                        if hr is not None:
                            hr.release()

                threading.Thread(
                    target=contextvars.copy_context().run,
                    args=(hedge_leg,), daemon=True,
                    name=f"srjt-pool-hedge-w{w2.wid}",
                ).start()
        while not done.wait(0.25):
            # both legs are bounded by their own (adaptive) socket
            # deadlines, so the event always settles; the check here
            # just surfaces a dying QUERY budget promptly
            deadline_mod.check(f"sidecar_pool_hedge_{op_name(op)}")
        with st_lock:
            winner = outcome["winner"]
            errors = dict(outcome["errors"])
            completed = outcome["completed"]
            legs = outcome["legs"]
        if winner is None:
            # every launched leg failed: re-raise the primary's error
            # (retry classification identical to the unhedged path)
            raise errors.get(False) or errors.get(True)
        resp, is_hedge = winner
        if hedged:
            if is_hedge:
                reg.counter("sidecar.pool.hedges_won").inc()
                metrics.event("sidecar.pool.hedge_won", op=op_name(op))
            if legs == 2:
                # the loser was either still in flight (cancelled: its
                # response will be discarded on arrival) or already
                # answered a duplicate that lost the winner slot —
                # either way exactly one completion reached the caller
                reg.counter("sidecar.pool.hedges_cancelled").inc()
        return resp

    @staticmethod
    def _worker_is_dead(w: _Worker, exc: BaseException) -> bool:
        """Transport faults and an exited process mean the WORKER is
        gone; a per-request deadline (DEADLINE_EXCEEDED) means it is
        slow — slow workers keep their slot (the breaker's deadline
        conflation stays a POOL-level verdict, not a slot eviction)."""
        if w.proc is not None and w.proc.poll() is not None:
            return True
        text = str(exc)
        return any(
            m in text
            for m in (
                "UNAVAILABLE",
                "Socket closed",
                "peer closed",
                "Connection refused",
                "Connection reset",
                "Broken pipe",
            )
        )

    def call(self, op: int, payload: bytes = b"",
             region: Optional[ArenaRegion] = None) -> bytes:
        """Run ``op`` on the pool under the retry orchestrator: routed
        to a live worker, failed over on worker death, degraded to the
        in-process host engine only when the device path truly cannot
        answer. Breaker discipline (ISSUE 5): the process-global
        breaker records a FAILURE only when the op failed with the
        WHOLE pool dark — one crashed worker among living peers is a
        failover, invisible to the breaker.

        Region contract: ``lease()`` a region, ``region.write()`` the
        request, pass ``region=``; the RESPONSE IS THE RETURN VALUE.
        (With hedging armed a raced attempt runs both legs on private
        leases, so the caller's region is NOT rewritten with the
        response — its post-call contents are unspecified; read the
        returned bytes, as ``call_arena`` does.) Within one call the
        pool snapshots the request up front and replays it (fresh
        generation) before every retry attempt — a dead worker's
        partial response can never be what the failover re-sends.

        srjt-trace (ISSUE 12): one ``pool.call`` span covers the whole
        call — every routed attempt (``pool.request``), hedge legs
        (``pool.hedge_leg`` siblings), and a degrade to the host engine
        (annotated ``host_fallback``) — so "the failover retry is a
        child of the original op span" holds by construction."""
        from .utils import tracing

        with tracing.span("pool.call", op=op_name(op)):
            return self._call_impl(op, payload, region)

    def _call_impl(self, op: int, payload: bytes,
                     region: Optional[ArenaRegion]) -> bytes:
        from .utils import deadline as deadline_mod, metrics, retry
        from .utils.errors import DeadlineExceeded, DeviceError

        deadline_mod.check(f"sidecar_pool_op_{op}")
        # the hedge budget's denominator: every pool call, hedged or not
        self._reg().counter("sidecar.pool.calls").inc()
        region_req = None
        if region is not None:
            # snapshot the request NOW, from the bytes the caller handed
            # write() — NOT an mmap re-read, which a stale worker's
            # straddling slab write could tear: every attempt (and the
            # host fallback) replays these bytes; the region itself is
            # scratch the previous attempt's response may have clobbered
            region_req = region.snapshot_bytes()
        br = sidecar.breaker()
        if not br.allow():
            self._host_fallback_count(op, "breaker_open")
            return sidecar._dispatch(
                op, payload if region_req is None else region_req, "host-fallback"
            )
        try:
            resp = retry.call_with_retry(
                self._attempt, op, payload, region, region_req,
                op_name=f"sidecar_pool_op_{op}",
            )
        except DeadlineExceeded:
            # same deliberate conflation as SupervisedClient.call: a
            # pool that cannot answer inside the budget is unavailable
            # for breaker purposes — unless the user cancelled
            d = deadline_mod.current()
            if d is not None and d.cancelled() and not d.expired():
                br.abort_probe()
            else:
                br.record_failure(cause="deadline")
            raise
        except DeviceError as e:
            if self.live_count() == 0:
                # the WHOLE pool is dark: this is what the breaker
                # exists to remember
                br.record_failure(cause=type(e).__name__)
            self._host_fallback_count(op, type(e).__name__)
            return sidecar._dispatch(
                op, payload if region_req is None else region_req, "host-fallback"
            )
        except Exception:
            br.record_success()  # semantic error: transport healthy
            raise
        except BaseException:
            br.abort_probe()
            raise
        br.record_success()
        return resp

    def call_arena(self, op: int, payload: bytes) -> bytes:
        """One-shot arena-resident exchange: lease a region, place the
        payload, run ``call``, release. The composable path is
        ``lease()`` + ``region.write()`` + ``call(op, region=...)`` for
        callers that reuse a region across requests."""
        region = self.lease(len(payload))
        try:
            region.write(payload)
            return self.call(op, region=region)
        finally:
            region.release()

    def _host_fallback_count(self, op: int, cause: str) -> None:
        from .utils import metrics, tracing

        self._reg().counter("sidecar.pool.host_fallbacks").inc()
        metrics.counter("sidecar.host_fallbacks").inc()
        metrics.event("sidecar.pool.degrade_to_host", op=op_name(op), cls=cause)
        # the degrade lands on the enclosing pool.call span: a query
        # whose answer came from the host engine says so in its trace
        tracing.annotate(host_fallback=cause)

    # -- the shared-memory data plane ----------------------------------------

    def lease(self, nbytes: int) -> ArenaRegion:
        """Lease a per-request region able to hold ``nbytes``; creates
        the slab (and uploads it to every live worker) on first use.
        Exhaustion raises retryably (RESOURCE_EXHAUSTED) so the split
        machinery engages."""
        # lease off the slab ensure_slab RETURNED — re-reading
        # self._slab here races a concurrent set_arena()/shutdown()
        # nulling it (a closed slab raises cleanly; None would not)
        return self.ensure_slab(min_bytes=0).lease(nbytes)

    def ensure_slab(self, min_bytes: int = 0) -> ArenaSlab:
        """Create the pool's slab arena if none exists — sized
        ``max(SRJT_ARENA_SLAB_BYTES, min_bytes + header)`` AT CREATION
        only — and upload the memfd to every live worker in slab mode.
        An already-created slab is returned as-is regardless of
        ``min_bytes`` (growing it would mean a re-upload to every
        worker mid-traffic; an oversized lease instead raises
        RESOURCE_EXHAUSTED so retry-with-split engages). Returns the
        slab. The memfd outlives any single worker: respawns re-upload
        it (re-hydration), so a kill -9 never strands the data plane."""
        from . import memgov
        from .utils.errors import DeadlineExceeded

        with self._lock:
            if self._slab is not None:
                return self._slab
            if self._closed:
                # a lease after shutdown would mint a slab nobody ever
                # closes (the conftest leak tripwire would catch it at
                # session end; refuse up front instead)
                raise ValueError("ensure_slab on a shut-down pool")
            want = self._slab_bytes
            if want is None:
                want = _env_int("SRJT_ARENA_SLAB_BYTES")
            want = max(int(want), int(min_bytes) + REGION_HDR_LEN)
            slab = ArenaSlab(want)
            self._slab = slab
            memgov.catalog().register_host_bytes(
                "sidecar.pool.arena", slab.size, pinned=True, kind="arena"
            )
            live = [w for w in self._workers if w.alive]
        # the upload round-trips run OUTSIDE the pool lock (a slow
        # worker must not stall routing), serialized per worker
        for w in live:
            try:
                with w.io_lock:
                    self._send_arena(w)
            except DeadlineExceeded:
                # the QUERY's budget died mid-upload: the worker is
                # healthy — eating this (as the pre-ISSUE-7 code did)
                # killed a live worker and lost the deadline signal
                raise
            except Exception as e:  # srjt-lint: allow-broad-except(an upload failure marks THIS worker dead and routing continues on its peers; the slab itself stays valid for the survivors)
                self._on_worker_failure(w, e)
        return slab

    def set_arena(self, size: int) -> ArenaSlab:
        """Create — or REPLACE — the pool's slab arena at ``size``
        bytes (rounded up to a power of two) and upload it to every
        live worker. Replacing releases and munmaps the old slab first;
        a replace with regions still leased is a caller bug and raises
        (the old pages are about to vanish under those leases)."""
        # outstanding-check and slab detach must be ONE critical
        # section: dropping the lock between them lets a concurrent
        # lease() slip in and get its region munmapped out from under
        # it (counted as a region leak it never caused)
        with self._lock:
            slab = self._slab
            if slab is not None and slab.outstanding:
                raise ValueError(
                    "set_arena: cannot replace a slab with "
                    f"{slab.outstanding} regions still leased"
                )
            self._slab = None
            self._slab_bytes = int(size)
            if slab is not None:
                # unregister INSIDE the critical section, like
                # _close_slab — outside it, a concurrent ensure_slab's
                # fresh registration would be the one deleted
                from . import memgov

                memgov.catalog().unregister("sidecar.pool.arena")
        if slab is not None:
            slab.close()
        return self.ensure_slab()

    def _send_arena(self, w: _Worker) -> None:
        """OP_SET_ARENA with the slab memfd over SCM_RIGHTS on the
        worker's supervised socket (legacy framing: the fd transfer is
        control plane — 16 payload bytes, size + slab mode word).
        Records WHICH socket carried the upload (worker-side arena
        state is per-connection)."""
        import array
        import socket as socket_mod

        c = w.client
        if c._sock is None:
            c.connect()
        # the slab reference is read under the pool lock (srjt-race
        # SRJT008: a concurrent set_arena()/_close_slab() nulls the
        # attribute) — the upload itself stays OUTSIDE the lock, and a
        # replace cannot munmap the pages mid-send because set_arena
        # refuses while regions are leased and re-uploads every live
        # worker itself afterwards
        with self._lock:
            slab = self._slab
        if slab is None:
            from .utils.errors import RetryableError

            raise RetryableError(
                f"sidecar pool: UNAVAILABLE: arena slab torn down while "
                f"re-hydrating w{w.wid} (set_arena/shutdown in flight)"
            )
        hdr = struct.pack("<IQ", OP_SET_ARENA, 16) + struct.pack(
            "<QQ", slab.size, ARENA_MODE_SLAB
        )
        c._sock.sendmsg(
            [hdr],
            [(
                socket_mod.SOL_SOCKET,
                socket_mod.SCM_RIGHTS,
                array.array("i", [slab.fd]).tobytes(),
            )],
        )
        status, rlen = struct.unpack("<IQ", sidecar._recv_exact(c._sock, 12))
        body = sidecar._recv_exact(c._sock, rlen) if rlen else b""
        if (status & ~_FLAG_MASK) != STATUS_OK:
            from .utils.errors import RetryableError

            raise RetryableError(
                f"sidecar pool: SET_ARENA failed on w{w.wid}: "
                f"{body.decode('utf-8', 'replace')}"
            )
        w.arena_conn = c._sock

    def _ensure_arena(self, w: _Worker) -> None:
        """Replay SET_ARENA when the supervised connection is not the
        one that carried the last upload — a timeout redial, a desync
        close, or a fresh client all silently dropped the worker-side
        mapping, and a region op on such a connection would error (or
        worse, a stale client would trust stale pages)."""
        c = w.client
        if c._sock is not None and c._sock is w.arena_conn:
            return
        self._send_arena(w)
        self._reg().counter("sidecar.pool.rehydrations").inc()
        from .utils import metrics

        metrics.event("sidecar.pool.rehydrate", wid=w.wid, cause="reconnect")

    # -- observability -------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-clean pool state for runtime.stats_report()."""
        reg = self._reg()
        with self._lock:
            slab = self._slab
            return {
                "size": self.size,
                "live": self.live_count(),
                "routable": self.routable_count(),
                "workers": {
                    f"w{w.wid}": {
                        "alive": w.alive,
                        "quarantined": w.quarantined,
                        "strikes": w.strikes,
                        "spawns": w.spawns,
                        "pid": None if w.proc is None else w.proc.pid,
                    }
                    for w in self._workers
                },
                "failovers": reg.value("sidecar.pool.failovers"),
                "worker_deaths": reg.value("sidecar.pool.worker_deaths"),
                "respawns": reg.value("sidecar.pool.respawns"),
                "rehydrations": reg.value("sidecar.pool.rehydrations"),
                "host_fallbacks": reg.value("sidecar.pool.host_fallbacks"),
                "quarantines": reg.value("sidecar.pool.quarantines"),
                "reinstatements": reg.value("sidecar.pool.reinstatements"),
                "hedges_launched": reg.value("sidecar.pool.hedges_launched"),
                "hedges_won": reg.value("sidecar.pool.hedges_won"),
                "arena_bytes": 0 if slab is None else slab.size,
                "slab_regions": 0 if slab is None else slab.outstanding,
                "region_leases": reg.value("sidecar.pool.region_leases"),
                "region_leaks": reg.value("sidecar.pool.region_leaks"),
            }

    def worker_stats(self, fold: bool = True) -> Dict[str, dict]:
        """Poll every LIVE worker's STATS verb; returns snapshots keyed
        per worker id. With ``fold`` (default) each worker's counters
        land in this process's registry as ``sidecar.worker.w<id>.*``
        gauges — the per-worker keying runtime.device_stats merges
        instead of assuming one connection (ISSUE 5 satellite)."""
        from .utils import metrics
        from .utils.errors import RetryableError

        out: Dict[str, dict] = {}
        for w in list(self._workers):
            if not w.alive or w.client is None:
                continue
            try:
                # one frame at a time on the slot's supervised
                # connection; slab regions are private per request, so
                # a STATS poll never clobbers an in-flight data op
                with w.io_lock:
                    stats = w.client.worker_stats(fold=False)
            except RetryableError:
                continue  # died between the liveness check and the poll
            out[f"w{w.wid}"] = stats
            if fold:
                counters = (stats.get("snapshot") or {}).get("counters") or {}
                # worker counters already live under sidecar.worker.*;
                # strip that base before the per-worker prefix so the
                # fold lands at sidecar.worker.w<id>.requests.PING, not
                # a stuttered sidecar.worker.w0.sidecar.worker....
                base = "sidecar.worker."
                metrics.fold_worker_counters(
                    {
                        (k[len(base):] if k.startswith(base) else k): v
                        for k, v in counters.items()
                    },
                    prefix=f"sidecar.worker.w{w.wid}.",
                )
        return out


# ---------------------------------------------------------------------------
# process-global pool (one chip, one supervised pool — mirrors breaker())
# ---------------------------------------------------------------------------

_POOL: Optional[SidecarPool] = None
_POOL_LOCK = threading.Lock()


def connect_pool(**kwargs) -> SidecarPool:
    """Create (or return) the process-global pool. Keyword overrides
    apply only on first creation."""
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            _POOL = SidecarPool(**kwargs)
    return _POOL


def current_pool() -> Optional[SidecarPool]:
    """The process-global pool if one is connected, else None — stats
    paths (runtime.device_stats / stats_report) consult this without
    ever spawning workers as a side effect."""
    return _POOL


def shutdown_pool() -> None:
    global _POOL
    with _POOL_LOCK:
        p, _POOL = _POOL, None
    if p is not None:
        p.shutdown()


def stats_section() -> Optional[dict]:
    """The ``pool`` section of runtime.stats_report(): None when no
    pool has been connected (the seed posture)."""
    p = current_pool()
    return None if p is None else p.snapshot()


def health_section() -> dict:
    """The ``health`` section of runtime.stats_report() (ISSUE 9):
    gray-failure verdicts — registry-direct, so it answers (zeros)
    even before any pool exists, plus the live pool's per-worker EWMA
    snapshot when one is connected."""
    from .utils import metrics

    reg = metrics.registry()
    out = {
        "quarantines": reg.value("sidecar.pool.quarantines"),
        "reinstatements": reg.value("sidecar.pool.reinstatements"),
        "probes": reg.value("sidecar.pool.quarantine_probes"),
        "quarantined_now": reg.value("sidecar.pool.quarantined"),
        "quarantine_fallbacks": reg.value("sidecar.pool.quarantine_fallbacks"),
    }
    p = current_pool()
    if p is not None:
        out["worker_latency"] = p._ewma.snapshot()
    return out


def hedge_section() -> dict:
    """The ``hedge`` section of runtime.stats_report() (ISSUE 9):
    hedged-dispatch accounting plus the adaptive-timeout clamp counts
    from both adaptive-deadline call sites."""
    from .utils import metrics

    reg = metrics.registry()
    return {
        "launched": reg.value("sidecar.pool.hedges_launched"),
        "won": reg.value("sidecar.pool.hedges_won"),
        "cancelled": reg.value("sidecar.pool.hedges_cancelled"),
        "suppressed": reg.value("sidecar.pool.hedges_suppressed"),
        "pool_calls": reg.value("sidecar.pool.calls"),
        "adaptive_timeout_clamps": {
            "sidecar": reg.value("sidecar.adaptive_timeout_clamps"),
            "exchange": reg.value("shuffle.tcp.adaptive_timeout_clamps"),
        },
    }

"""Crash-tolerant sidecar worker POOL with state re-hydration (ISSUE 5).

The single-worker sidecar (sidecar.py) concentrates all device state in
one long-lived child: before this module, a worker crash meant
reconnect-once -> circuit breaker -> permanent degrade-to-host for the
rest of the process — the SET_ARENA data plane and the device fast path
were simply gone. Theseus (PAPERS.md) treats worker failure as a
first-class event a query engine must survive, not observe. This module
is that layer:

- **Supervised pool of N workers** (``SRJT_SIDECAR_POOL_SIZE``,
  default 1 = today's footprint): each worker is its own spawned
  process + socket + ``SupervisedClient``, requests route round-robin
  over the LIVE set.
- **Failover**: a request that dies with its worker (kill -9, chaos
  ``crash`` fault, transport reset) marks the worker dead, counts ONE
  ``sidecar.pool.failovers``, and re-raises retryably — the existing
  retry orchestrator (utils/retry.py) re-runs the op, routing lands on
  a live worker, and the query never notices beyond latency.
- **Respawn + state re-hydration**: a background thread respawns the
  dead worker and REPLAYS its device state — the pool keeps the arena
  memfd (one shared memfd, every worker maps the same pages) and the
  client-side memgov catalog holds its host-tier accounting entry
  (``sidecar.pool.arena``), so a replacement worker gets OP_SET_ARENA
  re-uploaded before it takes traffic (``sidecar.pool.rehydrations``).
- **Pool-scoped breaker**: the process-global circuit breaker
  (sidecar.breaker()) now guards the POOL, not one worker — it records
  a failure only when an op fails with ZERO live workers; one crashed
  worker among living peers is a failover, not a trip.
- **Integrity end to end**: every frame the pool moves rides the CRC
  trailer protocol (utils/integrity.py), arena payloads included — a
  corrupted response is ``DataCorruption`` (retryable, the orchestrator
  re-fetches), never a wrong answer.

Observability (registry-direct, durable-counter contract):
``sidecar.pool.size`` / ``sidecar.pool.live`` gauges, per-worker
``sidecar.pool.worker.w<id>.alive`` state gauges,
``sidecar.pool.failovers`` / ``sidecar.pool.worker_deaths`` /
``sidecar.pool.respawns`` / ``sidecar.pool.rehydrations`` /
``sidecar.pool.host_fallbacks`` counters — all in
``runtime.stats_report()`` (``pool`` section), and
``worker_stats()`` merges every live worker's STATS snapshot keyed per
worker id (``sidecar.worker.w<id>.*`` gauges).

Environment:

    SRJT_SIDECAR_POOL_SIZE      workers to supervise (default 1)
    SRJT_POOL_RESPAWN_MAX       spawn attempts per death before the
                                worker is left dead (default 3)
    SRJT_POOL_RESPAWN_DELAY_S   pause between failed spawn attempts
                                (default 0.5)
"""

from __future__ import annotations

import mmap
import os
import struct
import threading
import time
from typing import Dict, Optional

from . import sidecar
from .sidecar import (
    OP_SET_ARENA,
    STATUS_OK,
    _FLAG_MASK,
    SupervisedClient,
    op_name,
    spawn_worker,
)

__all__ = [
    "SidecarPool",
    "connect_pool",
    "current_pool",
    "shutdown_pool",
    "stats_section",
]


def _env_int(name: str, default: int, minimum: int = 1) -> int:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        v = int(raw)
    except ValueError:
        import warnings

        warnings.warn(f"sidecar_pool: ignoring malformed {name}={raw!r}", stacklevel=2)
        return default
    return max(v, minimum)


class _Worker:
    """One supervised pool slot: the worker process, its socket, its
    client, and its liveness. The slot id (``wid``) is stable across
    respawns — metrics and routing name the SLOT, not the process.
    ``io_lock`` serializes frames on the worker's single supervised
    connection (concurrent callers of ``SidecarPool.call`` may route to
    the same slot); ``arena_conn`` remembers WHICH socket carried the
    last SET_ARENA — worker-side arena state is per-connection, so any
    reconnect invalidates it and the pool must replay."""

    __slots__ = (
        "wid", "proc", "sock_path", "client", "alive", "spawns",
        "io_lock", "arena_conn", "respawn_thread",
    )

    def __init__(self, wid: int):
        self.wid = wid
        self.proc = None
        self.sock_path: Optional[str] = None
        self.client: Optional[SupervisedClient] = None
        self.alive = False
        self.spawns = 0
        self.io_lock = threading.Lock()
        self.arena_conn = None
        self.respawn_thread: Optional[threading.Thread] = None


class SidecarPool:
    """Supervised pool of sidecar workers with health-checked routing,
    automatic respawn, arena re-hydration, and pool-scoped breaker
    accounting. ``call()`` is the public entry — same contract as
    ``SupervisedClient.call`` (results keep flowing: device path first,
    retry across workers, host engine as the floor), with worker death
    downgraded from "permanent degrade" to "one failover"."""

    def __init__(
        self,
        size: Optional[int] = None,
        deadline_s: Optional[float] = None,
        heartbeat_s: Optional[float] = None,
        env: Optional[dict] = None,
        startup_timeout_s: float = 60.0,
        spawn_fn=spawn_worker,
    ):
        if size is None:
            size = _env_int("SRJT_SIDECAR_POOL_SIZE", 1)
        if size < 1:
            raise ValueError(f"pool size must be >= 1, got {size}")
        self.size = int(size)
        self._deadline_s = deadline_s
        self._heartbeat_s = heartbeat_s
        self._env = dict(env) if env else None
        self._startup_timeout_s = float(startup_timeout_s)
        self._spawn_fn = spawn_fn
        self._respawn_max = _env_int("SRJT_POOL_RESPAWN_MAX", 3)
        from .utils.retry import env_float

        self._respawn_delay_s = env_float(
            os.environ, "SRJT_POOL_RESPAWN_DELAY_S", 0.5
        )
        self._lock = threading.RLock()
        # one shared arena => one in-flight arena op: the request bytes
        # at arena[0:len] and the response that replaces them are a
        # critical section across workers
        self._arena_io_lock = threading.Lock()
        self._rr = 0
        self._closed = False
        # client-side arena replay state: ONE memfd shared by every
        # worker (they all map the same pages), surviving any of them
        self._arena_fd: Optional[int] = None
        self._arena_size = 0
        self._arena_mm: Optional[mmap.mmap] = None
        self._workers = [_Worker(i) for i in range(self.size)]
        try:
            for w in self._workers:
                self._spawn_locked(w)
        except BaseException:
            self.shutdown()
            raise
        self._set_gauges()

    # -- lifecycle -----------------------------------------------------------

    def _reg(self):
        from .utils import metrics

        return metrics.registry()

    def _set_gauges(self) -> None:
        reg = self._reg()
        reg.gauge("sidecar.pool.size").set(self.size)
        reg.gauge("sidecar.pool.live").set(self.live_count())
        for w in self._workers:
            reg.gauge(f"sidecar.pool.worker.w{w.wid}.alive").set(
                1 if w.alive else 0
            )

    def _spawn_locked(self, w: _Worker) -> None:
        """Initial spawn of slot ``w`` (no arena exists yet; respawns
        go through ``_respawn``, which also re-hydrates state)."""
        proc, sock = self._spawn_fn(
            startup_timeout_s=self._startup_timeout_s, env=self._env
        )
        w.proc, w.sock_path = proc, sock
        w.client = SupervisedClient(
            sock, deadline_s=self._deadline_s, heartbeat_s=self._heartbeat_s
        )
        w.spawns += 1
        w.alive = True

    def shutdown(self) -> None:
        """Terminate every worker and release the arena. Idempotent.
        Joins in-flight respawn threads FIRST (bounded by one spawn
        attempt): a daemon respawner killed at interpreter exit while
        inside spawn_fn orphans its half-born worker — the child would
        outlive the pool, holding the chip and (if stdio is a pipe) the
        parent's readers. Once ``_closed`` is set the respawner reaps
        whatever it spawned and returns, so after the join every live
        proc is in a slot where the sweep below can reach it."""
        with self._lock:
            self._closed = True
            workers = list(self._workers)
        join_s = self._startup_timeout_s + self._respawn_delay_s + 10
        for w in workers:
            t = w.respawn_thread
            if t is not None and t.is_alive():
                t.join(timeout=join_s)
        for w in workers:
            if w.client is not None:
                w.client.close()
            if w.proc is not None and w.proc.poll() is None:
                w.proc.terminate()
        for w in workers:
            if w.proc is not None:
                try:
                    w.proc.wait(timeout=10)
                except Exception:
                    w.proc.kill()
            if w.sock_path:
                try:
                    os.unlink(w.sock_path)
                except OSError:
                    pass
            w.alive = False
        if self._arena_mm is not None:
            self._arena_mm.close()
            self._arena_mm = None
        if self._arena_fd is not None:
            os.close(self._arena_fd)
            self._arena_fd = None
            from . import memgov

            memgov.catalog().unregister("sidecar.pool.arena")
        self._set_gauges()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()

    # -- routing -------------------------------------------------------------

    def live_count(self) -> int:
        return sum(1 for w in self._workers if w.alive)

    def _pick(self) -> Optional[_Worker]:
        """Round-robin over live workers; None when the pool is dark."""
        with self._lock:
            n = len(self._workers)
            for i in range(n):
                w = self._workers[(self._rr + i) % n]
                if w.alive:
                    self._rr = (self._rr + i + 1) % n
                    return w
        return None

    def _on_worker_failure(self, w: _Worker, exc: BaseException) -> None:
        """A request died with its worker: mark the slot dead ONCE,
        count the failover (when living peers remain to fail over TO),
        and hand the slot to the background respawner."""
        from .utils import metrics

        reg = self._reg()
        with self._lock:
            if not w.alive or self._closed:
                return
            w.alive = False
            if w.client is not None:
                w.client.close()
            reg.counter("sidecar.pool.worker_deaths").inc()
            reg.gauge(f"sidecar.pool.worker.w{w.wid}.alive").set(0)
            live = self.live_count()
            reg.gauge("sidecar.pool.live").set(live)
            if live > 0:
                reg.counter("sidecar.pool.failovers").inc()
            metrics.event(
                "sidecar.pool.worker_death",
                wid=w.wid,
                live=live,
                cls=type(exc).__name__,
            )
            t = threading.Thread(
                target=self._respawn, args=(w,), daemon=True,
                name=f"srjt-pool-respawn-w{w.wid}",
            )
            w.respawn_thread = t  # shutdown joins this before reaping
            t.start()

    def _respawn(self, w: _Worker) -> None:
        """Background supervisor for one dead slot: reap the corpse,
        spawn a replacement (bounded attempts), re-hydrate state. The
        SPAWN happens outside the pool lock — routing to the surviving
        workers must never queue behind a replacement booting jax."""
        from .utils import metrics

        if w.proc is not None:
            sidecar._reap_worker(w.proc)
        if w.sock_path:
            try:
                os.unlink(w.sock_path)
            except OSError:
                pass
        for attempt in range(self._respawn_max):
            if self._closed or w.alive:
                return
            try:
                proc, sock = self._spawn_fn(
                    startup_timeout_s=self._startup_timeout_s, env=self._env
                )
            except BaseException as e:
                metrics.event(
                    "sidecar.pool.respawn_failed",
                    wid=w.wid, attempt=attempt, err=str(e)[:200],
                )
                time.sleep(self._respawn_delay_s)
                continue
            with self._lock:
                if self._closed:
                    sidecar._reap_worker(proc)
                    return
                w.proc, w.sock_path = proc, sock
                w.client = SupervisedClient(
                    sock,
                    deadline_s=self._deadline_s,
                    heartbeat_s=self._heartbeat_s,
                )
                w.spawns += 1
                has_arena = self._arena_fd is not None
            # state re-hydration OUTSIDE the pool lock (a wedged
            # replacement answering SET_ARENA slowly must not stall
            # routing to the survivors); nobody routes to this slot
            # until alive flips below, so its socket is private here
            try:
                if has_arena:
                    self._send_arena(w)
                    self._reg().counter("sidecar.pool.rehydrations").inc()
                    metrics.event("sidecar.pool.rehydrate", wid=w.wid)
            except BaseException as e:
                metrics.event(
                    "sidecar.pool.respawn_failed",
                    wid=w.wid, attempt=attempt, err=str(e)[:200],
                )
                sidecar._reap_worker(proc)
                continue
            with self._lock:
                if self._closed:
                    sidecar._reap_worker(proc)
                    return
                w.alive = True
                self._reg().counter("sidecar.pool.respawns").inc()
                self._set_gauges()
            metrics.event("sidecar.pool.respawn", wid=w.wid)
            return

    def wait_healthy(self, timeout_s: float = 60.0) -> bool:
        """Block until every slot is live (tests / operators)."""
        end = time.monotonic() + timeout_s
        while time.monotonic() < end:
            if self.live_count() == self.size:
                return True
            time.sleep(0.05)
        return self.live_count() == self.size

    # -- the data path -------------------------------------------------------

    def _attempt(
        self,
        op: int,
        payload: bytes,
        arena_len: Optional[int],
        arena_req: Optional[bytes] = None,
    ):
        """One routed exchange — the unit the retry orchestrator
        re-runs. Worker death re-raises retryably AFTER marking the
        slot dead, so the next attempt routes around the corpse: that
        re-route IS the failover. Arena requests REWRITE the request
        bytes (``arena_req``, snapshotted by ``call``) into the shared
        mapping first: the protocol answers at arena offset 0, so a
        prior attempt's (possibly partial) response must never be what
        the retry re-sends."""
        from .utils.errors import DataCorruption, RetryableError

        w = self._pick()
        if w is None:
            raise RetryableError(
                "sidecar pool: UNAVAILABLE: no live workers "
                f"(size={self.size}; respawn in progress or exhausted)"
            )
        try:
            if arena_len is None and self._arena_mm is None:
                # io_lock: one frame at a time on the slot's single
                # supervised connection (concurrent calls may route here)
                with w.io_lock:
                    return w.client.request(op, payload)
            # one shared arena => one in-flight op POOL-wide once it
            # exists: every worker maps the same pages and the protocol
            # opportunistically answers ANY fitting response through
            # them, so even a stream op on one worker would clobber an
            # arena op in flight on another — correctness over
            # concurrency here (arena-less pools keep per-slot routing)
            with self._arena_io_lock, w.io_lock:
                if arena_len is None:
                    return w.client.request(op, payload)
                # worker-side arena state is per-CONNECTION: replay
                # SET_ARENA if the client reconnected since the last
                # upload (timeout redial, desync close, respawn)
                self._ensure_arena(w)
                self._arena_mm[:arena_len] = arena_req
                return w.client.request(op, b"", arena_len=arena_len)
        except DataCorruption:
            # a corrupted FRAME is not a dead WORKER: the transport
            # round-tripped, the payload rotted. Retry re-sends; the
            # worker keeps its slot.
            raise
        except RetryableError as e:
            if self._worker_is_dead(w, e):
                self._on_worker_failure(w, e)
            raise

    @staticmethod
    def _worker_is_dead(w: _Worker, exc: BaseException) -> bool:
        """Transport faults and an exited process mean the WORKER is
        gone; a per-request deadline (DEADLINE_EXCEEDED) means it is
        slow — slow workers keep their slot (the breaker's deadline
        conflation stays a POOL-level verdict, not a slot eviction)."""
        if w.proc is not None and w.proc.poll() is not None:
            return True
        text = str(exc)
        return any(
            m in text
            for m in (
                "UNAVAILABLE",
                "Socket closed",
                "peer closed",
                "Connection refused",
                "Connection reset",
                "Broken pipe",
            )
        )

    def call(self, op: int, payload: bytes = b"", arena_len: Optional[int] = None) -> bytes:
        """Run ``op`` on the pool under the retry orchestrator: routed
        to a live worker, failed over on worker death, degraded to the
        in-process host engine only when the device path truly cannot
        answer. Breaker discipline (ISSUE 5): the process-global
        breaker records a FAILURE only when the op failed with the
        WHOLE pool dark — one crashed worker among living peers is a
        failover, invisible to the breaker.

        Arena contract: write the request into the shared mapping and
        pass ``arena_len=``; the arena is SCRATCH (responses land at
        offset 0), so rewrite before every call. Within one call the
        pool snapshots the request up front and replays it into the
        arena before every retry attempt — a dead worker's partial
        response can never be what the failover re-sends."""
        from .utils import deadline as deadline_mod, metrics, retry
        from .utils.errors import DeadlineExceeded, DeviceError

        deadline_mod.check(f"sidecar_pool_op_{op}")
        arena_req = None
        if arena_len is not None:
            if self._arena_mm is None:
                raise ValueError(
                    "arena_len given but no arena is set (set_arena first)"
                )
            # snapshot the request NOW: every attempt (and the host
            # fallback) replays these bytes — the shared arena itself is
            # scratch the previous attempt's response may have clobbered
            arena_req = bytes(self._arena_mm[:arena_len])
        br = sidecar.breaker()
        if not br.allow():
            self._host_fallback_count(op, "breaker_open")
            return sidecar._dispatch(
                op, payload if arena_req is None else arena_req, "host-fallback"
            )
        try:
            resp = retry.call_with_retry(
                self._attempt, op, payload, arena_len, arena_req,
                op_name=f"sidecar_pool_op_{op}",
            )
        except DeadlineExceeded:
            # same deliberate conflation as SupervisedClient.call: a
            # pool that cannot answer inside the budget is unavailable
            # for breaker purposes — unless the user cancelled
            d = deadline_mod.current()
            if d is not None and d.cancelled() and not d.expired():
                br.abort_probe()
            else:
                br.record_failure(cause="deadline")
            raise
        except DeviceError as e:
            if self.live_count() == 0:
                # the WHOLE pool is dark: this is what the breaker
                # exists to remember
                br.record_failure(cause=type(e).__name__)
            self._host_fallback_count(op, type(e).__name__)
            return sidecar._dispatch(
                op, payload if arena_req is None else arena_req, "host-fallback"
            )
        except Exception:
            br.record_success()  # semantic error: transport healthy
            raise
        except BaseException:
            br.abort_probe()
            raise
        br.record_success()
        return resp

    def _host_fallback_count(self, op: int, cause: str) -> None:
        from .utils import metrics

        self._reg().counter("sidecar.pool.host_fallbacks").inc()
        metrics.counter("sidecar.host_fallbacks").inc()
        metrics.event("sidecar.pool.degrade_to_host", op=op_name(op), cls=cause)

    # -- the shared-memory data plane ----------------------------------------

    def set_arena(self, size: int) -> mmap.mmap:
        """Create the pool's shared arena (one memfd) and upload it to
        every live worker. Returns the client-side mapping — write a
        payload into it and pass ``arena_len=`` to ``call``. The memfd
        outlives any single worker: respawns re-upload it
        (re-hydration), so a kill -9 never strands the data plane.
        Registered host-tier in the memgov catalog
        (``sidecar.pool.arena``) like every other arena consumer."""
        from . import memgov

        with self._lock:
            if self._arena_fd is not None:
                self._arena_mm.close()
                os.close(self._arena_fd)
                memgov.catalog().unregister("sidecar.pool.arena")
            fd = os.memfd_create("srjt-pool-arena")
            os.ftruncate(fd, size)
            self._arena_fd = fd
            self._arena_size = int(size)
            self._arena_mm = mmap.mmap(fd, size)
            memgov.catalog().register_host_bytes(
                "sidecar.pool.arena", size, pinned=True, kind="arena"
            )
            live = [w for w in self._workers if w.alive]
        # the upload round-trips run OUTSIDE the pool lock (a slow
        # worker must not stall routing), serialized per worker
        for w in live:
            try:
                with w.io_lock:
                    self._send_arena(w)
            except Exception as e:
                self._on_worker_failure(w, e)
        return self._arena_mm

    def _send_arena(self, w: _Worker) -> None:
        """OP_SET_ARENA with the pool memfd over SCM_RIGHTS on the
        worker's supervised socket (legacy framing: the fd transfer is
        control plane, 8 payload bytes — nothing for a CRC to protect
        that the OK/err status doesn't already say). Records WHICH
        socket carried the upload (worker-side arena state is
        per-connection) and hands the client the mapping so it can read
        arena-flagged responses."""
        import array
        import socket as socket_mod

        c = w.client
        if c._sock is None:
            c.connect()
        hdr = struct.pack("<IQ", OP_SET_ARENA, 8) + struct.pack("<Q", self._arena_size)
        c._sock.sendmsg(
            [hdr],
            [(
                socket_mod.SOL_SOCKET,
                socket_mod.SCM_RIGHTS,
                array.array("i", [self._arena_fd]).tobytes(),
            )],
        )
        status, rlen = struct.unpack("<IQ", sidecar._recv_exact(c._sock, 12))
        body = sidecar._recv_exact(c._sock, rlen) if rlen else b""
        if (status & ~_FLAG_MASK) != STATUS_OK:
            from .utils.errors import RetryableError

            raise RetryableError(
                f"sidecar pool: SET_ARENA failed on w{w.wid}: "
                f"{body.decode('utf-8', 'replace')}"
            )
        c.arena_mm = self._arena_mm
        w.arena_conn = c._sock

    def _ensure_arena(self, w: _Worker) -> None:
        """Replay SET_ARENA when the supervised connection is not the
        one that carried the last upload — a timeout redial, a desync
        close, or a fresh client all silently dropped the worker-side
        mapping, and an arena op on such a connection would error (or
        worse, a stale client would trust stale pages)."""
        c = w.client
        if c._sock is not None and c._sock is w.arena_conn:
            return
        self._send_arena(w)
        self._reg().counter("sidecar.pool.rehydrations").inc()
        from .utils import metrics

        metrics.event("sidecar.pool.rehydrate", wid=w.wid, cause="reconnect")

    # -- observability -------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-clean pool state for runtime.stats_report()."""
        reg = self._reg()
        with self._lock:
            return {
                "size": self.size,
                "live": self.live_count(),
                "workers": {
                    f"w{w.wid}": {
                        "alive": w.alive,
                        "spawns": w.spawns,
                        "pid": None if w.proc is None else w.proc.pid,
                    }
                    for w in self._workers
                },
                "failovers": reg.value("sidecar.pool.failovers"),
                "worker_deaths": reg.value("sidecar.pool.worker_deaths"),
                "respawns": reg.value("sidecar.pool.respawns"),
                "rehydrations": reg.value("sidecar.pool.rehydrations"),
                "host_fallbacks": reg.value("sidecar.pool.host_fallbacks"),
                "arena_bytes": self._arena_size if self._arena_fd is not None else 0,
            }

    def worker_stats(self, fold: bool = True) -> Dict[str, dict]:
        """Poll every LIVE worker's STATS verb; returns snapshots keyed
        per worker id. With ``fold`` (default) each worker's counters
        land in this process's registry as ``sidecar.worker.w<id>.*``
        gauges — the per-worker keying runtime.device_stats merges
        instead of assuming one connection (ISSUE 5 satellite)."""
        from .utils import metrics
        from .utils.errors import RetryableError

        out: Dict[str, dict] = {}
        for w in list(self._workers):
            if not w.alive or w.client is None:
                continue
            try:
                # same lock discipline as _attempt: once a shared arena
                # exists the worker may answer THROUGH it, so a STATS
                # poll must not interleave with an in-flight data op
                with self._arena_io_lock, w.io_lock:
                    stats = w.client.worker_stats(fold=False)
            except RetryableError:
                continue  # died between the liveness check and the poll
            out[f"w{w.wid}"] = stats
            if fold:
                counters = (stats.get("snapshot") or {}).get("counters") or {}
                # worker counters already live under sidecar.worker.*;
                # strip that base before the per-worker prefix so the
                # fold lands at sidecar.worker.w<id>.requests.PING, not
                # a stuttered sidecar.worker.w0.sidecar.worker....
                base = "sidecar.worker."
                metrics.fold_worker_counters(
                    {
                        (k[len(base):] if k.startswith(base) else k): v
                        for k, v in counters.items()
                    },
                    prefix=f"sidecar.worker.w{w.wid}.",
                )
        return out


# ---------------------------------------------------------------------------
# process-global pool (one chip, one supervised pool — mirrors breaker())
# ---------------------------------------------------------------------------

_POOL: Optional[SidecarPool] = None
_POOL_LOCK = threading.Lock()


def connect_pool(**kwargs) -> SidecarPool:
    """Create (or return) the process-global pool. Keyword overrides
    apply only on first creation."""
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            _POOL = SidecarPool(**kwargs)
    return _POOL


def current_pool() -> Optional[SidecarPool]:
    """The process-global pool if one is connected, else None — stats
    paths (runtime.device_stats / stats_report) consult this without
    ever spawning workers as a side effect."""
    return _POOL


def shutdown_pool() -> None:
    global _POOL
    with _POOL_LOCK:
        p, _POOL = _POOL, None
    if p is not None:
        p.shutdown()


def stats_section() -> Optional[dict]:
    """The ``pool`` section of runtime.stats_report(): None when no
    pool has been connected (the seed posture)."""
    p = current_pool()
    return None if p is None else p.snapshot()

"""End-to-end data integrity: CRC-checked frames and spills (ISSUE 5).

Thallus (PAPERS.md) checksums its columnar transport frames because on
a data path, silent corruption is indistinguishable from a wrong
answer: a truncated-then-resynced socket frame or a bit-rotted spill
file returns plausible rows, not an error. Until this module the stack
had zero checksums anywhere. It now provides the ONE helper every
data-at-rest / data-in-flight boundary shares:

- sidecar wire frames (sidecar.py): request and response payloads in
  BOTH directions carry a 4-byte CRC trailer right after the 12-byte
  header (the ``CRC_FLAG`` high bit of op/status marks its presence,
  negotiated per frame so the native C++ client — which never sets the
  bit — keeps its existing framing),
- the columnar frame codec (columnar/frames.py, ISSUE 6): every frame
  carries a header CRC plus one CRC per column/leaf payload, all drawn
  from and verified through this helper — wire tables, memgov disk
  spills (legacy SRJTSPL1 npz envelopes still verify through their
  original path), and TCP shuffle exchange partitions share it,
- in-mesh shuffle exchanges (parallel/shuffle.py): an order-independent
  payload checksum over the bytes entering and leaving the all-to-all
  (row order changes across the exchange, byte MULTISET must not).

A mismatch anywhere raises ``DataCorruption`` (utils/errors.py) — a
RETRYABLE taxonomy member, so the retry/split machinery re-fetches or
re-executes instead of returning wrong data — and lands registry-direct
under ``sidecar.integrity.*`` (``crc_mismatch`` total plus a
per-surface ``crc_mismatch.<where>`` breakdown; the durable-counter
contract: corruption is a rare recovery event, never gated off).

Checksum algorithm: CRC-32C (Castagnoli) via the optional ``crc32c``
accelerator module when importable, else zlib's C-speed CRC-32. The
polynomial choice is process-local and symmetric — every producer and
consumer (sidecar worker child processes included: they inherit the
same interpreter/env) resolves the same implementation through this
helper, so the two ends of a frame always agree. The trailer carries
no algorithm id; a deployment must not mix interpreters with and
without the accelerator across the sidecar boundary (PACKAGING.md
knob table).

Environment:

    SRJT_INTEGRITY_CHECKS  "0"/"false" disables every check (frames go
                           out without trailers, spills skip verify,
                           exchanges skip the payload checksum — the
                           seed posture, no extra syscalls or hashing
                           anywhere). Default: enabled.
"""

from __future__ import annotations

import contextlib
import struct
import zlib

from . import knobs
from .errors import DataCorruption

__all__ = [
    "checksum",
    "checksum_name",
    "verify",
    "raise_corruption",
    "enable",
    "disable",
    "is_enabled",
    "enabled",
    "disabled",
    "CRC_LEN",
    "pack_crc",
    "unpack_crc",
]

CRC_LEN = 4  # the trailer is one little-endian u32, whatever the impl

try:  # optional accelerator: real CRC-32C when the wheel is present
    import crc32c as _crc32c_mod

    def _crc(data, value: int = 0) -> int:
        return _crc32c_mod.crc32c(data, value)

    _CRC_NAME = "crc32c"
except ImportError:  # zlib's C implementation: same 32-bit contract

    def _crc(data, value: int = 0) -> int:
        return zlib.crc32(data, value)

    _CRC_NAME = "crc32-zlib"


def checksum(data, value: int = 0) -> int:
    """32-bit CRC of ``data`` (bytes-like); chainable via ``value``."""
    return _crc(data, value) & 0xFFFFFFFF


def checksum_name() -> str:
    """Which implementation this process resolved (observability)."""
    return _CRC_NAME


def pack_crc(crc: int) -> bytes:
    return struct.pack("<I", crc & 0xFFFFFFFF)


def unpack_crc(raw: bytes, offset: int = 0) -> int:
    return struct.unpack_from("<I", raw, offset)[0]


# ---------------------------------------------------------------------------
# enable gate (one boolean read on every guarded path)
# ---------------------------------------------------------------------------

_enabled = knobs.get_bool("SRJT_INTEGRITY_CHECKS")


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def is_enabled() -> bool:
    return _enabled


@contextlib.contextmanager
def enabled():
    global _enabled
    prev = _enabled
    _enabled = True
    try:
        yield
    finally:
        _enabled = prev


@contextlib.contextmanager
def disabled():
    global _enabled
    prev = _enabled
    _enabled = False
    try:
        yield
    finally:
        _enabled = prev


# ---------------------------------------------------------------------------
# verification + the corruption accounting every surface shares
# ---------------------------------------------------------------------------


def raise_corruption(where: str, detail: str = "") -> "DataCorruption":
    """Count a CRC mismatch (total + per-surface) and return the
    DataCorruption to raise — callers ``raise raise_corruption(...)``
    so the counter can never drift from the error. The message carries
    the taxonomy prefix the sidecar wire protocol re-classifies on."""
    from . import metrics

    reg = metrics.registry()
    reg.counter("sidecar.integrity.crc_mismatch").inc()
    reg.counter(f"sidecar.integrity.crc_mismatch.{where}").inc()
    metrics.event("integrity.crc_mismatch", where=where, detail=detail)
    return DataCorruption(
        f"CRC mismatch in {where}{f' ({detail})' if detail else ''} — "
        "payload corrupted in flight or at rest; re-fetch required"
    )


def verify(data, expected_crc: int, where: str) -> None:
    """Check ``data`` against the expected 32-bit CRC; mismatch counts
    and raises DataCorruption. No-op while the gate is off."""
    if not _enabled:
        return
    got = checksum(data)
    if got != (expected_crc & 0xFFFFFFFF):
        raise raise_corruption(
            where, f"expected 0x{expected_crc & 0xFFFFFFFF:08x}, got 0x{got:08x}"
        )


def stats_section() -> dict:
    """The ``integrity`` section of runtime.stats_report()."""
    from . import metrics

    reg = metrics.registry()
    return {
        "enabled": _enabled,
        "algorithm": _CRC_NAME,
        "crc_mismatch": reg.value("sidecar.integrity.crc_mismatch"),
        "frames_checked": reg.value("sidecar.integrity.frames_checked"),
        "spills_checked": reg.value("sidecar.integrity.spills_checked"),
        "exchanges_checked": reg.value("sidecar.integrity.exchanges_checked"),
        # columnar frame codec decodes that ran with verification
        # (columnar/frames.py — wire tables, spills, TCP exchanges)
        "frame_decodes_checked": reg.value(
            "sidecar.integrity.frame_decodes_checked"
        ),
    }

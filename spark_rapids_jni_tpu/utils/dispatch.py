"""Op-boundary dispatch wrapper: the JNI-entry-point analog.

Every reference JNI export runs the same preamble — device binding,
exception translation, NVTX range (RowConversionJni.cpp:42-57 pattern,
SURVEY §2.2). ``op_boundary`` is that preamble for the TPU build: fault
injection hook, tracing scope, and backend-error classification
(fatal vs retryable) in one decorator applied to public ops.
"""

from __future__ import annotations

import functools

from . import faultinj, tracing
from .errors import DeviceError, classify

__all__ = ["op_boundary"]


def op_boundary(name: str):
    """Wrap a public op with the dispatch preamble.

    - ``faultinj.maybe_inject(name)`` fires configured faults first
      (the CUPTI-callback interception point),
    - ``tracing.func_range(name)`` scopes the body for XProf,
    - backend exceptions are classified into Fatal/Retryable
      (CATCH_STD analog); host-side ValueError/TypeError/KeyError/
      IndexError pass through unchanged.
    """

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            faultinj.maybe_inject(name)
            with tracing.func_range(name):
                try:
                    return fn(*args, **kwargs)
                except DeviceError:
                    raise
                except (ValueError, TypeError, KeyError, IndexError):
                    raise
                except Exception as e:  # backend / runtime failures
                    if type(e).__module__.startswith("spark_rapids_jni_tpu"):
                        # the op's own documented API errors (CastError,
                        # ParquetReadError, ...) are results, not failures
                        raise
                    raise classify(e) from e

        return wrapper

    return deco

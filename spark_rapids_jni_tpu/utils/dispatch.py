"""Op-boundary dispatch wrapper: the JNI-entry-point analog.

Every reference JNI export runs the same preamble — device binding,
exception translation, NVTX range (RowConversionJni.cpp:42-57 pattern,
SURVEY §2.2). ``op_boundary`` is that preamble for the TPU build: fault
injection hook, tracing scope, backend-error classification (fatal vs
retryable), deadline scope/cancel point (utils/deadline.py), and —
when the retry orchestrator is armed (utils/retry.py,
``SRJT_RETRY_ENABLED=1``) — bounded retry with exponential backoff for
RetryableError, all in one decorator applied to public ops.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Dict, Optional

from . import deadline, faultinj, metrics, tracing
from .errors import DeviceError, classify
from .. import memgov

__all__ = ["op_boundary", "note_tier"]


# Kernel-tier observability (ISSUE 13): tiered ops report which
# formulation actually served a dispatch — ``pallas`` (kernel tier),
# ``xla`` (the fallback formulation), or ``host`` (host-engine
# degrade). Counted REGISTRY-DIRECT (the memory.split_retries
# discipline: durable bookkeeping, independent of the
# SRJT_METRICS_ENABLED hot-path gate) so BENCH drivers and the premerge
# kernel-tier gate can prove the pallas path engaged; with tracing
# armed the tier also lands as an annotation on the active op span, so
# flight-recorder output shows which kernel a slow query ran. Handles
# are cached (the record_op idiom): one dict read per note after the
# first dispatch of a tier.
_tier_handles: Dict[str, object] = {}
_tier_handles_lock = threading.Lock()


def note_tier(tier: str, op: Optional[str] = None) -> None:
    """Record the serving tier of the current dispatch (see above)."""
    c = _tier_handles.get(tier)
    if c is None:
        with _tier_handles_lock:
            c = _tier_handles.get(tier)
            if c is None:
                c = metrics.registry().counter(f"dispatch.tier.{tier}")
                _tier_handles[tier] = c
    c.inc()
    if metrics.is_enabled() and op is not None:
        metrics.event("dispatch.tier", op=op, tier=tier)
    if tracing.is_enabled():
        tracing.annotate(tier=tier)


def _run_boundary(attempt, name: str):
    """The dispatch core shared by the scoped and unscoped deadline
    branches of ``op_boundary``: retry arming + metrics timing. Only the
    OUTERMOST boundary owns the retry loop — a nested op's
    RetryableError propagates to the outer attempt, so a persistent
    failure costs max_attempts total re-runs, not
    max_attempts^nesting-depth. The retry-dispatch decision is written
    out twice so the disarmed-metrics path touches no clock."""
    from . import retry

    if not metrics.is_enabled():
        if retry.is_enabled() and not retry.in_attempt():
            return retry.call_with_retry(attempt, op_name=name)
        return attempt()
    t0 = time.perf_counter()
    try:
        if retry.is_enabled() and not retry.in_attempt():
            return retry.call_with_retry(attempt, op_name=name)
        return attempt()
    finally:
        metrics.record_op(name, time.perf_counter() - t0)


def op_boundary(name: str):
    """Wrap a public op with the dispatch preamble.

    - ``faultinj.maybe_inject(name)`` fires configured faults first
      (the CUPTI-callback interception point); injection sits INSIDE
      the retry attempt so chaos-injected RetryableErrors exercise the
      recovery path, not just the classification,
    - ``tracing.func_range(name)`` scopes the body for XProf,
    - backend exceptions are classified into Fatal/Retryable
      (CATCH_STD analog); host-side ValueError/TypeError/KeyError/
      IndexError pass through unchanged,
    - DEADLINE (utils/deadline.py): every wrapped op accepts a reserved
      ``deadline_s=`` keyword that opens a per-call budget scope; with
      none, the OUTERMOST boundary under an ambient ``SRJT_DEADLINE_SEC``
      opens the per-query scope — so one knob bounds the whole dispatch
      including retries and backoff sleeps. Nested boundaries do not
      open new scopes; they are cancel points consuming the enclosing
      budget (``DeadlineExceeded`` raises before the body runs once the
      budget is gone or the cancel token tripped). With no deadline
      anywhere the extra cost is one reserved-kwarg pop plus a
      context-var read,
    - with the retry orchestrator armed, RetryableError re-runs the op
      under the module RetryPolicy; FatalDeviceError NEVER retries.
      Disarmed (the default), RetryableError propagates to the caller
      unchanged — the seed's Spark-task-retry contract,
    - with the metrics subsystem armed (utils/metrics.py,
      ``SRJT_METRICS_ENABLED=1``), every dispatch records a call count
      and wall-clock histogram (``op.<name>.calls`` /
      ``op.<name>.wall_us``) spanning the full boundary including any
      retries/backoff; disarmed, the only cost is one boolean read —
      no clock, no registry touch,
    - MEMORY GOVERNOR (memgov/, ISSUE 4): with the governor armed
      (``SRJT_SPILL_ENABLED``, or implicitly by a declared
      ``SRJT_DEVICE_MEMORY_BUDGET``), the OUTERMOST boundary on a
      thread acquires the byte-weighted admission semaphore with the
      op's footprint estimate before dispatch — the reserved
      ``memory_bytes=`` keyword overrides the default input-bytes ×
      ``SRJT_MEMGOV_HEADROOM`` estimate — and releases it after.
      Admission sits INSIDE the retry attempt: a retryable admission
      denial (``MemoryBudgetExceeded``) rides the orchestrator's
      backoff/split machinery like any other RESOURCE_EXHAUSTED class.
      Disarmed (the default), the cost is one reserved-kwarg pop plus
      one boolean read — the metrics-stub pattern.
    """

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            budget_s = kwargs.pop("deadline_s", None)
            mem_bytes = kwargs.pop("memory_bytes", None)

            def attempt():
                faultinj.maybe_inject(name)
                adm = (
                    memgov.admit(name, args, kwargs, mem_bytes)
                    if memgov.is_enabled()
                    else None
                )
                try:
                    with tracing.func_range(name):
                        try:
                            return fn(*args, **kwargs)
                        except DeviceError:
                            raise
                        except (ValueError, TypeError, KeyError, IndexError):
                            raise
                        except Exception as e:  # backend / runtime failures
                            if type(e).__module__.startswith("spark_rapids_jni_tpu"):
                                # the op's own documented API errors (CastError,
                                # ParquetReadError, ...) are results, not failures
                                raise
                            raise classify(e) from e
                finally:
                    if adm is not None:
                        adm.release()

            # deadline scoping mirrors the retry nesting guard inside
            # _run_boundary: one scope per query, owned by the boundary
            # that opened it. The common fully-disarmed path pays two
            # kwargs.pops, two boolean reads (memgov + tracing gates),
            # a context-var read, and two extra frames (_run_boundary
            # and `scoped`) on top of what the seed paid — no clock, no
            # context manager.
            def scoped():
                dl = deadline.current()
                bs = budget_s
                if bs is None and dl is None:
                    bs = deadline.default_budget()
                if bs is not None:
                    with deadline.scope(bs) as d:
                        d.check(name)
                        return _run_boundary(attempt, name)
                if dl is not None:
                    dl.check(name)  # nested boundary: cancel point only
                return _run_boundary(attempt, name)

            # srjt-trace (ISSUE 12): the op span covers the WHOLE
            # boundary — deadline scope, every retry attempt, every
            # backoff — so retry annotations and split child spans
            # (utils/retry.py) land inside it. A nested boundary's span
            # is a child; the OUTERMOST boundary with no active trace
            # auto-roots a one-op trace (tracing.op_span policy).
            if tracing.is_enabled():
                with tracing.op_span(name):
                    return scoped()
            return scoped()

        return wrapper

    return deco

"""Deterministic fault injection at the op-dispatch boundary.

TPU-native analog of the reference's CUPTI injector (faultinj.cu, SURVEY
§2.4/§3.5): instead of hooking the CUDA driver, faults fire inside the
``op_boundary`` dispatch wrapper (utils/dispatch.py) — the same choke
point every public op crosses, which is where a PJRT-level hook would
sit. Feature parity:

- JSON config (reference: FAULT_INJECTOR_CONFIG_PATH, :80, :346-408),
  env var ``SRJT_FAULTINJ_CONFIG`` or programmatic ``configure()``,
- match by exact op name, a ``"prefix.*"`` rule (longest prefix wins —
  keys a whole choke-point family, e.g. ``"exchange.*"`` covers
  ``exchange.serve`` and ``exchange.frame``), or the ``"*"`` wildcard
  (:142-152),
- PER-WORKER targeting (ISSUE 9): any key may carry an ``@<tag>``
  suffix (``sidecar.worker.GROUPBY_SUM_F32@w1``) that matches only in
  a process whose ``SRJT_FAULTINJ_WORKER`` tag equals ``<tag>`` — the
  worker pool stamps every spawned worker ``w<slot>``, so ONE gray
  worker can be simulated deterministically while its peers stay
  clean. PER-RANK targeting (ISSUE 16) rides the same suffix:
  ``@r<N>`` keys match a process whose ``SRJT_FAULTINJ_RANK`` tag
  equals ``r<N>`` (the exchange-worker harness stamps every spawned
  rank), so a cluster profile can partition or kill exactly one rank.
  Resolution specificity, most-specific first:
  ``op@tag`` > ``op`` > longest ``prefix.*@tag`` > longest
  ``prefix.*`` > ``*@tag`` > ``*``,
- injection types: ``fatal`` (FatalDeviceError — the trap/assert
  analog, :135-140), ``retryable`` (RetryableError), ``exception``
  (plain RuntimeError — the FI_RETURN_VALUE analog), ``delay``
  (injected latency of ``delayMs`` milliseconds, no exception — the
  wedged-kernel analog that exercises timeout/deadline paths),
  ``hang`` (a sleep of ``delayMs`` milliseconds — default 30000,
  deliberately far past any sane deadline — that COOPERATIVELY polls
  the context-local deadline/cancel token (utils/deadline.py) and
  aborts with DeadlineExceeded the moment the budget dies: the chaos
  tool for deadline-expiry and circuit-breaker paths; with no active
  deadline the full hang is slept), ``spill_fail`` (a RetryableError
  meant for the memory governor's demotion choke point — key the rule
  ``"memgov.spill"``, which the spillable catalog (memgov/catalog.py)
  crosses on every spill; the catalog absorbs the failure, counts it,
  and keeps the entry resident), ``crash`` (the process SIGKILLs
  ITSELF the moment the rule fires — armed inside a sidecar worker,
  whose request loop injects under ``sidecar.worker.<OP>`` keys, this
  is the kill-9-mid-query chaos the worker-pool failover tier
  (sidecar_pool.py) exists to survive: the request is consumed, no
  response is ever written, the client sees a dead transport), ``corrupt``
  (byte-flips a payload AFTER its CRC is computed — modeling in-flight
  corruption the integrity layer (utils/integrity.py) must catch;
  inert under ``maybe_inject``, it fires only through
  ``maybe_corrupt(op, data)``, the hook the sidecar worker crosses on
  every response), ``reject`` (raises the retryable ``Overloaded``
  taxonomy member — key the rule ``"serve.admit"``, the choke point
  the serving scheduler (serve/) crosses on every submission, and the
  chaos tier exercises the shed path deterministically without real
  overload; ``delayMs`` doubles as the injected ``retry_after_s`` hint
  in milliseconds), ``netsplit`` (raises ``ConnectionRefusedError`` —
  the dropped/refused-TCP-connect analog for partition chaos; key it
  ``exchange.connect`` (the choke point every TCP exchange fetch
  crosses before its socket connect) with an ``@r<N>`` rank tag to
  partition exactly one rank: the client-side UNAVAILABLE
  classification and the cluster liveness/recovery machinery see
  precisely what a real network partition produces), ``torn_write``
  (truncates a durable record mid-write — inert under
  ``maybe_inject``, it fires only through ``maybe_torn(op, data)``,
  the hook the query journal (``journal.append``) and spill-manifest
  writer (``memgov.manifest``) cross on every record; ``delayMs``
  carries the bytes kept, so replay-past-torn-tail is
  deterministically testable),
- ``percent`` probability + ``interceptionCount`` budget (:255-315),
- per-rule SCHEDULING so chaos tests hit backoff/timeout paths
  deterministically: ``after`` skips the first N matching dispatches
  before the rule arms (faults mid-pipeline, not at step one);
  ``ramp`` scales the effective percent linearly from ``percent/ramp``
  up to ``percent`` over the first ``ramp`` armed dispatches (a storm
  that builds instead of a step function),
- deterministic via ``seed`` (:369-392),
- hot reload: config file mtime is polled on each dispatch (the
  inotify-thread analog, :429-480) when loaded from a path.

Config schema (faultinj/README.md:61-141 shape)::

    {
      "seed": 12345,
      "faults": {
        "convert_to_rows": {"type": "retryable", "percent": 50,
                             "interceptionCount": 2},
        "all_to_all_exchange": {"type": "delay", "percent": 30,
                                 "delayMs": 5, "after": 2, "ramp": 4},
        "hash_partition": {"type": "hang", "percent": 50,
                            "delayMs": 30000},
        "*": {"type": "fatal", "percent": 1}
      }
    }
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Dict, Optional

from .errors import FatalDeviceError, Overloaded, RetryableError

__all__ = [
    "configure",
    "configure_from_file",
    "disable",
    "maybe_inject",
    "maybe_corrupt",
    "maybe_torn",
    "is_enabled",
    "CacheEvictInjected",
]


class CacheEvictInjected(RuntimeError):
    """The ``cache_evict`` chaos payload (srjt-cache, ISSUE 17): raised
    out of ``maybe_inject("cache.<layer>.<key>")`` at the cache's
    lookup choke point. The cache layer CATCHES it, drops the named
    entry, counts ``cache.evict_injected``, and proceeds as a miss —
    the acceptance contract is that a poisoned/evicted entry recomputes
    and never serves stale bytes, so this never escapes to a caller."""


class _Rule:
    __slots__ = ("kind", "percent", "budget", "delay_ms", "after", "ramp", "calls")

    def __init__(
        self,
        kind: str,
        percent: float,
        budget: Optional[int],
        delay_ms: float = 0.0,
        after: int = 0,
        ramp: int = 0,
    ):
        self.kind = kind
        self.percent = percent
        self.budget = budget  # None == unlimited
        self.delay_ms = delay_ms  # kind == "delay" only
        self.after = after  # matching dispatches to skip before arming
        self.ramp = ramp  # armed dispatches over which percent scales in
        self.calls = 0  # matching dispatches seen (scheduling state)


class _State:
    def __init__(self):
        self.lock = threading.Lock()
        self.rules: Dict[str, _Rule] = {}
        self.rng = random.Random()
        self.path: Optional[str] = None
        self.mtime: float = 0.0
        self.enabled = False
        self.worker_tag: Optional[str] = None  # SRJT_FAULTINJ_WORKER
        self.rank_tag: Optional[str] = None  # SRJT_FAULTINJ_RANK


_state = _State()


def _parse(cfg: dict) -> None:
    # the process's worker tag is latched per configure (the spawned
    # worker reads its env-stamped slot name once, with the profile)
    from . import knobs as _k

    _state.worker_tag = _k.get_str("SRJT_FAULTINJ_WORKER") or None
    _state.rank_tag = _k.get_str("SRJT_FAULTINJ_RANK") or None
    _state.rules = {}
    for name, spec in (cfg.get("faults") or {}).items():
        kind = spec.get("type", "retryable")
        if kind not in ("fatal", "retryable", "exception", "delay", "hang",
                        "spill_fail", "crash", "corrupt", "reject",
                        "netsplit", "cache_evict", "torn_write"):
            raise ValueError(f"faultinj: unknown fault type {kind!r}")
        percent = float(spec.get("percent", 100))
        budget = spec.get("interceptionCount")
        # a hang exists to outlive deadlines: its default sleep is 30 s,
        # not the delay kind's 50 ms latency blip
        delay_ms = float(spec.get("delayMs", 30000.0 if kind == "hang" else 50))
        after = int(spec.get("after", 0))
        ramp = int(spec.get("ramp", 0))
        if delay_ms < 0 or after < 0 or ramp < 0:
            raise ValueError("faultinj: delayMs/after/ramp must be non-negative")
        _state.rules[name] = _Rule(
            kind, percent, None if budget is None else int(budget), delay_ms, after, ramp
        )
    _state.rng = random.Random(cfg.get("seed"))


def configure(cfg: dict) -> None:
    """Install a config programmatically (tests / in-process chaos)."""
    with _state.lock:
        _state.path = None
        _parse(cfg)
        _state.enabled = bool(_state.rules)


def configure_from_file(path: str) -> None:
    with _state.lock:
        with open(path) as f:
            _parse(json.load(f))
        _state.path = path
        _state.mtime = os.stat(path).st_mtime
        # file-backed configs stay active even when currently empty, so
        # the hot-reload poll keeps running (inotify-thread analog)
        _state.enabled = True


def disable() -> None:
    with _state.lock:
        _state.rules = {}
        _state.enabled = False
        _state.path = None


def is_enabled() -> bool:
    return _state.enabled


def _reload_if_changed() -> None:
    if _state.path is None:
        return
    try:
        m = os.stat(_state.path).st_mtime
    except OSError:
        return
    if m != _state.mtime:
        with open(_state.path) as f:
            _parse(json.load(f))
        _state.mtime = m


def _resolve_rule_locked(op_name: str) -> Optional[_Rule]:
    """Rule resolution, most-specific first (ISSUE 9): exact with one
    of this process's tags (``op@w1``, ``op@r2``) > plain exact >
    longest tag-suffixed prefix family (``prefix.*@w1``) > longest
    plain prefix family > tagged wildcard (``*@w1``) > bare ``*``.
    A process may carry BOTH a worker tag (SRJT_FAULTINJ_WORKER) and a
    rank tag (SRJT_FAULTINJ_RANK, ISSUE 16) — each specificity level
    tries the worker tag first, then the rank tag. Keys carrying a
    FOREIGN tag never match, so one profile can ramp a single gray
    worker — or partition a single exchange rank — while its peers
    run the same config clean."""
    tags = [t for t in (_state.worker_tag, _state.rank_tag) if t]
    for tag in tags:
        rule = _state.rules.get(f"{op_name}@{tag}")
        if rule is not None:
            return rule
    rule = _state.rules.get(op_name)
    if rule is not None:
        return rule
    for suffix in [f"@{t}" for t in tags] + [""]:
        best, best_len = None, -1
        for key, r in _state.rules.items():
            if suffix and not key.endswith(suffix):
                continue
            stem = key[: len(key) - len(suffix)] if suffix else key
            if "@" in stem:
                continue  # a foreign (or any) tag on the plain pass
            if (
                stem.endswith(".*")
                and op_name.startswith(stem[:-1])
                and len(stem) > best_len
            ):
                best, best_len = r, len(stem)
        if best is not None:
            return best
    for tag in tags:
        rule = _state.rules.get(f"*@{tag}")
        if rule is not None:
            return rule
    return _state.rules.get("*")


# rule families: each producer-side hook services only its own kinds,
# so a ``corrupt`` (or ``torn_write``) rule never burns scheduling
# state or budget on a ``maybe_inject`` dispatch — its choke point is
# the payload producer — and vice versa
_PRODUCER_FAMILIES = {"corrupt": "corrupt", "torn_write": "torn_write"}


def _draw_locked(op_name: str, family: str):
    """Locked half of fault arming shared by ``maybe_inject``,
    ``maybe_corrupt``, and ``maybe_torn``: resolve the rule, run the
    `after`/`ramp`/budget scheduling, draw the RNG, and return
    (kind, delay_ms) when the rule fires, else None. ``family`` selects
    which rule family this call site services ("inject", "corrupt", or
    "torn_write")."""
    _reload_if_changed()
    rule = _resolve_rule_locked(op_name)
    if rule is None:
        return None
    if _PRODUCER_FAMILIES.get(rule.kind, "inject") != family:
        return None
    if rule.budget is not None and rule.budget <= 0:
        return None
    # scheduling: count every matching dispatch; hold fire for the
    # first `after`, then ramp the effective percent over `ramp`
    # armed dispatches. The RNG draw happens only once armed, so a
    # seeded storm is bit-reproducible regardless of `after`.
    rule.calls += 1
    if rule.calls <= rule.after:
        return None
    percent = rule.percent
    if rule.ramp:
        armed = rule.calls - rule.after
        percent *= min(1.0, armed / rule.ramp)
    if _state.rng.uniform(0, 100) >= percent:
        return None
    if rule.budget is not None:
        rule.budget -= 1
    return rule.kind, rule.delay_ms


def maybe_inject(op_name: str) -> None:
    """Called by op_boundary before dispatch; raises the configured
    fault, sleeps (``delay`` kind), SIGKILLs the process (``crash``
    kind), or returns. Cheap when disabled (one attribute read).
    ``corrupt`` rules are inert here — they fire through
    ``maybe_corrupt`` at the payload producer."""
    if not _state.enabled:
        return
    with _state.lock:
        hit = _draw_locked(op_name, family="inject")
        if hit is None:
            return
        kind, delay_ms = hit
    if kind == "crash":
        # the kill-9 mid-op chaos (ISSUE 5): the request was consumed,
        # no response will ever be written, the peer sees a dead
        # transport. SIGKILL self — no atexit, no flush, no cleanup —
        # exactly the failure the pool's failover must survive.
        import signal

        os.kill(os.getpid(), signal.SIGKILL)
    if kind == "fatal":
        raise FatalDeviceError(f"injected fatal fault in {op_name}")
    if kind == "retryable":
        raise RetryableError(f"injected retryable fault in {op_name}")
    if kind == "reject":
        # the serving scheduler's admission chaos (serve/ calls
        # maybe_inject("serve.admit") on every submission): the shed
        # path fires deterministically — the scheduler counts it under
        # serve.shed_total like any organic shed, and the client sees
        # the same retryable Overloaded contract as a real storm.
        # delayMs carries the retry_after_s hint (in ms).
        raise Overloaded(
            f"injected admission reject in {op_name}",
            retry_after_s=delay_ms / 1000.0,
            cause="injected",
        )
    if kind == "netsplit":
        # the network-partition chaos (ISSUE 16): model the kernel
        # refusing the TCP connect to a partitioned peer. Raised as the
        # REAL OSError subclass so the exchange client's existing
        # (ConnectionError, OSError) -> retryable-UNAVAILABLE
        # classification — and everything above it (per-peer breaker,
        # liveness, epoch-fenced recovery) — exercises exactly the
        # production path. Key it exchange.connect@r<N> to partition
        # one rank.
        raise ConnectionRefusedError(
            f"injected netsplit in {op_name}: connection refused"
        )
    if kind == "cache_evict":
        # the cache-eviction chaos (srjt-cache, ISSUE 17): key it
        # ``cache.*`` (or a specific ``cache.plan.<fp>`` /
        # ``cache.sub.<fp>`` op) to force eviction of the entry being
        # looked up, mid-query. The cache layer converts this into
        # drop-and-recompute — never a caller-visible failure.
        raise CacheEvictInjected(f"injected cache eviction in {op_name}")
    if kind == "spill_fail":
        # the memory governor's demotion chaos (memgov/catalog.py calls
        # maybe_inject("memgov.spill") around every spill): same
        # retryable class, distinct message — the catalog catches it,
        # counts memgov.spill_failures, and leaves the entry resident
        raise RetryableError(f"injected spill failure in {op_name}")
    if kind == "delay":
        # latency, not failure: sleeps OUTSIDE the injector lock so a
        # delay storm cannot serialize every other dispatch behind it
        time.sleep(delay_ms / 1000.0)  # srjt-lint: allow-blocking(the injected delay IS the chaos payload; deadline scopes observe it as op latency)
        return
    if kind == "hang":
        _hang(op_name, delay_ms)  # outside the lock, like delay
        return
    raise RuntimeError(f"injected exception in {op_name}")


def _hang(op_name: str, delay_ms: float) -> None:
    """``hang`` kind: the wedged-dispatch analog that sleeps far past
    any deadline — but cooperatively. The sleep polls the context-local
    deadline/cancel token (utils/deadline.py) in small slices and
    raises DeadlineExceeded the moment the budget dies or the token
    trips: exactly the interrupt a real wedged kernel lacks and the
    deadline subsystem exists to provide. With no active deadline the
    full hang is slept — a chaos profile pointing ``hang`` at an
    unbudgeted op surfaces as the wall-clock it costs, which is the
    correct loud failure for a mis-armed harness."""
    from . import deadline as deadline_mod

    end = time.monotonic() + delay_ms / 1000.0
    while True:
        d = deadline_mod.current()
        if d is not None and d.done():
            raise d.exceeded(f"hang fault in {op_name}")
        now = time.monotonic()
        if now >= end:
            return
        step = end - now
        if d is not None:
            # wake just past the deadline edge, not a poll interval late
            step = min(step, max(d.remaining(), 0.0) + 0.005)
        time.sleep(min(step, 0.05))


def maybe_corrupt(op_name: str, data: bytes) -> bytes:
    """Chaos hook for payload producers (the sidecar worker crosses it
    on every response, keyed ``sidecar.worker.<OP>``): when a matched
    ``corrupt`` rule fires, return a byte-flipped COPY of ``data`` —
    the producer computes its CRC over the original first, so the
    corruption models the transport flipping bits after checksumming
    and the integrity layer MUST catch it. Honors the same
    `after`/`ramp`/budget scheduling as every other kind. Returns
    ``data`` unchanged when disabled, unmatched, or empty."""
    if not _state.enabled or not data:
        return data
    with _state.lock:
        hit = _draw_locked(op_name, family="corrupt")
        if hit is None:
            return data
        # up to 8 contiguous bytes XOR 0xFF at a seeded offset: enough
        # to defeat any checksum, deterministic under the profile seed
        off = _state.rng.randrange(len(data))
    buf = bytearray(data)
    for i in range(off, min(off + 8, len(buf))):
        buf[i] ^= 0xFF
    from . import metrics

    metrics.event("faultinj.corrupt", op=op_name, offset=off, nbytes=len(data))
    return bytes(buf)


def maybe_torn(op_name: str, data: bytes) -> bytes:
    """Chaos hook for durable-write producers (srjt-durable, ISSUE 20):
    when a matched ``torn_write`` rule fires, return a PREFIX of
    ``data`` — modeling the process dying (or the disk filling) mid
    ``write(2)``, the failure journal/manifest replay must truncate
    past. Key it ``journal.append`` (the query journal crosses it on
    every record) or ``memgov.manifest`` (the spill-manifest writer).
    ``delayMs`` carries the bytes KEPT when positive (clamped to
    len-1 so the tear is never a no-op); otherwise half the record is
    kept. Honors the same `after`/`ramp`/budget scheduling as every
    other kind. Returns ``data`` unchanged when disabled, unmatched,
    or too short to tear."""
    if not _state.enabled or len(data) < 2:
        return data
    with _state.lock:
        hit = _draw_locked(op_name, family="torn_write")
        if hit is None:
            return data
        _kind, delay_ms = hit
    keep = int(delay_ms) if delay_ms > 0 else len(data) // 2
    keep = max(1, min(keep, len(data) - 1))
    from . import metrics

    metrics.event("faultinj.torn_write", op=op_name, kept=keep,
                  nbytes=len(data))
    return data[:keep]


# env-var activation, like CUDA_INJECTION64_PATH + FAULT_INJECTOR_CONFIG_PATH.
# A bad/missing config degrades the injector, never the host process
# (the reference's injector has the same stance).
from . import knobs as _knobs

_env_cfg = _knobs.get_str("SRJT_FAULTINJ_CONFIG")
if _env_cfg:
    try:
        configure_from_file(_env_cfg)
    except Exception as e:  # srjt-lint: allow-broad-except(malformed chaos config degrades the injector, never the host process — the reference injector's stance)
        import warnings

        warnings.warn(f"faultinj: ignoring SRJT_FAULTINJ_CONFIG ({e})", stacklevel=1)

"""Deterministic fault injection at the op-dispatch boundary.

TPU-native analog of the reference's CUPTI injector (faultinj.cu, SURVEY
§2.4/§3.5): instead of hooking the CUDA driver, faults fire inside the
``op_boundary`` dispatch wrapper (utils/dispatch.py) — the same choke
point every public op crosses, which is where a PJRT-level hook would
sit. Feature parity:

- JSON config (reference: FAULT_INJECTOR_CONFIG_PATH, :80, :346-408),
  env var ``SRJT_FAULTINJ_CONFIG`` or programmatic ``configure()``,
- match by exact op name or ``"*"`` wildcard (:142-152),
- injection types: ``fatal`` (FatalDeviceError — the trap/assert
  analog, :135-140), ``retryable`` (RetryableError), ``exception``
  (plain RuntimeError — the FI_RETURN_VALUE analog),
- ``percent`` probability + ``interceptionCount`` budget (:255-315),
- deterministic via ``seed`` (:369-392),
- hot reload: config file mtime is polled on each dispatch (the
  inotify-thread analog, :429-480) when loaded from a path.

Config schema (faultinj/README.md:61-141 shape)::

    {
      "seed": 12345,
      "faults": {
        "convert_to_rows": {"type": "retryable", "percent": 50,
                             "interceptionCount": 2},
        "*": {"type": "fatal", "percent": 1}
      }
    }
"""

from __future__ import annotations

import json
import os
import random
import threading
from typing import Dict, Optional

from .errors import FatalDeviceError, RetryableError

__all__ = ["configure", "configure_from_file", "disable", "maybe_inject", "is_enabled"]


class _Rule:
    __slots__ = ("kind", "percent", "budget")

    def __init__(self, kind: str, percent: float, budget: Optional[int]):
        self.kind = kind
        self.percent = percent
        self.budget = budget  # None == unlimited


class _State:
    def __init__(self):
        self.lock = threading.Lock()
        self.rules: Dict[str, _Rule] = {}
        self.rng = random.Random()
        self.path: Optional[str] = None
        self.mtime: float = 0.0
        self.enabled = False


_state = _State()


def _parse(cfg: dict) -> None:
    _state.rules = {}
    for name, spec in (cfg.get("faults") or {}).items():
        kind = spec.get("type", "retryable")
        if kind not in ("fatal", "retryable", "exception"):
            raise ValueError(f"faultinj: unknown fault type {kind!r}")
        percent = float(spec.get("percent", 100))
        budget = spec.get("interceptionCount")
        _state.rules[name] = _Rule(kind, percent, None if budget is None else int(budget))
    _state.rng = random.Random(cfg.get("seed"))


def configure(cfg: dict) -> None:
    """Install a config programmatically (tests / in-process chaos)."""
    with _state.lock:
        _state.path = None
        _parse(cfg)
        _state.enabled = bool(_state.rules)


def configure_from_file(path: str) -> None:
    with _state.lock:
        with open(path) as f:
            _parse(json.load(f))
        _state.path = path
        _state.mtime = os.stat(path).st_mtime
        # file-backed configs stay active even when currently empty, so
        # the hot-reload poll keeps running (inotify-thread analog)
        _state.enabled = True


def disable() -> None:
    with _state.lock:
        _state.rules = {}
        _state.enabled = False
        _state.path = None


def is_enabled() -> bool:
    return _state.enabled


def _reload_if_changed() -> None:
    if _state.path is None:
        return
    try:
        m = os.stat(_state.path).st_mtime
    except OSError:
        return
    if m != _state.mtime:
        with open(_state.path) as f:
            _parse(json.load(f))
        _state.mtime = m


def maybe_inject(op_name: str) -> None:
    """Called by op_boundary before dispatch; raises the configured
    fault or returns. Cheap when disabled (one attribute read)."""
    if not _state.enabled:
        return
    with _state.lock:
        _reload_if_changed()
        rule = _state.rules.get(op_name) or _state.rules.get("*")
        if rule is None:
            return
        if rule.budget is not None and rule.budget <= 0:
            return
        if _state.rng.uniform(0, 100) >= rule.percent:
            return
        if rule.budget is not None:
            rule.budget -= 1
        kind = rule.kind
    if kind == "fatal":
        raise FatalDeviceError(f"injected fatal fault in {op_name}")
    if kind == "retryable":
        raise RetryableError(f"injected retryable fault in {op_name}")
    raise RuntimeError(f"injected exception in {op_name}")


# env-var activation, like CUDA_INJECTION64_PATH + FAULT_INJECTOR_CONFIG_PATH.
# A bad/missing config degrades the injector, never the host process
# (the reference's injector has the same stance).
_env_cfg = os.environ.get("SRJT_FAULTINJ_CONFIG")
if _env_cfg:
    try:
        configure_from_file(_env_cfg)
    except Exception as e:  # any malformed config: degrade, never crash
        import warnings

        warnings.warn(f"faultinj: ignoring SRJT_FAULTINJ_CONFIG ({e})", stacklevel=1)
